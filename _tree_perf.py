import time
import numpy as np, jax, jax.numpy as jnp
from transmogrifai_tpu.ops import trees as T
for N in (100_000, 1_000_000):
    F, B, D, R = 64, 32, 6, 20
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xd = jax.device_put(jnp.asarray(X)); yd = jax.device_put(jnp.asarray(y))
    w = jnp.ones(N, jnp.float32)
    t0 = time.time()
    edges = T.quantile_edges(Xd, B); Xb = T.bin_matrix(Xd, edges); Xb.block_until_ready()
    t_bin = time.time() - t0
    times = []
    for trial in range(3):
        key = jax.random.PRNGKey(trial)
        t0 = time.time()
        trees, base = T.fit_gbt(Xb, yd, w, key, n_rounds=R, depth=D, n_bins=B,
                                learning_rate=0.1, loss="logistic")
        s = float(np.asarray(trees.leaf).sum())
        times.append(time.time()-t0)
    margin = float(base) + np.asarray(T.predict_forest_bins(trees, Xb, D))[:, 0]
    acc = ((margin > 0) == (y > 0.5)).mean()
    print(f"N={N}: bin={t_bin:.2f}s fit times={['%.3f' % t for t in times]} acc={acc:.4f}")
