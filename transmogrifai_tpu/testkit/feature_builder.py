"""TestFeatureBuilder: (Dataset, Feature...) from in-memory typed values.

Reference: testkit/.../test/TestFeatureBuilder.scala:50,265,298 — builds a
DataFrame plus matching raw Features from literal typed values, arities 1-5,
variadic, and `random`. Here it returns a columnar Dataset whose columns line
up with FeatureGeneratorStage-origin Features, ready for Workflow or direct
stage fitting.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Type

from ..data.dataset import Dataset, column_from_values
from ..features.builder import FeatureBuilder
from ..features.feature import Feature
from ..types import FeatureType
from .random_data import RandomData

DEFAULT_NAMES = ("f1", "f2", "f3", "f4", "f5")


def _make_feature(name: str, type_cls: Type[FeatureType],
                  is_response: bool = False) -> Feature:
    builder = FeatureBuilder.of(name, type_cls).extract(
        lambda r, _n=name: r.get(_n))
    return builder.as_response() if is_response else builder.as_predictor()


def _infer_type(values: Sequence[Any]) -> Type[FeatureType]:
    for v in values:
        if isinstance(v, FeatureType):
            return type(v)
    raise ValueError("Pass FeatureType instances or use the (name, type, "
                     "values) form to build test features")


class TestFeatureBuilder:
    """``ds, (f1, f2) = TestFeatureBuilder.build(("age", Real, [...]), ...)``"""

    @staticmethod
    def build(*specs: Tuple, response_index: Optional[int] = None
              ) -> Tuple[Dataset, Tuple[Feature, ...]]:
        """Each spec: (name, FeatureTypeClass, values) or (name, values) with
        values as FeatureType instances. `response_index` marks one feature
        as the response."""
        cols = {}
        feats: List[Feature] = []
        for i, spec in enumerate(specs):
            if len(spec) == 3:
                name, tcls, values = spec
            else:
                name, values = spec
                tcls = _infer_type(values)
            raw = [v.value if isinstance(v, FeatureType) else v
                   for v in values]
            cols[name] = column_from_values(tcls, raw)
            feats.append(_make_feature(name, tcls,
                                       is_response=(i == response_index)))
        return Dataset(cols), tuple(feats)

    @staticmethod
    def random(n: int, **generators: RandomData
               ) -> Tuple[Dataset, Tuple[Feature, ...]]:
        """``ds, (age, name) = TestFeatureBuilder.random(100, age=RandomReal
        .normal(), name=RandomText.names())`` (reference TestFeatureBuilder
        .random:298)."""
        specs = []
        for name, gen in generators.items():
            vals = gen.take(n)
            specs.append((name, gen.type_cls, [v.value for v in vals]))
        return TestFeatureBuilder.build(*specs)
