"""Random typed-feature generators for tests.

Reference: testkit/src/main/scala/com/salesforce/op/testkit/ (16 files —
RandomReal.scala:44, RandomText, RandomIntegral, RandomBinary, RandomList,
RandomMap, RandomSet, RandomVector): distribution-parameterized infinite
streams of FeatureType values with a configurable probability of empties.

Python shape: every generator is an infinite iterator over FeatureType
instances; `take(n)` materializes a list, `with_probability_of_empty(p)`
injects missingness, `reset(seed)` makes runs reproducible.
"""
from __future__ import annotations

import string
from typing import Any, Callable, Dict, Generic, Iterator, List, Optional
from typing import Sequence, Type, TypeVar

import numpy as np

from ..types import (
    Base64, Binary, City, ComboBox, Country, Currency, Date, DateList,
    DateTime, Email, FeatureType, Geolocation, GeolocationMap, ID, Integral,
    MultiPickList, OPVector, Percent, Phone, PickList, PostalCode, Real,
    RealMap, RealNN, State, Street, Text, TextArea, TextList, TextMap, URL,
)

T = TypeVar("T", bound=FeatureType)

_FIRST_NAMES = ["Ada", "Alan", "Grace", "Edsger", "Barbara", "Donald",
                "Radia", "Vint", "Margaret", "Dennis", "Frances", "Ken"]
_LAST_NAMES = ["Lovelace", "Turing", "Hopper", "Dijkstra", "Liskov", "Knuth",
               "Perlman", "Cerf", "Hamilton", "Ritchie", "Allen", "Thompson"]
_DOMAINS = ["example.com", "mail.org", "site.net", "corp.io"]
_COUNTRIES = ["USA", "Canada", "Mexico", "France", "Germany", "Japan",
              "Brazil", "India", "Australia", "Kenya"]
_STATES = ["CA", "NY", "TX", "WA", "OR", "IL", "MA", "GA", "FL", "CO"]
_CITIES = ["Springfield", "Rivertown", "Lakeside", "Hillview", "Brookfield",
           "Fairmont", "Georgetown", "Clinton", "Salem", "Madison"]
_STREETS = ["Maple St", "Oak Ave", "Pine Rd", "Cedar Ln", "Elm Dr",
            "2nd St", "Park Blvd", "Main St", "River Rd", "Lake Ave"]


class RandomData(Generic[T]):
    """Infinite stream of FeatureType values (reference RandomData)."""

    def __init__(self, type_cls: Type[T], sample: Callable[[np.random.Generator], Any],
                 seed: int = 42):
        self.type_cls = type_cls
        self._sample = sample
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self._p_empty = 0.0

    # -- fluent config (reference withProbabilityOfEmpty) ------------------
    def with_probability_of_empty(self, p: float) -> "RandomData[T]":
        self._p_empty = float(p)
        return self

    def reset(self, seed: Optional[int] = None) -> "RandomData[T]":
        self._seed = self._seed if seed is None else seed
        self._rng = np.random.default_rng(self._seed)
        return self

    # -- stream ------------------------------------------------------------
    def __iter__(self) -> Iterator[T]:
        while True:
            yield self.next_value()

    def next_value(self) -> T:
        if self._p_empty > 0 and self._rng.uniform() < self._p_empty:
            return self.type_cls.empty()
        return self.type_cls(self._sample(self._rng))

    def take(self, n: int) -> List[T]:
        return [self.next_value() for _ in range(n)]

    def limit(self, n: int) -> List[T]:  # reference naming
        return self.take(n)


class RandomReal:
    """Reference RandomReal.scala:44 — distribution factories."""

    @staticmethod
    def normal(mean: float = 0.0, sigma: float = 1.0,
               of: Type[FeatureType] = Real, seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.normal(mean, sigma)), seed)

    @staticmethod
    def uniform(lo: float = 0.0, hi: float = 1.0,
                of: Type[FeatureType] = Real, seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.uniform(lo, hi)), seed)

    @staticmethod
    def poisson(lam: float = 1.0, of: Type[FeatureType] = Real,
                seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.poisson(lam)), seed)

    @staticmethod
    def exponential(scale: float = 1.0, of: Type[FeatureType] = Real,
                    seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.exponential(scale)), seed)

    @staticmethod
    def gamma(shape: float = 2.0, scale: float = 1.0,
              of: Type[FeatureType] = Real, seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.gamma(shape, scale)), seed)

    @staticmethod
    def lognormal(mean: float = 0.0, sigma: float = 1.0,
                  of: Type[FeatureType] = Real, seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.lognormal(mean, sigma)), seed)

    @staticmethod
    def weibull(a: float = 1.5, of: Type[FeatureType] = Real,
                seed: int = 42) -> RandomData:
        return RandomData(of, lambda r: float(r.weibull(a)), seed)

    # non-null variants
    @staticmethod
    def normal_nn(mean: float = 0.0, sigma: float = 1.0,
                  seed: int = 42) -> RandomData:
        return RandomData(RealNN, lambda r: float(r.normal(mean, sigma)), seed)

    @staticmethod
    def currencies(lo: float = 0.0, hi: float = 1000.0,
                   seed: int = 42) -> RandomData:
        return RandomData(Currency, lambda r: round(float(r.uniform(lo, hi)), 2),
                          seed)

    @staticmethod
    def percents(seed: int = 42) -> RandomData:
        return RandomData(Percent, lambda r: float(r.uniform(0, 100)), seed)


class RandomIntegral:
    """Reference RandomIntegral.scala."""

    @staticmethod
    def integrals(lo: int = 0, hi: int = 100, seed: int = 42) -> RandomData:
        return RandomData(Integral, lambda r: int(r.integers(lo, hi)), seed)

    @staticmethod
    def dates(start_ms: int = 1_500_000_000_000, step_ms: int = 86_400_000,
              seed: int = 42) -> RandomData:
        return RandomData(
            Date, lambda r: int(start_ms + r.integers(0, 1000) * step_ms),
            seed)

    @staticmethod
    def datetimes(start_ms: int = 1_500_000_000_000, seed: int = 42
                  ) -> RandomData:
        return RandomData(
            DateTime,
            lambda r: int(start_ms + r.integers(0, 10**9)), seed)


class RandomBinary:
    """Reference RandomBinary.scala — Bernoulli(p)."""

    def __new__(cls, probability_of_success: float = 0.5, seed: int = 42
                ) -> RandomData:
        p = probability_of_success
        return RandomData(Binary, lambda r: bool(r.uniform() < p), seed)


def _rand_str(r: np.random.Generator, k: int = 8) -> str:
    letters = np.array(list(string.ascii_lowercase))
    return "".join(r.choice(letters, size=k))


class RandomText:
    """Reference RandomText.scala — realistic typed text streams."""

    @staticmethod
    def strings(min_len: int = 3, max_len: int = 12, seed: int = 42
                ) -> RandomData:
        return RandomData(
            Text, lambda r: _rand_str(r, int(r.integers(min_len, max_len + 1))),
            seed)

    @staticmethod
    def textareas(sentences: int = 3, seed: int = 42) -> RandomData:
        def sample(r):
            return ". ".join(
                " ".join(_rand_str(r, int(r.integers(2, 9)))
                         for _ in range(int(r.integers(4, 10))))
                for _ in range(sentences))
        return RandomData(TextArea, sample, seed)

    @staticmethod
    def names(seed: int = 42) -> RandomData:
        return RandomData(
            Text, lambda r: f"{r.choice(_FIRST_NAMES)} {r.choice(_LAST_NAMES)}",
            seed)

    @staticmethod
    def emails(domain: Optional[str] = None, seed: int = 42) -> RandomData:
        return RandomData(
            Email,
            lambda r: f"{_rand_str(r, 6)}@{domain or r.choice(_DOMAINS)}",
            seed)

    @staticmethod
    def urls(seed: int = 42) -> RandomData:
        return RandomData(
            URL, lambda r: f"https://{_rand_str(r, 6)}.{r.choice(_DOMAINS)}",
            seed)

    @staticmethod
    def phones(seed: int = 42) -> RandomData:
        return RandomData(
            Phone, lambda r: "+1" + "".join(str(d) for d in
                                            r.integers(0, 10, size=10)),
            seed)

    @staticmethod
    def ids(seed: int = 42) -> RandomData:
        return RandomData(ID, lambda r: _rand_str(r, 12), seed)

    @staticmethod
    def countries(seed: int = 42) -> RandomData:
        return RandomData(Country, lambda r: str(r.choice(_COUNTRIES)), seed)

    @staticmethod
    def states(seed: int = 42) -> RandomData:
        return RandomData(State, lambda r: str(r.choice(_STATES)), seed)

    @staticmethod
    def cities(seed: int = 42) -> RandomData:
        return RandomData(City, lambda r: str(r.choice(_CITIES)), seed)

    @staticmethod
    def streets(seed: int = 42) -> RandomData:
        return RandomData(
            Street, lambda r: f"{int(r.integers(1, 9999))} {r.choice(_STREETS)}",
            seed)

    @staticmethod
    def postal_codes(seed: int = 42) -> RandomData:
        return RandomData(
            PostalCode, lambda r: f"{int(r.integers(10000, 99999))}", seed)

    @staticmethod
    def pick_lists(domain: Sequence[str], seed: int = 42) -> RandomData:
        dom = list(domain)
        return RandomData(PickList, lambda r: str(r.choice(dom)), seed)

    @staticmethod
    def combo_boxes(domain: Sequence[str], seed: int = 42) -> RandomData:
        dom = list(domain)
        return RandomData(ComboBox, lambda r: str(r.choice(dom)), seed)

    @staticmethod
    def base64(n_bytes: int = 24, seed: int = 42) -> RandomData:
        import base64 as b64
        return RandomData(
            Base64,
            lambda r: b64.b64encode(r.bytes(n_bytes)).decode("ascii"), seed)


class RandomList:
    """Reference RandomList.scala."""

    @staticmethod
    def of_texts(min_len: int = 0, max_len: int = 5, seed: int = 42
                 ) -> RandomData:
        return RandomData(
            TextList,
            lambda r: [_rand_str(r, 6)
                       for _ in range(int(r.integers(min_len, max_len + 1)))],
            seed)

    @staticmethod
    def of_dates(start_ms: int = 1_500_000_000_000, max_len: int = 5,
                 seed: int = 42) -> RandomData:
        return RandomData(
            DateList,
            lambda r: [int(start_ms + x)
                       for x in r.integers(0, 10**9,
                                           size=int(r.integers(0, max_len + 1)))],
            seed)


class RandomSet:
    """Reference RandomSet.scala — MultiPickList draws."""

    @staticmethod
    def of(domain: Sequence[str], min_len: int = 0, max_len: int = 3,
           seed: int = 42) -> RandomData:
        dom = list(domain)
        return RandomData(
            MultiPickList,
            lambda r: set(r.choice(dom, size=min(
                int(r.integers(min_len, max_len + 1)), len(dom)),
                replace=False).tolist()),
            seed)


class RandomMap:
    """Reference RandomMap.scala — keyed draws of a base generator."""

    @staticmethod
    def of_reals(keys: Sequence[str], seed: int = 42) -> RandomData:
        ks = list(keys)
        return RandomData(
            RealMap,
            lambda r: {k: float(r.normal()) for k in ks
                       if r.uniform() > 0.2},
            seed)

    @staticmethod
    def of_texts(keys: Sequence[str], seed: int = 42) -> RandomData:
        ks = list(keys)
        return RandomData(
            TextMap,
            lambda r: {k: _rand_str(r, 6) for k in ks if r.uniform() > 0.2},
            seed)

    @staticmethod
    def of_geolocations(keys: Sequence[str], seed: int = 42) -> RandomData:
        ks = list(keys)

        def sample(r):
            return {k: [float(r.uniform(-90, 90)),
                        float(r.uniform(-180, 180)), 1.0]
                    for k in ks if r.uniform() > 0.2}
        return RandomData(GeolocationMap, sample, seed)


class RandomVector:
    """Reference RandomVector.scala — dense vectors from a distribution."""

    @staticmethod
    def normal(dim: int, mean: float = 0.0, sigma: float = 1.0,
               seed: int = 42) -> RandomData:
        return RandomData(
            OPVector,
            lambda r: r.normal(mean, sigma, size=dim).astype(np.float32),
            seed)

    @staticmethod
    def dense(dim: int, lo: float = 0.0, hi: float = 1.0, seed: int = 42
              ) -> RandomData:
        return RandomData(
            OPVector,
            lambda r: r.uniform(lo, hi, size=dim).astype(np.float32), seed)


class RandomGeolocation:
    def __new__(cls, seed: int = 42) -> RandomData:
        return RandomData(
            Geolocation,
            lambda r: [float(r.uniform(-90, 90)),
                       float(r.uniform(-180, 180)),
                       float(r.integers(1, 10))],
            seed)
