"""Test scaffolding (reference testkit module, 2,769 LoC): random typed
data generators + TestFeatureBuilder."""
from .feature_builder import TestFeatureBuilder
from .random_data import (
    RandomBinary, RandomData, RandomGeolocation, RandomIntegral, RandomList,
    RandomMap, RandomReal, RandomSet, RandomText, RandomVector,
)

__all__ = [
    "RandomBinary", "RandomData", "RandomGeolocation", "RandomIntegral",
    "RandomList", "RandomMap", "RandomReal", "RandomSet", "RandomText",
    "RandomVector", "TestFeatureBuilder",
]
