"""Test scaffolding (reference testkit module, 2,769 LoC): random typed
data generators, TestFeatureBuilder, and feature asserts."""
from .asserts import assert_feature, assert_transforms
from .feature_builder import TestFeatureBuilder
from .random_data import (
    RandomBinary, RandomData, RandomGeolocation, RandomIntegral, RandomList,
    RandomMap, RandomReal, RandomSet, RandomText, RandomVector,
)

__all__ = [
    "assert_feature", "assert_transforms",
    "RandomBinary", "RandomData", "RandomGeolocation", "RandomIntegral",
    "RandomList", "RandomMap", "RandomReal", "RandomSet", "RandomText",
    "RandomVector", "TestFeatureBuilder",
]
