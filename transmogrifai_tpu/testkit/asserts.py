"""Feature assertion helpers for user test suites.

Reference: testkit/src/main/scala/com/salesforce/op/test/FeatureAsserts
.scala:63 (`assertFeature` — name/response/rawness/type/extractor checks on
a declared feature) and FeatureTestBase.scala. Downstream stage authors use
these the way the reference's ScalaTest traits are used; the framework's own
contract-law sweep (tests/test_stage_contracts.py) subsumes the stage-spec
traits, so only the feature-level asserts live here.
"""
from __future__ import annotations

from typing import Any, Optional, Type

from ..features.feature import Feature
from ..features.generator import FeatureGeneratorStage
from ..types import FeatureType


def assert_feature(f: Feature, *, in_row: Any, out: Any, name: str,
                   is_response: bool = False,
                   feature_type: Optional[Type[FeatureType]] = None,
                   window_ms: Optional[int] = None) -> None:
    """Assert a RAW feature's declaration end to end (reference
    assertFeature): naming, response flag, type, origin generator stage,
    and that the extractor maps ``in_row`` to ``out``."""
    assert f.name == name, f"name: {f.name!r} != {name!r}"
    assert f.is_response == is_response, \
        f"is_response: {f.is_response} != {is_response}"
    assert not f.parents, f"raw feature must have no parents, got {f.parents}"
    if feature_type is not None:
        assert f.feature_type is feature_type, \
            f"type: {f.feature_type.__name__} != {feature_type.__name__}"
    st = f.origin_stage
    assert isinstance(st, FeatureGeneratorStage), \
        f"origin must be a FeatureGeneratorStage, got {type(st).__name__}"
    assert st.uid.startswith("FeatureGeneratorStage_"), st.uid
    assert st.feature_name == name
    if window_ms is not None:
        got_w = getattr(st.aggregator, "window_ms", None)
        assert got_w == window_ms, f"window: {got_w} != {window_ms}"
    got = st.extract(in_row)
    got_v = got.value if isinstance(got, FeatureType) else got
    want_v = out.value if isinstance(out, FeatureType) else out
    assert got_v == want_v, f"extract({in_row!r}) = {got_v!r} != {want_v!r}"


def assert_transforms(stage, input_values, expected) -> None:
    """Assert a transformer's per-row outputs over typed input tuples
    (reference OpTransformerSpec's expected-outputs check, row level)."""
    assert len(input_values) == len(expected), \
        f"{len(input_values)} inputs vs {len(expected)} expected outputs"
    for vals, want in zip(input_values, expected):
        if not isinstance(vals, tuple):
            vals = (vals,)
        got = stage.transform_value(*vals)
        got_v = got.value if isinstance(got, FeatureType) else got
        want_v = want.value if isinstance(want, FeatureType) else want
        assert got_v == want_v, \
            f"{stage.stage_name}({vals!r}) = {got_v!r} != {want_v!r}"
