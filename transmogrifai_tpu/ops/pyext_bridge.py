"""Loader for the _tmog_pyext CPython extension (native/pyext.cpp).

Same posture as native_bridge: build on first use, expose typed wrappers,
return None (or raise ImportError from ``module()``) when unavailable so
every caller keeps a pure-Python fallback. TMOG_DISABLE_NATIVE disables
this tier too (one knob for all native code).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

_mod: Any = None
_tried = False


def module() -> Any:
    """The loaded extension module, or None."""
    global _mod, _tried
    if _tried:
        return _mod
    _tried = True
    if os.environ.get("TMOG_DISABLE_NATIVE"):
        return None
    try:
        from ..native.build import build_pyext
        path = build_pyext()
        if path is None:
            return None
        from importlib.machinery import ExtensionFileLoader
        from importlib.util import module_from_spec, spec_from_file_location
        loader = ExtensionFileLoader("_tmog_pyext", path)
        spec = spec_from_file_location("_tmog_pyext", path, loader=loader)
        if spec is None:
            return None
        m = module_from_spec(spec)
        loader.exec_module(m)
        _mod = m
    except (ImportError, OSError):
        _mod = None
    return _mod


def pack_strings(strings: Sequence[Any]
                 ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    m = module()
    if m is None:
        return None
    buf_b, off_b = m.pack_strings(strings)
    buf = np.frombuffer(buf_b, dtype=np.uint8)
    offsets = np.frombuffer(off_b, dtype=np.int64)
    return buf, offsets


def dict_encode(strings: Sequence[Any]
                ) -> Optional[Tuple[np.ndarray, List[str]]]:
    m = module()
    if m is None:
        return None
    codes = np.empty(len(strings), np.int64)
    _, uniques = m.dict_encode(strings, codes)
    return codes, uniques


def pivot_codes(data: Sequence[Any], index: Dict[str, int], other_code: int,
                null_code: int, clean_fn) -> Optional[np.ndarray]:
    m = module()
    if m is None:
        return None
    codes = np.empty(len(data), np.int64)
    m.pivot_codes(data, index, other_code, null_code, clean_fn, codes)
    return codes


def extract_key_columns(data: Sequence[Any], keys: Sequence[str],
                        clean_fn=None) -> Optional[Dict[str, List[Any]]]:
    m = module()
    if m is None:
        return None
    return m.extract_key_columns(data, tuple(keys),
                                 clean_fn if clean_fn is not None else None)


def float_column(vals: Sequence[Any], fill: float) -> Optional[np.ndarray]:
    m = module()
    if m is None:
        return None
    # tmoglint: disable=TPU003  C++ ext ABI: float_column fills a double buffer
    out = np.empty(len(vals), np.float64)
    m.float_column(vals, float(fill), out)
    return out


def all_ascii(data: Sequence[Any]) -> Optional[bool]:
    m = module()
    if m is None:
        return None
    return m.all_ascii(data)


def null_mask(data: Sequence[Any]) -> Optional[np.ndarray]:
    m = module()
    if m is None:
        return None
    out = np.empty(len(data), np.uint8)
    m.null_mask(data, out)
    return out.view(np.bool_)


def empty_mask(data: Sequence[Any]) -> Optional[np.ndarray]:
    m = module()
    if m is None:
        return None
    out = np.empty(len(data), np.uint8)
    m.empty_mask(data, out)
    return out.view(np.bool_)
