"""ctypes bridge to the native host kernels (native/hashing.cpp).

Loads _tmog_native.so (building it on first use) and exposes numpy-typed
wrappers. Every function returns None when the library is unavailable so
callers keep their NumPy fallback — the native path is an accelerator for
the host's text->tensor and CSV data loops, mirroring where the reference
leaned on JVM-native code (Spark HashingTF murmur3, spark-csv).
"""
from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("TMOG_DISABLE_NATIVE"):
        return None
    try:
        from ..native.build import build
        path = build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    f32p = ctypes.POINTER(ctypes.c_float)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    try:
        return _bind(lib, u8p, i64p, f64p, f32p, u32p)
    except AttributeError:
        # a stale prebuilt .so missing newer symbols (mtime defeated the
        # rebuild check): force a rebuild and dlopen it from a FRESH path —
        # CDLL of the original path would return the already-mapped stale
        # object. Failing that, degrade to the numpy fallbacks.
        try:
            import shutil
            import tempfile
            path = build(force=True)
            if path is not None:
                fd, fresh = tempfile.mkstemp(suffix="_tmog_native.so")
                os.close(fd)
                shutil.copyfile(path, fresh)
                fresh_lib = ctypes.CDLL(fresh)
                os.unlink(fresh)  # the live mapping keeps the file alive
                return _bind(fresh_lib, u8p, i64p, f64p, f32p, u32p)
        except (OSError, AttributeError):
            pass
        return None


def _bind(lib, u8p, i64p, f64p, f32p, u32p) -> ctypes.CDLL:
    global _lib
    lib.tmog_murmur3_32.restype = ctypes.c_uint32
    lib.tmog_murmur3_32.argtypes = [u8p, ctypes.c_int64, ctypes.c_uint32]
    lib.tmog_hash_strings.restype = None
    lib.tmog_hash_strings.argtypes = [u8p, i64p, ctypes.c_int64,
                                      ctypes.c_uint32, u32p]
    lib.tmog_hash_tokens_to_counts.restype = None
    lib.tmog_hash_tokens_to_counts.argtypes = [
        u8p, i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint32,
        f32p]
    # _s suffix = strided-output ABI (row_stride arg). The rename is
    # deliberate: changing the original symbol's signature in place would
    # let a stale prebuilt .so bind successfully and then read the output
    # pointer from the wrong stack slot; a NEW symbol makes staleness an
    # AttributeError the rebuild fallback handles.
    lib.tmog_tokenize_hash_counts_s.restype = None
    lib.tmog_tokenize_hash_counts_s.argtypes = [
        u8p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_int64, ctypes.c_int64, f32p]
    lib.tmog_csv_scan.restype = ctypes.c_int64
    lib.tmog_csv_scan.argtypes = [u8p, ctypes.c_int64, ctypes.c_uint8,
                                  i64p, ctypes.c_int64, i64p, ctypes.c_int64,
                                  i64p]
    lib.tmog_parse_floats.restype = None
    lib.tmog_parse_floats.argtypes = [u8p, i64p, ctypes.c_int64, f64p]
    lib.tmog_dict_encode.restype = ctypes.c_int64
    lib.tmog_dict_encode.argtypes = [u8p, i64p, ctypes.c_int64, i64p,
                                     ctypes.c_int64, i64p, i64p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _as_i64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _as_f64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def native_murmur3(data: bytes, seed: int = 0) -> Optional[int]:
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8) if data else \
        np.zeros(1, np.uint8)
    return int(lib.tmog_murmur3_32(_as_u8p(buf), len(data), seed))


def _pack_strings(strings: Sequence[str]):
    from . import pyext_bridge
    packed = pyext_bridge.pack_strings(strings)
    if packed is not None:
        return packed
    # surrogatepass: strings decoded upstream with errors='surrogateescape'
    # (raw byte columns) must hash/encode instead of crashing ingest
    encoded = [s.encode("utf-8", errors="surrogatepass") for s in strings]
    offsets = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    buf = np.frombuffer(b"".join(encoded), dtype=np.uint8) if encoded else \
        np.zeros(0, np.uint8)
    if buf.size == 0:
        buf = np.zeros(1, np.uint8)
    return buf, offsets


def native_hash_strings(strings: Sequence[str], seed: int = 0
                        ) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    buf, offsets = _pack_strings(strings)
    out = np.zeros(len(strings), np.uint32)
    lib.tmog_hash_strings(_as_u8p(buf), _as_i64p(offsets), len(strings),
                          seed, out.ctypes.data_as(
                              ctypes.POINTER(ctypes.c_uint32)))
    return out


def _as_f32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def native_hash_tokens(token_lists: Sequence[Optional[Sequence[str]]],
                       num_bins: int, seed: int = 0) -> Optional[np.ndarray]:
    """[rows of token lists] -> [n, bins] float32 counts, or None."""
    lib = _load()
    if lib is None:
        return None
    flat: List[str] = []
    counts = np.zeros(len(token_lists), np.int64)
    for i, toks in enumerate(token_lists):
        if toks:
            counts[i] = len(toks)
            flat.extend(toks)
    buf, offsets = _pack_strings(flat)
    out = np.zeros((len(token_lists), num_bins), np.float32)
    lib.tmog_hash_tokens_to_counts(
        _as_u8p(buf), _as_i64p(offsets), _as_i64p(counts),
        len(token_lists), num_bins, seed, _as_f32p(out))
    return out


def native_tokenize_hash_counts(docs: Sequence[Optional[str]], num_bins: int,
                                seed: int = 0, min_len: int = 1,
                                pad_cols: int = 0,
                                out: Optional[np.ndarray] = None
                                ) -> Optional[np.ndarray]:
    """Fused tokenize+hash+count over raw documents ->
    [n, bins + pad_cols] float32. `pad_cols` trailing zero columns let the
    caller append indicators (null tracking) in place — the C kernel
    writes with the wider row stride, so no second full-matrix copy.
    `out` (pre-ZEROED f32, row-major, unit inner stride — may be a column
    slice of a wider matrix) receives the counts in place: the kernel
    accumulates at out's base pointer with out's own row stride, which is
    what lets the serving sink write text counts straight into the final
    combined matrix."""
    lib = _load()
    if lib is None:
        return None
    from . import pyext_bridge
    packed = pyext_bridge.pack_strings(docs)  # None -> "" in C
    if packed is None:
        packed = _pack_strings([d or "" for d in docs])
    buf, offsets = packed
    if out is None:
        stride = num_bins + int(pad_cols)
        out = np.zeros((len(docs), stride), np.float32)
    else:
        if (out.dtype != np.float32 or out.ndim != 2
                or out.shape[0] != len(docs)
                or out.shape[1] < num_bins + int(pad_cols)
                or out.strides[1] != 4 or out.strides[0] % 4):
            return None
        stride = out.strides[0] // 4
    lib.tmog_tokenize_hash_counts_s(_as_u8p(buf), _as_i64p(offsets), len(docs),
                                  num_bins, seed, min_len, stride,
                                  _as_f32p(out))
    return out


def native_dict_encode(strings: Sequence[str]
                       ) -> Optional[tuple]:
    """Exact dictionary encoding: (codes int64 [n], uniques list[str]) in
    first-occurrence order, or None without the library. One O(n) hashed
    pass replacing np.unique's O(n log n) object sort at ingest."""
    lib = _load()
    if lib is None:
        return None
    n = len(strings)
    if n == 0:
        return np.zeros(0, np.int64), []
    buf, offsets = _pack_strings(strings)
    cap = 1
    while cap < 2 * n + 2:
        cap <<= 1
    table = np.empty(cap, np.int64)
    codes = np.empty(n, np.int64)
    firsts = np.empty(n, np.int64)
    n_unique = lib.tmog_dict_encode(
        _as_u8p(buf), _as_i64p(offsets), n, _as_i64p(table), cap,
        _as_i64p(codes), _as_i64p(firsts))
    if n_unique < 0:
        return None
    uniques = [strings[i] for i in firsts[:n_unique]]
    return codes, uniques


def native_csv_parse(data: bytes, delim: str = ","
                     ) -> Optional[List[List[str]]]:
    """Full-buffer CSV scan -> rows of string fields (quotes handled;
    doubled-quote fields re-parsed host-side)."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, np.uint8)
    cap = len(data) + 16  # upper bound: every byte a field
    bounds = np.zeros(cap * 2, np.int64)
    max_rows = data.count(b"\n") + 2
    row_counts = np.zeros(max_rows, np.int64)
    n_rows = np.zeros(1, np.int64)
    nf = lib.tmog_csv_scan(_as_u8p(buf), len(data), ord(delim),
                           _as_i64p(bounds), cap, _as_i64p(row_counts),
                           max_rows, _as_i64p(n_rows))
    if nf < 0:
        return None
    # bounds are BYTE offsets from the C scanner. Pure-ASCII buffers (the
    # common case) decode once and slice the str — byte and char offsets
    # coincide. Any non-ASCII byte forces per-field byte slicing: slicing a
    # decoded str with byte offsets would shift every later field.
    ascii_fast = data.isascii()
    text = data.decode("utf-8", errors="replace") if ascii_fast else ""

    def field(s: int, e: int) -> str:
        if ascii_fast:
            return text[s:e]
        return data[s:e].decode("utf-8", errors="replace")

    rows: List[List[str]] = []
    f = 0
    for r in range(int(n_rows[0])):
        cnt = int(row_counts[r])
        fields = []
        for j in range(cnt):
            s, e = int(bounds[2 * (f + j)]), int(bounds[2 * (f + j) + 1])
            if s < 0:  # doubled-quote field: unescape here
                s = -s - 1
                fields.append(field(s, e).replace('""', '"'))
            else:
                fields.append(field(s, e))
        rows.append(fields)
        f += cnt
    return rows


def native_parse_floats(data: bytes, bounds: np.ndarray
                        ) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, np.uint8)
    n = len(bounds) // 2
    # tmoglint: disable=TPU003  C ABI: tmog_parse_floats writes doubles
    out = np.zeros(n, np.float64)
    lib.tmog_parse_floats(_as_u8p(buf), _as_i64p(np.ascontiguousarray(
        bounds, np.int64)), n, _as_f64p(out))
    return out
