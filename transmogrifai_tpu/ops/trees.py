"""Histogram decision-tree kernels as XLA programs.

TPU-native replacement for the reference's only native-compute path —
XGBoost4J's C++ libxgboost (reference shim
core/src/main/scala/ml/dmlc/xgboost4j/scala/spark/XGBoostParams.scala) and
Spark MLlib's tree learners behind OpRandomForest*/OpGBT*/OpDecisionTree*
(core/.../impl/classification/, core/.../impl/regression/).

Design (TPU-first, not a port):
- Features are quantile-binned once to int8 (uint8 up to 255 bins, int32
  past that; `quantile_edges` / `bin_matrix`);
  all growth happens on the binned matrix, which is the XGBoost `hist`
  algorithm shape and keeps every per-level pass a dense, static-shape
  gather/segment-sum that XLA tiles well.
- Trees are complete binary trees of static depth in heap layout: internal
  node arrays `feat`/`thresh`/`miss` of length 2^depth - 1, leaf payloads
  [2^depth, K]. Bins are shifted: 0 is the dedicated missing bin, present
  values occupy [1, n_bins] (int8 holds up to 127 quantile bins, uint8 up
  to 255 — the XGBoost 256-bin default at 1 byte/cell), and
  every node learns the default direction for missing rows (`miss`,
  XGBoost's sparsity-aware split). A node that fails its split test is
  encoded as (feat=0, thresh=n_bins, miss=0): `bin > thresh` is then
  never true, so all rows fall left — traversal stays branchless and
  data-independent (no dynamic shapes under jit, reference-free control
  flow for lax.scan).
- Multi-output payloads unify every leaf statistic the reference needs:
  K=1 Newton leaves (-G/(H+lambda)) give XGBoost/GBT boosting steps;
  K=n_classes mean leaves (G/H with G=onehot·w, H=w) give RF/DT class
  distributions whose variance-reduction gain IS the Gini gain; K=1 mean
  leaves give regression-tree variance reduction (Spark `impurity`).
- Per-level gradient histograms are one reduction over (node, feature,
  bin) cells with three lowerings: a fused `segment_sum` on CPU/GPU, a
  chunked one-hot MXU contraction on TPU, and a pallas kernel (VMEM
  one-hot tiles) above _PALLAS_MIN_ROWS. Levels past the root compute
  left children only and derive siblings by subtraction. Under pjit row
  sharding the partial histograms all-reduce over ICI exactly where
  XGBoost used Rabit allreduce.
- TPU serializes data-dependent gathers, so routing, traversal, leaf
  lookup and digitize all lower as one-hot contractions / fused compares
  there (CPU keeps the gather forms; results agree up to f32 rounding).
- Row parallelism = whole-array ops over N; tree/round loops are lax.scan;
  the class axis of softmax boosting is vmapped.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

EPS = 1e-12


class Tree(NamedTuple):
    """One complete binary tree (heap layout). Leading axes may batch trees.

    Missing values occupy the dedicated bin 0 (bin_matrix); present values
    bin to [1, n_bins]. Each node carries a learned default direction for
    missing rows (XGBoost's sparsity-aware split: both directions are
    scored during growth and the better one recorded)."""
    feat: jax.Array    # int32 [..., 2^depth - 1] split feature id
    thresh: jax.Array  # int32 [..., 2^depth - 1] go right iff bin > thresh
    leaf: jax.Array    # f32   [..., 2^depth, K] leaf payload
    miss: jax.Array    # int32 [..., 2^depth - 1] 1 = missing goes right


# -- binning ----------------------------------------------------------------

_QUANTILE_SAMPLE = 131_072


def quantile_edges(X: jax.Array, n_bins: int) -> jax.Array:
    """Per-feature quantile bin edges over PRESENT values.

    X: [n, d] -> edges [d, n_bins - 1], ascending per feature. Constant
    features produce repeated edges (empty bins; zero split gain — harmless).
    Rows are strided-sampled above _QUANTILE_SAMPLE — the XGBoost `hist`
    approximation — so the sort stays cheap at 10M+ rows. NaN rows are
    excluded from the sketch (nanquantile), matching XGBoost: missing
    values get the dedicated bin 0 in bin_matrix, not a quantile slot; an
    all-NaN feature yields NaN edges, which bin every present value to 1
    and can never win a split.
    """
    n = X.shape[0]
    if n > _QUANTILE_SAMPLE:
        stride = -(-n // _QUANTILE_SAMPLE)  # ceil
        X = X[::stride]
    # cast only the (<=131K-row) sample to f32 — a bf16 sweep matrix must
    # not be copied whole
    X = jnp.asarray(X, jnp.float32)
    qs = jnp.arange(1, n_bins, dtype=jnp.float32) / n_bins
    edges = jnp.nanquantile(X, qs, axis=0)       # [n_bins-1, d]
    return jnp.asarray(edges.T, jnp.float32)     # [d, n_bins-1]


# Rows per chunk of the binning map — bounds the f32 canonicalized copy and
# the [chunk, d, B-1] digitize-compare broadcast to O(chunk * d * B) instead
# of O(n * d * B) (the 10M-row bench OOM'd binning: four live [10M, 64]
# copies).
_BIN_CHUNK = 1 << 18


def bin_dtype(n_bins: int):
    """Narrowest integer dtype holding shifted bins [0, n_bins] (bin 0 =
    missing, so the max stored value is n_bins itself): int8 up to 127
    quantile bins, uint8 up to 255 — the XGBoost 256-bin default stays at
    1 byte/cell, 4x less Xb traffic than the old int32 fall-through —
    and int32 beyond. Shared by the resident, streamed and host binning
    paths so the three can never disagree on width."""
    if n_bins <= 127:
        return jnp.int8
    return jnp.uint8 if n_bins <= 255 else jnp.int32


def _bin_block(xb, edges):
    """Digitize ONE row block against `edges` — THE binning rule, shared
    by the resident `bin_matrix` map and the streamed tile emission
    (`stream_bin_matrix`), so the two paths cannot drift.

    TPU: digitize by counting edges <= x (identical to right-side
    searchsorted) — a fused broadcast-compare+reduce instead of the
    binary-search gathers searchsorted lowers to (TPU serializes
    data-dependent gathers); CPU keeps the O(log B) search. The backend
    branch resolves at trace time."""
    n_bins = edges.shape[1] + 1
    out_dtype = bin_dtype(n_bins)
    xf = jnp.asarray(xb, jnp.float32)
    missing = jnp.isnan(xf)
    if jax.default_backend() == "tpu":
        # NaN >= edge is False, so the count is 0 for missing rows
        # before the shift; the where picks bin 0 for them explicitly
        bins = (xf[:, :, None] >= edges[None, :, :]).sum(axis=2) + 1
    else:
        xs = jnp.where(missing, -jnp.inf, xf)
        bins = jax.vmap(
            lambda col, e: jnp.searchsorted(e, col, side="right"),
            in_axes=(1, 0), out_axes=1)(xs, edges) + 1
    return jnp.where(missing, 0, bins).astype(out_dtype)


def bin_matrix(X: jax.Array, edges: jax.Array) -> jax.Array:
    """Digitize with a dedicated missing bin: NaN -> 0, present values ->
    1 + #edges below-or-equal (searchsorted right, shifted).

    X [n, d], edges [d, n_bins-1] -> int8 / uint8 / int32 (bin_dtype)
    [n, d] in [0, n_bins]. For present values `bin > t` is equivalent to
    `x >= edges[t-1]` for t in [1, n_bins-1] (right-side search counts
    edges <= x, so equality on an edge goes right) — the raw serving
    traversal compares with >=, which matters for discrete columns
    (one-hot indicators sit exactly on their edge). Missing rows route by
    each node's learned default direction (Tree.miss), never by the
    comparison. Row blocks are processed by a lax.map so the f32
    temporaries never exceed O(_BIN_CHUNK * d); 1-byte output (int8 up
    to 127 bins, uint8 to 255) keeps the resident binned matrix at n*d
    bytes (640MB at the 10M config) through the XGBoost 256-bin default.
    """
    N, d = X.shape

    def one_block(xb):
        return _bin_block(xb, edges)
    chunk = min(_BIN_CHUNK, N)
    nchunks = -(-N // chunk)
    pad = nchunks * chunk - N
    if pad:
        X = jnp.pad(X, ((0, pad), (0, 0)))
    out = jax.lax.map(one_block, X.reshape(nchunks, chunk, d))
    return out.reshape(nchunks * chunk, d)[:N]


def thresholds_to_values(feat: jax.Array, thresh: jax.Array,
                         edges: jax.Array) -> jax.Array:
    """Map bin thresholds to raw-value thresholds for serving on unbinned X.

    The raw rule for PRESENT values is `x >= value` (matching `bin > t`
    under shifted right-side binning: bin = 1 + #edges <= x, so bin > t
    iff x >= edges[t-1] for t in [1, n_bins-1]). t == 0 sends every
    present value right (-inf); dead nodes (thresh == n_bins, all-left)
    become +inf. Missing rows ignore the value and follow Tree.miss.
    """
    n_bins = edges.shape[1] + 1
    ti = jnp.clip(thresh - 1, 0, n_bins - 2)
    tv = edges[feat, ti]
    tv = jnp.where(thresh <= 0, -jnp.inf, tv)
    return jnp.where(thresh >= n_bins, jnp.inf, tv)


# -- streamed binning (tileplane) --------------------------------------------

def _x_source_with_dummies(source):
    """Wrap an x-only RowSource into the (x, y, w) chunk shape the stats
    engine's streamed driver expects (zero labels, unit weights)."""
    from ..parallel.tileplane import IterSource

    def factory():
        for chunk in source.chunks():
            x = np.asarray(chunk[0], np.float32)
            n = x.shape[0]
            yield (x, np.zeros(n, np.float32), np.ones(n, np.float32))

    return IterSource(factory, n_rows=source.n_rows)


def stream_quantile_edges(source, n_bins: int, *, hist_bins: int = 1024,
                          tile_rows: Optional[int] = None,
                          prefetch: Optional[int] = None) -> np.ndarray:
    """Per-feature quantile bin edges from a STREAMED source — the
    larger-than-HBM replacement for `quantile_edges`.

    Two statistics-engine passes over the source (both double-buffered
    via the tileplane): one for per-column min/max, one for fixed-range
    `hist_bins` histograms between them; the edges are then the inverse
    CDF of each column's histogram (linear interpolation inside the
    crossing bin — the XGBoost-hist sketch with uniform bins instead of
    a merged quantile sketch). Edge error is bounded by one histogram
    bin width, so `hist_bins >> n_bins` (default 1024 vs <= 127 tree
    bins) keeps streamed splits within a sliver of the resident sketch.
    NaN rows are excluded exactly like the resident path; an all-NaN
    column yields NaN edges (bins every present value to 1, never wins
    a split); a constant column yields repeated edges. Returns
    [d, n_bins - 1] float32."""
    from . import stats_engine as SE

    wrapped = _x_source_with_dummies(source)
    st, _ = SE.stream_stats(wrapped, tile_rows=tile_rows,
                            prefetch=prefetch)
    # host-only sketch finalize on [d]-vectors; device tiles stay f32
    f8 = np.float64  # tmoglint: disable=TPU003  host-only precision
    cnt = np.asarray(st.cnt, f8)
    lo = np.asarray(st.minv, f8)
    hi = np.asarray(st.maxv, f8)
    d = cnt.shape[0]
    ok = cnt > 0
    lo_r = np.where(ok, lo, 0.0).astype(np.float32)
    hi_r = np.where(ok, hi, 1.0).astype(np.float32)
    st2, _ = SE.stream_stats(_x_source_with_dummies(source),
                             tile_rows=tile_rows, lo=lo_r, hi=hi_r,
                             bins=int(hist_bins), prefetch=prefetch)
    hist = np.asarray(st2.hist, f8).reshape(d, hist_bins + 1)[:, :hist_bins]

    edges = np.full((d, n_bins - 1), np.nan, np.float32)
    qs = np.arange(1, n_bins, dtype=f8) / n_bins
    for j in range(d):
        total = hist[j].sum()
        if not ok[j] or total <= 0:
            continue  # all-NaN column: NaN edges, like nanquantile
        if hi[j] <= lo[j]:
            edges[j] = lo[j]  # constant feature: repeated edges
            continue
        bounds = lo[j] + (hi[j] - lo[j]) \
            * np.arange(1, hist_bins + 1, dtype=f8) / hist_bins
        cum = np.cumsum(hist[j])
        edges[j] = np.interp(qs * total,
                             np.concatenate(([0.0], cum)),
                             np.concatenate(([lo[j]], bounds))
                             ).astype(np.float32)
    return edges


def stream_bin_matrix(source, edges, *, tile_rows: Optional[int] = None,
                      sink=None, prefetch: Optional[int] = None):
    """Second streamed pass: emit the binned matrix tile-by-tile.

    Each fixed-shape tile runs the SAME `_bin_block` rule as the
    resident `bin_matrix` (exact parity by construction) under the
    double-buffered tileplane; the 1-byte (bin_dtype) output tiles are
    fetched with a one-tile lag (D2H of tile k overlaps tile k+1's
    compute) and handed to `sink(np_tile, n_valid)` — or, when `sink` is
    None, assembled into the full [n, d] host matrix, which at n*d bytes is
    the one artifact of the flow SMALL enough to keep (the 10M-row
    bench's binned matrix is 640MB vs 2.5GB of f32 X). TMOG_TILEPLANE=0
    degrades to run_tileplane's synchronous single-thread loop."""
    from ..parallel import tileplane as TP

    edges_j = jnp.asarray(edges, jnp.float32)
    d = int(edges_j.shape[0])
    c = int(tile_rows) if tile_rows else TP.tile_rows_for(4 * d,
                                                          source.n_rows)
    n_bins = int(np.asarray(edges).shape[1]) + 1
    out_dtype = np.dtype(bin_dtype(n_bins))
    parts: list = []
    full = None
    cursor = 0
    if sink is not None:
        out_sink = sink
    elif source.n_rows is not None:
        # known row count: write tiles straight into the final [n, d]
        # matrix — collecting tiles then concatenating would transiently
        # DOUBLE the peak host memory of the one artifact this flow keeps
        full = np.empty((int(source.n_rows), d), out_dtype)

        def out_sink(tile, n_valid):
            nonlocal cursor
            full[cursor:cursor + n_valid] = tile
            cursor += n_valid
    else:
        def out_sink(tile, n_valid):  # unknown length: concat at the end
            parts.append(tile)

    def step(carry, xt):
        return carry, _bin_tile_jit(xt, edges_j)

    # TMOG_TILEPLANE=0 degrades inside run_tileplane to the synchronous
    # single-thread loop — same tiles, same rule, no producer thread
    TP.run_tileplane(source, step, jnp.zeros((), jnp.int32),
                     tile_rows=c, label="tree_bin", sink=out_sink,
                     prefetch=prefetch)
    if sink is not None:
        return None
    if full is not None:
        return full[:cursor]
    return np.concatenate(parts, axis=0) if parts else \
        np.zeros((0, d), out_dtype)


@jax.jit
def _bin_tile_jit(x, edges):
    """One streamed tile's binned output (fixed shape: one executable
    for every tile of the pass)."""
    return _bin_block(x, edges)


# -- single-tree growth -----------------------------------------------------

def _soft_l1(G, alpha):
    """XGBoost's L1 soft-threshold on leaf gradient sums: shrink |G| by
    alpha, zero inside the dead zone (ThresholdL1 in xgboost's
    split_evaluator; OpXGBoostClassifier.setAlpha on the reference
    wrapper). alpha == 0 is the identity."""
    if isinstance(alpha, float) and alpha == 0.0:
        return G
    return jnp.sign(G) * jnp.maximum(jnp.abs(G) - alpha, 0.0)


def _split_scores(GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm, reg_lambda,
                  min_child_weight, min_instances, min_info_gain, gamma,
                  alpha, normalize_gain):
    """Gain + validity for every (node, feature, bin, missing-direction)
    split candidate — XGBoost's sparsity-aware split search.

    GL/HL/CL: cumulative left sums [nodes, F, B(, K)] over the shifted bin
    axis, so slot 0 (the missing bin) is inside every prefix; Gt/Ht/Ct
    totals; Gm/Hm/Cm the per-(node, feature) missing-bin mass. Direction 0
    keeps missing in the left prefix (default-left); direction 1 moves the
    missing mass right (left' = GL - Gm). Gain is the multi-output
    sum-of-squares improvement sum_k GL_k^2/(HL+l) + GR_k^2/(HR+l) -
    Gt_k^2/(Ht+l); for mean-mode payloads (H = weight) this is total
    variance reduction, i.e. n x the Spark impurity gain —
    `normalize_gain` divides by Ht to compare against Spark's per-row
    minInfoGain; `gamma` is XGBoost's complexity penalty.

    Returns gain [nodes, F, B, 2] with -inf at invalid candidates.
    """
    def score(G, H):
        Ga = _soft_l1(G, alpha)
        return (Ga * Ga).sum(-1) / (H + reg_lambda + EPS)

    parent = score(Gt, Ht)[:, None, None]
    norm = jnp.maximum(Ht, 1.0)[:, None, None] if normalize_gain else 1.0

    def one_direction(GLd, HLd, CLd):
        GR = Gt[:, None, None, :] - GLd
        HR = Ht[:, None, None] - HLd
        CR = Ct[:, None, None] - CLd
        gain = score(GLd, HLd) + score(GR, HR) - parent
        ok = ((HLd >= min_child_weight) & (HR >= min_child_weight)
              & (CLd >= min_instances) & (CR >= min_instances)
              & (gain / norm > min_info_gain) & (gain > 2.0 * gamma))
        return jnp.where(ok, gain, -jnp.inf)

    g_left = one_direction(GL, HL, CL)
    g_right = one_direction(GL - Gm[:, :, None, :], HL - Hm[:, :, None],
                            CL - Cm[:, :, None])
    return jnp.stack([g_left, g_right], axis=-1)


def _feature_mask(key: jax.Array, n_nodes: int, n_feat: int,
                  feature_frac: float) -> jax.Array:
    """Per-node random feature subset mask [n_nodes, F] (RF column sampling,
    Spark featureSubsetStrategy applied per node)."""
    k = max(1, int(round(feature_frac * n_feat)))
    if k >= n_feat:
        return jnp.ones((n_nodes, n_feat), bool)
    scores = jax.random.uniform(key, (n_nodes, n_feat))
    kth = jnp.sort(scores, axis=1)[:, k - 1:k]
    return scores <= kth


def _level_feature_mask(key: jax.Array, n_feat: int, frac: float,
                        within: Optional[jax.Array],
                        within_count: Optional[int] = None) -> jax.Array:
    """[F] bool level subset (XGBoost colsample_bylevel), sampled FROM the
    colsample_bytree subset when one is active — xgboost nests the two
    draws ('columns are subsampled from the set of columns chosen for the
    current tree'), so their intersection is never empty. `within` [F]
    bool (or None) restricts the draw; `within_count` is its static
    population (the bytree k), so the level keeps frac * bytree_k
    features. Excluded features score -inf; the k-th-largest threshold
    then only ever admits allowed features."""
    pool = within_count if within_count is not None else n_feat
    k = max(1, int(round(frac * pool)))
    if k >= n_feat and within is None:
        return jnp.ones((n_feat,), bool)
    scores = jax.random.uniform(key, (1, n_feat))
    if within is not None:
        scores = jnp.where(within[None, :], scores, -jnp.inf)
    kth = jnp.sort(scores, axis=1, descending=True)[:, k - 1:k]
    return (scores >= kth)[0] & jnp.isfinite(scores[0])


def _histograms_segment(Xb, G, H, count_unit, node, n_nodes: int, B: int):
    """One fused segment-sum over node*F*B ids (CPU/GPU path; under row
    sharding the partial sums all-reduce — the Rabit-allreduce slot)."""
    N, F = Xb.shape
    K = G.shape[1]
    ids = (node[:, None] * (F * B)
           + jnp.arange(F, dtype=jnp.int32)[None, :] * B + Xb)  # [N, F]
    ids_f = ids.reshape(-1)
    seg = n_nodes * F * B
    hg = jax.ops.segment_sum(
        jnp.broadcast_to(G[:, None, :], (N, F, K)).reshape(-1, K),
        ids_f, num_segments=seg).reshape(n_nodes, F, B, K)
    hh = jax.ops.segment_sum(
        jnp.broadcast_to(H[:, None], (N, F)).reshape(-1),
        ids_f, num_segments=seg).reshape(n_nodes, F, B)
    hc = jax.ops.segment_sum(
        jnp.broadcast_to(count_unit[:, None], (N, F)).reshape(-1),
        ids_f, num_segments=seg).reshape(n_nodes, F, B)
    return hg, hh, hc


# Rows per chunk of the matmul-histogram scan. Bounds the on-device
# temporaries (combined one-hot [chunk, F*B] + Q [chunk, nodes*C]) that the
# unchunked design materialized at full N — the round-2 bench OOM at the
# 10M-row config with 5 fold lanes vmapped on top.
_HIST_CHUNK = 65_536

# Above this many rows the level histograms go through the pallas kernel
# (ops/pallas_hist.py): the one-hot tiles then live only in VMEM instead
# of costing ~1GB of HBM write+read per 64K-row chunk. MUST stay above
# models/trees._VMAP_FOLD_MAX_ROWS so a pallas_call never sits under the
# fold vmap (models/trees.py asserts the ordering at import).
#
# Accumulation-width limit: all histogram channels (G/H/count) accumulate
# in f32, whose integer ladder ends at 2^24 (~16.7M). Per-NODE unit-weight
# counts are exact below that; past ~16M rows in a single node the
# empty-leaf zeroing (Cl >= 0.5) and min_child_weight comparisons can
# drift by ulps. The BASELINE 10M-row config sits safely inside the
# window; scaling a single unsharded fit past ~16M rows/node requires
# splitting counts into two channels or a widened final reduce. (Under
# pjit row sharding each shard accumulates its local rows only, so the
# per-shard bound is rows/shard, and the psum is exact far longer.)
_PALLAS_MIN_ROWS = 4_000_000

def pallas_enabled() -> bool:
    """The single pallas switch lives in ops/pallas_hist (env default
    TMOG_NO_PALLAS); these are convenience delegates."""
    from . import pallas_hist
    return pallas_hist.enabled()


def set_pallas_enabled(enabled: bool) -> None:
    """Runtime pallas kill switch (e.g. the bench's retry after a Mosaic
    compile failure on untested hardware). Flipping it clears every
    registered pallas-consuming jit cache (tree fits here, the streamed
    metric evaluator in the validator) so already-compiled executables
    cannot pin the previous choice — the flag is read at trace time and
    is not part of the jit key."""
    from . import pallas_hist
    pallas_hist.set_enabled(enabled)


def _histograms_pallas(Xb, G, H, count_unit, node, n_nodes: int, B: int):
    """Level histograms via the VMEM-resident pallas kernel (transposed
    operands — see ops/pallas_hist.py for the layout rationale). The
    unit-count channel is derived IN VMEM from the hessian plane
    (count_unit = (H > 0) by construction in every caller), saving one
    full-N f32 HBM stream per level."""
    from . import pallas_hist
    N, F = Xb.shape
    K = G.shape[1]
    C = K + 2
    pay = jnp.concatenate([G.T, H[None, :]], axis=0)         # [K+1, N]
    hist = pallas_hist.hist_pallas(
        Xb.T, pay, node[None, :].astype(jnp.float32),
        n_slots=n_nodes, n_bins=B, allow_bf16=True,
        derive_count=True)                                   # [nC, F*B]
    hist = hist.reshape(n_nodes, C, F, B)
    return (hist[:, :K].transpose(0, 2, 3, 1), hist[:, K], hist[:, K + 1])


def _histograms_matmul(Xb, G, H, count_unit, node, n_nodes: int, B: int):
    """Histograms as dense MXU contractions (TPU path — scatter-free).

    One combined one-hot over the (feature, bin) axis: oh[i, f*B+b] =
    (Xb[i, f] == b), so the whole level histogram is ONE contraction
    Q^T @ oh -> [n_nodes*C, F*B] per row chunk (Q folds the node one-hot
    with the K+2 payload channels). F*B ~ 2048 columns keeps the MXU tiles
    square-ish, and the chunked lax.scan caps HBM temporaries at
    O(_HIST_CHUNK * F * B) regardless of N. Under the fold-vmapped sweep
    the one-hot depends only on Xb (unbatched), so XLA shares it across
    fold lanes and batches the Q contraction.
    """
    N, F = Xb.shape
    K = G.shape[1]
    C = K + 2
    FB = F * B
    P = jnp.concatenate([G, H[:, None], count_unit[:, None]], axis=1)

    chunk = min(_HIST_CHUNK, N)
    nchunks = -(-N // chunk)
    pad = nchunks * chunk - N
    if pad:
        # zero-payload padding is inert: P rows are 0, so whatever one-hot
        # cell a padded row lands in receives +0
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        P = jnp.pad(P, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, pad),))

    offs = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    cols = jnp.arange(FB, dtype=jnp.int32)[None, :]

    def body(acc, sl):
        xb_c, p_c, node_c = sl
        oh = (jnp.repeat(xb_c.astype(jnp.int32) + offs, B, axis=1)
              == cols).astype(jnp.float32)                       # [c, F*B]
        node_oh = jax.nn.one_hot(node_c, n_nodes, dtype=jnp.float32)
        Q = (node_oh[:, :, None] * p_c[:, None, :]).reshape(chunk,
                                                            n_nodes * C)
        acc = acc + jax.lax.dot_general(
            Q, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [nC, F*B]
        return acc, None

    xs = (Xb.reshape(nchunks, chunk, F), P.reshape(nchunks, chunk, C),
          node.reshape(nchunks, chunk))
    acc0 = jnp.zeros((n_nodes * C, FB), jnp.float32)
    hist, _ = jax.lax.scan(body, acc0, xs)
    hist = hist.reshape(n_nodes, C, F, B)
    hg = hist[:, :K].transpose(0, 2, 3, 1)                       # [n,F,B,K]
    hh = hist[:, K]                                              # [n,F,B]
    hc = hist[:, K + 1]
    return hg, hh, hc


# Rows per chunk of the one-hot routing/prediction maps (bounds the
# [chunk, F] selection products).
_ROUTE_CHUNK = 1 << 20


def _onehot_route_step(xf, rel, f_lvl, t_lvl, m_lvl, n_nodes: int):
    """One gather-free routing step:
    rel' = 2*rel + ((bin > t(rel)) | (bin == 0 & miss(rel))).

    TPU serializes data-dependent row gathers, so the per-row feature
    select becomes a one-hot contraction: sel = onehot(rel) @ FS with
    FS[n, f] = (f_lvl[n] == f); the selected bin is then a masked row sum
    (exact: bin 0 contributes 0, so a missing row's masked sum is 0 —
    precisely the missing-bin value). Exact for bin values (< 2^24,
    f32-representable). Shared by training routing (_route_level_matmul)
    and prediction (_predict_bins_matmul)."""
    F = xf.shape[1]
    rel_oh = jax.nn.one_hot(rel, n_nodes, dtype=jnp.float32)
    FS = (f_lvl[:, None] == jnp.arange(F)[None, :]).astype(jnp.float32)
    sel = jnp.matmul(rel_oh, FS, preferred_element_type=jnp.float32)
    xb_sel = (xf * sel).sum(axis=1)
    tm = jnp.stack([t_lvl.astype(jnp.float32),
                    m_lvl.astype(jnp.float32)], axis=1)          # [n, 2]
    tm_sel = jnp.matmul(rel_oh, tm,
                        preferred_element_type=jnp.float32)      # [N, 2]
    right = (xb_sel > tm_sel[:, 0]) | ((xb_sel == 0.0)
                                       & (tm_sel[:, 1] > 0.5))
    return 2 * rel + right.astype(jnp.int32)


def _route_level_matmul(Xb, node, f_lvl, t_lvl, m_lvl, n_nodes: int):
    """Gather-free level routing over row chunks (see _onehot_route_step)."""
    N, F = Xb.shape

    def one_block(sl):
        xb_blk, node_blk = sl
        return _onehot_route_step(xb_blk.astype(jnp.float32), node_blk,
                                  f_lvl, t_lvl, m_lvl, n_nodes)

    chunk = min(_ROUTE_CHUNK, N)
    nchunks = -(-N // chunk)
    pad = nchunks * chunk - N
    if pad:
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, pad),))
    out = jax.lax.map(one_block, (Xb.reshape(nchunks, chunk, F),
                                  node.reshape(nchunks, chunk)))
    return out.reshape(-1)[:N]


@functools.partial(
    jax.jit,
    static_argnames=("depth", "n_bins", "leaf_mode", "feature_frac",
                     "normalize_gain", "allow_pallas", "alpha",
                     "max_delta_step", "level_feature_frac",
                     "feature_mask_count"))
def grow_tree(Xb: jax.Array, G: jax.Array, H: jax.Array,
              key: jax.Array, *, depth: int, n_bins: int,
              reg_lambda: float = 0.0, min_child_weight: float = 0.0,
              min_instances: float = 1.0, min_info_gain: float = 0.0,
              gamma: float = 0.0, leaf_mode: str = "newton",
              feature_frac: float = 1.0, learning_rate: float = 1.0,
              normalize_gain: bool = True,
              feature_mask: Optional[jax.Array] = None,
              allow_pallas: bool = True, alpha: float = 0.0,
              max_delta_step: float = 0.0,
              level_feature_frac: float = 1.0,
              feature_mask_count: Optional[int] = None) -> Tree:
    """Grow one depth-`depth` tree level-wise on binned features.

    Xb: int8/int32 [N, F] bins; G: f32 [N, K] per-row gradient payload (weights
    folded in); H: f32 [N] per-row hessian/weight (0 = row excluded, which
    is how bootstrap, fold masks and padding enter). Rows, features and bins
    are all machine axes; the level loop is a static Python unroll.

    `feature_frac` < 1 resamples a feature subset at every node (Spark RF
    featureSubsetStrategy semantics); `feature_mask` [F] bool fixes one
    subset for the whole tree (XGBoost colsample_bytree semantics).

    Bins arrive shifted (bin_matrix): 0 = missing, present in [1, n_bins],
    so histograms carry n_bins + 1 slots and every split learns the
    missing default direction (sparsity-aware search, _split_scores).
    """
    N, F = Xb.shape
    K = G.shape[1]
    B = n_bins + 1   # histogram slots: missing bin 0 + n_bins value bins
    count_unit = jnp.asarray(H > 0, jnp.float32)
    # TPU: histograms as MXU matmuls (scatter lowers poorly there) — via
    # the VMEM-resident pallas kernel at large N, the chunked XLA scan
    # otherwise; CPU/GPU: one fused segment-sum. Results agree up to f32
    # rounding (the TPU path derives right-child histograms by sibling
    # subtraction, so near-tie splits can differ across backends).
    use_matmul = jax.default_backend() == "tpu"
    use_pallas = False
    if use_matmul and allow_pallas and N >= _PALLAS_MIN_ROWS:
        from . import pallas_hist
        use_pallas = pallas_hist.available()  # honors the kill switch
    if use_matmul and N > _HIST_CHUNK:
        # pad rows ONCE to the histogram chunk multiple (zero payload =
        # inert) so the per-level histogram calls never re-copy the arrays
        pad = -(-N // _HIST_CHUNK) * _HIST_CHUNK - N
        if pad:
            Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
            G = jnp.pad(G, ((0, pad), (0, 0)))
            H = jnp.pad(H, ((0, pad),))
            count_unit = jnp.pad(count_unit, ((0, pad),))
            N += pad
    rows = jnp.arange(N)

    node = jnp.zeros(N, jnp.int32)   # in-level relative node id
    feats, threshs, misses = [], [], []
    last = None                      # split state for the leaf pass
    prev = None                      # previous level's raw histograms

    def _interleave(left, right, n_nodes):
        # children [2p] = left[p], [2p+1] = right[p]
        return jnp.stack([left, right], axis=1).reshape(
            (n_nodes,) + left.shape[1:])

    for d in range(depth):
        n_nodes = 1 << d
        if use_matmul and d > 0:
            # histogram subtraction (the XGBoost sibling trick): compute
            # LEFT children only — rows in right children carry the
            # out-of-range slot (dropped by one_hot / the pallas kernel)
            # — and derive right = parent - left from the previous
            # level's raw histograms. Halves the one-hot contraction
            # FLOPs of every level past the root.
            n_half = n_nodes // 2
            slots = jnp.where(node % 2 == 0, node // 2, n_half)
            fn = _histograms_pallas if use_pallas else _histograms_matmul
            hgl, hhl, hcl = fn(Xb, G, H, count_unit, slots, n_half, B)
            pg, ph, pc = prev
            hg = _interleave(hgl, pg - hgl, n_nodes)
            hh = _interleave(hhl, ph - hhl, n_nodes)
            hc = _interleave(hcl, pc - hcl, n_nodes)
        elif use_pallas:
            hg, hh, hc = _histograms_pallas(Xb, G, H, count_unit, node,
                                            n_nodes, B)
        elif use_matmul:
            hg, hh, hc = _histograms_matmul(Xb, G, H, count_unit, node,
                                            n_nodes, B)
        else:
            hg, hh, hc = _histograms_segment(Xb, G, H, count_unit, node,
                                             n_nodes, B)
        prev = (hg, hh, hc)

        GL = jnp.cumsum(hg, axis=2)
        HL = jnp.cumsum(hh, axis=2)
        CL = jnp.cumsum(hc, axis=2)
        Gt, Ht, Ct = GL[:, 0, -1, :], HL[:, 0, -1], CL[:, 0, -1]
        Gm, Hm, Cm = hg[:, :, 0, :], hh[:, :, 0], hc[:, :, 0]

        gain = _split_scores(GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm,
                             reg_lambda, min_child_weight, min_instances,
                             min_info_gain, gamma, alpha, normalize_gain)
        if feature_mask is not None:
            gain = jnp.where(feature_mask[None, :, None, None],
                             gain, -jnp.inf)
        if level_feature_frac < 1.0:  # XGBoost colsample_bylevel: one
            key, sub = jax.random.split(key)  # fresh subset per level,
            # nested inside the bytree subset when one is active
            fml = _level_feature_mask(sub, F, level_feature_frac,
                                      feature_mask, feature_mask_count)
            gain = jnp.where(fml[None, :, None, None], gain, -jnp.inf)
        if feature_frac < 1.0:
            key, sub = jax.random.split(key)
            fm = _feature_mask(sub, n_nodes, F, feature_frac)
            gain = jnp.where(fm[:, :, None, None], gain, -jnp.inf)

        flat = gain.reshape(n_nodes, F * B * 2)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        ok = jnp.isfinite(best_gain)
        f_lvl = jnp.where(ok, (best // (B * 2)).astype(jnp.int32), 0)
        t_lvl = jnp.where(ok, ((best // 2) % B).astype(jnp.int32), B - 1)
        m_lvl = jnp.where(ok, (best % 2).astype(jnp.int32), 0)
        feats.append(f_lvl)
        threshs.append(t_lvl)
        misses.append(m_lvl)
        last = (GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm, f_lvl, t_lvl, m_lvl)

        if use_pallas:
            # exact-equal decisions to _route_level_matmul (the selected
            # bin is a single one-hot term, f32-exact), one VMEM-resident
            # Xb pass instead of HBM selection products
            from . import pallas_hist
            node = pallas_hist.route_pallas(
                Xb.T, node[None].astype(jnp.float32), f_lvl[None],
                t_lvl[None], m_lvl[None],
                n_nodes=n_nodes)[0].astype(jnp.int32)
        elif use_matmul:
            node = _route_level_matmul(Xb, node, f_lvl, t_lvl, m_lvl,
                                       n_nodes)
        else:
            xb = Xb[rows, f_lvl[node]]
            right = (xb > t_lvl[node]) | ((xb == 0) & (m_lvl[node] > 0))
            node = 2 * node + right.astype(jnp.int32)

    # -- leaves -------------------------------------------------------------
    # Leaf sums come for free from the LAST level's cumulative histograms:
    # left child of node n = GL[n, f_n, t_n] (everything at or below the
    # chosen threshold), right child = Gt[n] - left. A dead node
    # (t = B-1) sends its whole mass left and 0 right — exactly the
    # all-rows-left traversal encoding. This removes the full-N
    # segment-sum (a scatter XLA serializes on TPU) from the leaf pass.
    n_leaves = 1 << depth
    if depth == 0:
        Gl = G.sum(axis=0, keepdims=True)                        # [1, K]
        Hl = H.sum()[None]
        Cl = count_unit.sum()[None]
    else:
        GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm, f_lvl, t_lvl, m_lvl = last
        n_nodes = n_leaves // 2
        nid = jnp.arange(n_nodes)
        # default-right splits move the missing-bin mass out of the prefix
        mr = m_lvl.astype(jnp.float32)
        Gleft = (GL[nid, f_lvl, t_lvl, :]
                 - mr[:, None] * Gm[nid, f_lvl, :])              # [n, K]
        Hleft = HL[nid, f_lvl, t_lvl] - mr * Hm[nid, f_lvl]      # [n]
        Cleft = CL[nid, f_lvl, t_lvl] - mr * Cm[nid, f_lvl]
        Gl = _interleave(Gleft, Gt - Gleft, n_leaves)
        Hl = _interleave(Hleft, Ht - Hleft, n_leaves)
        Cl = _interleave(Cleft, Ct - Cleft, n_leaves)
    if leaf_mode == "newton":
        leaf = -_soft_l1(Gl, alpha) / (Hl + reg_lambda + EPS)[:, None]
        if max_delta_step > 0.0:  # XGBoost max_delta_step: cap the raw
            # (pre-learning-rate) newton step — the imbalanced-logistic
            # stabilizer (xgboost doc: 'Maximum delta step we allow each
            # leaf output to be')
            leaf = jnp.clip(leaf, -max_delta_step, max_delta_step)
    else:  # mean
        leaf = Gl / (Hl + EPS)[:, None]
    # training-empty leaves predict exactly 0: the count histogram is
    # integer-exact, while sibling-subtracted G/H can leave f32 noise
    # whose ratio would be an arbitrary payload for a serving row routed
    # into an empty (min_instances=0) child
    leaf = jnp.where(Cl[:, None] >= 0.5, leaf, 0.0)
    return Tree(jnp.concatenate(feats), jnp.concatenate(threshs),
                learning_rate * leaf, jnp.concatenate(misses))


def predict_bins(tree: Tree, Xb: jax.Array, depth: int) -> jax.Array:
    """Traverse one tree on binned rows: Xb [N, F] -> leaf payload [N, K].

    CPU: data-dependent gathers (fast there). TPU: gather-free — per-level
    one-hot routing exactly as _route_level_matmul, and the leaf payload
    lookup as onehot(leaf) @ leaf-table, all inside one chunked lax.map."""
    if jax.default_backend() != "tpu":
        N = Xb.shape[0]
        rows = jnp.arange(N)
        rel = jnp.zeros(N, jnp.int32)
        for d in range(depth):
            idx = (1 << d) - 1 + rel
            f = tree.feat[idx]
            t = tree.thresh[idx]
            xb = Xb[rows, f]
            right = (xb > t) | ((xb == 0) & (tree.miss[idx] > 0))
            rel = 2 * rel + right.astype(jnp.int32)
        return tree.leaf[rel]
    return _predict_bins_matmul(tree, Xb, depth)


def _predict_bins_matmul(tree: Tree, Xb: jax.Array, depth: int) -> jax.Array:
    N, F = Xb.shape
    K = tree.leaf.shape[-1]
    n_leaves = 1 << depth

    def one_block(xb_blk):
        c = xb_blk.shape[0]
        xf = xb_blk.astype(jnp.float32)
        rel = jnp.zeros(c, jnp.int32)
        for d in range(depth):
            lo = (1 << d) - 1
            rel = _onehot_route_step(xf, rel, tree.feat[lo: lo + (1 << d)],
                                     tree.thresh[lo: lo + (1 << d)],
                                     tree.miss[lo: lo + (1 << d)], 1 << d)
        leaf_oh = jax.nn.one_hot(rel, n_leaves, dtype=jnp.float32)
        return jnp.matmul(leaf_oh, tree.leaf.astype(jnp.float32),
                          preferred_element_type=jnp.float32)   # [c, K]

    chunk = min(_ROUTE_CHUNK, N)
    nchunks = -(-N // chunk)
    pad = nchunks * chunk - N
    if pad:
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
    out = jax.lax.map(one_block, Xb.reshape(nchunks, chunk, F))
    return out.reshape(-1, K)[:N]


def predict_forest_bins(trees: Tree, Xb: jax.Array, depth: int) -> jax.Array:
    """Sum of payloads over a stacked batch of trees: [N, K]."""
    def one(carry, tree):
        return carry + predict_bins(tree, Xb, depth), None
    K = trees.leaf.shape[-1]
    init = jnp.zeros((Xb.shape[0], K), trees.leaf.dtype)
    out, _ = jax.lax.scan(one, init, trees)
    return out


# -- random forest ----------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=("n_trees", "depth", "n_bins", "leaf_mode",
                     "feature_frac", "bootstrap"))
def fit_forest(Xb: jax.Array, G: jax.Array, H: jax.Array, key: jax.Array, *,
               n_trees: int, depth: int, n_bins: int,
               subsample: float = 1.0, feature_frac: float = 1.0,
               reg_lambda: float = 0.0, min_instances: float = 1.0,
               min_info_gain: float = 0.0, leaf_mode: str = "mean",
               bootstrap: bool = True) -> Tree:
    """Random forest: scan of independent trees with Poisson bootstrap row
    weights (Spark's with-replacement bagging) and per-node feature subsets.

    Returns stacked Tree arrays with a leading [n_trees] axis; the ensemble
    prediction is the payload MEAN (class distribution / regression value).
    """
    def one(_, k):
        kb, kf = jax.random.split(k)
        if bootstrap:
            rw = jax.random.poisson(kb, subsample,
                                    (Xb.shape[0],)).astype(jnp.float32)
        else:
            rw = (jax.random.uniform(kb, (Xb.shape[0],))
                  < subsample).astype(jnp.float32)
        tree = grow_tree(Xb, G * rw[:, None], H * rw, kf, depth=depth,
                         n_bins=n_bins, reg_lambda=reg_lambda,
                         min_instances=min_instances,
                         min_info_gain=min_info_gain, leaf_mode=leaf_mode,
                         feature_frac=feature_frac, normalize_gain=True)
        return None, tree
    _, trees = jax.lax.scan(one, None, jax.random.split(key, n_trees))
    return trees


# -- gradient boosting ------------------------------------------------------

def _logistic_grad(margin, y, w):
    p = jax.nn.sigmoid(margin)
    return w * (p - y), jnp.maximum(w * p * (1.0 - p), EPS)


def _squared_grad(pred, y, w):
    return w * (pred - y), w


@functools.partial(
    jax.jit,
    static_argnames=("n_rounds", "depth", "n_bins", "loss", "subsample",
                     "feature_frac", "alpha", "max_delta_step",
                     "colsample_bylevel", "base_score"))
def fit_gbt(Xb: jax.Array, y: jax.Array, w: jax.Array, key: jax.Array, *,
            n_rounds: int, depth: int, n_bins: int,
            learning_rate: float = 0.1, reg_lambda: float = 1.0,
            min_child_weight: float = 0.0, min_instances: float = 1.0,
            min_info_gain: float = 0.0, gamma: float = 0.0,
            subsample: float = 1.0, feature_frac: float = 1.0,
            loss: str = "logistic", alpha: float = 0.0,
            max_delta_step: float = 0.0, colsample_bylevel: float = 1.0,
            base_score: Optional[float] = None) -> Tuple[Tree, jax.Array]:
    """Second-order boosted trees (XGBoost `hist` equivalent, one XLA program).

    loss='logistic' -> binary margins; loss='squared' -> regression. Returns
    (stacked trees, base_score). Prediction = base + sum of tree payloads.
    `base_score`: None derives the prior from the weighted label mean
    (better-calibrated start); a float pins the initial margin exactly the
    XGBoost way (probability for logistic, raw value for squared —
    OpXGBoostClassifier.setBaseScore on the reference wrapper).
    """
    grad_fn = _logistic_grad if loss == "logistic" else _squared_grad
    wsum = w.sum() + EPS
    if base_score is not None:
        if loss == "logistic":
            p0 = min(max(float(base_score), 1e-6), 1 - 1e-6)
            base = jnp.asarray(np.log(p0 / (1 - p0)), jnp.float32)
        else:
            base = jnp.asarray(float(base_score), jnp.float32)
    elif loss == "logistic":
        p0 = jnp.clip((w * y).sum() / wsum, 1e-6, 1 - 1e-6)
        base = jnp.log(p0 / (1 - p0))
    else:
        base = (w * y).sum() / wsum

    def one(carry, k):
        margin, = carry
        ks, kc, kf = jax.random.split(k, 3)
        g, h = grad_fn(margin, y, w)
        if subsample < 1.0:
            rw = (jax.random.uniform(ks, y.shape) < subsample
                  ).astype(jnp.float32)
            g, h = g * rw, h * rw
        fm = (_feature_mask(kc, 1, Xb.shape[1], feature_frac)[0]
              if feature_frac < 1.0 else None)  # colsample_bytree
        tree = grow_tree(Xb, g[:, None], h, kf, depth=depth, n_bins=n_bins,
                         reg_lambda=reg_lambda,
                         min_child_weight=min_child_weight,
                         min_instances=min_instances,
                         min_info_gain=min_info_gain, gamma=gamma,
                         leaf_mode="newton", feature_mask=fm,
                         learning_rate=learning_rate, normalize_gain=False,
                         alpha=alpha, max_delta_step=max_delta_step,
                         level_feature_frac=colsample_bylevel,
                         feature_mask_count=(
                             max(1, int(round(feature_frac * Xb.shape[1])))
                             if feature_frac < 1.0 else None))
        margin = margin + predict_bins(tree, Xb, depth)[:, 0]
        return (margin,), tree

    init = jnp.full(y.shape, base, jnp.float32)
    (_,), trees = jax.lax.scan(one, (init,), jax.random.split(key, n_rounds))
    return trees, base


# -- fold-fused growth: whole-tree level scan vs depth unroll ----------------
# TMOG_TREE_SCAN gates the whole-tree level-scan form of the fused fit
# (default ON): levels 0..depth-2 run inside ONE lax.scan whose carries are
# padded to the worst-level slot count (2^(depth-2)) with inactive slots
# masked, so the traced program — and its Mosaic route_hist kernel — exists
# ONCE per fit instead of once per level. Program size and trace/compile
# wall become O(1) in depth (the compile-knee attack; measurement harness
# tools/tpu_fuse_compile_knee.py). =0 restores the legacy depth-unrolled
# path, which produces bit-identical trees and margins.
_TREE_SCAN = os.environ.get("TMOG_TREE_SCAN", "").strip().lower() \
    not in ("0", "false", "off")


def tree_scan_enabled() -> bool:
    """Is the level-scan fused fit active? (env TMOG_TREE_SCAN,
    default on; runtime toggle set_tree_scan)."""
    return _TREE_SCAN


def set_tree_scan(enabled: bool) -> None:
    """Runtime toggle for the level-scan fused fit (the bench A/B lever).
    The choice is read at trace time — it is NOT part of the jit key — so
    flipping clears the fused-fit caches: a compiled unrolled program
    must never satisfy a scan request or vice versa."""
    global _TREE_SCAN
    if _TREE_SCAN == bool(enabled):
        return
    _TREE_SCAN = bool(enabled)
    fit_gbt_folds.clear_cache()
    _SHARDED_FIT_CACHE.clear()


def _allreduce(v, axis_name):
    """psum under the row-sharded driver (the Rabit-allreduce slot of the
    XGBoost hist design); identity on a single device."""
    return jax.lax.psum(v, axis_name) if axis_name is not None else v


def _shard_vary_opt(tree, axis_name):
    """shard_map varying-manual-axes shim for scan carries (see
    parallel/mesh.shard_vary); identity off-mesh."""
    if axis_name is None:
        return tree
    from ..parallel.mesh import shard_vary
    return shard_vary(tree, axis_name)


def _fold_split_scores(reg_lambda, min_child_weight, gamma):
    """_split_scores vmapped over the fold/lane axis.

    reg_lambda / min_child_weight / gamma (and learning_rate in the leaf
    pass) may be PER-LANE vectors [Fo] — the config-fused sweep batches
    grid points into the fold axis; eta and lambda are pure algebra
    scalars per lane. Scalars keep the scalar HLO — the single-config
    path's executables (and their persistent-cache entries) must stay
    byte-identical."""
    def _ax(v):
        return 0 if getattr(v, "ndim", 0) == 1 else None

    return jax.vmap(
        _split_scores,
        in_axes=(0,) * 9 + (_ax(reg_lambda), _ax(min_child_weight),
                            None, None, _ax(gamma), None, None))


def _leaf_payload(Gl, Hl, Cl, reg_lambda, alpha, max_delta_step,
                  learning_rate):
    """Per-fold newton leaves from leaf sufficient statistics [Fo, L(, K)]
    — the one shared leaf rule of both fused growth forms."""
    rl_col = reg_lambda[:, None] if getattr(reg_lambda, "ndim", 0) == 1 \
        else reg_lambda
    leaf = -_soft_l1(Gl, alpha) / (Hl + rl_col + EPS)[..., None]
    if max_delta_step > 0.0:  # [Fo, L, 1] — cap raw newton step
        leaf = jnp.clip(leaf, -max_delta_step, max_delta_step)
    leaf = jnp.where(Cl[..., None] >= 0.5, leaf, 0.0)
    lr_col = learning_rate[:, None, None] \
        if getattr(learning_rate, "ndim", 0) == 1 else learning_rate
    return lr_col * leaf


def _fold_leaves(last, *, n_leaves, reg_lambda, alpha, max_delta_step,
                 learning_rate):
    """Leaf payloads [Fo, n_leaves, 1] read off the LAST level's
    cumulative histograms (`last` as produced by the level split) — same
    free-leaf trick as grow_tree's leaf pass, vmapped over folds."""
    GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm, f_lvl, t_lvl, m_lvl = last
    n_half = n_leaves // 2

    def leaf_of(GLk, HLk, CLk, Gtk, Htk, Ctk, Gmk, Hmk, Cmk,
                fk, tk, mk):
        nid = jnp.arange(n_half)
        mr = mk.astype(jnp.float32)
        Gleft = GLk[nid, fk, tk, :] - mr[:, None] * Gmk[nid, fk, :]
        Hleft = HLk[nid, fk, tk] - mr * Hmk[nid, fk]
        Cleft = CLk[nid, fk, tk] - mr * Cmk[nid, fk]
        Gl = jnp.stack([Gleft, Gtk - Gleft], axis=1).reshape(
            n_leaves, Gleft.shape[-1])
        Hl = jnp.stack([Hleft, Htk - Hleft], axis=1).reshape(n_leaves)
        Cl = jnp.stack([Cleft, Ctk - Cleft], axis=1).reshape(n_leaves)
        return Gl, Hl, Cl

    Gl, Hl, Cl = jax.vmap(leaf_of)(GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm,
                                   f_lvl, t_lvl, m_lvl)
    return _leaf_payload(Gl, Hl, Cl, reg_lambda, alpha, max_delta_step,
                         learning_rate)


def _grow_tree_folds(Xb_t, G, H, *, depth, n_bins,
                     reg_lambda, min_child_weight, min_instances,
                     min_info_gain, gamma, learning_rate, feature_mask,
                     interpret=False, alpha=0.0, max_delta_step=0.0,
                     level_feature_frac=1.0, level_key=None,
                     feature_mask_count=None, axis_name=None):
    """Grow one tree PER FOLD level-wise in shared fused passes.

    Xb_t [F, N] transposed bins (N pre-padded to the route block size by
    the caller); G/H [Fo, N] per-fold payloads (excluded and padded rows
    enter as zeros exactly as in grow_tree; the unit-count channel is
    derived in VMEM as (H > 0) — grow_tree's count_unit — instead of
    streaming its own HBM plane). Each level past the root arrives from
    ONE fused route+histogram pass (pallas_hist.route_hist): the level's
    split tables route every row in VMEM and the surviving left-child
    slot ids feed the next level's histogram in the same read of the
    binned matrix, so each level costs ONE Xb pass for every (fold x
    config) lane together — not a histogram pass plus a routing pass.
    The per-node split algebra (cumsums, _split_scores, argmax, leaves)
    is the grow_tree math vmapped over the fold axis. On CPU the
    dispatchers drop to gather/segment-sum fallbacks (same decisions).

    Two program forms, decision/margin bit-identical (tests/
    test_tree_scan.py): the level-SCAN form (default) runs all mid-tree
    levels in one lax.scan at the fixed worst-level shape, the legacy
    unrolled form (TMOG_TREE_SCAN=0) emits one program section per
    level. `axis_name` names a shard_map mesh axis rows are sharded
    over: every level histogram psums across shards before the split
    algebra (DrJAX-style psum-merged MapReduce), routing stays local.

    Returns (Tree with leading [Fo] axes, leaf_rows [Fo, N]) where
    leaf_rows are the learning-rate-scaled per-row leaf payloads —
    bitwise what predict_bins returns for each fold's tree, read off the
    final routing state instead of re-traversed.
    """
    kw = dict(depth=depth, n_bins=n_bins, reg_lambda=reg_lambda,
              min_child_weight=min_child_weight,
              min_instances=min_instances, min_info_gain=min_info_gain,
              gamma=gamma, learning_rate=learning_rate,
              feature_mask=feature_mask, interpret=interpret, alpha=alpha,
              max_delta_step=max_delta_step,
              level_feature_frac=level_feature_frac, level_key=level_key,
              feature_mask_count=feature_mask_count, axis_name=axis_name)
    if tree_scan_enabled() and depth >= 1:
        return _grow_tree_folds_scan(Xb_t, G, H, **kw)
    return _grow_tree_folds_unrolled(Xb_t, G, H, **kw)


def _grow_tree_folds_scan(Xb_t, G, H, *, depth, n_bins, reg_lambda,
                          min_child_weight, min_instances, min_info_gain,
                          gamma, learning_rate, feature_mask,
                          interpret=False, alpha=0.0, max_delta_step=0.0,
                          level_feature_frac=1.0, level_key=None,
                          feature_mask_count=None, axis_name=None):
    """Whole-tree level-scan form of _grow_tree_folds.

    Levels 0..depth-2 run inside ONE lax.scan with fixed max-shape
    carries: the slot axis of every histogram/table is padded to
    S = 2^(depth-2) (the worst level the fused route+hist pass serves —
    exactly the shape plan_fused_hist already budgets), level d uses the
    first 2^d slots and masks the rest. One route_hist program — not
    depth-1 of them — reaches Mosaic, and the interleave/cumsum/argmax
    split algebra exists once in the HLO. The final level splits and
    routes outside the scan (its tables are twice the scan width and it
    needs no histogram pass), reusing the same split closure, so total
    program size is O(1) in depth.

    Bit-exactness vs the unrolled form: per-slot histogram sums are
    independent of the kernel's slot count, the split algebra is the
    same expression on the same values, and padded slots can never be
    selected by a row (their tables hold the dead all-left encoding).
    """
    from . import pallas_hist

    F, N = Xb_t.shape
    Fo = G.shape[0]
    B = n_bins + 1
    split_scores_f = _fold_split_scores(reg_lambda, min_child_weight, gamma)
    use_level_mask = level_feature_frac < 1.0 and level_key is not None
    key0 = level_key if level_key is not None \
        else jnp.zeros((2,), jnp.uint32)

    node = jnp.zeros((Fo, N), jnp.float32)
    pay = jnp.stack([G, H], axis=1).reshape(2 * Fo, N)

    def level_tables(full, n_act, lkey):
        """Split algebra for ONE level at padded slot width: cumsums over
        the shifted bin axis, sparsity-aware gains, argmax. Slots >=
        n_act (scan padding; None = all live) hold zero histograms —
        their gains are forced out so they land the dead all-left
        encoding (feat 0, thresh B-1, miss 0) deterministically; live
        slots see bit-identical algebra to the unrolled path."""
        S_pad = full.shape[1]
        hg = full[:, :, 0][..., None]                     # [Fo,S,F,B,1]
        hh = full[:, :, 1]                                # [Fo,S,F,B]
        hc = full[:, :, 2]
        GL = jnp.cumsum(hg, axis=3)
        HL = jnp.cumsum(hh, axis=3)
        CL = jnp.cumsum(hc, axis=3)
        Gt, Ht, Ct = GL[:, :, 0, -1, :], HL[:, :, 0, -1], CL[:, :, 0, -1]
        Gm, Hm, Cm = hg[:, :, :, 0, :], hh[:, :, :, 0], hc[:, :, :, 0]
        gain = split_scores_f(GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm,
                              reg_lambda, min_child_weight, min_instances,
                              min_info_gain, gamma, alpha, False)
        if feature_mask is not None:
            gain = jnp.where(feature_mask[None, None, :, None, None],
                             gain, -jnp.inf)
        if use_level_mask:
            # colsample_bylevel: one fresh subset per level, shared by
            # every fold (fold parity with the sequential loop), nested
            # inside the bytree subset exactly as grow_tree does
            lkey, sub = jax.random.split(lkey)
            fml = _level_feature_mask(sub, F, level_feature_frac,
                                      feature_mask, feature_mask_count)
            gain = jnp.where(fml[None, None, :, None, None],
                             gain, -jnp.inf)
        flat = gain.reshape(Fo, S_pad, F * B * 2)
        best = jnp.argmax(flat, axis=2)                   # [Fo, S]
        best_gain = jnp.take_along_axis(flat, best[..., None],
                                        axis=2)[..., 0]
        ok = jnp.isfinite(best_gain)
        if n_act is not None:
            ok = ok & (jnp.arange(S_pad, dtype=jnp.int32)[None, :] < n_act)
        f_lvl = jnp.where(ok, (best // (B * 2)).astype(jnp.int32), 0)
        t_lvl = jnp.where(ok, ((best // 2) % B).astype(jnp.int32), B - 1)
        m_lvl = jnp.where(ok, (best % 2).astype(jnp.int32), 0)
        last = (GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm, f_lvl, t_lvl, m_lvl)
        return f_lvl, t_lvl, m_lvl, lkey, last

    # root histogram: all rows slot 0, one plain batched pass — partial
    # sums psum-merge across row shards under the sharded driver
    root = _allreduce(pallas_hist.hist_folds(
        Xb_t, pay, node, n_slots=1, n_bins=B, interpret=interpret,
        allow_bf16=True, derive_count=True), axis_name)
    root = root.reshape(Fo, 1, 3, F, B)

    feats, threshs, misses = [], [], []
    if depth >= 2:
        S = 1 << (depth - 2)
        if S > 1:
            histL0 = jnp.concatenate(
                [root, jnp.zeros((Fo, S - 1, 3, F, B), jnp.float32)],
                axis=1)
        else:
            histL0 = root
        # seeding histL = prev = padded root makes the body UNIFORM: the
        # level-0 interleave yields [root, root - root, 0, ...] — the
        # root level's full histogram with no branch on the level index
        n_act_levels = jnp.asarray([1 << d for d in range(depth - 1)],
                                   jnp.int32)
        carry0 = _shard_vary_opt((node, histL0, histL0, key0), axis_name)

        def body(carry, n_act):
            node, prevh, histL, lkey = carry
            # full level histogram by sibling subtraction at the PADDED
            # width: slot 2p = left child (histL), 2p+1 = parent - left;
            # truncating the interleave at S keeps the carry fixed-shape
            # (levels inside the scan have at most S live nodes)
            full = jnp.stack([histL, prevh - histL], axis=2).reshape(
                Fo, 2 * S, 3, F, B)[:, :S]
            f_lvl, t_lvl, m_lvl, lkey, _ = level_tables(full, n_act, lkey)
            # fused pass: route with this level's tables AND accumulate
            # the next level's left-child histograms in ONE Xb read;
            # n_nodes is the padded width every level, so Mosaic sees
            # exactly one route_hist shape per fit
            hist, node = pallas_hist.route_hist(
                Xb_t, pay, node, f_lvl, t_lvl, m_lvl, n_nodes=S,
                n_bins=B, interpret=interpret, allow_bf16=True,
                derive_count=True)
            hist = _allreduce(hist, axis_name)
            return ((node, full, hist.reshape(Fo, S, 3, F, B), lkey),
                    (f_lvl, t_lvl, m_lvl))

        (node, prevh, histL, key0), (fs, ts, ms) = jax.lax.scan(
            body, carry0, n_act_levels)
        full_f = jnp.stack([histL, prevh - histL], axis=2).reshape(
            Fo, 2 * S, 3, F, B)
        for d in range(depth - 1):
            feats.append(fs[d][:, :1 << d])
            threshs.append(ts[d][:, :1 << d])
            misses.append(ms[d][:, :1 << d])
    else:
        full_f = root

    # final level: split + plain routing pass (no further histogram) —
    # one unrolled copy of the level body at twice the scan width
    n_half = 1 << (depth - 1)
    f_lvl, t_lvl, m_lvl, key0, last = level_tables(full_f, None, key0)
    feats.append(f_lvl)
    threshs.append(t_lvl)
    misses.append(m_lvl)
    node = pallas_hist.route(Xb_t, node, f_lvl, t_lvl, m_lvl,
                             n_nodes=n_half, interpret=interpret)

    leaf = _fold_leaves(last, n_leaves=1 << depth, reg_lambda=reg_lambda,
                        alpha=alpha, max_delta_step=max_delta_step,
                        learning_rate=learning_rate)
    leaf_rows = pallas_hist.table_lookup(
        leaf[:, :, 0], node, interpret=interpret)         # [Fo, N]
    tree = Tree(jnp.concatenate(feats, axis=1),
                jnp.concatenate(threshs, axis=1), leaf,
                jnp.concatenate(misses, axis=1))
    return tree, leaf_rows


def _grow_tree_folds_unrolled(Xb_t, G, H, *, depth, n_bins,
                              reg_lambda, min_child_weight, min_instances,
                              min_info_gain, gamma, learning_rate,
                              feature_mask, interpret=False, alpha=0.0,
                              max_delta_step=0.0, level_feature_frac=1.0,
                              level_key=None, feature_mask_count=None,
                              axis_name=None):
    """Legacy depth-unrolled form (TMOG_TREE_SCAN=0 kill switch): one
    program section per level, O(depth) HLO. See _grow_tree_folds."""
    from . import pallas_hist

    F, N = Xb_t.shape
    Fo = G.shape[0]
    B = n_bins + 1
    split_scores_f = _fold_split_scores(reg_lambda, min_child_weight, gamma)

    def interleave_f(left, right, n_nodes):
        # children along axis 1: [Fo, 2p, ...] from per-parent pairs
        return jnp.stack([left, right], axis=2).reshape(
            (Fo, n_nodes) + left.shape[2:])

    node = jnp.zeros((Fo, N), jnp.float32)
    # payload channel order per fold: the kernels expect fold-major
    # [Fo*C]; g/h are level-invariant, so build [Fo, 2, N] -> [2Fo, N]
    # once — the count channel is derived in VMEM (derive_count)
    pay = jnp.stack([G, H], axis=1).reshape(2 * Fo, N)
    feats, threshs, misses = [], [], []
    last = None
    prev = None
    hist = None
    for d in range(depth):
        n_nodes = 1 << d
        if d == 0:
            # root histogram: all rows slot 0, one plain batched pass
            hist = _allreduce(pallas_hist.hist_folds(
                Xb_t, pay, node, n_slots=1, n_bins=B,
                interpret=interpret, allow_bf16=True,
                derive_count=True), axis_name)            # [Fo*1*3, F*B]
            n_slots = 1
        else:
            # `hist` holds the LEFT-child histograms of THIS level,
            # produced by the fused route+hist pass at the end of the
            # previous iteration (sibling subtraction: right = parent -
            # left, same trick as grow_tree)
            n_slots = n_nodes // 2
        hist = hist.reshape(Fo, n_slots, 3, F, B)
        hgl = hist[:, :, 0][..., None]                        # [Fo,S,F,B,1]
        hhl = hist[:, :, 1]                                   # [Fo,S,F,B]
        hcl = hist[:, :, 2]
        if d == 0:
            hg, hh, hc = hgl, hhl, hcl
        else:
            pg, ph, pc = prev
            hg = interleave_f(hgl, pg - hgl, n_nodes)
            hh = interleave_f(hhl, ph - hhl, n_nodes)
            hc = interleave_f(hcl, pc - hcl, n_nodes)
        prev = (hg, hh, hc)

        GL = jnp.cumsum(hg, axis=3)                       # [Fo,n,F,B,1]
        HL = jnp.cumsum(hh, axis=3)
        CL = jnp.cumsum(hc, axis=3)
        Gt, Ht, Ct = GL[:, :, 0, -1, :], HL[:, :, 0, -1], CL[:, :, 0, -1]
        Gm, Hm, Cm = hg[:, :, :, 0, :], hh[:, :, :, 0], hc[:, :, :, 0]

        gain = split_scores_f(GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm,
                              reg_lambda, min_child_weight, min_instances,
                              min_info_gain, gamma, alpha, False)
        if feature_mask is not None:
            gain = jnp.where(feature_mask[None, None, :, None, None],
                             gain, -jnp.inf)
        if level_feature_frac < 1.0 and level_key is not None:
            # colsample_bylevel: one fresh subset per level, shared by
            # every fold (fold parity with the sequential loop, which
            # fits all folds with the same key), nested inside the
            # bytree subset exactly as grow_tree does
            level_key, sub = jax.random.split(level_key)
            fml = _level_feature_mask(sub, F, level_feature_frac,
                                      feature_mask, feature_mask_count)
            gain = jnp.where(fml[None, None, :, None, None],
                             gain, -jnp.inf)

        flat = gain.reshape(Fo, n_nodes, F * B * 2)
        best = jnp.argmax(flat, axis=2)                   # [Fo, n]
        best_gain = jnp.take_along_axis(flat, best[..., None],
                                        axis=2)[..., 0]
        ok = jnp.isfinite(best_gain)
        f_lvl = jnp.where(ok, (best // (B * 2)).astype(jnp.int32), 0)
        t_lvl = jnp.where(ok, ((best // 2) % B).astype(jnp.int32), B - 1)
        m_lvl = jnp.where(ok, (best % 2).astype(jnp.int32), 0)
        feats.append(f_lvl)
        threshs.append(t_lvl)
        misses.append(m_lvl)
        last = (GL, HL, CL, Gt, Ht, Ct, Gm, Hm, Cm, f_lvl, t_lvl, m_lvl)

        if d < depth - 1:
            # fused pass: route with this level's tables AND accumulate
            # the next level's left-child histograms in ONE Xb read
            hist, node = pallas_hist.route_hist(
                Xb_t, pay, node, f_lvl, t_lvl, m_lvl, n_nodes=n_nodes,
                n_bins=B, interpret=interpret, allow_bf16=True,
                derive_count=True)
            hist = _allreduce(hist, axis_name)
        else:
            # final level: no further histogram — plain routing pass to
            # land every row on its leaf
            node = pallas_hist.route(Xb_t, node, f_lvl, t_lvl, m_lvl,
                                     n_nodes=n_nodes, interpret=interpret)

    n_leaves = 1 << depth
    if depth == 0:
        Gl = _allreduce(G.sum(axis=1), axis_name)[:, None, None]
        Hl = _allreduce(H.sum(axis=1), axis_name)[:, None]
        Cl = _allreduce((H > 0).astype(jnp.float32).sum(axis=1),
                        axis_name)[:, None]
        leaf = _leaf_payload(Gl, Hl, Cl, reg_lambda, alpha,
                             max_delta_step, learning_rate)
    else:
        leaf = _fold_leaves(last, n_leaves=n_leaves, reg_lambda=reg_lambda,
                            alpha=alpha, max_delta_step=max_delta_step,
                            learning_rate=learning_rate)
    leaf_rows = pallas_hist.table_lookup(
        leaf[:, :, 0], node, interpret=interpret)         # [Fo, N]
    tree = Tree(jnp.concatenate(feats, axis=1),
                jnp.concatenate(threshs, axis=1), leaf,
                jnp.concatenate(misses, axis=1))
    return tree, leaf_rows


def _fit_gbt_folds_impl(Xb, y, W, key, *, n_rounds, depth, n_bins,
                        learning_rate=0.1, reg_lambda=1.0,
                        min_child_weight=0.0, min_instances=1.0,
                        min_info_gain=0.0, gamma=0.0, subsample=1.0,
                        feature_frac=1.0, loss="logistic",
                        interpret=False, alpha=0.0, max_delta_step=0.0,
                        colsample_bylevel=1.0, base_score=None,
                        axis_name=None):
    """Shared body of fit_gbt_folds (single device, axis_name=None) and
    fit_gbt_folds_sharded (inside shard_map: inputs hold this shard's
    LOCAL rows and every histogram/base-score reduction psums over
    `axis_name`)."""
    grad_fn = _logistic_grad if loss == "logistic" else _squared_grad
    Fo, N = W.shape
    n_orig = N
    if subsample < 1.0 and axis_name is not None:
        # per-shard uniform draws are index-local: every shard would draw
        # the SAME bits for its local rows — neither matching the
        # single-device mask nor independent. The sweep gate
        # (models/trees._sharded_route_ok) keeps such configs off this
        # route; this raise is the trace-time backstop, and tmoglint
        # SHD003 enforces it at LINT time: the raise is a recorded path
        # condition that makes the subsample draw below statically dead
        # on the sharded route — delete this guard and the linter flags
        # the draw before any sweep runs (tests/test_tmoglint_shd.py).
        raise ValueError("row subsample < 1.0 is not supported on the "
                         "sharded fused sweep route")
    wsum = _allreduce(W.sum(axis=1), axis_name) + EPS
    wy = _allreduce((W * y[None, :]).sum(axis=1), axis_name)
    if base_score is not None:  # pinned prior, fit_gbt semantics
        if loss == "logistic":
            # base_score is a python scalar at every call site (a jit
            # static arg of fit_gbt_folds / a closure constant of the
            # sharded driver), never traced
            # tmoglint: disable=TPU001  static python scalar
            p0 = min(max(float(base_score), 1e-6), 1 - 1e-6)
            base = jnp.full((Fo,), np.log(p0 / (1 - p0)), jnp.float32)
        else:
            # tmoglint: disable=TPU001  static python scalar
            base = jnp.full((Fo,), float(base_score), jnp.float32)
    elif loss == "logistic":
        p0 = jnp.clip(wy / wsum, 1e-6, 1 - 1e-6)
        base = jnp.log(p0 / (1 - p0))
    else:
        base = wy / wsum

    # pad rows once to the kernels' block size (inert: zero payloads)
    from . import pallas_hist
    blk = pallas_hist._ROUTE_BLK
    pad = (-N) % blk
    if pad:
        Xb = jnp.pad(Xb, ((0, pad), (0, 0)))
        y = jnp.pad(y, ((0, pad),))
        W = jnp.pad(W, ((0, 0), (0, pad)))
        N += pad
    valid = (jnp.arange(N) < n_orig).astype(jnp.float32)
    Xb_t = Xb.T

    def one(carry, k):
        margin, = carry
        ks, kc, kf = jax.random.split(k, 3)
        g, h = grad_fn(margin, y[None, :], W)             # [Fo, N] each
        # padded rows carry literal zeros (grow_tree pads H with 0; the
        # logistic clamp would otherwise leave them at EPS)
        h = h * valid[None, :]
        if subsample < 1.0:
            # draw over the UNPADDED row count so the mask matches
            # fit_gbt's uniform(ks, (n,)) unconditionally: under the
            # default jax_threefry_partitionable mode padded draws are
            # prefix-stable (bits are per-index), but with the flag off
            # bits depend on array size and a padded draw would break
            # the exact-parity contract above
            rw = (jax.random.uniform(ks, (n_orig,)) < subsample
                  ).astype(jnp.float32)
            rw = jnp.pad(rw, (0, N - n_orig))[None, :]
            g, h = g * rw, h * rw
        # count semantics follow grow_tree's count_unit = (H > 0) on the
        # POST-subsample hessian — derived in VMEM by the histogram
        # kernels (derive_count), no HBM plane: the logistic clamp keeps
        # excluded (W=0) real rows countable exactly as in the sequential
        # path, while subsampled-out and padded rows drop to 0
        fm = (_feature_mask(kc, 1, Xb_t.shape[0], feature_frac)[0]
              if feature_frac < 1.0 else None)
        # kf seeds the per-LEVEL colsample_bylevel draws (split exactly
        # like grow_tree splits its key, so the fused and sequential
        # routes draw identical level subsets); per-node resampling stays
        # unused — boosting samples features per tree/level, not per node
        tree, leaf_rows = _grow_tree_folds(
            Xb_t, g, h, depth=depth, n_bins=n_bins,
            reg_lambda=reg_lambda, min_child_weight=min_child_weight,
            min_instances=min_instances, min_info_gain=min_info_gain,
            gamma=gamma, learning_rate=learning_rate, feature_mask=fm,
            interpret=interpret, alpha=alpha,
            max_delta_step=max_delta_step,
            level_feature_frac=colsample_bylevel, level_key=kf,
            feature_mask_count=(
                # feature_frac: jit static arg / closure constant
                # tmoglint: disable=TPU001  static python scalar
                max(1, int(round(feature_frac * Xb_t.shape[0])))
                if feature_frac < 1.0 else None),
            axis_name=axis_name)
        return (margin + leaf_rows,), tree

    init = jnp.broadcast_to(base[:, None], (Fo, N)).astype(jnp.float32)
    init = _shard_vary_opt(init, axis_name)
    (margin,), trees = jax.lax.scan(one, (init,),
                                    jax.random.split(key, n_rounds))
    return trees, base, margin[:, :n_orig]


@functools.partial(
    jax.jit,
    static_argnames=("n_rounds", "depth", "n_bins", "loss", "subsample",
                     "feature_frac", "interpret", "alpha",
                     "max_delta_step", "colsample_bylevel", "base_score"))
def fit_gbt_folds(Xb: jax.Array, y: jax.Array, W: jax.Array,
                  key: jax.Array, *, n_rounds: int, depth: int,
                  n_bins: int, learning_rate: float = 0.1,
                  reg_lambda: float = 1.0, min_child_weight: float = 0.0,
                  min_instances: float = 1.0, min_info_gain: float = 0.0,
                  gamma: float = 0.0, subsample: float = 1.0,
                  feature_frac: float = 1.0, loss: str = "logistic",
                  interpret: bool = False, alpha: float = 0.0,
                  max_delta_step: float = 0.0,
                  colsample_bylevel: float = 1.0,
                  base_score: Optional[float] = None):
    """Boosted trees for every CV fold in ONE device program.

    The mask-fold sweep (models/trees.mask_fit_scores) above the fold-vmap
    row limit used to loop folds through fit_gbt sequentially — each fold
    re-reading the binned matrix and re-building the (feature, bin)
    one-hots that dominate the histogram kernel, with a contraction M dim
    (slots x 3 payload channels) far under the 128-row MXU tile. Here the
    folds share every Xb pass (fold-fused pallas histograms + routing) and
    stack their payload rows into the same contraction. Whole trees grow
    in ONE lax.scan over levels by default (TMOG_TREE_SCAN, see
    _grow_tree_folds), so the traced program is O(1) — not O(depth) — in
    size and one (shape, depth) compiles exactly one executable.

    Xb [N, F] binned (bin_matrix layout); y [N]; W [Fo, N] per-fold
    weights (0 = row excluded from that fold's fit). Per-fold quantities
    follow fit_gbt exactly — same base score, same gradient clamps, same
    per-round subsample/colsample draws (ONE draw shared by all folds,
    matching the sequential loop where every fold fits with the same
    key). Returns (trees [rounds, Fo, ...], base [Fo], margins [Fo, N]) —
    margins are the fitted scores for ALL rows (held-out rows are routed
    through each fold's trees), i.e. exactly what the sequential
    per-fold `base + predict_forest_bins(...)` loop produces.
    """
    return _fit_gbt_folds_impl(
        Xb, y, W, key, n_rounds=n_rounds, depth=depth, n_bins=n_bins,
        learning_rate=learning_rate, reg_lambda=reg_lambda,
        min_child_weight=min_child_weight, min_instances=min_instances,
        min_info_gain=min_info_gain, gamma=gamma, subsample=subsample,
        feature_frac=feature_frac, loss=loss, interpret=interpret,
        alpha=alpha, max_delta_step=max_delta_step,
        colsample_bylevel=colsample_bylevel, base_score=base_score)


#: jitted shard_map program per (mesh, static config) — an explicit dict
#: (not lru_cache) so the kill switches can DROP programs for real:
#: registering each rebuilt jit with the tracing fallback would retain
#: every cleared generation's executables forever, so instead ONE stable
#: probe (_ShardedJitProbe, registered at import) sums executable counts
#: over whatever programs are currently live here.
_SHARDED_FIT_CACHE: dict = {}


class _ShardedJitProbe:
    """Stable register_jit_fallback entry for the sharded fit programs:
    no-monitoring compile counting samples the LIVE cache only, and
    cleared programs become unreachable (no unbounded retention across
    set_tree_scan / pallas-toggle cache clears)."""

    @staticmethod
    def _cache_size():
        total = 0
        for fn in _SHARDED_FIT_CACHE.values():
            try:
                total += int(fn._cache_size())
            except Exception:
                pass
        return total


def _sharded_gbt_fn(mesh, static_kw):
    """One jitted shard_map program per (mesh, static config) — cached
    (mirroring ops/glm_sweep's sharded-driver caching) so repeated
    sweeps at one shape reuse the compiled executable."""
    fn = _SHARDED_FIT_CACHE.get((mesh, static_kw))
    if fn is not None:
        return fn
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import BATCH_AXIS, build_shard_map

    kw = dict(static_kw)

    def core(Xb, y, W, key, learning_rate, reg_lambda, min_child_weight,
             gamma):
        return _fit_gbt_folds_impl(
            Xb, y, W, key, learning_rate=learning_rate,
            reg_lambda=reg_lambda, min_child_weight=min_child_weight,
            gamma=gamma, axis_name=BATCH_AXIS, **kw)

    sm = build_shard_map(
        core, mesh,
        in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS), P(None, BATCH_AXIS),
                  P(), P(None), P(None), P(None), P(None)),
        # trees/base replicate (they are grown from psum-merged
        # histograms, identical on every shard); margins stay row-sharded
        out_specs=(P(), P(), P(None, BATCH_AXIS)))
    fn = jax.jit(sm)
    _SHARDED_FIT_CACHE[(mesh, static_kw)] = fn
    return fn


def fit_gbt_folds_sharded(Xb: jax.Array, y: jax.Array, W: jax.Array,
                          key: jax.Array, *, mesh, n_rounds: int,
                          depth: int, n_bins: int,
                          learning_rate=0.1, reg_lambda=1.0,
                          min_child_weight=0.0, min_instances: float = 1.0,
                          min_info_gain: float = 0.0, gamma=0.0,
                          subsample: float = 1.0, feature_frac: float = 1.0,
                          loss: str = "logistic", interpret: bool = False,
                          alpha: float = 0.0, max_delta_step: float = 0.0,
                          colsample_bylevel: float = 1.0,
                          base_score: Optional[float] = None):
    """fit_gbt_folds with rows sharded over the mesh batch axis.

    The DrJAX MapReduce shape over parallel/mesh.py: each device streams
    only its row shard of the binned matrix through the fused
    route+histogram passes, per-level histograms psum-merge across
    shards before the (replicated) split algebra, and routing stays
    local — so the (fold x config) lane axis of the sweep finally runs
    on a mesh instead of falling back to the sequential per-fold path.
    Requirements: the batch-axis device count must divide N (the
    validator pads rows up to a multiple of it via
    pad_rows_to_multiple) and subsample must stay 1.0 (per-shard
    draws are index-local — see _fit_gbt_folds_impl). The four per-lane
    algebra params always travel as [Fo] vectors here (one program
    shape for scalar and vector callers). Margins match the
    single-device fused fit up to f32 psum summation order.

    On a MULTI-PROCESS mesh Xb/y/W are THIS PROCESS's host-local rows
    (SPMD — every process calls with its own stripe); they land as the
    process's batch-axis block of one global array and the histogram
    psums become cross-host collectives. Histogram bin counts are
    integer sums of the same (row, weight) set as the single-process
    call, so trees match EXACTLY when gradients agree bit-for-bit and
    within f32 psum order otherwise. Margins come back as a HOST array
    holding only this process's rows (fetch_local), trimmed of layout
    padding.
    """
    from ..parallel.mesh import mesh_is_multiprocess

    Fo = W.shape[0]

    def lane(v):
        a = jnp.asarray(v, jnp.float32)
        return jnp.broadcast_to(a, (Fo,)) if a.ndim == 0 else a

    static_kw = (
        ("n_rounds", int(n_rounds)), ("depth", int(depth)),
        ("n_bins", int(n_bins)), ("min_instances", float(min_instances)),
        ("min_info_gain", float(min_info_gain)),
        ("subsample", float(subsample)),
        ("feature_frac", float(feature_frac)), ("loss", str(loss)),
        ("interpret", bool(interpret)), ("alpha", float(alpha)),
        ("max_delta_step", float(max_delta_step)),
        ("colsample_bylevel", float(colsample_bylevel)),
        ("base_score", None if base_score is None else float(base_score)))
    fn = _sharded_gbt_fn(mesh, static_kw)
    if mesh_is_multiprocess(mesh):
        from ..parallel import multihost as MH
        from ..parallel import podtrace

        Xl = np.asarray(Xb)
        n_local = Xl.shape[0]
        layout = MH.row_layout(n_local, mesh)
        with podtrace.ingest("tree_land", rows=int(n_local),
                             feat=int(Xl.shape[1])):
            # zero-weight padding is inert end to end: W=0 rows
            # contribute nothing to the base score, histograms or leaf
            # counts (the count unit is (H > 0) and H carries the
            # weight). Xb pads by repeating the last real row —
            # already-binned values, so any constant would do, but a
            # repeat keeps bin indices in range.
            Xb = MH.host_local_block(Xl, mesh, layout, pad_value=None)
            y = MH.host_local_block(np.asarray(y, np.float32), mesh,
                                    layout)
            W = MH.host_local_block(np.asarray(W, np.float32), mesh,
                                    layout, axis=1)
            key = MH.replicated_global(np.asarray(key), mesh)
            lanes = tuple(MH.replicated_global(np.asarray(lane(v)), mesh)
                          for v in (learning_rate, reg_lambda,
                                    min_child_weight, gamma))
        # collective window = sharded fit + local-margin fetch: the
        # histogram psums live inside the jitted program, so a victim
        # rank's barrier wall lands here (the skew table's attribution
        # contract — see parallel/podtrace.py)
        with podtrace.collective("tree_fit", rows=int(layout.n_padded),
                                 feat=int(Xl.shape[1]), folds=int(Fo),
                                 depth=int(depth), rounds=int(n_rounds)):
            trees, base, margins = fn(Xb, y, W, key, *lanes)
            margins = MH.fetch_local(margins, axis=1)[:, :n_local]
        return trees, base, margins
    return fn(Xb, y, W, key, lane(learning_rate), lane(reg_lambda),
              lane(min_child_weight), lane(gamma))


class _ShardedCacheClearer:
    """Adapter so the sharded-program dict sits on the pallas
    kill-switch consumer list (which calls .clear_cache())."""

    @staticmethod
    def clear_cache():
        _SHARDED_FIT_CACHE.clear()


@functools.partial(
    jax.jit,
    static_argnames=("n_rounds", "depth", "n_bins", "n_classes", "subsample",
                     "feature_frac", "alpha", "max_delta_step",
                     "colsample_bylevel"))
def fit_gbt_softmax(Xb: jax.Array, y: jax.Array, w: jax.Array,
                    key: jax.Array, *, n_rounds: int, depth: int,
                    n_bins: int, n_classes: int,
                    learning_rate: float = 0.1, reg_lambda: float = 1.0,
                    min_child_weight: float = 0.0, gamma: float = 0.0,
                    subsample: float = 1.0,
                    feature_frac: float = 1.0, alpha: float = 0.0,
                    max_delta_step: float = 0.0,
                    colsample_bylevel: float = 1.0) -> Tree:
    """Multiclass softmax boosting: per round, the class axis of the
    grad/hess tensors is vmapped into n_classes parallel tree growths
    (XGBoost multi:softprob shape). Returns trees with leading
    [n_rounds, n_classes] axes; margins = sum over rounds per class.
    """
    Y = jax.nn.one_hot(y.astype(jnp.int32), n_classes)

    def one(carry, k):
        margin, = carry                       # [N, C]
        ks, km, kf = jax.random.split(k, 3)
        p = jax.nn.softmax(margin, axis=1)
        g = w[:, None] * (p - Y)              # [N, C]
        h = jnp.maximum(w[:, None] * p * (1.0 - p), EPS)
        if subsample < 1.0:
            rw = (jax.random.uniform(ks, y.shape) < subsample
                  ).astype(jnp.float32)[:, None]
            g, h = g * rw, h * rw
        fm = (_feature_mask(km, 1, Xb.shape[1], feature_frac)[0]
              if feature_frac < 1.0 else None)  # colsample_bytree

        def per_class(gc, hc, kc):
            # allow_pallas=False: this grow sits under the class vmap and
            # pallas_call must not be batched
            return grow_tree(Xb, gc[:, None], hc, kc, depth=depth,
                             n_bins=n_bins, reg_lambda=reg_lambda,
                             min_child_weight=min_child_weight, gamma=gamma,
                             leaf_mode="newton", feature_mask=fm,
                             learning_rate=learning_rate,
                             normalize_gain=False, allow_pallas=False,
                             alpha=alpha, max_delta_step=max_delta_step,
                             level_feature_frac=colsample_bylevel,
                             feature_mask_count=(
                                 max(1, int(round(
                                     feature_frac * Xb.shape[1])))
                                 if feature_frac < 1.0 else None))
        trees = jax.vmap(per_class, in_axes=(1, 1, 0))(
            g, h, jax.random.split(kf, n_classes))
        step = jax.vmap(lambda t: predict_bins(t, Xb, depth)[:, 0])(trees)
        return (margin + step.T,), trees

    init = jnp.zeros((y.shape[0], n_classes), jnp.float32)
    (_,), trees = jax.lax.scan(one, (init,), jax.random.split(key, n_rounds))
    return trees


def _register_pallas_consumers():
    """Tree-fit executables bake the pallas choice in at trace time; the
    kill switch must be able to clear them (set_pallas_enabled)."""
    from . import pallas_hist
    for fn in (grow_tree, fit_forest, fit_gbt, fit_gbt_folds,
               fit_gbt_softmax, _ShardedCacheClearer()):
        pallas_hist.register_cache_consumer(fn)


_register_pallas_consumers()


def _register_trace_fallback():
    """Recompile-tracker fallback registration (utils/tracing): on jax
    builds without jax.monitoring, the span tree counts compiles of the
    tree-fit drivers by sampling their lowered-executable counts at span
    boundaries — the models/trees._timed_fused_fit kernel spans then
    still carry true recompile attribution."""
    from ..utils import tracing
    tracing.register_jit_fallback(grow_tree, fit_forest, fit_gbt,
                                  fit_gbt_folds, fit_gbt_softmax,
                                  _bin_tile_jit, _ShardedJitProbe())


_register_trace_fallback()


# -- host-side (numpy) ensemble traversal for serving -----------------------

def np_predict_ensemble(feat: np.ndarray, thresh_val: np.ndarray,
                        leaf: np.ndarray, X: np.ndarray,
                        depth: int,
                        miss: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized numpy traversal on RAW feature values.

    feat/thresh_val: [T, 2^depth - 1] (thresh in raw units; present values
    go right iff x >= thresh, +inf = all-left, -inf = all-present-right);
    miss: [T, 2^depth - 1] 0/1 learned default direction for NaN rows
    (None = all default-left, the pre-miss serialization); leaf:
    [T, 2^depth, K]; X: [N, F]. Returns per-tree payload sum [N, K] — this
    is the Spark-free "local scoring" path (reference
    local/.../OpWorkflowModelLocal.scala:93), no JAX required.

    Batches route through the native row-major traversal when the C++
    library is loaded (single-row calls stay in numpy: the ctypes
    call overhead exceeds one row's traversal).
    """
    N = X.shape[0]
    if N > 1:
        from . import trees_host as TH
        miss_arr = (np.zeros_like(np.asarray(feat, np.int32))
                    if miss is None else miss)
        out = TH.predict_raw_native(feat, thresh_val, leaf,
                                    np.asarray(X, np.float32), depth,
                                    miss_arr)
        if out is not None:
            return out
    T = feat.shape[0]
    rel = np.zeros((N, T), np.int64)
    t_idx = np.arange(T)[None, :]
    for d in range(depth):
        gi = (1 << d) - 1 + rel
        f = feat[t_idx, gi]                    # [N, T]
        tv = thresh_val[t_idx, gi]
        x = X[np.arange(N)[:, None], f]
        nan = np.isnan(x)
        right = ~nan & (x >= tv)               # NaN compares False
        if miss is not None:
            right |= nan & (miss[t_idx, gi] > 0)
        rel = 2 * rel + right
    return leaf[t_idx, rel].sum(axis=1)        # [N, K]
