"""Lane-batched streaming GLM sweep: every (fold x grid) fit in ONE pass
over the feature matrix per Newton iteration — and, since the
convergence-aware restructure, only for the lanes that still need it.

The vmapped sweep (`automl/tuning/validators._sweep`) runs `fit_one` per
lane, so each of the L = folds x grid lanes re-streams the [n, d] matrix
from HBM every iteration and materializes its own weighted [n, d] product
for the Gram matmul — at the 10M-row BASELINE config that is ~5GB of HBM
traffic per lane-iteration and forces the validator to chunk the grid to a
handful of lanes per program. The whole sweep is HBM-bound at a few
percent MFU.

This kernel restructures the math so X streams ONCE per iteration for ALL
lanes (reference workload: the 8-thread pool of OpValidator.scala:270-332,
every thread refitting against the same cached DataFrame):

- one row-block scan per Newton iteration, carrying per-lane accumulators
  (g [L, d], Hessians [L, d, d], intercept sums);
- lane etas in one MXU contraction `X_blk @ B.T` ([c, d] x [d, L]);
- every lane's weighted Gram from ONE batched einsum 'cl,cd,ce->lde'
  with S [c, L] the per-lane curvature weights (narrow path, d <= 128).
  A compressed upper-triangle form (xf[:, iu0] * xf[:, iu1] then an
  [L, c] x [c, T] matmul) halves the arithmetic but its column GATHER
  dominated the pass on TPU — 7.8 TF/s vs the einsum's 25.8 TF/s on a
  v5 lite at the BASELINE shapes (tools/tpu_glm_hess_ab.py). No
  per-lane scaled copy of X exists anywhere;
- per-lane 64x64 Newton solves + proximal L1 + intercept steps are
  batched dense linalg on [L, d, d] — microscopic next to the scan.

Convergence awareness (docs/performance.md "Convergence-aware GLM
sweep") adds three routes on top of the shared scan machinery:

1. `sweep_glm_squared_gram` — loss="squared" sufficient-statistics fast
   path. The squared-loss curvature is identically 1, so the lane Hessian
   collapses to the per-FOLD weighted Gram X^T diag(w * mask_f) X:
   iteration-invariant and only F matrices, not L. ONE streaming pass
   builds [F, d, d] Grams + X^T W_f y / X^T W_f 1 moments (psum'd under
   shard_map); the whole reg x alpha grid then solves off the cached
   moments — ridge lanes closed form (`ops/glm.ridge_gram_solve`),
   elastic-net lanes by proximal Newton on the cached Gram
   (`ops/glm.prox_newton_gram`, seeded from the ridge solution). Up to
   max_iter full-data passes become exactly one.
2. `sweep_glm_round` + the host driver `sweep_glm_streamed_rounds` — for
   IRLS losses (logistic, squared_hinge) the run-to-global-convergence
   while_loop is replaced by rounds of K iterations with a PER-LANE delta
   vector in the carry; after each round the host retires converged lanes
   (coefficients frozen — matching the per-lane solvers' own tol
   semantics, `ops/glm._newton_prox_fit`) and compacts survivors into the
   next round's program. The lane axis pads to a power-of-two bucket
   ladder (`bucket_lanes`) so recompiles are bounded and the jit cache is
   shared across rounds, chunks and sweeps; inert padded lanes carry zero
   fold weights. Round 0 optionally fits only each fold's
   strongest-regularization lane and seeds the rest of the fold from it
   (glmnet-style pathwise continuation).
3. `sweep_glm_streamed` — the legacy single-program global-max route,
   kept as the kill-switch fallback (TMOG_GLM_ROUNDS=0 / TMOG_GLM_GRAM=0)
   and the parity reference in tests. `tol`/`max_iter` are traced scalars
   on every route (they only feed while-loop conds), so tuning them never
   recompiles.

Fold masks enter as weights (mask * w), exactly like the vmapped path, so
fold semantics are identical; the elementwise residual/curvature rules per
loss mirror ops/glm's solvers (logistic IRLS, squared, squared-hinge).

Distribution: the `*_sharded` variants run the SAME cores inside a
shard_map over the mesh `batch` axis — each shard scans its local rows,
then every accumulator reduction psums over ICI/DCN (the Spark-shuffle /
Rabit-allreduce slot of SURVEY §2.9); the tiny replicated solves run on
every shard. Sharded standardization uses one-pass psum'd moments. The
replicated-out_spec claims of all four sharded drivers are proved
statically by tmoglint SHD001 (a missing psum is invisible on the
1-device CI mesh — docs/static_analysis.md).

Standardization note: the per-lane solvers standardize with the lane's own
(fold-masked) weights; these kernels standardize ONCE with the global
weights so the standardized matrix can be shared by every lane. Fold
means/stds differ from global ones by O(1/sqrt(n)) — statistically inert
at the scales where these kernels are selected (the validator still routes
small problems through the per-lane path).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import glm as G

EPS = 1e-12

# Rows per scan block on the narrow path: bounds the [c, d, d] pairwise
# intermediate XLA materializes when lowering the Gram einsum (f32, 512MB
# at d=64/c=32768) and the [c, L] residual/curvature blocks. _row_block()
# halves c as d grows so the transient never exceeds that budget (d=128
# would otherwise double it).
_ROW_BLOCK = 32_768


def _row_block(d: int) -> int:
    c = _ROW_BLOCK
    while c > 4_096 and c * d * d * 4 > 512 * (1 << 20):
        c //= 2
    return c

# Widest matrix the single-pass (narrow) route handles. The narrow path
# is the full symmetric per-lane Gram einsum 'cl,cd,ce->lde' — 2x the
# arithmetic of the old compressed-triangle pair-product form but 3.3x
# the throughput on v5e (the triangle's column gather xf[:, iu0] was the
# wall; tools/tpu_glm_hess_ab.py). Past this width the [c, d, d] blocks
# outgrow the transient budget and the kernel switches to the
# feature-tiled accumulation (same math, tile-pair granularity).
TRI_MAX_D = 128

# Feature-tile edge for the wide path: each scan step materializes one
# [c, TILE^2] pair-product block per tile pair. 64 keeps MXU tiles square
# and the transient at c * 16K floats.
_FEATURE_TILE = 64

# Rows per scan block on the wide path — c * TILE^2 * 4B = 64MB at 4096.
_ROW_BLOCK_WIDE = 4_096

# Graph-size ceiling for the tiled path: the tile-pair loop is a Python
# unroll inside the scan body inside the Newton while_loop, so pairs
# multiply XLA graph size. 406 pairs = d_pad 1792 (28 tiles) — far past
# any transmogrified width seen in practice, well before compile blowup.
_MAX_TILE_PAIRS = 406

# Newton iterations per jitted round on the retirement route; the
# retirement granularity / wasted-iteration tradeoff (a lane converging
# mid-round keeps iterating until the round ends). TMOG_GLM_ROUND_ITERS
# overrides per process.
ROUND_ITERS_DEFAULT = 5

# Smallest lane bucket on the compaction ladder: buckets below this save
# almost no per-pass work but add compile entries.
_BUCKET_MIN = 8


_bucket_floor_cached = None


def _bucket_floor() -> int:
    """The compaction ladder's smallest bucket — a plan-time decision
    since the autotuning PR (family ``glm_bucket``, docs/planning.md):
    a measured corpus may move it, a cold corpus (or TMOG_PLAN=0, or
    any planner fault) keeps the hand _BUCKET_MIN. Resolved ONCE per
    process: bucket_lanes is read per retirement round, and a corpus
    append from another process mid-sweep must not flip the floor
    between rounds of one sweep — the padded program shapes (and the
    'at most log2(L/floor)+1 distinct round programs' compile pin) are
    fixed for the process lifetime. A planner fault is NOT cached, so
    a transiently unreadable corpus can still resolve later."""
    global _bucket_floor_cached
    if _bucket_floor_cached is None:
        try:
            from ..planner.plan import planned_glm_bucket_floor
            _bucket_floor_cached = max(planned_glm_bucket_floor(), 1)
        except Exception:
            return _BUCKET_MIN
    return _bucket_floor_cached


def bucket_lanes(n_active: int) -> int:
    """Smallest power-of-two bucket >= n_active (floor _bucket_floor,
    hand default _BUCKET_MIN): the round kernel's lane axis is padded
    to this, so a sweep compiles at most log2(L/floor)+1 distinct round
    programs per (n, d, F) shape, reused across rounds, grid chunks and
    repeated sweeps."""
    b = _bucket_floor()
    while b < n_active:
        b *= 2
    return b


def streamed_route_ok(d: int, lanes: int, budget_bytes: float) -> bool:
    """Can the streamed kernel take a (d features, lanes) sweep within
    `budget_bytes` of device memory? Owns the kernel's own padding and
    graph-size policy so route guards (validators._streamable) cannot
    drift from it: per-iteration footprint is the assembled [L, d, d]
    Hessian + LU workspace + tile accumulators (~4x) at the ROUND
    DRIVER'S first-round bucket (bucket_lanes pads the lane axis to the
    next power of two, up to ~2x the logical lane count), and the tiled
    path's Python-unrolled tile-pair loop is capped before XLA graph
    size explodes."""
    if d <= TRI_MAX_D:
        d_work = d
    else:
        nt = -(-d // _FEATURE_TILE)
        if nt * (nt + 1) // 2 > _MAX_TILE_PAIRS:
            return False
        d_work = nt * _FEATURE_TILE
    return bucket_lanes(lanes) * d_work * d_work * 4.0 * 4.0 <= budget_bytes


def _residual_curvature(loss: str):
    """Unweighted per-row residual r and curvature s for eta [c, L]."""
    if loss == "logistic":
        def rc(eta, y):
            p = jax.nn.sigmoid(eta)
            return p - y[:, None], jnp.maximum(p * (1.0 - p), 1e-6)
    elif loss == "squared":
        def rc(eta, y):
            return eta - y[:, None], jnp.ones_like(eta)
    elif loss == "squared_hinge":
        def rc(eta, y):
            # loss 0.5*gap^2 (NOT gap^2): matches glm.fit_linear_svc's
            # residual/curvature so the streamed and per-lane routes see
            # the same effective L2 for a given reg_param
            ypm = (2.0 * y - 1.0)[:, None]
            gap = jnp.maximum(1.0 - ypm * eta, 0.0)
            return -gap * ypm, (gap > 0.0).astype(eta.dtype)
    else:
        raise ValueError(f"unknown streamed loss {loss!r}")
    return rc


# -- shared scan geometry ----------------------------------------------------

def _tiling(d: int):
    """(tiled, d_work, bt, tile_pairs) — the narrow/wide Gram geometry for
    a d-feature matrix, shared by every streamed route so their padding
    and transient budgets cannot diverge."""
    if d <= TRI_MAX_D:
        return False, d, 0, []
    bt = _FEATURE_TILE
    nt = -(-d // bt)
    return True, nt * bt, bt, [(a, b) for a in range(nt)
                               for b in range(a, nt)]


def _gram_fns(tiled: bool, d_work: int, lanes: int, bt: int, tile_pairs):
    """(hess_blocks, assemble, blocks0) for `lanes` weighted Grams of a
    d_work-wide block. `hess_blocks(xf [c, d_work] f32, S [c, lanes])`
    returns per-block accumulator contributions; `assemble` turns the
    summed accumulator into the full symmetric [lanes, d_work, d_work]."""
    if tiled:
        def hess_blocks(xf, S):
            # Tile-pair contributions [npairs, lanes, bt*bt] — the wide-d
            # path: each pair materializes only a [c, bt^2] product (the
            # [c, d(d+1)/2] full triangle would outgrow HBM past ~128
            # features); off-diagonal tile pairs are computed once and
            # mirrored at assembly, keeping the triangle savings at tile
            # granularity.
            out = []
            for a, b in tile_pairs:
                xa = xf[:, a * bt:(a + 1) * bt]
                xb = xf[:, b * bt:(b + 1) * bt]
                P = (xa[:, :, None] * xb[:, None, :]).reshape(-1, bt * bt)
                out.append(jnp.matmul(S.T, P,
                                      preferred_element_type=jnp.float32))
            return jnp.stack(out)

        def assemble(hA):
            H = jnp.zeros((lanes, d_work, d_work), jnp.float32)
            for p, (a, b) in enumerate(tile_pairs):
                blk = hA[p].reshape(lanes, bt, bt)
                H = H.at[:, a * bt:(a + 1) * bt,
                         b * bt:(b + 1) * bt].set(blk)
                if a != b:
                    H = H.at[:, b * bt:(b + 1) * bt,
                             a * bt:(a + 1) * bt].set(
                                 blk.transpose(0, 2, 1))
            return H

        blocks0 = jnp.zeros((len(tile_pairs), lanes, bt * bt), jnp.float32)
        return hess_blocks, assemble, blocks0

    def hess_blocks(xf, S):
        # Per-lane weighted Gram [lanes, d, d] for one row block, as ONE
        # einsum XLA tiles directly. The previous compressed-triangle form
        # (xf[:, iu0] * xf[:, iu1] -> [c, T] then an [L, c] x [c, T]
        # matmul) halved the contraction FLOPs but its column GATHER
        # dominated the whole pass on TPU: measured on v5 lite at the
        # BASELINE shapes, the gather-built triangle ran 7.8 TF/s
        # end-to-end while this full symmetric einsum runs 25.8 TF/s —
        # 1.7x faster despite doing 2x the arithmetic
        # (tools/tpu_glm_hess_ab.py).
        return jnp.einsum('cl,cd,ce->lde', S, xf, xf,
                          preferred_element_type=jnp.float32)

    return (hess_blocks, lambda hA: hA,
            jnp.zeros((lanes, d_work, d_work), jnp.float32))


def _blocked(Xs, y, w, fold_masks, c: int):
    """Row-pad to the block multiple with w=0 (inert everywhere) and
    reshape into scan blocks."""
    n = Xs.shape[0]
    F = fold_masks.shape[0]
    nb = -(-n // c)
    pad = nb * c - n
    if pad:
        Xs = jnp.pad(Xs, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        w = jnp.pad(w, (0, pad))
        fold_masks = jnp.pad(fold_masks, ((0, 0), (0, pad)))
    return (Xs.reshape(nb, c, Xs.shape[1]), y.reshape(nb, c),
            w.reshape(nb, c), fold_masks.reshape(F, nb, c).transpose(1, 0, 2))


def env_on(name: str, default: str = "1") -> bool:
    """Tri-state TMOG_* toggle parse, shared by every sweep knob
    (TMOG_GLM_GRAM / TMOG_GLM_ROUNDS in the validator routing,
    TMOG_GLM_WARMSTART here) so the accepted falsy spellings cannot
    drift between modules."""
    return os.environ.get(name, default).strip().lower() \
        not in ("0", "false", "off")


def _newton_prox_update(B, b0, gA, hA, g0A, h0A, wsum_l, l1, l2, eye,
                        assemble, fit_intercept: bool):
    """THE damped-Newton + proximal-L1 + intercept update from streamed
    accumulators, shared by the legacy global-max kernel and the
    retirement round kernel — the parity contract between the two routes
    (and the moment-space replay in ops/glm.prox_newton_gram) lives in
    this one function, so a change to the update rule reaches every route
    at once. Returns (B_new, b0_new, delta_vec [L])."""
    g = gA / wsum_l[:, None] + l2[:, None] * B
    H = assemble(hA) / wsum_l[:, None, None]
    H = H + (l2[:, None, None] + 1e-6) * eye[None]
    step = jnp.linalg.solve(H, g[..., None])[..., 0]
    B_new = B - step
    hdiag = jnp.maximum(jnp.diagonal(H, axis1=1, axis2=2), EPS)
    B_new = (jnp.sign(B_new)
             * jnp.maximum(jnp.abs(B_new) - l1[:, None] / hdiag, 0.0))
    if fit_intercept:
        b0_new = b0 - (g0A / wsum_l) / jnp.maximum(h0A / wsum_l, EPS)
    else:
        b0_new = b0
    delta = jnp.abs(B_new - B).max(axis=1) + jnp.abs(b0_new - b0)
    return B_new, b0_new, delta


# shard_map construction + carry-vary shims live in parallel/mesh.py since
# the one-pass stats engine (ops/stats_engine.py) shares them; the private
# names stay importable for existing callers
from ..parallel.mesh import build_shard_map as _build_shard_map  # noqa: E402
from ..parallel.mesh import mesh_is_multiprocess as _mesh_is_mp  # noqa: E402
from ..parallel.mesh import shard_vary as _shard_vary  # noqa: E402


def _is_global_array(a) -> bool:
    """True for a jax.Array whose shards span other processes (already
    landed on a multi-process mesh) — such inputs pass through the
    sharded entry points untouched."""
    return isinstance(a, jax.Array) and not a.is_fully_addressable


def _land_rows_multihost(mesh, X, y, w, fold_masks):
    """Land THIS PROCESS's host-local sweep rows as global batch-sharded
    arrays (multihost.host_local_block; every process calls with its own
    stripe — SPMD). X/y/w pad along rows with zeros (zero weight = inert
    in every accumulator), fold masks pad along their row axis (axis 1)
    with ones (irrelevant under w=0) — the uneven-stripe generalization
    of the validator's pad_rows_to_multiple."""
    from ..parallel import multihost as MH
    from ..parallel import podtrace

    Xl = np.asarray(X)
    n = Xl.shape[0]
    layout = MH.row_layout(n, mesh)
    wl = np.ones(n, np.float32) if w is None else np.asarray(w, np.float32)
    with podtrace.ingest("glm_land", rows=int(n),
                         cols=int(Xl.shape[1]) if Xl.ndim > 1 else 1):
        return (MH.host_local_block(Xl, mesh, layout),
                MH.host_local_block(np.asarray(y, np.float32), mesh,
                                    layout),
                MH.host_local_block(wl, mesh, layout),
                MH.host_local_block(np.asarray(fold_masks, np.float32),
                                    mesh, layout, pad_value=1.0, axis=1))


def _psum_moments(X, w, allreduce):
    """Two-pass weighted column moments in f32 (psum-aware). One-pass
    E[x^2]-mean^2 cancels catastrophically in f32 for large-mean features
    (epoch-millisecond timestamps would lose ALL unit-scale variance),
    silently diverging from the two-pass path."""
    f32 = jnp.float32
    wsum = jnp.maximum(allreduce(w.sum().astype(f32)), EPS)
    xf = X.astype(f32)
    mean = allreduce((xf * w[:, None]).sum(0)) / wsum
    centered = xf - mean[None, :]
    var = allreduce((centered * centered * w[:, None]).sum(0)) / wsum
    std = jnp.sqrt(jnp.maximum(var, EPS))
    return mean, std


@jax.jit
def glm_standardize_stats(X: jax.Array, w: jax.Array
                          ) -> Tuple[jax.Array, jax.Array]:
    """Global-weight column (mean, std) for the round driver — computed
    once per sweep, applied on the fly inside every round's scan so no
    standardized [n, d] copy is ever materialized."""
    return _psum_moments(X, w, lambda v: v)


@functools.lru_cache(maxsize=None)
def _sharded_stats_fn(mesh):
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import BATCH_AXIS

    def core(X, w):
        return _psum_moments(
            X, w, lambda v: jax.lax.psum(v, BATCH_AXIS))

    sm = _build_shard_map(core, mesh,
                          in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS)),
                          out_specs=(P(None), P(None)))
    return jax.jit(sm)


# -- legacy single-program route (global-max convergence) --------------------

def _streamed_core(X, y, w, fold_masks, regs, alphas, max_iter, tol, *,
                   loss, fit_intercept, standardize,
                   axis_name: Optional[str] = None):
    """The sweep body. Under shard_map, X/y/w/fold_masks hold this shard's
    LOCAL rows and `axis_name` names the mesh axis every accumulator
    reduction psums over; axis_name=None is the single-device path.
    max_iter/tol are traced scalars (they only feed the while-loop cond),
    so tuning them never triggers a recompile."""
    n, d = X.shape
    F = fold_masks.shape[0]
    Gn = regs.shape[0]
    L = F * Gn
    rc = _residual_curvature(loss)
    tiled, d_work, bt, tile_pairs = _tiling(d)
    if d_work > d:
        # zero columns are inert end to end: mean 0 -> centered 0,
        # grad 0, H diagonal = l2 + 1e-6 ridge -> Newton step 0, so
        # padded betas stay exactly 0 and are sliced off on return
        X = jnp.pad(X, ((0, 0), (0, d_work - d)))

    def allreduce(v):
        return jax.lax.psum(v, axis_name) if axis_name else v

    if standardize:
        if axis_name is None:
            Xs, mean, std = G._standardize(X, w)
        else:
            mean, std = _psum_moments(X, w, allreduce)
            Xs = ((X.astype(jnp.float32) - mean[None, :]) / std[None, :]) \
                .astype(X.dtype)
    else:
        Xs = X
        mean = jnp.zeros(d_work, jnp.float32)
        std = jnp.ones(d_work, jnp.float32)

    # lane layout: l = f * Gn + g  (fold-major, so per-fold weights expand
    # by broadcast over the grid axis)
    l1 = jnp.tile(regs * alphas, F)                     # [L]
    l2 = jnp.tile(regs * (1.0 - alphas), F)             # [L]
    wsum_f = jnp.maximum(
        allreduce((fold_masks * w[None, :]).sum(1)), EPS)         # [F]
    wsum_l = jnp.repeat(wsum_f, Gn)                     # [L]

    c = min(_ROW_BLOCK_WIDE if tiled else _row_block(d_work), n)
    xs = _blocked(Xs, y, w, fold_masks, c)

    eye = jnp.eye(d_work, dtype=jnp.float32)
    hess_blocks, assemble, h_acc0 = _gram_fns(tiled, d_work, L, bt,
                                              tile_pairs)

    def accumulate(B, b0):
        """One streaming pass: per-lane (g [L,d], Hessian blocks, g0, h0)."""
        Bt = B.T.astype(Xs.dtype)                       # [d, L]

        def body(acc, sl):
            x_blk, y_blk, w_blk, m_blk = sl             # m_blk [F, c]
            gA, hA, g0A, h0A = acc
            eta = jnp.matmul(x_blk, Bt,
                             preferred_element_type=jnp.float32) + b0[None, :]
            r0, s0 = rc(eta, y_blk)                     # [c, L]
            wlf = m_blk.T * w_blk[:, None]              # [c, F]
            wl = jnp.repeat(wlf, Gn, axis=1)            # [c, L] lane weights
            R = r0 * wl
            S = s0 * wl
            xf = x_blk.astype(jnp.float32)
            gA = gA + jnp.matmul(xf.T, R,
                                 preferred_element_type=jnp.float32).T
            hA = hA + hess_blocks(xf, S)
            return (gA, hA, g0A + R.sum(0), h0A + S.sum(0)), None

        acc0 = _shard_vary(
            (jnp.zeros((L, d_work), jnp.float32), h_acc0,
             jnp.zeros(L, jnp.float32), jnp.zeros(L, jnp.float32)),
            axis_name)
        (gA, hA, g0A, h0A), _ = jax.lax.scan(body, acc0, xs)
        # the Rabit-allreduce/Spark-shuffle slot: partial per-shard sums
        # combine over ICI/DCN
        return (allreduce(gA), allreduce(hA),
                allreduce(g0A), allreduce(h0A))

    def cond(state):
        i, _, _, delta = state
        return (i < max_iter) & (delta > tol)

    def body(state):
        i, B, b0, _ = state
        gA, hA, g0A, h0A = accumulate(B, b0)
        B_new, b0_new, delta_vec = _newton_prox_update(
            B, b0, gA, hA, g0A, h0A, wsum_l, l1, l2, eye, assemble,
            fit_intercept)
        return i + 1, B_new, b0_new, delta_vec.max()

    state = (jnp.asarray(0, jnp.int32), jnp.zeros((L, d_work), jnp.float32),
             jnp.zeros(L, jnp.float32), jnp.asarray(jnp.inf, jnp.float32))
    _, B, b0, _ = jax.lax.while_loop(cond, body, state)

    if standardize:
        B = B / std[None, :]
        b0 = b0 - (B * mean[None, :]).sum(1)
    B = B[:, :d]  # drop inert padded columns on the tiled path
    return B.reshape(F, Gn, d), b0.reshape(F, Gn)


@functools.partial(jax.jit,
                   static_argnames=("loss", "fit_intercept", "standardize"))
def sweep_glm_streamed(X: jax.Array, y: jax.Array, w: jax.Array,
                       fold_masks: jax.Array, regs: jax.Array,
                       alphas: jax.Array, *, loss: str = "logistic",
                       max_iter=50, tol=1e-6,
                       fit_intercept: bool = True,
                       standardize: bool = True
                       ) -> Tuple[jax.Array, jax.Array]:
    """All (fold, grid) fits in one program: returns (B [F, G, d] f32,
    b0 [F, G]) in RAW feature units (unstandardized). max_iter/tol are
    traced (distinct values share one executable)."""
    return _streamed_core(X, y, w, fold_masks, regs, alphas, max_iter, tol,
                          loss=loss, fit_intercept=fit_intercept,
                          standardize=standardize, axis_name=None)


@functools.lru_cache(maxsize=None)
def _sharded_sweep_fn(mesh, loss, fit_intercept, standardize):
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import BATCH_AXIS

    def core(X, y, w, fold_masks, regs, alphas, max_iter, tol):
        return _streamed_core(X, y, w, fold_masks, regs, alphas, max_iter,
                              tol, loss=loss, fit_intercept=fit_intercept,
                              standardize=standardize, axis_name=BATCH_AXIS)

    sm = _build_shard_map(
        core, mesh,
        in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS), P(BATCH_AXIS),
                  P(None, BATCH_AXIS), P(None), P(None), P(), P()),
        out_specs=(P(None, None, None), P(None, None)))
    return jax.jit(sm)


def sweep_glm_streamed_sharded(mesh, X, y, w, fold_masks, regs, alphas, *,
                               loss: str = "logistic", max_iter=50,
                               tol=1e-6, fit_intercept: bool = True,
                               standardize: bool = True
                               ) -> Tuple[jax.Array, jax.Array]:
    """Row-sharded streamed sweep over the mesh `batch` axis.

    Same math as sweep_glm_streamed; rows must be padded to the batch-axis
    multiple with zero weights (the validator's mesh device_put does
    this). Each shard scans only its local rows; accumulator psums ride
    ICI within a slice and DCN across slices. Sharded standardization uses
    one-pass psum'd moments (f32), which differs from the single-device
    two-pass by f32 rounding only.

    On a MULTI-PROCESS mesh, host (or fully-addressable) X/y/w/fold_masks
    are treated as THIS PROCESS's rows and landed as the process's
    batch-axis block of one global array (_land_rows_multihost); the
    accumulator psums then cross hosts over DCN. Already-global inputs
    pass through untouched."""
    fn = _sharded_sweep_fn(mesh, loss, bool(fit_intercept),
                           bool(standardize))
    if _mesh_is_mp(mesh):
        from ..parallel import multihost as MH
        from ..parallel import podtrace

        if not _is_global_array(X):
            X, y, w, fold_masks = _land_rows_multihost(mesh, X, y, w,
                                                       fold_masks)
        # flight recorder: the psums are inside the jitted program, so
        # the collective window is the whole sharded call; the explicit
        # block (recording only) pins the barrier wall to this bracket
        # instead of the caller's eventual fetch
        with podtrace.collective(
                "glm_sweep", rows=int(X.shape[0]), feat=int(X.shape[1]),
                lanes=int(np.asarray(regs).shape[0])) as _psp:
            out = fn(
                X, y, w, fold_masks,
                MH.replicated_global(np.asarray(regs, np.float32), mesh),
                MH.replicated_global(np.asarray(alphas, np.float32),
                                     mesh),
                MH.replicated_global(np.asarray(int(max_iter), np.int32),
                                     mesh),
                MH.replicated_global(np.asarray(float(tol), np.float32),
                                     mesh))
            if _psp is not None:
                jax.block_until_ready(out)
        return out
    return fn(
        X, y, w, fold_masks, regs, alphas,
        jnp.asarray(max_iter, jnp.int32), jnp.asarray(tol, jnp.float32))


# -- squared-loss sufficient-statistics fast path ----------------------------

def _gram_core(X, y, w, fold_masks, regs, alphas, max_iter, tol, *,
               fit_intercept, standardize,
               axis_name: Optional[str] = None):
    """loss="squared" fast path: ONE streaming pass accumulates per-FOLD
    sufficient statistics (weighted Gram [F, d, d] + X^T W_f y, X^T W_f 1,
    sums), then the whole reg x alpha grid solves off the cached moments:
    ridge lanes closed form, elastic-net lanes via proximal Newton seeded
    from the ridge solution (`ops/glm.{ridge_gram_solve,prox_newton_gram}`
    — the moment-space replay of the per-lane update rule). When
    standardize=True the column moments are computed first (one extra
    stats pass; raw-moment standardization in moment space would cancel
    catastrophically in f32 for large-mean columns), and standardization
    is applied per block on the fly — no [n, d] standardized copy."""
    n, d = X.shape
    F = fold_masks.shape[0]
    Gn = regs.shape[0]
    tiled, d_work, bt, tile_pairs = _tiling(d)
    if d_work > d:
        X = jnp.pad(X, ((0, 0), (0, d_work - d)))

    def allreduce(v):
        return jax.lax.psum(v, axis_name) if axis_name else v

    if standardize:
        mean, std = _psum_moments(X, w, allreduce)
    else:
        mean = jnp.zeros(d_work, jnp.float32)
        std = jnp.ones(d_work, jnp.float32)

    wsum_f = jnp.maximum(
        allreduce((fold_masks * w[None, :]).sum(1)), EPS)         # [F]

    c = min(_ROW_BLOCK_WIDE if tiled else _row_block(d_work), n)
    xs = _blocked(X, y, w, fold_masks, c)
    hess_blocks, assemble, h_acc0 = _gram_fns(tiled, d_work, F, bt,
                                              tile_pairs)

    def body(acc, sl):
        x_blk, y_blk, w_blk, m_blk = sl                 # m_blk [F, c]
        hA, cA, sxA, syA = acc
        xf = (x_blk.astype(jnp.float32) - mean[None, :]) / std[None, :]
        wlf = m_blk.T * w_blk[:, None]                  # [c, F]
        wy = wlf * y_blk[:, None]                       # [c, F]
        hA = hA + hess_blocks(xf, wlf)
        cA = cA + jnp.matmul(xf.T, wy,
                             preferred_element_type=jnp.float32).T
        sxA = sxA + jnp.matmul(xf.T, wlf,
                               preferred_element_type=jnp.float32).T
        syA = syA + wy.sum(0)
        return (hA, cA, sxA, syA), None

    acc0 = _shard_vary(
        (h_acc0, jnp.zeros((F, d_work), jnp.float32),
         jnp.zeros((F, d_work), jnp.float32), jnp.zeros(F, jnp.float32)),
        axis_name)
    (hA, cA, sxA, syA), _ = jax.lax.scan(body, acc0, xs)
    hA, cA, sxA, syA = (allreduce(hA), allreduce(cA),
                        allreduce(sxA), allreduce(syA))
    Gm_f = assemble(hA)                                 # [F, d, d]

    # expand per-fold moments to the fold-major lane axis l = f*Gn + g
    l1 = jnp.tile(regs * alphas, F)                     # [L]
    l2 = jnp.tile(regs * (1.0 - alphas), F)             # [L]
    Gm = jnp.repeat(Gm_f, Gn, axis=0)                   # [L, d, d]
    cm = jnp.repeat(cA, Gn, axis=0)
    sx = jnp.repeat(sxA, Gn, axis=0)
    sy = jnp.repeat(syA, Gn)
    sw = jnp.repeat(wsum_f, Gn)

    beta_r, b0_r = G.ridge_gram_solve(Gm, cm, sx, sy, sw, l2,
                                      fit_intercept=fit_intercept)
    beta_p, b0_p, iters = G.prox_newton_gram(
        Gm, cm, sx, sy, sw, l1, l2, beta_r, b0_r, max_iter, tol,
        fit_intercept=fit_intercept)
    is_l1 = l1 > 0.0
    B = jnp.where(is_l1[:, None], beta_p, beta_r)
    b0 = jnp.where(is_l1, b0_p, b0_r)

    if standardize:
        B = B / std[None, :]
        b0 = b0 - (B * mean[None, :]).sum(1)
    B = B[:, :d]
    return B.reshape(F, Gn, d), b0.reshape(F, Gn), iters


@functools.partial(jax.jit,
                   static_argnames=("fit_intercept", "standardize"))
def sweep_glm_squared_gram(X: jax.Array, y: jax.Array, w: jax.Array,
                           fold_masks: jax.Array, regs: jax.Array,
                           alphas: jax.Array, max_iter=50, tol=1e-6, *,
                           fit_intercept: bool = True,
                           standardize: bool = True
                           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Squared-loss (fold x grid) sweep from ONE streaming Gram pass.
    Returns (B [F, G, d] f32 RAW units, b0 [F, G], prox-solve iters)."""
    return _gram_core(X, y, w, fold_masks, regs, alphas, max_iter, tol,
                      fit_intercept=fit_intercept, standardize=standardize,
                      axis_name=None)


@functools.lru_cache(maxsize=None)
def _sharded_gram_fn(mesh, fit_intercept, standardize):
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import BATCH_AXIS

    def core(X, y, w, fold_masks, regs, alphas, max_iter, tol):
        return _gram_core(X, y, w, fold_masks, regs, alphas, max_iter, tol,
                          fit_intercept=fit_intercept,
                          standardize=standardize, axis_name=BATCH_AXIS)

    sm = _build_shard_map(
        core, mesh,
        in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS), P(BATCH_AXIS),
                  P(None, BATCH_AXIS), P(None), P(None), P(), P()),
        out_specs=(P(None, None, None), P(None, None), P()))
    return jax.jit(sm)


def sweep_glm_squared_gram_sharded(mesh, X, y, w, fold_masks, regs, alphas,
                                   max_iter=50, tol=1e-6, *,
                                   fit_intercept: bool = True,
                                   standardize: bool = True
                                   ) -> Tuple[jax.Array, jax.Array,
                                              jax.Array]:
    """Row-sharded Gram fast path: each shard accumulates its local rows'
    per-fold moments, one psum combines them, the grid solves replicated.
    Multi-process meshes follow sweep_glm_streamed_sharded's landing
    contract (host inputs = this process's rows)."""
    fn = _sharded_gram_fn(mesh, bool(fit_intercept), bool(standardize))
    if _mesh_is_mp(mesh):
        from ..parallel import multihost as MH
        from ..parallel import podtrace

        if not _is_global_array(X):
            X, y, w, fold_masks = _land_rows_multihost(mesh, X, y, w,
                                                       fold_masks)
        # collective window = sharded call + block (recording only):
        # the Gram psum is inside the program — see sweep_glm_streamed_
        # sharded above for the attribution contract
        with podtrace.collective(
                "glm_gram", rows=int(X.shape[0]), feat=int(X.shape[1]),
                lanes=int(np.asarray(regs).shape[0])) as _psp:
            out = fn(
                X, y, w, fold_masks,
                MH.replicated_global(np.asarray(regs, np.float32), mesh),
                MH.replicated_global(np.asarray(alphas, np.float32),
                                     mesh),
                MH.replicated_global(np.asarray(int(max_iter), np.int32),
                                     mesh),
                MH.replicated_global(np.asarray(float(tol), np.float32),
                                     mesh))
            if _psp is not None:
                jax.block_until_ready(out)
        return out
    return fn(
        X, y, w, fold_masks, regs, alphas,
        jnp.asarray(max_iter, jnp.int32), jnp.asarray(tol, jnp.float32))


# -- round kernel + host retirement driver (IRLS losses) ---------------------

def _round_core(X, y, w, fold_masks, sel, l1, l2, B0, b00, mean, std,
                iters_budget, tol, *, loss, fit_intercept,
                axis_name: Optional[str] = None):
    """K Newton iterations for one compacted lane bucket, with a PER-LANE
    delta vector in the carry so the host can retire converged lanes
    between rounds.

    sel [F, Lb] maps each bucket lane to its fold (one-hot columns);
    all-zero columns are the ladder's inert padding lanes — their weights
    vanish, so they sit at B=0/delta=0 and never gate the early exit.
    B0/b00 carry the lanes' standardized-space state between rounds (the
    host unstandardizes once at the end); mean/std are applied on the fly
    per block, so no standardized [n, d] copy is materialized per round.
    The while cond early-exits as soon as EVERY bucket lane's delta clears
    tol, so a round never burns budget on an already-converged bucket.
    Returns (B [Lb, d] standardized space, b0 [Lb], delta [Lb], iters)."""
    n, d = X.shape
    F = fold_masks.shape[0]
    Lb = sel.shape[1]
    rc = _residual_curvature(loss)
    tiled, d_work, bt, tile_pairs = _tiling(d)
    if d_work > d:
        dp = d_work - d
        X = jnp.pad(X, ((0, 0), (0, dp)))
        B0 = jnp.pad(B0, ((0, 0), (0, dp)))
        mean = jnp.pad(mean, (0, dp))
        std = jnp.pad(std, (0, dp), constant_values=1.0)

    def allreduce(v):
        return jax.lax.psum(v, axis_name) if axis_name else v

    wsum_f = jnp.maximum(
        allreduce((fold_masks * w[None, :]).sum(1)), EPS)         # [F]
    wsum_l = jnp.maximum((wsum_f[:, None] * sel).sum(0), EPS)     # [Lb]

    c = min(_ROW_BLOCK_WIDE if tiled else _row_block(d_work), n)
    xs = _blocked(X, y, w, fold_masks, c)
    eye = jnp.eye(d_work, dtype=jnp.float32)
    hess_blocks, assemble, h_acc0 = _gram_fns(tiled, d_work, Lb, bt,
                                              tile_pairs)

    def accumulate(B, b0):
        Bt = B.T.astype(X.dtype)                        # [d, Lb]

        def body(acc, sl):
            x_blk, y_blk, w_blk, m_blk = sl             # m_blk [F, c]
            gA, hA, g0A, h0A = acc
            # standardize on the fly; the low-precision cast keeps the
            # eta contraction on the bf16 MXU path exactly like the
            # materialized-Xs route
            xs_low = ((x_blk.astype(jnp.float32) - mean[None, :])
                      / std[None, :]).astype(X.dtype)
            eta = jnp.matmul(xs_low, Bt,
                             preferred_element_type=jnp.float32) + b0[None, :]
            r0, s0 = rc(eta, y_blk)                     # [c, Lb]
            wlf = m_blk.T * w_blk[:, None]              # [c, F]
            wl = jnp.matmul(wlf, sel,
                            preferred_element_type=jnp.float32)  # [c, Lb]
            R = r0 * wl
            S = s0 * wl
            xf = xs_low.astype(jnp.float32)
            gA = gA + jnp.matmul(xf.T, R,
                                 preferred_element_type=jnp.float32).T
            hA = hA + hess_blocks(xf, S)
            return (gA, hA, g0A + R.sum(0), h0A + S.sum(0)), None

        acc0 = _shard_vary(
            (jnp.zeros((Lb, d_work), jnp.float32), h_acc0,
             jnp.zeros(Lb, jnp.float32), jnp.zeros(Lb, jnp.float32)),
            axis_name)
        (gA, hA, g0A, h0A), _ = jax.lax.scan(body, acc0, xs)
        return (allreduce(gA), allreduce(hA),
                allreduce(g0A), allreduce(h0A))

    def cond(state):
        i, _, _, delta = state
        return (i < iters_budget) & (delta.max() > tol)

    def body(state):
        i, B, b0, _ = state
        gA, hA, g0A, h0A = accumulate(B, b0)
        B_new, b0_new, delta_vec = _newton_prox_update(
            B, b0, gA, hA, g0A, h0A, wsum_l, l1, l2, eye, assemble,
            fit_intercept)
        return i + 1, B_new, b0_new, delta_vec

    state = (jnp.asarray(0, jnp.int32), B0.astype(jnp.float32),
             b00.astype(jnp.float32),
             jnp.full((Lb,), jnp.inf, jnp.float32))
    i, B, b0, delta = jax.lax.while_loop(cond, body, state)
    return B[:, :d], b0, delta, i


@functools.partial(jax.jit, static_argnames=("loss", "fit_intercept"))
def sweep_glm_round(X: jax.Array, y: jax.Array, w: jax.Array,
                    fold_masks: jax.Array, sel: jax.Array, l1: jax.Array,
                    l2: jax.Array, B0: jax.Array, b00: jax.Array,
                    mean: jax.Array, std: jax.Array, iters_budget,
                    tol, *, loss: str, fit_intercept: bool = True
                    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One retirement round for a compacted lane bucket (see _round_core).
    Compiled per (n, d, F, bucket) shape; iters_budget/tol are traced."""
    return _round_core(X, y, w, fold_masks, sel, l1, l2, B0, b00, mean,
                       std, iters_budget, tol, loss=loss,
                       fit_intercept=fit_intercept, axis_name=None)


@functools.lru_cache(maxsize=None)
def _sharded_round_fn(mesh, loss, fit_intercept):
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import BATCH_AXIS

    def core(X, y, w, fold_masks, sel, l1, l2, B0, b00, mean, std,
             iters_budget, tol):
        return _round_core(X, y, w, fold_masks, sel, l1, l2, B0, b00,
                           mean, std, iters_budget, tol, loss=loss,
                           fit_intercept=fit_intercept,
                           axis_name=BATCH_AXIS)

    sm = _build_shard_map(
        core, mesh,
        in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS), P(BATCH_AXIS),
                  P(None, BATCH_AXIS), P(None, None), P(None), P(None),
                  P(None, None), P(None), P(None), P(None), P(), P()),
        out_specs=(P(None, None), P(None), P(None), P()))
    return jax.jit(sm)


# -- tileplane source route (X streamed from disk, never resident) -----------

@functools.partial(jax.jit, donate_argnums=(0,))
def _source_prep_step(carry, xt, yt, wt, mt):
    """Streamed prep-pass step: global-weight column moments via an exact
    Chan tile merge (one-pass raw E[x^2] would cancel catastrophically in
    f32 for large-mean columns — same rationale as _psum_moments'
    two-pass form, restated per tile) plus the per-fold weight sums. The
    carry is donated: one device-resident accumulator for the pass."""
    cnt, mean, m2, wsum_f = carry
    xf = xt.astype(jnp.float32)
    c_t = wt.sum()
    safe = jnp.maximum(c_t, EPS)
    mean_t = (xf * wt[:, None]).sum(0) / safe
    m2_t = (((xf - mean_t[None, :]) ** 2) * wt[:, None]).sum(0)
    n = cnt + c_t
    nsafe = jnp.maximum(n, EPS)
    delta = mean_t - mean
    return (n, mean + delta * (c_t / nsafe),
            m2 + m2_t + delta * delta * (cnt * c_t / nsafe),
            wsum_f + (mt * wt[:, None]).sum(0))


@functools.partial(jax.jit, static_argnames=("loss",), donate_argnums=(0,))
def _source_round_step(carry, xt, yt, wt, mt, B, b0, sel, mean, std, *,
                       loss: str):
    """One fixed-shape tile's contribution to the round accumulators
    (g [Lb, d_work], Hessian blocks, intercept sums) — the per-tile slice
    of _round_core.accumulate's scan body, standardizing on the fly.
    B/b0/sel/mean/std are per-PASS constants (mean/std column-padded to
    d_work by the driver); the donated carry is the pass's only
    accumulator. mt is [c, F] row-major (the natural source layout)."""
    rc = _residual_curvature(loss)
    d_work = mean.shape[0]
    Lb = B.shape[0]
    tiled, _, bt, tile_pairs = _tiling(d_work)
    if d_work > xt.shape[1]:
        xt = jnp.pad(xt, ((0, 0), (0, d_work - xt.shape[1])))
    hess_blocks, _, _ = _gram_fns(tiled, d_work, Lb, bt, tile_pairs)
    gA, hA, g0A, h0A = carry
    Bt = B.T.astype(xt.dtype)

    c = min(_ROW_BLOCK_WIDE if tiled else _row_block(d_work), xt.shape[0])
    nb = -(-xt.shape[0] // c)
    pad = nb * c - xt.shape[0]
    if pad:
        xt = jnp.pad(xt, ((0, pad), (0, 0)))
        yt = jnp.pad(yt, (0, pad))
        wt = jnp.pad(wt, (0, pad))
        mt = jnp.pad(mt, ((0, pad), (0, 0)))
    xs = (xt.reshape(nb, c, d_work), yt.reshape(nb, c), wt.reshape(nb, c),
          mt.reshape(nb, c, mt.shape[1]))

    def body(acc, sl):
        x_blk, y_blk, w_blk, m_blk = sl
        gA, hA, g0A, h0A = acc
        xs_low = ((x_blk.astype(jnp.float32) - mean[None, :])
                  / std[None, :]).astype(x_blk.dtype)
        eta = jnp.matmul(xs_low, Bt,
                         preferred_element_type=jnp.float32) + b0[None, :]
        r0, s0 = rc(eta, y_blk)                         # [c, Lb]
        wlf = m_blk * w_blk[:, None]                    # [c, F]
        wl = jnp.matmul(wlf, sel,
                        preferred_element_type=jnp.float32)  # [c, Lb]
        R = r0 * wl
        S = s0 * wl
        xf = xs_low.astype(jnp.float32)
        gA = gA + jnp.matmul(xf.T, R,
                             preferred_element_type=jnp.float32).T
        hA = hA + hess_blocks(xf, S)
        return (gA, hA, g0A + R.sum(0), h0A + S.sum(0)), None

    (gA, hA, g0A, h0A), _ = jax.lax.scan(body, (gA, hA, g0A, h0A), xs)
    return gA, hA, g0A, h0A


@functools.partial(jax.jit, static_argnames=("fit_intercept",))
def _source_round_update(gA, hA, g0A, h0A, B, b0, wsum_l, l1, l2, *,
                         fit_intercept: bool):
    """The Newton/prox/intercept update from one streamed pass's merged
    accumulators — the SAME _newton_prox_update as every other route, so
    the streamed-source sweep cannot drift from the resident kernels.
    Returns (B_new, b0_new, delta [Lb])."""
    d_work = B.shape[1]
    Lb = B.shape[0]
    tiled, _, bt, tile_pairs = _tiling(d_work)
    _, assemble, _ = _gram_fns(tiled, d_work, Lb, bt, tile_pairs)
    eye = jnp.eye(d_work, dtype=jnp.float32)
    return _newton_prox_update(B, b0, gA, hA, g0A, h0A, wsum_l, l1, l2,
                               eye, assemble, fit_intercept)


def _source_round_acc0(Lb: int, d_work: int):
    tiled, _, bt, tile_pairs = _tiling(d_work)
    _, _, h_acc0 = _gram_fns(tiled, d_work, Lb, bt, tile_pairs)
    return (jnp.zeros((Lb, d_work), jnp.float32), h_acc0,
            jnp.zeros(Lb, jnp.float32), jnp.zeros(Lb, jnp.float32))


def _new_round_state(L: int, d: int) -> Dict[str, Any]:
    return {"B": np.zeros((L, d), np.float32),
            "b0": np.zeros(L, np.float32),
            "delta": np.full(L, np.inf, np.float32),
            "iters": np.zeros(L, np.int32),
            "retired": np.zeros(L, bool), "warmed": False,
            "rounds": 0, "data_passes": 0, "lane_passes": 0,
            "padded_lane_passes": 0,
            "active_per_round": [], "iters_per_round": [],
            "bucket_sizes": []}


def sweep_glm_streamed_rounds(X, y, w, fold_masks, regs, alphas, *,
                              loss: str, max_iter: int = 50,
                              tol: float = 1e-6, fit_intercept: bool = True,
                              standardize: bool = True, mesh=None,
                              round_iters: Optional[int] = None,
                              warm_start: bool = True,
                              warm_seed: Optional[Tuple] = None,
                              state: Optional[Dict[str, Any]] = None,
                              on_round: Optional[Callable] = None
                              ) -> Tuple[np.ndarray, np.ndarray,
                                         Dict[str, Any]]:
    """Host-driven convergence-aware streamed sweep for the IRLS losses.

    Runs `sweep_glm_round` (K = round_iters or TMOG_GLM_ROUND_ITERS,
    default ROUND_ITERS_DEFAULT, Newton iterations per jitted round); after
    each round, lanes whose own delta cleared `tol` — or that exhausted
    `max_iter` — RETIRE with their coefficients frozen, and the survivors
    compact into the next round's power-of-two bucket (`bucket_lanes`).
    When `warm_start`, round 0 fits only each fold's
    strongest-regularization lane and seeds the rest of the fold from it
    (glmnet-style pathwise continuation), so low-reg lanes start near
    their optimum instead of at zero; TMOG_GLM_WARMSTART=0 disables.

    `warm_seed` is the SAME continuation applied ACROSS TIME instead of
    across the regularization path (the retrain controller's refit):
    ``(beta_raw [d], b0_raw)`` — a previously-fitted model's RAW-unit
    coefficients seed EVERY lane (converted into this sweep's
    standardized space once mean/std are known) and replace the
    pathwise round 0, so a refit over shifted data starts near the
    serving model's optimum. Ignored when the dimension disagrees with
    this sweep's `d` (the vectorization changed — cold start is the
    only honest option) or when a resumed `state` already carries
    coefficients.

    X/y/w/fold_masks are device arrays (pre-sharded when `mesh` is given,
    exactly like sweep_glm_streamed_sharded's contract) — OR X is a
    `parallel.tileplane.RowSource` whose chunks yield
    (x [c, d], y [c], w [c], fold_masks [c, F]) with y/w/fold_masks
    passed as None: then every data pass (the standardization prep pass
    and each Newton iteration of each round) streams tiles from the
    source through the double-buffered tileplane — X is never resident,
    so the sweep runs at data sizes no HBM holds, re-reading disk once
    per iteration. `state`/`on_round`
    are the round-granular checkpoint hooks
    (automl/tuning/checkpoint.RoundCheckpoint): `on_round(state)` fires
    after every retirement boundary with the full resumable state dict,
    and passing that dict back as `state` resumes bit-identically.

    Returns (B [F, G, d] f32 RAW units, b0 [F, G], info) where info holds
    the convergence telemetry (glm_rounds, data_passes, lane_passes,
    lanes_retired, active_per_round, iters_per_round, bucket_sizes)."""
    from ..parallel import tileplane as TP

    regs = np.asarray(regs, np.float32)
    alphas = np.asarray(alphas, np.float32)
    src_mode = isinstance(X, TP.RowSource)
    if src_mode:
        if mesh is not None:
            raise ValueError("mesh and RowSource are exclusive: a source "
                             "sweep streams tiles to the default device")
        if any(a is not None for a in (y, w, fold_masks)):
            raise ValueError("with a RowSource, y/w/fold_masks ride the "
                             "source chunks — pass them as None")
        probe = X.peek()
        d = int(probe[0].shape[1])
        F = int(probe[3].shape[1])
        tile_rows = TP.tile_rows_for(4 * (d + F + 2), X.n_rows)
        # ring depth resolved ONCE for the whole sweep (prep pass +
        # every Newton round) — per-round re-resolution could let a
        # mid-sweep env/corpus change vary the ring between rounds,
        # and one sweep should run one configuration end to end
        prefetch = TP.tile_prefetch_depth()
    else:
        if _mesh_is_mp(mesh) and not _is_global_array(X):
            # multi-process resume/round driver: host inputs are THIS
            # PROCESS's rows (same landing contract as the sharded
            # sweeps); the host-driven retirement loop below is
            # deterministic on replicated round outputs, so every
            # process takes identical retire/compact decisions
            X, y, w, fold_masks = _land_rows_multihost(mesh, X, y, w,
                                                       fold_masks)
        F = int(fold_masks.shape[0])
        d = int(X.shape[1])
    Gn = int(regs.shape[0])
    L = F * Gn
    K = int(round_iters if round_iters is not None
            else os.environ.get("TMOG_GLM_ROUND_ITERS",
                                str(ROUND_ITERS_DEFAULT)))
    K = max(K, 1)
    max_iter = int(max_iter)
    tol_f = float(tol)

    wsum_f_h = None
    if src_mode:
        # ONE streamed prep pass: exact Chan column moments + per-fold
        # weight sums (the resident path computes these per round from
        # the resident fold masks; here they are pass-invariant, so
        # hoisting them costs a single extra read of the stream)
        d_work = _tiling(d)[1]
        prep0 = (jnp.asarray(0.0, jnp.float32), jnp.zeros(d, jnp.float32),
                 jnp.zeros(d, jnp.float32), jnp.zeros(F, jnp.float32))
        (cnt, mu, m2, wsum_f_dev), _ = TP.run_tileplane(
            X, _source_prep_step, prep0, tile_rows=tile_rows,
            label="glm_prep", prefetch=prefetch)
        # host-side fold weight sums; device tiles stay f32
        wsum_f_h = np.maximum(np.asarray(
            wsum_f_dev, np.float64), EPS)  # tmoglint: disable=TPU003  host-only
        if standardize:
            var = jnp.maximum(m2 / jnp.maximum(cnt, EPS), EPS)
            mean = jnp.pad(mu, (0, d_work - d))
            std = jnp.pad(jnp.sqrt(var), (0, d_work - d),
                          constant_values=1.0)
        else:
            mean = jnp.zeros(d_work, jnp.float32)
            std = jnp.ones(d_work, jnp.float32)
    elif standardize:
        if mesh is None:
            mean, std = glm_standardize_stats(X, w)
        else:
            mean, std = _sharded_stats_fn(mesh)(X, w)
    elif _mesh_is_mp(mesh):
        from ..parallel import multihost as MH
        mean = MH.replicated_global(np.zeros(d, np.float32), mesh)
        std = MH.replicated_global(np.ones(d, np.float32), mesh)
    else:
        mean = jnp.zeros(d, jnp.float32)
        std = jnp.ones(d, jnp.float32)

    lane_fold = np.repeat(np.arange(F, dtype=np.int64), Gn)
    l1v = np.tile(regs * alphas, F).astype(np.float32)
    l2v = np.tile(regs * (1.0 - alphas), F).astype(np.float32)
    st = state if state is not None else _new_round_state(L, d)

    warm_seeded = False
    if (warm_seed is not None and not st["warmed"]
            and not st["retired"].any() and int(st["iters"].max()) == 0):
        seed_b = np.asarray(warm_seed[0], np.float32).reshape(-1)
        if seed_b.shape[0] == d:
            # across-time continuation: convert the RAW-unit seed into
            # THIS sweep's standardized space (st["B"] lives there; the
            # final unstandardize below inverts exactly this map)
            mean_h = np.asarray(mean, np.float32)[:d]
            std_h = np.asarray(std, np.float32)[:d]
            b_std = seed_b * std_h
            st["B"][:] = b_std[None, :]
            st["b0"][:] = (float(warm_seed[1])
                           + float((seed_b * mean_h).sum()))
            # the seed plays round 0's role: every lane starts near a
            # known-good solution, so the pathwise warm round is skipped
            st["warmed"] = True
            warm_seeded = True

    # span hook: each retirement round is one child span of whatever the
    # validator opened (run -> sweep_fit -> sweep_round), carrying the
    # bucket/active shape — the trace view of the bucket-ladder story, and
    # the recompile tracker's attribution unit for round programs
    from ..utils.metrics import collector as _collector
    from ..parallel import podtrace as _podtrace

    def _run_source_round(sel, l1b, l2b, B0, b00, budget):
        """One retirement round for a compacted bucket, each Newton
        iteration = one double-buffered streamed pass over the source
        (accumulate) + one tiny jitted update, with the same
        per-iteration early exit as the resident while_loop's cond."""
        d_work = int(mean.shape[0])
        Lb = sel.shape[1]
        wsum_l = jnp.asarray(np.maximum(
            (wsum_f_h[:, None] * sel).sum(0), EPS).astype(np.float32))
        sel_j = jnp.asarray(sel)
        l1j = jnp.asarray(l1b)
        l2j = jnp.asarray(l2b)
        B = jnp.asarray(np.pad(B0, ((0, 0), (0, d_work - d))))
        b0j = jnp.asarray(b00)
        it = 0
        delta = np.full(Lb, np.inf, np.float32)
        for _ in range(int(budget)):
            def step(carry, xt, yt, wt, mt, B=B, b0j=b0j):
                return _source_round_step(carry, xt, yt, wt, mt, B, b0j,
                                          sel_j, mean, std, loss=loss)

            (gA, hA, g0A, h0A), _ps = TP.run_tileplane(
                X, step, _source_round_acc0(Lb, d_work),
                tile_rows=tile_rows, label="glm_round",
                prefetch=prefetch)
            B, b0j, delta_dev = _source_round_update(
                gA, hA, g0A, h0A, B, b0j, wsum_l, l1j, l2j,
                fit_intercept=bool(fit_intercept))
            it += 1
            delta = np.asarray(delta_dev)  # [Lb]: the round's only fetch
            if float(delta.max()) <= tol_f:
                break
        return np.asarray(B)[:, :d], np.asarray(b0j), delta, it

    def run_round(idx, budget):
        k = len(idx)
        Lb = bucket_lanes(k)
        mp_round = (not src_mode) and _mesh_is_mp(mesh)
        with _collector.trace_span(
                f"glm_round[{Lb}]", kind="sweep_round", bucket=int(Lb),
                active=int(k), iters_budget=int(budget)), \
                _podtrace.pod_round(st["rounds"], bucket=int(Lb),
                                    active=int(k)):
            args = None
            with _podtrace.compute("glm_prep", lanes=int(Lb)):
                sel = np.zeros((F, Lb), np.float32)
                sel[lane_fold[idx], np.arange(k)] = 1.0
                l1b = np.zeros(Lb, np.float32)
                l1b[:k] = l1v[idx]
                # inert pads get l2=1 so their (zero-data) Hessian stays
                # well-conditioned; their B stays exactly 0 from the
                # zero init
                l2b = np.ones(Lb, np.float32)
                l2b[:k] = l2v[idx]
                B0 = np.zeros((Lb, d), np.float32)
                B0[:k] = st["B"][idx]
                b00 = np.zeros(Lb, np.float32)
                b00[:k] = st["b0"][idx]
                if not src_mode:
                    if mp_round:
                        from ..parallel import multihost as MH

                        def land(a, dt):
                            return MH.replicated_global(
                                np.asarray(a, dt), mesh)
                    else:
                        def land(a, dt):
                            return jnp.asarray(a, dt)
                    args = (X, y, w, fold_masks, land(sel, np.float32),
                            land(l1b, np.float32), land(l2b, np.float32),
                            land(B0, np.float32), land(b00, np.float32),
                            mean, std, land(budget, np.int32),
                            land(tol_f, np.float32))
            if src_mode:
                Bb, b0b, db, it = _run_source_round(sel, l1b, l2b, B0,
                                                    b00, budget)
            elif mesh is None:
                Bb, b0b, db, it = sweep_glm_round(
                    *args, loss=loss, fit_intercept=fit_intercept)
            else:
                # the psum lives INSIDE the jitted round program, so the
                # collective window on the multi-process path is program
                # call + result fetch: a victim rank's wall here is the
                # barrier wait the skew table attributes (single-process
                # meshes record the same window as plain compute)
                bracket = (_podtrace.collective if mp_round
                           else _podtrace.compute)
                with bracket("glm_round", rows=int(X.shape[0]),
                             feat=int(d), lanes=int(Lb),
                             iters=int(budget)):
                    Bb, b0b, db, it = _sharded_round_fn(
                        mesh, loss, bool(fit_intercept))(*args)
                    Bb = np.asarray(Bb)
                    b0b = np.asarray(b0b)
                    db = np.asarray(db)
                    it = int(it)
            with _podtrace.compute("glm_retire", active=int(k)):
                st["B"][idx] = np.asarray(Bb)[:k]
                st["b0"][idx] = np.asarray(b0b)[:k]
                st["delta"][idx] = np.asarray(db)[:k]
                it = int(it)
                st["iters"][idx] += it
                st["rounds"] += 1
                st["data_passes"] += it
                # useful work (active lanes) vs executed work (the
                # padded bucket the device actually ran) — the FLOP
                # model bills the latter
                st["lane_passes"] += it * k
                st["padded_lane_passes"] += it * Lb
                st["active_per_round"].append(k)
                st["iters_per_round"].append(it)
                st["bucket_sizes"].append(Lb)

    def retire(idx):
        st["retired"][idx] = (st["delta"][idx] <= tol_f) \
            | (st["iters"][idx] >= max_iter)

    if (warm_start and env_on("TMOG_GLM_WARMSTART") and not st["warmed"]
            and Gn > 1
            and not st["retired"].any() and int(st["iters"].max()) == 0):
        g_star = int(np.argmax(regs))
        warm_idx = np.arange(F, dtype=np.int64) * Gn + g_star
        run_round(warm_idx, min(K, max_iter))
        # pathwise continuation: every other lane of the fold starts at
        # its fold's strongest-regularization solution instead of zero
        for f in range(F):
            rows = np.arange(f * Gn, (f + 1) * Gn)
            others = rows[rows != warm_idx[f]]
            st["B"][others] = st["B"][warm_idx[f]]
            st["b0"][others] = st["b0"][warm_idx[f]]
        retire(warm_idx)
        st["warmed"] = True
        if on_round is not None:
            on_round(st)

    while True:
        active = np.flatnonzero(~st["retired"])
        if active.size == 0:
            break
        budget = max(1, min(K, int((max_iter - st["iters"][active]).min())))
        run_round(active, budget)
        retire(active)
        if on_round is not None:
            on_round(st)

    # host-side unstandardize, f32 like the on-device legacy route
    # (source-mode mean/std are column-padded to d_work; the pads are
    # inert — slice back to d)
    mean_h = np.asarray(mean, np.float32)[:d]
    std_h = np.asarray(std, np.float32)[:d]
    B = st["B"] / std_h[None, :]
    b0 = st["b0"] - (B * mean_h[None, :]).sum(1, dtype=np.float32)
    info = {"route": "streamed", "kernel": "rounds",
            "driver": "tileplane" if src_mode else "resident",
            "glm_rounds": int(st["rounds"]),
            "data_passes": int(st["data_passes"]),
            "lane_passes": int(st["lane_passes"]),
            "padded_lane_passes": int(st["padded_lane_passes"]),
            "lanes_total": L,
            "lanes_retired": int((st["delta"] <= tol_f).sum()),
            "lanes_at_cap": int(((st["delta"] > tol_f)
                                 & (st["iters"] >= max_iter)).sum()),
            "active_per_round": [int(v) for v in st["active_per_round"]],
            "iters_per_round": [int(v) for v in st["iters_per_round"]],
            "bucket_sizes": [int(v) for v in st["bucket_sizes"]],
            "warm_start": bool(st["warmed"]),
            "warm_seeded": warm_seeded}
    return B.reshape(F, Gn, d), b0.reshape(F, Gn), info


def sweep_scores_fold(X: jax.Array, B_f: jax.Array, b0_f: jax.Array
                      ) -> jax.Array:
    """[n, Gc] margins for one fold's grid chunk: one MXU contraction
    (bf16 X stays bf16; f32 accumulation)."""
    return jnp.matmul(X, B_f.T.astype(X.dtype),
                      preferred_element_type=jnp.float32) + b0_f[None, :]


# recompile-tracker fallback (utils/tracing): on jax builds without
# jax.monitoring the tracker samples these entries' lowered-executable
# counts at span boundaries instead of listening for compile events — the
# sweep kernels are exactly the programs whose "bounded recompiles on the
# bucket ladder" claim the tracer exists to verify
from ..utils import tracing as _tracing  # noqa: E402

_tracing.register_jit_fallback(
    sweep_glm_round, sweep_glm_streamed, sweep_glm_squared_gram,
    glm_standardize_stats, _source_prep_step, _source_round_step,
    _source_round_update)
