"""Lane-batched streaming GLM sweep: every (fold x grid) fit in ONE pass
over the feature matrix per Newton iteration.

The vmapped sweep (`automl/tuning/validators._sweep`) runs `fit_one` per
lane, so each of the L = folds x grid lanes re-streams the [n, d] matrix
from HBM every iteration and materializes its own weighted [n, d] product
for the Gram matmul — at the 10M-row BASELINE config that is ~5GB of HBM
traffic per lane-iteration and forces the validator to chunk the grid to a
handful of lanes per program. The whole sweep is HBM-bound at a few
percent MFU.

This kernel restructures the math so X streams ONCE per iteration for ALL
lanes (reference workload: the 8-thread pool of OpValidator.scala:270-332,
every thread refitting against the same cached DataFrame):

- one row-block scan per Newton iteration, carrying per-lane accumulators
  (g [L, d], Hessians [L, d, d], intercept sums);
- lane etas in one MXU contraction `X_blk @ B.T` ([c, d] x [d, L]);
- every lane's weighted Gram from ONE batched einsum 'cl,cd,ce->lde'
  with S [c, L] the per-lane curvature weights (narrow path, d <= 128).
  A compressed upper-triangle form (xf[:, iu0] * xf[:, iu1] then an
  [L, c] x [c, T] matmul) halves the arithmetic but its column GATHER
  dominated the pass on TPU — 7.8 TF/s vs the einsum's 25.8 TF/s on a
  v5 lite at the BASELINE shapes (tools/tpu_glm_hess_ab.py). No
  per-lane scaled copy of X exists anywhere;
- per-lane 64x64 Newton solves + proximal L1 + intercept steps are
  batched dense linalg on [L, d, d] — microscopic next to the scan.

Fold masks enter as weights (mask * w), exactly like the vmapped path, so
fold semantics are identical; the elementwise residual/curvature rules per
loss mirror ops/glm's solvers (logistic IRLS, squared, squared-hinge).

Distribution: `sweep_glm_streamed_sharded` runs the SAME core inside a
shard_map over the mesh `batch` axis — each shard scans its local rows,
then every accumulator reduction psums over ICI/DCN (the Spark-shuffle /
Rabit-allreduce slot of SURVEY §2.9); the tiny replicated solves run on
every shard. Sharded standardization uses one-pass psum'd moments.

Standardization note: the per-lane solvers standardize with the lane's own
(fold-masked) weights; this kernel standardizes ONCE with the global
weights so the standardized matrix can be shared by every lane. Fold
means/stds differ from global ones by O(1/sqrt(n)) — statistically inert
at the scales where this kernel is selected (the validator still routes
small problems through the per-lane path).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import glm as G

EPS = 1e-12

# Rows per scan block on the narrow path: bounds the [c, d, d] pairwise
# intermediate XLA materializes when lowering the Gram einsum (f32, 512MB
# at d=64/c=32768) and the [c, L] residual/curvature blocks. _row_block()
# halves c as d grows so the transient never exceeds that budget (d=128
# would otherwise double it).
_ROW_BLOCK = 32_768


def _row_block(d: int) -> int:
    c = _ROW_BLOCK
    while c > 4_096 and c * d * d * 4 > 512 * (1 << 20):
        c //= 2
    return c

# Widest matrix the single-pass (narrow) route handles. The narrow path
# is the full symmetric per-lane Gram einsum 'cl,cd,ce->lde' — 2x the
# arithmetic of the old compressed-triangle pair-product form but 3.3x
# the throughput on v5e (the triangle's column gather xf[:, iu0] was the
# wall; tools/tpu_glm_hess_ab.py). Past this width the [c, d, d] blocks
# outgrow the transient budget and the kernel switches to the
# feature-tiled accumulation (same math, tile-pair granularity).
TRI_MAX_D = 128

# Feature-tile edge for the wide path: each scan step materializes one
# [c, TILE^2] pair-product block per tile pair. 64 keeps MXU tiles square
# and the transient at c * 16K floats.
_FEATURE_TILE = 64

# Rows per scan block on the wide path — c * TILE^2 * 4B = 64MB at 4096.
_ROW_BLOCK_WIDE = 4_096

# Graph-size ceiling for the tiled path: the tile-pair loop is a Python
# unroll inside the scan body inside the Newton while_loop, so pairs
# multiply XLA graph size. 406 pairs = d_pad 1792 (28 tiles) — far past
# any transmogrified width seen in practice, well before compile blowup.
_MAX_TILE_PAIRS = 406


def streamed_route_ok(d: int, lanes: int, budget_bytes: float) -> bool:
    """Can the streamed kernel take a (d features, lanes) sweep within
    `budget_bytes` of device memory? Owns the kernel's own padding and
    graph-size policy so route guards (validators._streamable) cannot
    drift from it: per-iteration footprint is the assembled [L, d, d]
    Hessian + LU workspace + tile accumulators (~4x), and the tiled
    path's Python-unrolled tile-pair loop is capped before XLA graph
    size explodes."""
    if d <= TRI_MAX_D:
        d_work = d
    else:
        nt = -(-d // _FEATURE_TILE)
        if nt * (nt + 1) // 2 > _MAX_TILE_PAIRS:
            return False
        d_work = nt * _FEATURE_TILE
    return lanes * d_work * d_work * 4.0 * 4.0 <= budget_bytes


def _residual_curvature(loss: str):
    """Unweighted per-row residual r and curvature s for eta [c, L]."""
    if loss == "logistic":
        def rc(eta, y):
            p = jax.nn.sigmoid(eta)
            return p - y[:, None], jnp.maximum(p * (1.0 - p), 1e-6)
    elif loss == "squared":
        def rc(eta, y):
            return eta - y[:, None], jnp.ones_like(eta)
    elif loss == "squared_hinge":
        def rc(eta, y):
            # loss 0.5*gap^2 (NOT gap^2): matches glm.fit_linear_svc's
            # residual/curvature so the streamed and per-lane routes see
            # the same effective L2 for a given reg_param
            ypm = (2.0 * y - 1.0)[:, None]
            gap = jnp.maximum(1.0 - ypm * eta, 0.0)
            return -gap * ypm, (gap > 0.0).astype(eta.dtype)
    else:
        raise ValueError(f"unknown streamed loss {loss!r}")
    return rc


def _streamed_core(X, y, w, fold_masks, regs, alphas, *, loss, max_iter,
                   tol, fit_intercept, standardize,
                   axis_name: Optional[str] = None):
    """The sweep body. Under shard_map, X/y/w/fold_masks hold this shard's
    LOCAL rows and `axis_name` names the mesh axis every accumulator
    reduction psums over; axis_name=None is the single-device path."""
    n, d = X.shape
    F = fold_masks.shape[0]
    Gn = regs.shape[0]
    L = F * Gn
    rc = _residual_curvature(loss)
    tiled = d > TRI_MAX_D
    if tiled:
        bt = _FEATURE_TILE
        nt = -(-d // bt)
        d_pad = nt * bt
        if d_pad > d:
            # zero columns are inert end to end: mean 0 -> centered 0,
            # grad 0, H diagonal = l2 + 1e-6 ridge -> Newton step 0, so
            # padded betas stay exactly 0 and are sliced off on return
            X = jnp.pad(X, ((0, 0), (0, d_pad - d)))
        tile_pairs = [(a, b) for a in range(nt) for b in range(a, nt)]
        d_work = d_pad
    else:
        d_work = d

    def allreduce(v):
        return jax.lax.psum(v, axis_name) if axis_name else v

    if standardize:
        if axis_name is None:
            Xs, mean, std = G._standardize(X, w)
        else:
            # two-pass weighted moments with psum'd partials — one-pass
            # E[x^2]-mean^2 cancels catastrophically in f32 for
            # large-mean features (epoch-millisecond timestamps would
            # lose ALL unit-scale variance), silently diverging from the
            # single-device path
            f32 = jnp.float32
            wsum = jnp.maximum(allreduce(w.sum().astype(f32)), EPS)
            xf = X.astype(f32)
            mean = allreduce((xf * w[:, None]).sum(0)) / wsum
            centered = xf - mean[None, :]
            var = allreduce(
                (centered * centered * w[:, None]).sum(0)) / wsum
            std = jnp.sqrt(jnp.maximum(var, EPS))
            Xs = ((X.astype(f32) - mean[None, :]) / std[None, :]) \
                .astype(X.dtype)
    else:
        Xs = X
        mean = jnp.zeros(d_work, jnp.float32)
        std = jnp.ones(d_work, jnp.float32)

    # lane layout: l = f * Gn + g  (fold-major, so per-fold weights expand
    # by broadcast over the grid axis)
    l1 = jnp.tile(regs * alphas, F)                     # [L]
    l2 = jnp.tile(regs * (1.0 - alphas), F)             # [L]
    wsum_f = jnp.maximum(
        allreduce((fold_masks * w[None, :]).sum(1)), EPS)         # [F]
    wsum_l = jnp.repeat(wsum_f, Gn)                     # [L]

    # pad local rows to the block multiple with w=0 (inert everywhere)
    c = min(_ROW_BLOCK_WIDE if tiled else _row_block(d_work), n)
    nb = -(-n // c)
    pad = nb * c - n
    if pad:
        Xs = jnp.pad(Xs, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        w = jnp.pad(w, (0, pad))
        fold_masks = jnp.pad(fold_masks, ((0, 0), (0, pad)))
    xs = (Xs.reshape(nb, c, d_work), y.reshape(nb, c), w.reshape(nb, c),
          fold_masks.reshape(F, nb, c).transpose(1, 0, 2))

    eye = jnp.eye(d_work, dtype=jnp.float32)

    def _hessian_blocks_narrow(xf, S):
        """Per-lane weighted Gram [L, d, d] for one row block, as ONE
        einsum XLA tiles directly. The previous compressed-triangle form
        (xf[:, iu0] * xf[:, iu1] -> [c, T] then an [L, c] x [c, T]
        matmul) halved the contraction FLOPs but its column GATHER
        dominated the whole pass on TPU: measured on v5 lite at the
        BASELINE shapes, the gather-built triangle ran 7.8 TF/s
        end-to-end while this full symmetric einsum runs 25.8 TF/s —
        1.7x faster despite doing 2x the arithmetic
        (tools/tpu_glm_hess_ab.py)."""
        return jnp.einsum('cl,cd,ce->lde', S, xf, xf,
                          preferred_element_type=jnp.float32)

    def _hessian_blocks_tiled(xf, S):
        """Tile-pair contributions [npairs, L, bt*bt] for one row block —
        the wide-d path: each pair materializes only a [c, bt^2] product
        (the [c, d(d+1)/2] full triangle would outgrow HBM past ~128
        features); off-diagonal tile pairs are computed once and mirrored
        at assembly, keeping the triangle savings at tile granularity."""
        out = []
        for a, b in tile_pairs:
            xa = xf[:, a * bt:(a + 1) * bt]
            xb = xf[:, b * bt:(b + 1) * bt]
            P = (xa[:, :, None] * xb[:, None, :]).reshape(-1, bt * bt)
            out.append(jnp.matmul(S.T, P,
                                  preferred_element_type=jnp.float32))
        return jnp.stack(out)

    def _assemble_narrow(hA):
        return hA  # already the full symmetric [L, d, d]

    def _assemble_tiled(hA):
        H = jnp.zeros((L, d_work, d_work), jnp.float32)
        for p, (a, b) in enumerate(tile_pairs):
            blk = hA[p].reshape(L, bt, bt)
            H = H.at[:, a * bt:(a + 1) * bt, b * bt:(b + 1) * bt].set(blk)
            if a != b:
                H = H.at[:, b * bt:(b + 1) * bt,
                         a * bt:(a + 1) * bt].set(
                             blk.transpose(0, 2, 1))
        return H

    if tiled:
        hess_blocks, assemble = _hessian_blocks_tiled, _assemble_tiled
        h_acc0 = jnp.zeros((len(tile_pairs), L, bt * bt), jnp.float32)
    else:
        hess_blocks, assemble = _hessian_blocks_narrow, _assemble_narrow
        h_acc0 = jnp.zeros((L, d_work, d_work), jnp.float32)

    def accumulate(B, b0):
        """One streaming pass: per-lane (g [L,d], Hessian blocks, g0, h0)."""
        Bt = B.T.astype(Xs.dtype)                       # [d, L]

        def body(acc, sl):
            x_blk, y_blk, w_blk, m_blk = sl             # m_blk [F, c]
            gA, hA, g0A, h0A = acc
            eta = jnp.matmul(x_blk, Bt,
                             preferred_element_type=jnp.float32) + b0[None, :]
            r0, s0 = rc(eta, y_blk)                     # [c, L]
            wlf = m_blk.T * w_blk[:, None]              # [c, F]
            wl = jnp.repeat(wlf, Gn, axis=1)            # [c, L] lane weights
            R = r0 * wl
            S = s0 * wl
            xf = x_blk.astype(jnp.float32)
            gA = gA + jnp.matmul(xf.T, R,
                                 preferred_element_type=jnp.float32).T
            hA = hA + hess_blocks(xf, S)
            return (gA, hA, g0A + R.sum(0), h0A + S.sum(0)), None

        acc0 = (jnp.zeros((L, d_work), jnp.float32), h_acc0,
                jnp.zeros(L, jnp.float32), jnp.zeros(L, jnp.float32))
        if axis_name is not None:
            # under shard_map's varying-manual-axes tracking the carry
            # becomes batch-varying inside the body; the initial zeros
            # must carry the same type. pcast is the current spelling;
            # pvary the deprecated one on older jax.
            if hasattr(jax.lax, "pcast"):
                acc0 = jax.lax.pcast(acc0, axis_name, to="varying")
            elif hasattr(jax.lax, "pvary"):
                acc0 = jax.lax.pvary(acc0, axis_name)
        (gA, hA, g0A, h0A), _ = jax.lax.scan(body, acc0, xs)
        # the Rabit-allreduce/Spark-shuffle slot: partial per-shard sums
        # combine over ICI/DCN
        return (allreduce(gA), allreduce(hA),
                allreduce(g0A), allreduce(h0A))

    def cond(state):
        i, _, _, delta = state
        return (i < max_iter) & (delta > tol)

    def body(state):
        i, B, b0, _ = state
        gA, hA, g0A, h0A = accumulate(B, b0)
        g = gA / wsum_l[:, None] + l2[:, None] * B                  # [L, d]
        H = assemble(hA) / wsum_l[:, None, None]
        H = H + (l2[:, None, None] + 1e-6) * eye[None]
        step = jnp.linalg.solve(H, g[..., None])[..., 0]
        B_new = B - step
        hdiag = jnp.maximum(jnp.diagonal(H, axis1=1, axis2=2), EPS)
        B_new = (jnp.sign(B_new)
                 * jnp.maximum(jnp.abs(B_new) - l1[:, None] / hdiag, 0.0))
        if fit_intercept:
            b0_new = b0 - (g0A / wsum_l) / jnp.maximum(h0A / wsum_l, EPS)
        else:
            b0_new = b0
        delta = (jnp.abs(B_new - B).max(axis=1)
                 + jnp.abs(b0_new - b0)).max()
        return i + 1, B_new, b0_new, delta

    state = (jnp.asarray(0, jnp.int32), jnp.zeros((L, d_work), jnp.float32),
             jnp.zeros(L, jnp.float32), jnp.asarray(jnp.inf, jnp.float32))
    _, B, b0, _ = jax.lax.while_loop(cond, body, state)

    if standardize:
        B = B / std[None, :]
        b0 = b0 - (B * mean[None, :]).sum(1)
    B = B[:, :d]  # drop inert padded columns on the tiled path
    return B.reshape(F, Gn, d), b0.reshape(F, Gn)


@functools.partial(jax.jit,
                   static_argnames=("loss", "max_iter", "tol",
                                    "fit_intercept", "standardize"))
def sweep_glm_streamed(X: jax.Array, y: jax.Array, w: jax.Array,
                       fold_masks: jax.Array, regs: jax.Array,
                       alphas: jax.Array, *, loss: str = "logistic",
                       max_iter: int = 50, tol: float = 1e-6,
                       fit_intercept: bool = True,
                       standardize: bool = True
                       ) -> Tuple[jax.Array, jax.Array]:
    """All (fold, grid) fits in one program: returns (B [F, G, d] f32,
    b0 [F, G]) in RAW feature units (unstandardized)."""
    return _streamed_core(X, y, w, fold_masks, regs, alphas, loss=loss,
                          max_iter=max_iter, tol=tol,
                          fit_intercept=fit_intercept,
                          standardize=standardize, axis_name=None)


@functools.lru_cache(maxsize=None)
def _sharded_sweep_fn(mesh, loss, max_iter, tol, fit_intercept,
                      standardize):
    try:  # jax >= 0.8 top-level; experimental path for older releases
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import BATCH_AXIS

    core = functools.partial(
        _streamed_core, loss=loss, max_iter=max_iter, tol=tol,
        fit_intercept=fit_intercept, standardize=standardize,
        axis_name=BATCH_AXIS)
    # the Newton solve is a lax.while_loop; jax 0.4.x shard_map has no
    # replication rule for `while`, so replication checking must be off
    # (the accumulate() psums make every carry replicated by construction).
    # jax >= 0.6 renamed the knob check_rep -> check_vma.
    import inspect as _inspect
    sig = _inspect.signature(shard_map)
    if "check_rep" in sig.parameters:
        extra = {"check_rep": False}
    elif "check_vma" in sig.parameters:
        extra = {"check_vma": False}
    else:
        extra = {}
    sm = shard_map(
        core, mesh=mesh,
        in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS), P(BATCH_AXIS),
                  P(None, BATCH_AXIS), P(None), P(None)),
        out_specs=(P(None, None, None), P(None, None)), **extra)
    return jax.jit(sm)


def sweep_glm_streamed_sharded(mesh, X, y, w, fold_masks, regs, alphas, *,
                               loss: str = "logistic", max_iter: int = 50,
                               tol: float = 1e-6, fit_intercept: bool = True,
                               standardize: bool = True
                               ) -> Tuple[jax.Array, jax.Array]:
    """Row-sharded streamed sweep over the mesh `batch` axis.

    Same math as sweep_glm_streamed; rows must be padded to the batch-axis
    multiple with zero weights (the validator's mesh device_put does
    this). Each shard scans only its local rows; accumulator psums ride
    ICI within a slice and DCN across slices. Sharded standardization uses
    one-pass psum'd moments (f32), which differs from the single-device
    two-pass by f32 rounding only."""
    return _sharded_sweep_fn(mesh, loss, int(max_iter), float(tol),
                             bool(fit_intercept), bool(standardize))(
        X, y, w, fold_masks, regs, alphas)


def sweep_scores_fold(X: jax.Array, B_f: jax.Array, b0_f: jax.Array
                      ) -> jax.Array:
    """[n, Gc] margins for one fold's grid chunk: one MXU contraction
    (bf16 X stays bf16; f32 accumulation)."""
    return jnp.matmul(X, B_f.T.astype(X.dtype),
                      preferred_element_type=jnp.float32) + b0_f[None, :]
