"""Statistical reductions as XLA programs.

Reference equivalents: Spark MLlib ``Statistics.colStats`` + the hand-written
contingency statistics in utils/.../stats/OpStatistics.scala:39
(chiSquaredTest:188, mutualInfo:234, maxConfidences:280, contingencyStats:300)
used by the SanityChecker, and Pearson/Spearman correlations
(SanityChecker.fitFn, core/.../preparators/SanityChecker.scala:535).

All functions are pure, mask-aware (padded rows carry weight 0) and jittable;
on a sharded feature matrix the reductions lower to per-shard partial sums +
ICI all-reduce — the TPU version of Spark's treeAggregate.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

EPS = 1e-12


class ColStats(NamedTuple):
    """Per-column moments over valid (non-NaN, weighted) entries."""
    count: jax.Array        # [d] valid-entry count
    mean: jax.Array         # [d]
    variance: jax.Array     # [d] (unbiased)
    min: jax.Array          # [d]
    max: jax.Array          # [d]
    num_non_zeros: jax.Array  # [d]


@jax.jit
def col_stats(X: jax.Array, w: Optional[jax.Array] = None) -> ColStats:
    """Column statistics with NaN-as-missing handling.

    X: [n, d] float; NaN entries are missing. w: [n] row weights (0 for pads).
    """
    X = jnp.asarray(X)
    n, d = X.shape
    if w is None:
        w = jnp.ones((n,), X.dtype)
    valid = jnp.isfinite(X).astype(X.dtype) * w[:, None]
    Xz = jnp.where(jnp.isfinite(X), X, 0.0)
    cnt = valid.sum(axis=0)
    s1 = (Xz * valid).sum(axis=0)
    s2 = (Xz * Xz * valid).sum(axis=0)
    mean = s1 / jnp.maximum(cnt, EPS)
    var = (s2 - cnt * mean * mean) / jnp.maximum(cnt - 1.0, 1.0)
    var = jnp.maximum(var, 0.0)
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    xmin = jnp.where(valid > 0, Xz, big).min(axis=0)
    xmax = jnp.where(valid > 0, Xz, -big).max(axis=0)
    nnz = ((Xz != 0) & (valid > 0)).astype(X.dtype).sum(axis=0)
    return ColStats(count=cnt, mean=mean, variance=var, min=xmin, max=xmax,
                    num_non_zeros=nnz)


@jax.jit
def pearson_with_label(X: jax.Array, y: jax.Array,
                       w: Optional[jax.Array] = None) -> jax.Array:
    """Pearson correlation of every column with the label. [n,d],[n] -> [d].

    Matches OpStatistics.computeCorrelationsWithLabel (utils
    OpStatistics.scala:71). NaN entries contribute nothing.
    """
    X = jnp.asarray(X)
    y = jnp.asarray(y)
    n, d = X.shape
    if w is None:
        w = jnp.ones((n,), X.dtype)
    valid = jnp.isfinite(X).astype(X.dtype) * w[:, None]
    Xz = jnp.where(jnp.isfinite(X), X, 0.0)
    cnt = jnp.maximum(valid.sum(axis=0), EPS)
    mx = (Xz * valid).sum(axis=0) / cnt
    my = (y[:, None] * valid).sum(axis=0) / cnt
    dx = (Xz - mx[None, :]) * valid
    dy = (y[:, None] - my[None, :]) * valid
    cov = (dx * dy).sum(axis=0)
    vx = (dx * dx).sum(axis=0)
    vy = (dy * dy).sum(axis=0)
    return cov / jnp.sqrt(jnp.maximum(vx * vy, EPS * EPS))


@jax.jit
def pearson_matrix(X: jax.Array, w: Optional[jax.Array] = None) -> jax.Array:
    """Full Pearson correlation matrix [d,d] — one X^T X matmul on the MXU
    (the SanityChecker 'corrType=full' path). NaNs are imputed to column mean
    (pairwise-complete is a host decision; mean-impute keeps one matmul)."""
    X = jnp.asarray(X)
    n, d = X.shape
    if w is None:
        w = jnp.ones((n,), X.dtype)
    stats = col_stats(X, w)
    Xf = jnp.where(jnp.isfinite(X), X, stats.mean[None, :])
    wsum = jnp.maximum(w.sum(), EPS)
    mean = (Xf * w[:, None]).sum(axis=0) / wsum
    Xc = (Xf - mean[None, :]) * jnp.sqrt(w)[:, None]
    cov = Xc.T @ Xc
    sd = jnp.sqrt(jnp.maximum(jnp.diag(cov), EPS))
    return cov / (sd[:, None] * sd[None, :])


def _rank_with_nan(x: jax.Array, w: jax.Array) -> jax.Array:
    """Average (tie-aware) ranks, scipy.stats.rankdata 'average' semantics.

    Ties receive the mean of the positions they occupy — on discrete columns
    (the common case post-pivot) arbitrary within-tie order would drift the
    correlation away from Spark/scipy values, which feeds SanityChecker drop
    decisions. Tied group bounds come from two searchsorteds over the sorted
    values (XLA-friendly; no segment bookkeeping). NaN/pad rows rank NaN.
    """
    n = x.shape[0]
    finite = jnp.isfinite(x) & (w > 0)
    xk = jnp.where(finite, x, jnp.inf)
    order = jnp.argsort(xk)
    xs = xk[order]
    lo = jnp.searchsorted(xs, xs, side="left")    # first index of tie group
    hi = jnp.searchsorted(xs, xs, side="right")   # one past last index
    avg = (lo + hi + 1).astype(x.dtype) / 2.0     # mean of 1-based positions
    ranks = jnp.zeros((n,), x.dtype).at[order].set(avg)
    return jnp.where(finite, ranks, jnp.nan)


@jax.jit
def spearman_with_label(X: jax.Array, y: jax.Array,
                        w: Optional[jax.Array] = None) -> jax.Array:
    """Spearman = Pearson on ranks (SanityChecker CorrelationType.Spearman).

    Pairwise-complete: for each column, BOTH the column and the label are
    re-ranked within that column's valid (non-NaN, weighted) rows.
    """
    X = jnp.asarray(X)
    y = jnp.asarray(y)
    if w is None:
        w = jnp.ones(y.shape, X.dtype)

    def per_column(col):
        wv = w * jnp.isfinite(col).astype(X.dtype)
        cr = _rank_with_nan(col, wv)
        yr = _rank_with_nan(jnp.where(wv > 0, y, jnp.nan), wv)
        # zero-fill invalid label ranks: the weight mask excludes them, and
        # NaN * 0 would otherwise poison the weighted sums
        yr = jnp.where(wv > 0, yr, 0.0)
        return pearson_with_label(cr[:, None], yr, wv)[0]

    return jax.vmap(per_column, in_axes=1)(X)


# -- contingency statistics (OpStatistics.scala) ---------------------------

@jax.jit
def contingency_table(G: jax.Array, Y: jax.Array,
                      w: Optional[jax.Array] = None) -> jax.Array:
    """Contingency counts between a group of indicator columns and one-hot
    labels: [n,k] x [n,c] -> [k,c] — a single matmul (MXU) replacing the
    reference's reduceByKey count aggregation (SanityChecker.scala:440)."""
    G = jnp.asarray(G)
    Y = jnp.asarray(Y)
    if w is not None:
        G = G * w[:, None]
    Gz = jnp.where(jnp.isfinite(G), G, 0.0)
    return Gz.T @ Y


class ContingencyStats(NamedTuple):
    chi2: jax.Array             # scalar chi-squared statistic
    cramers_v: jax.Array        # scalar
    mutual_info: jax.Array      # scalar (natural log)
    pointwise_mutual_info: jax.Array  # [k, c]
    max_rule_confidences: jax.Array   # [k] max_c P(c | row k)
    supports: jax.Array         # [k] row support fraction


@jax.jit
def contingency_stats(table: jax.Array) -> ContingencyStats:
    """Chi²/Cramér's V/MI/PMI/max-rule-confidence from a [k,c] count table.

    Ports OpStatistics.{chiSquaredTest:188, mutualInfo:234,
    maxConfidences:280, contingencyStats:300}.
    """
    # dtype passthrough, not promotion: stays f32 unless the caller already
    # runs an x64 host table
    # tmoglint: disable=TPU003  dtype passthrough, not promotion
    t = jnp.asarray(table, jnp.float64 if table.dtype == jnp.float64 else jnp.float32)
    total = jnp.maximum(t.sum(), EPS)
    rows = t.sum(axis=1)
    cols = t.sum(axis=0)
    expected = rows[:, None] * cols[None, :] / total
    chi2 = jnp.where(expected > 0, (t - expected) ** 2 /
                     jnp.maximum(expected, EPS), 0.0).sum()
    k = (rows > 0).sum()
    c = (cols > 0).sum()
    dof = jnp.maximum(jnp.minimum(k - 1, c - 1), 1).astype(t.dtype)
    cramers_v = jnp.sqrt(chi2 / (total * dof))
    p = t / total
    px = rows / total
    py = cols / total
    pxy_ind = px[:, None] * py[None, :]
    pmi = jnp.where((p > 0) & (pxy_ind > 0),
                    jnp.log(jnp.maximum(p, EPS) / jnp.maximum(pxy_ind, EPS)),
                    0.0)
    mi = (jnp.where(p > 0, p * pmi, 0.0)).sum()
    conf = t / jnp.maximum(rows[:, None], EPS)
    max_conf = conf.max(axis=1)
    support = rows / total
    return ContingencyStats(chi2=chi2, cramers_v=cramers_v, mutual_info=mi,
                            pointwise_mutual_info=pmi,
                            max_rule_confidences=max_conf, supports=support)


def contingency_stats_host(table) -> ContingencyStats:
    """Numpy twin of `contingency_stats` for HOST-resident tables.

    The fused statistics engine (ops/stats_engine.py) returns ALL
    categorical contingency tables from its single device pass; the
    per-group chi2/Cramer's V/MI/rule-confidence derivations then run on
    [k, c]-shaped host tables — dispatching the jitted twin per group
    would reintroduce exactly the one-round-trip-per-group pattern the
    engine removes. Same formulas and EPS guards; f64 because it is host
    numpy on tiny tables."""
    import numpy as _np
    # tmoglint: disable=TPU003  host precision on tiny [k, c] tables
    t = _np.asarray(table, dtype=_np.float64)
    total = max(float(t.sum()), EPS)
    rows = t.sum(axis=1)
    cols = t.sum(axis=0)
    expected = rows[:, None] * cols[None, :] / total
    chi2 = float(_np.where(expected > 0, (t - expected) ** 2
                           / _np.maximum(expected, EPS), 0.0).sum())
    k = int((rows > 0).sum())
    c = int((cols > 0).sum())
    dof = max(min(k - 1, c - 1), 1)
    cramers_v = float(_np.sqrt(chi2 / (total * dof)))
    p = t / total
    px = rows / total
    py = cols / total
    pxy_ind = px[:, None] * py[None, :]
    pmi = _np.where((p > 0) & (pxy_ind > 0),
                    _np.log(_np.maximum(p, EPS)
                            / _np.maximum(pxy_ind, EPS)), 0.0)
    mi = float(_np.where(p > 0, p * pmi, 0.0).sum())
    conf = t / _np.maximum(rows[:, None], EPS)
    return ContingencyStats(
        chi2=chi2, cramers_v=cramers_v, mutual_info=mi,
        pointwise_mutual_info=pmi, max_rule_confidences=conf.max(axis=1),
        supports=rows / total)


@jax.jit
def fill_rate(X: jax.Array, w: Optional[jax.Array] = None) -> jax.Array:
    """Fraction of non-missing entries per column (RawFeatureFilter
    FeatureDistribution.fillRate, core/.../filters/FeatureDistribution.scala:92)."""
    X = jnp.asarray(X)
    n = X.shape[0]
    if w is None:
        w = jnp.ones((n,), X.dtype)
    tot = jnp.maximum(w.sum(), EPS)
    return (jnp.isfinite(X).astype(X.dtype) * w[:, None]).sum(axis=0) / tot


@jax.jit
def js_divergence(p: jax.Array, q: jax.Array) -> jax.Array:
    """Jensen-Shannon divergence between (batched) histograms, normalized.
    (FeatureDistribution.jsDivergence, core/.../filters/FeatureDistribution.scala:138)."""
    p = p / jnp.maximum(p.sum(axis=-1, keepdims=True), EPS)
    q = q / jnp.maximum(q.sum(axis=-1, keepdims=True), EPS)
    m = 0.5 * (p + q)

    def kl(a, b):
        return jnp.where(a > 0, a * jnp.log2(jnp.maximum(a, EPS) /
                                             jnp.maximum(b, EPS)), 0.0).sum(axis=-1)

    return 0.5 * kl(p, m) + 0.5 * kl(q, m)


def hist_bin_ids(V: jax.Array, lo: jax.Array, hi: jax.Array, bins: int,
                 ok: jax.Array) -> jax.Array:
    """Flattened column-offset histogram segment ids for a [n, K] matrix:
    column k's value lands in segment k*(bins+1) + bin, invalid entries
    (ok False) in the trailing missing segment. THE binning rule shared
    by `histogram_batched` (NaN-only missing) and the fused statistics
    engine's in-pass histograms (finite-only), so the two can never drift
    in clip semantics. The float-space clip runs BEFORE the int cast so
    +/-inf clips into the edge bins instead of hitting an undefined
    float->int conversion."""
    span = jnp.maximum(hi - lo, EPS)
    scaled = (jnp.where(ok, V, 0.0) - lo[None, :]) / span[None, :] * bins
    idx = jnp.clip(scaled, 0.0, float(bins - 1)).astype(jnp.int32)
    idx = jnp.where(ok, idx, bins)
    K = V.shape[1]
    return jnp.arange(K, dtype=jnp.int32)[None, :] * (bins + 1) + idx


@functools.partial(jax.jit, static_argnames=("bins",))
def histogram_batched(V: jax.Array, lo: jax.Array, hi: jax.Array,
                      bins: int, w: Optional[jax.Array] = None
                      ) -> jax.Array:
    """Fixed-range histograms of EVERY column at once: [n, K] -> [K,
    bins + 1], last bin = missing (NaN) mass. One jitted program for all
    of RawFeatureFilter's numeric fills (the previous per-column helper
    dispatched an un-jitted program per column); `lo`/`hi` are traced
    [K] vectors, so per-feature ranges never retrace, and `bins` is the
    only static. Binning via the flattened column-offset segment ids of
    ops/pallas_hist._hist_segment_jnp (histogram-as-GEMM's jnp twin)."""
    V = jnp.asarray(V)
    n, K = V.shape
    if w is None:
        w = jnp.ones((n,), jnp.float32)
    # missing == NaN only (the FeatureDistribution convention): +/-inf are
    # VALID values and clip into the edge bins, exactly like the original
    # per-column helper
    ids = hist_bin_ids(V, lo, hi, bins, ~jnp.isnan(V))
    wt = jnp.broadcast_to(w[:, None], (n, K))
    return jax.ops.segment_sum(
        wt.reshape(-1), ids.reshape(-1),
        num_segments=K * (bins + 1)).reshape(K, bins + 1)


@functools.partial(jax.jit, static_argnames=("bins",))
def histogram_fixed(x: jax.Array, lo: jax.Array, hi: jax.Array, bins: int,
                    w: Optional[jax.Array] = None) -> jax.Array:
    """Fixed-width histogram via one-hot segment sum (static shape: `bins`)."""
    x = jnp.asarray(x)
    if w is None:
        w = jnp.ones(x.shape, x.dtype)
    finite = jnp.isfinite(x)
    width = jnp.maximum(hi - lo, EPS)
    idx = jnp.clip(((x - lo) / width * bins).astype(jnp.int32), 0, bins - 1)
    idx = jnp.where(finite, idx, 0)
    wt = jnp.where(finite, w, 0.0)
    return jax.ops.segment_sum(wt, idx, num_segments=bins)
