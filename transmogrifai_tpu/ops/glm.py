"""Generalized-linear-model solvers as pure JAX programs.

These replace Spark MLlib's LBFGS/OWLQN/IRLS optimizers (used by the
reference's OpLogisticRegression / OpLinearRegression / OpLinearSVC /
OpGeneralizedLinearRegression wrappers, core/.../impl/{classification,
regression}/). Design goals:

* full-batch second-order steps — X^T W X is one MXU matmul; on a
  row-sharded X the Gram matrix reduction becomes an ICI psum inserted by
  XLA, so the same code scales from 1 chip to a pod;
* everything fixed-iteration (`lax.fori_loop`) and shape-static so the
  model-selector can `vmap` the whole fit over the hyperparameter grid and
  CV folds (grid x fold axes replace the reference's 8-thread pool,
  OpValidator.scala:318);
* elastic-net via proximal (FISTA-style) steps on the smooth Newton
  direction, matching Spark's OWLQN behavior closely enough for metric
  parity.

Weights: every solver takes per-row weights `w` — fold masks, balancing
weights and padding masks all enter here, so no data movement is needed
between folds.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

EPS = 1e-12


class GLMParams(NamedTuple):
    """Static-shape hyperparameters (vmappable leaves)."""
    reg: jax.Array          # total regularization strength (lambda)
    elastic_net: jax.Array  # alpha in [0,1]: 0 = ridge, 1 = lasso


def _solver_dtype(X: jax.Array):
    """Solver-state dtype: never below f32 even when X is bf16.

    Mixed precision, TPU-first: callers may ship the feature matrix in
    bfloat16 (halving HBM per vmapped sweep lane — the MXU consumes bf16
    natively), while beta/Hessian/solves stay float32. f32 inputs are
    byte-for-byte unaffected."""
    return jnp.promote_types(X.dtype, jnp.float32)


def _mm(a: jax.Array, b: jax.Array) -> jax.Array:
    """Matmul that keeps a low-precision left operand low-precision (no
    [n, d] f32 materialization of a bf16 X) and accumulates in f32."""
    return jnp.matmul(a, b.astype(a.dtype),
                      preferred_element_type=jnp.float32)


def _standardize(X: jax.Array, w: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Weighted column standardization; returns (Xs, mean, std).

    Xs keeps X's dtype (bf16 stays bf16 — centering in bf16 is safe for
    data of moderate dynamic range; pre-center on host otherwise); the
    mean/std statistics accumulate in f32."""
    f32 = jnp.float32
    wsum = jnp.maximum(w.sum().astype(f32), EPS)
    wx = w.astype(X.dtype)
    mean = jnp.sum(X * wx[:, None], axis=0, dtype=f32) / wsum
    centered = X - mean.astype(X.dtype)
    var = jnp.sum(centered * centered * wx[:, None], axis=0, dtype=f32) / wsum
    std = jnp.sqrt(jnp.maximum(var, EPS))
    return centered / std.astype(X.dtype), mean, std


def _unstandardize_beta(beta: jax.Array, intercept: jax.Array,
                        mean: jax.Array, std: jax.Array) -> Tuple[jax.Array, jax.Array]:
    b = beta / std
    return b, intercept - (b * mean).sum()


def _soft_threshold(x: jax.Array, t: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def _newton_prox_fit(grad_hess_fn, d: int, reg: jax.Array, alpha: jax.Array,
                     max_iter: int, tol: float, dtype=jnp.float32):
    """Damped-Newton with L2 in the Hessian and L1 via proximal step.

    grad_hess_fn(beta, b0) -> (g, H, g0, h0) for the unpenalized loss
    (beta: coefficients, b0: intercept handled separately, unregularized).
    """
    l1 = reg * alpha
    l2 = reg * (1.0 - alpha)

    def cond(state):
        i, _, _, delta = state
        return (i < max_iter) & (delta > tol)

    def body(state):
        i, beta, b0, _ = state
        g, H, g0, h0 = grad_hess_fn(beta, b0)
        g = g + l2 * beta
        H = H + l2 * jnp.eye(d, dtype=dtype)
        # solve with jitter for safety
        step = jnp.linalg.solve(H + 1e-6 * jnp.eye(d, dtype=dtype), g)
        beta_new = beta - step
        # proximal L1 using diagonal curvature as scaling
        hdiag = jnp.maximum(jnp.diag(H), EPS)
        beta_new = _soft_threshold(beta_new, l1 / hdiag)
        b0_new = b0 - g0 / jnp.maximum(h0, EPS)
        delta = jnp.abs(beta_new - beta).max() + jnp.abs(b0_new - b0)
        return i + 1, beta_new, b0_new, delta

    beta0 = jnp.zeros((d,), dtype)
    b00 = jnp.asarray(0.0, dtype)
    _, beta, b0, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), beta0, b00,
                     jnp.asarray(jnp.inf, dtype)))
    return beta, b0


# -- sufficient-statistics (Gram) solvers for the squared loss ---------------
#
# For loss="squared" the IRLS curvature is identically 1, so every lane
# Hessian collapses to the per-fold weighted Gram X^T diag(w) X —
# iteration-invariant. ops/glm_sweep streams those moments in ONE pass over
# X; the two solvers below then replay `_newton_prox_fit`'s exact update
# rule in moment space. They live HERE, next to the per-lane solvers whose
# fixed points they share, so the parity contract (pinned by
# tests/test_glm_convergence.py) cannot drift from the reference math.


def ridge_gram_solve(Gm: jax.Array, cm: jax.Array, sx: jax.Array,
                     sy: jax.Array, sw: jax.Array, l2: jax.Array,
                     fit_intercept: bool = True
                     ) -> Tuple[jax.Array, jax.Array]:
    """Closed-form weighted ridge from per-lane sufficient statistics.

    Gm [L, d, d] = X^T W_l X, cm [L, d] = X^T W_l y, sx [L, d] = X^T W_l 1,
    sy [L] = 1^T W_l y, sw [L] = 1^T W_l 1, l2 [L]. Solves the stationary
    point of `_newton_prox_fit(loss=squared, l1=0)` with the intercept
    eliminated: (G/sw - xbar xbar^T + l2 I) beta = c/sw - xbar ybar and
    b0 = ybar - xbar.beta — i.e. the point the per-lane Newton iteration
    converges toward, reached in one batched solve. The 1e-6 jitter matches
    the iterative Hessian's conditioning. Returns (beta [L, d], b0 [L])."""
    f32 = jnp.float32
    d = Gm.shape[-1]
    eye = jnp.eye(d, dtype=f32)
    sw_ = jnp.maximum(sw, EPS)
    if fit_intercept:
        xbar = sx / sw_[:, None]
        ybar = sy / sw_
        A = (Gm / sw_[:, None, None]
             - xbar[:, :, None] * xbar[:, None, :]
             + (l2 + 1e-6)[:, None, None] * eye[None])
        rhs = cm / sw_[:, None] - xbar * ybar[:, None]
        beta = jnp.linalg.solve(A, rhs[..., None])[..., 0]
        b0 = ybar - (beta * xbar).sum(1)
    else:
        A = Gm / sw_[:, None, None] + (l2 + 1e-6)[:, None, None] * eye[None]
        beta = jnp.linalg.solve(A, (cm / sw_[:, None])[..., None])[..., 0]
        b0 = jnp.zeros_like(sy)
    return beta, b0


def prox_newton_gram(Gm: jax.Array, cm: jax.Array, sx: jax.Array,
                     sy: jax.Array, sw: jax.Array, l1: jax.Array,
                     l2: jax.Array, beta0: jax.Array, b00: jax.Array,
                     max_iter, tol, fit_intercept: bool = True
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Lane-batched proximal Newton on cached squared-loss moments.

    Replays `_newton_prox_fit`'s update rule with every data-dependent term
    reconstructed from the sufficient statistics (curvature == 1, so no
    pass over X per iteration): grad = (G beta + b0 sx - c)/sw + l2 beta,
    H = G/sw + (l2 + 1e-6) I (iteration-invariant, factored once by shape),
    proximal L1 against H's diagonal, intercept step b0 - g0 (h0/wsum == 1
    because wsum IS the lane weight sum). Warm-startable via beta0/b00 —
    the Gram fast path seeds from `ridge_gram_solve` of the same l2
    (pathwise continuation). max_iter/tol are traced scalars. Returns
    (beta [L, d], b0 [L], iters executed)."""
    f32 = jnp.float32
    d = Gm.shape[-1]
    eye = jnp.eye(d, dtype=f32)
    sw_ = jnp.maximum(sw, EPS)
    H = Gm / sw_[:, None, None] + (l2 + 1e-6)[:, None, None] * eye[None]
    hdiag = jnp.maximum(jnp.diagonal(H, axis1=1, axis2=2), EPS)

    def cond(state):
        i, _, _, delta = state
        return (i < max_iter) & (delta > tol)

    def body(state):
        i, beta, b0, _ = state
        g = ((jnp.einsum('lde,le->ld', Gm, beta) + b0[:, None] * sx - cm)
             / sw_[:, None] + l2[:, None] * beta)
        step = jnp.linalg.solve(H, g[..., None])[..., 0]
        beta_new = _soft_threshold(beta - step, l1[:, None] / hdiag)
        if fit_intercept:
            g0 = ((sx * beta).sum(1) + b0 * sw_ - sy) / sw_
            b0_new = b0 - g0
        else:
            b0_new = b0
        delta = (jnp.abs(beta_new - beta).max(1)
                 + jnp.abs(b0_new - b0)).max()
        return i + 1, beta_new, b0_new, delta

    state = (jnp.asarray(0, jnp.int32), beta0.astype(f32),
             b00.astype(f32), jnp.asarray(jnp.inf, f32))
    i, beta, b0, _ = jax.lax.while_loop(cond, body, state)
    return beta, b0, i


def fit_logistic(X: jax.Array, y: jax.Array, w: jax.Array,
                 reg: jax.Array, elastic_net: jax.Array,
                 max_iter: int = 50, tol: float = 1e-6,
                 fit_intercept: bool = True,
                 standardize: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Binary logistic regression via IRLS-Newton (+proximal L1).

    Returns (coefficients [d], intercept). Matches Spark's
    LogisticRegression(standardization=true, family=binomial) closely.
    X may be bfloat16 (see _solver_dtype) — per-row work and the Xs*s
    product then stay bf16 while beta/H/solves run in f32.
    """
    dtype = _solver_dtype(X)
    n, d = X.shape
    Xs, mean, std = _standardize(X, w) if standardize else (X, jnp.zeros(d, dtype), jnp.ones(d, dtype))
    wsum = jnp.maximum(w.sum(), EPS)

    def grad_hess(beta, b0):
        eta = _mm(Xs, beta) + b0
        p = jax.nn.sigmoid(eta)
        r = (p - y) * w
        g = _mm(Xs.T, r) / wsum
        s = jnp.maximum(p * (1 - p), 1e-6) * w
        H = _mm((Xs * s.astype(Xs.dtype)[:, None]).T, Xs) / wsum
        g0 = r.sum() / wsum if fit_intercept else jnp.asarray(0.0, dtype)
        h0 = s.sum() / wsum if fit_intercept else jnp.asarray(1.0, dtype)
        return g, H, g0, h0

    beta, b0 = _newton_prox_fit(grad_hess, d, reg, elastic_net, max_iter, tol, dtype)
    if standardize:
        beta, b0 = _unstandardize_beta(beta, b0, mean, std)
    if not fit_intercept:
        b0 = jnp.asarray(0.0, dtype)
    return beta, b0


def fit_linear(X: jax.Array, y: jax.Array, w: jax.Array,
               reg: jax.Array, elastic_net: jax.Array,
               max_iter: int = 50, tol: float = 1e-6,
               fit_intercept: bool = True,
               standardize: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Weighted linear regression with elastic net (Spark LinearRegression).

    Ridge part closed-form per Newton step; L1 via proximal iterations.
    X may be bfloat16 (see _solver_dtype).
    """
    dtype = _solver_dtype(X)
    n, d = X.shape
    Xs, mean, std = _standardize(X, w) if standardize else (X, jnp.zeros(d, dtype), jnp.ones(d, dtype))
    wsum = jnp.maximum(w.sum(), EPS)

    def grad_hess(beta, b0):
        r = (_mm(Xs, beta) + b0 - y) * w
        g = _mm(Xs.T, r) / wsum
        H = _mm((Xs * w.astype(Xs.dtype)[:, None]).T, Xs) / wsum
        g0 = r.sum() / wsum if fit_intercept else jnp.asarray(0.0, dtype)
        h0 = w.sum() / wsum if fit_intercept else jnp.asarray(1.0, dtype)
        return g, H, g0, h0

    beta, b0 = _newton_prox_fit(grad_hess, d, reg, elastic_net, max_iter, tol, dtype)
    if standardize:
        beta, b0 = _unstandardize_beta(beta, b0, mean, std)
    if not fit_intercept:
        b0 = jnp.asarray(0.0, dtype)
    return beta, b0


def fit_linear_svc(X: jax.Array, y: jax.Array, w: jax.Array,
                   reg: jax.Array,
                   max_iter: int = 50, tol: float = 1e-6,
                   fit_intercept: bool = True,
                   standardize: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Linear SVM with squared-hinge loss + L2 (Spark LinearSVC semantics).

    Squared hinge is differentiable, so Newton steps apply with the
    active-set (margin<1) indicator inside the Hessian. X may be bfloat16
    (see _solver_dtype).
    """
    dtype = _solver_dtype(X)
    n, d = X.shape
    ypm = 2.0 * y - 1.0  # {0,1} -> {-1,+1}
    Xs, mean, std = _standardize(X, w) if standardize else (X, jnp.zeros(d, dtype), jnp.ones(d, dtype))
    wsum = jnp.maximum(w.sum(), EPS)

    def grad_hess(beta, b0):
        margin = ypm * (_mm(Xs, beta) + b0)
        active = (margin < 1.0).astype(dtype) * w
        r = -ypm * jnp.maximum(1.0 - margin, 0.0) * w  # d/d_eta of 0.5*max(0,1-m)^2 * ypm... scaled
        g = _mm(Xs.T, r) / wsum
        H = _mm((Xs * active.astype(Xs.dtype)[:, None]).T, Xs) / wsum
        g0 = r.sum() / wsum if fit_intercept else jnp.asarray(0.0, dtype)
        h0 = jnp.maximum(active.sum() / wsum, 1e-6) if fit_intercept else jnp.asarray(1.0, dtype)
        return g, H, g0, h0

    beta, b0 = _newton_prox_fit(grad_hess, d, reg, jnp.asarray(0.0, dtype),
                                max_iter, tol, dtype)
    if standardize:
        beta, b0 = _unstandardize_beta(beta, b0, mean, std)
    if not fit_intercept:
        b0 = jnp.asarray(0.0, dtype)
    return beta, b0


def fit_softmax(X: jax.Array, Y: jax.Array, w: jax.Array,
                reg: jax.Array, elastic_net: jax.Array,
                max_iter: int = 100, lr: float = 1.0,
                fit_intercept: bool = True,
                standardize: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Multinomial logistic regression; Y is one-hot [n, c].

    Uses Boehning's (1992) curvature bound: the softmax Hessian satisfies
    H <= 0.5 (1 - 1/c) X^T W X per class block, so a CONSTANT preconditioner
    A = 0.5(1-1/c) X^T W X + l2 I can be Cholesky-factored once and every
    iteration is pure matmuls + triangular solves — monotone convergence and
    an ideal TPU profile (no per-iteration d x d solves).
    Returns (B [d, c], b0 [c]). X may be bfloat16 (see _solver_dtype).
    """
    dtype = _solver_dtype(X)
    n, d = X.shape
    c = Y.shape[1]
    Xs, mean, std = _standardize(X, w) if standardize else (X, jnp.zeros(d, dtype), jnp.ones(d, dtype))
    wsum = jnp.maximum(w.sum(), EPS)
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    I = jnp.eye(d, dtype=dtype)

    coef = 0.5 * (1.0 - 1.0 / c)
    A = coef * _mm((Xs * w.astype(Xs.dtype)[:, None]).T, Xs) / wsum \
        + l2 * I + 1e-6 * I
    chol = jax.scipy.linalg.cho_factor(A)
    hdiag = jnp.maximum(jnp.diag(A), EPS)
    h0 = jnp.maximum(coef * w.sum() / wsum, 1e-6)

    def body(_, state):
        B, b0 = state
        logits = _mm(Xs, B) + b0[None, :]
        P = jax.nn.softmax(logits, axis=1)
        R = (P - Y) * w[:, None]          # [n, c]
        G = _mm(Xs.T, R) / wsum + l2 * B  # [d, c]
        B_new = B - jax.scipy.linalg.cho_solve(chol, G)
        B_new = _soft_threshold(B_new, l1 / hdiag[:, None])
        if fit_intercept:
            b0_new = b0 - (R.sum(0) / wsum) / h0
        else:
            b0_new = b0
        return B_new, b0_new

    B0 = jnp.zeros((d, c), dtype)
    b00 = jnp.zeros((c,), dtype)
    B, b0 = jax.lax.fori_loop(0, max_iter, body, (B0, b00))
    if standardize:
        Bu = B / std[:, None]
        b0 = b0 - (Bu * mean[:, None]).sum(0)
        B = Bu
    return B, b0


def fit_glr(X: jax.Array, y: jax.Array, w: jax.Array,
            reg: jax.Array, family: str = "gaussian",
            max_iter: int = 25, fit_intercept: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Generalized linear regression via IRLS (Spark
    GeneralizedLinearRegression families: gaussian/identity, poisson/log,
    gamma/log, tweedie — gaussian & poisson are the reference's default grid,
    DefaultSelectorParams.DistFamily).
    """
    dtype = _solver_dtype(X)
    n, d = X.shape
    wsum = jnp.maximum(w.sum(), EPS)
    I = jnp.eye(d, dtype=dtype)

    if family == "gaussian":
        link, inv_link, var_fn = (lambda m: m), (lambda e: e), (lambda m: jnp.ones_like(m))
    elif family == "poisson":
        link = lambda m: jnp.log(jnp.maximum(m, EPS))
        inv_link = jnp.exp
        var_fn = lambda m: jnp.maximum(m, EPS)
    elif family == "gamma":
        link = lambda m: jnp.log(jnp.maximum(m, EPS))
        inv_link = jnp.exp
        var_fn = lambda m: jnp.maximum(m * m, EPS)
    else:
        raise ValueError(f"Unsupported GLR family: {family}")

    def body(_, state):
        beta, b0 = state
        eta = X @ beta + b0
        mu = inv_link(eta)
        if family == "gaussian":
            z = y
            s = w
        else:
            # canonical log link: d_mu/d_eta = mu
            z = eta + (y - mu) / jnp.maximum(mu, EPS)
            s = w * jnp.maximum(mu, EPS)  # working weights mu^2/var * ... = mu for poisson
            if family == "gamma":
                s = w  # mu^2/var = 1 for gamma with log link
        A = (X * s[:, None]).T @ X / wsum + reg * I + 1e-6 * I
        rhs = X.T @ (s * (z - b0)) / wsum
        beta_new = jnp.linalg.solve(A, rhs)
        if fit_intercept:
            b0_new = (s * (z - X @ beta_new)).sum() / jnp.maximum(s.sum(), EPS)
        else:
            b0_new = b0
        return beta_new, b0_new

    beta0 = jnp.zeros((d,), dtype)
    b00 = jnp.asarray(0.0, dtype)
    return jax.lax.fori_loop(0, max_iter, body, (beta0, b00))


def fit_naive_bayes(X: jax.Array, Y: jax.Array, w: jax.Array,
                    smoothing: float = 1.0) -> Tuple[jax.Array, jax.Array]:
    """Multinomial naive Bayes (Spark NaiveBayes modelType=multinomial):
    requires nonnegative features. Returns (log_prob [c, d], log_prior [c])."""
    w_ = w[:, None]
    class_count = (Y * w_).sum(0)                      # [c]
    feat_count = Y.T @ (jnp.maximum(X, 0.0) * w_)      # [c, d]
    log_prior = jnp.log(jnp.maximum(class_count, EPS)) - \
        jnp.log(jnp.maximum(class_count.sum(), EPS))
    num = feat_count + smoothing
    den = feat_count.sum(1, keepdims=True) + smoothing * X.shape[1]
    log_prob = jnp.log(num) - jnp.log(den)
    return log_prob, log_prior
