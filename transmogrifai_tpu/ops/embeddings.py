"""Word embeddings via hashed co-occurrence factorization.

Reference: core/.../impl/feature/OpWord2Vec.scala wraps Spark ML Word2Vec
(skip-gram, async SGD over a driver-broadcast vocab). The TPU-native design
swaps the sampling loop for a GloVe-style closed-form pipeline that is
entirely matmul-shaped:

1. host: hash tokens into a fixed vocab of V bins (no dynamic vocab — the
   same hash-early trick the vectorizers use) and accumulate a windowed
   co-occurrence matrix C [V, V] with vectorized numpy scatters;
2. device: factorize M = log(1 + C) with alternating least squares —
   each half-step is one Gram matrix + one [V, V] x [V, d] matmul + one
   Cholesky solve, repeated a fixed number of iterations.

Document embeddings are mean-pooled word vectors (Spark Word2Vec.transform
does exactly this average).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .hashing import hash_string


def hash_token_ids(tokens: Sequence[str], vocab_bins: int,
                   seed: int = 0) -> np.ndarray:
    """Token strings -> hashed vocab ids (native murmur3 when built)."""
    try:
        from .native_bridge import native_hash_strings
        out = native_hash_strings(list(tokens), seed)
        if out is not None:
            return (out % vocab_bins).astype(np.int64)
    except ImportError:
        pass
    return np.fromiter((hash_string(t, vocab_bins, seed) for t in tokens),
                       np.int64, len(tokens))


def cooccurrence_matrix(token_lists: Sequence[Optional[Sequence[str]]],
                        vocab_bins: int, window: int = 5,
                        seed: int = 0) -> np.ndarray:
    """Symmetric windowed co-occurrence counts [V, V].

    Per document the inner accumulation is vectorized (np.add.at per window
    offset over the whole id array); only the document loop is Python.
    """
    # int64 accumulation is exact at any corpus size (f32 +1 saturates at
    # 2^24 per cell; f64 doubles host->device traffic); the returned f32
    # only feeds log1p, where >=2^24 counts lose < 1e-7 relative
    C = np.zeros((vocab_bins, vocab_bins), np.int64)
    for toks in token_lists:
        if not toks or len(toks) < 2:
            continue
        ids = hash_token_ids(list(toks), vocab_bins, seed)
        for off in range(1, min(window, len(ids) - 1) + 1):
            a, b = ids[:-off], ids[off:]
            np.add.at(C, (a, b), 1)
            np.add.at(C, (b, a), 1)
    return C.astype(np.float32)


@partial(jax.jit, static_argnames=("dim", "n_iter"))
def factorize_embeddings(C: jax.Array, key: jax.Array, dim: int,
                         n_iter: int = 10, reg: float = 1e-2) -> jax.Array:
    """ALS factorization of log(1+C) -> row embeddings [V, dim].

    Symmetric target, two factors W/H pulled together by averaging at the
    end (standard GloVe practice: w + w~).
    """
    M = jnp.log1p(jnp.asarray(C, jnp.float32))
    v = M.shape[0]
    k1, k2 = jax.random.split(key)
    W = jax.random.normal(k1, (v, dim), jnp.float32) * 0.1
    H = jax.random.normal(k2, (v, dim), jnp.float32) * 0.1
    I = jnp.eye(dim, dtype=jnp.float32)

    def body(_, state):
        W, H = state
        G = H.T @ H + reg * I
        W = jax.scipy.linalg.solve(G, (M @ H).T, assume_a="pos").T
        G2 = W.T @ W + reg * I
        H = jax.scipy.linalg.solve(G2, (M.T @ W).T, assume_a="pos").T
        return W, H

    W, H = jax.lax.fori_loop(0, n_iter, body, (W, H))
    return 0.5 * (W + H)


def mean_pool_docs(token_lists: Sequence[Optional[Sequence[str]]],
                   embeddings: np.ndarray, seed: int = 0) -> np.ndarray:
    """Documents -> [n, dim] mean of hashed word vectors (empty doc -> 0).

    Vectorized: one flat hash pass + np.add.at segment-sum over doc ids.
    """
    n = len(token_lists)
    V, dim = embeddings.shape
    lengths = np.fromiter((len(t) if t else 0 for t in token_lists),
                          np.int64, n)
    total = int(lengths.sum())
    # f32 accumulator: doc lengths are tiny (<<2^24 terms) so the mean-pool
    # sum stays within f32 tolerance of the f64 reference (tested in
    # tests/test_tmoglint.py::test_mean_pool_f32_matches_f64)
    out = np.zeros((n, dim), np.float32)
    if not total:
        return out
    flat: List[str] = [t for toks in token_lists if toks for t in toks]
    ids = hash_token_ids(flat, V, seed)
    doc_of = np.repeat(np.arange(n), lengths)
    np.add.at(out, doc_of, embeddings[ids])
    nz = lengths > 0
    out[nz] /= lengths[nz, None]
    return out
