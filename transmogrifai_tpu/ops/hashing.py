"""MurmurHash3 (x86 32-bit) for the hashing trick.

Reference: the vectorizers hash text with MurmurHash3
(TransmogrifierDefaults.HashAlgorithm=MurMur3, hashing in
OPCollectionHashingVectorizer.scala). Implemented here in pure
Python/NumPy; the native C++ fast path (native/hashing.cpp, loaded via
ctypes) takes over for bulk token streams when built — see
ops/native_bridge.py.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3_x86_32 over bytes (matches the standard reference vector)."""
    h = seed & _MASK
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    tail = data[4 * nblocks:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def hash_string(s: str, num_bins: int, seed: int = 0) -> int:
    # surrogatepass mirrors native_bridge._pack_strings so the numpy
    # fallback hashes surrogate-bearing strings identically to the C++ path
    return murmur3_32(s.encode("utf-8", errors="surrogatepass"),
                      seed) % num_bins


def hash_tokens_to_counts(token_lists: Sequence[Optional[Sequence[str]]],
                          num_bins: int, seed: int = 0,
                          binary: bool = False) -> np.ndarray:
    """[n rows of token lists] -> [n, num_bins] count (or 0/1) matrix."""
    try:
        from .native_bridge import native_hash_tokens
        out = native_hash_tokens(token_lists, num_bins, seed)
        if out is not None:
            return np.minimum(out, 1.0) if binary else out
    except ImportError:
        pass
    out = np.zeros((len(token_lists), num_bins), dtype=np.float32)
    for i, toks in enumerate(token_lists):
        if not toks:
            continue
        for t in toks:
            out[i, hash_string(t, num_bins, seed)] += 1.0
    if binary:
        out = np.minimum(out, 1.0)
    return out
