"""LDA topic modeling as batched EM matmuls.

Reference: core/.../impl/feature/OpLDA.scala:60 (199 LoC) wraps Spark ML's
LDA (EM/online variational optimizers) over a count-vector column. The
TPU-native design runs MAP-smoothed multinomial EM where BOTH steps are
dense matmuls on the [docs, vocab] count matrix — a fixed-iteration
`lax.fori_loop` of four GEMMs per iteration, ideal MXU shape, no sampling
and no sparse scatter:

    pred  = theta @ beta                    # [n, v] expected word mass
    R     = C / pred                        # responsibility ratios
    theta <- norm(theta * (R @ beta^T) + (alpha - 1))
    beta  <- norm(beta  * (theta^T @ R) + (eta - 1))

This is the collapsed-to-EM view of variational LDA with Dirichlet priors
(alpha on doc-topic, eta on topic-word) folded in as MAP pseudo-counts.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

EPS = 1e-12


def _norm_rows(M: jax.Array) -> jax.Array:
    M = jnp.maximum(M, EPS)
    return M / M.sum(axis=1, keepdims=True)


@partial(jax.jit, static_argnames=("n_topics", "n_iter"))
def fit_lda(C: jax.Array, key: jax.Array, n_topics: int, n_iter: int = 50,
            alpha: float = 1.1, eta: float = 1.01
            ) -> Tuple[jax.Array, jax.Array]:
    """Fit topics on a count matrix C [n, v].

    Returns (theta [n, k] doc-topic mix, beta [k, v] topic-word dists).
    Deterministic given `key`; n_iter is fixed (XLA-friendly, no
    convergence branch — Spark's default maxIter=10-ish is far below 50).
    """
    C = jnp.asarray(C, jnp.float32)
    n, v = C.shape
    k1, k2 = jax.random.split(key)
    theta = _norm_rows(jax.random.uniform(k1, (C.shape[0], n_topics),
                                          minval=0.5, maxval=1.5))
    beta = _norm_rows(jax.random.uniform(k2, (n_topics, v),
                                         minval=0.5, maxval=1.5))

    def body(_, state):
        th, be = state
        pred = th @ be                               # [n, v]
        R = C / jnp.maximum(pred, EPS)
        th_new = _norm_rows(th * (R @ be.T) + (alpha - 1.0))
        be_new = _norm_rows(be * (th.T @ R) + (eta - 1.0))
        return th_new, be_new

    theta, beta = jax.lax.fori_loop(0, n_iter, body, (theta, beta))
    return theta, beta


@partial(jax.jit, static_argnames=("n_iter",))
def lda_fold_in(C: jax.Array, beta: jax.Array, n_iter: int = 25,
                alpha: float = 1.1) -> jax.Array:
    """Infer doc-topic mixes for NEW documents against frozen topics
    (the transform path: Spark's LDAModel.transform topicDistribution)."""
    C = jnp.asarray(C, jnp.float32)
    theta = jnp.full((C.shape[0], beta.shape[0]),
                     1.0 / beta.shape[0], jnp.float32)

    def body(_, th):
        pred = th @ beta
        R = C / jnp.maximum(pred, EPS)
        return _norm_rows(th * (R @ beta.T) + (alpha - 1.0))

    return jax.lax.fori_loop(0, n_iter, body, theta)
