"""Host (C++) tree training — the small-N/deep-tree twin of ops/trees.py.

The XLA kernels are shaped for the device regime (N >> 2^depth: dense
per-level histograms -> MXU contractions). On the CPU backend at
Titanic-like scale with the reference's default grids (maxDepth up to 12)
the dense design pays for thousands of empty nodes; this module routes
those fits through native/trees.cpp — an occupancy-aware level-wise
builder, the same role libxgboost's C++ plays behind the reference's
OpXGBoost* (SURVEY 2.9) — and returns arrays in exactly the Tree layout
ops/trees.py produces, so freezing/serving/persistence are unchanged.

Binning here is a numpy twin of quantile_edges/bin_matrix (same strided
sample, same right-side searchsorted with the shifted missing bin 0), so a
native fit and an XLA fit grow from identical binned matrices.

Everything degrades gracefully: `available()` is False when the native
library cannot build, and callers keep the XLA path.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from . import trees as T

_lib: Optional[ctypes.CDLL] = None
_tried = False

_i32p = ctypes.POINTER(ctypes.c_int32)
_f32p = ctypes.POINTER(ctypes.c_float)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("TMOG_DISABLE_NATIVE") or \
            os.environ.get("TMOG_DISABLE_NATIVE_TREES"):
        return None
    try:
        from ..native.build import build
        path = build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.tmog_gbt_fit.restype = ctypes.c_int
        lib.tmog_gbt_softmax_fit.restype = ctypes.c_int
        lib.tmog_rf_fit.restype = ctypes.c_int
        lib.tmog_debug_group_sweeps.restype = ctypes.c_int64
        lib.tmog_predict_bins.restype = ctypes.c_int
        lib.tmog_predict_raw.restype = ctypes.c_int
    except (OSError, AttributeError):
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


# -- numpy binning twin ------------------------------------------------------

def quantile_edges_host(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Numpy twin of ops/trees.quantile_edges: [d, n_bins-1] f32 edges over
    present values, strided sample above the same _QUANTILE_SAMPLE cap."""
    n = X.shape[0]
    if n > T._QUANTILE_SAMPLE:
        stride = -(-n // T._QUANTILE_SAMPLE)
        X = X[::stride]
    X = np.asarray(X, np.float32)
    # host-only quantile math: f64 keeps the edge interpolation exact and the
    # returned edges are cast to f32 below, so no f64 reaches the device
    # tmoglint: disable=TPU003  host precision, result cast to f32
    qs = np.arange(1, n_bins, dtype=np.float64) / n_bins
    with np.errstate(invalid="ignore"):
        # tmoglint: disable=TPU003  host precision, result cast to f32
        edges = np.nanquantile(X.astype(np.float64), qs, axis=0)
    return np.asarray(edges.T, np.float32)


def bin_matrix_host(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Numpy twin of ops/trees.bin_matrix: NaN -> 0, present -> 1 +
    right-side searchsorted. uint8 when the bins fit (<= 255 value bins —
    the Xb stream is the native builder's dominant memory traffic at big
    N, and trees.cpp reads 1-byte bins as uint8_t), int32 otherwise."""
    X = np.asarray(X, np.float32)
    n, d = X.shape
    n_bins = edges.shape[1] + 1
    dtype = np.uint8 if n_bins <= 255 else np.int32
    out = np.empty((n, d), dtype)
    for f in range(d):
        col = X[:, f]
        missing = np.isnan(col)
        b = np.searchsorted(edges[f], np.where(missing, -np.inf, col),
                            side="right") + 1
        out[:, f] = np.where(missing, 0, b)
    return out


def bin_context(X: np.ndarray, n_bins: int
                ) -> Tuple[np.ndarray, np.ndarray, int]:
    """(Xb uint8|int32, edges, n_bins) — host twin of _TreeEstimator._bin."""
    X = np.asarray(X, np.float32)
    edges = quantile_edges_host(X, n_bins)
    return bin_matrix_host(X, edges), edges, n_bins


# -- native drivers ----------------------------------------------------------

def _c(arr: np.ndarray, ptr):
    return arr.ctypes.data_as(ptr)


def _xb_native(Xb: np.ndarray):
    """(contiguous array, void pointer, itemsize) for the bin matrix —
    uint8/int8 pass through (itemsize 1), everything else widens to
    int32."""
    if Xb.dtype in (np.uint8, np.int8):
        Xb = np.ascontiguousarray(Xb)
        return Xb, Xb.ctypes.data_as(ctypes.c_void_p), 1
    Xb = np.ascontiguousarray(Xb, np.int32)
    return Xb, Xb.ctypes.data_as(ctypes.c_void_p), 4


def fit_gbt_host(Xb: np.ndarray, y: np.ndarray, w: np.ndarray, *,
                 n_rounds: int, depth: int, n_bins: int,
                 learning_rate: float = 0.1, reg_lambda: float = 1.0,
                 min_child_weight: float = 0.0, min_instances: float = 1.0,
                 min_info_gain: float = 0.0, gamma: float = 0.0,
                 subsample: float = 1.0, feature_frac: float = 1.0,
                 seed: int = 42, loss: str = "logistic"):
    """Native fit_gbt twin. Returns (Tree-of-ndarrays [R, ...], base) or
    None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    Xb, xb_ptr, itemsize = _xb_native(np.asarray(Xb))
    N, F = Xb.shape
    B = n_bins + 1
    M, L = (1 << depth) - 1, 1 << depth
    y32 = np.ascontiguousarray(y, np.float32)
    w32 = np.ascontiguousarray(w, np.float32)
    feat = np.zeros((n_rounds, M), np.int32)
    thresh = np.zeros((n_rounds, M), np.int32)
    miss = np.zeros((n_rounds, M), np.int32)
    leaf = np.zeros((n_rounds, L), np.float32)
    base = ctypes.c_float(0.0)
    rc = lib.tmog_gbt_fit(
        xb_ptr, ctypes.c_int64(N), ctypes.c_int32(F),
        ctypes.c_int32(B), ctypes.c_int32(itemsize),
        _c(y32, _f32p), _c(w32, _f32p),
        ctypes.c_int32(0 if loss == "logistic" else 1),
        ctypes.c_int32(n_rounds), ctypes.c_int32(depth),
        ctypes.c_double(learning_rate), ctypes.c_double(reg_lambda),
        ctypes.c_double(min_child_weight), ctypes.c_double(min_instances),
        ctypes.c_double(min_info_gain), ctypes.c_double(gamma),
        ctypes.c_double(subsample), ctypes.c_double(feature_frac),
        ctypes.c_uint64(seed & (2**64 - 1)),
        _c(feat, _i32p), _c(thresh, _i32p), _c(miss, _i32p),
        _c(leaf, _f32p), ctypes.byref(base))
    if rc != 0:
        return None
    tree = T.Tree(feat=feat, thresh=thresh, leaf=leaf[:, :, None], miss=miss)
    return tree, float(base.value)


def fit_gbt_softmax_host(Xb: np.ndarray, y: np.ndarray, w: np.ndarray, *,
                         n_rounds: int, depth: int, n_bins: int,
                         n_classes: int, learning_rate: float = 0.1,
                         reg_lambda: float = 1.0,
                         min_child_weight: float = 0.0, gamma: float = 0.0,
                         subsample: float = 1.0, feature_frac: float = 1.0,
                         seed: int = 42):
    """Native fit_gbt_softmax twin: Tree arrays with leading
    [n_rounds, n_classes] axes, or None."""
    lib = _load()
    if lib is None:
        return None
    Xb, xb_ptr, itemsize = _xb_native(np.asarray(Xb))
    N, F = Xb.shape
    B = n_bins + 1
    M, L = (1 << depth) - 1, 1 << depth
    RC = n_rounds * n_classes
    y32 = np.ascontiguousarray(y, np.float32)
    w32 = np.ascontiguousarray(w, np.float32)
    feat = np.zeros((RC, M), np.int32)
    thresh = np.zeros((RC, M), np.int32)
    miss = np.zeros((RC, M), np.int32)
    leaf = np.zeros((RC, L), np.float32)
    rc = lib.tmog_gbt_softmax_fit(
        xb_ptr, ctypes.c_int64(N), ctypes.c_int32(F),
        ctypes.c_int32(B), ctypes.c_int32(itemsize),
        _c(y32, _f32p), _c(w32, _f32p),
        ctypes.c_int32(n_classes), ctypes.c_int32(n_rounds),
        ctypes.c_int32(depth), ctypes.c_double(learning_rate),
        ctypes.c_double(reg_lambda), ctypes.c_double(min_child_weight),
        ctypes.c_double(gamma), ctypes.c_double(subsample),
        ctypes.c_double(feature_frac), ctypes.c_uint64(seed & (2**64 - 1)),
        _c(feat, _i32p), _c(thresh, _i32p), _c(miss, _i32p),
        _c(leaf, _f32p))
    if rc != 0:
        return None
    shape = (n_rounds, n_classes)
    return T.Tree(feat=feat.reshape(shape + (M,)),
                  thresh=thresh.reshape(shape + (M,)),
                  leaf=leaf.reshape(shape + (L, 1)),
                  miss=miss.reshape(shape + (M,)))


def fit_forest_host(Xb: np.ndarray, G: np.ndarray, H: np.ndarray, *,
                    n_trees: int, depth: int, n_bins: int,
                    subsample: float = 1.0, feature_frac: float = 1.0,
                    reg_lambda: float = 0.0, min_instances: float = 1.0,
                    min_info_gain: float = 0.0, bootstrap: bool = True,
                    seed: int = 42):
    """Native fit_forest twin (mean leaves): stacked Tree or None."""
    lib = _load()
    if lib is None:
        return None
    Xb, xb_ptr, itemsize = _xb_native(np.asarray(Xb))
    N, F = Xb.shape
    B = n_bins + 1
    G = np.ascontiguousarray(G, np.float32)
    K = G.shape[1]
    H32 = np.ascontiguousarray(H, np.float32)
    M, L = (1 << depth) - 1, 1 << depth
    feat = np.zeros((n_trees, M), np.int32)
    thresh = np.zeros((n_trees, M), np.int32)
    miss = np.zeros((n_trees, M), np.int32)
    leaf = np.zeros((n_trees, L, K), np.float32)
    rc = lib.tmog_rf_fit(
        xb_ptr, ctypes.c_int64(N), ctypes.c_int32(F),
        ctypes.c_int32(B), ctypes.c_int32(itemsize),
        _c(G, _f32p), _c(H32, _f32p), ctypes.c_int32(K),
        ctypes.c_int32(n_trees), ctypes.c_int32(depth),
        ctypes.c_double(reg_lambda), ctypes.c_double(min_instances),
        ctypes.c_double(min_info_gain), ctypes.c_double(subsample),
        ctypes.c_double(feature_frac), ctypes.c_int32(1 if bootstrap else 0),
        ctypes.c_uint64(seed & (2**64 - 1)),
        _c(feat, _i32p), _c(thresh, _i32p), _c(miss, _i32p),
        _c(leaf, _f32p))
    if rc != 0:
        return None
    return T.Tree(feat=feat, thresh=thresh, leaf=leaf, miss=miss)


def predict_bins_host(trees: T.Tree, Xb: np.ndarray, depth: int
                      ) -> np.ndarray:
    """Sum of tree payloads on binned rows (mirrors predict_forest_bins).
    trees may carry any leading batch axes. Native row-major traversal
    when the library is loaded (each row's bins stay in cache across the
    ensemble); numpy gather fallback otherwise."""
    feat = np.ascontiguousarray(np.asarray(trees.feat), np.int32)
    thresh = np.ascontiguousarray(np.asarray(trees.thresh), np.int32)
    miss = np.ascontiguousarray(np.asarray(trees.miss), np.int32)
    leaf = np.ascontiguousarray(np.asarray(trees.leaf), np.float32)
    M = feat.shape[-1]
    K = leaf.shape[-1]
    feat = feat.reshape(-1, M)
    thresh = thresh.reshape(-1, M)
    miss = miss.reshape(-1, M)
    leaf = leaf.reshape(-1, leaf.shape[-2], K)
    N = Xb.shape[0]
    out = np.zeros((N, K), np.float32)

    lib = _load()
    if lib is not None:
        Xbc, xb_ptr, itemsize = _xb_native(np.asarray(Xb))
        rc = lib.tmog_predict_bins(
            xb_ptr, ctypes.c_int64(N), ctypes.c_int32(Xbc.shape[1]),
            ctypes.c_int32(itemsize), _c(feat, _i32p), _c(thresh, _i32p),
            _c(miss, _i32p), _c(leaf, _f32p),
            ctypes.c_int32(feat.shape[0]), ctypes.c_int32(depth),
            ctypes.c_int32(K), _c(out, _f32p))
        if rc == 0:
            return out

    rows = np.arange(N)
    for t in range(feat.shape[0]):
        rel = np.zeros(N, np.int64)
        for d in range(depth):
            gi = (1 << d) - 1 + rel
            f = feat[t, gi]
            b = Xb[rows, f]
            right = (b > thresh[t, gi]) | ((b == 0) & (miss[t, gi] > 0))
            rel = 2 * rel + right
        out += leaf[t, rel]
    return out


def predict_raw_native(feat: np.ndarray, thresh_val: np.ndarray,
                       leaf: np.ndarray, X: np.ndarray, depth: int,
                       miss: np.ndarray) -> Optional[np.ndarray]:
    """Native raw-value ensemble traversal (serving twin of
    ops/trees.np_predict_ensemble); None when the library is absent."""
    lib = _load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, np.float32)
    feat = np.ascontiguousarray(feat, np.int32)
    tv = np.ascontiguousarray(thresh_val, np.float32)
    miss = np.ascontiguousarray(miss, np.int32)
    leaf = np.ascontiguousarray(leaf, np.float32)
    N, F = X.shape
    T_, K = feat.shape[0], leaf.shape[-1]
    out = np.zeros((N, K), np.float32)
    rc = lib.tmog_predict_raw(
        _c(X, _f32p), ctypes.c_int64(N), ctypes.c_int32(F),
        _c(feat, _i32p), _c(tv, _f32p), _c(miss, _i32p), _c(leaf, _f32p),
        ctypes.c_int32(T_), ctypes.c_int32(depth), ctypes.c_int32(K),
        _c(out, _f32p))
    return out if rc == 0 else None
