"""Pallas TPU kernel for tree gradient histograms.

The XLA chunked histogram path (ops/trees._histograms_matmul) materializes
its [chunk, F*B] one-hot block in HBM every scan step — ~1GB of write+read
traffic per 64K-row chunk, ~150GB per level at the 10M-row BASELINE
config, which dominates the tree sweep's wall clock. This kernel builds
the one-hot tiles directly in VMEM (they never exist in HBM) and leaves
one MXU contraction per row block:

    out[slot*C + c, f*B + b] += sum_i  1[slot_i = slot] * P[c, i]
                                     * 1[Xb[f, i] = b]

- inputs arrive TRANSPOSED ([F, N] / [C, N] / [1, N]) so the huge axis is
  minor: TPU tiling pads the minor axis to 128 lanes, and feeding [N, C]
  with C=4 would inflate HBM 32x (the round-2 fold-vmap OOM was exactly
  this padding on [5, 10M] arrays);
- the (feature, bin) one-hot is a VPU broadcast-compare reshaped
  [F, B, blk] -> [F*B, blk] (leading-dim merge, layout-free);
- slot one-hots drop out-of-range ids (slot = n_slots encodes "row
  contributes nothing" — how histogram subtraction or padded rows enter);
- grid steps run sequentially on the core, accumulating into the same
  VMEM output block (zeroed at step 0).

Reference workload: XGBoost's hist-method gradient histograms, the C++
path behind the reference's OpXGBoost* wrappers (SURVEY §2.9).
"""
from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

_BLK = 4096


def _is_v5_plus() -> bool:
    """Device-generation probe shared by every VMEM budget: v5e+ carries
    128MB of VMEM per core, older generations 16-32MB. False on a
    backend that cannot report a device (budgets then stay at the
    conservative older-generation values)."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return False
    return any(s in kind for s in ("v5", "v6", "v7"))


def _tile_budget() -> int:
    """VMEM budget for the [cols, blk] f32 one-hot tile, by device
    generation. On v5e+ a 16MB tile (plus the accumulator and payload
    tiles, all much smaller) clears the compiler's headroom while
    cutting the grid-step count 4x vs the old 4MB budget — at 10M rows
    the per-step loop overhead and the skinny [S*C, 256] matmuls were
    the tree sweep's real wall (8.5s warm fit, BENCH_NOTES r3). Older
    generations keep the conservative 4MB budget known to compile
    there."""
    return (24 << 20) if _is_v5_plus() else (4 << 20)


def block_rows(n_onehot_cols: int) -> int:
    """Rows per grid step, sized so the [cols, blk] f32 one-hot tile stays
    within the device's tile budget (v5e tree histograms: F*B ~ 2048 ->
    2048 rows; 4096-bin rank metrics -> 1024)."""
    blk = _BLK
    budget = _tile_budget()
    while blk > 128 and n_onehot_cols * blk * 4 > budget:
        blk //= 2
    return blk


def _vmem_limit() -> int:
    """Usable VMEM per core, with compiler headroom held back (100 of
    128MB on v5e+, 12 of 16MB older). The limit gates kernel forms
    whose residents scale with problem shape (the fused fold
    histogram's output block) — exceeding it is a Mosaic compile
    error, not a slowdown."""
    return (100 << 20) if _is_v5_plus() else (12 << 20)


@dataclasses.dataclass(frozen=True)
class HistPlan:
    """Tile/residency plan for one fused multi-(fold x config-lane)
    histogram program — the single place tile shapes are derived from
    (rows, cols, slots, lanes). Produced by plan_fused_hist; consumed by
    the sweep chunker (plan_lane_chunk / models/trees) and the VMEM gate
    (fused_hist_fits)."""

    lanes: int        # fold x config lanes resident in one program
    n_slots: int      # worst-level slot count budgeted (2^(depth-2))
    blk: int          # rows per grid step (the HBM->VMEM tile height)
    out_bytes: int    # fused output block, fully VMEM-resident
    vmem_bytes: int   # estimated total VMEM residents
    fits: bool        # vmem_bytes within the device budget


def plan_fused_hist(n_feat: int, n_bins: int, lanes: int, depth: int,
                    channels: int = 3) -> HistPlan:
    """Plan VMEM residency for the fused histogram kernel at this shape.

    The fused output block [lanes * n_slots * channels, F * B] f32 is
    fully VMEM-resident and scales with every one of those factors;
    block_rows only budgets the one-hot tile, so XGB-shaped configs
    (256 bins, depth 6, a few hundred features, 3-5 folds) would sail
    past a Mosaic compile failure with no library-level fallback. Worst
    level is the deepest histogram pass: sibling subtraction halves the
    slot count, so n_slots = 2^(depth-2) for depth >= 2. Under the
    level-scan fit (ops/trees, TMOG_TREE_SCAN default) this is not just
    the worst case but THE per-program shape: every fused pass runs at
    the padded 2^(depth-2) slot width, and Mosaic compiles exactly one
    route_hist program per (shape, depth) instead of one per level.
    Residents:
    output block + the [F*B, blk] f32 one-hot tile (+ a bf16 copy when
    the bf16 input mode is on) + the f32 Xb/payload/slot tiles + the
    route-fused node one-hot tile (the route+hist kernel keeps a
    [n_pad, blk] node one-hot alive next to the histogram operands).
    """
    cols = n_feat * n_bins
    n_slots = 1 << max(depth - 2, 0)
    out_b = lanes * n_slots * channels * cols * 4
    blk = block_rows(cols)
    onehot_b = cols * blk * 4
    if _HIST_BF16:
        onehot_b += cols * blk * 2
    minor_b = (n_feat + lanes * channels + lanes) * blk * 8
    # route-fused node one-hot: worst routed level has 2^(depth-2) nodes,
    # minor-padded to 128 lanes (the final level routes through the
    # standalone route kernel, whose residents are strictly smaller)
    route_b = max(-(-n_slots // 128) * 128, 128) * blk * 4
    vmem = out_b + onehot_b + minor_b + route_b
    return HistPlan(lanes=lanes, n_slots=n_slots, blk=blk, out_bytes=out_b,
                    vmem_bytes=vmem, fits=vmem <= _vmem_limit())


def fused_hist_fits(n_feat: int, n_bins: int, n_folds: int, depth: int,
                    channels: int = 3) -> bool:
    """Will the fold-fused histogram kernel's VMEM residents fit? (Thin
    gate over plan_fused_hist; callers — models/trees._fused_route_ok —
    fall back to the sequential per-fold path when this returns False.)"""
    return plan_fused_hist(n_feat, n_bins, n_folds, depth, channels).fits


def plan_lane_chunk(n_feat: int, n_bins: int, n_folds: int, n_configs: int,
                    depth: int, channels: int = 3,
                    n_shards: int = 1) -> int:
    """Configs per fused sweep program, honoring every budget at once.

    The single planner for the config-fused sweep: lanes = configs x
    folds share one residency of the binned matrix, but three budgets cap
    how many fit one program — the VMEM plan (plan_fused_hist), the HBM
    lane budget (TMOG_GRID_FUSE_HBM_LANES: each lane carries 4 lane-sized
    f32 planes — W, g, h, margins), and the fused output block cap
    (TMOG_GRID_FUSE_OUT_MB: Mosaic's layout search explodes when the out
    block nears the scoped-VMEM boundary; r5 session 2 saw 20+ min
    compiles at a 16MB block). Returns the largest config chunk (halving
    from n_configs) that clears ALL THREE, and 0 when even a single
    config's fold lanes violate any cap — callers must then fall back to
    the per-config route (a chunk of 1 that only cleared the VMEM gate
    used to sail past the HBM/out-block caps; ADVICE round 5).

    `n_shards` is the lane-shard budget of the mesh route
    (fit_gbt_folds_sharded): the 4 row-planes every lane carries shard
    over the mesh batch axis, so per-device HBM pressure divides by the
    shard count and the lane budget multiplies by it. VMEM and
    out-block caps are PER DEVICE and do not scale — the fused output
    block is replicated on every shard (psum-merged)."""
    # caps resolve through the plan-time autotuner (docs/planning.md):
    # explicitly-set TMOG_GRID_FUSE_HBM_LANES / _OUT_MB win (hand beats
    # model, logged as plan_override), a measured corpus may move them
    # (the out-MB candidates are pre-filtered through the compile-knee
    # term, so the cap can never reach a block size whose predicted
    # Mosaic compile busts the budget), and a cold corpus / TMOG_PLAN=0
    # / any planner fault keeps the 64-lane / 8MB hand defaults
    try:
        from ..planner.plan import planned_grid_fuse_caps
        lane_cap, out_mb_cap = planned_grid_fuse_caps()
    except Exception:
        lane_cap = int(os.environ.get("TMOG_GRID_FUSE_HBM_LANES", "64"))
        out_mb_cap = float(os.environ.get("TMOG_GRID_FUSE_OUT_MB", "8"))
    hbm_lane_budget = lane_cap * max(int(n_shards), 1)

    def ok(chunk: int) -> bool:
        lanes = chunk * n_folds
        plan = plan_fused_hist(n_feat, n_bins, lanes, depth, channels)
        return (plan.fits and lanes <= hbm_lane_budget
                and plan.out_bytes / 1e6 <= out_mb_cap)

    chunk = max(n_configs, 1)
    while chunk > 1 and not ok(chunk):
        chunk = (chunk + 1) // 2
    if chunk == 1 and not ok(1):
        return 0
    return chunk


# -- analytic HBM traffic (roofline accounting) -----------------------------

def sweep_level_bytes(n_rows: int, n_feat: int, lanes: int, *,
                      channels: int = 2, xb_itemsize: int = 1,
                      fused=True) -> int:
    """Analytic HBM bytes moved for ONE mid-sweep tree level.

    Three routes, honest about what each actually streamed:

    fused='per_fold' (or False): the sequential per-lane route (r5's
    fallback when fold fusion was gated off) — every lane re-streams the
    binned matrix for its histogram pass AND again for its routing pass,
    plus per-lane payload (g/h, `channels` f32 planes), the slot plane
    and the node read+write.

    fused='r5' models what the r5 production TPU route ACTUALLY moved
    per config: the fold axis was already fused (one hist_pallas + one
    route_pallas per level for all `lanes` folds, so Xb streams twice
    per level total), but the count channel was its own HBM plane and
    routing was a separate pass.

    fused='fused' (or True): the batched route+hist kernel — ONE
    residency of the binned matrix serves every (fold x config) lane,
    the count channel is derived in VMEM from the hessian (no HBM
    plane), and routing rides the same pass (node read + next-level node
    write per lane).

    The bench/tools roofline reports are computed from this single model
    so the numbers cannot drift from the kernels they describe.
    """
    mode = {True: "fused", False: "per_fold"}.get(fused, fused)
    xb = n_rows * n_feat * xb_itemsize
    pay = channels * 4 * n_rows            # g/h f32 planes per lane
    node = 4 * n_rows                      # f32 slot/node plane
    if mode == "per_fold":
        # hist pass: Xb + payload + count plane + slot ids; route pass:
        # Xb again + node read + node write
        per_lane = 2 * xb + pay + 2 * node + 2 * node
        return lanes * per_lane
    if mode == "r5":
        # fold-fused hist pass (payload + streamed count + slot ids per
        # lane) + separate fold-fused route pass (node read + write)
        return 2 * xb + lanes * (pay + 2 * node + 2 * node)
    if mode != "fused":
        raise ValueError(f"unknown traffic mode {fused!r}")
    return xb + lanes * (pay + 2 * node)   # node read + new-node write


def fused_fit_bytes(n_rows: int, n_feat: int, lanes: int, depth: int,
                    n_rounds: int, *, xb_itemsize: int = 1) -> int:
    """Analytic HBM bytes for one whole fused-sweep GBT fit (all rounds).

    Per round: the level-0 histogram pass (Xb + per-lane payload + slot),
    depth-1 fused route+hist passes (_grow_tree_folds calls route_hist
    for every d in 0..depth-2; sweep_level_bytes each), the final
    standalone route (Xb + node read/write per lane) and the leaf lookup
    + margin update (3 lane planes). Used by the sweep's roofline spans
    (utils/metrics collector) — analytic by construction since the whole
    fit is one jitted program."""
    xb = n_rows * n_feat * xb_itemsize
    plane = 4 * n_rows
    level0 = xb + lanes * (2 * plane + plane)      # g/h + slot ids
    mid = max(depth - 1, 0) * sweep_level_bytes(
        n_rows, n_feat, lanes, xb_itemsize=xb_itemsize, fused=True)
    final_route = (xb + lanes * 2 * plane) if depth >= 1 else 0
    leaf_margin = lanes * 3 * plane
    return n_rounds * (level0 + mid + final_route + leaf_margin)


# THE pallas kill switch — single flag for every consumer (tree
# histograms, lane-batched metrics). Env default: TMOG_NO_PALLAS truthy
# (not "0"/"false"/"") disables; set_enabled() is the runtime toggle.
_enabled = os.environ.get("TMOG_NO_PALLAS", "").strip().lower() \
    in ("", "0", "false")

# jitted functions whose compiled executables bake the pallas choice in;
# cleared on toggle so a cached program cannot pin the previous choice
_cache_consumers = []


def register_cache_consumer(fn) -> None:
    """Register a jitted function that traces through available()."""
    _cache_consumers.append(fn)


def enabled() -> bool:
    return _enabled


def set_enabled(enabled: bool) -> None:
    global _enabled
    if _enabled == bool(enabled):
        return
    _enabled = bool(enabled)
    for fn in _cache_consumers:
        fn.clear_cache()


def available() -> bool:
    """Pallas path usable? (enabled + TPU backend + pallas importable.)"""
    if not _enabled or jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except Exception:
        return False
    return True


# Kernel variant selector (first-contact A/B lever): the "reshape" form
# builds the (feature, bin) one-hot as a 3D broadcast-compare reshaped
# [F, B, blk] -> [F*B, blk] (a leading-dim merge); "concat" builds it as F
# independent [B, blk] 2D compares concatenated along the leading dim — no
# 3D intermediate and no reshape at all, a genuinely different Mosaic
# lowering path in case the reshape form is what stalled the round-3
# 10M-row first contact (note jnp.repeat would NOT qualify: it lowers to
# the same broadcast+reshape). Runtime-switchable so
# tools/tpu_staged_probe.py can try both. NOTE: the bf16 input mode
# always builds its one-hot with the per-feature concat form (a full-size
# f32 one-hot next to its bf16 copy would overflow the scoped-VMEM stack,
# and Mosaic rejects bf16 compares), so this A/B lever only
# distinguishes lowerings on the f32 path — which is exactly what the
# probe's pallas_direct stage runs (it does not pass allow_bf16).
_VARIANTS = ("reshape", "concat")
_VARIANT = os.environ.get("TMOG_PALLAS_HIST_VARIANT", "reshape").strip() \
    or "reshape"

# Histogram contraction input dtype. bf16 doubles the MXU ceiling (the
# fused fold fit runs near the f32 matmul peak); the one-hot operand is
# EXACT in bf16 (0/1) and counts stay integer-exact (1.0 payloads, f32
# accumulation) — only the g/h payload channels quantize (~0.4%
# relative). Flip with TMOG_HIST_BF16=0 to fall back to full-f32 inputs.
_HIST_BF16 = os.environ.get("TMOG_HIST_BF16", "1").strip().lower() \
    not in ("0", "false", "off")


def set_hist_bf16(enabled: bool) -> None:
    """Toggle bf16 histogram inputs. hist_pallas itself resolves the flag
    OUTSIDE its jit (it becomes the use_bf16 cache key), so only the
    registered consumer jits — which bake the flag into their traces —
    need their caches cleared."""
    global _HIST_BF16
    if _HIST_BF16 == bool(enabled):
        return
    _HIST_BF16 = bool(enabled)
    for fn in _cache_consumers:
        fn.clear_cache()


def set_variant(name: str) -> None:
    global _VARIANT
    if name not in _VARIANTS:
        raise ValueError(f"unknown pallas hist variant: {name!r}")
    if name != _VARIANT:
        _VARIANT = name
        for fn in _cache_consumers:
            fn.clear_cache()
        _hist_pallas_jit.clear_cache()
        _route_hist_pallas_jit.clear_cache()


def _feature_onehot(xf, *, F, B, blk, variant, use_bf16):
    """(feature, bin) one-hot tile [F*B, blk] — the shared VPU expansion
    both histogram kernels contract against. Comparisons must run in f32
    (Mosaic rejects bf16 cmpf vectors, like the f32-iota restriction
    below); bf16 mode therefore builds the one-hot feature-by-feature,
    casting each [B, blk] slice down immediately — one full-size f32
    one-hot next to its bf16 copy would blow the 16MB scoped-VMEM stack.
    Mosaic's tpu.iota only produces integer vectors; build int32 and cast
    (f32 iota verified fine in interpret mode but fails TPU lowering)."""
    mxu_dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    if variant == "concat" or use_bf16:
        bins2 = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0) \
            .astype(jnp.float32)                            # [B, 1]
        return jnp.concatenate(
            [(xf[f:f + 1, :] == bins2).astype(mxu_dtype)    # [B, blk]
             for f in range(F)], axis=0)                    # [F*B, blk]
    bins = jax.lax.broadcasted_iota(jnp.int32, (1, B, 1), 1) \
        .astype(jnp.float32)
    oh = (xf[:, None, :] == bins).astype(jnp.float32)       # [F, B, blk]
    return oh.reshape(F * B, blk)


def _fold_payload(pay_ref, k, C, mxu_dtype, derive_count):
    """Fold k's payload rows, with the unit-count channel derived in VMEM
    when derive_count: count = (h > 0) on the LAST input channel (the
    hessian) — exactly grow_tree's count_unit, computed on the VPU
    instead of streamed as its own HBM plane."""
    pay = pay_ref[k * C:(k + 1) * C, :]                     # [C, blk] f32
    if derive_count:
        cnt = (pay[C - 1:C, :] > 0.0).astype(jnp.float32)
        pay = jnp.concatenate([pay, cnt], axis=0)           # [C+1, blk]
    return pay.astype(mxu_dtype)


def _kernel(xb_ref, pay_ref, slot_ref, out_ref, *, F, B, C, n_slots,
            n_folds, variant, use_bf16=False, derive_count=False):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    blk = xb_ref.shape[1]
    mxu_dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    xf = xb_ref[:].astype(jnp.float32)                      # [F, blk]
    oh = _feature_onehot(xf, F=F, B=B, blk=blk, variant=variant,
                         use_bf16=use_bf16)

    # fold-fused: each fold contributes its own slot one-hot x payload
    # rows to ONE contraction, so the (feature, bin) one-hot above — the
    # dominant VPU cost — and the Xb traffic are built once for all folds,
    # and the matmul M dim grows n_folds x (the single-fold M of S*C rows
    # is far below the 128-row MXU tile; see BENCH_NOTES round-4 session 2)
    Co = C + (1 if derive_count else 0)
    slots = jax.lax.broadcasted_iota(jnp.int32, (n_slots, blk), 0) \
        .astype(jnp.float32)
    qs = []
    for k in range(n_folds):
        slot = slot_ref[k:k + 1, :]                         # [1, blk]
        slot_oh = (slots == slot).astype(mxu_dtype)         # [n_slots, blk]
        pay = _fold_payload(pay_ref, k, C, mxu_dtype, derive_count)
        qs.append((slot_oh[:, None, :] * pay[None, :, :])
                  .reshape(n_slots * Co, blk))
    q = qs[0] if n_folds == 1 else jnp.concatenate(qs, axis=0)

    out_ref[:] += jax.lax.dot_general(
        q, oh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [Fo*S*Co, F*B]


def hist_pallas(Xb_t: jax.Array, pay_t: jax.Array, slot_t: jax.Array,
                *, n_slots: int, n_bins: int,
                interpret: bool = False,
                allow_bf16: bool = False,
                derive_count: bool = False) -> jax.Array:
    """Gradient histograms [n_folds * n_slots * Co, F * n_bins] (f32).

    Xb_t [F, N] int bins; pay_t [n_folds * C, N] f32 payload channels;
    slot_t [n_folds, N] f32 slot ids (n_slots drops the row). The fold
    axis batches independent slot assignments over the SAME binned matrix
    (CV fold masks AND fused config lanes in the tree sweep): one
    (feature, bin) one-hot serves every lane and the contraction M dim
    scales with n_folds. n_folds is slot_t's leading dim (C must divide
    pay_t's). Ragged N pads internally with dropped-slot rows; the block
    size adapts to the one-hot width so VMEM tiles stay bounded (see
    block_rows), and the sequential grid double-buffers the HBM->VMEM
    tile streams (pallas pipelines the next block's DMA under the current
    block's contraction).

    derive_count: append a unit-count channel computed IN VMEM as
    (last-channel > 0) — grow_tree's count_unit = (H > 0) without its own
    HBM plane (Co = C + 1; counts stay integer-exact, bf16 included).

    allow_bf16: opt-in to bf16 contraction INPUTS (f32 accumulation) when
    the module flag agrees (TMOG_HIST_BF16, default on) — the tree-fit
    consumers take it (one-hots and unit counts are exact in bf16; the
    g/h payloads quantize ~0.4% relative, within the tree-quality gates);
    the rank-metric consumer keeps full-precision weights. The resolved
    dtype choice is a jit-cache key of the inner impl (NOT a trace-time
    global read), so set_hist_bf16 toggles cannot serve stale-dtype
    executables even through wrapped/monkeypatched references.
    """
    return _hist_pallas_jit(Xb_t, pay_t, slot_t, n_slots=n_slots,
                            n_bins=n_bins, interpret=interpret,
                            use_bf16=allow_bf16 and _HIST_BF16,
                            derive_count=derive_count)


def _check_variant():
    if _VARIANT not in _VARIANTS:  # env typo must not silently re-run
        raise ValueError(          # the default variant as false evidence
            f"TMOG_PALLAS_HIST_VARIANT={_VARIANT!r}; expected one of "
            f"{_VARIANTS}")


@functools.partial(jax.jit,
                   static_argnames=("n_slots", "n_bins", "interpret",
                                    "use_bf16", "derive_count"))
def _hist_pallas_jit(Xb_t, pay_t, slot_t, *, n_slots, n_bins,
                     interpret, use_bf16, derive_count=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, N = Xb_t.shape
    n_folds = slot_t.shape[0]
    if pay_t.shape[0] % n_folds:
        raise ValueError(f"pay_t channels {pay_t.shape[0]} not a multiple "
                         f"of slot_t folds {n_folds}")
    C = pay_t.shape[0] // n_folds
    Co = C + (1 if derive_count else 0)
    B = n_bins
    blk = block_rows(F * B)
    pad = (-N) % blk
    if pad:
        Xb_t = jnp.pad(Xb_t, ((0, 0), (0, pad)))
        pay_t = jnp.pad(pay_t, ((0, 0), (0, pad)))
        slot_t = jnp.pad(slot_t, ((0, 0), (0, pad)),
                         constant_values=float(n_slots))  # dropped
        N += pad

    _check_variant()
    kernel = functools.partial(_kernel, F=F, B=B, C=C, n_slots=n_slots,
                               n_folds=n_folds, variant=_VARIANT,
                               use_bf16=use_bf16, derive_count=derive_count)
    return pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_folds * C, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_folds, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (n_folds * n_slots * Co, F * B), lambda i: (0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (n_folds * n_slots * Co, F * B), jnp.float32),
        interpret=interpret,
    )(Xb_t, pay_t, slot_t)


def _hist_segment_jnp(Xb_t, pay_t, slot_t, *, n_slots, n_bins,
                      derive_count=False):
    """Pure-jnp twin of hist_pallas (CPU/GPU fallback): one fused
    segment-sum per fold lane over (slot, feature, bin) cells, same
    [n_folds * n_slots * Co, F * B] output layout. Out-of-range slot ids
    (>= n_slots — padding / sibling-subtraction drops) land in a spill
    segment that is sliced away."""
    F, N = Xb_t.shape
    n_folds = slot_t.shape[0]
    C = pay_t.shape[0] // n_folds
    B = n_bins
    fb = (jnp.arange(F, dtype=jnp.int32)[:, None] * B
          + Xb_t.astype(jnp.int32))                          # [F, N]
    seg = n_slots * F * B

    def one_fold(slot_k, pay_k):
        if derive_count:
            cnt = (pay_k[C - 1:C, :] > 0.0).astype(jnp.float32)
            pay_k = jnp.concatenate([pay_k, cnt], axis=0)
        Co = pay_k.shape[0]
        slot_i = slot_k.astype(jnp.int32)                    # [N]
        ids = jnp.where(slot_i[None, :] >= n_slots, seg,
                        slot_i[None, :] * (F * B) + fb)      # [F, N]
        data = jnp.broadcast_to(pay_k[:, None, :], (Co, F, N))
        hist = jax.ops.segment_sum(
            data.reshape(Co, F * N).T, ids.reshape(-1),
            num_segments=seg + 1)[:seg]                      # [seg, Co]
        return hist.reshape(n_slots, F, B, Co) \
            .transpose(0, 3, 1, 2).reshape(n_slots * Co, F * B)

    pay_f = pay_t.reshape(n_folds, C, N)
    out = jax.vmap(one_fold)(slot_t, pay_f)                  # [Fo, S*Co, FB]
    return out.reshape(-1, F * B)


def hist_folds(Xb_t: jax.Array, pay_t: jax.Array, slot_t: jax.Array, *,
               n_slots: int, n_bins: int, interpret: bool = False,
               allow_bf16: bool = False,
               derive_count: bool = False) -> jax.Array:
    """Batched multi-(fold x lane) histogram dispatcher: the VMEM pallas
    kernel on a live TPU (or in interpret mode for tests), the pure-jnp
    segment-sum fallback everywhere else — same signature and output
    layout as hist_pallas, so CPU CI exercises the exact call shape the
    TPU sweep runs."""
    if interpret or available():
        return hist_pallas(Xb_t, pay_t, slot_t, n_slots=n_slots,
                           n_bins=n_bins, interpret=interpret,
                           allow_bf16=allow_bf16,
                           derive_count=derive_count)
    return _hist_segment_jnp(Xb_t, pay_t, slot_t, n_slots=n_slots,
                             n_bins=n_bins, derive_count=derive_count)


# -- level routing ----------------------------------------------------------
# Training-time routing (rel' = 2*rel + go_right) is one read of the binned
# matrix per level, but the XLA gather-free form (trees._onehot_route_step)
# materializes [chunk, F] f32 selection products in HBM — 48ms/level at the
# 10M-row config vs ~1ms of Xb traffic. Here the one-hots and products live
# only in VMEM, and (like the histograms) a fold axis shares the Xb read
# across every CV fold's tree.

_ROUTE_BLK = 4096


def _pad_minor(a: jax.Array, mult: int = 128) -> jax.Array:
    """Pad the minor axis up to a Mosaic-friendly multiple; padded slots
    are inert wherever a one-hot over REAL ids selects columns."""
    pad = (-a.shape[-1]) % mult
    if pad:
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, pad)])
    return a


def _route_kernel(xb_ref, node_ref, tbl_ref, out_ref, *, F, n_pad,
                  n_folds):
    blk = xb_ref.shape[1]
    xf = xb_ref[:].astype(jnp.float32)                      # [F, blk]
    fi = jax.lax.broadcasted_iota(jnp.int32, (F, blk), 0) \
        .astype(jnp.float32)
    ni = jax.lax.broadcasted_iota(jnp.int32, (n_pad, blk), 0) \
        .astype(jnp.float32)
    rows = []
    for k in range(n_folds):
        node = node_ref[k:k + 1, :]                         # [1, blk]
        noh = (ni == node).astype(jnp.float32)              # [n_pad, blk]
        tbl = tbl_ref[3 * k:3 * k + 3, :]                   # [3, n_pad]
        ftm = jax.lax.dot_general(                          # [3, blk]
            tbl, noh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        mask = (fi == ftm[0:1, :]).astype(jnp.float32)      # [F, blk]
        xsel = jnp.sum(xf * mask, axis=0, keepdims=True)    # [1, blk]
        right = jnp.logical_or(
            xsel > ftm[1:2, :],
            jnp.logical_and(xsel == 0.0, ftm[2:3, :] > 0.5))
        rows.append(2.0 * node + right.astype(jnp.float32))
    out_ref[:] = rows[0] if n_folds == 1 else \
        jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("n_nodes", "interpret"))
def route_pallas(Xb_t: jax.Array, node_t: jax.Array, f_lvl: jax.Array,
                 t_lvl: jax.Array, m_lvl: jax.Array, *, n_nodes: int,
                 interpret: bool = False) -> jax.Array:
    """One level of tree routing for every fold in one Xb pass.

    Xb_t [F, N] int bins; node_t [n_folds, N] f32 in-level node ids;
    f_lvl/t_lvl/m_lvl [n_folds, n_nodes] split tables. Returns the next
    level's ids [n_folds, N] f32 (2*node + right; right uses the learned
    missing direction for bin 0 — same decision as trees._onehot_route_step
    and the serving traversals). Out-of-range node ids (e.g. row padding)
    select no table entry and route as node 0's split of feature 0 — the
    caller slices padded rows away.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, N = Xb_t.shape
    n_orig = N
    Fo = node_t.shape[0]
    tbl = jnp.stack([f_lvl.astype(jnp.float32),
                     t_lvl.astype(jnp.float32),
                     m_lvl.astype(jnp.float32)], axis=1)    # [Fo, 3, n]
    tbl = _pad_minor(tbl.reshape(3 * Fo, n_nodes))          # [3Fo, n_pad]
    n_pad = tbl.shape[1]
    blk = _ROUTE_BLK
    pad = (-N) % blk
    if pad:
        Xb_t = jnp.pad(Xb_t, ((0, 0), (0, pad)))
        node_t = jnp.pad(node_t, ((0, 0), (0, pad)),
                         constant_values=float(n_pad))      # inert
        N += pad
    kernel = functools.partial(_route_kernel, F=F, n_pad=n_pad, n_folds=Fo)
    out = pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3 * Fo, n_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((Fo, blk), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fo, N), jnp.float32),
        interpret=interpret,
    )(Xb_t, node_t, tbl)
    return out[:, :n_orig]


def _route_level_jnp(Xb_t, node_t, f_lvl, t_lvl, m_lvl):
    """Gather-form twin of route_pallas's decision (CPU fallback). Node
    ids must be in-range [0, n_nodes) — true for every caller (routing
    always starts at node 0 and doubles)."""
    node_i = node_t.astype(jnp.int32)                        # [Fo, N]
    f = jnp.take_along_axis(f_lvl, node_i, axis=1)           # [Fo, N]
    t = jnp.take_along_axis(t_lvl, node_i, axis=1)
    mdir = jnp.take_along_axis(m_lvl, node_i, axis=1)
    xsel = jnp.take_along_axis(Xb_t.astype(jnp.int32), f, axis=0)
    right = (xsel > t) | ((xsel == 0) & (mdir > 0))
    return node_t * 2.0 + right.astype(jnp.float32)


def route(Xb_t: jax.Array, node_t: jax.Array, f_lvl: jax.Array,
          t_lvl: jax.Array, m_lvl: jax.Array, *, n_nodes: int,
          interpret: bool = False) -> jax.Array:
    """Level-routing dispatcher: route_pallas on a live TPU / in
    interpret mode, the gather form on CPU (identical decisions — the
    pallas selected-bin is a single f32-exact one-hot term)."""
    if interpret or available():
        return route_pallas(Xb_t, node_t, f_lvl, t_lvl, m_lvl,
                            n_nodes=n_nodes, interpret=interpret)
    return _route_level_jnp(Xb_t, node_t, f_lvl, t_lvl, m_lvl)


# -- fused route + histogram ------------------------------------------------
# One pass of the binned matrix per level instead of two: the level-d
# split tables route every row IN VMEM and the surviving (left-child)
# slot ids feed the level-(d+1) histogram contraction in the same grid
# step — the route pass's separate HBM read of Xb disappears. Works
# because new_node = 2*node + right is even exactly when the row goes
# left, and sibling subtraction histograms LEFT children only: the
# level-(d+1) slot id of a left row is its OLD node id, known the moment
# `right` is computed. Fold lanes (CV folds x fused config lanes) share
# the Xb read and the (feature, bin) one-hot exactly as in _kernel.


def _route_hist_kernel(xb_ref, pay_ref, node_ref, tbl_ref, hist_ref,
                       node_out_ref, *, F: int, B: int, C: int, n_nodes: int,
                       n_pad: int, n_folds: int,
                       variant, use_bf16=False, derive_count=False):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    blk = xb_ref.shape[1]
    mxu_dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    xf = xb_ref[:].astype(jnp.float32)                      # [F, blk]
    oh = _feature_onehot(xf, F=F, B=B, blk=blk, variant=variant,
                         use_bf16=use_bf16)
    fi = jax.lax.broadcasted_iota(jnp.int32, (F, blk), 0) \
        .astype(jnp.float32)
    ni = jax.lax.broadcasted_iota(jnp.int32, (n_pad, blk), 0) \
        .astype(jnp.float32)
    slots = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, blk), 0) \
        .astype(jnp.float32)
    Co = C + (1 if derive_count else 0)
    rows, qs = [], []
    for k in range(n_folds):
        node = node_ref[k:k + 1, :]                         # [1, blk]
        noh = (ni == node).astype(jnp.float32)              # [n_pad, blk]
        tbl = tbl_ref[3 * k:3 * k + 3, :]                   # [3, n_pad]
        ftm = jax.lax.dot_general(                          # [3, blk]
            tbl, noh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        mask = (fi == ftm[0:1, :]).astype(jnp.float32)      # [F, blk]
        xsel = jnp.sum(xf * mask, axis=0, keepdims=True)    # [1, blk]
        rightf = jnp.logical_or(
            xsel > ftm[1:2, :],
            jnp.logical_and(xsel == 0.0, ftm[2:3, :] > 0.5)
        ).astype(jnp.float32)                               # [1, blk]
        rows.append(2.0 * node + rightf)
        # next level's LEFT-child slot id = old node for left rows; right
        # rows shift past the iota range (node + n_nodes >= n_nodes) —
        # the same dropped-slot encoding hist_pallas uses for padding
        slot_oh = (slots == node + float(n_nodes) * rightf) \
            .astype(mxu_dtype)                              # [n_nodes, blk]
        pay = _fold_payload(pay_ref, k, C, mxu_dtype, derive_count)
        qs.append((slot_oh[:, None, :] * pay[None, :, :])
                  .reshape(n_nodes * Co, blk))
    q = qs[0] if n_folds == 1 else jnp.concatenate(qs, axis=0)
    hist_ref[:] += jax.lax.dot_general(
        q, oh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [Fo*S*Co, F*B]
    node_out_ref[:] = rows[0] if n_folds == 1 else \
        jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "interpret",
                                    "use_bf16", "derive_count"))
def _route_hist_pallas_jit(Xb_t, pay_t, node_t, f_lvl, t_lvl, m_lvl, *,
                           n_nodes, n_bins, interpret, use_bf16,
                           derive_count=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, N = Xb_t.shape
    n_orig = N
    Fo = node_t.shape[0]
    if pay_t.shape[0] % Fo:
        raise ValueError(f"pay_t channels {pay_t.shape[0]} not a multiple "
                         f"of node_t folds {Fo}")
    C = pay_t.shape[0] // Fo
    Co = C + (1 if derive_count else 0)
    B = n_bins
    tbl = jnp.stack([f_lvl.astype(jnp.float32),
                     t_lvl.astype(jnp.float32),
                     m_lvl.astype(jnp.float32)], axis=1)    # [Fo, 3, n]
    tbl = _pad_minor(tbl.reshape(3 * Fo, n_nodes))          # [3Fo, n_pad]
    n_pad = tbl.shape[1]
    blk = block_rows(F * B)
    pad = (-N) % blk
    if pad:
        Xb_t = jnp.pad(Xb_t, ((0, 0), (0, pad)))
        pay_t = jnp.pad(pay_t, ((0, 0), (0, pad)))
        # padded rows carry node id n_pad: they select no table entry
        # (route as feature-0/thresh-0, then are sliced away) and can
        # never match a histogram slot (payload is zero anyway)
        node_t = jnp.pad(node_t, ((0, 0), (0, pad)),
                         constant_values=float(n_pad))
        N += pad

    _check_variant()
    kernel = functools.partial(_route_hist_kernel, F=F, B=B, C=C,
                               n_nodes=n_nodes, n_pad=n_pad, n_folds=Fo,
                               variant=_VARIANT, use_bf16=use_bf16,
                               derive_count=derive_count)
    hist, node_out = pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo * C, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3 * Fo, n_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((Fo * n_nodes * Co, F * B), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Fo * n_nodes * Co, F * B), jnp.float32),
            jax.ShapeDtypeStruct((Fo, N), jnp.float32),
        ],
        interpret=interpret,
    )(Xb_t, pay_t, node_t, tbl)
    return hist, node_out[:, :n_orig]


def route_hist(Xb_t: jax.Array, pay_t: jax.Array, node_t: jax.Array,
               f_lvl: jax.Array, t_lvl: jax.Array, m_lvl: jax.Array, *,
               n_nodes: int, n_bins: int, interpret: bool = False,
               allow_bf16: bool = False, derive_count: bool = False):
    """Route one level AND histogram the next level's left children in a
    single pass over the binned matrix, for every (fold x config) lane.

    Xb_t [F, N] int bins; pay_t [Fo * C, N] f32 payload channels (g/h per
    lane, fold-major; derive_count appends the in-VMEM unit-count
    channel); node_t [Fo, N] f32 in-level node ids; f_lvl/t_lvl/m_lvl
    [Fo, n_nodes] the level's split tables. Returns (hist, new_node):
    hist [Fo * n_nodes * Co, F * n_bins] — the level-(d+1) LEFT-child
    histograms (n_slots = this level's n_nodes, sibling-subtraction
    layout) — and new_node [Fo, N] = 2*node + right, bitwise what
    route_pallas returns. On CPU the jnp fallback chains the gather-form
    route with the segment-sum histogram (identical decisions; histogram
    equal up to f32 summation order).
    """
    if interpret or available():
        return _route_hist_pallas_jit(
            Xb_t, pay_t, node_t, f_lvl, t_lvl, m_lvl, n_nodes=n_nodes,
            n_bins=n_bins, interpret=interpret,
            use_bf16=allow_bf16 and _HIST_BF16,
            derive_count=derive_count)
    new_node = _route_level_jnp(Xb_t, node_t, f_lvl, t_lvl, m_lvl)
    right = new_node - 2.0 * node_t                          # 0/1
    slots = node_t + float(n_nodes) * right                  # left keeps id
    hist = _hist_segment_jnp(Xb_t, pay_t, slots, n_slots=n_nodes,
                             n_bins=n_bins, derive_count=derive_count)
    return hist, new_node


def _lookup_kernel(tbl_ref, idx_ref, out_ref, *, m_pad, n_folds):
    blk = idx_ref.shape[1]
    mi = jax.lax.broadcasted_iota(jnp.int32, (m_pad, blk), 0) \
        .astype(jnp.float32)
    rows = []
    for k in range(n_folds):
        idx = idx_ref[k:k + 1, :]                           # [1, blk]
        noh = (mi == idx).astype(jnp.float32)               # [m_pad, blk]
        rows.append(jax.lax.dot_general(
            tbl_ref[k:k + 1, :], noh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))            # [1, blk]
    out_ref[:] = rows[0] if n_folds == 1 else \
        jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def table_lookup_pallas(tbl: jax.Array, idx_t: jax.Array, *,
                        interpret: bool = False) -> jax.Array:
    """Per-fold small-table lookup out[k, i] = tbl[k, idx[k, i]].

    tbl [n_folds, M] f32 (e.g. leaf payloads); idx_t [n_folds, N] f32 ids.
    Out-of-range ids (>= M, e.g. row padding) return 0. TPU gathers from
    tiny tables by huge index vectors serialize; the one-hot contraction
    here stays on the MXU/VPU and reads idx_t exactly once.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Fo, M = tbl.shape
    N = idx_t.shape[1]
    n_orig = N
    tblp = _pad_minor(tbl)
    m_pad = tblp.shape[1]
    blk = _ROUTE_BLK
    pad = (-N) % blk
    if pad:
        idx_t = jnp.pad(idx_t, ((0, 0), (0, pad)),
                        constant_values=float(m_pad))       # -> 0
        N += pad
    kernel = functools.partial(_lookup_kernel, m_pad=m_pad, n_folds=Fo)
    return pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((Fo, m_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((Fo, blk), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fo, N), jnp.float32),
        interpret=interpret,
    )(tblp, idx_t)[:, :n_orig]


def table_lookup(tbl: jax.Array, idx_t: jax.Array, *,
                 interpret: bool = False) -> jax.Array:
    """Per-fold table-lookup dispatcher: the one-hot contraction kernel
    on a live TPU / in interpret mode, a plain gather on CPU (same
    out-of-range -> 0 contract)."""
    if interpret or available():
        return table_lookup_pallas(tbl, idx_t, interpret=interpret)
    M = tbl.shape[1]
    idx = idx_t.astype(jnp.int32)
    vals = jnp.take_along_axis(tbl, jnp.clip(idx, 0, M - 1), axis=1)
    return jnp.where((idx >= 0) & (idx < M), vals, 0.0)
