"""Pallas TPU kernel for tree gradient histograms.

The XLA chunked histogram path (ops/trees._histograms_matmul) materializes
its [chunk, F*B] one-hot block in HBM every scan step — ~1GB of write+read
traffic per 64K-row chunk, ~150GB per level at the 10M-row BASELINE
config, which dominates the tree sweep's wall clock. This kernel builds
the one-hot tiles directly in VMEM (they never exist in HBM) and leaves
one MXU contraction per row block:

    out[slot*C + c, f*B + b] += sum_i  1[slot_i = slot] * P[c, i]
                                     * 1[Xb[f, i] = b]

- inputs arrive TRANSPOSED ([F, N] / [C, N] / [1, N]) so the huge axis is
  minor: TPU tiling pads the minor axis to 128 lanes, and feeding [N, C]
  with C=4 would inflate HBM 32x (the round-2 fold-vmap OOM was exactly
  this padding on [5, 10M] arrays);
- the (feature, bin) one-hot is a VPU broadcast-compare reshaped
  [F, B, blk] -> [F*B, blk] (leading-dim merge, layout-free);
- slot one-hots drop out-of-range ids (slot = n_slots encodes "row
  contributes nothing" — how histogram subtraction or padded rows enter);
- grid steps run sequentially on the core, accumulating into the same
  VMEM output block (zeroed at step 0).

Reference workload: XGBoost's hist-method gradient histograms, the C++
path behind the reference's OpXGBoost* wrappers (SURVEY §2.9).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

_BLK = 4096


def _is_v5_plus() -> bool:
    """Device-generation probe shared by every VMEM budget: v5e+ carries
    128MB of VMEM per core, older generations 16-32MB. False on a
    backend that cannot report a device (budgets then stay at the
    conservative older-generation values)."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return False
    return any(s in kind for s in ("v5", "v6", "v7"))


def _tile_budget() -> int:
    """VMEM budget for the [cols, blk] f32 one-hot tile, by device
    generation. On v5e+ a 16MB tile (plus the accumulator and payload
    tiles, all much smaller) clears the compiler's headroom while
    cutting the grid-step count 4x vs the old 4MB budget — at 10M rows
    the per-step loop overhead and the skinny [S*C, 256] matmuls were
    the tree sweep's real wall (8.5s warm fit, BENCH_NOTES r3). Older
    generations keep the conservative 4MB budget known to compile
    there."""
    return (24 << 20) if _is_v5_plus() else (4 << 20)


def block_rows(n_onehot_cols: int) -> int:
    """Rows per grid step, sized so the [cols, blk] f32 one-hot tile stays
    within the device's tile budget (v5e tree histograms: F*B ~ 2048 ->
    2048 rows; 4096-bin rank metrics -> 1024)."""
    blk = _BLK
    budget = _tile_budget()
    while blk > 128 and n_onehot_cols * blk * 4 > budget:
        blk //= 2
    return blk


def _vmem_limit() -> int:
    """Usable VMEM per core, with compiler headroom held back (100 of
    128MB on v5e+, 12 of 16MB older). The limit gates kernel forms
    whose residents scale with problem shape (the fused fold
    histogram's output block) — exceeding it is a Mosaic compile
    error, not a slowdown."""
    return (100 << 20) if _is_v5_plus() else (12 << 20)


def fused_hist_fits(n_feat: int, n_bins: int, n_folds: int, depth: int,
                    channels: int = 3) -> bool:
    """Will the fold-fused histogram kernel's VMEM residents fit?

    The fused output block [n_folds * n_slots * channels, F * B] f32 is
    fully VMEM-resident and scales with every one of those factors;
    block_rows only budgets the one-hot tile, so XGB-shaped configs
    (256 bins, depth 6, a few hundred features, 3-5 folds) would sail
    past a Mosaic compile failure with no library-level fallback. Worst
    level is the deepest histogram pass: sibling subtraction halves the
    slot count, so n_slots = 2^(depth-2) for depth >= 2. Residents:
    output block + the [F*B, blk] f32 one-hot tile (+ a bf16 copy when
    the bf16 input mode is on) + the f32 Xb/payload/slot tiles.
    Callers (models/trees._fused_route_ok) fall back to the sequential
    per-fold path when this returns False.
    """
    cols = n_feat * n_bins
    n_slots = 1 << max(depth - 2, 0)
    out_b = n_folds * n_slots * channels * cols * 4
    blk = block_rows(cols)
    onehot_b = cols * blk * 4
    if _HIST_BF16:
        onehot_b += cols * blk * 2
    minor_b = (n_feat + n_folds * channels + n_folds) * blk * 8
    return out_b + onehot_b + minor_b <= _vmem_limit()


# THE pallas kill switch — single flag for every consumer (tree
# histograms, lane-batched metrics). Env default: TMOG_NO_PALLAS truthy
# (not "0"/"false"/"") disables; set_enabled() is the runtime toggle.
_enabled = os.environ.get("TMOG_NO_PALLAS", "").strip().lower() \
    in ("", "0", "false")

# jitted functions whose compiled executables bake the pallas choice in;
# cleared on toggle so a cached program cannot pin the previous choice
_cache_consumers = []


def register_cache_consumer(fn) -> None:
    """Register a jitted function that traces through available()."""
    _cache_consumers.append(fn)


def enabled() -> bool:
    return _enabled


def set_enabled(enabled: bool) -> None:
    global _enabled
    if _enabled == bool(enabled):
        return
    _enabled = bool(enabled)
    for fn in _cache_consumers:
        fn.clear_cache()


def available() -> bool:
    """Pallas path usable? (enabled + TPU backend + pallas importable.)"""
    if not _enabled or jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except Exception:
        return False
    return True


# Kernel variant selector (first-contact A/B lever): the "reshape" form
# builds the (feature, bin) one-hot as a 3D broadcast-compare reshaped
# [F, B, blk] -> [F*B, blk] (a leading-dim merge); "concat" builds it as F
# independent [B, blk] 2D compares concatenated along the leading dim — no
# 3D intermediate and no reshape at all, a genuinely different Mosaic
# lowering path in case the reshape form is what stalled the round-3
# 10M-row first contact (note jnp.repeat would NOT qualify: it lowers to
# the same broadcast+reshape). Runtime-switchable so
# tools/tpu_staged_probe.py can try both. NOTE: the bf16 input mode
# always builds its one-hot with the per-feature concat form (a full-size
# f32 one-hot next to its bf16 copy would overflow the scoped-VMEM stack,
# and Mosaic rejects bf16 compares), so this A/B lever only
# distinguishes lowerings on the f32 path — which is exactly what the
# probe's pallas_direct stage runs (it does not pass allow_bf16).
_VARIANTS = ("reshape", "concat")
_VARIANT = os.environ.get("TMOG_PALLAS_HIST_VARIANT", "reshape").strip() \
    or "reshape"

# Histogram contraction input dtype. bf16 doubles the MXU ceiling (the
# fused fold fit runs near the f32 matmul peak); the one-hot operand is
# EXACT in bf16 (0/1) and counts stay integer-exact (1.0 payloads, f32
# accumulation) — only the g/h payload channels quantize (~0.4%
# relative). Flip with TMOG_HIST_BF16=0 to fall back to full-f32 inputs.
_HIST_BF16 = os.environ.get("TMOG_HIST_BF16", "1").strip().lower() \
    not in ("0", "false", "off")


def set_hist_bf16(enabled: bool) -> None:
    """Toggle bf16 histogram inputs. hist_pallas itself resolves the flag
    OUTSIDE its jit (it becomes the use_bf16 cache key), so only the
    registered consumer jits — which bake the flag into their traces —
    need their caches cleared."""
    global _HIST_BF16
    if _HIST_BF16 == bool(enabled):
        return
    _HIST_BF16 = bool(enabled)
    for fn in _cache_consumers:
        fn.clear_cache()


def set_variant(name: str) -> None:
    global _VARIANT
    if name not in _VARIANTS:
        raise ValueError(f"unknown pallas hist variant: {name!r}")
    if name != _VARIANT:
        _VARIANT = name
        for fn in _cache_consumers:
            fn.clear_cache()
        _hist_pallas_jit.clear_cache()


def _kernel(xb_ref, pay_ref, slot_ref, out_ref, *, F, B, C, n_slots,
            n_folds, variant, use_bf16=False):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    blk = xb_ref.shape[1]
    mxu_dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    # comparisons must run in f32 (Mosaic rejects bf16 cmpf vectors, like
    # the f32-iota restriction below); bf16 mode therefore builds the
    # one-hot feature-by-feature, casting each [B, blk] slice down
    # immediately — one full-size f32 one-hot next to its bf16 copy would
    # blow the 16MB scoped-VMEM stack
    xf = xb_ref[:].astype(jnp.float32)                      # [F, blk]
    # Mosaic's tpu.iota only produces integer vectors; build int32 and
    # cast (f32 iota verified fine in interpret mode but fails TPU
    # lowering)
    if variant == "concat" or use_bf16:
        bins2 = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0) \
            .astype(jnp.float32)                            # [B, 1]
        oh = jnp.concatenate(
            [(xf[f:f + 1, :] == bins2).astype(mxu_dtype)    # [B, blk]
             for f in range(F)], axis=0)                    # [F*B, blk]
    else:
        bins = jax.lax.broadcasted_iota(jnp.int32, (1, B, 1), 1) \
            .astype(jnp.float32)
        oh = (xf[:, None, :] == bins).astype(jnp.float32)   # [F, B, blk]
        oh = oh.reshape(F * B, blk)

    # fold-fused: each fold contributes its own slot one-hot x payload
    # rows to ONE contraction, so the (feature, bin) one-hot above — the
    # dominant VPU cost — and the Xb traffic are built once for all folds,
    # and the matmul M dim grows n_folds x (the single-fold M of S*C rows
    # is far below the 128-row MXU tile; see BENCH_NOTES round-4 session 2)
    slots = jax.lax.broadcasted_iota(jnp.int32, (n_slots, blk), 0) \
        .astype(jnp.float32)
    qs = []
    for k in range(n_folds):
        slot = slot_ref[k:k + 1, :]                         # [1, blk]
        slot_oh = (slots == slot).astype(mxu_dtype)         # [n_slots, blk]
        pay = pay_ref[k * C:(k + 1) * C, :].astype(mxu_dtype)
        qs.append((slot_oh[:, None, :] * pay[None, :, :])
                  .reshape(n_slots * C, blk))
    q = qs[0] if n_folds == 1 else jnp.concatenate(qs, axis=0)

    out_ref[:] += jax.lax.dot_general(
        q, oh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [Fo*S*C, F*B]


def hist_pallas(Xb_t: jax.Array, pay_t: jax.Array, slot_t: jax.Array,
                *, n_slots: int, n_bins: int,
                interpret: bool = False,
                allow_bf16: bool = False) -> jax.Array:
    """Gradient histograms [n_folds * n_slots * C, F * n_bins] (f32).

    Xb_t [F, N] int bins; pay_t [n_folds * C, N] f32 payload channels;
    slot_t [n_folds, N] f32 slot ids (n_slots drops the row). The fold
    axis batches independent slot assignments over the SAME binned matrix
    (CV fold masks in the tree sweep): one (feature, bin) one-hot serves
    every fold and the contraction M dim scales with n_folds. n_folds is
    slot_t's leading dim (C must divide pay_t's). Ragged N pads internally
    with dropped-slot rows; the block size adapts to the one-hot width so
    VMEM tiles stay bounded (see block_rows).

    allow_bf16: opt-in to bf16 contraction INPUTS (f32 accumulation) when
    the module flag agrees (TMOG_HIST_BF16, default on) — the tree-fit
    consumers take it (one-hots and unit counts are exact in bf16; the
    g/h payloads quantize ~0.4% relative, within the tree-quality gates);
    the rank-metric consumer keeps full-precision weights. The resolved
    dtype choice is a jit-cache key of the inner impl (NOT a trace-time
    global read), so set_hist_bf16 toggles cannot serve stale-dtype
    executables even through wrapped/monkeypatched references.
    """
    return _hist_pallas_jit(Xb_t, pay_t, slot_t, n_slots=n_slots,
                            n_bins=n_bins, interpret=interpret,
                            use_bf16=allow_bf16 and _HIST_BF16)


@functools.partial(jax.jit,
                   static_argnames=("n_slots", "n_bins", "interpret",
                                    "use_bf16"))
def _hist_pallas_jit(Xb_t, pay_t, slot_t, *, n_slots, n_bins,
                     interpret, use_bf16):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, N = Xb_t.shape
    n_folds = slot_t.shape[0]
    if pay_t.shape[0] % n_folds:
        raise ValueError(f"pay_t channels {pay_t.shape[0]} not a multiple "
                         f"of slot_t folds {n_folds}")
    C = pay_t.shape[0] // n_folds
    B = n_bins
    blk = block_rows(F * B)
    pad = (-N) % blk
    if pad:
        Xb_t = jnp.pad(Xb_t, ((0, 0), (0, pad)))
        pay_t = jnp.pad(pay_t, ((0, 0), (0, pad)))
        slot_t = jnp.pad(slot_t, ((0, 0), (0, pad)),
                         constant_values=float(n_slots))  # dropped
        N += pad

    if _VARIANT not in _VARIANTS:  # env typo must not silently re-run
        raise ValueError(          # the default variant as false evidence
            f"TMOG_PALLAS_HIST_VARIANT={_VARIANT!r}; expected one of "
            f"{_VARIANTS}")
    kernel = functools.partial(_kernel, F=F, B=B, C=C, n_slots=n_slots,
                               n_folds=n_folds, variant=_VARIANT,
                               use_bf16=use_bf16)
    return pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_folds * C, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_folds, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (n_folds * n_slots * C, F * B), lambda i: (0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (n_folds * n_slots * C, F * B), jnp.float32),
        interpret=interpret,
    )(Xb_t, pay_t, slot_t)


# -- level routing ----------------------------------------------------------
# Training-time routing (rel' = 2*rel + go_right) is one read of the binned
# matrix per level, but the XLA gather-free form (trees._onehot_route_step)
# materializes [chunk, F] f32 selection products in HBM — 48ms/level at the
# 10M-row config vs ~1ms of Xb traffic. Here the one-hots and products live
# only in VMEM, and (like the histograms) a fold axis shares the Xb read
# across every CV fold's tree.

_ROUTE_BLK = 4096


def _pad_minor(a: jax.Array, mult: int = 128) -> jax.Array:
    """Pad the minor axis up to a Mosaic-friendly multiple; padded slots
    are inert wherever a one-hot over REAL ids selects columns."""
    pad = (-a.shape[-1]) % mult
    if pad:
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, pad)])
    return a


def _route_kernel(xb_ref, node_ref, tbl_ref, out_ref, *, F, n_pad,
                  n_folds):
    blk = xb_ref.shape[1]
    xf = xb_ref[:].astype(jnp.float32)                      # [F, blk]
    fi = jax.lax.broadcasted_iota(jnp.int32, (F, blk), 0) \
        .astype(jnp.float32)
    ni = jax.lax.broadcasted_iota(jnp.int32, (n_pad, blk), 0) \
        .astype(jnp.float32)
    rows = []
    for k in range(n_folds):
        node = node_ref[k:k + 1, :]                         # [1, blk]
        noh = (ni == node).astype(jnp.float32)              # [n_pad, blk]
        tbl = tbl_ref[3 * k:3 * k + 3, :]                   # [3, n_pad]
        ftm = jax.lax.dot_general(                          # [3, blk]
            tbl, noh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        mask = (fi == ftm[0:1, :]).astype(jnp.float32)      # [F, blk]
        xsel = jnp.sum(xf * mask, axis=0, keepdims=True)    # [1, blk]
        right = jnp.logical_or(
            xsel > ftm[1:2, :],
            jnp.logical_and(xsel == 0.0, ftm[2:3, :] > 0.5))
        rows.append(2.0 * node + right.astype(jnp.float32))
    out_ref[:] = rows[0] if n_folds == 1 else \
        jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("n_nodes", "interpret"))
def route_pallas(Xb_t: jax.Array, node_t: jax.Array, f_lvl: jax.Array,
                 t_lvl: jax.Array, m_lvl: jax.Array, *, n_nodes: int,
                 interpret: bool = False) -> jax.Array:
    """One level of tree routing for every fold in one Xb pass.

    Xb_t [F, N] int bins; node_t [n_folds, N] f32 in-level node ids;
    f_lvl/t_lvl/m_lvl [n_folds, n_nodes] split tables. Returns the next
    level's ids [n_folds, N] f32 (2*node + right; right uses the learned
    missing direction for bin 0 — same decision as trees._onehot_route_step
    and the serving traversals). Out-of-range node ids (e.g. row padding)
    select no table entry and route as node 0's split of feature 0 — the
    caller slices padded rows away.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, N = Xb_t.shape
    n_orig = N
    Fo = node_t.shape[0]
    tbl = jnp.stack([f_lvl.astype(jnp.float32),
                     t_lvl.astype(jnp.float32),
                     m_lvl.astype(jnp.float32)], axis=1)    # [Fo, 3, n]
    tbl = _pad_minor(tbl.reshape(3 * Fo, n_nodes))          # [3Fo, n_pad]
    n_pad = tbl.shape[1]
    blk = _ROUTE_BLK
    pad = (-N) % blk
    if pad:
        Xb_t = jnp.pad(Xb_t, ((0, 0), (0, pad)))
        node_t = jnp.pad(node_t, ((0, 0), (0, pad)),
                         constant_values=float(n_pad))      # inert
        N += pad
    kernel = functools.partial(_route_kernel, F=F, n_pad=n_pad, n_folds=Fo)
    out = pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3 * Fo, n_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((Fo, blk), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fo, N), jnp.float32),
        interpret=interpret,
    )(Xb_t, node_t, tbl)
    return out[:, :n_orig]


def _lookup_kernel(tbl_ref, idx_ref, out_ref, *, m_pad, n_folds):
    blk = idx_ref.shape[1]
    mi = jax.lax.broadcasted_iota(jnp.int32, (m_pad, blk), 0) \
        .astype(jnp.float32)
    rows = []
    for k in range(n_folds):
        idx = idx_ref[k:k + 1, :]                           # [1, blk]
        noh = (mi == idx).astype(jnp.float32)               # [m_pad, blk]
        rows.append(jax.lax.dot_general(
            tbl_ref[k:k + 1, :], noh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))            # [1, blk]
    out_ref[:] = rows[0] if n_folds == 1 else \
        jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def table_lookup_pallas(tbl: jax.Array, idx_t: jax.Array, *,
                        interpret: bool = False) -> jax.Array:
    """Per-fold small-table lookup out[k, i] = tbl[k, idx[k, i]].

    tbl [n_folds, M] f32 (e.g. leaf payloads); idx_t [n_folds, N] f32 ids.
    Out-of-range ids (>= M, e.g. row padding) return 0. TPU gathers from
    tiny tables by huge index vectors serialize; the one-hot contraction
    here stays on the MXU/VPU and reads idx_t exactly once.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Fo, M = tbl.shape
    N = idx_t.shape[1]
    n_orig = N
    tblp = _pad_minor(tbl)
    m_pad = tblp.shape[1]
    blk = _ROUTE_BLK
    pad = (-N) % blk
    if pad:
        idx_t = jnp.pad(idx_t, ((0, 0), (0, pad)),
                        constant_values=float(m_pad))       # -> 0
        N += pad
    kernel = functools.partial(_lookup_kernel, m_pad=m_pad, n_folds=Fo)
    return pl.pallas_call(
        kernel,
        grid=(N // blk,),
        in_specs=[
            pl.BlockSpec((Fo, m_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Fo, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((Fo, blk), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fo, N), jnp.float32),
        interpret=interpret,
    )(tblp, idx_t)[:, :n_orig]
