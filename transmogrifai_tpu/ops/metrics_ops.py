"""Classification/regression metric kernels (pure jnp, mask-aware).

Reference: core/.../evaluators/ — OpBinaryClassificationEvaluator.scala:56
(Precision/Recall/F1/AuROC/AuPR/Error/TP-TN-FP-FN + threshold curves),
OpMultiClassificationEvaluator.scala:58, OpRegressionEvaluator.scala:61.

AuROC/AuPR are sort-based with exact tie handling (metrics evaluated only at
threshold boundaries), matching Spark MLlib's BinaryClassificationMetrics
semantics. All functions accept a weight vector so padded rows (device
sharding) and fold masks (CV) cost nothing.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

EPS = 1e-12


def _sorted_cum_counts(scores: jax.Array, labels: jax.Array,
                       w: Optional[jax.Array] = None):
    """Sort by score desc; cumulative weighted TP/FP; tie-boundary mask."""
    if w is None:
        w = jnp.ones_like(scores)
    order = jnp.argsort(-scores)
    s = scores[order]
    y = labels[order]
    ww = w[order]
    tps = jnp.cumsum(y * ww)
    fps = jnp.cumsum((1.0 - y) * ww)
    # boundary i is valid if score[i] != score[i+1] (last of a tie group)
    nxt = jnp.concatenate([s[1:], jnp.array([-jnp.inf], s.dtype)])
    boundary = (s != nxt)
    # zero-weight rows (padding) sort to a tie group; ensure they are inert:
    # their ww=0 contributes nothing to cumsums. They may create spurious
    # boundaries but with unchanged cumulative counts => zero-area segments.
    return tps, fps, boundary


@jax.jit
def au_roc(scores: jax.Array, labels: jax.Array,
           w: Optional[jax.Array] = None) -> jax.Array:
    """Area under ROC (trapezoid over tie-boundary points)."""
    tps, fps, boundary = _sorted_cum_counts(scores, labels, w)
    P = tps[-1]
    N = fps[-1]
    tpr = tps / jnp.maximum(P, EPS)
    fpr = fps / jnp.maximum(N, EPS)
    # prepend (0,0): integrate sum over boundary points of
    # (fpr_i - fpr_prev) * (tpr_i + tpr_prev)/2, walking only boundaries.
    # Implement with carry-forward of previous boundary values via scan.
    def step(carry, xy):
        pf, pt, acc = carry
        f, t, b = xy
        area = jnp.where(b, (f - pf) * (t + pt) * 0.5, 0.0)
        pf = jnp.where(b, f, pf)
        pt = jnp.where(b, t, pt)
        return (pf, pt, acc + area), None

    (pf, pt, acc), _ = jax.lax.scan(
        step, (jnp.array(0.0, tpr.dtype), jnp.array(0.0, tpr.dtype),
               jnp.array(0.0, tpr.dtype)),
        (fpr, tpr, boundary))
    return acc


@jax.jit
def au_pr(scores: jax.Array, labels: jax.Array,
          w: Optional[jax.Array] = None) -> jax.Array:
    """Area under precision-recall (step interpolation / average precision)."""
    tps, fps, boundary = _sorted_cum_counts(scores, labels, w)
    P = jnp.maximum(tps[-1], EPS)
    recall = tps / P
    precision = tps / jnp.maximum(tps + fps, EPS)

    def step(carry, xy):
        pr, acc = carry
        r, p, b = xy
        area = jnp.where(b, (r - pr) * p, 0.0)
        pr = jnp.where(b, r, pr)
        return (pr, acc + area), None

    (_, acc), _ = jax.lax.scan(
        step, (jnp.array(0.0, recall.dtype), jnp.array(0.0, recall.dtype)),
        (recall, precision, boundary))
    return acc


def _bin_idx(scores: jax.Array, n_bins: int) -> jax.Array:
    """Shared score->bucket rule for every binned-counts route (scores pass
    through a sigmoid — monotone, so ranking is unchanged whether the
    caller supplies margins or probabilities)."""
    p = jax.nn.sigmoid(scores.astype(jnp.float32))
    return jnp.clip((p * n_bins).astype(jnp.int32), 0, n_bins - 1)


def _binned_cum_counts(scores: jax.Array, labels: jax.Array,
                       w: Optional[jax.Array], n_bins: int):
    """Weighted TP/FP cumulative counts over a score histogram.

    Scores land in `n_bins` equal-width buckets (_bin_idx); one
    scatter-add replaces the O(n log n) sort of `_sorted_cum_counts`.
    Cumulative counts run from the high-score end, so bucket k's entry is
    the (TP, FP) at threshold k/n_bins."""
    if w is None:
        w = jnp.ones_like(scores)
    idx = _bin_idx(scores, n_bins)
    pos = jnp.zeros(n_bins, jnp.float32).at[idx].add(labels * w)
    neg = jnp.zeros(n_bins, jnp.float32).at[idx].add((1.0 - labels) * w)
    tps = jnp.cumsum(pos[::-1])
    fps = jnp.cumsum(neg[::-1])
    return tps, fps


def binned_cum_counts_lanes(scores: jax.Array, labels: jax.Array,
                            w_lanes: jax.Array, n_bins: int
                            ) -> Tuple[jax.Array, jax.Array]:
    """Per-lane weighted TP/FP cumulative counts: scores [L, n] (one lane
    per fold/grid cell over the SAME rows), labels [n], w_lanes [L, n].

    TPU route: ONE pallas histogram call for all lanes — the lane id is
    the kernel's slot axis (ops/pallas_hist.py), so the [L, n] scatter-add
    the vmapped path would lower to (TPU serializes scatters) becomes MXU
    one-hot contractions over VMEM tiles. CPU/fallback: vmap of the
    scatter path. Identical results.
    """
    L, n = scores.shape

    def _vmapped():
        return jax.vmap(
            lambda s, wl: _binned_cum_counts(s, labels, wl, n_bins)
        )(scores, w_lanes)

    if jax.default_backend() != "tpu":
        return _vmapped()
    from . import pallas_hist
    if not pallas_hist.available():
        return _vmapped()

    idx = _bin_idx(scores, n_bins)
    pos_w = w_lanes * labels[None, :]
    neg_w = w_lanes * (1.0 - labels[None, :])
    lane = jnp.broadcast_to(
        jnp.arange(L, dtype=jnp.float32)[:, None], (L, n))
    total = L * n
    flat = lambda a: a.reshape(1, total)
    pay = jnp.concatenate([flat(pos_w), flat(neg_w)], axis=0)
    # ragged totals pad inside the kernel call (dropped-slot rows)
    hist = pallas_hist.hist_pallas(flat(idx), pay, flat(lane),
                                   n_slots=L, n_bins=n_bins)  # [L*2, bins]
    hist = hist.reshape(L, 2, n_bins)
    tps = jnp.cumsum(hist[:, 0, ::-1], axis=1)
    fps = jnp.cumsum(hist[:, 1, ::-1], axis=1)
    return tps, fps


def _au_pr_from_counts(tps: jax.Array, fps: jax.Array) -> jax.Array:
    """Average precision from cumulative counts; bins on the LAST axis
    (shared by the scalar and lane-batched routes)."""
    P = jnp.maximum(tps[..., -1:], EPS)
    recall = tps / P
    precision = tps / jnp.maximum(tps + fps, EPS)
    dr = jnp.diff(recall, axis=-1, prepend=0.0)
    return (dr * precision).sum(axis=-1)


def _au_roc_from_counts(tps: jax.Array, fps: jax.Array) -> jax.Array:
    """Trapezoid AuROC from cumulative counts; bins on the LAST axis."""
    P = jnp.maximum(tps[..., -1:], EPS)
    N = jnp.maximum(fps[..., -1:], EPS)
    tpr = tps / P
    fpr = fps / N
    dfpr = jnp.diff(fpr, axis=-1, prepend=0.0)
    tpr_prev = jnp.concatenate(
        [jnp.zeros(tpr.shape[:-1] + (1,), tpr.dtype), tpr[..., :-1]],
        axis=-1)
    return (dfpr * (tpr + tpr_prev) * 0.5).sum(axis=-1)


def au_pr_binned_lanes(scores: jax.Array, labels: jax.Array,
                       w_lanes: jax.Array, n_bins: int) -> jax.Array:
    """[L] average-precision values from per-lane binned counts (same
    approximation contract as au_pr_binned)."""
    return _au_pr_from_counts(
        *binned_cum_counts_lanes(scores, labels, w_lanes, n_bins))


def au_roc_binned_lanes(scores: jax.Array, labels: jax.Array,
                        w_lanes: jax.Array, n_bins: int) -> jax.Array:
    """[L] AuROC values from per-lane binned counts."""
    return _au_roc_from_counts(
        *binned_cum_counts_lanes(scores, labels, w_lanes, n_bins))


def au_pr_binned(scores: jax.Array, labels: jax.Array,
                 w: Optional[jax.Array] = None,
                 n_bins: int = 4096) -> jax.Array:
    """Histogram-approximate AuPR (average precision over bin boundaries).

    O(n) scatter-add instead of an O(n log n) device sort — the in-sweep
    ranking metric for very large n (the model selector's final winner is
    still scored with the exact `au_pr`). Approximation error is the score
    mass sharing a 1/n_bins-wide bucket: ~1e-4 at the default 4096 bins for
    smooth score distributions (the reference's threshold curves likewise
    bin at numBins=100, OpBinaryClassificationEvaluator.scala:68)."""
    tps, fps = _binned_cum_counts(scores, labels, w, n_bins)
    return _au_pr_from_counts(tps, fps)


def au_roc_binned(scores: jax.Array, labels: jax.Array,
                  w: Optional[jax.Array] = None,
                  n_bins: int = 4096) -> jax.Array:
    """Histogram-approximate AuROC (trapezoid over bin boundaries); see
    au_pr_binned for the approximation contract."""
    tps, fps = _binned_cum_counts(scores, labels, w, n_bins)
    return _au_roc_from_counts(tps, fps)


class BinaryMetrics(NamedTuple):
    au_roc: jax.Array
    au_pr: jax.Array
    precision: jax.Array
    recall: jax.Array
    f1: jax.Array
    error: jax.Array
    tp: jax.Array
    tn: jax.Array
    fp: jax.Array
    fn: jax.Array


@jax.jit
def binary_metrics(scores: jax.Array, labels: jax.Array,
                   w: Optional[jax.Array] = None,
                   threshold: float = 0.5) -> BinaryMetrics:
    scores = jnp.asarray(scores)
    labels = jnp.asarray(labels)
    if w is None:
        w = jnp.ones_like(scores)
    pred = (scores >= threshold).astype(scores.dtype)
    tp = (w * pred * labels).sum()
    fp = (w * pred * (1 - labels)).sum()
    tn = (w * (1 - pred) * (1 - labels)).sum()
    fn = (w * (1 - pred) * labels).sum()
    precision = tp / jnp.maximum(tp + fp, EPS)
    recall = tp / jnp.maximum(tp + fn, EPS)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, EPS)
    error = (fp + fn) / jnp.maximum(tp + tn + fp + fn, EPS)
    return BinaryMetrics(
        au_roc=au_roc(scores, labels, w), au_pr=au_pr(scores, labels, w),
        precision=precision, recall=recall, f1=f1, error=error,
        tp=tp, tn=tn, fp=fp, fn=fn)


@partial(jax.jit, static_argnames=("num_bins",))
def threshold_curves(scores: jax.Array, labels: jax.Array,
                     w: Optional[jax.Array] = None,
                     num_bins: int = 100) -> Dict[str, jax.Array]:
    """Precision/recall/F1 at evenly spaced thresholds (numBins=100,
    reference OpBinaryClassificationEvaluator threshold metrics)."""
    scores = jnp.asarray(scores)
    labels = jnp.asarray(labels)
    if w is None:
        w = jnp.ones_like(scores)
    thresholds = jnp.linspace(0.0, 1.0, num_bins)

    def at(th):
        pred = (scores >= th).astype(scores.dtype)
        tp = (w * pred * labels).sum()
        fp = (w * pred * (1 - labels)).sum()
        fn = (w * (1 - pred) * labels).sum()
        prec = tp / jnp.maximum(tp + fp, EPS)
        rec = tp / jnp.maximum(tp + fn, EPS)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, EPS)
        return prec, rec, f1

    prec, rec, f1 = jax.vmap(at)(thresholds)
    return {"thresholds": thresholds, "precision": prec, "recall": rec, "f1": f1}


class MultiMetrics(NamedTuple):
    precision: jax.Array  # weighted
    recall: jax.Array
    f1: jax.Array
    error: jax.Array


@partial(jax.jit, static_argnames=("n_classes",))
def multiclass_metrics(pred: jax.Array, labels: jax.Array, n_classes: int,
                       w: Optional[jax.Array] = None) -> MultiMetrics:
    """Weighted precision/recall/F1/error from predicted & true class ids."""
    pred = jnp.asarray(pred)
    labels = jnp.asarray(labels)
    if w is None:
        w = jnp.ones(pred.shape, jnp.float32)
    P = jax.nn.one_hot(pred.astype(jnp.int32), n_classes, dtype=w.dtype)
    Y = jax.nn.one_hot(labels.astype(jnp.int32), n_classes, dtype=w.dtype) * w[:, None]
    conf = Y.T @ P  # [true, pred], row-weighted once via Y
    tp = jnp.diag(conf)
    per_pred = conf.sum(axis=0)
    per_true = conf.sum(axis=1)
    prec_c = tp / jnp.maximum(per_pred, EPS)
    rec_c = tp / jnp.maximum(per_true, EPS)
    f1_c = 2 * prec_c * rec_c / jnp.maximum(prec_c + rec_c, EPS)
    weights = per_true / jnp.maximum(per_true.sum(), EPS)
    precision = (prec_c * weights).sum()
    recall = (rec_c * weights).sum()
    f1 = (f1_c * weights).sum()
    error = 1.0 - tp.sum() / jnp.maximum(conf.sum(), EPS)
    return MultiMetrics(precision=precision, recall=recall, f1=f1, error=error)


class ThresholdMetrics(NamedTuple):
    """Top-N per-threshold correctness counts (reference
    OpMultiClassificationEvaluator.scala:295 ThresholdMetrics). For each
    (top-N, threshold) cell over n rows:
    correct   — true-class score in the top N AND >= threshold;
    incorrect — top predicted score >= threshold AND (true class not in
                top N OR its score < threshold);
    no_prediction — top predicted score < threshold.
    The three [len(top_ns), T] count arrays sum to n in every cell."""

    top_ns: Tuple[int, ...]
    thresholds: jax.Array            # [T]
    correct_counts: jax.Array        # [len(top_ns), T] int32
    incorrect_counts: jax.Array      # [len(top_ns), T] int32
    no_prediction_counts: jax.Array  # [len(top_ns), T] int32

    def to_json(self) -> Dict[str, object]:
        import numpy as _np
        return {
            "top_ns": list(self.top_ns),
            "thresholds": _np.asarray(self.thresholds).tolist(),
            "correct_counts": {
                str(t): _np.asarray(self.correct_counts[i]).tolist()
                for i, t in enumerate(self.top_ns)},
            "incorrect_counts": {
                str(t): _np.asarray(self.incorrect_counts[i]).tolist()
                for i, t in enumerate(self.top_ns)},
            "no_prediction_counts": {
                str(t): _np.asarray(self.no_prediction_counts[i]).tolist()
                for i, t in enumerate(self.top_ns)},
        }


@partial(jax.jit, static_argnames=("top_ns",))
def _threshold_metrics_kernel(probs: jax.Array, labels: jax.Array,
                              thresholds: jax.Array, top_ns: Tuple[int, ...]
                              ) -> Tuple[jax.Array, jax.Array]:
    """One pass over [n, C] probabilities — no sort, no gather.

    Reference computeMetrics (OpMultiClassificationEvaluator.scala:188)
    sorts each row's scores; here the true class's rank comes from two
    fused comparisons (scores strictly greater + equal-score ties at lower
    index, matching the stable descending sort), the true-class score from
    a one-hot contraction, and each per-threshold fill range from an
    indexWhere-equivalent first-True argmax. Everything lowers to
    elementwise compares + reductions on the MXU/VPU."""
    n, C = probs.shape
    T = thresholds.shape[0]
    lbl = labels.astype(jnp.int32)
    valid = (lbl >= 0) & (lbl < C)          # scores.lift(label) semantics
    onehot = jax.nn.one_hot(jnp.where(valid, lbl, 0), C, dtype=probs.dtype)
    s_true = jnp.where(valid, (probs * onehot).sum(1), 0.0)
    s_top = probs.max(1)
    # rank of the true class under a STABLE descending sort (scala sortBy):
    # strictly-greater scores, plus equal scores at a lower class index
    idx = jnp.arange(C)[None, :]
    gt = (probs > s_true[:, None]).sum(1)
    ties_before = ((probs == s_true[:, None])
                   & (idx < lbl[:, None])).sum(1)
    rank = gt + ties_before
    # indexWhere(_ > score): first threshold index exceeding the score,
    # T when none does (argmax of a boolean row finds the first True)
    def cutoff(score):
        over = thresholds[None, :] > score[:, None]      # [n, T]
        return jnp.where(over.any(1), jnp.argmax(over, 1), T)
    c_true = cutoff(s_true)[:, None]                     # [n, 1]
    c_top = cutoff(s_top)[:, None]
    k = jnp.arange(T)[None, :]                           # [1, T]
    before_true = k < c_true                             # arrayFill(0, cTrue)
    before_top = k < c_top
    correct_rows, incorrect_rows = [], []
    for t in top_ns:
        in_topn = (valid & (rank < t))[:, None]          # [n, 1]
        corr = in_topn & before_true
        incorr = jnp.where(in_topn, (~before_true) & before_top, before_top)
        correct_rows.append(corr.sum(0, dtype=jnp.int32))
        incorrect_rows.append(incorr.sum(0, dtype=jnp.int32))
    return jnp.stack(correct_rows), jnp.stack(incorrect_rows)


def multiclass_threshold_metrics(probs: jax.Array, labels: jax.Array,
                                 top_ns: Tuple[int, ...] = (1, 3),
                                 thresholds: Optional[jax.Array] = None
                                 ) -> ThresholdMetrics:
    """Top-N threshold metrics for multiclass probabilities (reference
    calculateThresholdMetrics, OpMultiClassificationEvaluator.scala:154;
    default thresholds 0.00..1.00 step 0.01 as in the reference)."""
    probs = jnp.asarray(probs)
    if thresholds is None:
        thresholds = jnp.arange(101, dtype=jnp.float32) / 100.0
    else:
        thresholds = jnp.asarray(thresholds, jnp.float32)
    top_ns = tuple(int(t) for t in top_ns)
    if not top_ns or any(t <= 0 for t in top_ns):
        raise ValueError("top_ns must be non-empty positive ints")
    correct, incorrect = _threshold_metrics_kernel(
        probs, jnp.asarray(labels), thresholds, top_ns)
    n = probs.shape[0]
    return ThresholdMetrics(
        top_ns=top_ns, thresholds=thresholds,
        correct_counts=correct, incorrect_counts=incorrect,
        no_prediction_counts=n - correct - incorrect)


class RegressionMetrics(NamedTuple):
    rmse: jax.Array
    mse: jax.Array
    mae: jax.Array
    r2: jax.Array


@jax.jit
def regression_metrics(pred: jax.Array, labels: jax.Array,
                       w: Optional[jax.Array] = None) -> RegressionMetrics:
    pred = jnp.asarray(pred)
    labels = jnp.asarray(labels)
    if w is None:
        w = jnp.ones_like(pred)
    tot = jnp.maximum(w.sum(), EPS)
    err = pred - labels
    mse = (w * err * err).sum() / tot
    mae = (w * jnp.abs(err)).sum() / tot
    ybar = (w * labels).sum() / tot
    ss_tot = (w * (labels - ybar) ** 2).sum()
    ss_res = (w * err * err).sum()
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, EPS)
    return RegressionMetrics(rmse=jnp.sqrt(mse), mse=mse, mae=mae, r2=r2)
