"""One-pass sharded statistics engine for the pre-model statistics layer.

The SanityChecker (automl/preparators.py), RawFeatureFilter
(filters/raw_feature_filter.py) and RecordInsightsCorr (insights/corr.py)
each used to make several separate device passes over the full feature
matrix — per-column moments, label correlations, the feature-feature
Pearson matrix, label moments, plus one device round-trip per categorical
indicator group and one un-jitted histogram program per numeric column.
All of those reductions are bandwidth-bound: the roofline is ONE read of X
(arxiv 2008.01040's learned TPU performance model puts fused reductions at
the HBM roof), and the DrJAX decomposition (arxiv 2403.07128) — sharded
map + psum-merged sufficient statistics — is exactly the shape this module
implements.

One blocked/jitted scan over row tiles accumulates EVERY sufficient
statistic in a single read of X:

- per-column count / mean / M2 / min / max / nnz via an exact
  Welford-style tile merge (two-pass moments WITHIN the in-registers
  tile, Chan's parallel merge ACROSS tiles — no catastrophic f32
  cancellation for large-mean columns, unlike raw E[x^2]-mean^2);
- label cross co-moments (the `X^T y` slot) and per-column-masked label
  moments with the same tile merge, giving pairwise-complete Pearson
  correlations with the label;
- the capped feature-feature Gram for the full Pearson matrix,
  shift-centered at the first tile's column means so the f32 matmul
  accumulators stay cancellation-safe;
- ALL categorical contingency tables as one matmul per tile against an
  on-device one-hot label (built per tile from the distinct-value vector;
  the [n, C] one-hot never exists in HBM), replacing the per-group host
  loop;
- numeric histograms for every column at once via the flattened-ids
  binning trick of ops/pallas_hist._hist_segment_jnp (column-offset
  segment ids, one segment-sum per tile);
- whole-label moments (count/mean/variance/min/max).

Three drivers mirror the PR 3 GLM sweep architecture:

- `fused_stats` — single jitted program for HBM-resident data;
- `fused_stats_sharded` — the SAME core under shard_map over the
  data-parallel mesh `batch` axis (parallel/mesh.build_shard_map), with
  an exact Chan merge ACROSS shards done as two tiny psum rounds, so
  stats run where sweep data already lives, no host gather (the
  psum-reaches-every-replicated-output contract is tmoglint-SHD001-
  checked — it cannot fail visibly on a 1-device-per-shard CI mesh);
- `stream_stats` — the double-buffered tileplane driver
  (parallel/tileplane.py) for datasets larger than HBM: a producer
  thread device_puts tile k+1 while the device Chan-merges tile k into
  a DEVICE-resident carry (fetched once at the end); accepts a
  `tileplane.RowSource` (Avro/CSV reader adapter) so X need never
  exist as one array, and a `mesh` for the shard_map tile lane.
  TMOG_TILEPLANE=0 restores the legacy synchronous loop with per-tile
  host f64 merge.

`run_stats` is the routed front door: it picks a driver, times the pass
with a block_until_ready fence, and reports a `stats_pass` kernel span +
StatsPass telemetry (utils/metrics) with analytic bytes so the "one pass"
claim is runtime-verifiable from any traced run.

The legacy multi-pass path (ops/stats called per statistic) is kept by
the consumers as a kill switch: TMOG_STATS_FUSED=0.
"""
from __future__ import annotations

import functools
import os
import time
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import stats as S
from .glm_sweep import env_on
from ..parallel.mesh import BATCH_AXIS, build_shard_map, shard_vary

EPS = 1e-12

# Rows per scan tile: bounds the [c, d] f32 tile transient at ~32MB plus
# the per-tile one-hot/segment intermediates. Matches the glm_sweep tile
# philosophy — the scan carry ([d]-vectors + the optional [d, d] Gram
# accumulators) is microscopic next to the tile itself.
_TILE_BUDGET_BYTES = 32 << 20

# Widest matrix for which the full d x d Pearson Gram is accumulated.
# Past this, the three [d, d] f32 accumulators and the per-tile matmuls
# stop being "free riders" on the bandwidth-bound pass; the consumers
# (SanityChecker max_corr_matrix_columns, default 256) cap well below.
GRAM_MAX_D = 1024


def stats_row_block(d: int, n: int) -> int:
    c = _TILE_BUDGET_BYTES // max(4 * d, 1)
    c = max(min(c, 1 << 16), 1024)
    return max(min(c, n), 1)


def fused_enabled() -> bool:
    """THE kill switch for the one-pass engine (TMOG_STATS_FUSED=0
    restores the legacy multi-pass statistics in every consumer)."""
    return env_on("TMOG_STATS_FUSED")


def stream_threshold_bytes() -> int:
    """X size above which run_stats routes through the streamed driver
    (default 4GB — roughly the point where a second full-matrix resident
    would pressure a single device's HBM)."""
    return int(os.environ.get("TMOG_STATS_STREAM_MB", "4096")) << 20


def stream_tile_rows_default() -> int:
    """Rows per streamed statistics tile. An explicitly-set
    TMOG_STATS_TILE_ROWS wins (hand beats model, logged as a
    plan_override event); otherwise the plan-time autotuner picks the
    tile shape — cold corpus / TMOG_PLAN=0 / any planner fault all
    yield the 2^18 hand default (docs/planning.md)."""
    try:
        from ..planner.plan import planned_stats_tile_rows
        return planned_stats_tile_rows()
    except Exception:
        return int(os.environ.get("TMOG_STATS_TILE_ROWS", str(1 << 18)))


def stats_pass_bytes(n: int, d: int, *, itemsize: int = 4,
                     y2d: bool = False, weighted: bool = False) -> int:
    """Analytic HBM bytes for ONE engine pass: a single read of X plus the
    label (a second [n, d] plane in rank/2-D-label mode) and the optional
    weight vector. Output vectors ([d]-shaped moments, the capped Gram)
    are noise at any n worth measuring. Analytic by construction — the
    whole pass is one jitted program, so per-invocation byte counters
    cannot exist inside it (same contract as pallas_hist traffic models).
    """
    b = n * d * itemsize
    b += n * d * 4 if y2d else n * 4
    if weighted:
        b += n * 4
    return int(b)


def legacy_pass_count(*, corr_matrix: bool, n_groups: int = 0,
                      spearman: bool = False) -> int:
    """How many device passes over X the pre-engine SanityChecker path
    made for the same statistics: col_stats + corr-with-label (2 passes
    through pearson/spearman internals) + the optional pearson matrix
    (col_stats + matmul = 2) + one contingency matmul per categorical
    group. Used by bench --stats-roofline and docs/performance.md so the
    before/after accounting has one source."""
    passes = 1 + (2 if spearman else 1)
    if corr_matrix:
        passes += 2
    return passes + n_groups


# -- results ----------------------------------------------------------------

class FusedStats(NamedTuple):
    """Host-side (numpy) results of one engine pass.

    Per-column arrays are [d]; `m2` is the raw centered second moment
    (population variance = m2 / count — RecordInsightsCorr needs the
    population convention, ColStats the unbiased one). `corr_matrix`,
    `contingency` ([d, C] vs the distinct label values, columns
    optionally clipped to 1 for multi-hot groups) and `hist`
    ([d, bins + 1]; last bin = missing mass) are None unless requested.
    """

    count: np.ndarray
    mean: np.ndarray
    variance: np.ndarray
    m2: np.ndarray
    min: np.ndarray
    max: np.ndarray
    num_non_zeros: np.ndarray
    fill_rate: np.ndarray
    corr_label: np.ndarray
    wsum: float
    label_count: float
    label_mean: float
    label_variance: float
    label_min: float
    label_max: float
    corr_matrix: Optional[np.ndarray] = None
    contingency: Optional[np.ndarray] = None
    hist: Optional[np.ndarray] = None


class _State(NamedTuple):
    """Mergeable sufficient-statistics state (device or host arrays).

    Moment fields are Chan-mergeable (count/mean/M2 + co-moments); the
    rest merge by elementwise min/max/sum. Optional members are None when
    the corresponding statistic was not requested (the pytree structure
    is fixed per trace by the driver's static flags)."""

    wsum: Any
    cnt: Any          # [d] valid weighted count
    mean: Any         # [d]
    m2: Any           # [d]
    cy: Any           # [d] co-moment of column with label (column-masked)
    ymean: Any        # [d] label mean over column-valid rows
    ym2: Any          # [d]
    minv: Any         # [d]
    maxv: Any         # [d]
    nnz: Any          # [d]
    ycnt: Any         # scalar: label moments over finite-label rows
    lmean: Any
    lm2: Any
    lmin: Any
    lmax: Any
    gzz: Any = None   # [d, d] shift-centered Gram accumulators
    gzv: Any = None
    gvv: Any = None
    cont: Any = None  # [d, C]
    hist: Any = None  # [d * (bins + 1)] flat


def _chan_merge(nA, mA, m2A, nB, mB, m2B):
    """Chan/Welford parallel merge of weighted (count, mean, M2)."""
    n = nA + nB
    safe = jnp.maximum(n, EPS)
    delta = mB - mA
    mean = mA + delta * (nB / safe)
    m2 = m2A + m2B + delta * delta * (nA * nB / safe)
    return n, mean, m2


def _tile_state(xb, yb, wb, shift, distinct, clip, lo, hi, *, bins: int,
                corr_matrix: bool, y2d: bool, big: float) -> _State:
    """Exact two-pass moments of ONE tile (the tile lives in registers /
    VMEM — the second 'pass' re-reads no HBM), shaped as a _State ready
    for the Chan merge."""
    finite = jnp.isfinite(xb)
    v01 = finite.astype(jnp.float32)
    v = v01 * wb[:, None]                                  # [c, d]
    xz = jnp.where(finite, xb, 0.0).astype(jnp.float32)
    cnt = v.sum(0)
    safe = jnp.maximum(cnt, EPS)
    mean = (xz * v).sum(0) / safe
    dx = xz - mean[None, :]
    m2 = (dx * dx * v).sum(0)

    yz2 = yb if y2d else yb[:, None]
    yz2 = jnp.where(jnp.isfinite(yz2), yz2, 0.0).astype(jnp.float32)
    ymean = (yz2 * v).sum(0) / safe
    dy = yz2 - ymean[None, :]
    ym2 = (dy * dy * v).sum(0)
    cy = (dx * dy * v).sum(0)

    minv = jnp.where(v > 0, xz, big).min(0)
    maxv = jnp.where(v > 0, xz, -big).max(0)
    nnz = ((xz != 0) & (v > 0)).astype(jnp.float32).sum(0)
    wsum = wb.sum()

    if y2d:
        ycnt = jnp.asarray(0.0, jnp.float32)
        lmean = jnp.asarray(0.0, jnp.float32)
        lm2 = jnp.asarray(0.0, jnp.float32)
        lmin = jnp.asarray(big, jnp.float32)
        lmax = jnp.asarray(-big, jnp.float32)
    else:
        lv = jnp.isfinite(yb).astype(jnp.float32) * wb
        yz = jnp.where(jnp.isfinite(yb), yb, 0.0).astype(jnp.float32)
        ycnt = lv.sum()
        lsafe = jnp.maximum(ycnt, EPS)
        lmean = (yz * lv).sum() / lsafe
        lm2 = (((yz - lmean) ** 2) * lv).sum()
        lmin = jnp.where(lv > 0, yz, big).min()
        lmax = jnp.where(lv > 0, yz, -big).max()

    gzz = gzv = gvv = None
    if corr_matrix:
        z = (xz - shift[None, :]) * v01                    # [c, d]
        zw = z * wb[:, None]
        vw = v01 * wb[:, None]
        gzz = jnp.matmul(zw.T, z, preferred_element_type=jnp.float32)
        gzv = jnp.matmul(zw.T, v01, preferred_element_type=jnp.float32)
        gvv = jnp.matmul(vw.T, v01, preferred_element_type=jnp.float32)

    cont = None
    if distinct is not None:
        yoh = (yb[:, None] == distinct[None, :]).astype(jnp.float32)
        xc = xz
        if clip is not None:
            xc = jnp.where(clip[None, :], jnp.minimum(xz, 1.0), xz)
        cont = jnp.matmul((xc * v).T, yoh,
                          preferred_element_type=jnp.float32)

    hist = None
    if bins > 0:
        d = xb.shape[1]
        # the shared binning rule (ops/stats.hist_bin_ids) with the
        # engine's finite-only validity mask — same clip semantics as the
        # standalone histogram_batched fallback by construction
        ids = S.hist_bin_ids(xb, lo, hi, bins, finite)
        wt = jnp.broadcast_to(wb[:, None], xb.shape)
        hist = jax.ops.segment_sum(wt.reshape(-1), ids.reshape(-1),
                                   num_segments=d * (bins + 1))

    return _State(wsum=wsum, cnt=cnt, mean=mean, m2=m2, cy=cy, ymean=ymean,
                  ym2=ym2, minv=minv, maxv=maxv, nnz=nnz, ycnt=ycnt,
                  lmean=lmean, lm2=lm2, lmin=lmin, lmax=lmax, gzz=gzz,
                  gzv=gzv, gvv=gvv, cont=cont, hist=hist)


def _merge_states(a: _State, b: _State) -> _State:
    """Chan-merge two states (jnp; works on traced or concrete arrays)."""
    cnt, mean, m2 = _chan_merge(a.cnt, a.mean, a.m2, b.cnt, b.mean, b.m2)
    safe = jnp.maximum(cnt, EPS)
    dxm = b.mean - a.mean
    dym = b.ymean - a.ymean
    cross = a.cnt * b.cnt / safe
    cy = a.cy + b.cy + dxm * dym * cross
    ymean = a.ymean + dym * (b.cnt / safe)
    ym2 = a.ym2 + b.ym2 + dym * dym * cross
    ycnt, lmean, lm2 = _chan_merge(a.ycnt, a.lmean, a.lm2,
                                   b.ycnt, b.lmean, b.lm2)
    return _State(
        wsum=a.wsum + b.wsum, cnt=cnt, mean=mean, m2=m2, cy=cy,
        ymean=ymean, ym2=ym2,
        minv=jnp.minimum(a.minv, b.minv), maxv=jnp.maximum(a.maxv, b.maxv),
        nnz=a.nnz + b.nnz, ycnt=ycnt, lmean=lmean, lm2=lm2,
        lmin=jnp.minimum(a.lmin, b.lmin), lmax=jnp.maximum(a.lmax, b.lmax),
        gzz=None if a.gzz is None else a.gzz + b.gzz,
        gzv=None if a.gzv is None else a.gzv + b.gzv,
        gvv=None if a.gvv is None else a.gvv + b.gvv,
        cont=None if a.cont is None else a.cont + b.cont,
        hist=None if a.hist is None else a.hist + b.hist)


def _zero_state(d: int, *, corr_matrix: bool, n_classes: int, bins: int,
                big: float) -> _State:
    f32 = jnp.float32
    return _State(
        wsum=jnp.asarray(0.0, f32), cnt=jnp.zeros(d, f32),
        mean=jnp.zeros(d, f32), m2=jnp.zeros(d, f32), cy=jnp.zeros(d, f32),
        ymean=jnp.zeros(d, f32), ym2=jnp.zeros(d, f32),
        minv=jnp.full(d, big, f32), maxv=jnp.full(d, -big, f32),
        nnz=jnp.zeros(d, f32), ycnt=jnp.asarray(0.0, f32),
        lmean=jnp.asarray(0.0, f32), lm2=jnp.asarray(0.0, f32),
        lmin=jnp.asarray(big, f32), lmax=jnp.asarray(-big, f32),
        gzz=jnp.zeros((d, d), f32) if corr_matrix else None,
        gzv=jnp.zeros((d, d), f32) if corr_matrix else None,
        gvv=jnp.zeros((d, d), f32) if corr_matrix else None,
        cont=jnp.zeros((d, n_classes), f32) if n_classes else None,
        hist=jnp.zeros(d * (bins + 1), f32) if bins else None)


def _first_tile_shift(X, w, c: int, allreduce) -> jax.Array:
    """Per-column masked mean of the first row tile — the common Gram
    shift. Under shard_map the psum makes it identical on every shard
    (accumulators centered at different shifts could not be psum-merged).
    The first tile is read twice (once here, once in the scan): 1/n_tiles
    of a pass, ignored by the traffic model."""
    xb = X[:c]
    finite = jnp.isfinite(xb)
    v = finite.astype(jnp.float32) * w[:c, None]
    xz = jnp.where(finite, xb, 0.0).astype(jnp.float32)
    s = allreduce((xz * v).sum(0))
    n = allreduce(v.sum(0))
    return jnp.where(n > 0, s / jnp.maximum(n, EPS), 0.0)


# -- finalize (host, f64) ----------------------------------------------------

def _finalize(st, shift, *, bins: int) -> FusedStats:
    """Moment state -> FusedStats. Host-side numpy: the state is [d]/[d,d]
    shaped — microscopic — and f64 here costs nothing while keeping the
    tiny final divisions exact. Mirrors ops/stats formulas exactly
    (unbiased variance clamp, EPS-guarded correlation denominators)."""
    # host finalize on fetched [d]-vectors; f64 never touches the device
    # program
    f8 = np.float64  # tmoglint: disable=TPU003  host-only precision
    cnt = np.asarray(st.cnt, f8)
    mean = np.asarray(st.mean, f8)
    m2 = np.asarray(st.m2, f8)
    cy = np.asarray(st.cy, f8)
    ym2 = np.asarray(st.ym2, f8)
    wsum = float(np.asarray(st.wsum))
    variance = np.maximum(m2 / np.maximum(cnt - 1.0, 1.0), 0.0)
    corr = cy / np.sqrt(np.maximum(m2 * ym2, EPS * EPS))
    fill = cnt / max(wsum, EPS)
    ycnt = float(np.asarray(st.ycnt))

    corr_matrix = None
    if st.gzz is not None:
        gzz = np.asarray(st.gzz, f8)
        gzv = np.asarray(st.gzv, f8)
        gvv = np.asarray(st.gvv, f8)
        a = mean - np.asarray(shift, f8)
        cov = gzz - gzv * a[None, :] - (gzv * a[None, :]).T \
            + np.outer(a, a) * gvv
        sd = np.sqrt(np.maximum(np.diag(cov), EPS))
        corr_matrix = cov / (sd[:, None] * sd[None, :])

    hist = None
    if st.hist is not None:
        hist = np.asarray(st.hist, f8).reshape(-1, bins + 1)

    return FusedStats(
        count=cnt, mean=mean, variance=variance, m2=m2,
        min=np.asarray(st.minv, f8), max=np.asarray(st.maxv, f8),
        num_non_zeros=np.asarray(st.nnz, f8), fill_rate=fill,
        corr_label=corr, wsum=wsum, label_count=ycnt,
        label_mean=float(np.asarray(st.lmean)),
        label_variance=float(max(np.asarray(st.lm2)
                                 / max(ycnt - 1.0, 1.0), 0.0)),
        label_min=float(np.asarray(st.lmin)),
        label_max=float(np.asarray(st.lmax)),
        corr_matrix=corr_matrix,
        contingency=(None if st.cont is None
                     else np.asarray(st.cont, f8)),
        hist=hist)


# -- drivers -----------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("bins", "corr_matrix"))
def _fused_stats_jit(X, y, w, distinct, clip, lo, hi, *, bins: int,
                     corr_matrix: bool):
    """Single-program driver: one scan, returns (state, shift)."""
    n, d = X.shape
    shift = jnp.zeros(d, jnp.float32)
    if corr_matrix:
        shift = _first_tile_shift(X, w, min(stats_row_block(d, n), n),
                                  lambda v: v)
    st = _scan_state_single(X, y, w, distinct, clip, lo, hi, bins=bins,
                            corr_matrix=corr_matrix, shift=shift)
    return st, shift


def _scan_state_single(X, y, w, distinct, clip, lo, hi, *, bins,
                       corr_matrix, shift, axis_name=None):
    """Single-scan body shared by the jitted single-program and sharded
    cores (shift already resolved by the caller)."""
    n, d = X.shape
    big = float(np.finfo(np.float32).max)
    y2d = y.ndim == 2
    c = stats_row_block(d, n)
    nb = -(-n // c)
    pad = nb * c - n
    if pad:
        X = jnp.pad(X, ((0, pad), (0, 0)))
        y = jnp.pad(y, ((0, pad), (0, 0)) if y2d else (0, pad))
        w = jnp.pad(w, (0, pad))
    Xs = X.reshape(nb, c, d)
    ys = y.reshape((nb, c, d) if y2d else (nb, c))
    ws = w.reshape(nb, c)

    def body(acc, sl):
        xb, yb, wb = sl
        st = _tile_state(xb, yb, wb, shift, distinct, clip, lo, hi,
                         bins=bins, corr_matrix=corr_matrix, y2d=y2d,
                         big=big)
        return _merge_states(acc, st), None

    acc0 = shard_vary(
        _zero_state(d, corr_matrix=corr_matrix,
                    n_classes=0 if distinct is None else distinct.shape[0],
                    bins=bins, big=big),
        axis_name)
    st, _ = jax.lax.scan(body, acc0, (Xs, ys, ws))
    if axis_name is None:
        return st

    def psum(v):
        return jax.lax.psum(v, axis_name)

    cnt_g = psum(st.cnt)
    safe = jnp.maximum(cnt_g, EPS)
    mean_g = psum(st.cnt * st.mean) / safe
    ymean_g = psum(st.cnt * st.ymean) / safe
    m2_g = psum(st.m2 + st.cnt * (st.mean - mean_g) ** 2)
    ym2_g = psum(st.ym2 + st.cnt * (st.ymean - ymean_g) ** 2)
    cy_g = psum(st.cy + st.cnt * (st.mean - mean_g) * (st.ymean - ymean_g))
    ycnt_g = psum(st.ycnt)
    lsafe = jnp.maximum(ycnt_g, EPS)
    lmean_g = psum(st.ycnt * st.lmean) / lsafe
    lm2_g = psum(st.lm2 + st.ycnt * (st.lmean - lmean_g) ** 2)
    return _State(
        wsum=psum(st.wsum), cnt=cnt_g, mean=mean_g, m2=m2_g, cy=cy_g,
        ymean=ymean_g, ym2=ym2_g,
        minv=jax.lax.pmin(st.minv, axis_name),
        maxv=jax.lax.pmax(st.maxv, axis_name),
        nnz=psum(st.nnz), ycnt=ycnt_g, lmean=lmean_g, lm2=lm2_g,
        lmin=jax.lax.pmin(st.lmin, axis_name),
        lmax=jax.lax.pmax(st.lmax, axis_name),
        gzz=None if st.gzz is None else psum(st.gzz),
        gzv=None if st.gzv is None else psum(st.gzv),
        gvv=None if st.gvv is None else psum(st.gvv),
        cont=None if st.cont is None else psum(st.cont),
        hist=None if st.hist is None else psum(st.hist))


@functools.lru_cache(maxsize=None)
def _sharded_stats_fn(mesh, bins: int, corr_matrix: bool,
                      have_distinct: bool, have_clip: bool,
                      have_hist: bool, y2d: bool):
    """shard_map-wrapped core for one (mesh, feature-flag) combination.

    The optional-statistics flags select the exact positional signature so
    shard_map's in_specs always match the arg pytree (None args do not
    thread through shard_map specs)."""
    from jax.sharding import PartitionSpec as P

    def core(X, y, w, *extras):
        it = iter(extras)
        distinct = next(it) if have_distinct else None
        clip = next(it) if have_clip else None
        lo = next(it) if have_hist else None
        hi = next(it) if have_hist else None
        shift = jnp.zeros(X.shape[1], jnp.float32)
        if corr_matrix:
            shift = _first_tile_shift(
                X, w, min(stats_row_block(X.shape[1], X.shape[0]),
                          X.shape[0]),
                lambda v: jax.lax.psum(v, BATCH_AXIS))
        st = _scan_state_single(X, y, w, distinct, clip, lo, hi,
                                bins=bins, corr_matrix=corr_matrix,
                                shift=shift, axis_name=BATCH_AXIS)
        return st, shift

    n_extras = int(have_distinct) + int(have_clip) + 2 * int(have_hist)
    in_specs = (P(BATCH_AXIS, None),
                P(BATCH_AXIS, None) if y2d else P(BATCH_AXIS),
                P(BATCH_AXIS)) + (P(None),) * n_extras
    sm = build_shard_map(core, mesh, in_specs=in_specs, out_specs=P())
    return jax.jit(sm)


def _as_f32(x):
    a = jnp.asarray(x)
    if a.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        a = a.astype(jnp.float32)
    return a


def fused_stats(X, y, w=None, *, distinct=None, clip=None, lo=None,
                hi=None, bins: int = 0,
                corr_matrix: bool = False) -> Tuple[_State, jax.Array]:
    """One-pass sufficient statistics as a SINGLE jitted program.

    X [n, d] (NaN = missing); y [n] label or [n, d] per-column label
    (rank mode); w [n] row weights (None = 1). distinct [C] enables the
    batched contingency accumulation (clip [d] bool marks multi-hot
    columns counted at-most-once); (lo, hi, bins) enables fused
    histograms. Returns the raw (state, shift) pair; `run_stats` is the
    finalizing front door."""
    X = _as_f32(X)
    y = _as_f32(y)
    n, d = X.shape
    if corr_matrix and d > GRAM_MAX_D:
        raise ValueError(f"corr_matrix capped at {GRAM_MAX_D} columns "
                         f"(got {d}); the consumers cap far below")
    w = jnp.ones(n, jnp.float32) if w is None else _as_f32(w)
    distinct = None if distinct is None else _as_f32(distinct)
    clip = None if clip is None else jnp.asarray(clip, bool)
    lo = None if lo is None else _as_f32(lo)
    hi = None if hi is None else _as_f32(hi)
    if (lo is None) != (bins == 0):
        raise ValueError("histograms need both bins>0 and lo/hi ranges")
    return _fused_stats_jit(X, y, w, distinct, clip, lo, hi,
                            bins=int(bins), corr_matrix=bool(corr_matrix))


def fused_stats_sharded(mesh, X, y, w=None, *, distinct=None, clip=None,
                        lo=None, hi=None, bins: int = 0,
                        corr_matrix: bool = False):
    """The SAME one-pass core under shard_map over the mesh `batch` axis.

    X/y/w may be host arrays (device_put with row padding + zero-weight
    pad mask happens here) or pre-sharded jax arrays whose rows already
    divide the batch axis — the no-host-gather path when the matrix
    already lives on the mesh. Accumulator merges psum over ICI/DCN; the
    tiny finalize runs replicated.

    On a MULTI-PROCESS mesh X/y/w are THIS PROCESS's host-local row
    block (every process calls with its own rows — SPMD); the blocks
    land as the process's `batch`-axis stripe of one global array
    (multihost.host_local_block) and the psum merges become genuine
    cross-host collectives. The set of (row, weight) pairs equals the
    single-process call's, so the sufficient statistics match within
    float tolerance (docs/performance.md)."""
    from ..parallel import mesh as M

    if M.mesh_is_multiprocess(mesh):
        from ..parallel import multihost as MH

        Xl = np.asarray(X, np.float32)
        yl = np.asarray(y, np.float32)
        n, d = Xl.shape
        if corr_matrix and d > GRAM_MAX_D:
            raise ValueError(f"corr_matrix capped at {GRAM_MAX_D} columns")
        wl = np.ones(n, np.float32) if w is None else \
            np.asarray(w, np.float32)
        layout = MH.row_layout(n, mesh)       # collective (count gather)
        X = MH.host_local_block(Xl, mesh, layout)
        y = MH.host_local_block(yl, mesh, layout)
        w = MH.host_local_block(wl, mesh, layout)  # zero weight = inert pad
        extras = []
        if distinct is not None:
            extras.append(MH.replicated_global(
                np.asarray(distinct, np.float32), mesh))
        if clip is not None:
            extras.append(MH.replicated_global(np.asarray(clip, bool),
                                               mesh))
        if lo is not None:
            extras.append(MH.replicated_global(np.asarray(lo, np.float32),
                                               mesh))
            extras.append(MH.replicated_global(np.asarray(hi, np.float32),
                                               mesh))
        fn = _sharded_stats_fn(mesh, int(bins), bool(corr_matrix),
                               distinct is not None, clip is not None,
                               lo is not None, y.ndim == 2)
        return fn(X, y, w, *extras)

    X = _as_f32(X)
    y = _as_f32(y)
    n, d = X.shape
    if corr_matrix and d > GRAM_MAX_D:
        raise ValueError(f"corr_matrix capped at {GRAM_MAX_D} columns")
    w = jnp.ones(n, jnp.float32) if w is None else _as_f32(w)
    n_shards = mesh.shape[BATCH_AXIS]
    if n % n_shards:
        pad = n_shards - n % n_shards
        X = jnp.pad(X, ((0, pad), (0, 0)))
        y = jnp.pad(y, ((0, pad), (0, 0)) if y.ndim == 2 else (0, pad))
        w = jnp.pad(w, (0, pad))
    X = jax.device_put(X, M.batch_sharding(mesh, ndim=2))
    y = jax.device_put(y, M.batch_sharding(mesh, ndim=y.ndim))
    w = jax.device_put(w, M.batch_sharding(mesh, ndim=1))
    extras = []
    if distinct is not None:
        extras.append(jax.device_put(_as_f32(distinct), M.replicated(mesh)))
    if clip is not None:
        extras.append(jax.device_put(jnp.asarray(clip, bool),
                                     M.replicated(mesh)))
    if lo is not None:
        extras.append(jax.device_put(_as_f32(lo), M.replicated(mesh)))
        extras.append(jax.device_put(_as_f32(hi), M.replicated(mesh)))
    fn = _sharded_stats_fn(mesh, int(bins), bool(corr_matrix),
                           distinct is not None, clip is not None,
                           lo is not None, y.ndim == 2)
    return fn(X, y, w, *extras)


@functools.partial(jax.jit, static_argnames=("bins", "corr_matrix"))
def _stream_tile_jit(X, y, w, shift, distinct, clip, lo, hi, *, bins: int,
                     corr_matrix: bool):
    """One streamed tile's state (tiles arrive padded to a fixed row
    count with w=0, so every tile shares ONE executable)."""
    return _scan_state_single(X, y, w, distinct, clip, lo, hi, bins=bins,
                              corr_matrix=corr_matrix, shift=shift)


@jax.jit
def _tile_shift_jit(X, w):
    """Gram shift from the FIRST tile, on device: the per-column masked
    mean of the tile that is already resident for the pass's first step.
    Replaces the old host pre-pass over X[:c] (which read the first
    tile's rows twice — once on host, once when the loop re-sliced
    0:c)."""
    return _first_tile_shift(X, w, X.shape[0], lambda v: v)


@functools.partial(jax.jit, static_argnames=("bins", "corr_matrix"),
                   donate_argnums=(0,))
def _tileplane_step_jit(carry, X, y, w, distinct, clip, lo, hi, *,
                        bins: int, corr_matrix: bool):
    """Tileplane step: fold one fixed-shape tile into the DEVICE-resident
    carry (state, shift). The carry is DONATED — the output state aliases
    the input buffers, so a whole streamed pass updates one state
    in place and fetches it ONCE at the end (the legacy loop fetched and
    host-merged after every tile). Tile buffers are not donate-marked:
    they have no same-shaped output to alias (XLA would warn and copy);
    their last reference dies at dispatch, which frees them just as
    early."""
    st, shift = carry
    ts = _scan_state_single(X, y, w, distinct, clip, lo, hi, bins=bins,
                            corr_matrix=corr_matrix, shift=shift)
    return _merge_states(st, ts), shift


@functools.lru_cache(maxsize=None)
def _tileplane_sharded_step(mesh, bins: int, corr_matrix: bool,
                            have_distinct: bool, have_clip: bool,
                            have_hist: bool, y2d: bool):
    """The SAME tile-merge step under shard_map over the mesh batch axis:
    each shard scans its rows of the tile, a psum round Chan-merges
    across shards, and the replicated result merges into the replicated
    carry — the tileplane's optional mesh lane."""
    from jax.sharding import PartitionSpec as P

    def core(carry, X, y, w, *extras):
        it = iter(extras)
        distinct = next(it) if have_distinct else None
        clip = next(it) if have_clip else None
        lo = next(it) if have_hist else None
        hi = next(it) if have_hist else None
        st, shift = carry
        ts = _scan_state_single(X, y, w, distinct, clip, lo, hi,
                                bins=bins, corr_matrix=corr_matrix,
                                shift=shift, axis_name=BATCH_AXIS)
        return _merge_states(st, ts), shift

    n_extras = int(have_distinct) + int(have_clip) + 2 * int(have_hist)
    in_specs = (P(), P(BATCH_AXIS, None),
                P(BATCH_AXIS, None) if y2d else P(BATCH_AXIS),
                P(BATCH_AXIS)) + (P(),) * n_extras
    sm = build_shard_map(core, mesh, in_specs=in_specs, out_specs=P())
    # same donation rule as the single-device step: the replicated carry
    # aliases its output, so the [d, d] Gram accumulators update in place.
    # EXCEPT on a multi-process mesh: donating buffers into a program
    # whose psums run gloo cross-host collectives corrupts the CPU
    # client's heap on this jaxlib (observed: "corrupted double-linked
    # list" aborts on the second donated step) — the pod path keeps the
    # carry copy instead
    from ..parallel.mesh import mesh_is_multiprocess
    donate = () if mesh_is_multiprocess(mesh) else (0,)
    return jax.jit(sm, donate_argnums=donate)


@functools.lru_cache(maxsize=None)
def _tile_shift_sharded(mesh):
    from jax.sharding import PartitionSpec as P

    def core(X, w):
        return _first_tile_shift(X, w, X.shape[0],
                                 lambda v: jax.lax.psum(v, BATCH_AXIS))

    sm = build_shard_map(core, mesh, in_specs=(P(BATCH_AXIS, None),
                                               P(BATCH_AXIS)),
                         out_specs=P())
    return jax.jit(sm)


def _merge_states_host(a, b):
    """Host-side f64 Chan merge of two fetched states (streamed driver).
    Same arithmetic as _merge_states; numpy so a multi-hour stream never
    dispatches merge programs."""
    # host-side streamed-merge accumulators; device tiles stay f32
    f8 = np.float64  # tmoglint: disable=TPU003  host-only precision

    def arr(x):
        return np.asarray(x, f8)

    nA, nB = arr(a.cnt), arr(b.cnt)
    n = nA + nB
    safe = np.maximum(n, EPS)
    dxm = arr(b.mean) - arr(a.mean)
    dym = arr(b.ymean) - arr(a.ymean)
    cross = nA * nB / safe
    mean = arr(a.mean) + dxm * (nB / safe)
    m2 = arr(a.m2) + arr(b.m2) + dxm * dxm * cross
    cy = arr(a.cy) + arr(b.cy) + dxm * dym * cross
    ymean = arr(a.ymean) + dym * (nB / safe)
    ym2 = arr(a.ym2) + arr(b.ym2) + dym * dym * cross
    lA, lB = float(arr(a.ycnt)), float(arr(b.ycnt))
    ln = lA + lB
    lsafe = max(ln, EPS)
    ldm = float(arr(b.lmean)) - float(arr(a.lmean))
    lmean = float(arr(a.lmean)) + ldm * lB / lsafe
    lm2 = float(arr(a.lm2)) + float(arr(b.lm2)) + ldm * ldm * lA * lB / lsafe
    opt = {k: (None if getattr(a, k) is None
               else arr(getattr(a, k)) + arr(getattr(b, k)))
           for k in ("gzz", "gzv", "gvv", "cont", "hist")}
    return _State(
        wsum=float(arr(a.wsum)) + float(arr(b.wsum)), cnt=n, mean=mean,
        m2=m2, cy=cy, ymean=ymean, ym2=ym2,
        minv=np.minimum(arr(a.minv), arr(b.minv)),
        maxv=np.maximum(arr(a.maxv), arr(b.maxv)),
        nnz=arr(a.nnz) + arr(b.nnz), ycnt=ln, lmean=lmean, lm2=lm2,
        lmin=min(float(arr(a.lmin)), float(arr(b.lmin))),
        lmax=max(float(arr(a.lmax)), float(arr(b.lmax))), **opt)


def _fetch_state(st: _State) -> _State:
    return _State(*[None if x is None else np.asarray(x) for x in st])


# last streamed pass's pipeline stats (rows/tiles/peak-buffer): run_stats
# reads them for telemetry when the input was a RowSource whose row count
# is unknown before the pass
_last_stream_stats = None


def _stream_source(X, y, w, tile_rows: Optional[int]):
    """(source, tile_rows, d_probe) for the streamed driver. X may be a
    tileplane.RowSource yielding (x, y, w) chunks (y/w args must be None
    then) or a host array with companion y/w arrays."""
    from ..parallel import tileplane as TP

    if isinstance(X, TP.RowSource):
        if y is not None or w is not None:
            raise ValueError("pass y/w inside the RowSource chunks")
        x0 = X.peek()[0]
        d = int(x0.shape[1])
        c = int(tile_rows) if tile_rows else TP.tile_rows_for(4 * d,
                                                              X.n_rows)
        return X, c, d
    X = np.asarray(X)
    y = np.asarray(y)
    n, d = X.shape
    w_full = np.ones(n, np.float32) if w is None else \
        np.asarray(w, np.float32)
    c = int(tile_rows or min(stream_tile_rows_default(), max(n, 1)))
    return TP.ArraySource(X, y, w_full, chunk_rows=c), c, d


def stream_stats(X, y=None, w=None, *, tile_rows: Optional[int] = None,
                 distinct=None, clip=None, lo=None, hi=None, bins: int = 0,
                 corr_matrix: bool = False, mesh=None,
                 prefetch: Optional[int] = None):
    """Streamed row-tile driver for data larger than HBM.

    X may be a host array (with y/w arrays) or a `tileplane.RowSource`
    whose chunks yield (x, y, w) — e.g. the Avro/CSV reader adapter —
    so the matrix never materializes anywhere. Tiles flow through ONE
    fixed-shape jitted tile program via the double-buffered tileplane
    (parallel/tileplane.py): the producer thread device_puts tile k+1
    while the device merges tile k into the DEVICE-resident carry, which
    is fetched once at the end. With `mesh`, each tile is row-sharded
    over the batch axis and the tile step psum-merges across shards.
    The Gram shift comes from the first tile ON DEVICE (no second read
    of its rows). TMOG_TILEPLANE=0 restores the legacy synchronous loop
    with per-tile host f64 merge. Still exactly one read of every row of
    X per pass. `prefetch` overrides the tileplane ring depth for this
    pass (None = env > planner > hand default 1; bit-identical at any
    depth). Returns (merged host state, shift)."""
    from ..parallel import mesh as M
    from ..parallel import tileplane as TP

    global _last_stream_stats
    source, c, d = _stream_source(X, y, w, tile_rows)
    if corr_matrix and d > GRAM_MAX_D:
        raise ValueError(f"corr_matrix capped at {GRAM_MAX_D} columns")
    distinct_j = None if distinct is None else _as_f32(distinct)
    clip_j = None if clip is None else jnp.asarray(clip, bool)
    lo_j = None if lo is None else _as_f32(lo)
    hi_j = None if hi is None else _as_f32(hi)
    bins = int(bins)
    corr_matrix = bool(corr_matrix)
    big = float(np.finfo(np.float32).max)

    if not TP.tileplane_enabled() and (mesh is None
                                       or not M.mesh_is_multiprocess(mesh)):
        # legacy synchronous loop (kill switch): per-tile dispatch ->
        # fetch -> host f64 Chan merge; same tile content as the
        # pipeline (shared assembly), zero copy/compute overlap.
        # A multi-process mesh NEVER takes this branch — its psum is a
        # pod collective every process must join, so it falls through to
        # the mesh tile path (which run_tileplane already runs
        # synchronously when the shardings span processes)
        merged = None
        shift = None
        for tile, _n_valid in TP.iter_fixed_tiles(source, c):
            xt, yt, wt = (jnp.asarray(a) for a in tile)
            if shift is None:
                shift = _tile_shift_jit(xt, wt) if corr_matrix \
                    else jnp.zeros(d, jnp.float32)
            st = _stream_tile_jit(xt, yt, wt, shift, distinct_j, clip_j,
                                  lo_j, hi_j, bins=bins,
                                  corr_matrix=corr_matrix)
            st = _fetch_state(st)
            merged = st if merged is None else \
                _merge_states_host(merged, st)
        _last_stream_stats = None
        return merged, np.asarray(shift, np.float32) if shift is not None \
            else np.zeros(d, np.float32)

    # tileplane path: device-resident carry, double-buffered H2D
    probe = source.peek()
    y2d = probe[1].ndim == 2
    shardings = None
    pc = 1
    if mesh is not None:
        n_shards = mesh.shape[M.BATCH_AXIS]
        pc = M.mesh_process_count(mesh)
        if pc > 1:
            # SPMD streaming: `source` is THIS PROCESS's stripe of the
            # row stream. The tile step's psum is a pod collective, so
            # every process must run the SAME tile count with the SAME
            # (uniform) tile shape: size tiles from the pod-uniform
            # padded per-process row count (row_layout is itself the
            # pod's one host collective), then pad the local stream so
            # uneven stripes still emit identical tile sequences.
            from ..parallel import multihost as MH

            if source.n_rows is None:
                raise ValueError("multi-host streaming needs a source "
                                 "with a known n_rows (the local stripe "
                                 "row count)")
            layout = MH.row_layout(int(source.n_rows), mesh)
            if not tile_rows:
                c = TP.tile_rows_for(4 * d, layout.per_process * pc)
            c = -(-c // n_shards) * n_shards
            c_local = c // pc
            n_tiles = -(-layout.per_process // c_local)
            source = TP.PaddedSource(source, n_tiles * c_local)
        else:
            c = -(-c // n_shards) * n_shards
        shardings = (M.batch_sharding(mesh, ndim=2),
                     M.batch_sharding(mesh, ndim=2 if y2d else 1),
                     M.batch_sharding(mesh, ndim=1))
        step_fn = _tileplane_sharded_step(
            mesh, bins, corr_matrix, distinct is not None,
            clip is not None, lo is not None, y2d)
        shift_fn = _tile_shift_sharded(mesh)
    else:
        step_fn = functools.partial(_tileplane_step_jit, bins=bins,
                                    corr_matrix=corr_matrix)
        shift_fn = _tile_shift_jit

    extras = tuple(a for a in (distinct_j, clip_j, lo_j, hi_j)
                   if a is not None)
    if mesh is not None:
        if pc > 1:
            from ..parallel import multihost as MH
            extras = tuple(MH.replicated_global(np.asarray(a), mesh)
                           for a in extras)
        else:
            extras = tuple(jax.device_put(a, M.replicated(mesh))
                           for a in extras)

    def step(carry, xt, yt, wt):
        if mesh is not None:
            return step_fn(carry, xt, yt, wt, *extras)
        return step_fn(carry, xt, yt, wt, distinct_j, clip_j, lo_j, hi_j)

    first_tile = None
    if corr_matrix:
        def first_tile(carry, xt, yt, wt):
            return carry[0], shift_fn(xt, wt)

    carry0 = (_zero_state(d, corr_matrix=corr_matrix,
                          n_classes=0 if distinct is None
                          else int(np.asarray(distinct).shape[0]),
                          bins=bins, big=big),
              jnp.zeros(d, jnp.float32))
    if pc > 1:
        # a multi-process jit cannot adopt single-device carry leaves:
        # land them replicated over the global mesh up front
        from ..parallel import multihost as MH
        carry0 = jax.tree_util.tree_map(
            lambda a: MH.replicated_global(np.asarray(a), mesh), carry0)
    # depth resolved HERE (env > planner > hand default 1) so the pass
    # stats record the ring the pass actually ran with; depth never
    # changes tile boundaries, so results are bit-identical at any value
    depth = max(1, int(prefetch)) if prefetch else TP.tile_prefetch_depth()
    (st, shift), ps = TP.run_tileplane(
        source, step, carry0, tile_rows=c // pc, label="stats",
        first_tile=first_tile, shardings=shardings, prefetch=depth)
    _last_stream_stats = ps
    if pc > 1:
        # flight recorder: the ONE fetch of the pass is where a victim
        # rank absorbs its peers' lag (the tile psums are inside the
        # sharded step) — bracket it as the pass's collective window
        from ..parallel import podtrace
        with podtrace.collective("stats_fetch",
                                 rows=int(source.n_rows or 0),
                                 cols=int(d)):
            return _fetch_state(st), np.asarray(shift, np.float32)
    # the ONE fetch of the pass
    return _fetch_state(st), np.asarray(shift, np.float32)


# -- the routed, telemetry-emitting front door -------------------------------

_seen_shapes: set = set()


def run_stats(X, y=None, w=None, *, distinct=None, clip=None, lo=None,
              hi=None,
              bins: int = 0, corr_matrix: bool = False, mesh=None,
              driver: Optional[str] = None,
              tile_rows: Optional[int] = None,
              label: str = "stats") -> FusedStats:
    """One engine pass, finalized, timed and reported.

    Routing: `driver` in {"fused", "sharded", "streamed"} forces a route;
    otherwise `mesh` selects sharded, a host matrix larger than
    TMOG_STATS_STREAM_MB selects streamed, and everything else runs the
    single program. The pass is timed behind a block_until_ready fence
    and reported as a `stats_pass[<driver>]` kernel span (analytic bytes
    -> roofline attribution), a StatsPass telemetry record and a
    `stats_pass` event (utils/metrics.collector)."""
    from ..parallel.tileplane import RowSource
    from ..utils.metrics import collector

    src_mode = isinstance(X, RowSource)
    if src_mode:
        n, d = X.n_rows, None  # resolved after the pass
        y2d = False
        driver = "streamed"
    else:
        n, d = np.asarray(X).shape if isinstance(X, np.ndarray) else X.shape
        y2d = (np.asarray(y).ndim if isinstance(y, np.ndarray)
               else y.ndim) == 2
    if driver is None:
        if mesh is not None:
            driver = "sharded"
        elif isinstance(X, np.ndarray) and \
                X.nbytes > stream_threshold_bytes():
            driver = "streamed"
        else:
            driver = "fused"

    kw = dict(distinct=distinct, clip=clip, lo=lo, hi=hi, bins=bins,
              corr_matrix=corr_matrix)
    key = (driver, n, d, bins, corr_matrix, distinct is not None, y2d)
    cold = key not in _seen_shapes
    _seen_shapes.add(key)

    t0 = time.perf_counter()
    if driver == "sharded":
        if mesh is None:
            raise ValueError("driver='sharded' needs a mesh")
        st, shift = fused_stats_sharded(mesh, X, y, w, **kw)
        jax.block_until_ready(st)
    elif driver == "streamed":
        # mesh here selects the tileplane's shard_map lane (tiles
        # row-sharded over the batch axis, psum tile merge)
        st, shift = stream_stats(X, y, w, tile_rows=tile_rows, mesh=mesh,
                                 **kw)
        # host state: the pass already blocked on the final fetch
    else:
        st, shift = fused_stats(X, y, w, **kw)
        jax.block_until_ready(st)
    wall = time.perf_counter() - t0

    if driver == "streamed" and _last_stream_stats is not None:
        ps = _last_stream_stats
        n, tiles = ps.rows, ps.tiles
        d = int(np.asarray(st.cnt).shape[0])
    else:
        if d is None:
            d = int(np.asarray(st.cnt).shape[0])
        if n is None:
            n = int(round(float(np.asarray(st.wsum))))
        c = stats_row_block(d, n) if driver != "streamed" else \
            int(tile_rows or min(stream_tile_rows_default(), max(n, 1)))
        tiles = -(-n // c)
    bytes_hbm = stats_pass_bytes(n, d, y2d=y2d, weighted=w is not None)
    collector.stats_pass(driver=driver, rows=int(n), cols=int(d),
                         tiles=int(tiles), bytes_hbm=float(bytes_hbm),
                         wall_seconds=wall, cold=cold, label=label)
    return _finalize(st, shift, bins=int(bins))


# -- spearman rank pre-pass --------------------------------------------------

@jax.jit
def _rank_block_jit(Xc, y, w):
    """Tie-aware ranks of a COLUMN BLOCK plus the label re-ranked within
    each column's valid rows (pairwise-complete Spearman semantics,
    identical to ops/stats.spearman_with_label's inner vmap). Invalid
    entries rank NaN so the moment engine's finite mask drops them."""
    def per_col(col):
        wv = w * jnp.isfinite(col).astype(jnp.float32)
        cr = S._rank_with_nan(col, wv)
        yr = S._rank_with_nan(jnp.where(wv > 0, y, jnp.nan), wv)
        return cr, yr

    return jax.vmap(per_col, in_axes=1, out_axes=1)(Xc)


def rank_matrices(X, y, w=None, *, col_block: int = 128
                  ) -> Tuple[jax.Array, jax.Array]:
    """Blocked device rank pre-pass: (Rx [n, d], Ry [n, d]) ready for the
    moment engine's 2-D-label mode. Columns process in fixed-width blocks
    (ragged tail NaN-padded -> one executable), bounding the per-program
    sort workspace."""
    X = _as_f32(X)
    y = _as_f32(y)
    n, d = X.shape
    w = jnp.ones(n, jnp.float32) if w is None else _as_f32(w)
    cb = min(col_block, d)
    rx_parts, ry_parts = [], []
    for s in range(0, d, cb):
        xc = X[:, s:s + cb]
        if xc.shape[1] < cb:
            xc = jnp.pad(xc, ((0, 0), (0, cb - xc.shape[1])),
                         constant_values=jnp.nan)
        rx, ry = _rank_block_jit(xc, y, w)
        rx_parts.append(rx[:, :min(cb, d - s)])
        ry_parts.append(ry[:, :min(cb, d - s)])
    if len(rx_parts) == 1:
        return rx_parts[0], ry_parts[0]
    return jnp.concatenate(rx_parts, 1), jnp.concatenate(ry_parts, 1)


# recompile-tracker fallback (utils/tracing): on jax builds without
# jax.monitoring the tracker samples these entries' lowered-executable
# counts at span boundaries — the stats engine's "one program per shape"
# claim is exactly what the tracer verifies
from ..utils import tracing as _tracing  # noqa: E402

_tracing.register_jit_fallback(_fused_stats_jit, _stream_tile_jit,
                               _rank_block_jit, _tileplane_step_jit,
                               _tile_shift_jit)
