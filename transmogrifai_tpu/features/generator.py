"""FeatureGeneratorStage: the origin stage of every raw feature.

Reference: features/.../stages/FeatureGeneratorStage.scala:61 — holds the
user's extract function, the monoid aggregator, and the time window. Readers
call these to turn raw records into feature columns.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

from ..stages.base import PipelineStage
from ..types import FeatureType
from .aggregators import FeatureAggregator
from .feature import Feature


class FeatureGeneratorStage(PipelineStage):
    """Origin stage: record -> feature value."""

    input_types = ()  # source stage: extracts from raw records, no inputs

    def __init__(self, name: str, feature_type: Type[FeatureType],
                 extract_fn: Callable[[Any], Any],
                 is_response: bool = False,
                 aggregator: Optional[FeatureAggregator] = None,
                 event_time_fn: Optional[Callable[[Any], Optional[int]]] = None,
                 uid: Optional[str] = None):
        self.feature_name = name
        self.feature_type = feature_type
        self.extract_fn = extract_fn
        self.is_response = is_response
        self.aggregator = aggregator or FeatureAggregator(type_cls=feature_type)
        self.event_time_fn = event_time_fn
        # which reader's records this feature extracts from (JoinedReader
        # routing; set via FeatureBuilder.from_reader or directly)
        self.reader_hint: Optional[Any] = None
        super().__init__(operation_name=f"gen_{name}", uid=uid)
        self.output_type = feature_type

    def extract(self, record: Any) -> Any:
        """Extract the raw value from one record (row dict or object)."""
        v = self.extract_fn(record)
        if isinstance(v, FeatureType):
            return v.value
        return self.feature_type(v).value

    def get_output(self) -> Feature:
        return Feature(
            name=self.feature_name,
            feature_type=self.feature_type,
            is_response=self.is_response,
            origin_stage=self,
            parents=(),
        )

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(name=self.feature_name, type=self.feature_type.type_name(),
                 is_response=self.is_response)
        return d

    @classmethod
    def from_save_args(cls, args: Dict[str, Any]) -> "FeatureGeneratorStage":
        """Rebuilt generators extract by field name from dict records — the
        original user extract lambda is not persisted (same restriction as
        the reference: FeatureGeneratorStage extract functions must be
        re-supplied for retraining; scoring reads named columns)."""
        name = args["name"]
        tcls = FeatureType.from_name(args["type"])
        return cls(name=name, feature_type=tcls,
                   extract_fn=lambda rec: rec.get(name) if isinstance(rec, dict)
                   else getattr(rec, name, None),
                   is_response=bool(args.get("is_response", False)),
                   uid=args.get("uid"))
