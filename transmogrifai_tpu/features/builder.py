"""FeatureBuilder: the user API for declaring raw features.

Reference: features/.../FeatureBuilder.scala — e.g.
``FeatureBuilder.Real[Passenger].extract(_.getAge).asPredictor`` becomes::

    age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()

plus ``FeatureBuilder.from_dataset`` mirroring ``fromDataFrame:190`` (schema
auto-inference: every column becomes a feature of its inferred type, the
named response becomes RealNN).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..types import (
    Binary, City, ComboBox, Country, Currency, Date, DateTime, Email,
    FeatureType, Geolocation, ID, Integral, MultiPickList, OPVector, Percent,
    Phone, PickList, PostalCode, Real, RealNN, State, Street, Text, TextArea,
    TextList,
)
from .aggregators import FeatureAggregator, MonoidAggregator
from .feature import Feature
from .generator import FeatureGeneratorStage


class _TypedFeatureBuilder:
    def __init__(self, name: str, type_cls: Type[FeatureType]):
        self.name = name
        self.type_cls = type_cls
        self._extract_fn: Optional[Callable[[Any], Any]] = None
        self._aggregator: Optional[FeatureAggregator] = None
        self._window_ms: Optional[int] = None
        self._event_time_fn: Optional[Callable[[Any], Optional[int]]] = None

    def extract(self, fn: Callable[[Any], Any]) -> "_TypedFeatureBuilder":
        """Set the record->value extraction function
        (reference FeatureBuilder.extract:246)."""
        self._extract_fn = fn
        return self

    def aggregate(self, plus,
                  zero: Callable[[], Any] = lambda: None) -> "_TypedFeatureBuilder":
        """Monoid for event aggregation (reference FeatureBuilder
        .aggregate:283-302). Pass a callable plus (with optional zero) or
        a named default: "sum" | "min" | "max" | "last" | "first" |
        "union" | "mean" | "mode" | "concat" | "logical_and" |
        "logical_or" | "logical_xor" | "midpoint" ("first"/"last" follow
        event TIME, not encounter order)."""
        if isinstance(plus, str):
            from .aggregators import named_aggregator
            agg = named_aggregator(plus, self.type_cls)
        else:
            agg = MonoidAggregator(zero=zero, plus=plus)
        self._aggregator = FeatureAggregator(
            type_cls=self.type_cls, aggregator=agg)
        return self

    def window(self, ms: int) -> "_TypedFeatureBuilder":
        """Aggregation time window (reference FeatureBuilder.window:311)."""
        self._window_ms = ms
        return self

    def event_time(self, fn: Callable[[Any], Optional[int]]) -> "_TypedFeatureBuilder":
        self._event_time_fn = fn
        return self

    def _build(self, is_response: bool) -> Feature:
        if self._extract_fn is None:
            name = self.name
            self._extract_fn = lambda r: r.get(name) if isinstance(r, dict) \
                else getattr(r, name, None)
        agg = self._aggregator or FeatureAggregator(type_cls=self.type_cls,
                                                    window_ms=self._window_ms)
        if self._window_ms is not None:
            agg.window_ms = self._window_ms
        stage = FeatureGeneratorStage(
            name=self.name, feature_type=self.type_cls,
            extract_fn=self._extract_fn, is_response=is_response,
            aggregator=agg, event_time_fn=self._event_time_fn)
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)


class _FeatureBuilderMeta(type):
    """FeatureBuilder.<TypeName>(name) for every registered feature type."""

    def __getattr__(cls, type_name: str):
        try:
            tcls = FeatureType.from_name(type_name)
        except ValueError:
            raise AttributeError(type_name) from None
        return lambda name: _TypedFeatureBuilder(name, tcls)


class FeatureBuilder(metaclass=_FeatureBuilderMeta):
    """``FeatureBuilder.Real("age")``, ``FeatureBuilder.PickList("sex")``, ..."""

    @staticmethod
    def of(name: str, type_cls: Type[FeatureType]) -> _TypedFeatureBuilder:
        return _TypedFeatureBuilder(name, type_cls)

    # -- schema inference (reference fromDataFrame:190) --------------------
    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]], response: str,
                  non_nullable: Sequence[str] = ()) -> Tuple[Feature, List[Feature]]:
        """Infer a feature per key from example row dicts; the response
        becomes RealNN. Returns (response_feature, predictor_features)."""
        keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        feats: List[Feature] = []
        resp: Optional[Feature] = None
        for k in keys:
            vals = [r.get(k) for r in rows]
            tcls = RealNN if k == response else infer_feature_type(vals)
            b = _TypedFeatureBuilder(k, tcls).extract(_dict_getter(k, tcls))
            if k == response:
                resp = b.as_response()
            else:
                feats.append(b.as_predictor())
        if resp is None:
            raise ValueError(f"Response column '{response}' not found")
        return resp, feats


def _dict_getter(key: str, tcls: Type[FeatureType]) -> Callable[[Any], Any]:
    if issubclass(tcls, RealNN):
        return lambda r: float(r.get(key)) if r.get(key) is not None else 0.0
    return lambda r: r.get(key)


def infer_feature_type(values: Sequence[Any]) -> Type[FeatureType]:
    """Infer the FeatureType of a column from sample raw values (the analogue
    of fromDataFrame's schema mapping — here duck-typed since there is no
    Spark schema)."""
    non_null = [v for v in values if v is not None]
    if not non_null:
        return Text
    v = non_null[0]
    if isinstance(v, bool):
        return Binary
    if isinstance(v, (int, np.integer)):
        distinct = set(non_null)
        if distinct <= {0, 1}:
            return Binary
        return Integral
    if isinstance(v, (float, np.floating)):
        return Real
    if isinstance(v, str):
        distinct = {str(x) for x in non_null}
        if len(distinct) <= max(10, int(0.1 * len(non_null))) and len(distinct) < 100:
            return PickList
        return Text
    if isinstance(v, (list, tuple)):
        if v and isinstance(v[0], str):
            return TextList
        if len(v) == 3 and all(isinstance(x, (int, float)) for x in v):
            return Geolocation
        return TextList
    if isinstance(v, set):
        return MultiPickList
    if isinstance(v, dict):
        vv = next(iter(v.values()), None)
        from ..types import RealMap, TextMap
        return RealMap if isinstance(vv, (int, float)) else TextMap
    return Text
