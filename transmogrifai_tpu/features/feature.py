"""Feature: lazy, immutable DAG node.

Reference: features/.../FeatureLike.scala:48 (transformWith:210, traverse:309,
parentStages:363) and Feature.scala. A Feature names a typed column that will
exist once its origin stage runs; the workflow reconstructs the whole stage
DAG from result features by walking parents (OpWorkflow.setStagesDAG).

TransientFeature (reference TransientFeature.scala) — the serializable handle
that avoids dragging the whole graph into stage closures — is unnecessary
here (no JVM closure shipping), so stages hold plain (name, type, is_response)
handles produced by ``Feature.to_handle``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple, Type, TYPE_CHECKING

from ..types import FeatureType, OPVector, RealNN
from ..utils.uid import make_uid

if TYPE_CHECKING:
    from ..stages.base import PipelineStage


@dataclass(frozen=True)
class FeatureHandle:
    """Lightweight (name, typeName, isResponse) handle used inside stages
    (reference TransientFeature)."""
    name: str
    type_name: str
    is_response: bool = False

    @property
    def feature_type(self) -> Type[FeatureType]:
        return FeatureType.from_name(self.type_name)


@dataclass(frozen=True)
class FeatureHistory:
    """Provenance: originating raw features + stage chain
    (reference utils FeatureHistory)."""
    origin_features: Tuple[str, ...]
    stages: Tuple[str, ...]


class Feature:
    """A typed node in the feature lineage DAG."""

    def __init__(self, name: str, feature_type: Type[FeatureType],
                 is_response: bool = False,
                 origin_stage: Optional["PipelineStage"] = None,
                 parents: Sequence["Feature"] = (),
                 uid: Optional[str] = None):
        self.name = name
        self.feature_type = feature_type
        self.is_response = is_response
        self.origin_stage = origin_stage
        self.parents: Tuple[Feature, ...] = tuple(parents)
        self.uid = uid or make_uid("Feature")

    # -- basic protocol ----------------------------------------------------
    @property
    def is_raw(self) -> bool:
        from ..features.generator import FeatureGeneratorStage
        return self.origin_stage is None or isinstance(self.origin_stage, FeatureGeneratorStage)

    @property
    def type_name(self) -> str:
        return self.feature_type.type_name()

    def to_handle(self) -> FeatureHandle:
        return FeatureHandle(name=self.name, type_name=self.type_name,
                             is_response=self.is_response)

    def __repr__(self) -> str:
        return (f"Feature(name={self.name!r}, type={self.type_name}, "
                f"response={self.is_response}, raw={self.is_raw})")

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Feature) and other.uid == self.uid

    def __hash__(self) -> int:
        return hash(self.uid)

    # -- graph operations --------------------------------------------------
    def transform_with(self, stage: "PipelineStage", *others: "Feature") -> "Feature":
        """Apply a stage to (self, *others) yielding the stage's output feature
        (reference FeatureLike.transformWith:210-275)."""
        return stage.set_input(self, *others).get_output()

    def traverse(self, visit: Callable[["Feature"], None]) -> None:
        """Depth-first over ancestors, self first (reference traverse:309)."""
        seen: Set[str] = set()

        def go(f: "Feature") -> None:
            if f.uid in seen:
                return
            seen.add(f.uid)
            visit(f)
            for p in f.parents:
                go(p)

        go(self)

    def all_features(self) -> List["Feature"]:
        out: List[Feature] = []
        self.traverse(out.append)
        return out

    def raw_features(self) -> List["Feature"]:
        return [f for f in self.all_features() if f.is_raw]

    def parent_stages(self) -> Dict["PipelineStage", int]:
        """All ancestor stages with their distance from this feature
        (reference parentStages:363). Distance = max hops to this node."""
        dist: Dict[str, int] = {}
        stages: Dict[str, "PipelineStage"] = {}

        def go(f: "Feature", d: int) -> None:
            st = f.origin_stage
            if st is not None:
                if st.uid not in dist or dist[st.uid] < d:
                    dist[st.uid] = d
                    stages[st.uid] = st
            for p in f.parents:
                go(p, d + 1)

        go(self, 0)
        return {stages[u]: d for u, d in dist.items()}

    def history(self) -> FeatureHistory:
        origins: List[str] = []
        stage_uids: List[str] = []
        for f in self.all_features():
            if f.is_raw and f.name not in origins:
                origins.append(f.name)
            if f.origin_stage is not None and f.origin_stage.uid not in stage_uids:
                stage_uids.append(f.origin_stage.uid)
        return FeatureHistory(origin_features=tuple(sorted(origins)),
                              stages=tuple(stage_uids))

    def pretty_parent_stages(self, indent: int = 0) -> str:
        lines: List[str] = []

        def go(f: "Feature", depth: int) -> None:
            tag = f.origin_stage.stage_name if f.origin_stage else "raw"
            lines.append("  " * depth + f"+-- {f.name} [{f.type_name}] <- {tag}")
            for p in f.parents:
                go(p, depth + 1)

        go(self, indent)
        return "\n".join(lines)

    def copy_with(self, **kwargs: Any) -> "Feature":
        args = dict(name=self.name, feature_type=self.feature_type,
                    is_response=self.is_response, origin_stage=self.origin_stage,
                    parents=self.parents, uid=self.uid)
        args.update(kwargs)
        return Feature(**args)
