"""Monoid aggregators for event aggregation in readers.

Reference: features/.../aggregators/ (9 files, ~1,200 LoC on algebird):
MonoidAggregatorDefaults.scala:41 default table, Numerics.scala
(sum/min/max/mean/logical ops), Text.scala (concat with separator, mode),
Geolocation.scala (3D geographic midpoint), Maps.scala (per-key value
monoids), TimeBasedAggregator.scala (event-date first/last). Here: the
same palette as (prepare, zero, plus, present) quadruples per feature
type, applied host-side by the aggregate readers when collapsing many
events per key into one row.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Type

from ..types import (
    Binary, Currency, Date, DateList, DateTime, FeatureType, Geolocation,
    Integral, MultiPickList, OPList, OPMap, OPNumeric, OPSet, OPVector,
    Percent, PickList, Real, RealNN, Text, TextArea, TextList,
)


@dataclass
class MonoidAggregator:
    """prepare -> zero/plus fold -> present (reference algebird
    MonoidAggregator shape).

    ``prepare(value, time)`` lifts a raw extracted value (+ its event
    time) into the accumulator domain; ``plus`` is associative over that
    domain; ``present`` lowers the final accumulator back to a raw value.
    Constructing with just (zero, plus) keeps the legacy two-field form:
    identity prepare (value only) and identity present.
    """

    zero: Callable[[], Any]
    plus: Callable[[Any, Any], Any]
    prepare: Optional[Callable[[Any, Optional[int]], Any]] = None
    present: Optional[Callable[[Any], Any]] = None

    def reduce(self, values, times=None) -> Any:
        """Fold raw values; `times` is an optional parallel sequence of
        event times (time-aware aggregators read them via prepare).
        Values and times are SEPARATE sequences on purpose: a raw value
        may itself be a tuple (lat/lon pairs), so pair-packing would be
        ambiguous."""
        acc = self.zero()
        if times is None:
            for val in values:
                item = self.prepare(val, None) if self.prepare else val
                acc = self.plus(acc, item)
        else:
            for val, t in zip(values, times):
                item = self.prepare(val, t) if self.prepare else val
                acc = self.plus(acc, item)
        return self.present(acc) if self.present else acc


# -- option-lifted scalar monoids -------------------------------------------

def _sum_option(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a + b


def _union_list(a, b):
    return (a or []) + (b or [])


def _union_set(a, b):
    return (a or set()) | (b or set())


def _logical_or(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a or b


def _logical_and(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a and b


def _logical_xor(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return bool(a) ^ bool(b)


def _min_option(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_option(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


# -- mean (count-carrying pair monoid, reference MeanDouble) ----------------

def _mean_prepare(v, _t):
    return None if v is None else (float(v), 1)


def _percent_prepare(v, _t):
    """Reference PercentPrepare.prepareFn: clamp to [0, 1]."""
    if v is None:
        return None
    return (min(max(float(v), 0.0), 1.0), 1)


def _pair_sum(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return (a[0] + b[0], a[1] + b[1])


def _mean_present(acc):
    if acc is None or acc[1] == 0:
        return None
    return acc[0] / acc[1]


def mean_aggregator(percent: bool = False) -> MonoidAggregator:
    """Reference MeanReal/MeanCurrency/MeanPercent (Numerics.scala:102)."""
    return MonoidAggregator(
        zero=lambda: None, plus=_pair_sum,
        prepare=_percent_prepare if percent else _mean_prepare,
        present=_mean_present)


# -- time-based first/last (reference TimeBasedAggregator.scala) ------------
# Missing-time semantics: an untimed event can never beat a timed one
# (+inf for first / -inf for last); among untimed-only streams the tie
# rules reduce to encounter order (first keeps the earliest encountered,
# last the latest). The reference never faces the mix — its Event.date is
# always set — so this is the conservative extension.

def _first_prepare(v, t):
    return None if v is None else (t if t is not None else math.inf, v)


def _last_prepare(v, t):
    return None if v is None else (t if t is not None else -math.inf, v)


def _last_by_time(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return b if b[0] >= a[0] else a


def _first_by_time(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return b if b[0] < a[0] else a


def _timed_present(acc):
    return None if acc is None else acc[1]


def first_aggregator() -> MonoidAggregator:
    """Value of the EARLIEST event by event time (reference
    FirstAggregator)."""
    return MonoidAggregator(zero=lambda: None, plus=_first_by_time,
                            prepare=_first_prepare, present=_timed_present)


def last_aggregator() -> MonoidAggregator:
    """Value of the LATEST event by event time (reference LastAggregator)."""
    return MonoidAggregator(zero=lambda: None, plus=_last_by_time,
                            prepare=_last_prepare, present=_timed_present)


# -- text: concat + mode (reference Text.scala) -----------------------------

def concat_aggregator(separator: str = ",") -> MonoidAggregator:
    """ConcatTextWithSeparator (Text/TextArea use " ", others ",")."""
    def plus(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return f"{a}{separator}{b}"
    return MonoidAggregator(zero=lambda: None, plus=plus)


def mode_aggregator() -> MonoidAggregator:
    """ModePickList: the most frequent non-empty value (ties: the
    lexicographically smallest, deterministic like the reference's
    min-by over the count map)."""
    def prepare(v, _t):
        return {} if v is None else {v: 1}

    def plus(a, b):
        out = dict(a)
        for k, c in b.items():
            out[k] = out.get(k, 0) + c
        return out

    def present(acc):
        if not acc:
            return None
        top = max(acc.values())
        return min(k for k, c in acc.items() if c == top)

    return MonoidAggregator(zero=dict, plus=plus, prepare=prepare,
                            present=present)


# -- geolocation midpoint (reference Geolocation.scala) ---------------------

def _geo_prepare(v, _t):
    """(lat, lon[, acc]) -> unit-sphere (x, y, z, acc, count)."""
    if not v:
        return None
    lat = math.radians(float(v[0]))
    lon = math.radians(float(v[1]))
    acc = float(v[2]) if len(v) > 2 else 0.0
    return (math.cos(lat) * math.cos(lon),
            math.cos(lat) * math.sin(lon),
            math.sin(lat), acc, 1.0)


def _geo_plus(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return tuple(x + y for x, y in zip(a, b))


def _geo_present(acc):
    """Average 3D position back to (lat, lon, max-ish accuracy). The
    reference derives accuracy from the aggregate bounding-box width
    (GeolocationAccuracy.forRangeInUnits); carrying the mean input
    accuracy keeps the same 3-slot value shape with a simpler, monotone
    summary."""
    if acc is None or acc[4] == 0:
        return None
    n = acc[4]
    x, y, z = acc[0] / n, acc[1] / n, acc[2] / n
    lat = math.degrees(math.atan2(z, math.sqrt(x * x + y * y)))
    lon = math.degrees(math.atan2(y, x))
    return [lat, lon, acc[3] / n]


def geolocation_midpoint_aggregator() -> MonoidAggregator:
    """Geographic midpoint by unit-sphere averaging (reference
    GeolocationMidpoint: 'each list really represents just one object,
    so the default is the geographic midpoint')."""
    return MonoidAggregator(zero=lambda: None, plus=_geo_plus,
                            prepare=_geo_prepare, present=_geo_present)


# -- maps: per-key value monoids (reference Maps.scala) ---------------------

def map_value_aggregator(value_plus: Callable[[Any, Any], Any],
                         value_prepare: Optional[Callable] = None,
                         value_present: Optional[Callable] = None
                         ) -> MonoidAggregator:
    """Union maps whose shared keys combine by a VALUE monoid (reference
    UnionSumNumericMap / UnionMeanDoubleMap / UnionConcat*Map...)."""
    def prepare(v, t):
        if not v:
            return {}
        if value_prepare:
            return {k: value_prepare(x, t) for k, x in v.items()}
        return dict(v)

    def plus(a, b):
        out = dict(a)
        for k, x in b.items():
            out[k] = value_plus(out[k], x) if k in out else x
        return out

    def present(acc):
        if value_present:
            return {k: value_present(x) for k, x in acc.items()}
        return acc

    return MonoidAggregator(zero=dict, plus=plus, prepare=prepare,
                            present=present)


def _vector_combine(a, b):
    """CombineVector (OPVector.scala:43): concatenation."""
    if a is None:
        return b
    if b is None:
        return a
    import numpy as np
    return np.concatenate([np.asarray(a), np.asarray(b)])


def named_aggregator(name: str, type_cls: Type[FeatureType]
                     ) -> MonoidAggregator:
    """Named monoids (reference aggregator case objects):
    sum|min|max|last|first|union|mean|mode|concat|logical_and|logical_or|
    logical_xor|midpoint."""
    if name == "sum":
        return MonoidAggregator(lambda: None, _sum_option)
    if name == "min":
        return MonoidAggregator(lambda: None, _min_option)
    if name == "max":
        return MonoidAggregator(lambda: None, _max_option)
    if name == "last":
        return last_aggregator()
    if name == "first":
        return first_aggregator()
    if name == "mean":
        return mean_aggregator(percent=issubclass(type_cls, Percent))
    if name == "mode":
        return mode_aggregator()
    if name == "concat":
        sep = " " if issubclass(type_cls, (TextArea,)) \
            or type_cls is Text else ","
        return concat_aggregator(sep)
    if name == "logical_or":
        return MonoidAggregator(lambda: None, _logical_or)
    if name == "logical_and":
        return MonoidAggregator(lambda: None, _logical_and)
    if name == "logical_xor":
        return MonoidAggregator(lambda: None, _logical_xor)
    if name == "midpoint":
        return geolocation_midpoint_aggregator()
    if name == "union":
        if issubclass(type_cls, OPSet):
            return MonoidAggregator(lambda: set(), _union_set)
        if issubclass(type_cls, OPMap):
            return map_value_aggregator(lambda a, b: b)  # last per key
        return MonoidAggregator(lambda: [], _union_list)
    raise ValueError(
        f"Unknown aggregator name {name!r} (sum|min|max|last|first|union|"
        f"mean|mode|concat|logical_and|logical_or|logical_xor|midpoint)")


class MonoidAggregatorDefaults:
    """Default aggregator per feature type — the reference dispatch table
    (MonoidAggregatorDefaults.scala:56-115): numerics sum, Percent mean
    (clamped), Binary logical OR, Date/DateTime max, text concat,
    PickList mode, sets union, lists concat, Geolocation midpoint,
    OPVector combine; maps union with the matching VALUE monoid per key.
    """

    @staticmethod
    def aggregator_for(type_cls: Type[FeatureType]) -> MonoidAggregator:
        # maps first (an OPMap is not a Text); per-key value monoid echoes
        # the scalar default of the value type. issubclass dispatch,
        # most-specific first (PercentMap/CurrencyMap/Prediction ARE
        # RealMaps, DateTimeMap IS a DateMap), so user subclasses of any
        # numeric map inherit the numeric monoid instead of string concat
        if issubclass(type_cls, OPMap):
            from ..types import (
                BinaryMap, DateMap, GeolocationMap, MultiPickListMap,
                NumericMap, PercentMap, Prediction,
            )
            if issubclass(type_cls, GeolocationMap):
                return map_value_aggregator(
                    _geo_plus, value_prepare=_geo_prepare,
                    value_present=_geo_present)
            if issubclass(type_cls, MultiPickListMap):
                return map_value_aggregator(
                    lambda a, b: (set(a) | set(b)))
            if issubclass(type_cls, BinaryMap):
                return map_value_aggregator(_logical_or)
            if issubclass(type_cls, DateMap):
                return map_value_aggregator(_max_option)
            if issubclass(type_cls, (PercentMap, Prediction)):
                return map_value_aggregator(
                    _pair_sum,
                    value_prepare=(_percent_prepare
                                   if issubclass(type_cls, PercentMap)
                                   else _mean_prepare),
                    value_present=_mean_present)
            if issubclass(type_cls, NumericMap):
                return map_value_aggregator(_sum_option)
            # text-valued maps: per-key concat — " " for free-text
            # TextMap/TextAreaMap themselves, "," for the structured
            # subclasses (reference UnionConcat*Map, Maps.scala:139-152)
            from ..types import TextAreaMap, TextMap
            sep = " " if type_cls in (TextMap, TextAreaMap) else ","

            def _concat_kv(a, b, _s=sep):
                if a is None:
                    return b
                if b is None:
                    return a
                return f"{a}{_s}{b}"

            return map_value_aggregator(_concat_kv)
        if issubclass(type_cls, Binary):
            return MonoidAggregator(lambda: None, _logical_or)
        if issubclass(type_cls, (Date, DateTime)):
            return MonoidAggregator(lambda: None, _max_option)
        if issubclass(type_cls, Percent):
            return mean_aggregator(percent=True)
        if issubclass(type_cls, OPNumeric):
            return MonoidAggregator(lambda: None, _sum_option)
        if issubclass(type_cls, OPSet):  # includes MultiPickList
            return MonoidAggregator(set, _union_set)
        if issubclass(type_cls, Geolocation):
            return geolocation_midpoint_aggregator()
        if issubclass(type_cls, OPVector):
            return MonoidAggregator(lambda: None, _vector_combine)
        if issubclass(type_cls, OPList):
            return MonoidAggregator(list, _union_list)
        if issubclass(type_cls, PickList):
            return mode_aggregator()
        if issubclass(type_cls, (TextArea,)) or type_cls is Text:
            return concat_aggregator(" ")
        if issubclass(type_cls, Text):
            return concat_aggregator(",")
        return MonoidAggregator(lambda: None,
                                lambda a, b: b if b is not None else a)


@dataclass
class FeatureAggregator:
    """Aggregator + optional event-time window filter (reference
    FeatureAggregator / TimeBasedAggregator)."""

    type_cls: Type[FeatureType]
    aggregator: Optional[MonoidAggregator] = None
    window_ms: Optional[int] = None  # only events within window of cutoff

    def __post_init__(self):
        if self.aggregator is None:
            self.aggregator = MonoidAggregatorDefaults.aggregator_for(self.type_cls)

    def extract(self, events, event_time_fn=None, cutoff_time: Optional[int] = None,
                is_response: bool = False) -> Any:
        """Aggregate raw extracted values from events.

        Window predicate matches the reference exactly
        (GenericFeatureAggregator.filterByDateWithCutoff,
        features/.../aggregators/FeatureAggregator.scala:114-124):
        predictors keep ``cutoff - window <= t < cutoff``, responses keep
        ``cutoff <= t <= cutoff + window`` (windows optional). Event times
        flow into the aggregator (time-based first/last).
        """
        vals, times = [], []
        for ev_val, ev_time in events:
            if cutoff_time is not None and ev_time is not None:
                if is_response:
                    if ev_time < cutoff_time:
                        continue
                    if self.window_ms is not None and \
                            ev_time > cutoff_time + self.window_ms:
                        continue
                else:
                    if ev_time >= cutoff_time:
                        continue
                    if self.window_ms is not None and \
                            ev_time < cutoff_time - self.window_ms:
                        continue
            vals.append(ev_val)
            times.append(ev_time)
        return self.aggregator.reduce(vals, times)
