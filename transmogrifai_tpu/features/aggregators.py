"""Monoid aggregators for event aggregation in readers.

Reference: features/.../aggregators/ (MonoidAggregatorDefaults.scala:41,
TimeBasedAggregator, per-type aggregators) built on algebird. Here: plain
(zero, plus, present) triples per feature type, applied host-side by the
aggregate readers when collapsing many events per key into one row.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Type

from ..types import (
    Binary, Currency, Date, DateList, DateTime, FeatureType, Geolocation,
    Integral, MultiPickList, OPList, OPMap, OPNumeric, OPSet, Percent,
    Real, RealNN, Text, TextList,
)


@dataclass
class MonoidAggregator:
    """zero + associative plus over raw values (None = empty)."""

    zero: Callable[[], Any]
    plus: Callable[[Any, Any], Any]

    def reduce(self, values) -> Any:
        acc = self.zero()
        for v in values:
            acc = self.plus(acc, v)
        return acc


def _sum_option(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a + b


def _union_list(a, b):
    return (a or []) + (b or [])


def _union_set(a, b):
    return (a or set()) | (b or set())


def _union_map_last(a, b):
    out = dict(a or {})
    out.update(b or {})
    return out


def _logical_or(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a or b


def _min_option(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_option(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _last_option(a, b):
    return b if b is not None else a


def _first_option(a, b):
    return a if a is not None else b


def named_aggregator(name: str, type_cls: Type[FeatureType]
                     ) -> MonoidAggregator:
    """Named default monoids (reference MonoidAggregatorDefaults named
    aggregators): sum/min/max/last/first/union."""
    if name == "sum":
        return MonoidAggregator(lambda: None, _sum_option)
    if name == "min":
        return MonoidAggregator(lambda: None, _min_option)
    if name == "max":
        return MonoidAggregator(lambda: None, _max_option)
    if name == "last":
        return MonoidAggregator(lambda: None, _last_option)
    if name == "first":
        return MonoidAggregator(lambda: None, _first_option)
    if name == "union":
        if issubclass(type_cls, OPSet):
            return MonoidAggregator(lambda: set(), _union_set)
        if issubclass(type_cls, OPMap):
            return MonoidAggregator(lambda: {}, _union_map_last)
        return MonoidAggregator(lambda: [], _union_list)
    raise ValueError(f"Unknown aggregator name {name!r} "
                     f"(sum|min|max|last|first|union)")


class MonoidAggregatorDefaults:
    """Default aggregator per feature type (reference
    MonoidAggregatorDefaults.scala:41): numerics sum, booleans OR, text
    concatenates into lists? — the reference keeps *last* non-empty for plain
    text, unions for collections, min for Date (first event), sum for
    numerics."""

    @staticmethod
    def aggregator_for(type_cls: Type[FeatureType]) -> MonoidAggregator:
        if issubclass(type_cls, Binary):
            return MonoidAggregator(lambda: None, _logical_or)
        if issubclass(type_cls, (Date, DateTime)):
            return MonoidAggregator(lambda: None, _max_option)
        if issubclass(type_cls, OPNumeric):
            return MonoidAggregator(lambda: None, _sum_option)
        if issubclass(type_cls, (MultiPickList,)) or issubclass(type_cls, OPSet):
            return MonoidAggregator(set, _union_set)
        if issubclass(type_cls, Geolocation):
            # keep last non-empty location
            return MonoidAggregator(
                list, lambda a, b: b if b else a)
        if issubclass(type_cls, OPList):
            return MonoidAggregator(list, _union_list)
        if issubclass(type_cls, OPMap):
            return MonoidAggregator(dict, _union_map_last)
        if issubclass(type_cls, Text):
            # concatenate distinct-preserving: keep last non-empty
            return MonoidAggregator(lambda: None, lambda a, b: b if b is not None else a)
        return MonoidAggregator(lambda: None, lambda a, b: b if b is not None else a)


@dataclass
class FeatureAggregator:
    """Aggregator + optional event-time window filter (reference
    FeatureAggregator / TimeBasedAggregator)."""

    type_cls: Type[FeatureType]
    aggregator: Optional[MonoidAggregator] = None
    window_ms: Optional[int] = None  # only events within window of cutoff

    def __post_init__(self):
        if self.aggregator is None:
            self.aggregator = MonoidAggregatorDefaults.aggregator_for(self.type_cls)

    def extract(self, events, event_time_fn=None, cutoff_time: Optional[int] = None,
                is_response: bool = False) -> Any:
        """Aggregate raw extracted values from events.

        Predictors keep events at/before cutoff; responses keep events after
        (reference AggregateDataReader semantics, DataReader.scala:219-246).
        """
        vals = []
        for ev_val, ev_time in events:
            if cutoff_time is not None and ev_time is not None:
                if is_response:
                    if ev_time <= cutoff_time:
                        continue
                else:
                    if ev_time > cutoff_time:
                        continue
                    if self.window_ms is not None and ev_time < cutoff_time - self.window_ms:
                        continue
            vals.append(ev_val)
        return self.aggregator.reduce(vals)
