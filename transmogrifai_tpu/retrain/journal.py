"""Crash-safe controller journal: the retrain state machine's memory.

Append-only JSONL riding the EventLog discipline (one record per line,
`seq` strictly increasing, monotone across reopen) with one addition the
liveness log deliberately does not pay: every append is ``flush`` +
``fsync``, because the journal is CORRECTNESS state, not telemetry — a
``kill -9`` between any two controller transitions must leave a journal
from which the next incarnation resumes exactly once (no orphaned
challenger pool, no double rollout; docs/retraining.md "The journal").

Record shape::

    {"seq": N, "ts": epoch_s, "cycle": "rc-<hex>", "state": "<STATE>",
     ...transition fields}

A crash can tear at most the LAST line (single write + fsync per
record); replay skips an unparseable trailing line, so the resumed
controller sees the last transition that was durably recorded —
re-entering a state whose side effect may or may not have happened is
each state's own idempotence problem, solved in
controller.RetrainController.resume() (worker pid file, candidate-hash
probe against the live champion).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class RetrainJournal:
    """Append-only, fsync-per-record JSONL journal for one controller.

    Reopening an existing journal continues `seq` where the file left
    off (the EventLog contract), so a resumed controller's records
    interleave monotonically with its predecessor's."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._seq = 0
        for rec in self.records():
            s = rec.get("seq")
            if isinstance(s, int) and s >= self._seq:
                self._seq = s + 1
        # a crash can leave a TORN final line with no newline; appending
        # straight after it would weld the next record onto the garbage
        # and lose BOTH — terminate the torn tail first so it stays an
        # isolated unparseable line replay skips forever
        try:
            with open(path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() > 0:
                    fh.seek(-1, os.SEEK_END)
                    torn = fh.read(1) != b"\n"
                else:
                    torn = False
        except OSError:
            torn = False
        self._f = open(path, "a", encoding="utf-8")
        if torn:
            self._f.write("\n")
            self._f.flush()

    def append(self, cycle: Optional[str], state: str,
               **fields: Any) -> Dict[str, Any]:
        """Durably record one transition. Raises on I/O failure — a
        journal that cannot be written means the controller must NOT
        proceed to the state it was about to record (fail-stop beats
        resuming from a lie)."""
        with self._lock:
            rec: Dict[str, Any] = {"seq": self._seq,
                                   "ts": round(time.time(), 6),
                                   "cycle": cycle, "state": state}
            rec.update({k: v for k, v in fields.items() if v is not None})
            line = json.dumps(rec, default=str)
            # this lock EXISTS to serialize the durable line write (the
            # EventLog discipline): seq monotonicity + whole-line
            # atomicity across threads ARE the journal's contract, so
            # the I/O inside the critical section is the design
            # tmoglint: disable=THR002  serialized durable write IS the lock's job
            self._f.write(line + "\n")
            # tmoglint: disable=THR002  flush+fsync pair with the write
            self._f.flush()
            os.fsync(self._f.fileno())
            self._seq += 1
            return rec

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    # -- replay --------------------------------------------------------------
    def records(self) -> List[Dict[str, Any]]:
        """Every durable record, in order. A torn final line (crash
        mid-append) is skipped; a torn line anywhere else is skipped
        too (it cannot exist under the single-writer fsync discipline,
        but replay must not die on a corrupt file)."""
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            pass
        return out

    def last_cycle(self) -> Tuple[Optional[str], List[Dict[str, Any]]]:
        """(cycle id, that cycle's records in order) for the most recent
        cycle the journal names, or (None, []) for a fresh journal.
        Non-cycle records (controller start/stop marks) are ignored."""
        recs = self.records()
        last: Optional[str] = None
        for rec in recs:
            if rec.get("cycle"):
                last = rec["cycle"]
        if last is None:
            return None, []
        return last, [r for r in recs if r.get("cycle") == last]
