"""Drift-triggered continuous retraining (docs/retraining.md).

The loop ROADMAP item 4 names, closed: the drift monitor (monitor/)
detects that the world changed, this package retrains — a sandboxed
refit worker over the recent traffic window plus historical data, GLM
lanes warm-started from the serving model's coefficients and the sweep
narrowed to the champion's winning config — validates the candidate
behind a hard gate, and hands it to the fleet's zero-downtime
champion/challenger rollout (fleet/rollout.py). Every transition is
journaled (crash-safe resume, exactly one rollout) and every failure
class lands in quarantine with its evidence while the champion keeps
serving.

- :mod:`controller` — the RetrainController state machine
  (IDLE -> TRIGGERED -> FITTING -> VALIDATING -> ROLLING_OUT ->
  COOLDOWN, QUARANTINED for failed candidates), trigger debounce,
  storm breaker, fault containment;
- :mod:`refit` — the ``retrain-worker`` subprocess body, RefitSpec /
  retrain.json recipe contract, TMOG_RETRAIN_FAULT injection hooks;
- :mod:`journal` — the append+fsync transition journal.
"""
from .controller import (COOLDOWN, FITTING, IDLE, QUARANTINED,
                         ROLLING_OUT, TRIGGERED, VALIDATING,
                         RetrainConflict, RetrainController,
                         RetrainPolicy)
from .journal import RetrainJournal
from .refit import (FAULT_CLASSES, FAULT_ENV, RefitSpec, injected_fault,
                    load_recipe, run_refit, run_retrain_worker)

__all__ = [
    "RetrainController", "RetrainPolicy", "RetrainConflict",
    "RetrainJournal", "RefitSpec", "run_refit", "run_retrain_worker",
    "load_recipe", "injected_fault", "FAULT_ENV", "FAULT_CLASSES",
    "IDLE", "TRIGGERED", "FITTING", "VALIDATING", "ROLLING_OUT",
    "COOLDOWN", "QUARANTINED",
]
