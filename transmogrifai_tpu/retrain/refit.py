"""The sandboxed refit worker: one retrain candidate, one subprocess.

``python -m transmogrifai_tpu retrain-worker <spec.json>`` is the unit
the RetrainController launches (and kills, and retries): it fits ONE
candidate model from the recent traffic window plus historical data and
writes a ``candidate_report.json`` the controller's validation gate
reads. Running it as a real subprocess is the containment boundary —
a crashed, hung or OOM'd refit takes down exactly this process, never
the controller or the serving fleet, and the controller's timeout +
``kill`` always works because there is a pid to kill.

The refit recipe (``retrain.json`` next to the champion model, written
by the training pipeline) names a BUILDER — ``"module:function"``
returning an untrained :class:`~transmogrifai_tpu.workflow.Workflow` —
because a saved model artifact holds fitted transformers, not the
estimator recipe that produced them; the builder IS that recipe. The
worker then applies the two across-time shortcuts the ROADMAP names:

- **GLM warm start across time**: the champion's selected linear
  model's coefficients seed every lane of the streamed GLM round driver
  (ops/glm_sweep ``warm_seed`` — the PR 3 pathwise continuation applied
  across time instead of across the regularization path), so the refit
  starts near the serving model's optimum instead of at zero;
- **champion-config narrowing**: the hyperparameter grid collapses to
  the champion's winning (model, grid) cell (``narrow_to_champion``),
  which is how "trees re-swept at the champion config" lands — the
  sweep re-fits the winning config on fresh data rather than re-running
  model selection.

Fault injection (``TMOG_RETRAIN_FAULT``, docs/retraining.md): the hooks
tests and ci.sh use to PROVE containment at every stage. Each fires at
the stage it names and is inert when unset:

- ``fit_crash``     — the worker dies (exit 13) mid-fit;
- ``fit_hang``      — the worker sleeps past any timeout;
- ``bad_artifact``  — the candidate's op-model.json is corrupted after
  save (an artifact that exists but cannot be loaded);
- ``validation_fail`` — the candidate reports a holdout metric that
  cannot pass the gate.
"""
from __future__ import annotations

import importlib
import json
import logging
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_log = logging.getLogger("transmogrifai_tpu.retrain")

SPEC_JSON = "spec.json"
REPORT_JSON = "candidate_report.json"
RECIPE_JSON = "retrain.json"

#: the fault-injection env knob (docs/retraining.md "Fault injection")
FAULT_ENV = "TMOG_RETRAIN_FAULT"
FAULT_CLASSES = ("fit_crash", "fit_hang", "bad_artifact",
                 "validation_fail", "rollout_reject")


def injected_fault() -> Optional[str]:
    """The active fault class, or None. Unknown values are ignored (a
    typo'd chaos knob must not invent a new failure mode)."""
    v = os.environ.get(FAULT_ENV, "").strip().lower()
    return v if v in FAULT_CLASSES else None


@dataclass
class RefitSpec:
    """Everything one refit worker run needs, JSON round-trippable (the
    controller writes it into the cycle dir; the worker subprocess and
    a human post-mortem both read the same file)."""

    champion_dir: str
    out_dir: str
    builder: str                       # "module:function" -> Workflow
    history: List[str] = field(default_factory=list)   # labeled CSV/Avro
    window: Optional[str] = None       # recent-traffic records (CSV)
    holdout_fraction: float = 0.2
    seed: int = 7
    narrow_to_champion: bool = True
    warm_start: bool = True
    builder_path: Optional[str] = None  # sys.path entry for the builder

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "RefitSpec":
        keys = {f for f in RefitSpec("", "", "").__dict__}
        return RefitSpec(**{k: v for k, v in d.items() if k in keys})

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=1)
        return path

    @staticmethod
    def load(path: str) -> "RefitSpec":
        with open(path) as fh:
            return RefitSpec.from_json(json.load(fh))


def load_recipe(model_dir: str) -> Optional[Dict[str, Any]]:
    """The ``retrain.json`` recipe next to a model artifact ({"builder":
    "module:function", "history": [paths], optional "builder_path",
    "fraction", "min_shadow", "replicas"}), or None when the model has
    no refit recipe (the controller then refuses to auto-retrain)."""
    p = os.path.join(model_dir, RECIPE_JSON)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) and doc.get("builder") \
            else None
    except (OSError, json.JSONDecodeError):
        return None


# -- champion introspection ---------------------------------------------------

def champion_config(model: Any) -> Dict[str, Any]:
    """The champion's winning (model name, grid) + linear coefficients
    when its selected model is a linear family — the warm-start seed and
    the narrowed sweep cell. Tolerant: a champion without a selector (or
    with a tree winner) yields partial info and the refit proceeds
    without the missing shortcut."""
    out: Dict[str, Any] = {"best_model_name": None, "best_grid": None,
                           "coef": None, "intercept": None}
    summary = getattr(model, "selector_summary", lambda: None)()
    if summary is not None:
        out["best_model_name"] = summary.best_model_name
        out["best_grid"] = dict(summary.best_grid or {})
    sel = getattr(model, "_selected_model", lambda: None)()
    best = getattr(sel, "best_model", None)
    beta = getattr(best, "beta", None)
    if beta is not None:
        out["coef"] = np.asarray(beta, np.float32)
        out["intercept"] = float(getattr(best, "intercept", 0.0))
    return out


def find_selector(wf: Any) -> Any:
    """The built workflow's ModelSelector stage, or None."""
    from ..automl.selector import ModelSelector
    from ..workflow.dag import collect_features

    for f in collect_features(wf.result_features):
        if isinstance(f.origin_stage, ModelSelector):
            return f.origin_stage
    return None


def apply_champion_shortcuts(wf: Any, cfg: Dict[str, Any], *,
                             narrow: bool, warm: bool) -> Dict[str, Any]:
    """Mutate the built workflow's ModelSelector in place: narrow the
    sweep to the champion's winning cell and seed the GLM warm start.
    Returns {"narrowed": bool, "warm_seeded": bool} for the report."""
    applied = {"narrowed": False, "warm_seeded": False}
    selector = find_selector(wf)
    if selector is None:
        return applied
    if narrow and cfg.get("best_model_name"):
        kept = []
        for est, grids in selector.models:
            if type(est).__name__ == cfg["best_model_name"]:
                grid = cfg.get("best_grid") or {}
                kept.append((est, [dict(grid)] if grid else grids))
        if kept:
            selector.models = kept
            applied["narrowed"] = True
    if warm and cfg.get("coef") is not None:
        selector.warm_seed = {"beta": cfg["coef"],
                              "intercept": cfg.get("intercept", 0.0)}
        applied["warm_seeded"] = True
    return applied


# -- data assembly ------------------------------------------------------------

def _read_records(path: str) -> List[Dict[str, Any]]:
    if path.endswith(".avro"):
        from ..readers.avro import read_avro_file
        return list(read_avro_file(path))
    from ..readers.readers import CSVReader
    return CSVReader(path).read()


def assemble_training_records(spec: RefitSpec, label_name: str
                              ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
    """History records + the LABELED slice of the traffic window.

    Live /score traffic rarely carries labels; window records that do
    (a label feed joined upstream, or a smoke test that includes them)
    join the training set, the rest only serve the validation-gate
    monitor replay. Returns (records, provenance counts)."""
    records: List[Dict[str, Any]] = []
    counts = {"history_rows": 0, "window_rows": 0,
              "window_rows_labeled": 0}
    for p in spec.history:
        rows = _read_records(p)
        counts["history_rows"] += len(rows)
        records.extend(rows)
    if spec.window and os.path.exists(spec.window):
        rows = _read_records(spec.window)
        counts["window_rows"] = len(rows)
        labeled = [r for r in rows if r.get(label_name) is not None]
        counts["window_rows_labeled"] = len(labeled)
        records.extend(labeled)
    return records, counts


def holdout_split(records: List[Dict[str, Any]], fraction: float,
                  seed: int) -> Tuple[List[Dict[str, Any]],
                                      List[Dict[str, Any]]]:
    """Deterministic (seeded) train/holdout split — the gate compares
    candidate vs champion on the SAME holdout rows."""
    rng = np.random.default_rng(int(seed))
    n = len(records)
    k = int(round(n * float(fraction)))
    idx = rng.permutation(n)
    hold = set(int(i) for i in idx[:k])
    train = [r for i, r in enumerate(records) if i not in hold]
    held = [r for i, r in enumerate(records) if i in hold]
    return train, held


def gate_evaluator(problem_type: Optional[str]) -> Tuple[Any, str]:
    """(evaluator, metric name) for the validation gate's holdout
    comparison: AuPR for binary (the ISSUE's gate), error rate for
    multiclass, RMSE for regression."""
    from ..evaluators.evaluators import (BinaryClassificationEvaluator,
                                         MultiClassificationEvaluator,
                                         RegressionEvaluator)
    if problem_type == "multiclass":
        return MultiClassificationEvaluator(metric="error"), "error"
    if problem_type == "regression":
        return RegressionEvaluator(metric="rmse"), "rmse"
    return BinaryClassificationEvaluator(metric="au_pr"), "au_pr"


def holdout_metric(model: Any, records: List[Dict[str, Any]],
                   evaluator: Any, metric: str) -> Optional[float]:
    """One model's gate metric on the holdout records; None when it
    cannot be computed (empty holdout, degenerate labels)."""
    from ..readers.readers import ListReader
    if not records:
        return None
    try:
        ds = ListReader(records).generate_dataset(model.raw_features())
        out = model.evaluate(evaluator, ds)
        v = out.get(metric)
        return float(v) if v is not None and np.isfinite(v) else None
    except Exception:
        _log.exception("retrain: holdout evaluation failed")
        return None


# -- the worker body ----------------------------------------------------------

def _import_builder(spec: RefitSpec):
    mod_name, _, fn_name = spec.builder.partition(":")
    if not fn_name:
        raise ValueError(f"builder {spec.builder!r} is not "
                         f"'module:function'")
    for p in (spec.builder_path, os.path.dirname(os.path.abspath(
            os.path.join(spec.champion_dir, RECIPE_JSON)))):
        if p and p not in sys.path:
            sys.path.insert(0, p)
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name)
    return fn


def run_refit(spec: RefitSpec) -> Dict[str, Any]:
    """Fit one candidate per `spec`; returns the report it also writes
    to ``<out_dir>/candidate_report.json``. Raises on unrecoverable
    errors (the CLI maps them to a nonzero exit the controller books as
    a fit failure)."""
    from ..workflow.io import model_content_hash
    from ..workflow.workflow import WorkflowModel

    fault = injected_fault()
    t0 = time.monotonic()
    champion = WorkflowModel.load(spec.champion_dir)
    cfg = champion_config(champion)
    label_name = champion._response_name()

    builder = _import_builder(spec)
    wf = builder()
    applied = apply_champion_shortcuts(
        wf, cfg, narrow=spec.narrow_to_champion, warm=spec.warm_start)

    records, counts = assemble_training_records(spec, label_name)
    if not records:
        raise ValueError("refit has no training records (empty history "
                         "and unlabeled window)")
    train, held = holdout_split(records, spec.holdout_fraction, spec.seed)

    if fault == "fit_crash":
        _log.error("retrain-worker: injected fit_crash — dying mid-fit")
        os._exit(13)
    if fault == "fit_hang":
        _log.error("retrain-worker: injected fit_hang — sleeping past "
                   "any timeout")
        while True:  # the controller's timeout + kill is the exit
            time.sleep(3600.0)

    from ..readers.readers import ListReader
    model = wf.set_reader(ListReader(train)).train()
    model.save(spec.out_dir)  # writes monitor.json (profile rebuilt)
    # the candidate inherits the champion's refit recipe: once it SWAPS
    # in it IS the champion dir, and the next cycle (or a fleet started
    # fresh on it) must find retrain.json there — without this the
    # "continuous" loop would be one-shot
    recipe_src = os.path.join(spec.champion_dir, RECIPE_JSON)
    if os.path.exists(recipe_src):
        import shutil
        shutil.copy(recipe_src, os.path.join(spec.out_dir, RECIPE_JSON))

    if fault == "bad_artifact":
        _log.error("retrain-worker: injected bad_artifact — corrupting "
                   "the candidate's op-model.json")
        with open(os.path.join(spec.out_dir, "op-model.json"), "w") as fh:
            fh.write("{corrupt json the loader must refuse")

    # honesty check on the across-time warm start: the seed is only
    # ever CONSUMED by the IRLS rounds kernel, which returns the truth
    # as info["warm_seeded"] (a dimension-mismatched seed is ignored —
    # a new categorical level widens the design matrix and cold start
    # is the only honest option — and the squared-loss/Gram and legacy
    # routes never take a seed at all). Reporting the assignment alone
    # would claim a warm start the fit never took.
    if applied["warm_seeded"]:
        sel = find_selector(wf)
        tel = getattr(getattr(sel, "validator", None),
                      "last_streamed_telemetry", None) if sel else None
        applied["warm_seeded"] = bool(tel and tel.get("warm_seeded"))

    summary = model.selector_summary()
    problem = summary.problem_type if summary is not None else None
    evaluator, metric = gate_evaluator(problem)
    cand_metric = holdout_metric(model, held, evaluator, metric)
    champ_metric = holdout_metric(champion, held, evaluator, metric)
    if fault == "validation_fail":
        _log.error("retrain-worker: injected validation_fail — "
                   "reporting a gate-failing holdout metric")
        cand_metric = (0.0 if evaluator.is_larger_better(metric)
                       else float("1e9"))

    report = {
        "champion_dir": spec.champion_dir,
        "candidate_dir": spec.out_dir,
        "champion_hash": model_content_hash(spec.champion_dir),
        "candidate_hash": model_content_hash(spec.out_dir),
        "metric": metric,
        "metric_larger_better": bool(evaluator.is_larger_better(metric)),
        "candidate_metric": cand_metric,
        "champion_metric": champ_metric,
        "train_rows": len(train),
        "holdout_rows": len(held),
        "warm_seeded": applied["warm_seeded"],
        "narrowed": applied["narrowed"],
        "best_model_name": cfg.get("best_model_name"),
        "best_grid": cfg.get("best_grid"),
        "fault_injected": fault,
        "wall_s": round(time.monotonic() - t0, 3),
        **counts,
    }
    with open(os.path.join(spec.out_dir, REPORT_JSON), "w") as fh:
        json.dump(report, fh, indent=1, default=str)
    return report


def run_retrain_worker(args: Any) -> int:
    """Body of ``python -m transmogrifai_tpu retrain-worker`` (cli.py
    parses). Exit 0 on a written candidate + report, nonzero otherwise;
    the controller treats any nonzero exit (or timeout-kill) as a fit
    failure and retries with backoff."""
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
    spec = RefitSpec.load(args.spec)
    # the worker stamps its pid next to the spec so a RESUMED controller
    # (kill -9 mid-FITTING) can reap an orphaned worker before
    # relaunching — no two workers ever fit the same cycle
    pid_path = os.path.join(os.path.dirname(os.path.abspath(args.spec)),
                            "worker.pid")
    try:
        with open(pid_path, "w") as fh:
            fh.write(str(os.getpid()))
    except OSError:
        pass
    try:
        report = run_refit(spec)
    except Exception as e:  # noqa: BLE001 - the exit code IS the signal
        _log.exception("retrain-worker: refit failed")
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
              file=sys.stderr)
        return 1
    print(json.dumps(report, default=str))
    return 0
