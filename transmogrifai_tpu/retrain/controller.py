"""RetrainController: drift alert -> refit -> validate -> rollout,
with hard failure containment (docs/retraining.md).

The state machine (one cycle at a time)::

    IDLE -> TRIGGERED -> FITTING -> VALIDATING -> ROLLING_OUT -> COOLDOWN
                             |           |             |
                             v           v             v
                         QUARANTINED (cycle terminal; controller cools down)

- **Triggers**: ``drift_alert`` events tailed from an event log
  (utils/tracing.follow_events — rotation-safe), the fleet's pooled
  ``GET /drift`` verdict (a poll callable), or a manual ``POST
  /retrain``. Alerts are debounced: the per-window ``window_id``
  collapses a window's per-feature alert fan-out into one trigger, a
  ``model_content_hash`` mismatch drops stale alerts raised by a
  pre-swap model's monitor, `min_interval_s` cooldown separates cycles,
  and the storm breaker refuses more than `max_retrains_per_window`
  cycle starts per `storm_window_s` (a flapping feature cannot melt the
  training budget).
- **FITTING** is a sandboxed SUBPROCESS (retrain/refit.py) with a hard
  timeout and exponential-backoff retries: a crashed/hung/OOM'd refit
  takes down exactly one worker process, and the champion fleet never
  stops serving.
- **VALIDATING** is the gate between a candidate and traffic: the
  artifact must LOAD, the monitor profile must have been rebuilt, the
  holdout gate metric must be within tolerance of the champion ON THE
  SAME HOLDOUT, the offline ``monitor`` CLI must be green on a replay
  of the triggering traffic window, and a candidate byte-identical to a
  previously quarantined one is refused outright (nothing quarantined
  is ever retried verbatim).
- **ROLLING_OUT** hands the candidate to the fleet's existing
  shadow -> verdict -> swap path (fleet/rollout.RolloutManager,
  duck-typed) and waits for the terminal verdict.
- **QUARANTINED**: the whole cycle directory (spec, window snapshot,
  worker log, candidate artifact, report) moves to
  ``quarantine/<cycle>/`` and a ledger line records why — evidence
  preserved, champion untouched.

Crash safety: every transition is journaled (retrain/journal.py,
append+fsync) BEFORE its side effect starts, so ``kill -9`` of the
controller at any point resumes exactly once: a mid-FITTING kill reaps
the orphaned worker via its pid file and relaunches with the attempt
budget it had left; a mid-ROLLING_OUT kill first probes whether the
swap already landed (current champion hash == journaled candidate
hash) — if it did, the cycle completes without a second rollout, and if
it provably did not, exactly one recovery rollout runs.

Fault injection: ``TMOG_RETRAIN_FAULT=rollout_reject`` is handled HERE
(the other classes fire inside the worker): the verdict path is forced
to the rejected branch so tests and ci.sh can prove the containment of
a dirty shadow verdict without shipping a deliberately-bad model.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..utils.metrics import collector
from ..utils.tracing import follow_events
from ..workflow.io import model_content_hash
from . import refit as RF
from .journal import RetrainJournal

_log = logging.getLogger("transmogrifai_tpu.retrain")

IDLE = "idle"
TRIGGERED = "triggered"
FITTING = "fitting"
VALIDATING = "validating"
ROLLING_OUT = "rolling_out"
COOLDOWN = "cooldown"
QUARANTINED = "quarantined"

#: rollout terminal states the controller waits for (the fleet
#: RolloutManager's vocabulary)
_ROLLOUT_DONE = ("swapped", "rejected")
_ROLLOUT_LIVE = ("warming", "shadow")


class RetrainConflict(RuntimeError):
    """A retrain cycle is already in flight (or the trigger is
    suppressed by cooldown/storm policy without force): well-formed but
    cannot proceed NOW — the fleet frontend maps this to HTTP 409,
    mirroring RolloutConflict."""


@dataclass
class RetrainPolicy:
    """Debounce/containment knobs of one controller."""

    min_interval_s: float = 60.0      # cooldown between cycle starts
    storm_window_s: float = 3600.0    # storm-breaker lookback
    max_retrains_per_window: int = 4  # cycle starts per storm window
    fit_timeout_s: float = 900.0      # worker wall clock, then SIGKILL
    fit_attempts: int = 3             # total tries (1 + retries)
    backoff_base_s: float = 1.0       # exponential retry backoff
    backoff_cap_s: float = 30.0
    metric_tolerance: float = 0.02    # holdout gate slack vs champion
    require_monitor_green: bool = True  # offline replay gate on window
    monitor_timeout_s: float = 300.0  # replay subprocess budget
    sandbox_load_probe: bool = True   # artifact load gate in a child proc
    load_probe_timeout_s: float = 120.0  # load-probe subprocess budget
    rollout_timeout_s: float = 600.0  # shadow -> verdict budget
    rollout_fraction: float = 0.5     # shadow mirror fraction
    rollout_min_shadow: int = 64      # pairs before the verdict
    window_capacity: int = 4096       # traffic-tap ring bound

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class _Cycle:
    """One retrain cycle's context (reconstructable from the journal)."""

    def __init__(self, cycle_id: str, cycle_dir: str,
                 trigger: Optional[Dict[str, Any]] = None,
                 champion_dir: str = "", champion_hash: Optional[str] = None):
        self.id = cycle_id
        self.dir = cycle_dir
        self.trigger = trigger or {}
        self.champion_dir = champion_dir
        self.champion_hash = champion_hash
        self.attempt = 0
        self.report: Optional[Dict[str, Any]] = None
        self.candidate_hash: Optional[str] = None

    @property
    def spec_path(self) -> str:
        return os.path.join(self.dir, RF.SPEC_JSON)

    @property
    def candidate_dir(self) -> str:
        return os.path.join(self.dir, "candidate")

    @property
    def window_path(self) -> str:
        return os.path.join(self.dir, "window.csv")


class RetrainController:
    """Close the loop: drift alerts in, validated rollouts out.

    Collaborators are duck-typed for testability: `rollout` needs
    ``start(dir, fraction=, min_shadow=, replicas=)`` + ``status() ->
    {"state": ...}`` (the fleet RolloutManager fits); `launcher`
    (tests inject fakes) takes a spec path and returns a Popen-like
    object with poll/wait/kill; `champion_dir_fn` returns the model dir
    currently serving (it CHANGES after a swap). `alert_log` is an
    events.jsonl path to tail; `drift_poll` a callable returning the
    fleet's pooled /drift payload (either or both may be None)."""

    def __init__(self, champion_dir_fn: Callable[[], Optional[str]], *,
                 root: str,
                 rollout: Any = None,
                 policy: Optional[RetrainPolicy] = None,
                 recipe: Optional[Dict[str, Any]] = None,
                 launcher: Optional[Callable[[str], Any]] = None,
                 alert_log: Optional[str] = None,
                 drift_poll: Optional[Callable[[], Any]] = None,
                 drift_poll_interval_s: float = 2.0,
                 python: str = sys.executable,
                 env: Optional[Dict[str, str]] = None):
        self.champion_dir_fn = champion_dir_fn
        self.root = root
        self.rollout = rollout
        self.policy = policy or RetrainPolicy()
        self._recipe = recipe
        self._launcher = launcher or self._spawn_worker
        self.alert_log = alert_log
        self.drift_poll = drift_poll
        self.drift_poll_interval_s = float(drift_poll_interval_s)
        self.python = python
        self.env = dict(os.environ)
        if env:
            self.env.update(env)
        # every child (worker, monitor replay, load probe) must import
        # THIS package, wherever the parent was launched from
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        pp = self.env.get("PYTHONPATH")
        if not pp:
            self.env["PYTHONPATH"] = pkg_root
        elif pkg_root not in pp.split(os.pathsep):
            self.env["PYTHONPATH"] = pkg_root + os.pathsep + pp
        os.makedirs(root, exist_ok=True)
        self.quarantine_root = os.path.join(root, "quarantine")
        os.makedirs(self.quarantine_root, exist_ok=True)
        self.journal = RetrainJournal(os.path.join(root, "journal.jsonl"))
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self.state = IDLE
        self.cycle: Optional[_Cycle] = None
        self.last_verdict: Optional[Dict[str, Any]] = None
        self.cycles_total = 0
        self.swapped_total = 0
        self.quarantined_total = 0
        self.suppressed: Dict[str, int] = {}
        self._last_cycle_end = -float("inf")
        self._cycle_starts: "deque[float]" = deque(maxlen=256)
        #: (window_id, target, metric) triples already triggered/judged —
        #: the double-trigger dedupe (bounded)
        self._seen_alerts: "deque[Tuple]" = deque(maxlen=1024)
        self._seen_set: Set[Tuple] = set()
        #: champion-dir -> content hash (immutable artifacts; a swap
        #: changes the DIR) — _champion_hash runs per alert
        self._hash_cache: Dict[str, str] = {}
        #: one retrain_storm_breaker event per breaker episode (the
        #: poll re-delivers suppressed alerts every couple of seconds)
        self._storm_announced = False
        #: same discipline for "unconfigured": one evented suppression
        #: per missing-recipe episode, not one per poll delivery
        self._unconfigured_announced = False
        #: raw single-record /score bodies tapped off live traffic —
        #: the "recent traffic window" the refit and the replay gate see
        self._traffic: "deque[bytes]" = deque(
            maxlen=self.policy.window_capacity)
        self._cycle_thread: Optional[threading.Thread] = None
        self._alert_thread: Optional[threading.Thread] = None
        self._poll_thread: Optional[threading.Thread] = None
        self._load_quarantine_index()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RetrainController":
        """Resume any journaled in-flight cycle, then start the alert
        tail / drift poll threads."""
        self.resume()
        if self.alert_log is not None:
            self._alert_thread = threading.Thread(
                target=self._tail_loop, name="retrain-tail", daemon=True)
            self._alert_thread.start()
        if self.drift_poll is not None:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="retrain-poll", daemon=True)
            self._poll_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        for t in (self._alert_thread, self._poll_thread,
                  self._cycle_thread):
            if t is not None and t.is_alive():
                t.join(10.0)
        t = self._cycle_thread
        if t is None or not t.is_alive():
            self.journal.close()
        else:
            # a straggling cycle thread (a validation replay can run
            # minutes with no stop checks) may still need to journal
            # its pause state — closing under it would turn the append
            # into an exception; the fd dies with the process anyway
            _log.warning("retrain: close() leaving the journal open "
                         "for a still-running cycle thread")

    # -- traffic tap ---------------------------------------------------------
    def tap(self, body: bytes) -> None:
        """Record one successful single-record /score request body (the
        fleet frontend calls this post-reply). deque append is atomic
        and bounded — the request thread pays one append, nothing
        else."""
        self._traffic.append(body)

    # -- status --------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            cyc = self.cycle
            return {
                "state": self.effective_state(),
                "cycle": None if cyc is None else {
                    "id": cyc.id, "dir": cyc.dir,
                    "attempt": cyc.attempt,
                    "champion_dir": cyc.champion_dir,
                    "trigger": cyc.trigger},
                "last_verdict": self.last_verdict,
                "cycles_total": self.cycles_total,
                "swapped_total": self.swapped_total,
                "quarantined_total": self.quarantined_total,
                "suppressed": dict(self.suppressed),
                "cooldown_remaining_s": round(
                    max(self._cooldown_remaining(), 0.0), 3),
                "quarantine": self.quarantine_list(),
                "policy": self.policy.to_json(),
                "window_rows_tapped": len(self._traffic),
            }

    def effective_state(self) -> str:
        """COOLDOWN decays to IDLE once min_interval_s has passed."""
        with self._lock:
            if self.state == COOLDOWN and self._cooldown_remaining() <= 0:
                return IDLE
            return self.state

    def _cooldown_remaining(self) -> float:
        with self._lock:  # reentrant — callers already hold it
            return (self._last_cycle_end + self.policy.min_interval_s
                    - time.monotonic())

    # -- trigger paths -------------------------------------------------------
    def handle_alert(self, alert: Dict[str, Any]) -> Optional[str]:
        """One drift alert (event payload or pooled-/drift alert row):
        returns the suppression reason, or None when it started a
        cycle."""
        wid = alert.get("window_id")
        key = (wid, alert.get("target"), alert.get("metric")) \
            if wid else None
        # the (possibly first-per-champion) artifact sha256 runs before
        # the lock is taken — /healthz polls effective_state() under it
        champ_hash = self._champion_hash()
        with self._lock:
            if key is not None and key in self._seen_set:
                return self._suppress("duplicate", alert, log=False)
            stamped = alert.get("model_content_hash")
            # PERMANENT suppressions remember the key (the alert can
            # never become actionable — re-deliveries just spam);
            # TRANSIENT ones (busy/cooldown/storm/unconfigured) must
            # NOT: a pooled /drift poll re-delivers the same window_id
            # while it stays open, and that re-delivery is exactly what
            # lets a deferred trigger fire once the controller frees up
            if stamped and champ_hash and stamped != champ_hash:
                if key is not None:
                    self._remember(key)
                return self._suppress("stale_model", alert)
            if wid and (champ_hash, wid) in self._quarantined_triggers:
                if key is not None:
                    self._remember(key)
                return self._suppress("quarantined_trigger", alert)
            # transient suppressions are counted but not evented: the
            # pooled poll re-delivers the same alerts every couple of
            # seconds for as long as the condition lasts (a whole
            # 900s fit for "busy"), and per-delivery events would flood
            # the shared fleet log the liveness tooling consumes
            if self.state not in (IDLE, COOLDOWN):
                return self._suppress("busy", alert, log=False)
            if self._cooldown_remaining() > 0:
                return self._suppress("cooldown", alert, log=False)
            if self._storm_count() >= self.policy.max_retrains_per_window:
                if not self._storm_announced:
                    self._storm_announced = True
                    collector.event("retrain_storm_breaker",
                                    window_s=self.policy.storm_window_s,
                                    starts=self._storm_count())
                return self._suppress("storm_breaker", alert, log=False)
            self._storm_announced = False
            try:
                reserved = self._reserve_cycle()
            except RuntimeError as e:
                # announce ONCE per missing-recipe episode: the pooled
                # poll re-delivers the alert fan-out every couple of
                # seconds for as long as the recipe stays absent, and
                # per-delivery events would flood the shared fleet log
                announce = not self._unconfigured_announced
                self._unconfigured_announced = True
                if announce:
                    _log.warning("retrain: cannot start a cycle: %s", e)
                return self._suppress("unconfigured", alert,
                                      log=announce)
            self._unconfigured_announced = False
        # the heavy mint (window CSV, spec, journal fsync) runs outside
        # the lock; a failure rolls the reservation back to IDLE and the
        # un-remembered key lets the alert's re-delivery retry
        self._launch_cycle(reserved, trigger=alert, reason="drift_alert")
        with self._lock:
            if key is not None:
                self._remember(key)
        return None

    def trigger(self, reason: str = "manual",
                force: bool = False) -> Dict[str, Any]:
        """Manual trigger (``POST /retrain``). Raises RetrainConflict on
        a concurrent cycle, and — unless `force` — on cooldown/storm
        suppression. Returns status()."""
        with self._lock:
            if self.state not in (IDLE, COOLDOWN):
                raise RetrainConflict(
                    f"a retrain cycle is already {self.state}"
                    f" ({self.cycle.id if self.cycle else '?'})")
            if not force:
                if self._cooldown_remaining() > 0:
                    raise RetrainConflict(
                        f"cooling down for another "
                        f"{self._cooldown_remaining():.1f}s (force=true "
                        f"overrides)")
                if self._storm_count() >= \
                        self.policy.max_retrains_per_window:
                    raise RetrainConflict(
                        "storm breaker open: "
                        f"{self._storm_count()} retrains in the last "
                        f"{self.policy.storm_window_s:.0f}s (force=true "
                        "overrides)")
            reserved = self._reserve_cycle()
        self._launch_cycle(reserved, trigger={"reason": reason},
                           reason=reason)
        return self.status()

    def _remember(self, key: Tuple) -> None:
        if len(self._seen_alerts) == self._seen_alerts.maxlen:
            old = self._seen_alerts[0]
            self._seen_set.discard(old)
        self._seen_alerts.append(key)
        self._seen_set.add(key)

    def _suppress(self, reason: str, alert: Dict[str, Any],
                  log: bool = True) -> str:
        self.suppressed[reason] = self.suppressed.get(reason, 0) + 1
        if log:
            collector.event("retrain_suppressed", reason=reason,
                            window_id=alert.get("window_id"),
                            target=alert.get("target"),
                            metric=alert.get("metric"))
            _log.info("retrain: alert suppressed (%s): %s/%s", reason,
                      alert.get("target"), alert.get("metric"))
        return reason

    def _storm_count(self) -> int:
        cut = time.monotonic() - self.policy.storm_window_s
        return sum(1 for t in self._cycle_starts if t >= cut)

    def _champion_hash(self) -> Optional[str]:
        """Content hash of the CURRENT champion dir, cached per dir —
        artifacts are immutable once saved (a swap changes the dir, not
        the files), and this runs on every alert: without the cache a
        drifting window's per-feature fan-out re-sha256s a potentially
        huge arrays.npz once per alert per poll. Only the cache lookup/
        fill holds the lock; the sha256 of a multi-GB artifact must
        never run under it (``effective_state`` — and through it the
        fleet /healthz — blocks on the same lock)."""
        try:
            d = self.champion_dir_fn()
            if not d:
                return None
            with self._lock:
                h = self._hash_cache.get(d)
            if h is None:
                h = model_content_hash(d)
                if h:
                    with self._lock:
                        self._hash_cache[d] = h
            return h
        except Exception:
            return None

    # -- cycle machinery -----------------------------------------------------
    def _reserve_cycle(self) -> Tuple[str, Dict[str, Any]]:
        """Caller holds the lock. Validates that a trigger can become a
        cycle (champion + recipe exist — RuntimeError otherwise, the
        "unconfigured" path) and RESERVES the state machine: state
        flips to TRIGGERED so concurrent triggers conflict while the
        heavy mint (:meth:`_launch_cycle`) runs outside the lock."""
        champion_dir = self.champion_dir_fn()
        if not champion_dir:
            raise RuntimeError("no champion model dir to retrain")
        recipe = self._recipe or RF.load_recipe(champion_dir)
        if not recipe:
            raise RuntimeError(
                f"no retrain recipe: put {RF.RECIPE_JSON} next to "
                f"{champion_dir} (or configure the controller with one)")
        self.state = TRIGGERED
        return champion_dir, recipe

    def _launch_cycle(self, reserved: Tuple[str, Dict[str, Any]],
                      trigger: Dict[str, Any], reason: str) -> None:
        """The heavy half of a trigger, run WITHOUT the lock (window
        CSV, spec write, artifact hash, journal fsync — /healthz reads
        the state under the lock and must never wait on disk): mints
        the cycle, journals TRIGGERED, then commits the in-memory state
        and starts the cycle thread. ANY failure — journal append on a
        full disk included — rolls the TRIGGERED reservation back to
        IDLE and re-raises: a failed trigger must leave the controller
        retriggerable, never wedged in a stateless TRIGGERED."""
        champion_dir, recipe = reserved
        try:
            cycle_id = f"rc-{int(time.time()):x}-{os.urandom(3).hex()}"
            cycle_dir = os.path.join(self.root, "cycles", cycle_id)
            os.makedirs(cycle_dir, exist_ok=True)
            cyc = _Cycle(cycle_id, cycle_dir, trigger=trigger,
                         champion_dir=champion_dir,
                         champion_hash=self._champion_hash())
            window = self._snapshot_window(cyc.window_path)
            spec = RF.RefitSpec(
                champion_dir=champion_dir,
                out_dir=cyc.candidate_dir,
                builder=str(recipe["builder"]),
                history=[str(p) for p in recipe.get("history", [])],
                window=window,
                holdout_fraction=float(recipe.get("holdout_fraction",
                                                  0.2)),
                seed=int(recipe.get("seed", 7)),
                narrow_to_champion=bool(recipe.get("narrow_to_champion",
                                                   True)),
                warm_start=bool(recipe.get("warm_start", True)),
                builder_path=recipe.get("builder_path"))
            spec.save(cyc.spec_path)
            # journal BEFORE the in-memory commit: a failed append
            # leaves nothing to roll back but the reservation (a torn
            # line is terminated on the journal's next reopen)
            self.journal.append(cyc.id, TRIGGERED, cycle_dir=cyc.dir,
                                champion_dir=champion_dir,
                                champion_hash=cyc.champion_hash,
                                trigger=trigger, reason=reason)
        except BaseException:
            with self._lock:
                if self.state == TRIGGERED:
                    self.state = IDLE
            raise
        with self._lock:
            self._recipe_runtime = recipe  # rollout fraction etc.
            self.cycle = cyc
            self.cycles_total += 1
            self._cycle_starts.append(time.monotonic())
            self._cycle_thread = threading.Thread(
                target=self._run_cycle, args=(cyc, FITTING),
                name=f"retrain-{cyc.id}", daemon=True)
            t = self._cycle_thread
        collector.event("retrain_triggered", cycle=cyc.id, reason=reason,
                        window_id=trigger.get("window_id"),
                        target=trigger.get("target"),
                        champion_dir=champion_dir)
        _log.info("retrain: cycle %s TRIGGERED (%s) — champion %s",
                  cyc.id, reason, champion_dir)
        t.start()

    def _snapshot_window(self, path: str) -> Optional[str]:
        """The tapped traffic ring as one CSV (the refit's recent-window
        slice and the validation gate's replay file). None when no
        traffic was tapped."""
        bodies = list(self._traffic)
        records: List[Dict[str, Any]] = []
        keys: List[str] = []
        for b in bodies:
            try:
                rec = json.loads(b)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if not isinstance(rec, dict):
                continue  # bulk bodies are batch jobs, not the window
            flat = {k: v for k, v in rec.items()
                    if v is None or isinstance(v, (int, float, str, bool))}
            if not flat:
                continue
            records.append(flat)
            for k in flat:
                if k not in keys:
                    keys.append(k)
        if not records:
            return None
        import csv
        # runs WITHOUT the controller lock (inside _launch_cycle — the
        # CSV write must not stall /healthz readers of the state): the
        # TRIGGERED reservation serializes cycle mints, so exactly one
        # snapshot is ever in flight, and the deque's atomic append
        # means a tap racing the list() above lands in this cycle or
        # the next, never torn.
        with open(path, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=keys)
            w.writeheader()
            for r in records:
                w.writerow(r)
        return path

    # -- the cycle thread ----------------------------------------------------
    def _run_cycle(self, cyc: _Cycle, entry_state: str) -> None:
        try:
            if entry_state == FITTING:
                if not self._fit(cyc):
                    return  # quarantined inside
                entry_state = VALIDATING
            if entry_state == VALIDATING:
                if not self._validate(cyc):
                    return
                if self._stop.is_set():
                    # close() raced the (stop-check-free) validation:
                    # pause at the journaled VALIDATING state — resume
                    # re-validates and still rolls out exactly once
                    _log.info("retrain: cycle %s paused after "
                              "validation by controller stop; journal "
                              "will resume it", cyc.id)
                    return
                entry_state = ROLLING_OUT
            if entry_state == ROLLING_OUT:
                self._roll_out(cyc)
        except Exception as e:  # noqa: BLE001 - containment of last resort
            if self._stop.is_set():
                # a graceful close() raced this thread (e.g. the
                # journal closed under a long validation replay): an
                # operator restart must NEVER ban a candidate — leave
                # the journal's last state for resume() instead of
                # quarantining
                _log.warning("retrain: cycle %s interrupted by "
                             "controller stop (%s: %s); journal will "
                             "resume it", cyc.id, type(e).__name__, e)
                return
            _log.exception("retrain: cycle %s failed unexpectedly",
                           cyc.id)
            self._quarantine(cyc, f"controller_error: "
                                  f"{type(e).__name__}: {e}")

    def _set_state(self, cyc: _Cycle, state: str, **fields: Any) -> None:
        with self._lock:
            self.state = state
        self.journal.append(cyc.id, state, **fields)

    # FITTING ---------------------------------------------------------------
    def _spawn_worker(self, spec_path: str) -> Any:
        cmd = [self.python, "-m", "transmogrifai_tpu", "retrain-worker",
               spec_path]
        log_path = os.path.join(os.path.dirname(spec_path), "worker.log")
        with open(log_path, "ab") as lf:
            return subprocess.Popen(cmd, env=self.env, stdout=lf,
                                    stderr=lf)

    def _fit(self, cyc: _Cycle) -> bool:
        """FITTING with timeout + exponential-backoff retries. Returns
        True when a worker exited 0; quarantines and returns False when
        the attempt budget is spent."""
        while True:
            cyc.attempt += 1
            self._set_state(cyc, FITTING, attempt=cyc.attempt)
            collector.event("retrain_fit_started", cycle=cyc.id,
                            attempt=cyc.attempt)
            outcome = self._run_worker_once(cyc)
            if outcome is None:
                return True
            if self._stop.is_set():
                # GRACEFUL stop (close()/SIGTERM) is not a failure: the
                # journal still reads FITTING, so the next incarnation's
                # resume() re-enters this cycle — quarantining here
                # would permanently ban a candidate hash over an
                # operator restart
                _log.info("retrain: cycle %s paused mid-FITTING by "
                          "controller stop; journal will resume it",
                          cyc.id)
                return False
            if cyc.attempt >= self.policy.fit_attempts:
                self._quarantine(cyc, f"fit_failed after "
                                      f"{cyc.attempt} attempt(s): "
                                      f"{outcome}")
                return False
            backoff = min(self.policy.backoff_base_s
                          * (2 ** (cyc.attempt - 1)),
                          self.policy.backoff_cap_s)
            collector.event("retrain_fit_retry", cycle=cyc.id,
                            attempt=cyc.attempt, error=outcome,
                            backoff_s=round(backoff, 3))
            _log.warning("retrain: cycle %s fit attempt %d failed (%s);"
                         " retrying in %.1fs", cyc.id, cyc.attempt,
                         outcome, backoff)
            if self._stop.wait(backoff):
                _log.info("retrain: cycle %s paused mid-retry by "
                          "controller stop; journal will resume it",
                          cyc.id)
                return False

    def _run_worker_once(self, cyc: _Cycle) -> Optional[str]:
        """One worker launch; None on success, else the failure reason.
        The timeout path SIGKILLs the worker — a hung fit must not
        outlive its budget, and the champion never depended on it."""
        try:
            proc = self._launcher(cyc.spec_path)
        except Exception as e:  # noqa: BLE001
            return f"spawn failed: {type(e).__name__}: {e}"
        deadline = time.monotonic() + self.policy.fit_timeout_s
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if time.monotonic() >= deadline:
                _log.warning("retrain: cycle %s worker exceeded "
                             "fit_timeout_s=%.0f — killing", cyc.id,
                             self.policy.fit_timeout_s)
                try:
                    proc.kill()
                    proc.wait(10.0)
                except Exception:  # noqa: BLE001
                    pass
                return f"fit_timeout after {self.policy.fit_timeout_s}s"
            if self._stop.wait(0.1):
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001
                    pass
                return "controller stopped"
        if rc != 0:
            return f"fit_crash rc={rc}"
        return None

    # VALIDATING ------------------------------------------------------------
    def _validate(self, cyc: _Cycle) -> bool:
        self._set_state(cyc, VALIDATING)
        ok, reasons, report = self.validate_candidate(cyc)
        cyc.report = report
        cyc.candidate_hash = (report or {}).get("candidate_hash") or \
            model_content_hash(cyc.candidate_dir)
        if ok:
            collector.event("retrain_candidate_ready", cycle=cyc.id,
                            candidate_hash=cyc.candidate_hash,
                            metric=(report or {}).get("metric"),
                            candidate_metric=(report or {}).get(
                                "candidate_metric"),
                            champion_metric=(report or {}).get(
                                "champion_metric"))
            return True
        collector.event("retrain_validation_failed", cycle=cyc.id,
                        reasons="; ".join(reasons))
        self._quarantine(cyc, f"validation_failed: "
                              f"{'; '.join(reasons)}")
        return False

    def validate_candidate(self, cyc: _Cycle
                           ) -> Tuple[bool, List[str],
                                      Optional[Dict[str, Any]]]:
        """The gate, in order of cheapness. Every reason is recorded —
        a quarantined candidate's evidence names exactly which bar it
        missed."""
        reasons: List[str] = []
        report: Optional[Dict[str, Any]] = None
        rp = os.path.join(cyc.candidate_dir, RF.REPORT_JSON)
        try:
            with open(rp) as fh:
                report = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            reasons.append(f"candidate report unreadable: "
                           f"{type(e).__name__}")
        # artifact must LOAD (a corrupt op-model.json / arrays.npz must
        # never reach the rollout path, let alone traffic) — probed in
        # a child process: the worker's output is untrusted, and an
        # artifact whose load OOMs or segfaults must take down the
        # probe, never the serving fleet
        err = self._load_probe(cyc.candidate_dir)
        if err is not None:
            reasons.append(f"candidate artifact unloadable: {err}")
            return False, reasons, report
        if not os.path.exists(os.path.join(cyc.candidate_dir,
                                           "monitor.json")):
            reasons.append("candidate has no monitor.json (profile not "
                           "rebuilt; the new champion would serve "
                           "unmonitored)")
        if report is not None:
            cand = report.get("candidate_metric")
            champ = report.get("champion_metric")
            metric = report.get("metric", "au_pr")
            tol = self.policy.metric_tolerance
            if cand is None:
                reasons.append(f"holdout {metric} missing for the "
                               f"candidate")
            elif champ is not None:
                larger = bool(report.get("metric_larger_better", True))
                bad = (cand < champ - tol) if larger else \
                    (cand > champ + tol)
                if bad:
                    reasons.append(
                        f"holdout {metric} {cand:.4f} outside tolerance "
                        f"of champion {champ:.4f} (+/-{tol})")
        # nothing quarantined is ever retried verbatim
        chash = (report or {}).get("candidate_hash") or \
            model_content_hash(cyc.candidate_dir)
        with self._lock:
            repeat = bool(chash) and chash in self._quarantined_hashes
        if repeat:
            reasons.append(f"candidate {chash} is byte-identical to a "
                           f"quarantined one")
        if not reasons and self.policy.require_monitor_green:
            r = self._monitor_replay(cyc)
            if r is not None:
                reasons.append(r)
        return (not reasons), reasons, report

    _LOAD_PROBE_SRC = (
        "import sys\n"
        "from transmogrifai_tpu.workflow.workflow import WorkflowModel\n"
        "try:\n"
        "    WorkflowModel.load(sys.argv[1])\n"
        "except Exception as e:\n"
        "    sys.stderr.write(f'{type(e).__name__}: {e}')\n"
        "    sys.exit(4)\n"
    )

    def _load_probe(self, candidate_dir: str) -> Optional[str]:
        """Prove the candidate artifact loads, without loading it HERE.
        None = loadable; a reason string otherwise. The in-process
        fallback (``sandbox_load_probe=False``) exists for tests that
        drive the state machine with fakes — production controllers
        keep the boundary: untrusted bytes never deserialize inside
        the fleet frontend."""
        if not self.policy.sandbox_load_probe:
            try:
                from ..workflow.workflow import WorkflowModel
                WorkflowModel.load(candidate_dir)
            except Exception as e:  # noqa: BLE001
                return f"{type(e).__name__}: {e}"
            return None
        cmd = [self.python, "-c", self._LOAD_PROBE_SRC, candidate_dir]
        try:
            proc = subprocess.run(
                cmd, env=self.env, capture_output=True, text=True,
                timeout=self.policy.load_probe_timeout_s)
        except subprocess.TimeoutExpired:
            return (f"load probe exceeded "
                    f"{self.policy.load_probe_timeout_s}s")
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip()[-300:]
            return tail or f"load probe died rc={proc.returncode}"
        return None

    def _monitor_replay(self, cyc: _Cycle) -> Optional[str]:
        """The offline ``monitor`` CLI over the triggering traffic
        window, against the CANDIDATE's rebuilt profile: the drift that
        triggered this cycle must be GONE on the candidate. None =
        green; a reason string otherwise. No window snapshot = nothing
        to replay (manual triggers on idle fleets)."""
        if not os.path.exists(cyc.window_path):
            return None
        cmd = [self.python, "-m", "transmogrifai_tpu", "monitor",
               cyc.candidate_dir, cyc.window_path, "--fail-on-drift"]
        try:
            proc = subprocess.run(
                cmd, env=self.env, capture_output=True, text=True,
                timeout=self.policy.monitor_timeout_s)
        except subprocess.TimeoutExpired:
            return (f"monitor replay exceeded "
                    f"{self.policy.monitor_timeout_s}s")
        if proc.returncode == 3:
            return ("monitor replay still drifting on the triggering "
                    "window (the candidate did not learn the shift)")
        if proc.returncode != 0:
            return (f"monitor replay failed rc={proc.returncode}: "
                    f"{proc.stderr[-300:]}")
        return None

    # ROLLING_OUT -----------------------------------------------------------
    def _roll_out(self, cyc: _Cycle) -> None:
        """Hand the candidate to the fleet's shadow -> verdict -> swap
        path. The ROLLING_OUT journal record lands BEFORE start() so a
        crash anywhere in here resumes into the exactly-once probe."""
        self._set_state(cyc, ROLLING_OUT,
                        candidate_dir=cyc.candidate_dir,
                        candidate_hash=cyc.candidate_hash)
        recipe = getattr(self, "_recipe_runtime", None) or self._recipe \
            or RF.load_recipe(cyc.champion_dir) or {}
        fraction = float(recipe.get("fraction",
                                    self.policy.rollout_fraction))
        min_shadow = int(recipe.get("min_shadow",
                                    self.policy.rollout_min_shadow))
        replicas = recipe.get("replicas")
        # the recipe's rollout_* keys relax the shadow-verdict
        # comparison for THIS cycle's adapted candidate only — passed
        # per start() so operator-initiated rollouts keep the fleet's
        # base guards (only when present: duck-typed fakes need not
        # grow the kwarg)
        thresholds = {k[len("rollout_"):]: float(recipe[k])
                      for k in ("rollout_max_pred_js", "rollout_max_psi",
                                "rollout_max_score_shift")
                      if recipe.get(k) is not None}
        start_kw: Dict[str, Any] = dict(fraction=fraction,
                                        min_shadow=min_shadow,
                                        replicas=replicas)
        if thresholds:
            start_kw["thresholds"] = thresholds
        collector.event("retrain_rollout_started", cycle=cyc.id,
                        candidate_dir=cyc.candidate_dir,
                        fraction=fraction, min_shadow=min_shadow)
        if RF.injected_fault() == "rollout_reject":
            _log.error("retrain: injected rollout_reject — forcing the "
                       "dirty-verdict branch")
            self._rollout_rejected(cyc, {"reasons": ["injected "
                                                     "rollout_reject"]})
            return
        if self.rollout is None:
            self._quarantine(cyc, "no rollout manager configured")
            return
        deadline = time.monotonic() + self.policy.rollout_timeout_s
        while True:
            try:
                self.rollout.start(cyc.candidate_dir, **start_kw)
                break
            except Exception as e:  # noqa: BLE001
                # a CONFLICT (another rollout holds the slot right now)
                # is transient — waiting for the slot is right, exactly
                # like an HTTP client retrying the 409; judged by name
                # to stay duck-typed (tests drive fakes, and importing
                # fleet.rollout here would cycle through fleet/__init__
                # -> frontend -> this module). Anything else (broken
                # artifact, spawn failure) is terminal: quarantine.
                if (type(e).__name__ == "RolloutConflict"
                        and time.monotonic() < deadline
                        and not self._stop.is_set()):
                    _log.info("retrain: cycle %s rollout slot busy "
                              "(%s); waiting", cyc.id, e)
                    if not self._stop.wait(1.0):
                        continue
                if self._stop.is_set():
                    _log.info("retrain: cycle %s paused before rollout "
                              "start by controller stop; journal will "
                              "resume it", cyc.id)
                    return
                # a deadline-expired CONFLICT is still slot contention
                # (someone else held the rollout for the whole budget)
                # — not the candidate's fault: keep the evidence but
                # don't ban the hash/trigger, the same candidate may
                # ship once the slot frees up
                self._quarantine(
                    cyc, f"rollout start failed: "
                         f"{type(e).__name__}: {e}",
                    ban=type(e).__name__ != "RolloutConflict")
                return
        self._await_rollout(cyc)

    def _await_rollout(self, cyc: _Cycle) -> None:
        deadline = time.monotonic() + self.policy.rollout_timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            st = (self.rollout.status() or {}).get("state")
            if st in _ROLLOUT_DONE:
                break
            time.sleep(0.1)
        status = self.rollout.status() or {}
        st = status.get("state")
        # attribute the verdict to THIS cycle only when the manager
        # names OUR candidate: a terminal state can belong to someone
        # else's rollout (ours died, an operator took the slot) and
        # must not book a swap — or a hash-banning rejection — onto
        # this cycle. Duck-typed fakes that report no challenger_dir
        # are trusted (they only ever run our candidate).
        ro_dir = status.get("challenger_dir")
        ours = ro_dir is None or ro_dir == cyc.candidate_dir
        if st == "swapped" and ours:
            self._swapped(cyc, status.get("last_verdict"))
        elif st == "rejected" and ours:
            self._rollout_rejected(cyc, status.get("last_verdict")
                                    or {"reasons": ["rollout rejected"]})
        elif self._stop.is_set():
            # GRACEFUL stop with the rollout still live: leave it and
            # the journal's ROLLING_OUT record alone — resume() probes
            # swap-landed / still-live / dead and takes exactly one
            # recovery path. Quarantining a validated candidate over an
            # operator restart would ban its hash forever.
            _log.info("retrain: cycle %s paused mid-ROLLING_OUT by "
                      "controller stop; journal will resume it", cyc.id)
        else:
            if ours:  # never abort someone ELSE's live rollout
                try:
                    self.rollout.abort()
                except Exception:  # noqa: BLE001
                    pass
                # the verdict can land in the race window between the
                # status read above and abort()'s state guard (which
                # no-ops on a terminal rollout): re-read BEFORE
                # quarantining — moving cycles/<id>/ after the swap
                # landed would relocate the SERVING champion's model
                # dir out from under the fleet
                status = self.rollout.status() or {}
                st2 = status.get("state")
                ro_dir = status.get("challenger_dir")
                verdict2 = status.get("last_verdict") or {}
                if ro_dir is None or ro_dir == cyc.candidate_dir:
                    if st2 == "swapped":
                        self._swapped(cyc, status.get("last_verdict"))
                        return
                    if st2 == "rejected" and not verdict2.get("aborted"):
                        # a REAL shadow verdict landed in the race (our
                        # abort no-oped against it) — book the
                        # rejection; our own abort landing instead
                        # falls through to the honest timeout reason
                        self._rollout_rejected(
                            cyc, verdict2
                            or {"reasons": ["rollout rejected"]})
                        return
            # no verdict inside the budget (thin shadow traffic, or a
            # foreign rollout holding the slot) is not the candidate's
            # fault — keep the evidence, don't ban the hash/trigger
            self._quarantine(cyc, f"rollout did not reach a verdict "
                                  f"within "
                                  f"{self.policy.rollout_timeout_s}s "
                                  f"(state {st})", ban=False)

    def _swapped(self, cyc: _Cycle, verdict: Any) -> None:
        with self._lock:
            self.swapped_total += 1
            self.last_verdict = {"cycle": cyc.id, "outcome": "swapped",
                                 "candidate_dir": cyc.candidate_dir,
                                 "candidate_hash": cyc.candidate_hash,
                                 "verdict": verdict,
                                 "report": cyc.report}
        collector.event("retrain_swapped", cycle=cyc.id,
                        candidate_dir=cyc.candidate_dir,
                        candidate_hash=cyc.candidate_hash)
        _log.info("retrain: cycle %s SWAPPED -> %s", cyc.id,
                  cyc.candidate_dir)
        self._finish(cyc, COOLDOWN)

    def _rollout_rejected(self, cyc: _Cycle, verdict: Dict) -> None:
        collector.event("retrain_rollout_rejected", cycle=cyc.id,
                        reasons="; ".join(verdict.get("reasons", [])))
        # an OPERATOR abort (verdict marker from RolloutManager.abort)
        # is not the candidate's fault: quarantine the cycle's evidence
        # but do NOT ban the hash/trigger — the same candidate may ship
        # on the next cycle once the slot frees up
        self._quarantine(cyc, f"rollout_rejected: "
                              f"{'; '.join(verdict.get('reasons', []))}",
                         verdict=verdict,
                         ban=not verdict.get("aborted", False))

    # QUARANTINE / COOLDOWN --------------------------------------------------
    def _quarantine(self, cyc: _Cycle, reason: str,
                    verdict: Any = None, ban: bool = True) -> None:
        """Move the cycle's whole evidence trail into quarantine, ledger
        it, cool down. The champion was never touched. `ban=False`
        (operator abort) keeps the evidence but leaves candidate_hash /
        window_id out of the ledger entry, so neither this incarnation
        nor a resumed one (the index rebuilds FROM the ledger) refuses
        the candidate or the trigger later — the failure was not the
        candidate's."""
        dest = os.path.join(self.quarantine_root, cyc.id)
        try:
            if os.path.isdir(cyc.dir):
                shutil.move(cyc.dir, dest)
        except OSError:
            _log.exception("retrain: quarantine move failed for %s",
                           cyc.id)
            dest = cyc.dir  # evidence stays where it is
        chash = cyc.candidate_hash if ban else None
        entry = {"cycle": cyc.id, "reason": reason, "dir": dest,
                 "candidate_hash": chash,
                 "champion_hash": cyc.champion_hash,
                 "window_id": ((cyc.trigger or {}).get("window_id")
                               if ban else None),
                 "ts": round(time.time(), 3)}
        try:
            with open(os.path.join(self.quarantine_root,
                                   "ledger.jsonl"), "a") as fh:
                fh.write(json.dumps(entry, default=str) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            _log.exception("retrain: quarantine ledger write failed")
        with self._lock:
            self.quarantined_total += 1
            self._quarantine_entries.append(entry)
            if chash:
                self._quarantined_hashes.add(chash)
            wid = entry["window_id"]
            if wid:
                self._quarantined_triggers.add((cyc.champion_hash, wid))
            self.last_verdict = {"cycle": cyc.id,
                                 "outcome": "quarantined",
                                 "reason": reason, "dir": dest,
                                 "verdict": verdict,
                                 "report": cyc.report}
        self.journal.append(cyc.id, QUARANTINED, reason=reason,
                            quarantine_dir=dest, candidate_hash=chash)
        collector.event("retrain_quarantined", cycle=cyc.id,
                        reason=reason, quarantine_dir=dest)
        _log.warning("retrain: cycle %s QUARANTINED (%s) — evidence in "
                     "%s; champion untouched", cyc.id, reason, dest)
        self._finish(cyc, COOLDOWN)

    def _finish(self, cyc: _Cycle, state: str) -> None:
        with self._lock:
            self.state = state
            self._last_cycle_end = time.monotonic()
            self.cycle = None
        self.journal.append(cyc.id, COOLDOWN)

    def quarantine_list(self) -> List[Dict[str, Any]]:
        """The ledger, from the in-memory mirror (loaded once at
        construction, appended in _quarantine): status()/GET /retrainz
        poll this — re-parsing the whole JSONL under the controller
        lock per poll would contend with trigger handling and grow
        with the ledger."""
        with self._lock:
            return list(self._quarantine_entries)

    @staticmethod
    def _read_ledger(path: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            pass
        return out

    def _load_quarantine_index(self) -> None:
        entries = self._read_ledger(os.path.join(self.quarantine_root,
                                                 "ledger.jsonl"))
        hashes: Set[str] = set()
        triggers: Set[Tuple] = set()
        for e in entries:
            if e.get("candidate_hash"):
                hashes.add(e["candidate_hash"])
            if e.get("window_id"):
                triggers.add((e.get("champion_hash"), e["window_id"]))
        with self._lock:  # cycle + trigger threads read these sets
            self._quarantine_entries = entries
            self._quarantined_hashes = hashes
            self._quarantined_triggers = triggers

    # -- crash resume --------------------------------------------------------
    def resume(self) -> Dict[str, Any]:
        """Replay the journal; re-enter an in-flight cycle EXACTLY
        ONCE. Returns a description of what happened (tests assert on
        it). Idempotent for a clean journal."""
        cycle_id, recs = self.journal.last_cycle()
        if cycle_id is None or not recs:
            return {"resumed": False, "reason": "empty journal"}
        last = recs[-1]
        st = last.get("state")

        def _ended_ago() -> float:
            """Wall seconds since the journal's last record — restart
            downtime COUNTS toward the cooldown (restarting the fleet a
            day after the last cycle must not re-impose a full
            min_interval_s before a real alert can trigger)."""
            ts = last.get("ts")
            if isinstance(ts, (int, float)):
                return max(0.0, time.time() - float(ts))
            return 0.0

        if st in (COOLDOWN, None):
            # the cycle finished; only the cooldown clock carries over
            with self._lock:
                self.state = COOLDOWN
                self._last_cycle_end = time.monotonic() - _ended_ago()
            return {"resumed": False, "reason": "last cycle complete"}
        first = recs[0]
        cyc = _Cycle(cycle_id, first.get("cycle_dir", ""),
                     trigger=first.get("trigger") or {},
                     champion_dir=first.get("champion_dir", ""),
                     champion_hash=first.get("champion_hash"))
        cyc.attempt = max([int(r.get("attempt", 0)) for r in recs] or [0])
        cand_hash = None
        for r in recs:
            if r.get("candidate_hash"):
                cand_hash = r["candidate_hash"]
        cyc.candidate_hash = cand_hash
        if st == QUARANTINED:
            # the quarantine ledger landed (it precedes the journal
            # record)? Either way the cycle is terminal — only the
            # missing COOLDOWN mark is replayed.
            self.journal.append(cyc.id, COOLDOWN)
            with self._lock:
                self.state = COOLDOWN
                self._last_cycle_end = time.monotonic() - _ended_ago()
            return {"resumed": False, "reason": "was quarantined"}
        self._reap_orphan_worker(cyc)
        collector.event("retrain_resumed", cycle=cyc.id, at_state=st)
        _log.warning("retrain: resuming cycle %s from journaled state "
                     "%s", cyc.id, st)
        if st in (TRIGGERED, FITTING):
            entry = FITTING
        elif st == VALIDATING:
            entry = VALIDATING
        elif st == ROLLING_OUT:
            # EXACTLY-ONCE probe: did the swap land before the crash?
            champ = self._champion_hash()
            if cyc.candidate_hash and champ and \
                    champ == cyc.candidate_hash:
                _log.info("retrain: cycle %s swap already landed "
                          "(champion hash == candidate); completing "
                          "without a second rollout", cyc.id)
                self._swapped(cyc, {"resumed": True})
                with self._lock:
                    # the cycle actually ended before the crash —
                    # restart downtime counts toward the cooldown here
                    # exactly as in the COOLDOWN/QUARANTINED branches
                    # (_finish just stamped "now")
                    self._last_cycle_end = \
                        time.monotonic() - _ended_ago()
                return {"resumed": True, "at_state": st,
                        "action": "swap_already_landed"}
            ro_status = (self.rollout.status() or {}) \
                if self.rollout is not None else {}
            live = ro_status.get("state")
            # same attribution rule as _await_rollout: only a rollout
            # the manager says is running OUR candidate (or a fake that
            # reports no challenger_dir) is this cycle's — an operator
            # rollout that took the slot after the crash must neither
            # be awaited as ours nor have its rejection banish our
            # candidate; a foreign slot-holder means OUR rollout died,
            # which is exactly the one-recovery-pass case below
            ro_dir = ro_status.get("challenger_dir")
            ours = ro_dir is None or ro_dir == cyc.candidate_dir
            if live in _ROLLOUT_LIVE and ours:
                with self._lock:
                    self.state = ROLLING_OUT
                    self.cycle = cyc
                    self._cycle_thread = threading.Thread(
                        target=self._await_rollout, args=(cyc,),
                        name=f"retrain-{cyc.id}", daemon=True)
                    t = self._cycle_thread
                t.start()
                return {"resumed": True, "at_state": st,
                        "action": "awaiting_live_rollout"}
            if live == "rejected" and ours:
                self._rollout_rejected(
                    cyc, {"reasons": ["rejected before the crash"]})
                return {"resumed": True, "at_state": st,
                        "action": "was_rejected"}
            # the rollout provably did not swap and is not live (it died
            # with the controller's process): ONE recovery pass,
            # re-validated first — the candidate artifact sat on disk
            # across the crash
            entry = VALIDATING
        else:
            return {"resumed": False, "reason": f"unknown state {st}"}
        with self._lock:
            self.state = entry
            self.cycle = cyc
            self.cycles_total += 1
            self._cycle_starts.append(time.monotonic())
            self._cycle_thread = threading.Thread(
                target=self._run_cycle, args=(cyc, entry),
                name=f"retrain-{cyc.id}", daemon=True)
            t = self._cycle_thread
        t.start()
        return {"resumed": True, "at_state": st, "action": f"re-enter "
                                                           f"{entry}"}

    def _reap_orphan_worker(self, cyc: _Cycle) -> None:
        """A kill -9 of the controller mid-FITTING leaves the worker
        subprocess orphaned; its pid file (written by retrain-worker)
        lets the resumed controller kill it before relaunching, so two
        workers never fit one cycle. Best-effort with a cmdline check
        against pid reuse."""
        pid_path = os.path.join(cyc.dir, "worker.pid")
        try:
            with open(pid_path) as fh:
                pid = int(fh.read().strip())
        except (OSError, ValueError):
            return
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as fh:
                cmdline = fh.read().decode("utf-8", "replace")
        except OSError:
            return  # no such process
        if "retrain-worker" not in cmdline:
            return  # pid was reused by something else — leave it alone
        _log.warning("retrain: reaping orphaned worker pid=%d of cycle "
                     "%s", pid, cyc.id)
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass

    # -- trigger threads -----------------------------------------------------
    def _tail_loop(self) -> None:
        try:
            for rec in follow_events(self.alert_log, stop=self._stop,
                                     poll_s=0.2):
                if rec.get("event") == "drift_alert":
                    try:
                        self.handle_alert(rec)
                    except RetrainConflict:
                        pass
                    except Exception:  # noqa: BLE001
                        _log.exception("retrain: alert handling failed")
        except Exception:  # noqa: BLE001
            _log.exception("retrain: alert tail died")

    def _poll_loop(self) -> None:
        poll_broken = False
        while not self._stop.wait(self.drift_poll_interval_s):
            try:
                payload = self.drift_poll()
            except Exception:  # noqa: BLE001
                # one log line per error EPISODE (the poll re-fires
                # every couple of seconds — flooding would bury the
                # diagnostic), but never silent: this poll IS the
                # auto-retrain trigger source, and a persistently
                # failing /drift otherwise kills it with no trace
                if not poll_broken:
                    poll_broken = True
                    _log.exception(
                        "retrain: drift poll failing; auto-trigger "
                        "degraded until it recovers")
                continue
            if poll_broken:
                poll_broken = False
                _log.info("retrain: drift poll recovered")
            if not isinstance(payload, dict) or \
                    not payload.get("alerting"):
                continue
            pooled = payload.get("pooled") or {}
            for a in pooled.get("alerts", []):
                alert = dict(a)
                alert.setdefault("window_id", pooled.get("window_id"))
                alert.setdefault("model_content_hash",
                                 pooled.get("model_content_hash"))
                try:
                    self.handle_alert(alert)
                except RetrainConflict:
                    pass
                except Exception:  # noqa: BLE001
                    _log.exception("retrain: pooled alert handling "
                                   "failed")
