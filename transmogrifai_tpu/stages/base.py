"""Pipeline stage base classes.

Reference: features/.../stages/OpPipelineStages.scala (OpPipelineStageBase:56,
OpPipelineStage1..2N:219-504, OpTransformer:527) and the concrete lambda-style
bases under features/.../stages/base/{unary,binary,ternary,quaternary,sequence}.

TPU-first redesign: the reference's OpTransformer protocol is a *row* function
(transformRow / transformKeyValue) executed inside one fused rdd.map per DAG
layer. Here the primary protocol is *columnar*: ``transform_columns`` maps
whole input columns to an output column. Stages whose math is numeric expose a
traceable ``jax_fn`` (arrays -> array); the workflow scheduler fuses every
jax-able stage of a DAG layer into ONE jitted XLA program over the device
feature matrix (the analogue of FitStagesUtil.applyOpTransformations:96 — but
fusion happens in the compiler, not in a row loop). A per-row path
(``transform_value``) remains for local scoring and contract tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..data.dataset import Column, Dataset, column_from_values
from ..data.vector import VectorMetadata
from ..features.feature import Feature, FeatureHandle
from ..types import ColumnKind, FeatureType
from ..utils.uid import make_uid
from .params import HasParams, Param


class PipelineStage(HasParams):
    """Base of every stage: typed inputs, a single typed output feature.

    (Multi-output stages in the reference — OpPipelineStage3To2 etc — are not
    used by any shipped component, so single-output is the contract here.)
    """

    # expected FeatureType classes of inputs. None entries = any type.
    # For sequence stages, checked against every sequence input.
    input_types: Tuple[Optional[Type[FeatureType]], ...] = ()
    output_type: Type[FeatureType] = FeatureType
    # sequence stages accept a variable number of trailing inputs
    is_sequence: bool = False
    # number of fixed (non-sequence) leading inputs for sequence stages
    fixed_arity: int = 0

    def __init__(self, operation_name: str, uid: Optional[str] = None, **params: Any):
        self.operation_name = operation_name
        self.uid = uid or make_uid(type(self))
        self._init_params(**params)
        self._input_features: Tuple[Feature, ...] = ()
        self._output_name_override: Optional[str] = None

    # -- identity ----------------------------------------------------------
    @property
    def stage_name(self) -> str:
        return f"{type(self).__name__}_{self.operation_name}"

    def __repr__(self) -> str:
        ins = ", ".join(f.name for f in self._input_features)
        return f"{type(self).__name__}(op={self.operation_name}, in=[{ins}], uid={self.uid})"

    # -- wiring ------------------------------------------------------------
    def check_input_types(self, features: Sequence[Feature]) -> None:
        if self.is_sequence:
            fixed = features[:self.fixed_arity]
            seq = features[self.fixed_arity:]
            expected_fixed = self.input_types[:self.fixed_arity]
            seq_type = self.input_types[self.fixed_arity] if len(
                self.input_types) > self.fixed_arity else None
            for i, (f, t) in enumerate(zip(fixed, expected_fixed)):
                if t is not None and not issubclass(f.feature_type, t):
                    raise TypeError(
                        f"{self.stage_name} input {i} must be {t.__name__}, "
                        f"got {f.type_name} ({f.name})")
            for f in seq:
                if seq_type is not None and not issubclass(f.feature_type, seq_type):
                    raise TypeError(
                        f"{self.stage_name} sequence inputs must be "
                        f"{seq_type.__name__}, got {f.type_name} ({f.name})")
        else:
            if self.input_types and len(features) != len(self.input_types):
                raise TypeError(
                    f"{self.stage_name} expects {len(self.input_types)} inputs, "
                    f"got {len(features)}")
            for i, (f, t) in enumerate(zip(features, self.input_types)):
                if t is not None and not issubclass(f.feature_type, t):
                    raise TypeError(
                        f"{self.stage_name} input {i} must be {t.__name__}, "
                        f"got {f.type_name} ({f.name})")

    def set_input(self, *features: Feature) -> "PipelineStage":
        self.check_input_types(features)
        self._input_features = tuple(features)
        return self

    @property
    def input_features(self) -> Tuple[Feature, ...]:
        return self._input_features

    def input_names(self) -> List[str]:
        return [f.name for f in self._input_features]

    def input_handles(self) -> List[FeatureHandle]:
        return [f.to_handle() for f in self._input_features]

    # -- output ------------------------------------------------------------
    def set_output_name(self, name: str) -> "PipelineStage":
        self._output_name_override = name
        return self

    def output_name(self) -> str:
        if self._output_name_override:
            return self._output_name_override
        base = "-".join(f.name for f in self._input_features) or "out"
        suffix = self.uid.rsplit("_", 1)[-1]
        return f"{base}_{self.operation_name}_{suffix}"

    def output_is_response(self) -> bool:
        """Output is a response iff any input is (reference
        OpPipelineStage.outputIsResponse)."""
        return any(f.is_response for f in self._input_features)

    def get_output(self) -> Feature:
        if not self._input_features:
            raise ValueError(f"{self.stage_name}: set_input before get_output")
        return Feature(
            name=self.output_name(),
            feature_type=self.output_type,
            is_response=self.output_is_response(),
            origin_stage=self,
            parents=self._input_features,
        )

    # -- persistence hooks (stages/io.py drives these) ---------------------
    def save_args(self) -> Dict[str, Any]:
        """Constructor args needed to rebuild this stage on load (reference
        OpPipelineStageWriter ctor-arg capture, but explicit, not reflective).
        Declared param values ride along so load restores them (reference
        stages persist their Spark params in the same JSON)."""
        d = {"operation_name": self.operation_name, "uid": self.uid}
        d.update(self.param_values())
        # a contract pinned on the *instance* (Estimator.fit narrowing the
        # fitted model to its estimator's types) must survive save/load, or
        # reloaded models silently revert to the permissive class default
        if "input_types" in self.__dict__:
            d["pinned_input_types"] = [
                None if t is None else t.type_name()
                for t in self.input_types]
            d["pinned_is_sequence"] = bool(self.is_sequence)
            d["pinned_fixed_arity"] = int(self.fixed_arity)
        return d

    @classmethod
    def from_save_args(cls, args: Dict[str, Any]) -> "PipelineStage":
        """Rebuild from save_args (reference OpPipelineStageReader.scala:52).
        Default: cls(**args) filtered through the ctor signature; stages whose
        state is not plain ctor kwargs override this."""
        from .registry import default_from_save_args
        if args.get("lambda"):
            raise ValueError(
                f"{cls.__name__} wraps a python lambda and cannot be rebuilt "
                f"from JSON; pass it via load(..., custom_stages={{uid: stage}})")
        return default_from_save_args(cls, args)

    def copy(self, **param_overrides: Any) -> "PipelineStage":
        """Fresh instance with same ctor args (new uid) and current+overridden
        params — used by the model selector to expand grids."""
        import inspect
        args = self.save_args()
        args.pop("uid", None)
        sig = inspect.signature(type(self).__init__)
        accepted = set(sig.parameters) - {"self"}
        has_kwargs = any(p.kind is inspect.Parameter.VAR_KEYWORD
                         for p in sig.parameters.values())
        if not has_kwargs:
            args = {k: v for k, v in args.items() if k in accepted}
        else:
            # kwargs catch-all is the declared-params channel; drop ctor args
            # the subclass sets itself (e.g. hardcoded operation_name)
            args = {k: v for k, v in args.items()
                    if k in accepted or self.has_param(k)}
        clone = type(self)(**args)
        for k, v in self.param_values().items():
            clone.set_param(k, v)
        for k, v in param_overrides.items():
            clone.set_param(k, v)
        if self._input_features:
            clone.set_input(*self._input_features)
        return clone


class Transformer(PipelineStage):
    """A stage that maps input columns to an output column with no fitting.

    Implement ONE of:
      * ``transform_value(*vals)``   — per-row (always works; slow path)
      * ``transform_columns(*cols)`` — columnar override (fast path)
      * ``get_jax_fn() -> fn``       — pure array math; makes the stage fusable
                                       into the layer's jitted XLA program.
    """

    def get_jax_fn(self) -> Optional[Callable]:
        """Pure fn arrays->array (batched over rows), or None if not jax-able."""
        return None

    def transform_value(self, *vals: FeatureType) -> FeatureType:
        fn = self.get_jax_fn()
        if fn is not None:
            args = [np.asarray(np.nan if v.value is None else
                               (v.value if isinstance(v.value, np.ndarray)
                                else float(v.value)))
                    for v in vals]
            # jax fns are batched over rows: add/strip a singleton batch dim
            out = np.asarray(fn(*[a[None] for a in args]))[0]
            if self.output_type.column_kind != ColumnKind.VECTOR and out.ndim == 0:
                out = out.item()
                if isinstance(out, float) and np.isnan(out):
                    out = None
            return self.output_type(out)
        raise NotImplementedError(
            f"{self.stage_name} implements neither transform_value nor a jax fn")

    def transform_columns(self, *cols: Column) -> Column:
        fn = self.get_jax_fn()
        if fn is not None and all(
                c.kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL,
                           ColumnKind.VECTOR) for c in cols):
            arrays = [c.data for c in cols]
            out = np.asarray(fn(*arrays))
            kind = self.output_type.column_kind
            if kind == ColumnKind.VECTOR:
                if out.ndim == 1:
                    out = out[:, None]
                return Column(kind=kind, data=out.astype(np.float32),
                              metadata=self.output_metadata())
            return Column(kind=kind, data=out.astype(np.float64))
        return self._transform_columns_rowwise(*cols)

    def _transform_columns_rowwise(self, *cols: Column) -> Column:
        in_types = [f.feature_type for f in self._input_features] or \
            [t or FeatureType for t in self.input_types]
        n = len(cols[0]) if cols else 0
        out_vals = []
        for i in range(n):
            vals = []
            for c, t in zip(cols, in_types):
                vals.append(self._value_from_column(c, t, i))
            out_vals.append(self.transform_value(*vals))
        return self._column_from_outputs(out_vals)

    @staticmethod
    def _value_from_column(col: Column, t: Type[FeatureType], i: int) -> FeatureType:
        v = col.data[i]
        if col.kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL):
            v = None if (isinstance(v, float) and np.isnan(v)) else v
        return t(v)

    def _column_from_outputs(self, out_vals: List[FeatureType]) -> Column:
        col = column_from_values(self.output_type, out_vals)
        if col.kind == ColumnKind.VECTOR:
            col.metadata = self.output_metadata()
        return col

    def output_metadata(self) -> Optional[VectorMetadata]:
        """VectorMetadata for vector-producing transformers (override)."""
        return None

    def transform(self, ds: Dataset) -> Dataset:
        """Append this stage's output column to the dataset."""
        cols = [ds.column(n) for n in self.input_names()]
        out = self.transform_columns(*cols)
        return ds.with_column(self.output_name(), out)

    def transform_keyvalue(self, row: Dict[str, Any]) -> Any:
        """Row-level scoring protocol (reference OpTransformer.transformKeyValue
        :551) used by the local scorer: dict in -> raw output value.

        Serving records carry no labels; a missing response value is replaced
        by a placeholder (fitted transformers never read responses — the
        reference's scoring path likewise runs label-free) so non-nullable
        response types (RealNN) don't reject None.
        """
        vals = []
        for f in self._input_features:
            t = f.feature_type
            v = row.get(f.name)
            if v is None and f.is_response:
                try:
                    vals.append(t(None))
                except Exception:
                    vals.append(t(0.0))
            else:
                vals.append(t(v))
        return self.transform_value(*vals).value


class Estimator(PipelineStage):
    """A stage that must be fit: produces a fitted Transformer (its 'model').

    Two-phase contract (the key to static XLA shapes — reference estimator/model
    split, e.g. SmartTextVectorizer.fitFn -> SmartTextVectorizerModelArgs):
    ``fit_columns`` runs stats (host or device reductions) and returns a fitted
    Transformer whose shapes are fully concrete.
    """

    def fit_columns(self, *cols: Column) -> Transformer:
        raise NotImplementedError

    def fit(self, ds: Dataset) -> Transformer:
        cols = [ds.column(n) for n in self.input_names()]
        model = self.fit_columns(*cols)
        # pin the fitted instance to this estimator's contract: model
        # classes that declare a broad element type (e.g. OneHotModel's
        # (None,)) enforce, per instance, exactly what their estimator
        # accepted — the estimator/model pair always sees the same features
        model.input_types = tuple(self.input_types)
        model.is_sequence = self.is_sequence
        model.fixed_arity = self.fixed_arity
        model.set_input(*self._input_features)
        model.set_output_name(self.output_name())
        # model replaces the estimator as origin of the output feature
        model.uid = self.uid
        return model


# -- lambda-style concrete bases ------------------------------------------
# (reference stages/base/{unary,binary,ternary,quaternary}/ — arity is just
# len(input_types) here; these helpers keep user code as terse as the Scala
# lambda bases)

class LambdaTransformer(Transformer):
    """Transformer from a row-level python function."""

    def __init__(self, operation_name: str,
                 transform_fn: Callable[..., FeatureType],
                 input_types: Sequence[Optional[Type[FeatureType]]],
                 output_type: Type[FeatureType],
                 uid: Optional[str] = None, **params: Any):
        self.input_types = tuple(input_types)
        self.output_type = output_type
        self._fn = transform_fn
        super().__init__(operation_name, uid=uid, **params)

    def transform_value(self, *vals: FeatureType) -> FeatureType:
        out = self._fn(*vals)
        if not isinstance(out, FeatureType):
            out = self.output_type(out)
        return out

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d["lambda"] = True  # lambda stages need re-registration on load
        return d


def unary_transformer(operation_name: str, fn: Callable, in_type, out_type,
                      **params) -> LambdaTransformer:
    return LambdaTransformer(operation_name, fn, (in_type,), out_type, **params)


def binary_transformer(operation_name: str, fn: Callable, in1, in2, out_type,
                       **params) -> LambdaTransformer:
    return LambdaTransformer(operation_name, fn, (in1, in2), out_type, **params)


class JaxTransformer(Transformer):
    """Transformer defined purely by array math — fusable into the layer's
    XLA program. Pass the batched arrays->array fn to the ctor (or override
    ``get_jax_fn`` in a subclass)."""

    def __init__(self, operation_name: str,
                 fn: Optional[Callable] = None,
                 input_types: Sequence[Optional[Type[FeatureType]]] = (),
                 output_type: Type[FeatureType] = FeatureType,
                 uid: Optional[str] = None, **params: Any):
        self._fn = fn
        if input_types:
            self.input_types = tuple(input_types)
        if output_type is not FeatureType:
            self.output_type = output_type
        super().__init__(operation_name, uid=uid, **params)

    def get_jax_fn(self) -> Optional[Callable]:
        return self._fn

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        if self._fn is not None:
            # ctor-passed callables can't round-trip through JSON; flag so
            # load fails fast with the custom_stages hint (subclasses that
            # override get_jax_fn rebuild their fn and don't set this)
            d["lambda"] = True
        return d
