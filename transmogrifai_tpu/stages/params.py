"""Typed stage-parameter system.

Reference: Spark ML ``Params``/``ParamMap`` as used by every OP stage, plus
``OpParams`` JSON overrides (features/.../OpParams.scala:81). Stages declare
params with defaults and validators; ``ParamMap`` is a plain dict used by the
model-selector grids; params round-trip through JSON for persistence and for
the ``stage_params`` override mechanism (OpWorkflow.setStageParameters,
core/.../OpWorkflow.scala:166).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class Param:
    name: str
    doc: str = ""
    default: Any = None
    validator: Optional[Callable[[Any], bool]] = None

    def validate(self, value: Any) -> None:
        if self.validator is not None and not self.validator(value):
            raise ValueError(f"Invalid value for param '{self.name}': {value!r}")


class HasParams:
    """Mixin giving a stage a declared-param dictionary.

    Subclasses declare params via ``_declare_params`` returning a list of
    Param; instances hold current values in ``_param_values``.
    """

    @classmethod
    def _declare_params(cls) -> List[Param]:
        return []

    def _init_params(self, **overrides: Any) -> None:
        self._params: Dict[str, Param] = {}
        for klass in reversed(type(self).__mro__):
            declare = klass.__dict__.get("_declare_params")
            if declare is not None:
                for p in declare.__func__(type(self)):
                    self._params[p.name] = p
        self._param_values: Dict[str, Any] = {
            name: copy.copy(p.default) for name, p in self._params.items()
        }
        for k, v in overrides.items():
            self.set_param(k, v)

    # -- access ------------------------------------------------------------
    def has_param(self, name: str) -> bool:
        return name in self._params

    def get_param(self, name: str) -> Any:
        if name not in self._params:
            raise KeyError(f"{type(self).__name__} has no param '{name}'")
        return self._param_values[name]

    def set_param(self, name: str, value: Any) -> "HasParams":
        if name not in self._params:
            raise KeyError(f"{type(self).__name__} has no param '{name}'")
        self._params[name].validate(value)
        self._param_values[name] = value
        return self

    def set_params(self, **kwargs: Any) -> "HasParams":
        for k, v in kwargs.items():
            self.set_param(k, v)
        return self

    def param_values(self) -> Dict[str, Any]:
        return dict(self._param_values)

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self._params.items()):
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, "
                         f"current: {self._param_values[name]!r})")
        return "\n".join(lines)


# A hyperparameter assignment used by model-selector grids: stage-param name -> value.
ParamMap = Dict[str, Any]


def param_grid(**axes: List[Any]) -> List[ParamMap]:
    """Cartesian product grid builder (reference ParamGridBuilder usage in
    Binary/Multi/Regression selector factories)."""
    import itertools
    names = list(axes.keys())
    grids: List[ParamMap] = []
    for combo in itertools.product(*[axes[n] for n in names]):
        grids.append(dict(zip(names, combo)))
    return grids
