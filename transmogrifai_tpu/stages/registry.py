"""Stage class registry + save-arg (de)serialization.

Reference: features/.../stages/OpPipelineStageWriter.scala:52 /
OpPipelineStageReader.scala:52 — stages persist as JSON of ctor args and are
recovered reflectively. Here recovery is explicit: every stage class exposes
``save_args()`` (JSON-able ctor kwargs) and the classmethod
``from_save_args``; the registry maps class names to classes. Arrays embedded
in save_args are hoisted into a side npz store by ``pack_args`` so the JSON
graph stays small and arrays load zero-copy.
"""
from __future__ import annotations

import importlib
import inspect
from typing import Any, Dict, Optional, Type

import numpy as np

# Modules scanned for PipelineStage subclasses. Extended via register_module /
# register_stage for user stages (the reference's analogous requirement: stage
# classes must be on the classpath at load time).
_STAGE_MODULES = [
    "transmogrifai_tpu.stages.base",
    "transmogrifai_tpu.features.generator",
    "transmogrifai_tpu.automl.vectorizers.base",
    "transmogrifai_tpu.automl.vectorizers.numeric",
    "transmogrifai_tpu.automl.vectorizers.categorical",
    "transmogrifai_tpu.automl.vectorizers.text",
    "transmogrifai_tpu.automl.vectorizers.dates",
    "transmogrifai_tpu.automl.vectorizers.geo",
    "transmogrifai_tpu.automl.vectorizers.maps",
    "transmogrifai_tpu.automl.vectorizers.combiner",
    "transmogrifai_tpu.automl.preparators",
    "transmogrifai_tpu.automl.selector",
    "transmogrifai_tpu.models.glm",
    "transmogrifai_tpu.models.trees",
    "transmogrifai_tpu.models.mlp",
    "transmogrifai_tpu.insights.loco",
    "transmogrifai_tpu.insights.corr",
    "transmogrifai_tpu.transformers.math",
    "transmogrifai_tpu.transformers.misc",
    "transmogrifai_tpu.transformers.text",
    "transmogrifai_tpu.transformers.topics",
    "transmogrifai_tpu.transformers.ner",
]

_EXTRA_STAGES: Dict[str, type] = {}
_registry_cache: Optional[Dict[str, type]] = None


def register_stage(cls: type) -> type:
    """Register a user stage class for load-time recovery (decorator-friendly)."""
    global _registry_cache
    _EXTRA_STAGES[cls.__name__] = cls
    _registry_cache = None
    return cls


def register_module(module_name: str) -> None:
    global _registry_cache
    if module_name not in _STAGE_MODULES:
        _STAGE_MODULES.append(module_name)
        _registry_cache = None


def stage_registry() -> Dict[str, type]:
    global _registry_cache
    if _registry_cache is not None:
        return _registry_cache
    from .base import PipelineStage
    reg: Dict[str, type] = {}
    for mod_name in _STAGE_MODULES:
        try:
            mod = importlib.import_module(mod_name)
        except ImportError:
            continue
        for obj in vars(mod).values():
            if isinstance(obj, type) and issubclass(obj, PipelineStage):
                reg[obj.__name__] = obj
    reg.update(_EXTRA_STAGES)
    _registry_cache = reg
    return reg


def resolve_stage_class(name: str) -> type:
    reg = stage_registry()
    if name not in reg:
        raise KeyError(
            f"Unknown stage class '{name}'. Register its module via "
            f"transmogrifai_tpu.stages.registry.register_module/register_stage "
            f"before loading (reference: stage classes must be on the "
            f"classpath, OpPipelineStageReader.scala:52)")
    return reg[name]


# -- array packing ---------------------------------------------------------

def pack_args(obj: Any, store: Dict[str, np.ndarray], prefix: str) -> Any:
    """Recursively replace ndarrays with {"__ndarray__": key} refs, hoisting
    the arrays into `store` (saved as one npz next to the JSON graph)."""
    if isinstance(obj, np.ndarray):
        key = f"{prefix}.{len(store)}"
        store[key] = obj
        return {"__ndarray__": key}
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, dict):
        return {str(k): pack_args(v, store, prefix) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [pack_args(v, store, prefix) for v in obj]
    return obj


def unpack_args(obj: Any, store: Dict[str, np.ndarray]) -> Any:
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__ndarray__"}:
            return store[obj["__ndarray__"]]
        return {k: unpack_args(v, store) for k, v in obj.items()}
    if isinstance(obj, list):
        return [unpack_args(v, store) for v in obj]
    return obj


def build_stage(class_name: str, args: Dict[str, Any]):
    """Instantiate a stage from its class name + unpacked save_args."""
    cls = resolve_stage_class(class_name)
    stage = cls.from_save_args(args)
    _apply_pinned_contract(stage, args)
    return stage


def _apply_pinned_contract(stage, args: Dict[str, Any]) -> None:
    """Restore an instance-level contract saved by PipelineStage.save_args
    (Estimator.fit pins fitted models to their estimator's types)."""
    pinned = args.get("pinned_input_types")
    if pinned is None:
        return
    from ..types import FeatureType
    stage.input_types = tuple(
        None if n is None else FeatureType.from_name(n) for n in pinned)
    if "pinned_is_sequence" in args:
        stage.is_sequence = bool(args["pinned_is_sequence"])
    if "pinned_fixed_arity" in args:
        stage.fixed_arity = int(args["pinned_fixed_arity"])


def default_from_save_args(cls: type, args: Dict[str, Any]):
    """Construct cls(**args), dropping keys its __init__ does not accept
    (mirror of PipelineStage.copy's filtering)."""
    args = {k: v for k, v in args.items()
            if k != "lambda" and not k.startswith("pinned_")}
    sig = inspect.signature(cls.__init__)
    has_kwargs = any(p.kind is inspect.Parameter.VAR_KEYWORD
                     for p in sig.parameters.values())
    if not has_kwargs:
        accepted = set(sig.parameters) - {"self"}
        args = {k: v for k, v in args.items() if k in accepted}
    return cls(**args)
