"""Batched LOCO knockout programs — one device program per model family.

Reference RecordInsightsLOCO.scala:62 loops rows x columns through the
fitted Spark model. Round-3's loco.py already batched rows but still drove
one forward pass per column from the host (567 dispatches on a 567-column
vector). This module collapses the knockout axis itself into the program:

- GLM families (logistic/SVC/softmax/linear/naive Bayes): the knocked-out
  score is CLOSED FORM — zeroing column j shifts the margin by
  ``-X[:, j] * beta[j]`` — so all [n, d] knockouts are one jitted
  elementwise program, no per-column passes at all.
- Tree ensembles: one jitted ``lax.scan`` over the features the ensemble
  actually splits on (host-derived static set; untouched features have
  identically zero delta), each step re-traversing all trees on-device.

Both routes chunk rows to a fixed shape so one compile serves any n, and
return the same [n, d, c] delta tensor as the host loop (parity-tested in
tests/test_loco_batched.py).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_ROW_CHUNK = 4096


def _pad_rows(X: np.ndarray, chunk: int) -> Tuple[np.ndarray, int]:
    n = X.shape[0]
    pad = (-n) % chunk
    if pad:
        X = np.concatenate([X, np.zeros((pad,) + X.shape[1:], X.dtype)])
    return X, n


# -- GLM closed forms --------------------------------------------------------

@jax.jit
def _logistic_deltas(X, beta, b0):
    """[n, d, 2] probability deltas for a binary logistic model."""
    m = X @ beta + b0                                   # [n]
    knocked = m[:, None] - X * beta[None, :]            # [n, d]
    dp1 = jax.nn.sigmoid(m)[:, None] - jax.nn.sigmoid(knocked)
    return jnp.stack([-dp1, dp1], axis=2)


@jax.jit
def _margin_deltas(X, beta):
    """[n, d, 2] raw-margin deltas (SVC: no probabilities, score = raw)."""
    dm = X * beta[None, :]                              # [n, d]
    return jnp.stack([-dm, dm], axis=2)


@jax.jit
def _softmax_deltas(X, B, b0):
    """[n, d, c] probability deltas for a multinomial logistic model."""
    logits = X @ B + b0[None, :]                        # [n, c]
    knocked = logits[:, None, :] - X[:, :, None] * B[None, :, :]  # [n, d, c]
    return (jax.nn.softmax(logits, axis=-1)[:, None, :]
            - jax.nn.softmax(knocked, axis=-1))


@functools.partial(jax.jit, static_argnames=("log_link",))
def _linreg_deltas(X, beta, b0, log_link: bool):
    """[n, d, 1] prediction deltas for a (log-)linear regression."""
    if not log_link:
        return (X * beta[None, :])[:, :, None]
    eta = X @ beta + b0
    knocked = eta[:, None] - X * beta[None, :]
    return (jnp.exp(eta)[:, None] - jnp.exp(knocked))[:, :, None]


@jax.jit
def _nb_deltas(X, log_prob, log_prior):
    """[n, d, c] probability deltas for naive Bayes (raw = relu(X) @ W.T)."""
    A = jnp.maximum(X, 0.0)
    raw = A @ log_prob.T + log_prior[None, :]           # [n, c]
    knocked = raw[:, None, :] - A[:, :, None] * log_prob.T[None, :, :]
    return (jax.nn.softmax(raw, axis=-1)[:, None, :]
            - jax.nn.softmax(knocked, axis=-1))


# -- tree ensembles ----------------------------------------------------------

def _traverse_pertree(feat, thresh, miss, X, depth: int):
    """Leaf index per (row, tree) on raw values: [N, T] int32.

    Same routing contract as ops/trees.np_predict_ensemble: present values
    go right iff x >= thresh (NaN compares False), missing rows follow the
    learned ``miss`` direction."""
    N = X.shape[0]
    T = feat.shape[0]
    rows = jnp.arange(N)[:, None]
    t_idx = jnp.arange(T)[None, :]
    rel = jnp.zeros((N, T), jnp.int32)
    for d in range(depth):
        gi = (1 << d) - 1 + rel
        f = feat[t_idx, gi]                             # [N, T]
        tv = thresh[t_idx, gi]
        x = X[rows, f]
        nan = jnp.isnan(x)
        right = (~nan & (x >= tv)) | (nan & (miss[t_idx, gi] > 0))
        rel = 2 * rel + right.astype(jnp.int32)
    return rel


@functools.partial(jax.jit, static_argnames=("depth",))
def _tree_knockout_sums(feat, thresh, leaf, miss, W, X, active, depth: int):
    """Aggregate knocked-out scores in ONE program.

    leaf: [T, L, K]; W: [T, G] per-tree group weights (softmax boosting
    groups trees by class; binary/regression use G=1, all-ones).
    Returns (base [N, G, K], knocked [A, N, G, K]) where knocked[a] is the
    aggregate with column active[a] zeroed.
    """
    T = feat.shape[0]
    t_idx = jnp.arange(T)[None, :]

    def agg(Xc):
        rel = _traverse_pertree(feat, thresh, miss, Xc, depth)   # [N, T]
        per = leaf[t_idx, rel]                                   # [N, T, K]
        return jnp.einsum("ntk,tg->ngk", per, W)                 # [N, G, K]

    base = agg(X)

    def step(_, j):
        return None, agg(X.at[:, j].set(0.0))

    _, knocked = lax.scan(step, None, active)
    return base, knocked


def active_features(feat: np.ndarray, thresh: np.ndarray) -> np.ndarray:
    """Features the ensemble actually splits on (finite threshold nodes).
    Dead/degenerate nodes carry +/-inf thresholds: their routing cannot
    change under knockout, so their features contribute zero delta."""
    real = np.isfinite(thresh)
    return np.unique(np.asarray(feat)[real]).astype(np.int32)


def _scores_from_agg(agg: jnp.ndarray, mode: str, base: float,
                     n_trees: int) -> jnp.ndarray:
    """[.., G, K] aggregate -> [.., c] score columns matching
    models/trees predict_arrays (prob when probabilistic, else prediction).
    """
    if mode == "classify_mean":
        p = jnp.clip(agg[..., 0, :] / n_trees, 0.0, None)
        return p / jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-12)
    if mode == "margin":
        m = agg[..., 0, 0] + base
        p1 = jax.nn.sigmoid(m)
        return jnp.stack([1.0 - p1, p1], axis=-1)
    if mode == "regress_mean":
        return (agg[..., 0, :] / n_trees)
    if mode == "regress_sum":
        return agg[..., 0, :] + base
    if mode == "softmax":
        return jax.nn.softmax(agg[..., 0], axis=-1)     # G = n_classes, K=1
    raise ValueError(f"unknown ensemble mode: {mode}")


def tree_knockout_deltas(feat, thresh, leaf, miss, X, depth: int, mode: str,
                         base: float = 0.0,
                         class_of_tree: Optional[np.ndarray] = None,
                         row_chunk: int = _ROW_CHUNK) -> np.ndarray:
    """[n, d, c] LOCO deltas for a heap-layout ensemble, scanning only the
    features the ensemble uses."""
    X = np.ascontiguousarray(X, np.float32)
    n, d = X.shape
    T = feat.shape[0]
    act = active_features(feat, thresh)
    if class_of_tree is not None:
        G = int(class_of_tree.max()) + 1
        W = np.zeros((T, G), np.float32)
        W[np.arange(T), class_of_tree] = 1.0
    else:
        W = np.ones((T, 1), np.float32)

    feat_j = jnp.asarray(feat, jnp.int32)
    thresh_j = jnp.asarray(thresh, jnp.float32)
    leaf_j = jnp.asarray(leaf, jnp.float32)
    miss_j = jnp.asarray(miss, jnp.int32)
    W_j = jnp.asarray(W)
    act_j = jnp.asarray(act)

    chunk = min(row_chunk, max(n, 1))
    Xp, n_real = _pad_rows(X, chunk)
    n_scores = None
    out = None
    for s in range(0, Xp.shape[0], chunk):
        b, k = _tree_knockout_sums(feat_j, thresh_j, leaf_j, miss_j, W_j,
                                   jnp.asarray(Xp[s:s + chunk]), act_j, depth)
        sb = _scores_from_agg(b, mode, base, T)          # [chunk, c]
        sk = _scores_from_agg(k, mode, base, T)          # [A, chunk, c]
        deltas = np.asarray(sb[None] - sk, np.float64)   # [A, chunk, c]
        if out is None:
            n_scores = deltas.shape[-1]
            out = np.zeros((Xp.shape[0], d, n_scores), np.float64)
        out[s:s + chunk][:, act, :] = np.moveaxis(deltas, 0, 1)
    if out is None:
        return np.zeros((0, d, 1), np.float64)
    return out[:n_real]


# -- dispatch ----------------------------------------------------------------

def _tree_route_wins() -> bool:
    """The scan route wins on accelerators (one device program instead of
    one RPC per column). On a CPU backend the host loop's native C++
    traversal (ops/trees_host, the libxgboost-role kernel) is faster than
    XLA re-traversal — route there unless the native library is absent."""
    if jax.default_backend() != "cpu":
        return True
    try:
        from ..ops import trees_host
        return not trees_host.available()
    except Exception:
        return True


def knockout_deltas(model, X: np.ndarray, row_chunk: int = _ROW_CHUNK,
                    force_tree: Optional[bool] = None) -> Optional[np.ndarray]:
    """[n, d, c] LOCO deltas via the family's device program, or None when
    the model family has no batched route (caller falls back to the host
    knockout loop). ``force_tree`` overrides the backend-aware tree-route
    choice (tests exercise the scan route on CPU through it)."""
    from ..automl.selector import SelectedModel
    from ..models.glm import (LinearBinaryModel, LinearRegressionModel,
                              NaiveBayesModel, SoftmaxModel)
    from ..models.trees import SoftmaxEnsembleModel, TreeEnsembleModel

    if isinstance(model, SelectedModel):
        # the wrapper only remaps `pred`; deltas are computed on prob/raw,
        # which delegate unchanged to the wrapped winner
        model = model.best_model

    X = np.ascontiguousarray(X, np.float32)

    if isinstance(model, LinearBinaryModel):
        beta = jnp.asarray(model.beta)
        if model.probabilistic:
            return np.asarray(_logistic_deltas(X, beta, model.intercept),
                              np.float64)
        return np.asarray(_margin_deltas(X, beta), np.float64)
    if isinstance(model, SoftmaxModel):
        return np.asarray(
            _softmax_deltas(X, jnp.asarray(model.B), jnp.asarray(model.b0)),
            np.float64)
    if isinstance(model, LinearRegressionModel):
        return np.asarray(
            _linreg_deltas(X, jnp.asarray(model.beta), model.intercept,
                           model.link == "log"), np.float64)
    if isinstance(model, NaiveBayesModel):
        return np.asarray(
            _nb_deltas(X, jnp.asarray(model.log_prob),
                       jnp.asarray(model.log_prior)), np.float64)
    if isinstance(model, (SoftmaxEnsembleModel, TreeEnsembleModel)):
        use_scan = force_tree if force_tree is not None else _tree_route_wins()
        if not use_scan:
            return None
        if isinstance(model, SoftmaxEnsembleModel):
            C = model.n_classes
            class_of_tree = (np.arange(model.feat.shape[0]) % C) \
                .astype(np.int32)
            return tree_knockout_deltas(
                model.feat, model.thresh_val, model.leaf, model.miss, X,
                model.depth, "softmax", class_of_tree=class_of_tree,
                row_chunk=row_chunk)
        return tree_knockout_deltas(
            model.feat, model.thresh_val, model.leaf, model.miss, X,
            model.depth, model.mode, base=model.base, row_chunk=row_chunk)
    return None
