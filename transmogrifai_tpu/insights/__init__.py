"""Model explainability (reference ModelInsights.scala:72 and
impl/insights/RecordInsightsLOCO.scala:62)."""
from .loco import RecordInsightsLOCO
from .model_insights import (
    DerivedFeatureInsights, FeatureInsights, ModelInsights,
    extract_insights, model_contributions,
)

__all__ = [
    "DerivedFeatureInsights", "FeatureInsights", "ModelInsights",
    "RecordInsightsLOCO", "extract_insights", "model_contributions",
]
