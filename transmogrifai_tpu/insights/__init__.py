"""Model explainability (reference ModelInsights.scala:72 and
impl/insights/RecordInsightsLOCO.scala:62)."""
from .corr import RecordInsightsCorr
from .loco import RecordInsightsLOCO
from .model_insights import (
    DerivedFeatureInsights, FeatureInsights, ModelInsights,
    extract_insights, model_contributions,
)

__all__ = [
    "DerivedFeatureInsights", "FeatureInsights", "ModelInsights",
    "RecordInsightsCorr", "RecordInsightsLOCO", "extract_insights", "model_contributions",
]
