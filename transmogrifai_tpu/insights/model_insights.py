"""ModelInsights: merged per-feature diagnostics of a fitted workflow.

Reference: core/.../ModelInsights.scala:72 (extractFromStages used at
OpWorkflowModel.scala:173, prettyPrint:99) — joins the assembled vector's
column provenance (OpVectorMetadata) with SanityChecker statistics, the
ModelSelector summary, RawFeatureFilter results, and the winning model's
per-column contributions into one JSON artifact + pretty tables.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


# -- contributions ----------------------------------------------------------

def model_contributions(model: Any, n_cols: int) -> Optional[np.ndarray]:
    """Per-column contribution of a fitted model: |coefficient| for linear
    family, split-frequency importance for tree ensembles (reference exposes
    Spark's coefficients/featureImportances through ModelInsights).
    Returns [n_cols] or None when the model family has no notion of it."""
    from ..models import glm
    from ..models import trees as tr
    from ..automl.selector import SelectedModel

    if isinstance(model, SelectedModel):
        return model_contributions(model.best_model, n_cols)
    if isinstance(model, glm.LinearBinaryModel):
        return np.abs(model.beta[:n_cols])
    if isinstance(model, glm.LinearRegressionModel):
        return np.abs(model.beta[:n_cols])
    if isinstance(model, glm.SoftmaxModel):
        return np.abs(model.B[:n_cols, :]).sum(axis=1)
    if isinstance(model, glm.NaiveBayesModel):
        return np.abs(model.log_prob.T[:n_cols, :]).sum(axis=1)
    if isinstance(model, (tr.TreeEnsembleModel, tr.SoftmaxEnsembleModel)):
        live = np.isfinite(model.thresh_val)          # dead splits are +inf
        counts = np.bincount(model.feat[live].ravel(), minlength=n_cols)
        total = counts.sum()
        return (counts / total if total else counts).astype(np.float64)[:n_cols]
    return None


# -- insight records --------------------------------------------------------

@dataclass
class DerivedFeatureInsights:
    """One column of the model's input vector (reference Insights per
    derived feature)."""

    column_name: str
    column_index: int
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    contribution: Optional[float] = None
    corr_label: Optional[float] = None
    cramers_v: Optional[float] = None
    variance: Optional[float] = None
    mean: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class FeatureInsights:
    """All derived columns of one raw feature + exclusion info."""

    feature_name: str
    feature_type: str = ""
    derived: List[DerivedFeatureInsights] = field(default_factory=list)
    excluded_by: Optional[str] = None     # 'SanityChecker'|'RawFeatureFilter'
    exclusion_reasons: List[str] = field(default_factory=list)

    def max_contribution(self) -> float:
        vals = [d.contribution for d in self.derived
                if d.contribution is not None]
        return max(vals) if vals else 0.0

    def max_corr(self) -> float:
        vals = [abs(d.corr_label) for d in self.derived
                if d.corr_label is not None and np.isfinite(d.corr_label)]
        return max(vals) if vals else 0.0

    def to_json(self) -> Dict[str, Any]:
        return {"feature_name": self.feature_name,
                "feature_type": self.feature_type,
                "derived": [d.to_json() for d in self.derived],
                "excluded_by": self.excluded_by,
                "exclusion_reasons": list(self.exclusion_reasons)}


@dataclass
class ModelInsights:
    """The merged artifact (reference ModelInsights case class)."""

    label_name: Optional[str]
    problem_type: Optional[str]
    features: List[FeatureInsights] = field(default_factory=list)
    selected_model: Optional[Dict[str, Any]] = None
    validation_results: List[Dict[str, Any]] = field(default_factory=list)
    train_evaluation: Dict[str, float] = field(default_factory=dict)
    holdout_evaluation: Dict[str, float] = field(default_factory=dict)
    stage_names: List[str] = field(default_factory=list)
    blacklisted: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "label_name": self.label_name,
            "problem_type": self.problem_type,
            "features": [f.to_json() for f in self.features],
            "selected_model": self.selected_model,
            "validation_results": self.validation_results,
            "train_evaluation": self.train_evaluation,
            "holdout_evaluation": self.holdout_evaluation,
            "stage_names": self.stage_names,
            "blacklisted": self.blacklisted,
        }

    # -- pretty (reference prettyPrint:99 -> README tables) ----------------
    def pretty(self, top_k: int = 15) -> str:
        lines: List[str] = []
        if self.selected_model:
            lines.append(
                f"Selected model: {self.selected_model.get('best_model_type')}"
                f" grid={self.selected_model.get('best_grid')}")
        # scalar metrics only: structured entries (threshold_metrics
        # curves) live in the JSON artifact, not the table
        if self.train_evaluation:
            ev = ", ".join(f"{k}={v:.4f}"
                           for k, v in sorted(self.train_evaluation.items())
                           if isinstance(v, float))
            lines.append(f"Train evaluation: {ev}")
        if self.holdout_evaluation:
            ev = ", ".join(f"{k}={v:.4f}"
                           for k, v in sorted(self.holdout_evaluation.items())
                           if isinstance(v, float))
            lines.append(f"Holdout evaluation: {ev}")

        ranked = sorted(self.features, key=lambda f: -f.max_contribution())
        lines.append("")
        lines.append(f"{'Top Model Contributions':<32}{'Contribution':>14}")
        for f in ranked[:top_k]:
            lines.append(f"{f.feature_name:<32}{f.max_contribution():>14.4f}")

        by_corr = sorted(self.features, key=lambda f: -f.max_corr())
        lines.append("")
        lines.append(f"{'Top Correlations':<32}{'Correlation':>14}")
        for f in by_corr[:top_k]:
            lines.append(f"{f.feature_name:<32}{f.max_corr():>14.4f}")

        excluded = [f for f in self.features if f.excluded_by]
        if excluded:
            lines.append("")
            lines.append("Excluded features:")
            for f in excluded:
                why = "; ".join(f.exclusion_reasons) or f.excluded_by
                lines.append(f"  {f.feature_name} ({f.excluded_by}): {why}")
        return "\n".join(lines)


# -- extraction -------------------------------------------------------------

def _final_vector_metadata(model) -> Optional[Any]:
    """Metadata of the vector the winning model consumed: the sanity
    checker's post-slice metadata when present, else the last vector-
    producing stage's."""
    sc = model._sanity_checker()
    if sc is not None and getattr(sc, "metadata", None) is not None:
        idx = getattr(sc, "indices_to_keep", None)
        md = sc.metadata
        return md.select(list(idx)) if idx is not None else md
    for st in reversed(model.stages):
        md = st.output_metadata()
        if md is not None:
            return md
    return None


def extract_insights(model) -> ModelInsights:
    """Build ModelInsights from a fitted WorkflowModel (reference
    extractFromStages, OpWorkflowModel.scala:173)."""
    sel = model._selected_model()
    sel_summary = model.selector_summary()
    sc_summary = model.sanity_checker_summary()
    md = _final_vector_metadata(model)

    # sanity-checker stats by column name (first entry is the label)
    stats_by_name: Dict[str, Dict[str, Any]] = {}
    label_name = None
    if sc_summary is not None:
        cs = sc_summary.column_stats
        if cs:
            label_name = cs[0]["name"]
        for st in cs[1:]:
            stats_by_name[st["name"]] = st

    contrib = None
    if sel is not None and md is not None:
        contrib = model_contributions(sel, md.size)

    features: Dict[str, FeatureInsights] = {}
    if md is not None:
        for c in md.columns:
            fi = features.setdefault(
                c.parent_feature_name,
                FeatureInsights(feature_name=c.parent_feature_name,
                                feature_type=c.parent_feature_type))
            name = c.column_name()
            st = stats_by_name.get(name, {})
            fi.derived.append(DerivedFeatureInsights(
                column_name=name, column_index=c.index,
                grouping=c.grouping, indicator_value=c.indicator_value,
                contribution=(float(contrib[c.index])
                              if contrib is not None and c.index < len(contrib)
                              else None),
                corr_label=st.get("corr_label"),
                cramers_v=st.get("cramers_v"),
                variance=st.get("variance"),
                mean=st.get("mean")))

    # columns the SanityChecker dropped still deserve a line w/ reasons.
    # Resolve each dropped column's parent from the checker's PRE-slice
    # vector metadata — string-splitting the column name breaks for any raw
    # feature whose name contains an underscore (e.g. 'pickup_time').
    dropped_parent: Dict[str, str] = {}
    if sc_summary is not None and sc_summary.dropped:
        sc_stage = model._sanity_checker()
        if sc_stage is not None and \
                getattr(sc_stage, "metadata", None) is not None:
            dropped_parent = {c.column_name(): c.parent_feature_name
                              for c in sc_stage.metadata.columns}
    if sc_summary is not None:
        for dropped_col in sc_summary.dropped:
            reasons = sc_summary.drop_reasons.get(dropped_col, [])
            parent = dropped_parent.get(dropped_col,
                                        dropped_col.split("_")[0])
            fi = features.setdefault(parent, FeatureInsights(parent))
            if fi.excluded_by is None and all(
                    d.column_name != dropped_col for d in fi.derived):
                fi.derived.append(DerivedFeatureInsights(
                    column_name=dropped_col, column_index=-1))
            # only mark the whole feature excluded when ALL its columns drop
        kept_parents = {c.parent_feature_name for c in md.columns} if md else set()
        for name, fi in features.items():
            if name not in kept_parents and sc_summary.dropped:
                fi.excluded_by = "SanityChecker"
                fi.exclusion_reasons = sorted({
                    r for col in sc_summary.dropped
                    if dropped_parent.get(col, col.split("_")[0]) == name
                    for r in sc_summary.drop_reasons.get(col, [])})

    # raw-feature-filter exclusions
    if model.rff_results is not None:
        for name in model.rff_results.dropped_features:
            fi = features.setdefault(name, FeatureInsights(name))
            fi.excluded_by = "RawFeatureFilter"
            fi.exclusion_reasons = [
                k for r in model.rff_results.exclusion_reasons
                if r.name == name and r.key is None and r.excluded
                for k, v in r.to_json().items()
                if isinstance(v, bool) and v]

    return ModelInsights(
        label_name=label_name,
        problem_type=(sel_summary.problem_type if sel_summary else None),
        features=list(features.values()),
        selected_model=({"best_model_type": sel_summary.best_model_type,
                         "best_model_name": sel_summary.best_model_name,
                         "best_grid": sel_summary.best_grid}
                        if sel_summary else None),
        validation_results=(sel_summary.validation_results
                            if sel_summary else []),
        train_evaluation=(sel_summary.train_evaluation if sel_summary else {}),
        holdout_evaluation=(sel_summary.holdout_evaluation
                            if sel_summary else {}),
        stage_names=[st.stage_name for st in model.stages],
        blacklisted=list(model.blacklist),
    )
