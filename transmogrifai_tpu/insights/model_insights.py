"""ModelInsights: merged per-feature diagnostics of a fitted workflow.

Reference: core/.../ModelInsights.scala:72 (extractFromStages used at
OpWorkflowModel.scala:173, prettyPrint:99) — joins the assembled vector's
column provenance (OpVectorMetadata) with SanityChecker statistics, the
ModelSelector summary, RawFeatureFilter results, and the winning model's
per-column contributions into one JSON artifact + pretty tables.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


# -- contributions ----------------------------------------------------------

def model_contributions(model: Any, n_cols: int) -> Optional[np.ndarray]:
    """Per-column contribution of a fitted model: |coefficient| for linear
    family, split-frequency importance for tree ensembles (reference exposes
    Spark's coefficients/featureImportances through ModelInsights).
    Returns [n_cols] or None when the model family has no notion of it."""
    from ..models import glm
    from ..models import trees as tr
    from ..automl.selector import SelectedModel

    if isinstance(model, SelectedModel):
        return model_contributions(model.best_model, n_cols)
    if isinstance(model, glm.LinearBinaryModel):
        return np.abs(model.beta[:n_cols])
    if isinstance(model, glm.LinearRegressionModel):
        return np.abs(model.beta[:n_cols])
    if isinstance(model, glm.SoftmaxModel):
        return np.abs(model.B[:n_cols, :]).sum(axis=1)
    if isinstance(model, glm.NaiveBayesModel):
        return np.abs(model.log_prob.T[:n_cols, :]).sum(axis=1)
    if isinstance(model, (tr.TreeEnsembleModel, tr.SoftmaxEnsembleModel)):
        live = np.isfinite(model.thresh_val)          # dead splits are +inf
        counts = np.bincount(model.feat[live].ravel(), minlength=n_cols)
        total = counts.sum()
        return (counts / total if total else counts).astype(np.float64)[:n_cols]
    return None


def model_contributions_per_class(model: Any,
                                  n_cols: int) -> Optional[np.ndarray]:
    """[n_cols, c] per-class contributions where the family has them
    (reference Insights.contribution is a Seq — one weight per class for
    multinomial models); single-column for binary/regression/tree models."""
    from ..models import glm
    from ..automl.selector import SelectedModel

    if isinstance(model, SelectedModel):
        return model_contributions_per_class(model.best_model, n_cols)
    if isinstance(model, glm.SoftmaxModel):
        return np.abs(model.B[:n_cols, :])
    if isinstance(model, glm.NaiveBayesModel):
        return np.abs(model.log_prob.T[:n_cols, :])
    flat = model_contributions(model, n_cols)
    return None if flat is None else flat[:, None]


# -- insight records --------------------------------------------------------

@dataclass
class LabelSummary:
    """Label provenance + distribution (reference LabelSummary,
    ModelInsights.scala:291)."""

    label_name: Optional[str] = None
    raw_feature_name: List[str] = field(default_factory=list)
    raw_feature_type: List[str] = field(default_factory=list)
    stages_applied: List[str] = field(default_factory=list)
    sample_size: Optional[float] = None
    # {"kind": "continuous", min, max, mean, variance} or
    # {"kind": "discrete", "domain": [...], "prob": [...]}
    distribution: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class DerivedFeatureInsights:
    """One column of the model's input vector (reference Insights per
    derived feature, ModelInsights.scala:372)."""

    column_name: str
    column_index: int
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    stages_applied: List[str] = field(default_factory=list)
    excluded: Optional[bool] = None
    contribution: Optional[float] = None
    contributions: List[float] = field(default_factory=list)  # per class
    corr_label: Optional[float] = None
    cramers_v: Optional[float] = None
    mutual_information: Optional[float] = None
    pointwise_mutual_information: Dict[str, float] = field(default_factory=dict)
    count_matrix: Dict[str, float] = field(default_factory=dict)
    variance: Optional[float] = None
    mean: Optional[float] = None
    min: Optional[float] = None
    max: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class FeatureInsights:
    """All derived columns of one raw feature + exclusion info
    (reference FeatureInsights, ModelInsights.scala:336)."""

    feature_name: str
    feature_type: str = ""
    derived: List[DerivedFeatureInsights] = field(default_factory=list)
    excluded_by: Optional[str] = None     # 'SanityChecker'|'RawFeatureFilter'
    exclusion_reasons: List[str] = field(default_factory=list)
    # RawFeatureFilter artifacts for this raw feature, when it ran
    rff_metrics: List[Dict[str, Any]] = field(default_factory=list)
    rff_distributions: List[Dict[str, Any]] = field(default_factory=list)

    def max_contribution(self) -> float:
        vals = [d.contribution for d in self.derived
                if d.contribution is not None]
        return max(vals) if vals else 0.0

    def max_corr(self) -> float:
        vals = [abs(d.corr_label) for d in self.derived
                if d.corr_label is not None and np.isfinite(d.corr_label)]
        return max(vals) if vals else 0.0

    def to_json(self) -> Dict[str, Any]:
        return {"feature_name": self.feature_name,
                "feature_type": self.feature_type,
                "derived": [d.to_json() for d in self.derived],
                "excluded_by": self.excluded_by,
                "exclusion_reasons": list(self.exclusion_reasons),
                "rff_metrics": list(self.rff_metrics),
                "rff_distributions": list(self.rff_distributions)}


@dataclass
class ModelInsights:
    """The merged artifact (reference ModelInsights case class)."""

    label_name: Optional[str]
    problem_type: Optional[str]
    features: List[FeatureInsights] = field(default_factory=list)
    selected_model: Optional[Dict[str, Any]] = None
    validation_results: List[Dict[str, Any]] = field(default_factory=list)
    train_evaluation: Dict[str, float] = field(default_factory=dict)
    holdout_evaluation: Dict[str, float] = field(default_factory=dict)
    stage_names: List[str] = field(default_factory=list)
    blacklisted: List[str] = field(default_factory=list)
    label: Optional[LabelSummary] = None
    # per-stage parameter snapshot (reference stageInfo map)
    stage_info: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "label_name": self.label_name,
            "problem_type": self.problem_type,
            "label": self.label.to_json() if self.label else None,
            "features": [f.to_json() for f in self.features],
            "selected_model": self.selected_model,
            "validation_results": self.validation_results,
            "train_evaluation": self.train_evaluation,
            "holdout_evaluation": self.holdout_evaluation,
            "stage_names": self.stage_names,
            "stage_info": self.stage_info,
            "blacklisted": self.blacklisted,
        }

    # -- pretty (reference prettyPrint:99 -> README tables) ----------------
    def pretty(self, top_k: int = 15) -> str:
        lines: List[str] = []
        if self.selected_model:
            lines.append(
                f"Selected model: {self.selected_model.get('best_model_type')}"
                f" grid={self.selected_model.get('best_grid')}")
        # scalar metrics only: structured entries (threshold_metrics
        # curves) live in the JSON artifact, not the table
        if self.train_evaluation:
            ev = ", ".join(f"{k}={v:.4f}"
                           for k, v in sorted(self.train_evaluation.items())
                           if isinstance(v, float))
            lines.append(f"Train evaluation: {ev}")
        if self.holdout_evaluation:
            ev = ", ".join(f"{k}={v:.4f}"
                           for k, v in sorted(self.holdout_evaluation.items())
                           if isinstance(v, float))
            lines.append(f"Holdout evaluation: {ev}")

        ranked = sorted(self.features, key=lambda f: -f.max_contribution())
        lines.append("")
        lines.append(f"{'Top Model Contributions':<32}{'Contribution':>14}")
        for f in ranked[:top_k]:
            lines.append(f"{f.feature_name:<32}{f.max_contribution():>14.4f}")

        by_corr = sorted(self.features, key=lambda f: -f.max_corr())
        lines.append("")
        lines.append(f"{'Top Correlations':<32}{'Correlation':>14}")
        for f in by_corr[:top_k]:
            lines.append(f"{f.feature_name:<32}{f.max_corr():>14.4f}")

        excluded = [f for f in self.features if f.excluded_by]
        if excluded:
            lines.append("")
            lines.append("Excluded features:")
            for f in excluded:
                why = "; ".join(f.exclusion_reasons) or f.excluded_by
                lines.append(f"  {f.feature_name} ({f.excluded_by}): {why}")
        return "\n".join(lines)


# -- extraction -------------------------------------------------------------

def _final_vector_metadata(model) -> Optional[Any]:
    """Metadata of the vector the winning model consumed: the sanity
    checker's post-slice metadata when present, else the last vector-
    producing stage's."""
    sc = model._sanity_checker()
    if sc is not None and getattr(sc, "metadata", None) is not None:
        # the fitted checker's metadata is already the POST-slice view
        # (SanityChecker.fit builds it via meta.select(keep))
        return sc.metadata
    for st in reversed(model.stages):
        md = st.output_metadata()
        if md is not None:
            return md
    return None


def _feature_graph_by_name(model) -> Dict[str, Any]:
    """name -> Feature for every node reachable from the result features."""
    out: Dict[str, Any] = {}
    for rf in getattr(model, "result_features", ()):
        for f in rf.all_features():
            out.setdefault(f.name, f)
    return out


def _stages_applied(feature) -> List[str]:
    """Stage-name chain that produced this feature from its raw inputs
    (reference Insights.stagesApplied via FeatureHistory)."""
    if feature is None:
        return []
    names: List[str] = []
    for st in feature.parent_stages():
        nm = getattr(st, "stage_name", None) or type(st).__name__
        if nm not in names:
            names.append(nm)
    return names


def _label_summary(model, sc_summary, label_name) -> LabelSummary:
    """Reference LabelSummary: provenance from the label feature's history,
    distribution from the checker's label stats."""
    graph = _feature_graph_by_name(model)
    lf = graph.get(label_name) if label_name else None
    raws = [f.name for f in lf.raw_features()] if lf is not None else []
    raw_types = [f.type_name for f in lf.raw_features()] if lf is not None \
        else []
    sample, dist = None, None
    if sc_summary is not None and sc_summary.column_stats:
        ls = sc_summary.column_stats[0]
        sample = ls.get("count")
        ld = getattr(sc_summary, "label_distribution", None)
        if ld:
            total = sum(ld["counts"]) or 1.0
            dist = {"kind": "discrete",
                    "domain": [str(v) for v in ld["domain"]],
                    "prob": [c / total for c in ld["counts"]]}
        else:
            dist = {"kind": "continuous", "min": ls.get("min"),
                    "max": ls.get("max"), "mean": ls.get("mean"),
                    "variance": ls.get("variance")}
    return LabelSummary(label_name=label_name, raw_feature_name=raws,
                        raw_feature_type=raw_types,
                        stages_applied=_stages_applied(lf),
                        sample_size=sample, distribution=dist)


def extract_insights(model) -> ModelInsights:
    """Build ModelInsights from a fitted WorkflowModel (reference
    extractFromStages, OpWorkflowModel.scala:173)."""
    sel = model._selected_model()
    sel_summary = model.selector_summary()
    sc_summary = model.sanity_checker_summary()
    md = _final_vector_metadata(model)

    # sanity-checker stats by column name (first entry is the label)
    stats_by_name: Dict[str, Dict[str, Any]] = {}
    label_name = None
    if sc_summary is not None:
        cs = sc_summary.column_stats
        if cs:
            label_name = cs[0]["name"]
        for st in cs[1:]:
            stats_by_name[st["name"]] = st

    # categorical group stats indexed by member column name: the group's
    # MI is shared, the PMI / contingency columns are per member
    cat_by_col: Dict[str, Dict[str, Any]] = {}
    label_domain: List[str] = []
    if sc_summary is not None:
        ld = getattr(sc_summary, "label_distribution", None)
        if ld:
            label_domain = [str(v) for v in ld["domain"]]
        for g in sc_summary.categorical_stats:
            for pos, col in enumerate(g.get("categorical_features", [])):
                cat_by_col[col] = {"group": g, "pos": pos}

    contrib = None
    contrib_pc = None
    if sel is not None and md is not None:
        contrib = model_contributions(sel, md.size)
        contrib_pc = model_contributions_per_class(sel, md.size)

    graph = _feature_graph_by_name(model)
    dropped_set = set(sc_summary.dropped) if sc_summary is not None else set()

    features: Dict[str, FeatureInsights] = {}
    if md is not None:
        for c in md.columns:
            fi = features.setdefault(
                c.parent_feature_name,
                FeatureInsights(feature_name=c.parent_feature_name,
                                feature_type=c.parent_feature_type))
            name = c.column_name()
            st = stats_by_name.get(name, {})
            mi = pmi = counts = None
            cat = cat_by_col.get(name)
            if cat is not None:
                # contingency/PMI rows are the group's member features,
                # columns the label values (preparators._categorical_tests)
                g, pos = cat["group"], cat["pos"]
                mi = g.get("mutual_info")
                pm = g.get("pointwise_mutual_info")
                cm = g.get("contingency_matrix")

                def _label_row(matrix):
                    if matrix is None or pos >= len(matrix):
                        return None
                    row = matrix[pos]
                    dom = (label_domain if len(label_domain) == len(row)
                           else [str(i) for i in range(len(row))])
                    return {dom[j]: float(v) for j, v in enumerate(row)}

                pmi = _label_row(pm)
                counts = _label_row(cm)
            fi.derived.append(DerivedFeatureInsights(
                column_name=name, column_index=c.index,
                grouping=c.grouping, indicator_value=c.indicator_value,
                stages_applied=_stages_applied(
                    graph.get(c.parent_feature_name)),
                excluded=(name in dropped_set) if sc_summary is not None
                else None,
                contribution=(float(contrib[c.index])
                              if contrib is not None and c.index < len(contrib)
                              else None),
                contributions=([float(v) for v in contrib_pc[c.index]]
                               if contrib_pc is not None
                               and c.index < len(contrib_pc) else []),
                corr_label=st.get("corr_label"),
                cramers_v=st.get("cramers_v"),
                mutual_information=mi,
                pointwise_mutual_information=pmi or {},
                count_matrix=counts or {},
                variance=st.get("variance"),
                mean=st.get("mean"),
                min=st.get("min"), max=st.get("max")))

    # columns the SanityChecker dropped still deserve a line w/ reasons.
    # Their parents come from the summary's dropped_parents map (resolved
    # at fit time from the PRE-slice metadata) — string-splitting the
    # column name breaks for any raw feature whose name contains an
    # underscore (e.g. 'pickup_time').
    dropped_parent: Dict[str, str] = {}
    if sc_summary is not None and sc_summary.dropped:
        dropped_parent = dict(getattr(sc_summary, "dropped_parents", {}))
    if sc_summary is not None:
        for dropped_col in sc_summary.dropped:
            reasons = sc_summary.drop_reasons.get(dropped_col, [])
            parent = dropped_parent.get(dropped_col,
                                        dropped_col.split("_")[0])
            fi = features.setdefault(parent, FeatureInsights(parent))
            if fi.excluded_by is None and all(
                    d.column_name != dropped_col for d in fi.derived):
                fi.derived.append(DerivedFeatureInsights(
                    column_name=dropped_col, column_index=-1))
            # only mark the whole feature excluded when ALL its columns drop
        kept_parents = {c.parent_feature_name for c in md.columns} if md else set()
        for name, fi in features.items():
            if name not in kept_parents and sc_summary.dropped:
                fi.excluded_by = "SanityChecker"
                fi.exclusion_reasons = sorted({
                    r for col in sc_summary.dropped
                    if dropped_parent.get(col, col.split("_")[0]) == name
                    for r in sc_summary.drop_reasons.get(col, [])})

    # raw-feature-filter exclusions
    if model.rff_results is not None:
        for name in model.rff_results.dropped_features:
            fi = features.setdefault(name, FeatureInsights(name))
            fi.excluded_by = "RawFeatureFilter"
            fi.exclusion_reasons = [
                k for r in model.rff_results.exclusion_reasons
                if r.name == name and r.key is None and r.excluded
                for k, v in r.to_json().items()
                if isinstance(v, bool) and v]

    # RawFeatureFilter per-feature artifacts (reference FeatureInsights
    # metrics/distributions fields)
    if model.rff_results is not None:
        rff = model.rff_results
        for fd in rff.train_distributions:
            fi = features.get(fd.name)
            if fi is not None:
                d = fd.to_json() if hasattr(fd, "to_json") else dict(
                    fd.__dict__)
                fi.rff_distributions.append(d)
        for er in rff.exclusion_reasons:
            fi = features.get(er.name)
            if fi is not None:
                fi.rff_metrics.append(er.to_json()
                                      if hasattr(er, "to_json")
                                      else dict(er.__dict__))

    stage_info: Dict[str, Dict[str, Any]] = {}
    for st in model.stages:
        try:
            stage_info[st.stage_name] = {
                k: v for k, v in st.param_values().items()
                if isinstance(v, (int, float, str, bool, type(None)))}
        except Exception:
            stage_info[st.stage_name] = {}

    problem_type = sel_summary.problem_type if sel_summary else None
    return ModelInsights(
        label_name=label_name,
        problem_type=problem_type,
        label=_label_summary(model, sc_summary, label_name),
        stage_info=stage_info,
        features=list(features.values()),
        selected_model=({"best_model_type": sel_summary.best_model_type,
                         "best_model_name": sel_summary.best_model_name,
                         "best_grid": sel_summary.best_grid}
                        if sel_summary else None),
        validation_results=(sel_summary.validation_results
                            if sel_summary else []),
        train_evaluation=(sel_summary.train_evaluation if sel_summary else {}),
        holdout_evaluation=(sel_summary.holdout_evaluation
                            if sel_summary else {}),
        stage_names=[st.stage_name for st in model.stages],
        blacklisted=list(model.blacklist),
    )
