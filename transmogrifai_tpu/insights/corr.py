"""RecordInsightsCorr: correlation-based per-record insights.

Reference: core/.../impl/insights/RecordInsightsCorr.scala — per-column
Pearson correlation between feature values and the model's score over the
scored batch; each record's insight is the correlation-weighted, centered
feature value (columns that both correlate with the score and deviate from
their mean on this record rank highest).

The whole computation is ONE pass of the one-pass statistics engine
(ops/stats_engine.py) over the scored batch — the column means/deviations
and the score cross-moments that used to be two separate matrix reductions
come out of the same blocked scan (corr_label with the score as the
"label", population sd from the returned M2). TMOG_STATS_FUSED=0 restores
the two-reduction numpy path. The per-record contribution assembly is
O(n * d) output construction either way.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.dataset import Column, column_from_values
from ..stages.base import Transformer
from ..types import OPVector, Prediction, TextMap

EPS = 1e-12

# Elements below which the scored batch stays on the numpy reductions:
# transform-time batches vary in shape (ragged last batch, per-request
# serving), and the engine's jitted scan bakes the row count into its
# trace — a retrace per new shape plus a host->device round-trip costs
# more than two vectorized numpy passes until the matrix is big enough
# to be bandwidth-bound.
_FUSED_MIN_ELEMENTS = 1 << 20


class RecordInsightsCorr(Transformer):
    """(features OPVector, prediction) -> TextMap of top-K contributions."""

    input_types = (OPVector, Prediction)
    output_type = TextMap

    def __init__(self, top_k: int = 20, uid: Optional[str] = None, **params):
        self.top_k = int(top_k)
        super().__init__(params.pop("operation_name", "corrInsights"),
                         uid=uid, **params)

    @staticmethod
    def _scores(pred_col: Column) -> np.ndarray:
        """Score per row: last probability column when present (P(class1)
        for binary), else the prediction itself. Prediction columns are
        dense [pred, raw_*, prob_*] blocks with named metadata; map-kind
        columns of Prediction dicts (the row-level API boundary) are also
        accepted."""
        if pred_col.data.dtype == object:
            out = np.empty(len(pred_col.data), np.float64)
            for i, m in enumerate(pred_col.data):
                prob_keys = sorted(
                    (k for k in m if k.startswith("probability_")),
                    key=lambda k: int(k.rsplit("_", 1)[1]))
                out[i] = m[prob_keys[-1]] if prob_keys else m["prediction"]
            return out
        data = np.asarray(pred_col.data, np.float64)
        if data.ndim == 1:
            return data
        md = pred_col.metadata
        if md is not None:
            prob_idx = [c.index for c in md.columns
                        if (c.descriptor_value or "").startswith(
                            "probability_")]
            if prob_idx:
                return data[:, prob_idx[-1]]
        return data[:, 0]

    def transform_columns(self, *cols: Column) -> Column:
        from ..ops import stats_engine as SE

        vec, pred = cols
        X = np.asarray(vec.data, np.float64)          # [n, d]
        s = self._scores(pred)                        # [n]
        n, d = X.shape
        names = (vec.metadata.column_names() if vec.metadata is not None
                 else [f"f{j}" for j in range(d)])
        if SE.fused_enabled() and X.size >= _FUSED_MIN_ELEMENTS:
            # means + score cross-moments in ONE engine pass; population
            # sd reconstructed from the returned M2 (the legacy path's
            # np.std convention)
            st = SE.run_stats(X, s, label="corr_insights")
            mu = st.mean
            sd = np.sqrt(np.maximum(st.m2 / np.maximum(st.count, 1.0),
                                    0.0)) + EPS
            corr = st.corr_label
        else:
            mu = X.mean(axis=0)
            sd = X.std(axis=0) + EPS
            s_c = s - s.mean()
            corr = ((X - mu) * s_c[:, None]).sum(axis=0) / (
                n * sd * (s.std() + EPS))
        contrib = corr[None, :] * (X - mu) / sd       # [n, d]
        k = min(self.top_k, d)
        vals: List[Dict[str, str]] = []
        for i in range(n):
            order = np.argsort(-np.abs(contrib[i]))[:k]
            vals.append({names[j]: json.dumps(
                {"contribution": float(contrib[i, j]),
                 "correlation": float(corr[j])}) for j in order})
        return column_from_values(TextMap, vals)

    def transform_value(self, *vals):
        # single-record correlation is undefined; emit empty (reference
        # Corr insights are batch-only as well)
        return TextMap({})

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(top_k=self.top_k)
        return d
