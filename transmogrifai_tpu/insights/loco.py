"""RecordInsightsLOCO: per-row leave-one-column-out attribution.

Reference: core/.../impl/insights/RecordInsightsLOCO.scala:62 — for each
row, each feature-vector column is knocked out (set to the vector's zero)
and the fitted model re-scored; the top-K absolute score deltas are emitted
as an ordered map {column_name: [(class, delta), ...]}.

TPU-shaped: instead of the reference's per-row per-column loop, the whole
[n_cols] knockout axis is one batched forward pass per column over the full
row block — D matmuls on the device path, each [N, d], with no row loop.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..data.dataset import Column, Dataset, column_from_values
from ..stages.base import Transformer
from ..types import OPVector, TextMap


class RecordInsightsLOCO(Transformer):
    """Transformer: features OPVector -> TextMap of top-K column deltas."""

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(self, model: Any = None, top_k: int = 20,
                 operation_name: str = "locoInsights",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)
        self.model = model
        self.top_k = int(top_k)

    # -- scoring ------------------------------------------------------------
    def _base_scores(self, X: np.ndarray) -> np.ndarray:
        """Score vector used for deltas: P(class) columns when the model is
        probabilistic, else margin/prediction (reference uses rawPrediction
        per class)."""
        pred, raw, prob = self.model.predict_arrays(X)
        if prob is not None:
            return np.asarray(prob, np.float64)
        if raw is not None:
            return np.asarray(raw, np.float64)
        return np.asarray(pred, np.float64)[:, None]

    def insights_matrix(self, X: np.ndarray) -> np.ndarray:
        """[n, d, c] deltas: base_score - score_with_column_zeroed.

        Known model families route through a single device program per
        family (insights/knockout.py: closed-form GLM shifts, lax.scan tree
        re-traversal over the ensemble's active features); anything else
        falls back to the generic one-pass-per-column host loop below."""
        X = np.ascontiguousarray(X, np.float32)
        from .knockout import knockout_deltas
        batched = knockout_deltas(self.model, X)
        if batched is not None:
            return batched
        return self.insights_matrix_loop(X)

    def insights_matrix_loop(self, X: np.ndarray) -> np.ndarray:
        """Generic host knockout loop (one forward pass per column); also
        the parity oracle for the batched routes."""
        X = np.ascontiguousarray(X, np.float32)
        base = self._base_scores(X)                       # [n, c]
        n, d = X.shape
        out = np.zeros((n, d, base.shape[1]), np.float64)
        for j in range(d):
            Xj = X.copy()
            Xj[:, j] = 0.0
            out[:, j, :] = base - self._base_scores(Xj)
        return out

    # -- column path ---------------------------------------------------------
    def transform_columns(self, *cols: Column) -> Column:
        vec = cols[-1]
        X = np.asarray(vec.data, np.float32)
        names = (vec.metadata.column_names() if vec.metadata is not None
                 else [f"f{j}" for j in range(X.shape[1])])
        deltas = self.insights_matrix(X)                  # [n, d, c]
        strength = np.abs(deltas).max(axis=2)             # [n, d]
        k = min(self.top_k, X.shape[1])
        # top-k per row in one vectorized argpartition + within-k sort
        orders = np.argpartition(-strength, kth=k - 1, axis=1)[:, :k]
        part = np.take_along_axis(strength, orders, axis=1)
        orders = np.take_along_axis(orders, np.argsort(-part, axis=1), axis=1)
        n_classes = deltas.shape[2]
        vals: List[Dict[str, Any]] = []
        for i in range(X.shape[0]):
            # TextMap values are strings: per-class deltas as JSON, matching
            # the reference's serialized insight arrays
            vals.append({
                names[j]: json.dumps([[int(c), float(deltas[i, j, c])]
                                      for c in range(n_classes)])
                for j in orders[i]
            })
        return column_from_values(TextMap, vals)

    def transform_value(self, *vals):
        X = np.asarray(vals[-1].value, np.float32)[None, :]
        col = self.transform_columns(
            Column(kind="vector", data=X,
                   metadata=getattr(vals[-1], "metadata", None)))
        return TextMap(col.data[0])

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(top_k=self.top_k,
                 model_class=type(self.model).__name__ if self.model else None,
                 model_args=self.model.save_args() if self.model else None)
        return d

    @classmethod
    def from_save_args(cls, args: Dict[str, Any]) -> "RecordInsightsLOCO":
        model = None
        if args.get("model_class"):
            from ..stages.registry import build_stage
            model = build_stage(args["model_class"], args["model_args"])
        return cls(model=model, top_k=args.get("top_k", 20),
                   operation_name=args.get("operation_name", "locoInsights"),
                   uid=args.get("uid"))
