"""Data ingestion (reference readers module, 2,454 LoC): batch readers
(list/CSV/JSONL/Parquet/Avro), temporal aggregation, conditional and joined
readers, streaming micro-batch readers."""
from .avro import AvroReader, read_avro_file, write_avro_file
from .readers import (
    AggregateReader, ConditionalReader, CSVReader, DataReaders,
    JSONLinesReader, JoinedAggregateReader, JoinedReader, ListReader,
    ParquetReader, Reader, TimeBasedFilter, TimeColumn,
)
from .streaming import (
    AvroStreamingReader, CSVStreamingReader, FileStreamingReader,
    ListStreamingReader, StreamingReader, score_stream,
)

__all__ = [
    "AggregateReader", "AvroReader", "AvroStreamingReader",
    "ConditionalReader", "CSVReader", "CSVStreamingReader", "DataReaders",
    "FileStreamingReader", "JSONLinesReader", "JoinedAggregateReader",
    "JoinedReader", "ListReader", "TimeBasedFilter", "TimeColumn",
    "ListStreamingReader", "ParquetReader", "Reader", "StreamingReader",
    "read_avro_file", "score_stream", "write_avro_file",
]
