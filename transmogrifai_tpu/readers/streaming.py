"""Streaming (micro-batch) readers for scoring.

Reference: readers/.../StreamingReaders.scala:43-59 (`StreamingReaders
.Simple.avro` — Spark DStreams of new avro files) and the StreamingScore
run type (OpWorkflowRunner.scala:232). The DStream abstraction maps to a
plain iterator of record batches.

Scoring rides the tileplane (parallel/tileplane.py): incoming record
batches are re-grouped into FIXED-size record tiles whose raw-feature
Dataset is assembled on a background producer thread while the device
scores the previous tile through the fitted workflow's batch programs —
one executable per tile shape (the ragged tail pads by repeating its
last record and the pad rows are dropped after scoring), host record
parsing overlapped with device compute. TMOG_TILEPLANE=0 restores the
legacy per-record `score_function` loop.
"""
from __future__ import annotations

import glob
import os
import time
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from .readers import Reader

Record = Dict[str, Any]


class StreamingReader:
    """Base: iterate record micro-batches."""

    def __init__(self, key_fn: Optional[Callable[[Record], str]] = None):
        self.key_fn = key_fn

    def stream(self) -> Iterator[List[Record]]:
        raise NotImplementedError


class ListStreamingReader(StreamingReader):
    """Batches from an in-memory sequence (testing / replay)."""

    def __init__(self, records: Sequence[Record], batch_size: int = 100,
                 key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self.records = list(records)
        self.batch_size = int(batch_size)

    def stream(self) -> Iterator[List[Record]]:
        for i in range(0, len(self.records), self.batch_size):
            yield self.records[i:i + self.batch_size]


class FileStreamingReader(StreamingReader):
    """One batch per new file matching a glob pattern, in mtime order
    (the reference's 'new files in a directory' DStream source). `poll()`
    re-scans and yields only unseen files, enabling tail-follow loops.

    A file is only yielded once its SIZE is stable: each candidate is
    stat'd twice within the scan, and a file whose size changed — there
    or since the previous poll's observation — is deferred to the next
    poll (a writer is mid-flight; an mtime-ordered glob alone would hand
    a truncated container to the decoder). Stable files yield on first
    sight, so a quiet directory behaves exactly as before."""

    def __init__(self, pattern: str, reader_factory: Callable[[str], Reader],
                 key_fn: Optional[Callable[[Record], str]] = None,
                 stripe: bool = False):
        super().__init__(key_fn)
        self.pattern = pattern
        self.reader_factory = reader_factory
        #: multi-host SPMD striping: when True and >1 jax processes are
        #: up, every listing keeps only THIS PROCESS's contiguous stripe
        #: (parallel/multihost.stripe_paths) — each host opens only its
        #: own shard files. Meant for one-shot batch listings: a
        #: tail-follow loop could observe files at different times on
        #: different hosts and mis-stripe.
        self.stripe = stripe
        self._seen: set = set()
        # path -> last observed size, for candidates deferred mid-write
        self._pending: Dict[str, int] = {}
        # path -> (size, mtime) from the most recent _size stat: the
        # sort key reads mtime from HERE, so each candidate costs its
        # stability stats only — no third per-candidate stat per scan —
        # and ordering can't shift under a mid-scan mtime touch
        self._statted: Dict[str, Tuple[int, float]] = {}

    def _size(self, p: str) -> int:
        """Stat seam (monkeypatched by tests to simulate active writers);
        -1 = vanished between glob and stat. ONE os.stat serves both the
        size-stability check and the mtime ordering (cached in
        `_statted`)."""
        try:
            st = os.stat(p)
        except OSError:
            self._statted.pop(p, None)
            return -1
        self._statted[p] = (st.st_size, st.st_mtime)
        return st.st_size

    def _paths(self) -> List[str]:
        out = []
        matched = set()
        for p in glob.glob(self.pattern):
            matched.add(p)
            if p in self._seen:
                continue
            s1 = self._size(p)
            if s1 < 0:
                self._pending.pop(p, None)
                continue
            prev = self._pending.get(p)
            if prev is not None:
                # deferred last poll: admit only once the size held still
                if prev == s1:
                    self._pending.pop(p)
                    out.append(p)
                else:
                    self._pending[p] = s1
                continue
            s2 = self._size(p)
            if s2 == s1:
                out.append(p)
            elif s2 >= 0:
                self._pending[p] = s2  # actively growing: next poll
        # purge deferred entries whose file vanished (rotated temp files
        # would otherwise leak one ledger entry each in tail-follow loops)
        for p in list(self._pending):
            if p not in matched:
                self._pending.pop(p)

        def order(p: str) -> Tuple[float, str]:
            st = self._statted.get(p)
            if st is None:
                # only reachable when a test monkeypatches _size past
                # the cache; real scans always statted admitted paths
                try:
                    return (os.path.getmtime(p), p)
                except OSError:
                    return (0.0, p)
            return (st[1], p)

        # mtime order with the PATH as tiebreak: equal mtimes (same-run
        # shard writers, coarse filesystems) sort lexicographically, so
        # shard order — and everything downstream that must be
        # bit-identical across ingest worker counts — is deterministic
        ordered = sorted(out, key=order)
        if self.stripe:
            from ..parallel import multihost as MH
            if MH.process_count() > 1:
                ordered = MH.stripe_paths(ordered)
        return ordered

    def stream(self) -> Iterator[List[Record]]:
        for p in self._paths():
            self._seen.add(p)
            yield self.reader_factory(p).read()

    def poll(self) -> List[List[Record]]:
        return [batch for batch in self.stream()]

    def snapshot_paths(self) -> List[str]:
        """Currently-stable unseen shards in deterministic order WITHOUT
        consuming them (`stream()` marks files seen; this does not).
        The sharded ingest engine (parallel/ingest.sharded_reader_source)
        builds its per-worker shard assignment from this listing and
        re-reads the same files once per pass."""
        return self._paths()


class IterStreamingReader(StreamingReader):
    """Batches of `batch_records` off a fresh-iterator factory — a
    file-backed stream that decodes LAZILY (the monitor's bulk replay
    route: the tileplane producer pulls the next batch only as the
    device drains the previous tiles, so a bulk file never materializes
    as one record list)."""

    def __init__(self, factory: Callable[[], Iterator[Record]],
                 batch_records: int = 1024,
                 key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self.factory = factory
        self.batch_records = max(1, int(batch_records))

    def stream(self) -> Iterator[List[Record]]:
        buf: List[Record] = []
        for rec in self.factory():
            buf.append(rec)
            if len(buf) >= self.batch_records:
                yield buf
                buf = []
        if buf:
            yield buf


class AvroStreamingReader(FileStreamingReader):
    """Reference StreamingReaders.Simple.avro."""

    def __init__(self, pattern: str,
                 key_fn: Optional[Callable[[Record], str]] = None):
        from .avro import AvroReader
        super().__init__(pattern, lambda p: AvroReader(p), key_fn)


class CSVStreamingReader(FileStreamingReader):
    def __init__(self, pattern: str,
                 key_fn: Optional[Callable[[Record], str]] = None):
        from .readers import CSVReader
        super().__init__(pattern, lambda p: CSVReader(p), key_fn)


# -- tileplane bulk scoring ---------------------------------------------------

def score_tile_rows_default() -> int:
    """Records per scoring tile: the fixed batch shape every stage
    program compiles ONCE for. An explicitly-set TMOG_SCORE_TILE_ROWS
    wins (hand beats model, logged as a plan_override event); otherwise
    the plan-time autotuner picks the tile — cold corpus / TMOG_PLAN=0
    / any planner fault all yield the 1024 hand default
    (docs/planning.md)."""
    try:
        from ..planner.plan import planned_score_tile_rows
        return planned_score_tile_rows()
    except Exception:
        return int(os.environ.get("TMOG_SCORE_TILE_ROWS", "1024"))


def _record_tiles(stream_reader: StreamingReader, tile_rows: int
                  ) -> Iterator[Tuple[List[Record], int]]:
    """Re-group ragged reader batches into fixed `tile_rows`-record
    tiles; the tail tile pads by REPEATING its last record (real values
    keep every stage's numerics on the fast path — zero-pad would
    inject synthetic NaN rows into vectorizers) and reports its valid
    count so the pad scores are dropped."""
    buf: List[Record] = []
    start = 0  # cursor instead of re-slicing: a whole-file reader batch
    # (FileStreamingReader yields one batch per FILE) would otherwise
    # memcpy the remaining pointer list once per tile — O(N^2)
    for batch in stream_reader.stream():
        buf.extend(batch)
        while len(buf) - start >= tile_rows:
            yield buf[start:start + tile_rows], tile_rows
            start += tile_rows
        if start:
            del buf[:start]
            start = 0
    if buf:
        n = len(buf)
        yield buf + [buf[-1]] * (tile_rows - n), n


def _scoring_dataset(records: List[Record], raw_feats):
    """Raw-feature Dataset for one record tile. Response features are NOT
    extracted (serving records are unlabeled — reference StreamingScore
    semantics, same as local/scoring.score_function): their columns fill
    with missing values so non-nullable response types (RealNN labels)
    never see a None."""
    from ..data.dataset import Column, Dataset, column_from_values
    from ..types import ColumnKind

    n = len(records)
    cols = {}
    for f in raw_feats:
        kind = f.feature_type.column_kind
        if f.is_response:
            if kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL):
                # _record_tiles pads every tile (tail repeats its last
                # record) to tile_rows before records reach here
                # tmoglint: disable=TRC003  n IS the fixed tile shape
                filled = np.full(n, np.nan, np.float64)
                cols[f.name] = Column(kind=kind, data=filled)
            else:
                # tmoglint: disable=TRC003  n is the fixed tile shape (ditto)
                empty = np.empty(n, dtype=object)
                cols[f.name] = Column(kind=kind, data=empty)
        else:
            gen = f.origin_stage
            cols[f.name] = column_from_values(
                f.feature_type, [gen.extract(r) for r in records])
    return Dataset(cols)


def _row_value(col, i: int, feature_type=None):
    """One row of a scored column in the same shape the per-record
    score_function yields. A map-typed result feature (Prediction) that
    the batch path stored as a NAMED vector column unpacks back into its
    {metadata column -> float} dict; other vectors stay arrays; numeric
    NaN -> None like an absent FeatureType value."""
    v = col.data[i]
    if col.kind == "vector":
        if (feature_type is not None
                and getattr(feature_type, "column_kind", None) == "map"
                and col.metadata is not None):
            # the dense prediction block unpacks through the SAME
            # boundary converter the local scorer uses
            from ..models.prediction import row_prediction
            return row_prediction(col, i).value
        return np.asarray(v)
    if col.kind in ("float", "int", "bool"):
        f = float(v)
        return None if np.isnan(f) else f
    return v


def score_stream(model, stream_reader: StreamingReader, *,
                 tile_rows: Optional[int] = None
                 ) -> Iterator[List[Dict[str, Any]]]:
    """Score a record stream with the fitted workflow.

    Tileplane path (default): fixed-size record tiles, raw-feature
    Dataset assembly on the producer thread (`tile_copy` spans — the
    host->device feed stage), batch scoring through the workflow's
    already-compiled fixed-shape stage programs on the caller's thread
    (`tile_compute` spans), pad rows dropped. Yields one list of
    {result_feature: value} dicts per TILE.

    TMOG_TILEPLANE=0 (or tile_rows=0) restores the reference semantics:
    per-batch, per-record scoring via `model.score_function()`
    (StreamingScore: scoreFn over the DStream), yielding one list per
    reader batch."""
    from ..parallel import tileplane as TP

    if tile_rows is None:
        tile_rows = score_tile_rows_default()
    if not TP.tileplane_enabled() or int(tile_rows) <= 0:
        fn = model.score_function()
        for batch in stream_reader.stream():
            yield [fn(r) for r in batch]
        return

    from ..utils.metrics import collector

    tile_rows = int(tile_rows)
    raw = model.raw_features()
    result_types = {f.name: f.feature_type for f in model.result_features}
    # tile spans anchor to the span current at STREAM start: the producer
    # thread must not adopt the stage spans the scoring thread opens
    anchor = collector.trace.current() if collector.enabled else None

    def produce():
        k = 0
        for recs, n_valid in _record_tiles(stream_reader, tile_rows):
            t0 = time.perf_counter()
            ds = _scoring_dataset(recs, raw)
            if collector.enabled:
                collector.trace.add_complete(
                    "tile_copy", "tile", time.perf_counter() - t0,
                    parent_span=anchor, tile=k, rows=int(n_valid),
                    label="score")
            k += 1
            yield ds, n_valid

    k = 0
    for ds, n_valid in TP.pipelined(produce(), label="score"):
        t0 = time.perf_counter()
        scored = model.score(ds)
        cols = [(nm, scored.column(nm), t)
                for nm, t in result_types.items() if nm in scored]
        out = [{nm: _row_value(col, i, t) for nm, col, t in cols}
               for i in range(n_valid)]
        if collector.enabled:
            collector.trace.add_complete(
                "tile_compute", "tile", time.perf_counter() - t0,
                parent_span=anchor, tile=k, rows=int(n_valid),
                label="score")
        k += 1
        yield out
