"""Streaming (micro-batch) readers for scoring.

Reference: readers/.../StreamingReaders.scala:43-59 (`StreamingReaders
.Simple.avro` — Spark DStreams of new avro files) and the StreamingScore
run type (OpWorkflowRunner.scala:232). The DStream abstraction maps to a
plain iterator of record batches; the fitted model scores each batch with
its already-compiled layer programs, so scoring latency is one device step
per batch.
"""
from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

from .readers import Reader

Record = Dict[str, Any]


class StreamingReader:
    """Base: iterate record micro-batches."""

    def __init__(self, key_fn: Optional[Callable[[Record], str]] = None):
        self.key_fn = key_fn

    def stream(self) -> Iterator[List[Record]]:
        raise NotImplementedError


class ListStreamingReader(StreamingReader):
    """Batches from an in-memory sequence (testing / replay)."""

    def __init__(self, records: Sequence[Record], batch_size: int = 100,
                 key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self.records = list(records)
        self.batch_size = int(batch_size)

    def stream(self) -> Iterator[List[Record]]:
        for i in range(0, len(self.records), self.batch_size):
            yield self.records[i:i + self.batch_size]


class FileStreamingReader(StreamingReader):
    """One batch per new file matching a glob pattern, in mtime order
    (the reference's 'new files in a directory' DStream source). `poll()`
    re-scans and yields only unseen files, enabling tail-follow loops."""

    def __init__(self, pattern: str, reader_factory: Callable[[str], Reader],
                 key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self.pattern = pattern
        self.reader_factory = reader_factory
        self._seen: set = set()

    def _paths(self) -> List[str]:
        paths = [p for p in glob.glob(self.pattern) if p not in self._seen]
        return sorted(paths, key=lambda p: (os.path.getmtime(p), p))

    def stream(self) -> Iterator[List[Record]]:
        for p in self._paths():
            self._seen.add(p)
            yield self.reader_factory(p).read()

    def poll(self) -> List[List[Record]]:
        return [batch for batch in self.stream()]


class AvroStreamingReader(FileStreamingReader):
    """Reference StreamingReaders.Simple.avro."""

    def __init__(self, pattern: str,
                 key_fn: Optional[Callable[[Record], str]] = None):
        from .avro import AvroReader
        super().__init__(pattern, lambda p: AvroReader(p), key_fn)


class CSVStreamingReader(FileStreamingReader):
    def __init__(self, pattern: str,
                 key_fn: Optional[Callable[[Record], str]] = None):
        from .readers import CSVReader
        super().__init__(pattern, lambda p: CSVReader(p), key_fn)


def score_stream(model, stream_reader: StreamingReader
                 ) -> Iterator[List[Dict[str, Any]]]:
    """Score every micro-batch with the fitted workflow's row function
    (reference StreamingScore: per-batch scoreFn over the DStream)."""
    fn = model.score_function()
    for batch in stream_reader.stream():
        yield [fn(r) for r in batch]
