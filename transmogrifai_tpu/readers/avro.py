"""Avro Object Container File reader (pure Python, no dependency).

Reference: readers/.../AvroReaders.scala + utils/.../io/AvroInOut.scala —
Avro is the reference's native event format. This is a self-contained OCF
decoder: magic/metadata/sync framing, null and deflate codecs, and the
standard binary encoding for records of null/boolean/int/long/float/double/
bytes/string/enum/fixed/array/map/union — the shapes the reference's
schemas (e.g. Passenger) use.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import (Any, BinaryIO, Callable, Dict, Iterator, List,
                    Optional, Sequence, Tuple)

_MAGIC = b"Obj\x01"


class AvroDecodeError(ValueError):
    pass


class _Bin:
    """Avro binary decoder over a byte buffer."""

    def __init__(self, data: bytes):
        self.buf = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        if n < 0:  # negative decoded length would rewind the cursor
            raise AvroDecodeError("negative length in avro data")
        if self.pos + n > len(self.buf):
            raise AvroDecodeError("truncated avro data")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    # zig-zag varint
    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            if self.pos >= len(self.buf):
                raise AvroDecodeError("truncated avro data")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
            if shift > 63:
                raise AvroDecodeError("malformed varint (shift > 63)")
        return (acc >> 1) ^ -(acc & 1)

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def boolean(self) -> bool:
        return self.read(1) != b"\x00"


def _resolve(schema: Any, named: Dict[str, Any]) -> Any:
    if isinstance(schema, str) and schema in named:
        return named[schema]
    return schema


def _collect_named(schema: Any, named: Dict[str, Any]) -> None:
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            named[schema["name"]] = schema
            ns = schema.get("namespace")
            if ns:
                named[f"{ns}.{schema['name']}"] = schema
        for key in ("fields", "items", "values"):
            v = schema.get(key)
            if isinstance(v, list):
                for f in v:
                    _collect_named(f.get("type") if isinstance(f, dict)
                                   else f, named)
            elif v is not None:
                _collect_named(v, named)
    elif isinstance(schema, list):
        for s in schema:
            _collect_named(s, named)


def _decode(schema: Any, d: _Bin, named: Dict[str, Any]) -> Any:
    schema = _resolve(schema, named)
    if isinstance(schema, list):                     # union
        idx = d.long()
        if idx < 0 or idx >= len(schema):
            raise AvroDecodeError(f"bad union index {idx}")
        return _decode(schema[idx], d, named)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: _decode(f["type"], d, named)
                    for f in schema["fields"]}
        if t == "enum":
            return schema["symbols"][d.long()]
        if t == "fixed":
            return d.read(int(schema["size"]))
        if t == "array":
            out: List[Any] = []
            while True:
                n = d.long()
                if n == 0:
                    break
                if n < 0:
                    d.long()  # block byte size, unused
                    n = -n
                for _ in range(n):
                    out.append(_decode(schema["items"], d, named))
            return out
        if t == "map":
            m: Dict[str, Any] = {}
            while True:
                n = d.long()
                if n == 0:
                    break
                if n < 0:
                    d.long()
                    n = -n
                for _ in range(n):
                    k = d.string()
                    m[k] = _decode(schema["values"], d, named)
            return m
        # logical types ride on a primitive "type"
        return _decode(t, d, named)
    # primitive
    if schema == "null":
        return None
    if schema == "boolean":
        return d.boolean()
    if schema in ("int", "long"):
        return d.long()
    if schema == "float":
        return d.float_()
    if schema == "double":
        return d.double()
    if schema == "bytes":
        return d.bytes_()
    if schema == "string":
        return d.string()
    raise AvroDecodeError(f"unsupported schema: {schema!r}")


def _open_ocf(path: str) -> Tuple[_Bin, Any, str, bytes, Dict[str, Any]]:
    """Parse one OCF header: returns the decoder positioned at the first
    data block plus (schema, codec, sync, named-type registry). Shared
    by the record iterator and the columnar block reader so both see
    the identical framing/codec contract."""
    with open(path, "rb") as f:
        data = f.read()
    d = _Bin(data)
    if d.read(4) != _MAGIC:
        raise AvroDecodeError(f"{path}: not an Avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = d.long()
        if n == 0:
            break
        if n < 0:
            d.long()
            n = -n
        for _ in range(n):
            k = d.string()
            meta[k] = d.bytes_()
    sync = d.read(16)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode()
    named: Dict[str, Any] = {}
    _collect_named(schema, named)
    return d, schema, codec, sync, named


def _iter_ocf_blocks(d: _Bin, codec: str, sync: bytes
                     ) -> Iterator[Tuple[int, _Bin]]:
    """Yield (record_count, block decoder) per data block."""
    while not d.at_end():
        count = d.long()
        size = d.long()
        block = d.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise AvroDecodeError(f"unsupported codec {codec!r}")
        yield count, _Bin(block)
        if d.read(16) != sync:
            raise AvroDecodeError("sync marker mismatch")


def read_avro_file(path: str) -> Iterator[Dict[str, Any]]:
    """Iterate records of one OCF file."""
    d, schema, codec, sync, named = _open_ocf(path)
    for count, bd in _iter_ocf_blocks(d, codec, sync):
        for _ in range(count):
            yield _decode(schema, bd, named)


def read_avro_columns(path: str, *,
                      fields: Optional[Sequence[str]] = None,
                      batch_records: int = 8192
                      ) -> Iterator[Dict[str, List[Any]]]:
    """Stream one OCF file as `{field -> value list}` COLUMN chunks of
    up to `batch_records` records: block decode appends each field value
    straight into its column list — the per-record dict the row readers
    build (and the per-cell walk consuming it) never exists. The
    sharded ingest engine's parse workers feed these lists to ONE
    vectorized conversion per column (readers.columnar_f32,
    docs/performance.md "Ingest pipeline").

    The top-level schema must be a record (what write_avro_file and
    every reference DataReaders.Simple.avro flow produce). `fields`
    restricts OUTPUT to the named subset — the wire format is
    positional, so skipped fields still decode, they just never
    allocate per-record containers."""
    d, schema, codec, sync, named = _open_ocf(path)
    schema = _resolve(schema, named)
    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        raise AvroDecodeError(
            f"{path}: columnar decode needs a top-level record schema, "
            f"got {schema!r}")
    fspecs = [(f["name"], f["type"]) for f in schema["fields"]]
    keep = set(fields) if fields is not None else None
    out_names = [nm for nm, _ in fspecs if keep is None or nm in keep]
    cols: Dict[str, List[Any]] = {nm: [] for nm in out_names}
    n_buf = 0
    for count, bd in _iter_ocf_blocks(d, codec, sync):
        for _ in range(count):
            for nm, ftype in fspecs:
                v = _decode(ftype, bd, named)
                if keep is None or nm in keep:
                    cols[nm].append(v)
            n_buf += 1
            if n_buf >= batch_records:
                yield cols
                cols = {nm: [] for nm in out_names}
                n_buf = 0
    if n_buf:
        yield cols


from .readers import Reader


class AvroReader(Reader):
    """Reader over one or more Avro container files (reference
    DataReaders.Simple.avro, AvroReaders.scala)."""

    def __init__(self, paths, key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(key_fn)
        self.paths = [paths] if isinstance(paths, str) else list(paths)

    def read(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for p in self.paths:
            out.extend(read_avro_file(p))
        return out


# -- writer (for test fixtures + score export) ------------------------------

def _zigzag(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    return bytes(out)


def _encode(schema: Any, v: Any, out: bytearray) -> None:
    if isinstance(schema, list):  # union: null | T
        if v is None:
            out += _zigzag(schema.index("null"))
            return
        idx = next(i for i, s in enumerate(schema) if s != "null")
        out += _zigzag(idx)
        _encode(schema[idx], v, out)
        return
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _encode(f["type"], v.get(f["name"]), out)
            return
        if t == "array":
            if v:
                out += _zigzag(len(v))
                for item in v:
                    _encode(schema["items"], item, out)
            out += _zigzag(0)
            return
        if t == "map":
            if v:
                out += _zigzag(len(v))
                for k, item in v.items():
                    _encode("string", k, out)
                    _encode(schema["values"], item, out)
            out += _zigzag(0)
            return
        _encode(t, v, out)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out += b"\x01" if v else b"\x00"
    elif schema in ("int", "long"):
        out += _zigzag(int(v))
    elif schema == "float":
        out += struct.pack("<f", float(v))
    elif schema == "double":
        out += struct.pack("<d", float(v))
    elif schema == "string":
        b = str(v).encode("utf-8")
        out += _zigzag(len(b)) + b
    elif schema == "bytes":
        out += _zigzag(len(v)) + bytes(v)
    else:
        raise AvroDecodeError(f"unsupported write schema {schema!r}")


def write_avro_file(path: str, schema: Dict[str, Any],
                    records: List[Dict[str, Any]],
                    codec: str = "null") -> None:
    if codec not in ("null", "deflate"):
        # an unknown codec would be STAMPED into the container header
        # over an uncompressed payload — unreadable far from the cause
        raise ValueError(f"unsupported Avro codec {codec!r} "
                         f"(null | deflate)")
    sync = b"\x00\x01\x02\x03\x04\x05\x06\x07\x08\x09\x0a\x0b\x0c\x0d\x0e\x0f"
    out = bytearray()
    out += _MAGIC
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out += _zigzag(len(meta))
    for k, v in meta.items():
        kb = k.encode()
        out += _zigzag(len(kb)) + kb + _zigzag(len(v)) + v
    out += _zigzag(0)
    out += sync
    block = bytearray()
    for r in records:
        _encode(schema, r, block)
    payload = bytes(block)
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    out += _zigzag(len(records)) + _zigzag(len(payload)) + payload + sync
    with open(path, "wb") as f:
        f.write(bytes(out))


def infer_avro_schema(rows: List[Dict[str, Any]],
                      name: str = "Record") -> Dict[str, Any]:
    """Infer a nullable Avro record schema from python rows (reference
    utils/io/CSVToAvro + CSVAutoReaders schema inference): bool -> boolean,
    64-bit int -> long, float -> double, everything else -> string
    (including out-of-range ints, which a "long" varint would silently
    wrap); a column with any missing value becomes a [null, T] union.
    Names are sanitized to the Avro name grammar
    ([A-Za-z_][A-Za-z0-9_]*) so spec-compliant readers accept the file;
    the original column names stay as the field order's source keys via
    csv_to_avro's mapping."""
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    lo, hi = -(1 << 63), (1 << 63) - 1
    fields = []
    used = set()
    for k in keys:
        vals = [r.get(k) for r in rows]
        present = [v for v in vals if v is not None]
        nullable = len(present) < len(vals)
        if present and all(isinstance(v, bool) for v in present):
            t = "boolean"
        elif present and all(
                isinstance(v, bool)
                or (isinstance(v, int) and lo <= v <= hi)
                for v in present):
            t = "long"
        elif present and all(isinstance(v, (bool, float))
                             or (isinstance(v, int) and lo <= v <= hi)
                             for v in present):
            t = "double"
        else:
            t = "string"
        fields.append({"name": _dedup_name(avro_name(k), used),
                       "type": ["null", t] if nullable or not present
                       else t})
    return {"type": "record", "name": avro_name(name), "fields": fields}


def _dedup_name(base: str, used: set) -> str:
    """Distinct sanitized names: 'a-b' and 'a_b' both map to 'a_b', which
    would be a spec-invalid duplicate field AND silently collapse a
    column — suffix collisions instead."""
    out = base
    i = 2
    while out in used:
        out = f"{base}_{i}"
        i += 1
    used.add(out)
    return out


def avro_name(raw: str) -> str:
    """Sanitize to the Avro name grammar [A-Za-z_][A-Za-z0-9_]*
    (ASCII only — unicode alphanumerics are rejected by spec readers)."""
    out = "".join(c if ("a" <= c <= "z" or "A" <= c <= "Z"
                        or "0" <= c <= "9" or c == "_") else "_"
                  for c in raw)
    if not out or "0" <= out[0] <= "9":
        out = "_" + out
    return out


def csv_to_avro(csv_path: str, avro_path: str,
                schema: Optional[Dict[str, Any]] = None,
                codec: str = "null") -> Dict[str, Any]:
    """Convert a CSV file to Avro (reference utils/io/CSVToAvro): read
    with the CSV reader's type coercion, infer a nullable record schema
    unless one is given, write with the container codec. Returns the
    schema used."""
    from .readers import CSVReader

    rows = CSVReader(csv_path).read()
    headers: List[str] = []
    if rows:
        for r in rows:
            for k in r:
                if k not in headers:
                    headers.append(k)
    else:
        # header-only CSV: the header still declares the columns
        # (reference CSVToAvro derives the schema from the header)
        import csv as _csv
        with open(csv_path, newline="") as f:
            first = next(_csv.reader(f), [])
        headers = [h for h in first if h]
    if schema is None:
        base = os.path.splitext(os.path.basename(csv_path))[0]
        if rows:
            schema = infer_avro_schema(rows, name=base.title())
        else:
            used: set = set()
            schema = {"type": "record", "name": avro_name(base.title()),
                      "fields": [{"name": _dedup_name(avro_name(h), used),
                                  "type": ["null", "string"]}
                                 for h in headers]}
    # Avro field name -> original CSV column. Resolve by NAME (direct
    # header match, then unique sanitized match); fall back to position
    # only for the leftovers — a caller-supplied schema may order fields
    # differently from the CSV, where a pure positional zip would swap
    # columns.
    by_sanitized: Dict[str, List[str]] = {}
    for h in headers:
        by_sanitized.setdefault(avro_name(h), []).append(h)
    key_of: Dict[str, str] = {}
    unresolved = []
    taken = set()
    for f in schema["fields"]:
        fn = f["name"]
        if fn in headers:
            key_of[fn] = fn
            taken.add(fn)
        elif len(by_sanitized.get(fn, [])) == 1:
            key_of[fn] = by_sanitized[fn][0]
            taken.add(key_of[fn])
        else:
            unresolved.append(fn)
    leftovers = [h for h in headers if h not in taken]
    for fn, h in zip(unresolved, leftovers):
        key_of[fn] = h
    types = {f["name"]: f["type"] for f in schema["fields"]}

    def norm(fname, v):
        t = types.get(fname)
        t = [x for x in t if x != "null"][0] if isinstance(t, list) else t
        if v is None:
            return None
        if t == "string" and not isinstance(v, str):
            return str(v)
        if t == "double" and isinstance(v, (int, bool)):
            return float(v)
        if t == "long" and isinstance(v, float) and float(v).is_integer():
            return int(v)
        return v

    records = [{fn: norm(fn, r.get(key_of.get(fn, fn))) for fn in types}
               for r in rows]
    write_avro_file(avro_path, schema, records, codec=codec)
    return schema
