"""Data readers: ingestion + temporal aggregation.

Reference: readers/ module — Reader.scala:96, DataReader.scala:57-252,
JoinedDataReader.scala, DataReaders factory. The reference delegates
partitioned execution to Spark; here ingestion is a host-side columnar
pipeline (records -> extract per raw feature -> typed Column arrays) feeding
the device matrix. reduceByKey becomes an in-memory group-by with monoid
aggregators (the same per-feature aggregators, applied with cutoff-time
semantics).
"""
from __future__ import annotations

import csv as _csv
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Dataset, column_from_values
from ..features.feature import Feature
from ..features.generator import FeatureGeneratorStage
from ..types import FeatureType


Record = Any  # dict-like or object; feature extract fns know how to read it


class Reader:
    """Base reader: produce records, then materialize the raw-feature dataset
    (reference Reader.generateDataFrame, DataReader.scala:173)."""

    def __init__(self, key_fn: Optional[Callable[[Record], str]] = None):
        self.key_fn = key_fn

    def read(self) -> List[Record]:
        raise NotImplementedError

    # -- joins (reference Reader.scala:112-134) ----------------------------
    def outer_join(self, other: "Reader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, join_type="outer", **kw)

    def left_outer_join(self, other: "Reader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, join_type="left", **kw)

    def inner_join(self, other: "Reader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, join_type="inner", **kw)

    def _generator_of(self, f: Feature) -> FeatureGeneratorStage:
        st = f.origin_stage
        if not isinstance(st, FeatureGeneratorStage):
            raise ValueError(f"Feature '{f.name}' is not a raw feature")
        return st

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        records = self.read()
        gens = [self._generator_of(f) for f in raw_features]
        cols = {}
        for f, g in zip(raw_features, gens):
            vals = [g.extract(r) for r in records]
            cols[f.name] = column_from_values(f.feature_type, vals)
        key_col = None
        if self.key_fn is not None:
            keys = np.empty(len(records), dtype=object)
            for i, r in enumerate(records):
                keys[i] = str(self.key_fn(r))
            from ..data.dataset import Column
            from ..types import ColumnKind
            key_col = Column(kind=ColumnKind.STRING, data=keys)
        ds = Dataset(cols)
        if key_col is not None:
            ds = ds.with_column(KEY_COLUMN, key_col)
        return ds


KEY_COLUMN = "key"


class ListReader(Reader):
    """Reader over in-memory records (dicts or objects)."""

    def __init__(self, records: Sequence[Record],
                 key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self._records = list(records)

    def read(self) -> List[Record]:
        return self._records


class CSVReader(Reader):
    """CSV reader with light type coercion (reference CSVReaders.scala /
    CSVAutoReaders.scala — schema'd and auto-inferring variants)."""

    def __init__(self, path: str, key_fn: Optional[Callable[[Record], str]] = None,
                 schema: Optional[Dict[str, Callable[[str], Any]]] = None,
                 null_values: Sequence[str] = ("", "NA", "null", "NULL", "None"),
                 columns: Optional[Sequence[str]] = None):
        """``columns`` names the fields of a HEADERLESS file (reference
        ``DataReaders.Simple.csvCase`` reads schema from the case class, so
        its files carry no header row — e.g. the Titanic training CSV)."""
        super().__init__(key_fn)
        self.path = path
        self.schema = schema
        self.null_values = set(null_values)
        self.columns = list(columns) if columns is not None else None

    def _coerce(self, name: str, v: str) -> Any:
        if v is None or v in self.null_values:
            return None
        if self.schema and name in self.schema:
            try:
                return self.schema[name](v)
            except (ValueError, TypeError):
                return None
        try:
            f = float(v)
            if f.is_integer() and "." not in v and "e" not in v.lower():
                return int(v)
            return f
        except ValueError:
            return v

    def read(self) -> List[Record]:
        # native C++ scan when built (ops/native_bridge; the reference's
        # spark-csv data-loader slot), python csv module otherwise
        try:
            from ..ops.native_bridge import native_csv_parse
            with open(self.path, "rb") as fb:
                rows = native_csv_parse(fb.read())
        except Exception:
            rows = None
        if rows is not None and rows:
            if self.columns is not None:
                header, body = self.columns, rows
            else:
                header, body = rows[0], rows[1:]
            return [{k: self._coerce(k, v)
                     for k, v in zip(header, self._checked(r, i))}
                    for i, r in enumerate(body) if any(f != "" for f in r)]
        out: List[Record] = []
        with open(self.path, newline="") as fh:
            if self.columns is not None:
                for i, raw in enumerate(_csv.reader(fh)):
                    if any(f != "" for f in raw):
                        out.append({k: self._coerce(k, v) for k, v
                                    in zip(self.columns, self._checked(raw, i))})
            else:
                for row in _csv.DictReader(fh):
                    out.append({k: self._coerce(k, v) for k, v in row.items()})
        return out

    def _checked(self, row: Sequence[str], i: int) -> Sequence[str]:
        """In explicit-columns mode a field-count mismatch is malformed input
        — zip() would silently null or drop trailing fields otherwise."""
        if self.columns is not None and len(row) != len(self.columns):
            raise ValueError(
                f"{self.path}: row {i + 1} has {len(row)} fields, expected "
                f"{len(self.columns)} ({', '.join(self.columns[:4])}...)")
        return row

    def iter_records(self) -> Iterable[Record]:
        """Stream records one at a time off the file handle (python csv
        module only — no whole-file native scan). The bulk monitor route
        (monitor/offline._file_stream_reader) reads through this so the
        tileplane pulls record batches incrementally instead of
        materializing the file before the first tile scores."""
        with open(self.path, newline="") as fh:
            if self.columns is not None:
                for i, raw in enumerate(_csv.reader(fh)):
                    if any(f != "" for f in raw):
                        yield {k: self._coerce(k, v) for k, v
                               in zip(self.columns, self._checked(raw, i))}
            else:
                for row in _csv.DictReader(fh):
                    yield {k: self._coerce(k, v) for k, v in row.items()}


# -- columnar decode (parallel/ingest fast lane) ------------------------------

_F32_NULL_VALUES = ("", "NA", "null", "NULL", "None")


def columnar_f32(values: Sequence[Any],
                 null_values: Sequence[str] = _F32_NULL_VALUES
                 ) -> np.ndarray:
    """ONE vectorized float32 conversion for a whole column chunk — the
    columnar replacement for the per-cell CSVReader._coerce walk on
    numeric ingest paths (parallel/ingest.sharded_reader_source).

    String columns map the null spellings to NaN in one `np.isin` pass,
    then parse with a single `astype`; numeric/bool columns are one
    `astype`; object columns (Avro nullable unions) map None -> NaN in
    one array build. Null handling matches _coerce's None for the
    zero-weight / NaN-missing conventions downstream."""
    arr = np.asarray(values)
    if arr.dtype.kind in "fiub":
        return arr.astype(np.float32, copy=False)
    if arr.dtype.kind in "US":
        if null_values:
            mask = np.isin(arr, np.asarray(list(null_values)))
            if mask.any():
                arr = np.where(mask, "nan", arr)
        return arr.astype(np.float32)
    return np.array([np.nan if v is None else v for v in values],
                    dtype=np.float32)


def csv_columnar_chunks(path: str, *,
                        columns: Optional[Sequence[str]] = None,
                        fields: Optional[Sequence[str]] = None,
                        batch_records: int = 8192,
                        null_values: Sequence[str] = _F32_NULL_VALUES
                        ) -> Iterable[Dict[str, np.ndarray]]:
    """Stream a CSV file as `{column -> float32 array}` chunks of up to
    `batch_records` rows: rows buffer raw, transpose once per chunk
    (a single C-level `zip(*rows)`), and each kept column converts with
    ONE vectorized columnar_f32 call — no per-cell coercion, no
    per-record dicts. This is the parse-worker decode of the sharded
    ingest engine (docs/performance.md "Ingest pipeline").

    `fields` names the columns of a HEADERLESS file (same contract as
    CSVReader(columns=...)); otherwise the first row is the header.
    `columns` restricts output to the named subset (decode still reads
    every cell off disk, but only kept columns pay conversion). Blank
    rows are skipped and a field-count mismatch raises — same
    malformed-input posture as CSVReader._checked."""
    with open(path, newline="") as fh:
        reader = _csv.reader(fh)
        if fields is not None:
            names = [str(c) for c in fields]
        else:
            try:
                names = next(reader)
            except StopIteration:
                return
        keep = [(nm, j) for j, nm in enumerate(names)
                if columns is None or nm in set(columns)]
        n_fields = len(names)
        buf: List[Sequence[str]] = []

        def flush() -> Dict[str, np.ndarray]:
            cols = list(zip(*buf))
            return {nm: columnar_f32(cols[j], null_values)
                    for nm, j in keep}

        for i, raw in enumerate(reader):
            if not any(f != "" for f in raw):
                continue
            if len(raw) != n_fields:
                raise ValueError(
                    f"{path}: row {i + 1} has {len(raw)} fields, "
                    f"expected {n_fields}")
            buf.append(raw)
            if len(buf) >= batch_records:
                yield flush()
                buf = []
        if buf:
            yield flush()


class JSONLinesReader(Reader):
    def __init__(self, path: str, key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self.path = path

    def read(self) -> List[Record]:
        with open(self.path) as fh:
            return [json.loads(line) for line in fh if line.strip()]


class ParquetReader(Reader):
    """Parquet via pyarrow if available (reference ParquetProductReader)."""

    def __init__(self, path: str, key_fn: Optional[Callable[[Record], str]] = None):
        super().__init__(key_fn)
        self.path = path

    def read(self) -> List[Record]:
        try:
            import pyarrow.parquet as pq  # optional dep
        except ImportError as e:
            raise ImportError(
                "ParquetReader requires pyarrow; not available in this "
                "environment — use CSVReader/JSONLinesReader") from e
        table = pq.read_table(self.path)
        return table.to_pylist()


class AggregateReader(Reader):
    """Groups event records by key and aggregates each feature with its monoid
    aggregator relative to a cutoff time — one output row per key (reference
    AggregatedReader.generateDataFrame, DataReader.scala:206-252)."""

    def __init__(self, base: Reader, key_fn: Callable[[Record], str],
                 cutoff_time: Optional[int] = None,
                 event_time_fn: Optional[Callable[[Record], Optional[int]]] = None):
        super().__init__(key_fn)
        self.base = base
        self.cutoff_time = cutoff_time
        self.event_time_fn = event_time_fn

    def read(self) -> List[Record]:
        return self.base.read()

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        records = self.read()
        gens = [self._generator_of(f) for f in raw_features]
        # group by key preserving first-seen order (reduceByKey equivalent)
        groups: Dict[str, List[Record]] = {}
        order: List[str] = []
        for r in records:
            k = str(self.key_fn(r))
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(r)
        cols: Dict[str, Any] = {}
        for f, g in zip(raw_features, gens):
            time_fn = g.event_time_fn or self.event_time_fn
            vals = []
            for k in order:
                events = []
                for r in groups[k]:
                    t = time_fn(r) if time_fn else None
                    events.append((g.extract(r), t))
                vals.append(g.aggregator.extract(
                    events, cutoff_time=self.cutoff_time,
                    is_response=f.is_response))
            cols[f.name] = column_from_values(f.feature_type, vals)
        ds = Dataset(cols)
        keys = np.empty(len(order), dtype=object)
        for i, k in enumerate(order):
            keys[i] = k
        from ..data.dataset import Column
        from ..types import ColumnKind
        return ds.with_column(KEY_COLUMN, Column(kind=ColumnKind.STRING, data=keys))


class ConditionalReader(AggregateReader):
    """Two-pass temporal reader (reference ConditionalDataReader): pass 1
    finds each key's target time via a condition; pass 2 aggregates
    predictors before and responses after that per-key time."""

    def __init__(self, base: Reader, key_fn: Callable[[Record], str],
                 condition_fn: Callable[[Record], bool],
                 event_time_fn: Callable[[Record], Optional[int]],
                 drop_if_no_condition: bool = True):
        super().__init__(base, key_fn, cutoff_time=None, event_time_fn=event_time_fn)
        self.condition_fn = condition_fn
        self.drop_if_no_condition = drop_if_no_condition

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        records = self.read()
        gens = [self._generator_of(f) for f in raw_features]
        groups: Dict[str, List[Record]] = {}
        order: List[str] = []
        for r in records:
            k = str(self.key_fn(r))
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(r)
        # pass 1: per-key target time = earliest event satisfying condition
        target: Dict[str, Optional[int]] = {}
        for k in order:
            times = [self.event_time_fn(r) for r in groups[k] if self.condition_fn(r)]
            times = [t for t in times if t is not None]
            target[k] = min(times) if times else None
        keep = [k for k in order
                if target[k] is not None or not self.drop_if_no_condition]
        cols: Dict[str, Any] = {}
        for f, g in zip(raw_features, gens):
            vals = []
            for k in keep:
                events = [(g.extract(r), self.event_time_fn(r)) for r in groups[k]]
                vals.append(g.aggregator.extract(
                    events, cutoff_time=target[k], is_response=f.is_response))
            cols[f.name] = column_from_values(f.feature_type, vals)
        ds = Dataset(cols)
        keys = np.empty(len(keep), dtype=object)
        for i, k in enumerate(keep):
            keys[i] = k
        from ..data.dataset import Column
        from ..types import ColumnKind
        return ds.with_column(KEY_COLUMN, Column(kind=ColumnKind.STRING, data=keys))


def _merge_join_indices(lkeys: np.ndarray, rkeys: np.ndarray,
                        join_type: str):
    """Columnar one-to-many join plan: (l_idx, r_idx) row-index arrays into
    the two sides (-1 = no match on that side). Sorted-merge via
    argsort/searchsorted — no per-row python dict (reference
    JoinedDataReader joins Spark DataFrames; a 10M-row parent-child join
    must not walk a hash per row on the host)."""
    L = len(lkeys)
    if len(rkeys) == 0:
        if join_type in ("left", "outer"):
            return np.arange(L, dtype=np.int64), np.full(L, -1, np.int64)
        return np.empty(0, np.int64), np.empty(0, np.int64)
    r_order = np.argsort(rkeys, kind="stable")
    rsorted = rkeys[r_order]
    lo = np.searchsorted(rsorted, lkeys, "left")
    hi = np.searchsorted(rsorted, lkeys, "right")
    m = hi - lo
    n_per = np.where(m > 0, m, 1 if join_type in ("left", "outer") else 0)
    total = int(n_per.sum())
    l_idx = np.repeat(np.arange(L), n_per)
    starts = np.cumsum(n_per) - n_per
    off = np.arange(total) - np.repeat(starts, n_per)
    has = np.repeat(m > 0, n_per)
    r_pos = np.repeat(lo, n_per) + off
    r_idx = np.where(has, r_order[np.where(has, r_pos, 0)], -1)
    if join_type == "outer" and len(rkeys):
        # append right rows whose key never appears on the left
        if L:
            lsorted = np.sort(lkeys)
            pos = np.clip(np.searchsorted(lsorted, rkeys), 0, L - 1)
            matched = lsorted[pos] == rkeys
        else:
            matched = np.zeros(len(rkeys), bool)
        extra = np.flatnonzero(~matched)
        l_idx = np.concatenate([l_idx, np.full(len(extra), -1)])
        r_idx = np.concatenate([r_idx, extra])
    return l_idx.astype(np.int64), r_idx.astype(np.int64)


def _gather_column(col, idx: np.ndarray):
    """Columnar take with -1 -> missing, preserving the column's storage
    (NaN for float kinds, None for object kinds)."""
    from ..data.dataset import Column
    from ..types import ColumnKind
    miss = idx < 0
    safe = np.where(miss, 0, idx)
    data = col.data
    if not isinstance(data, np.ndarray):
        data = np.asarray(data, dtype=object)
    if len(data) == 0:   # gathering from an empty side: all-missing rows
        if col.kind == ColumnKind.VECTOR:
            out = np.full((len(idx), 0), np.nan, np.float32)
        elif data.dtype.kind == "f":
            out = np.full(len(idx), np.nan)
        else:
            out = np.full(len(idx), None, dtype=object)
        return Column(kind=col.kind, data=out, metadata=col.metadata)
    out = data[safe]
    if miss.any():
        out = out.copy()
        if data.dtype.kind == "f":
            out[miss] = np.nan
        else:
            out = out.astype(object)
            out[miss] = None
    return Column(kind=col.kind, data=out, metadata=col.metadata)


class JoinedReader(Reader):
    """Key-joins two readers' generated datasets (reference
    JoinedDataReader.scala:83). Columnar sorted-merge, one-to-many aware:
    joining a parent reader to an event-level child reader emits one row
    per (parent, child event) pair — feed that to
    ``with_secondary_aggregation`` to re-aggregate per key afterwards
    (reference JoinedAggregateDataReader)."""

    def __init__(self, left: Reader, right: Reader, join_type: str = "outer",
                 left_features: Optional[Sequence[str]] = None,
                 right_features: Optional[Sequence[str]] = None):
        super().__init__(None)
        self.left = left
        self.right = right
        if join_type not in ("outer", "inner", "left"):
            raise ValueError(f"Unsupported join type: {join_type}")
        self.join_type = join_type
        self.left_features = set(left_features) if left_features else None
        self.right_features = set(right_features) if right_features else None

    def with_secondary_aggregation(
            self, time_filter: "TimeBasedFilter",
            combined: bool = False) -> "JoinedAggregateReader":
        """Re-aggregate joined child rows per key with a time-based filter
        (reference JoinedDataReader.withSecondaryAggregation:232)."""
        return JoinedAggregateReader(
            self.left, self.right, time_filter, join_type=self.join_type,
            left_features=self.left_features,
            right_features=self.right_features, combined=combined)

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        left_feats, right_feats = [], []
        for f in raw_features:
            side = self._side_of(f)
            (left_feats if side == "left" else right_feats).append(f)
        lds = self.left.generate_dataset(left_feats)
        rds = self.right.generate_dataset(right_feats)
        if KEY_COLUMN not in lds or KEY_COLUMN not in rds:
            raise ValueError("JoinedReader requires key_fn on both readers")
        lkeys = np.asarray(lds.data(KEY_COLUMN), dtype=object)
        rkeys = np.asarray(rds.data(KEY_COLUMN), dtype=object)
        l_idx, r_idx = _merge_join_indices(lkeys, rkeys, self.join_type)
        cols: Dict[str, Any] = {}
        for f in left_feats:
            cols[f.name] = _gather_column(lds.column(f.name), l_idx)
        for f in right_feats:
            cols[f.name] = _gather_column(rds.column(f.name), r_idx)
        keys = np.empty(len(l_idx), dtype=object)
        lm = l_idx >= 0
        keys[lm] = lkeys[l_idx[lm]]
        keys[~lm] = rkeys[r_idx[~lm]]
        ds = Dataset(cols)
        from ..data.dataset import Column
        from ..types import ColumnKind
        return ds.with_column(
            KEY_COLUMN, Column(kind=ColumnKind.STRING, data=keys))

    def _side_of(self, f: Feature) -> str:
        """Route a feature to the reader whose records it extracts from:
        by explicit left_features/right_features name sets, else by the
        generator's reader_hint. Ambiguity is an error, not a guess."""
        if self.left_features is not None and f.name in self.left_features:
            return "left"
        if self.right_features is not None and f.name in self.right_features:
            return "right"
        hint = getattr(f.origin_stage, "reader_hint", None)
        if hint is self.left or hint == id(self.left):
            return "left"
        if hint is self.right or hint == id(self.right):
            return "right"
        raise ValueError(
            f"JoinedReader cannot route feature '{f.name}': pass "
            "left_features/right_features name lists or set the generator's "
            "reader_hint")


@dataclass
class TimeColumn:
    """Time column for post-join aggregation (reference TimeColumn,
    JoinedDataReader.scala:54): ``keep=False`` drops it from the result."""

    name: str
    keep: bool = True


@dataclass
class TimeBasedFilter:
    """Window filter for post-join conditional aggregation (reference
    TimeBasedFilter, JoinedDataReader.scala:69). ``time_window`` is in the
    same units as the two time columns (reference uses millis)."""

    condition: TimeColumn
    primary: TimeColumn
    time_window: int


class JoinedAggregateReader(JoinedReader):
    """Join then RE-AGGREGATE per key with a time-based filter (reference
    JoinedAggregateDataReader, JoinedDataReader.scala:250-345).

    The join emits one row per (parent, child event) pair; this reader then
    groups by key and folds each feature with its generator's monoid, but
    only over rows inside the feature's time window relative to the row's
    condition time (JoinedConditionalAggregator:430-441):

    - predictors: ``cutoff - window < t < cutoff``
    - responses:  ``cutoff <= t < cutoff + window``

    Parent-side features keep one copy per key (DummyJoinedAggregator)
    unless ``combined=True`` (reference isCombinedJoin), in which case they
    are window-filtered too. The per-feature window defaults to the
    filter's but is overridden by the feature generator's own
    ``aggregator.window_ms`` (reference getConditionalAggregators:337).
    """

    def __init__(self, left: Reader, right: Reader,
                 time_filter: TimeBasedFilter, join_type: str = "outer",
                 left_features: Optional[Sequence[str]] = None,
                 right_features: Optional[Sequence[str]] = None,
                 combined: bool = False):
        super().__init__(left, right, join_type=join_type,
                         left_features=left_features,
                         right_features=right_features)
        self.time_filter = time_filter
        self.combined = combined

    def _time_values(self, ds: Dataset, name: str) -> np.ndarray:
        """Column -> float64 time array; missing -> 0 (reference
        JoinedConditionalAggregator.update: getOrElse(0L))."""
        if name not in ds:
            raise ValueError(
                f"time filter column '{name}' is not in the joined data — "
                "include its feature in raw_features")
        arr = ds.column(name).data
        if isinstance(arr, np.ndarray) and arr.dtype.kind == "f":
            return np.nan_to_num(arr, nan=0.0)
        return np.array([0.0 if v is None else float(v) for v in arr])

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        joined = super().generate_dataset(raw_features)
        keys = np.asarray(joined.data(KEY_COLUMN), dtype=object)
        n = len(keys)
        # group numbers in first-seen key order (np.unique sorts; reorder
        # by first occurrence so output matches AggregateReader's order)
        uniq, first_idx, inv = np.unique(keys, return_index=True,
                                         return_inverse=True)
        rank = np.argsort(np.argsort(first_idx))
        group_of_row = rank[inv]
        n_groups = len(uniq)
        ordered_keys = uniq[np.argsort(first_idx)]
        # member rows per group, original order preserved within group
        row_order = np.argsort(group_of_row, kind="stable")
        bounds = np.searchsorted(group_of_row[row_order],
                                 np.arange(n_groups + 1))
        t = self._time_values(joined, self.time_filter.primary.name)
        cutoff = self._time_values(joined, self.time_filter.condition.name)

        left_names = {f.name for f in raw_features
                      if self._side_of(f) == "left"}
        drop = {c.name for c in (self.time_filter.condition,
                                 self.time_filter.primary) if not c.keep}
        cols: Dict[str, Any] = {}
        for f in raw_features:
            if f.name in drop:
                continue
            g = self._generator_of(f)
            data = joined.column(f.name).data
            is_float = isinstance(data, np.ndarray) and data.dtype.kind == "f"
            dummy = f.name in left_names and not self.combined
            if dummy:
                ok = np.ones(n, bool)
            else:
                w = g.aggregator.window_ms
                w = self.time_filter.time_window if w is None else w
                if f.is_response:
                    ok = (t >= cutoff) & (t < cutoff + w)
                else:
                    ok = (t < cutoff) & (t > cutoff - w)
            vals = []
            for gi in range(n_groups):
                rows = row_order[bounds[gi]:bounds[gi + 1]]
                if dummy:
                    # one copy per key (merge keeps the later value)
                    v = data[rows[-1]]
                    vals.append(None if is_float and np.isnan(v) else v)
                    continue
                rows = rows[ok[rows]]
                ev = [(None if is_float and np.isnan(data[r]) else data[r],
                       t[r]) for r in rows]
                vals.append(g.aggregator.aggregator.reduce(
                    [v for v, _ in ev], [tt for _, tt in ev]))
            cols[f.name] = column_from_values(f.feature_type, vals)
        ds = Dataset(cols)
        from ..data.dataset import Column
        from ..types import ColumnKind
        return ds.with_column(
            KEY_COLUMN, Column(kind=ColumnKind.STRING,
                               data=ordered_keys.astype(object)))


class DataReaders:
    """Factory namespace (reference DataReaders.scala:44-198)."""

    class Simple:
        csv = CSVReader
        json_lines = JSONLinesReader
        parquet = ParquetReader
        records = ListReader

    class Aggregate:
        @staticmethod
        def csv(path: str, key_fn, cutoff_time=None, event_time_fn=None, **kw):
            return AggregateReader(CSVReader(path, **kw), key_fn,
                                   cutoff_time, event_time_fn)

        @staticmethod
        def records(records, key_fn, cutoff_time=None, event_time_fn=None):
            return AggregateReader(ListReader(records), key_fn,
                                   cutoff_time, event_time_fn)

    class Conditional:
        @staticmethod
        def records(records, key_fn, condition_fn, event_time_fn, **kw):
            return ConditionalReader(ListReader(records), key_fn,
                                     condition_fn, event_time_fn, **kw)
