"""Vector column metadata: provenance of every column of the feature matrix.

Reference: features/.../utils/spark/{OpVectorMetadata,OpVectorColumnMetadata}.scala.
In the reference this provenance rides Spark DataFrame Metadata; here it is an
explicit sidecar carried next to the dense matrix, preserved through
save/load, and consumed by the SanityChecker (feature-to-drop reasons keyed by
column) and ModelInsights (per-feature contributions).
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional, Sequence

NULL_STRING = "NullIndicatorValue"   # reference OpVectorColumnMetadata.NullString
OTHER_STRING = "OTHER"               # reference OpVectorColumnMetadata.OtherString


@dataclass(frozen=True)
class VectorColumnMetadata:
    """One column of an assembled feature vector.

    parent_feature_name: raw/derived feature this column came from
    parent_feature_type: FeatureType type name of the parent
    grouping: name of the group (e.g. the categorical value set or map key)
    indicator_value: the categorical value this column indicates, if any
    descriptor_value: descriptor for non-indicator derived cols (e.g. 'x', 'y')
    index: position in the assembled vector
    """

    parent_feature_name: str
    parent_feature_type: str
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_STRING

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_STRING

    def column_name(self) -> str:
        parts = [self.parent_feature_name]
        if self.grouping is not None:
            parts.append(self.grouping)
        if self.indicator_value is not None:
            parts.append(self.indicator_value)
        elif self.descriptor_value is not None:
            parts.append(self.descriptor_value)
        return "_".join(parts)

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorColumnMetadata":
        return VectorColumnMetadata(**d)


@dataclass
class VectorMetadata:
    """Metadata of a whole assembled vector (reference OpVectorMetadata)."""

    name: str
    columns: List[VectorColumnMetadata] = field(default_factory=list)
    history: Dict[str, List[str]] = field(default_factory=dict)  # feature -> origin stage chain

    def __post_init__(self):
        self.columns = [
            VectorColumnMetadata(**{**c.to_json(), "index": i})
            if c.index != i else c
            for i, c in enumerate(self.columns)
        ]

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.column_name() for c in self.columns]

    def parent_features(self) -> List[str]:
        seen, out = set(), []
        for c in self.columns:
            if c.parent_feature_name not in seen:
                seen.add(c.parent_feature_name)
                out.append(c.parent_feature_name)
        return out

    def index_of(self, column_name: str) -> int:
        for c in self.columns:
            if c.column_name() == column_name:
                return c.index
        raise KeyError(column_name)

    def select(self, indices: Sequence[int]) -> "VectorMetadata":
        """Metadata after keeping only `indices` (SanityCheckerModel slice)."""
        cols = [VectorColumnMetadata(**{**self.columns[i].to_json(), "index": j})
                for j, i in enumerate(indices)]
        return VectorMetadata(name=self.name, columns=cols, history=dict(self.history))

    @staticmethod
    def concat(name: str, parts: Sequence["VectorMetadata"]) -> "VectorMetadata":
        cols: List[VectorColumnMetadata] = []
        history: Dict[str, List[str]] = {}
        for p in parts:
            for c in p.columns:
                cols.append(VectorColumnMetadata(**{**c.to_json(), "index": len(cols)}))
            history.update(p.history)
        return VectorMetadata(name=name, columns=cols, history=history)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "columns": [c.to_json() for c in self.columns],
            "history": self.history,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorMetadata":
        return VectorMetadata(
            name=d["name"],
            columns=[VectorColumnMetadata.from_json(c) for c in d["columns"]],
            history=dict(d.get("history", {})),
        )
