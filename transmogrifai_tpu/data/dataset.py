"""Columnar host-side dataset.

Replaces the reference's Spark DataFrame as the carrier of feature columns
(reference readers generate a schema'd DataFrame: readers/.../DataReader.scala:173).

TPU-first layout: numeric columns are dense numpy float64 with NaN-as-missing
so they lower straight to f32 device arrays; string/list/map columns are
host-only object arrays consumed by (two-phase) vectorizers which emit dense
VECTOR columns; VECTOR columns are 2-D float32 blocks with a VectorMetadata
sidecar — those blocks are what gets `device_put` onto the chip, sharded on
the batch mesh axis.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..types import (
    Binary, ColumnKind, FeatureType, Integral, OPMap, OPNumeric, OPVector,
    Real, Text,
)
from .vector import VectorMetadata


@dataclass
class Column:
    """One named column: kind + backing array (+ vector metadata if dense)."""

    kind: str
    data: Any  # np.ndarray (1-D object/float64, or 2-D float32 for VECTOR)
    metadata: Optional[VectorMetadata] = None

    def __len__(self) -> int:
        return len(self.data)

    @property
    def width(self) -> int:
        if self.kind == ColumnKind.VECTOR:
            return self.data.shape[1]
        return 1


def column_from_values(type_cls: Type[FeatureType], values: Iterable[Any]) -> Column:
    """Build a Column from raw per-row python values, coercing through the
    feature type (the columnar analogue of FeatureTypeSparkConverter)."""
    kind = type_cls.column_kind
    vals = [type_cls(v).value if not isinstance(v, FeatureType) else v.value
            for v in values]
    if kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL):
        arr = np.array(
            [np.nan if v is None else (1.0 if v is True else (0.0 if v is False else float(v)))
             for v in vals], dtype=np.float64)
        return Column(kind=kind, data=arr)
    if kind == ColumnKind.VECTOR:
        widths = {len(v) for v in vals}
        if len(widths) > 1:
            raise ValueError(f"Ragged vector column: widths {sorted(widths)}")
        mat = np.stack([np.asarray(v, dtype=np.float32) for v in vals]) if vals else \
            np.zeros((0, 0), dtype=np.float32)
        return Column(kind=kind, data=mat)
    # host-only object columns (string / lists / sets / maps / geo)
    arr = np.empty(len(vals), dtype=object)
    for i, v in enumerate(vals):
        arr[i] = v
    return Column(kind=kind, data=arr)


class Dataset:
    """Ordered dict of named columns with uniform row count."""

    def __init__(self, columns: Optional[Dict[str, Column]] = None,
                 n_rows: Optional[int] = None):
        self._columns: Dict[str, Column] = dict(columns or {})
        lengths = {len(c) for c in self._columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"Column length mismatch: {lengths}")
        self._n_rows = n_rows if n_rows is not None else (lengths.pop() if lengths else 0)

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_features(pairs: Sequence[Tuple[str, Type[FeatureType], Iterable[Any]]]
                      ) -> "Dataset":
        cols = {name: column_from_values(tcls, vals) for name, tcls, vals in pairs}
        return Dataset(cols)

    @staticmethod
    def from_dicts(rows: Sequence[Dict[str, Any]],
                   schema: Dict[str, Type[FeatureType]]) -> "Dataset":
        cols = {}
        for name, tcls in schema.items():
            cols[name] = column_from_values(tcls, [r.get(name) for r in rows])
        return Dataset(cols)

    # -- access ------------------------------------------------------------
    @property
    def n_rows(self) -> int:
        return self._n_rows

    def __len__(self) -> int:
        return self._n_rows

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    def column(self, name: str) -> Column:
        return self._columns[name]

    def __getitem__(self, name: str) -> Column:
        return self._columns[name]

    def data(self, name: str):
        return self._columns[name].data

    def with_column(self, name: str, col: Column) -> "Dataset":
        if self._columns and len(col) != self._n_rows:
            raise ValueError(
                f"Column '{name}' has {len(col)} rows, dataset has {self._n_rows}")
        cols = dict(self._columns)
        cols[name] = col
        return Dataset(cols, n_rows=len(col) if not self._columns else self._n_rows)

    def select(self, names: Sequence[str]) -> "Dataset":
        return Dataset({n: self._columns[n] for n in names}, n_rows=self._n_rows)

    def drop(self, names: Sequence[str]) -> "Dataset":
        drop = set(names)
        return Dataset({n: c for n, c in self._columns.items() if n not in drop},
                       n_rows=self._n_rows)

    def take(self, idx: np.ndarray) -> "Dataset":
        """Row subset/gather (used by splitters for the test holdout)."""
        cols = {}
        for n, c in self._columns.items():
            cols[n] = Column(kind=c.kind, data=c.data[idx], metadata=c.metadata)
        return Dataset(cols, n_rows=int(len(idx)))

    def head(self, k: int = 5) -> List[Dict[str, Any]]:
        out = []
        for i in range(min(k, self._n_rows)):
            row = {}
            for n, c in self._columns.items():
                v = c.data[i]
                row[n] = v.tolist() if isinstance(v, np.ndarray) else v
            out.append(row)
        return out

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{c.kind}" for n, c in self._columns.items())
        return f"Dataset(rows={self._n_rows}, columns=[{cols}])"
