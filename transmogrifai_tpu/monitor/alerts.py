"""Drift thresholds -> alerts: the policy layer of the monitor.

A DriftPolicy maps one window's drift report (monitor/drift.window_report)
to a list of typed alerts. Each alert becomes a ``drift_alert`` event on
the streaming event log (which ``trace-report --check`` surfaces as a
failure, exactly like a post-warmup ``serve_recompile``), a field in the
``GET /drift`` payload, and — when the optional hard health gate is on —
a degraded ``/healthz`` (HTTP 503) until a clean window closes.

Default thresholds follow the PSI conventions (0.25 = major shift) and
RawFeatureFilter's fill-rate semantics, tightened for serve-time use:
RFF's fit-time defaults (0.90 JS / 20x fill ratio) answer "is this
feature unusable?", the monitor's answer "has traffic moved enough that
a human should look?". `min_rows` suppresses alerts from windows too
small to be statistically meaningful (a timer-closed trickle window).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List


@dataclass
class DriftPolicy:
    """Per-window alert thresholds. None disables a check."""

    max_js: float = 0.25          # per-feature JS divergence, [0, 1] scale
    max_psi: float = 0.25         # per-feature PSI ("major shift" floor)
    max_fill_diff: float = 0.5    # |window fill-rate - train fill-rate|
    max_fill_ratio: float = 10.0  # max/min fill-rate ratio (inf alerts)
    max_pred_js: float = 0.25     # prediction calibration-bin JS
    max_score_shift: float = 0.2  # |window score mean - train mean|
    min_rows: int = 32            # windows below this never alert

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "DriftPolicy":
        return DriftPolicy(**{k: v for k, v in d.items()
                              if k in DriftPolicy().__dict__})

    # -- evaluation --------------------------------------------------------
    def _alert(self, target: str, metric: str, value,
               threshold: float) -> Dict[str, Any]:
        # value None = unbounded (an infinite fill ratio): every alert
        # payload must stay strict-RFC-8259 JSON — NaN/inf literals
        # would make /drift, the offline CLI report and events.jsonl
        # unparseable exactly when the worst drift fires
        return {"target": target, "metric": metric,
                "value": None if value is None else round(float(value), 6),
                "threshold": float(threshold)}

    def evaluate(self, report: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Alerts raised by one window report (drift.window_report calls
        this; the report dict carries the result under "alerts")."""
        out: List[Dict[str, Any]] = []
        if report.get("rows", 0.0) < self.min_rows:
            return out
        for f in report.get("features", []):
            name = f["feature"]
            if self.max_js is not None and f["js"] > self.max_js:
                out.append(self._alert(name, "js", f["js"], self.max_js))
            if self.max_psi is not None:
                # sampling-noise compensation (drift.psi_sampling_noise):
                # the effective threshold carries the expected PSI of an
                # UNdrifted window of this size plus 2x headroom for its
                # variance — tiny windows can't alert on pure noise,
                # production-size windows see max_psi essentially as-is
                thr = self.max_psi + 2.0 * f.get("psi_noise", 0.0)
                if f["psi"] > thr:
                    out.append(self._alert(name, "psi", f["psi"], thr))
            if self.max_fill_diff is not None and \
                    f["fill_rate_diff"] > self.max_fill_diff:
                out.append(self._alert(name, "fill_rate_diff",
                                       f["fill_rate_diff"],
                                       self.max_fill_diff))
            if self.max_fill_ratio is not None:
                ratio = f.get("fill_ratio")
                if ratio is None or ratio > self.max_fill_ratio:
                    # None = one side entirely empty (infinite ratio)
                    out.append(self._alert(name, "fill_ratio", ratio,
                                           self.max_fill_ratio))
        pred = report.get("prediction")
        if pred is not None and pred.get("rows", 0.0) >= self.min_rows:
            if self.max_pred_js is not None and pred["js"] > self.max_pred_js:
                out.append(self._alert("__prediction__", "prediction_js",
                                       pred["js"], self.max_pred_js))
            if self.max_psi is not None:
                thr = self.max_psi + 2.0 * pred.get("psi_noise", 0.0)
                if pred["psi"] > thr:
                    out.append(self._alert("__prediction__",
                                           "prediction_psi", pred["psi"],
                                           thr))
            if self.max_score_shift is not None and \
                    pred["mean_shift"] > self.max_score_shift:
                out.append(self._alert("__prediction__", "score_shift",
                                       pred["mean_shift"],
                                       self.max_score_shift))
        return out
