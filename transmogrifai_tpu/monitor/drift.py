"""Drift metrics over histogram sketches: JS divergence, PSI, fill rate,
prediction drift.

Host-side numpy on tiny [bins]-shaped tables (the window rollover path —
dispatching a device program per metric would cost more than the math).
`js_divergence_hist` is THE Jensen-Shannon implementation:
filters/sketches.FeatureDistribution.js_divergence (fit-time
RawFeatureFilter) delegates here, so fit-time and serve-time drift can
never disagree on the metric. Every comparison is defined for an
all-zero side: an EMPTY traffic window reports 0 drift, not NaN —
absence of evidence is not evidence of drift (the fill-rate gate is
what catches a feature that stopped arriving).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

EPS = 1e-12
#: Laplace pseudo-count added to every bin inside PSI — an empty bin in
#: a small window then reads as "about half an observation" instead of a
#: hard zero, keeping the log-ratio finite WITHOUT the blow-up a fixed
#: fraction floor produces (a floored-at-1e-4 empty bin against 10% of
#: train mass contributes ~0.7 PSI of pure sampling noise per bin)
PSI_PSEUDO = 0.5


def _normalize(h) -> Optional[np.ndarray]:
    """Histogram -> probability vector; None when the side is all-zero
    (or negative-garbage) so callers can apply the zero-window identity."""
    p = np.asarray(h, np.float64)
    s = p.sum()
    if not np.isfinite(s) or s <= 0.0:
        return None
    return p / s


def js_divergence_nats(p, q) -> float:
    """Jensen-Shannon divergence in nats: bounded [0, ln 2], symmetric,
    0.0 when either side is an all-zero histogram (zero-window identity).

    No epsilon in the log denominator: m = (p+q)/2 is strictly positive
    wherever p (or q) is, so the KL terms are well-defined exactly."""
    pn, qn = _normalize(p), _normalize(q)
    if pn is None or qn is None:
        return 0.0
    m = 0.5 * (pn + qn)

    def kl(a: np.ndarray) -> float:
        mask = a > 0
        return float(np.sum(a[mask] * np.log(a[mask] / m[mask])))

    # clip guards float round-off at the [0, ln 2] boundaries
    return float(np.clip(0.5 * kl(pn) + 0.5 * kl(qn), 0.0, np.log(2.0)))


def js_divergence_hist(p, q) -> float:
    """JS divergence scaled to [0, 1] (the FeatureDistribution
    convention: nats / ln 2)."""
    return js_divergence_nats(p, q) / float(np.log(2.0))


def coarsen(h, target_bins: int = 10) -> np.ndarray:
    """Sum consecutive bin groups down to <= target_bins. PSI over many
    fine bins is dominated by per-bin sampling noise (expected PSI of an
    UNdrifted window is ~bins/rows); the industry convention computes it
    over ~10 deciles, so drift scoring coarsens the 40-bin sketch before
    the PSI log-ratio. JS stays at full resolution (its zero bins
    contribute nothing)."""
    h = np.asarray(h, np.float64)
    n = len(h)
    if n <= target_bins:
        return h
    group = int(np.ceil(n / target_bins))
    pad = (-n) % group
    if pad:
        h = np.concatenate([h, np.zeros(pad)])
    return h.reshape(-1, group).sum(axis=1)


def psi(p, q, pseudo: float = PSI_PSEUDO) -> float:
    """Population Stability Index between two COUNT histograms: sum over
    bins of (q_i - p_i) * ln(q_i / p_i) on Laplace-smoothed fractions
    ((count + pseudo) / (total + pseudo * bins)). Symmetric by
    construction; 0.0 when either side is all-zero (zero-window
    identity) and exactly 0.0 for identical histograms. Conventional
    reading: < 0.1 stable, 0.1-0.25 moderate shift, > 0.25 major shift
    (the alert policy additionally compensates the small-sample
    expectation, see psi_sampling_noise)."""
    pc = np.asarray(p, np.float64)
    qc = np.asarray(q, np.float64)
    if _normalize(pc) is None or _normalize(qc) is None:
        return 0.0
    bins = len(pc)
    pn = (pc + pseudo) / (pc.sum() + pseudo * bins)
    qn = (qc + pseudo) / (qc.sum() + pseudo * bins)
    return float(np.sum((qn - pn) * np.log(qn / pn)))


def psi_sampling_noise(p, q) -> float:
    """First-order expectation of PSI between two samples of the SAME
    distribution: for multinomial counts over B occupied bins,
    E[PSI] ~= (B - 1) * (1/n + 1/m) (the chi-square mean, since
    PSI -> chi2/n for small deviations). The alert policy compares
    measured PSI against threshold + this bias, so a small window
    (low n) cannot alert on pure sampling noise while a production-size
    window (n in the thousands) sees an essentially unshifted
    threshold."""
    pn, qn = np.asarray(p, np.float64), np.asarray(q, np.float64)
    n, m = pn.sum(), qn.sum()
    if n <= 0 or m <= 0:
        return 0.0
    b = max(int(((pn > 0) | (qn > 0)).sum()), 1)
    return float((b - 1) * (1.0 / n + 1.0 / m))


def fill_rate_of(rows: float, nulls: float) -> float:
    return 0.0 if rows <= 0 else max(rows - nulls, 0.0) / rows


def fill_ratio(a: float, b: float) -> float:
    """max/min of two fill rates (RFF relative_fill_ratio semantics);
    inf when one side is entirely empty while the other is not, 1.0 when
    both are empty."""
    lo, hi = min(a, b), max(a, b)
    if hi == 0.0:
        return 1.0
    return float("inf") if lo == 0.0 else hi / lo


# -- per-window report -------------------------------------------------------

def feature_drift(entry: Any, hist: np.ndarray, rows: float,
                  nulls: float) -> Dict[str, Any]:
    """Drift metrics for one feature: profile entry (monitor/profile
    FeatureProfile) vs one window's histogram + fill counts."""
    train_fill = fill_rate_of(entry.count, entry.nulls)
    win_fill = fill_rate_of(rows, nulls)
    cp, cq = coarsen(entry.hist), coarsen(hist)
    return {
        "feature": entry.name,
        "kind": entry.kind,
        "rows": float(rows),
        "js": round(js_divergence_hist(entry.hist, hist), 6),
        "psi": round(psi(cp, cq), 6),
        "psi_noise": round(psi_sampling_noise(cp, cq), 6),
        "fill_rate": round(win_fill, 6),
        "train_fill_rate": round(train_fill, 6),
        "fill_rate_diff": round(abs(win_fill - train_fill), 6),
        "fill_ratio": (fill_ratio(win_fill, train_fill)
                       if np.isfinite(fill_ratio(win_fill, train_fill))
                       else None),
    }


def prediction_drift(pred: Any, hist: np.ndarray, count: float,
                     ssum: float) -> Dict[str, Any]:
    """Prediction-distribution drift: JS + PSI over the calibration-bin
    occupancy plus the raw score-mean shift (absolute, and scaled by the
    training score std when it is nonzero)."""
    mean = (ssum / count) if count > 0 else 0.0
    shift = abs(mean - pred.mean) if count > 0 else 0.0
    cp, cq = coarsen(pred.hist), coarsen(hist)
    return {
        "field": pred.field,
        "rows": float(count),
        "js": round(js_divergence_hist(pred.hist, hist), 6),
        "psi": round(psi(cp, cq), 6),
        "psi_noise": round(psi_sampling_noise(cp, cq), 6),
        "mean": round(mean, 6),
        "train_mean": round(pred.mean, 6),
        "mean_shift": round(shift, 6),
        "mean_shift_sigmas": (round(shift / pred.std, 4)
                              if pred.std > 0 else None),
    }


def window_report(profile: Any, snapshot: Any, policy: Any) -> Dict[str, Any]:
    """One window's full drift report: per-feature metrics, prediction
    drift, and the alerts the policy raises. `profile` is a
    ReferenceProfile, `snapshot` a window.WindowSnapshot, `policy` an
    alerts.DriftPolicy."""
    feats: List[Dict[str, Any]] = []
    for entry in profile.features:
        hist = snapshot.hists.get(entry.name)
        if hist is None:
            continue
        feats.append(feature_drift(entry, hist, snapshot.rows,
                                   snapshot.nulls.get(entry.name, 0.0)))
    pred = None
    if profile.prediction is not None and snapshot.pred_hist is not None:
        pred = prediction_drift(profile.prediction, snapshot.pred_hist,
                                snapshot.pred_count, snapshot.pred_sum)
    report: Dict[str, Any] = {
        "window": snapshot.index,
        "rows": float(snapshot.rows),
        "wall_s": round(snapshot.wall_s, 3),
        "features": feats,
        "prediction": pred,
    }
    report["alerts"] = policy.evaluate(report)
    worst = max(feats, key=lambda f: f["js"], default=None)
    report["worst_feature"] = worst["feature"] if worst else None
    report["worst_js"] = worst["js"] if worst else 0.0
    return report
