"""Windowed serve-side sketches: the live half of train-vs-score drift.

A ServeMonitor accumulates, over TUMBLING windows of live traffic, the
same sufficient statistics the ReferenceProfile froze at fit time:

- numeric raw features: ONE fixed-shape jitted sketch program per
  serving batch bucket — a [B, K] matrix (the engine's already-assembled
  padded buffers, pad rows weighted 0) bins through the SHARED rule
  ops/stats.hist_bin_ids against the profile's pinned edges and adds
  into a device-resident [K, bins+1] running state. Dispatch is async
  and nothing is fetched until the window closes, so accumulation never
  blocks the request path; the per-bucket shapes are prewarmed with the
  ladder, so the post-warmup zero-recompile contract holds with
  monitoring on (RecompileTracker + trace-report --check keep pinning
  it).
- categorical/text/list/map features: crc32 hash-bin tables built on
  HOST from the raw values (filters/sketches.hash_hist_update — the
  profile side's exact code), on the thread that assembled the batch
  (the micro-batcher's dispatcher for queued traffic).
- prediction: score-mean moments + calibration-bin occupancy
  (monitor/profile.score_hist, shared with the profile builder).

Window state is a plain sum of sufficient statistics — the DrJAX
psum-friendly MapReduce shape (PAPERS arxiv 2403.07128): a future
multi-host deployment merges windows with one psum over the flat
histogram state, no new math.

On rollover the device state is fetched ONCE (the only sync, a few KB),
compared against the profile (monitor/drift.window_report), evaluated by
the DriftPolicy, and emitted as a ``drift_window`` event plus one
``drift_alert`` event per threshold breach.
"""
from __future__ import annotations

import functools
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..filters.sketches import hash_hist_update
from ..ops.stats import hist_bin_ids
from ..utils.metrics import collector
from . import drift
from .alerts import DriftPolicy
from .profile import ReferenceProfile, score_hist

_log = logging.getLogger("transmogrifai_tpu.monitor")

DEFAULT_WINDOW_ROWS = 4096
DEFAULT_WINDOW_SECONDS = 60.0


@functools.partial(jax.jit, static_argnames=("bins",),
                   donate_argnums=(0,))
def _numeric_sketch_step(state, X, w, lo, hi, bins: int):
    """state [K, bins+1] += weighted histogram of X [B, K] (NaN rows to
    the trailing missing bin, pad rows carry w=0). The binning rule is
    ops/stats.hist_bin_ids — shared with histogram_batched, which built
    the profile side — so window and profile can never drift in clip
    semantics. One executable per (B, K) shape: B is a prewarmed bucket
    rung, K is fixed by the profile.

    The state is DONATED (tmoglint BUF002, the tileplane carry rule:
    "the carry is donated, tiles are not"): every observed batch updates
    the [K, bins+1] accumulator in place instead of allocating a fresh
    one per dispatch. observe_numeric rebinds `self._num_state` to the
    aliased output in the same statement, so the dead input buffer is
    never reachable again; the first step of a window receives a host
    numpy array, which has no device buffer to donate and simply
    transfers."""
    X = jnp.asarray(X)
    n, K = X.shape
    ids = hist_bin_ids(X, lo, hi, bins, ~jnp.isnan(X))
    wt = jnp.broadcast_to(w[:, None], (n, K))
    return state + jax.ops.segment_sum(
        wt.reshape(-1), ids.reshape(-1),
        num_segments=K * (bins + 1)).reshape(K, bins + 1)


@dataclass
class WindowSnapshot:
    """One closed window's host-side sufficient statistics."""

    index: int
    rows: float
    wall_s: float
    hists: Dict[str, np.ndarray]   # feature -> [bins] valid mass
    nulls: Dict[str, float]        # feature -> missing rows in window
    pred_hist: Optional[np.ndarray] = None
    pred_count: float = 0.0
    pred_sum: float = 0.0


class ServeMonitor:
    """Tumbling-window drift monitor over a ReferenceProfile.

    All observe/rollover methods are internally locked (re-entrant): the
    serving engine calls under its own batch lock, the offline driver
    from its own threads. A window closes when `window_rows` rows have
    accumulated or `window_seconds` elapsed with traffic in it —
    whichever first — or on an explicit force (drain/shutdown/offline
    end-of-file)."""

    def __init__(self, profile: ReferenceProfile, *,
                 policy: Optional[DriftPolicy] = None,
                 window_rows: int = DEFAULT_WINDOW_ROWS,
                 window_seconds: float = DEFAULT_WINDOW_SECONDS,
                 health_gate: bool = False,
                 history: int = 8):
        self.profile = profile
        self.policy = policy or DriftPolicy()
        self.window_rows = int(window_rows)
        self.window_seconds = float(window_seconds)
        self.health_gate = bool(health_gate)
        self.bins = int(profile.bins)
        self.numeric_names: List[str] = profile.numeric_names
        self.hashed_names: List[str] = profile.hashed_names
        edges = profile.numeric_edges()
        self._K = len(self.numeric_names)
        # pinned edges live on device once; traced inputs of the sketch
        self._lo = jnp.asarray(edges["lo"]) if self._K else None
        self._hi = jnp.asarray(edges["hi"]) if self._K else None
        self._lock = threading.RLock()
        # identity prefix of every window this monitor closes: the
        # profile's model hash (which model's reference) + a per-monitor
        # nonce (two replicas of the same model both close "window 3" —
        # their alerts must NOT dedupe against each other). The id is
        # STABLE for one window: every alert a window raises shares it,
        # which is exactly what lets a consumer collapse the N
        # per-feature alerts of one window into one trigger.
        self._window_uid = os.urandom(4).hex()
        self.n_windows = 0
        self.alerts_total = 0
        self.rows_total = 0
        self.alerting = False
        self.last_report: Optional[Dict[str, Any]] = None
        self.history: "deque[Dict[str, Any]]" = deque(maxlen=history)
        self._t_last_close = time.monotonic()
        self._reset_window()

    # -- window state ------------------------------------------------------
    def _reset_window(self) -> None:
        # numpy zeros: the first sketch step transfers them; subsequent
        # states stay device-resident, no extra executable involved
        self._num_state: Any = (np.zeros((self._K, self.bins + 1),
                                         np.float32) if self._K else None)
        self._hash_hists = {nm: np.zeros(self.bins, np.float64)
                            for nm in self.hashed_names}
        self._hash_nulls = {nm: 0.0 for nm in self.hashed_names}
        self._rows = 0
        pred = self.profile.prediction
        self._pred_hist = (np.zeros(self.profile.pred_bins, np.float64)
                          if pred is not None else None)
        self._pred_count = 0.0
        self._pred_sum = 0.0
        self._t_open = time.monotonic()

    # -- observation -------------------------------------------------------
    def observe_numeric(self, X: np.ndarray, w: np.ndarray) -> None:
        """Async device accumulation of one padded batch ([B, K] f32 in
        profile numeric order, w=0 pad rows). Does not sync."""
        if self._K == 0:
            return
        with self._lock:
            self._num_state = _numeric_sketch_step(
                self._num_state, X, w, self._lo, self._hi, self.bins)

    def observe_hashed(self, values: Dict[str, Sequence[Any]]) -> None:
        """Host crc32 hash-bin accumulation of raw object values
        ({feature: values of the window's valid rows})."""
        with self._lock:
            for nm, vals in values.items():
                hist = self._hash_hists.get(nm)
                if hist is None:
                    continue
                nulls = 0
                for v in vals:
                    if not hash_hist_update(hist, v):
                        nulls += 1
                self._hash_nulls[nm] += nulls

    def observe_scores(self, scores: np.ndarray) -> None:
        """Prediction-distribution accumulation (host; shares
        profile.score_hist with the profile builder)."""
        pred = self.profile.prediction
        if pred is None:
            return
        s = np.asarray(scores, np.float64)
        s = s[np.isfinite(s)]
        if s.size == 0:
            return
        with self._lock:
            # the _pred_hist check belongs INSIDE the lock: a rollover
            # on the dispatcher thread swaps the window buffers, and an
            # unlocked check could read the old window's hist while the
            # locked block below adds into the new one (tmoglint THR001)
            if self._pred_hist is None:
                return
            self._pred_hist += score_hist(s, pred.lo, pred.hi,
                                          self.profile.pred_bins)
            self._pred_count += float(s.size)
            self._pred_sum += float(s.sum())

    def add_rows(self, n: int) -> None:
        """Count n observed rows toward the window and roll over when a
        boundary is crossed."""
        with self._lock:
            self._rows += int(n)
            self.rows_total += int(n)
        self.maybe_rollover()

    def observe_batch(self, X: Optional[np.ndarray], w: Optional[np.ndarray],
                      hashed: Dict[str, Sequence[Any]],
                      scores: Optional[np.ndarray], n_rows: int) -> None:
        """One served batch's full observation (engine fast path)."""
        with self._lock:
            if X is not None and w is not None:
                self.observe_numeric(X, w)
            if hashed:
                self.observe_hashed(hashed)
            if scores is not None:
                self.observe_scores(scores)
            self.add_rows(n_rows)

    # -- rollover ----------------------------------------------------------
    def maybe_rollover(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """Close the window when full / timed out / forced. Returns the
        new report when one was produced. The only device sync in the
        monitor happens here (one [K, bins+1] fetch)."""
        with self._lock:
            now = time.monotonic()
            if self._rows <= 0:
                if self.alerting and \
                        now - self._t_last_close >= self.window_seconds:
                    # alert TTL: a FULL window elapsed with zero traffic
                    # (e.g. the load balancer rotated this replica off
                    # after the health gate degraded). The stale verdict
                    # expires so /healthz can recover and let probes —
                    # and then real traffic, and a real re-verdict —
                    # back in; a latched gate with no traffic could
                    # otherwise never clear without a restart
                    self.alerting = False
                    self._t_last_close = now
                    collector.event("drift_alert_expired",
                                    idle_seconds=round(
                                        now - self._t_open, 3))
                    _log.info("drift: alert verdict expired after an "
                              "idle window; health gate cleared")
                self._t_open = now  # idle: restart the window timer
                return None
            due = (self._rows >= self.window_rows
                   or now - self._t_open >= self.window_seconds)
            if not (due or force):
                return None
            return self._close_window()

    def _close_window(self) -> Dict[str, Any]:
        wall = time.monotonic() - self._t_open
        hists: Dict[str, np.ndarray] = {}
        nulls: Dict[str, float] = {}
        if self._K and self._num_state is not None:
            # THE documented sync: one [K, bins+1] fetch per window
            # close (docs/monitoring.md), a few KB — the lock hold is
            # the design, not an accident
            # tmoglint: disable=THR002  the monitor's ONLY sync, by design
            num = np.asarray(self._num_state, np.float64)
            for k, nm in enumerate(self.numeric_names):
                hists[nm] = num[k, :self.bins]
                nulls[nm] = float(num[k, self.bins])
        for nm in self.hashed_names:
            hists[nm] = self._hash_hists[nm]
            nulls[nm] = float(self._hash_nulls[nm])
        snap = WindowSnapshot(
            index=self.n_windows, rows=float(self._rows), wall_s=wall,
            hists=hists, nulls=nulls, pred_hist=self._pred_hist,
            pred_count=self._pred_count, pred_sum=self._pred_sum)
        report = drift.window_report(self.profile, snap, self.policy)
        # stable window identity + the profiled model's content hash:
        # repeated alerts for ONE window share window_id (a consumer
        # dedupes the per-feature fan-out into one trigger) and a stale
        # alert from a pre-swap model is recognizable by hash mismatch
        report["window_id"] = self.window_id(snap.index)
        report["model_content_hash"] = self.profile.model_hash
        self.n_windows += 1
        alerts = report["alerts"]
        self.alerts_total += len(alerts)
        self.alerting = bool(alerts)
        self.last_report = report
        self.history.append(report)
        collector.event("drift_window", window=report["window"],
                        window_id=report["window_id"],
                        rows=report["rows"],
                        wall_seconds=round(report["wall_s"], 3),
                        worst_feature=report["worst_feature"],
                        worst_js=report["worst_js"],
                        alerts=len(alerts))
        self._t_last_close = time.monotonic()
        for a in alerts:
            collector.event("drift_alert", window=report["window"],
                            window_id=report["window_id"],
                            model_content_hash=report[
                                "model_content_hash"], **a)
            _log.warning("drift_alert window=%d %s %s=%s > %.4f",
                         report["window"], a["target"], a["metric"],
                         "inf" if a["value"] is None
                         else f"{a['value']:.4f}", a["threshold"])
        self._reset_window()
        return report

    def window_id(self, index: int) -> str:
        """The stable identity of window `index` for THIS monitor over
        THIS model: ``<model_hash>:<monitor-nonce>:w<index>``."""
        return (f"{self.profile.model_hash or 'unstamped'}:"
                f"{self._window_uid}:w{int(index)}")

    def window_state(self) -> Dict[str, Any]:
        """The CURRENT (still-open) window's raw sufficient statistics
        as a JSON-able dict — the fleet merge unit (``GET /drift/window``
        in serve/frontend, pooled by fleet/telemetry.merge_window_states
        before ONE fleet-level DriftPolicy verdict).

        Everything here is a plain sum, so adding two replicas' states
        component-wise IS the state of one monitor that observed both
        traffic streams (the DrJAX MapReduce shape applied host-side
        across processes). Reading does not close or reset the window;
        it costs one small device fetch, on the caller's (telemetry
        poll) cadence, not the request path's."""
        with self._lock:
            hists: Dict[str, List[float]] = {}
            nulls: Dict[str, float] = {}
            if self._K and self._num_state is not None:
                # same fetch _close_window performs, read-only: a few KB
                # on an explicit telemetry poll
                # tmoglint: disable=THR002  explicit poll-path sync, by design
                num = np.asarray(self._num_state, np.float64)
                for k, nm in enumerate(self.numeric_names):
                    hists[nm] = [float(x) for x in num[k, :self.bins]]
                    nulls[nm] = float(num[k, self.bins])
            for nm in self.hashed_names:
                hists[nm] = [float(x) for x in self._hash_hists[nm]]
                nulls[nm] = float(self._hash_nulls[nm])
            return {
                "window_index": self.n_windows,
                "nonce": self._window_uid,
                "rows": float(self._rows),
                "wall_s": round(time.monotonic() - self._t_open, 6),
                "hists": hists,
                "nulls": nulls,
                "pred_hist": ([float(x) for x in self._pred_hist]
                              if self._pred_hist is not None else None),
                "pred_count": float(self._pred_count),
                "pred_sum": float(self._pred_sum),
            }

    # -- prewarm -----------------------------------------------------------
    def prewarm(self, shapes: Sequence[int]) -> None:
        """Compile the sketch program for every serving bucket shape
        (called inside ServingEngine.prewarm, BEFORE the recompile watch
        arms), then discard the template observations."""
        if self._K:
            for b in shapes:
                self.observe_numeric(np.zeros((int(b), self._K), np.float32),
                                     np.zeros(int(b), np.float32))
        with self._lock:
            self._reset_window()

    # -- reporting ---------------------------------------------------------
    def healthy(self) -> bool:
        with self._lock:  # `alerting` flips on the dispatcher thread
            return not (self.health_gate and self.alerting)

    def report(self) -> Dict[str, Any]:
        """The ``GET /drift`` payload."""
        with self._lock:
            return {
                "windows": self.n_windows,
                "window_rows": self.window_rows,
                "window_seconds": self.window_seconds,
                "rows_total": self.rows_total,
                "rows_in_window": self._rows,
                "alerts_total": self.alerts_total,
                "alerting": self.alerting,
                "health_gate": self.health_gate,
                "policy": self.policy.to_json(),
                "last": self.last_report,
                "history": list(self.history),
            }

    def gauge_state(self) -> Dict[str, Any]:
        """Drift gauges for the ``GET /metrics/history`` ring
        (serve/reqtrace.GaugeSampler): the alerting verdict + window
        progress as plain values — no device fetch, no report build."""
        with self._lock:
            return {"drift_alerting": self.alerting,
                    "drift_windows": self.n_windows,
                    "drift_alerts_total": self.alerts_total,
                    "rows_in_window": self._rows}

    def metrics(self) -> Dict[str, Any]:
        """Compact counters for the ``/metrics`` payload."""
        with self._lock:
            return {"windows": self.n_windows,
                    "rows_total": self.rows_total,
                    "rows_in_window": self._rows,
                    "alerts_total": self.alerts_total,
                    "alerting": self.alerting,
                    "last_worst_js": (self.last_report or {}).get(
                        "worst_js", 0.0)}
