"""Reference profiles: the train-side half of continuous drift monitoring.

At fit/save time one profile is computed per model and persisted NEXT TO
the model artifact (``monitor.json``, via workflow/io.py — same contract
as the ``serve.json`` prewarm manifest): per raw predictor feature a
training sketch — numeric histogram with PINNED edges (lo/hi from the
one-pass statistics engine's Summary, so serve-side windows bin against
the training range and location shift piles into edge bins exactly like
RawFeatureFilter's train-vs-score comparison), or a crc32 hash-bin table
for categorical/text/list/map features (filters/sketches semantics) —
plus fill rates and the TRAINING PREDICTION distribution (calibration-bin
occupancy over the score range + mean/std). The serve monitor
(monitor/window.py) accumulates the same sufficient statistics over live
traffic and monitor/drift.py compares the two.

The profile is built from the model's cached training data
(``model._train_data`` holds the RFF-cleaned raw columns AND the
prediction column right after train()), so it reflects exactly what the
model trained on. TMOG_MONITOR_PROFILE=0 disables the automatic build at
save time.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..filters import sketches

_log = logging.getLogger("transmogrifai_tpu.monitor")

DEFAULT_BINS = 40
DEFAULT_PRED_BINS = 10
PROFILE_VERSION = 1


@dataclass
class FeatureProfile:
    """One raw feature's training sketch."""

    name: str
    kind: str                 # "numeric" | "hashed"
    count: float              # total training rows
    nulls: float              # missing/empty rows
    hist: List[float]         # [bins] valid mass (numeric: pinned-edge
    #                           histogram; hashed: crc32 bin table)
    lo: float = 0.0           # pinned histogram edges (numeric only)
    hi: float = 0.0

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind, "count": self.count,
                "nulls": self.nulls, "hist": list(self.hist),
                "lo": self.lo, "hi": self.hi}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "FeatureProfile":
        return FeatureProfile(
            name=d["name"], kind=d["kind"], count=float(d["count"]),
            nulls=float(d["nulls"]), hist=[float(x) for x in d["hist"]],
            lo=float(d.get("lo", 0.0)), hi=float(d.get("hi", 0.0)))


@dataclass
class PredictionProfile:
    """Training prediction distribution: calibration-bin occupancy over
    [lo, hi] plus moments of the score stream."""

    feature: str              # prediction result-feature name
    field: str                # "probability_1" | "prediction"
    count: float
    mean: float
    std: float
    hist: List[float]         # [pred_bins]
    lo: float
    hi: float

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__, hist=list(self.hist))

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "PredictionProfile":
        return PredictionProfile(
            feature=d["feature"], field=d["field"], count=float(d["count"]),
            mean=float(d["mean"]), std=float(d["std"]),
            hist=[float(x) for x in d["hist"]],
            lo=float(d["lo"]), hi=float(d["hi"]))


@dataclass
class ReferenceProfile:
    """The persisted training profile a serve-side monitor compares
    windows against."""

    bins: int = DEFAULT_BINS
    pred_bins: int = DEFAULT_PRED_BINS
    rows: float = 0.0
    features: List[FeatureProfile] = field(default_factory=list)
    prediction: Optional[PredictionProfile] = None
    version: int = PROFILE_VERSION
    #: content hash of the model artifact this profile was frozen next
    #: to (workflow/io.model_content_hash, stamped by save_profile_for).
    #: Rides every drift_alert payload so a consumer (the retrain
    #: controller) can discard a STALE alert raised by a pre-swap
    #: model's monitor; None on pre-stamp profiles.
    model_hash: Optional[str] = None

    def feature(self, name: str) -> Optional[FeatureProfile]:
        return next((f for f in self.features if f.name == name), None)

    @property
    def numeric_names(self) -> List[str]:
        return [f.name for f in self.features if f.kind == "numeric"]

    @property
    def hashed_names(self) -> List[str]:
        return [f.name for f in self.features if f.kind == "hashed"]

    def numeric_edges(self) -> Dict[str, np.ndarray]:
        """Pinned lo/hi vectors in `numeric_names` order — the traced
        range inputs of the window sketch program."""
        num = [f for f in self.features if f.kind == "numeric"]
        return {"lo": np.asarray([f.lo for f in num], np.float32),
                "hi": np.asarray([f.hi for f in num], np.float32)}

    def to_json(self) -> Dict[str, Any]:
        return {"version": self.version, "bins": self.bins,
                "pred_bins": self.pred_bins, "rows": self.rows,
                "features": [f.to_json() for f in self.features],
                "prediction": (self.prediction.to_json()
                               if self.prediction else None),
                "model_hash": self.model_hash}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ReferenceProfile":
        return ReferenceProfile(
            bins=int(d["bins"]), pred_bins=int(d["pred_bins"]),
            rows=float(d.get("rows", 0.0)),
            features=[FeatureProfile.from_json(x) for x in d["features"]],
            prediction=(PredictionProfile.from_json(d["prediction"])
                        if d.get("prediction") else None),
            version=int(d.get("version", PROFILE_VERSION)),
            model_hash=d.get("model_hash"))


# -- score extraction ---------------------------------------------------------

def score_field_of(col) -> str:
    """Which scalar tracks the prediction distribution: P(class 1) for
    probabilistic classifiers, else the raw prediction value."""
    from ..models.prediction import probability_of
    prob = probability_of(col)
    return ("probability_1" if prob is not None and prob.shape[1] >= 2
            else "prediction")


def scores_of_column(col, fld: str) -> np.ndarray:
    from ..models.prediction import prediction_of, probability_of
    if fld == "probability_1":
        return np.asarray(probability_of(col)[:, 1], np.float64)
    return np.asarray(prediction_of(col), np.float64)


def score_of(row: Dict[str, Any], prediction_name: str, fld: str
             ) -> Optional[float]:
    """The same scalar out of ONE scored row dict ({result: value}) —
    the shape score_stream and the serving engine emit."""
    v = row.get(prediction_name)
    if v is None:
        return None
    if isinstance(v, dict):
        v = v.get(fld, v.get("prediction"))
    elif hasattr(v, "value") and isinstance(v.value, dict):
        v = v.value.get(fld, v.value.get("prediction"))
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return None if np.isnan(f) else f


def score_hist(scores: np.ndarray, lo: float, hi: float,
               bins: int) -> np.ndarray:
    """Calibration-bin occupancy: fixed-edge histogram of a score
    stream, clipping out-of-range scores into the edge bins (a drifted
    model scoring outside the training range is still mass, not loss).
    Shared by the profile builder and the window accumulator."""
    s = np.asarray(scores, np.float64)
    s = s[np.isfinite(s)]
    if s.size == 0:
        return np.zeros(bins, np.float64)
    span = max(hi - lo, 1e-12)
    idx = np.clip(((s - lo) / span * bins).astype(np.int64), 0, bins - 1)
    return np.bincount(idx, minlength=bins).astype(np.float64)


# -- building -----------------------------------------------------------------

def build_profile(model: Any, ds: Any = None, *, bins: int = DEFAULT_BINS,
                  pred_bins: int = DEFAULT_PRED_BINS) -> ReferenceProfile:
    """Build the training ReferenceProfile for a fitted WorkflowModel.

    `ds` defaults to the model's cached post-train dataset (raw +
    prediction columns). Numeric features sketch through the shared
    one-pass engine path (filters/sketches.compute_distributions — the
    SAME code RawFeatureFilter bins with), object features through the
    crc32 hash tables; per-map-key sketches are collapsed to the
    whole-map feature sketch (feature-level drift is the serve-side
    granularity)."""
    if ds is None:
        ds = getattr(model, "_train_data", None)
    if ds is None:
        raise ValueError("build_profile needs a dataset (model has no "
                         "cached training data — pass ds= explicitly)")
    predictors = [f for f in model.raw_features() if not f.is_response]
    names = [f.name for f in predictors if f.name in ds]
    from ..types import ColumnKind
    names = [nm for nm in names
             if ds.column(nm).kind != ColumnKind.VECTOR]
    dists = sketches.compute_distributions(ds, names, bins)
    feats: List[FeatureProfile] = []
    for d in dists:
        if d.key is not None:
            continue  # map keys collapse to the whole-map sketch
        if d.count > 0 and d.count - d.nulls == 0:
            # all-missing at train time (e.g. a feature RawFeatureFilter
            # nulled in place): no reference distribution exists, and a
            # serve-side window that DOES carry values would alert
            # forever — the feature is already excluded from the model
            continue
        numeric = ds.column(d.name).kind in sketches.NUMERIC_KINDS
        feats.append(FeatureProfile(
            name=d.name, kind="numeric" if numeric else "hashed",
            count=float(d.count), nulls=float(d.nulls),
            hist=[float(x) for x in d.distribution],
            lo=float(d.summary[0]) if numeric else 0.0,
            hi=float(d.summary[1]) if numeric else 0.0))

    prediction = None
    try:
        pred_name = model._prediction_name()
    except ValueError:
        pred_name = None
    if pred_name and pred_name in ds:
        col = ds.column(pred_name)
        fld = score_field_of(col)
        s = scores_of_column(col, fld)
        s = s[np.isfinite(s)]
        if s.size:
            if fld == "probability_1":
                lo, hi = 0.0, 1.0  # probabilities: calibration bins
            else:
                lo, hi = float(s.min()), float(s.max())
            prediction = PredictionProfile(
                feature=pred_name, field=fld, count=float(s.size),
                mean=float(s.mean()), std=float(s.std()),
                hist=[float(x) for x in score_hist(s, lo, hi, pred_bins)],
                lo=lo, hi=hi)

    return ReferenceProfile(bins=bins, pred_bins=pred_bins,
                            rows=float(len(ds)), features=feats,
                            prediction=prediction)


def save_profile_for(model: Any, path: str) -> Optional[str]:
    """Best-effort profile build + save at model-save time (workflow/io
    calls this). Monitoring must never fail a model save: errors log and
    return None. TMOG_MONITOR_PROFILE=0 disables."""
    import os

    from ..workflow.io import save_monitor_profile
    if os.environ.get("TMOG_MONITOR_PROFILE", "1").lower() in ("0", "off",
                                                               "false"):
        return None
    if getattr(model, "_train_data", None) is None:
        return None  # loaded/reconstructed model: no training data cached
    try:
        profile = build_profile(model)
        # stamp the artifact identity: save_model writes op-model.json +
        # arrays.npz BEFORE calling here, so the hash names exactly the
        # model this profile describes — drift_alert payloads carry it
        # and the retrain controller drops alerts from a pre-swap model
        from ..workflow.io import model_content_hash
        profile.model_hash = model_content_hash(path)
        return save_monitor_profile(path, profile.to_json())
    except Exception:
        _log.exception("monitor: reference-profile build failed; model "
                       "saved WITHOUT monitor.json")
        return None
