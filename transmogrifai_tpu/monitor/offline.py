"""Offline drift reports: the serve-side drift engine over a bulk file.

``python -m transmogrifai_tpu monitor <model_dir> <data>`` loads the
model and its ``monitor.json`` reference profile, scores the file
through the tileplane ``score_stream`` lane (readers/streaming.py —
producer-thread record assembly overlapped with device scoring, the
PR 6 bulk path), and feeds the SAME ServeMonitor the serving engine
uses: raw records tee off the stream into the hash/numeric sketches
while the scored tiles feed the prediction sketch. Batch scoring and
live serving therefore share one drift engine and one verdict — the
ci.sh smoke pins that an offline report over a shifted file agrees with
the serve-side alert on the same distribution.

By default the whole file is ONE window (end-of-file forces the
rollover); ``--window-rows`` re-enables tumbling windows for
position-in-file drift hunting. Note the prediction stream lags the raw
stream by the tileplane's in-flight tiles, so windowed offline reports
attribute scores to windows approximately; the default single window is
exact.
"""
from __future__ import annotations

import json
import logging
import sys
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ..filters.sketches import numeric_value
from .alerts import DriftPolicy
from .profile import ReferenceProfile, score_of
from .window import ServeMonitor

_log = logging.getLogger("transmogrifai_tpu.monitor")


class _TeeReader:
    """StreamingReader wrapper: batches pass through to score_stream's
    producer thread and ALSO queue for the monitor (main thread pops).
    deque append/popleft are atomic, so no extra lock is needed."""

    def __init__(self, inner):
        self.inner = inner
        self.batches: "deque[List[Dict[str, Any]]]" = deque()

    def stream(self) -> Iterator[List[Dict[str, Any]]]:
        for b in self.inner.stream():
            self.batches.append(b)
            yield b


def observe_raw_records(monitor: ServeMonitor, records: List[Dict[str, Any]],
                        generators: Dict[str, Any]) -> None:
    """Feed one batch of RAW records into the window sketches: numeric
    matrix (profile order) through the jitted sketch, object values
    through the host hash path. Shared by the offline driver and the
    engine's single-record local route."""
    from ..local.scoring import _extract

    n = len(records)
    if n == 0:
        return
    if monitor.numeric_names:
        X = np.empty((n, len(monitor.numeric_names)), np.float32)
        for j, nm in enumerate(monitor.numeric_names):
            gen = generators[nm]
            for i, rec in enumerate(records):
                X[i, j] = numeric_value(_extract(gen, rec))
        monitor.observe_numeric(X, np.ones(n, np.float32))
    if monitor.hashed_names:
        monitor.observe_hashed(
            {nm: [_extract(generators[nm], rec) for rec in records]
             for nm in monitor.hashed_names if nm in generators})
    monitor.add_rows(n)


def offline_report(model: Any, stream_reader: Any,
                   profile: ReferenceProfile, *,
                   policy: Optional[DriftPolicy] = None,
                   tile_rows: int = 1024,
                   window_rows: int = 0) -> Dict[str, Any]:
    """Drift report for a record stream scored through score_stream.

    window_rows=0 (default): one window over the whole stream."""
    from ..readers.streaming import score_stream

    monitor = ServeMonitor(
        profile, policy=policy,
        window_rows=window_rows if window_rows > 0 else 2 ** 62,
        window_seconds=float("inf"))
    generators = {f.name: f.origin_stage for f in model.raw_features()
                  if not f.is_response}
    pred = profile.prediction
    rows = 0
    tee = _TeeReader(stream_reader)
    for tile in score_stream(model, tee, tile_rows=tile_rows):
        while tee.batches:
            batch = tee.batches.popleft()
            rows += len(batch)
            observe_raw_records(monitor, batch, generators)
        if pred is not None:
            svals = [score_of(row, pred.feature, pred.field) for row in tile]
            monitor.observe_scores(
                np.asarray([v for v in svals if v is not None], np.float64))
    while tee.batches:  # raw batches the last tile didn't flush
        batch = tee.batches.popleft()
        rows += len(batch)
        observe_raw_records(monitor, batch, generators)
    monitor.maybe_rollover(force=True)
    reports = list(monitor.history)
    return {
        "rows": rows,
        "windows": monitor.n_windows,
        "alerts_total": monitor.alerts_total,
        "verdict": "drift" if monitor.alerts_total else "ok",
        "policy": monitor.policy.to_json(),
        "last": monitor.last_report,
        "reports": reports,
    }


# -- the `monitor` CLI body ---------------------------------------------------

def _file_stream_reader(path: str, batch_records: int):
    """A single bulk file as a record stream (CSV or Avro), decoded
    LAZILY: batches come off the file as the scoring tileplane drains
    them instead of materializing the whole record list up front —
    the monitor's bulk replay now holds at most the in-flight tiles
    plus one decode batch, whatever the file size."""
    from ..readers.streaming import IterStreamingReader
    if path.endswith(".avro"):
        from ..readers.avro import read_avro_file

        def records():
            return read_avro_file(path)
    else:
        from ..readers.readers import CSVReader

        def records():
            return CSVReader(path).iter_records()
    return IterStreamingReader(records, batch_records=batch_records)


def run_monitor(args: Any) -> int:
    """Body of ``python -m transmogrifai_tpu monitor`` (cli.py parses).

    Prints one JSON report line; --fail-on-drift exits 3 when any
    drift_alert fired, so CI/cron can gate on batch-side drift exactly
    like trace-report --check gates the serve side."""
    import os

    from ..utils.metrics import collector
    from ..workflow.io import load_monitor_profile
    from ..workflow.workflow import WorkflowModel

    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
    model = WorkflowModel.load(args.model_dir)
    doc = None
    if getattr(args, "profile", None):
        with open(args.profile) as fh:
            doc = json.load(fh)
    else:
        doc = load_monitor_profile(args.model_dir)
    if not doc:
        print(json.dumps({"error": f"no monitor.json under "
                                   f"{args.model_dir} — save the model "
                                   f"from a fitted session (or pass "
                                   f"--profile)"}), file=sys.stderr)
        return 2
    profile = ReferenceProfile.from_json(doc)

    policy = DriftPolicy()
    for knob in ("max_js", "max_psi", "max_fill_diff", "max_fill_ratio",
                 "max_pred_js", "max_score_shift", "min_rows"):
        v = getattr(args, knob, None)
        if v is not None:
            setattr(policy, knob, type(getattr(policy, knob))(v))

    metrics_loc = getattr(args, "metrics_location", None)
    if metrics_loc:
        os.makedirs(metrics_loc, exist_ok=True)
        collector.attach_event_log(os.path.join(metrics_loc,
                                                "events.jsonl"))
    try:
        report = offline_report(
            model, _file_stream_reader(args.data, int(args.tile_rows)),
            profile, policy=policy, tile_rows=int(args.tile_rows),
            window_rows=int(getattr(args, "window_rows", 0) or 0))
    finally:
        if metrics_loc:
            collector.detach_event_log()
    report["model_dir"] = args.model_dir
    report["data"] = args.data
    print(json.dumps(report, default=str))
    if getattr(args, "fail_on_drift", False) and report["verdict"] == "drift":
        return 3
    return 0
