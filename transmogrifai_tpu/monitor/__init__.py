"""Online drift & model-health monitoring (docs/monitoring.md).

RawFeatureFilter's headline safety feature — comparing training-time and
scoring-time feature distributions and flagging the ones that drift —
runs once, at fit time. In the production story scoring is a long-lived
service (serve/, docs/serving.md), and nothing watched the traffic: a
feature pipeline can silently rot under the served model. This package
is the serve-side half of that comparison, run continuously:

- :mod:`profile` — ReferenceProfile: per-feature training sketches
  (numeric histograms with pinned edges from the one-pass stats engine,
  crc32 hash-bin tables via filters/sketches, fill rates) plus the
  training prediction distribution, persisted next to the model
  (``monitor.json``, riding workflow/io like ``serve.json``);
- :mod:`window` — ServeMonitor: tumbling-window accumulation of the
  same sufficient statistics over live traffic — one fixed-shape jitted
  sketch program per serving bucket (prewarmed with the ladder, so the
  post-warmup zero-recompile contract holds) plus a host path for
  hash-binned raw values assembled on the batcher thread;
- :mod:`drift` — PSI, Jensen-Shannon divergence (THE shared
  implementation behind FeatureDistribution.js_divergence), fill-rate
  drift and prediction drift (score-mean shift + calibration-bin
  occupancy) per window;
- :mod:`alerts` — DriftPolicy thresholds -> ``drift_alert`` events,
  the ``GET /drift`` payload, ``/metrics`` fields and the optional
  ``/healthz`` hard gate;
- :mod:`offline` — ``python -m transmogrifai_tpu monitor <model_dir>
  <data>``: the same drift engine over a bulk file via the tileplane
  ``score_stream`` lane, so batch scoring and serving share one verdict.

Window merges are plain sufficient-statistic sums (DrJAX-style
psum-friendly MapReduce shape, PAPERS arxiv 2403.07128), so the same
sketch program can later ride the multi-host mesh: a cross-host window
merge is one psum over the flat histogram state.
"""
from .alerts import DriftPolicy
from .drift import js_divergence_hist, js_divergence_nats, psi, window_report
from .offline import offline_report, run_monitor
from .profile import (PredictionProfile, ReferenceProfile, build_profile,
                      score_of)
from .window import ServeMonitor, WindowSnapshot

__all__ = [
    "DriftPolicy", "PredictionProfile", "ReferenceProfile", "ServeMonitor",
    "WindowSnapshot", "build_profile", "js_divergence_hist",
    "js_divergence_nats", "offline_report", "psi", "run_monitor",
    "score_of", "window_report",
]
