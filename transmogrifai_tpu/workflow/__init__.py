"""Workflow engine: DAG assembly, layered XLA-fused fit/transform, scoring,
persistence (reference core/.../OpWorkflow.scala, OpWorkflowModel.scala,
utils/stages/FitStagesUtil.scala)."""
from .dag import (CutDAG, StagesDAG, collect_features, collect_raw_features,
                  compute_dag, cut_dag, validate_stages)
from .fitting import LayerRunner
from .io import load_model, save_model
from .runner import (EvaluateResult, FeaturesResult, OpApp, OpParams,
                     OpWorkflowRunner, ReaderParams, ScoreResult, TrainResult)
from .workflow import Workflow, WorkflowModel

__all__ = [
    "CutDAG", "StagesDAG", "collect_features", "collect_raw_features",
    "compute_dag", "cut_dag", "validate_stages", "LayerRunner",
    "load_model", "save_model", "Workflow", "WorkflowModel",
    "EvaluateResult", "FeaturesResult", "OpApp", "OpParams",
    "OpWorkflowRunner", "ReaderParams", "ScoreResult", "TrainResult",
]
