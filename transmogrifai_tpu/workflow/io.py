"""Workflow-model persistence: one JSON graph + one npz array store.

Reference: core/.../OpWorkflowModelWriter.scala:52 (single ``op-model.json``
with uids, features JSON, stages JSON, params) and OpWorkflowModelReader.scala
:51. Spark's per-stage native saves become entries in ``arrays.npz``; loading
rebuilds stages via the registry (stages/registry.py) and re-wires the feature
lineage graph, after which scoring recompiles the same XLA programs.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.vector import VectorMetadata
from ..features.feature import Feature
from ..features.generator import FeatureGeneratorStage
from ..stages.base import PipelineStage
from ..stages.registry import build_stage, pack_args, unpack_args
from ..types import FeatureType
from .dag import StagesDAG, collect_features
from .workflow import WorkflowModel

MODEL_JSON = "op-model.json"
ARRAYS_NPZ = "arrays.npz"
SERVE_JSON = "serve.json"
MONITOR_JSON = "monitor.json"
FORMAT_VERSION = 1


def save_model(model: WorkflowModel, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.remove(path)
    os.makedirs(path, exist_ok=True)

    store: Dict[str, np.ndarray] = {}
    features = collect_features(model.result_features)

    feat_json: List[Dict[str, Any]] = []
    gen_stages: Dict[str, FeatureGeneratorStage] = {}
    for f in features:
        entry = {
            "uid": f.uid,
            "name": f.name,
            "type": f.feature_type.type_name(),
            "is_response": f.is_response,
            "origin_stage_uid": f.origin_stage.uid if f.origin_stage else None,
            "parent_uids": [p.uid for p in f.parents],
        }
        feat_json.append(entry)
        if isinstance(f.origin_stage, FeatureGeneratorStage):
            gen_stages[f.origin_stage.uid] = f.origin_stage

    gen_json = [
        {"class": type(g).__name__, "args": pack_args(g.save_args(), store, g.uid)}
        for g in gen_stages.values()
    ]

    layers_json: List[List[Dict[str, Any]]] = []
    for layer in model.dag.layers:
        lj: List[Dict[str, Any]] = []
        for st in layer:
            entry = {
                "class": type(st).__name__,
                "uid": st.uid,
                "args": pack_args(st.save_args(), store, st.uid),
                "input_uids": [f.uid for f in st.input_features],
                "output_name": st.output_name(),
            }
            md = getattr(st, "output_metadata", lambda: None)()
            if isinstance(md, VectorMetadata):
                entry["metadata"] = md.to_json()
            lj.append(entry)
        layers_json.append(lj)

    from .. import __version__
    doc = {
        "format_version": FORMAT_VERSION,
        # provenance stamp (reference VersionInfo in model metadata):
        # which framework build trained this artifact
        "framework_version": __version__,
        "result_feature_uids": [f.uid for f in model.result_features],
        "blacklisted_features": model.blacklist,
        "features": feat_json,
        "generators": gen_json,
        "stage_layers": layers_json,
        "raw_feature_filter": (model.rff_results.to_json()
                               if model.rff_results is not None else None),
    }
    with open(os.path.join(path, MODEL_JSON), "w") as fh:
        json.dump(doc, fh, indent=1)
    np.savez_compressed(os.path.join(path, ARRAYS_NPZ), **store)

    # drift-monitoring reference profile (docs/monitoring.md): when the
    # model still carries its post-train dataset, freeze the per-feature
    # training sketches + prediction distribution next to the artifact so
    # `serve` and the offline `monitor` CLI can compare live traffic
    # against them. Best-effort by contract (a monitoring failure must
    # never fail a model save); TMOG_MONITOR_PROFILE=0 disables.
    from ..monitor.profile import save_profile_for
    save_profile_for(model, path)


def load_model(path: str,
               custom_stages: Optional[Dict[str, PipelineStage]] = None
               ) -> WorkflowModel:
    with open(os.path.join(path, MODEL_JSON)) as fh:
        doc = json.load(fh)
    if doc.get("format_version", 0) > FORMAT_VERSION:
        raise ValueError(f"Model format {doc['format_version']} is newer than "
                         f"this library supports ({FORMAT_VERSION})")
    npz_path = os.path.join(path, ARRAYS_NPZ)
    store: Dict[str, np.ndarray] = {}
    if os.path.exists(npz_path):
        with np.load(npz_path, allow_pickle=False) as z:
            store = {k: z[k] for k in z.files}
    custom_stages = custom_stages or {}

    # 1. rebuild stages
    stages: Dict[str, PipelineStage] = {}
    for gj in doc["generators"]:
        args = unpack_args(gj["args"], store)
        st = custom_stages.get(args.get("uid")) or build_stage(gj["class"], args)
        stages[st.uid] = st
    layer_entries: List[List[Dict[str, Any]]] = doc["stage_layers"]
    for layer in layer_entries:
        for ej in layer:
            if ej["uid"] in custom_stages:
                st = custom_stages[ej["uid"]]
            else:
                st = build_stage(ej["class"], unpack_args(ej["args"], store))
            stages[ej["uid"]] = st

    # 2. rebuild the feature graph (parents before children by construction)
    feats: Dict[str, Feature] = {}
    for fj in doc["features"]:
        origin = stages.get(fj["origin_stage_uid"]) if fj["origin_stage_uid"] else None
        f = Feature(
            name=fj["name"],
            feature_type=FeatureType.from_name(fj["type"]),
            is_response=fj["is_response"],
            origin_stage=origin,
            parents=[feats[p] for p in fj["parent_uids"]],
            uid=fj["uid"],
        )
        feats[f.uid] = f

    # 3. wire stage inputs / outputs
    for layer in layer_entries:
        for ej in layer:
            st = stages[ej["uid"]]
            st.set_input(*[feats[u] for u in ej["input_uids"]])
            st.set_output_name(ej["output_name"])
            if ej.get("metadata") and hasattr(st, "set_metadata"):
                st.set_metadata(VectorMetadata.from_json(ej["metadata"]))

    dag = StagesDAG(layers=[[stages[ej["uid"]] for ej in layer]
                            for layer in layer_entries])

    rff = None
    if doc.get("raw_feature_filter"):
        try:
            from ..filters.raw_feature_filter import RawFeatureFilterResults
            rff = RawFeatureFilterResults.from_json(doc["raw_feature_filter"])
        except ImportError:
            rff = None

    model = WorkflowModel(
        result_features=[feats[u] for u in doc["result_feature_uids"]],
        dag=dag,
        blacklist=doc.get("blacklisted_features", []),
        rff_results=rff,
    )
    # model-load hook for serving: remember WHERE the artifact lives so
    # the engine can pick up the prewarm manifest (serve.json) written by
    # `serve --prewarm-only` without the caller re-plumbing the path
    model.source_path = path
    return model


# -- serving prewarm manifest -------------------------------------------------
# `serve --prewarm-only` records the bucket ladder + template record it
# compiled alongside the model artifact; a later `serve <dir>` (same or
# fresh process) prewarms the SAME ladder, so every executable is a
# persistent-compilation-cache hit and startup performs zero XLA compiles
# (docs/serving.md "Deploy-time prewarm"). Since the fleet PR the
# manifest is also the FLEET CONTRACT (docs/fleet.md): it stamps a model
# content hash + whether a monitor profile existed at prewarm time, and
# adoption verifies both — a stale manifest (model re-saved after the
# prewarm) would otherwise silently prewarm-miss the persistent cache
# and cost every replica a full compile at startup.

def model_content_hash(model_dir: Optional[str]) -> Optional[str]:
    """Content hash of the model artifact (op-model.json + arrays.npz),
    16 hex chars. This is the identity the serve.json manifest stamps at
    --prewarm-only time and every adoption re-computes: equal hash =>
    the persistent-cache entries the prewarm populated belong to THIS
    model. None when there is no artifact to hash."""
    import hashlib

    if not model_dir or not os.path.exists(os.path.join(model_dir,
                                                        MODEL_JSON)):
        return None
    h = hashlib.sha256()
    for fname in (MODEL_JSON, ARRAYS_NPZ):
        p = os.path.join(model_dir, fname)
        h.update(fname.encode())
        if not os.path.exists(p):
            h.update(b"|absent")
            continue
        with open(p, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()[:16]


def manifest_stamp(model_dir: Optional[str]) -> Dict[str, Any]:
    """The freshness fields `serve --prewarm-only` stamps into
    serve.json: the model content hash and whether a monitor.json
    reference profile was present when the ladder compiled."""
    return {
        "model_hash": model_content_hash(model_dir),
        "monitor_profile": bool(
            model_dir
            and os.path.exists(os.path.join(model_dir, MONITOR_JSON))),
    }


def verify_serve_manifest(model_dir: Optional[str],
                          manifest: Optional[Dict[str, Any]]
                          ) -> List[str]:
    """Mismatch strings for a manifest adopted against the CURRENT
    artifact state; empty list = fresh (or too old to carry the stamp —
    pre-stamp manifests verify vacuously rather than failing every
    existing deployment). The serving engine warns on any mismatch and
    `serve --strict-manifest` turns it into a startup failure (rc 2);
    the fleet supervisor runs replicas strict, so a replica REFUSES to
    join a fleet whose manifest disagrees with its model artifact."""
    problems: List[str] = []
    if not manifest or not model_dir:
        return problems
    stamped = manifest.get("model_hash")
    if stamped is not None:
        now = model_content_hash(model_dir)
        if now != stamped:
            problems.append(
                f"model_hash {now} != manifest {stamped} (model re-saved "
                f"after `serve --prewarm-only`; prewarm will miss the "
                f"persistent cache)")
    if "monitor_profile" in manifest:
        has_prof = os.path.exists(os.path.join(model_dir, MONITOR_JSON))
        if bool(manifest["monitor_profile"]) != has_prof:
            problems.append(
                f"monitor.json {'appeared' if has_prof else 'vanished'} "
                f"since the manifest was written (monitor_profile="
                f"{manifest['monitor_profile']})")
    return problems


def save_serve_manifest(model_dir: str, manifest: Dict[str, Any]) -> str:
    p = os.path.join(model_dir, SERVE_JSON)
    with open(p, "w") as fh:
        json.dump(manifest, fh, indent=1, default=str)
    return p


def load_serve_manifest(model_dir: Optional[str]) -> Optional[Dict[str, Any]]:
    if not model_dir:
        return None
    p = os.path.join(model_dir, SERVE_JSON)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) else None
    except (OSError, json.JSONDecodeError):
        return None  # a corrupt manifest must not block serving startup


# -- drift-monitoring reference profile ---------------------------------------
# Written at save time from the model's cached training data (see
# monitor/profile.py); `serve` adopts it to run the continuous
# train-vs-score comparison and `python -m transmogrifai_tpu monitor`
# replays it over bulk files (docs/monitoring.md). Same robustness
# contract as the serve manifest: a corrupt profile disables monitoring,
# it never blocks startup.

def save_monitor_profile(model_dir: str, profile_json: Dict[str, Any]) -> str:
    p = os.path.join(model_dir, MONITOR_JSON)
    with open(p, "w") as fh:
        json.dump(profile_json, fh, indent=1, default=str)
    return p


def load_monitor_profile(model_dir: Optional[str]
                         ) -> Optional[Dict[str, Any]]:
    if not model_dir:
        return None
    p = os.path.join(model_dir, MONITOR_JSON)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) and doc.get("features") \
            is not None else None
    except (OSError, json.JSONDecodeError):
        return None
