"""Workflow: DAG assembly, training, scoring.

Reference: core/.../OpWorkflow.scala:59 (setResultFeatures:85 reconstructs
the stage DAG from feature lineage; train:332 / fitStages:368),
core/.../OpWorkflowCore.scala:52 (shared state, applyTransformationsDAG:290)
and core/.../OpWorkflowModel.scala:59 (score:254, scoreAndEvaluate:291,
evaluate:319, summaryPretty:205, save:219).

TPU-first: train fits the DAG layer-by-layer, each layer's transform is one
jitted XLA program (workflow/fitting.py); the fitted model's score path is a
fixed pipeline of compiled programs reusable on any backend (TPU for bulk
scoring, CPU for "local" serving — replacing the reference's MLeap path).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Column, Dataset
from ..evaluators.evaluators import Evaluator
from ..features.feature import Feature
from ..readers.readers import Reader
from ..stages.base import PipelineStage, Transformer
from ..types import ColumnKind, Prediction
from ..utils.gcpause import paused_gc
from .dag import (StagesDAG, collect_features, collect_raw_features,
                  compute_dag, validate_stages)
from .fitting import LayerRunner


def _copy_dag(dag: StagesDAG) -> StagesDAG:
    """Fresh unfitted copies of every stage, wiring (inputs, output names,
    uids) preserved — used for per-fold refits in workflow-level CV."""
    layers = []
    for layer in dag.layers:
        row = []
        for st in layer:
            c = st.copy()
            c.uid = st.uid
            c.set_output_name(st.output_name())
            row.append(c)
        layers.append(row)
    return StagesDAG(layers=layers)


def _grid_key(g: Dict[str, Any]) -> str:
    import json
    return json.dumps({k: g[k] for k in sorted(g)}, sort_keys=True,
                      default=str)


class Workflow:
    """Assembles the stage DAG from result features and trains it."""

    def __init__(self):
        self._result_features: Tuple[Feature, ...] = ()
        self._reader: Optional[Reader] = None
        self._input_dataset: Optional[Dataset] = None
        self._raw_feature_filter = None  # set via with_raw_feature_filter
        self._blacklist: List[str] = []

    # -- configuration (reference OpWorkflow setters) ----------------------
    def set_result_features(self, *features: Feature) -> "Workflow":
        self._result_features = tuple(features)
        dag = compute_dag(self._result_features)
        validate_stages(dag)
        return self

    def set_reader(self, reader: Reader) -> "Workflow":
        self._reader = reader
        return self

    def set_input_dataset(self, ds: Dataset) -> "Workflow":
        self._input_dataset = ds
        return self

    def with_raw_feature_filter(self, rff) -> "Workflow":
        """Attach a RawFeatureFilter (reference OpWorkflow.withRawFeatureFilter
        :523); applied to raw data before fitting, its exclusions become the
        workflow blacklist."""
        self._raw_feature_filter = rff
        return self

    @property
    def result_features(self) -> Tuple[Feature, ...]:
        return self._result_features

    def raw_features(self) -> List[Feature]:
        return collect_raw_features(self._result_features)

    # -- data --------------------------------------------------------------
    def generate_raw_data(self) -> Dataset:
        """Reference OpWorkflow.generateRawData:222."""
        raw = self.raw_features()
        if self._reader is not None:
            ds = self._reader.generate_dataset(raw)
        elif self._input_dataset is not None:
            ds = self._input_dataset
            missing = [f.name for f in raw if f.name not in ds]
            if missing:
                raise ValueError(
                    f"Input dataset is missing raw feature columns: {missing}")
        else:
            raise ValueError("Set a reader or an input dataset before training")
        if self._raw_feature_filter is not None:
            result = self._raw_feature_filter.apply(ds, self.raw_features())
            self._blacklist = list(result.dropped)
            ds = result.cleaned
        return ds

    def with_model_stages(self, model: "WorkflowModel") -> "Workflow":
        """Splice a fitted model's stages back into this workflow
        (reference OpWorkflow.withModelStages:457): on the next train(),
        estimators whose uid matches a fitted stage REUSE it instead of
        refitting — incremental retrain fits only the stages that changed
        (e.g. swap the selector, keep the fitted vectorizers)."""
        self._prefitted = {st.uid: st for st in model.stages}
        return self

    def with_workflow_cv(self) -> "Workflow":
        """Leakage-free workflow-level CV (reference OpWorkflowCore
        .withWorkflowCV:104): every estimator between the first fitted
        statistic and the model selector is REFIT inside each fold, so no
        fold's validation rows leak into upstream vectorizer/sanity-checker
        statistics."""
        self._workflow_cv = True
        return self

    # -- training ----------------------------------------------------------
    def train(self) -> "WorkflowModel":
        from ..utils.metrics import collector
        with paused_gc(), collector.trace_span(
                f"{type(self).__name__}.train", kind="workflow"):
            return self._train()

    def _train(self) -> "WorkflowModel":
        raw_data = self.generate_raw_data()
        dag = compute_dag(self._result_features)
        validate_stages(dag)
        runner = LayerRunner()
        if getattr(self, "_workflow_cv", False):
            from .dag import cut_dag
            cut = cut_dag(dag)
            if cut.model_selector is not None:
                self._run_workflow_cv(raw_data, cut, runner)
        transformed, fitted_dag = runner.fit_dag(
            raw_data, dag, prefitted=getattr(self, "_prefitted", None))
        model = WorkflowModel(
            result_features=self._result_features,
            dag=fitted_dag,
            runner=runner,
            blacklist=list(self._blacklist),
            rff_results=(self._raw_feature_filter.results
                         if self._raw_feature_filter is not None else None),
        )
        model._train_data = transformed
        model._reader = self._reader
        return model

    def _run_workflow_cv(self, raw_data: Dataset, cut, runner) -> None:
        """Reference ModelSelector.findBestEstimator:112 + OpValidator
        .applyDAG:228: per fold, refit the in-CV ('during') DAG on the fold's
        train rows only, transform ALL rows with those fold-fitted stages,
        then run the (model x grid) sweep through the validator's DEVICE
        paths — the fold enters as one weight mask over the fold-fitted
        matrix (vmapped/streamed GLM lanes, mask-fold trees, checkpoint
        cells), not a host fit_arrays loop. Feature spaces may differ per
        fold (per-fold vocabularies), which is exactly why each fold gets
        its own matrix + single-mask validate() call. The winning config
        replaces the selector's candidate list before the normal full fit;
        the full sweep results are stashed for the ModelSelectorSummary."""
        from ..models.base import _as_labels, _as_matrix

        sel = cut.model_selector
        ds1, _ = runner.fit_dag(raw_data, cut.before)
        label_name, vec_name = sel.input_names()
        y = _as_labels(ds1.column(label_name))
        masks = sel.validator.fold_masks(y)
        evaluator = sel.validator.evaluator
        metric = evaluator.default_metric
        larger = evaluator.is_larger_better()
        problem_type = getattr(sel, "problem_type", "binary")

        cells: Dict[tuple, List[float]] = {}
        self._workflow_cv_routes = {}
        grid_keys = {}
        for mi, (est, grids) in enumerate(sel.models):
            for g in (grids or [dict()]):
                grid_keys[(est.uid, _grid_key(g))] = (mi, _grid_key(g))
        for f in range(masks.shape[0]):
            tr = np.flatnonzero(masks[f] > 0)
            va = np.flatnonzero(masks[f] <= 0)
            # in-fold refit of the during-DAG (fresh copies per fold so the
            # real stages stay unfitted for the final full fit)
            fold_runner = type(runner)()
            during_copy = _copy_dag(cut.during)
            ds_tr, fitted_during = fold_runner.fit_dag(ds1.take(tr),
                                                       during_copy)
            # fit_dag already transformed the train rows; transform only
            # the validation slice and reassemble row order — the fitted
            # stages are the same objects, so the feature space matches
            ds_va = fold_runner.apply_dag(ds1.take(va), fitted_during)
            Xtr = _as_matrix(ds_tr.column(vec_name))
            Xva = _as_matrix(ds_va.column(vec_name))
            Xf = np.empty((len(y), Xtr.shape[1]), Xtr.dtype)
            Xf[tr] = Xtr
            Xf[va] = Xva
            candidates = [(est, [dict(g) for g in (grids or [dict()])])
                          for est, grids in sel.models]
            fold_best = sel.validator.validate(
                candidates, Xf, y, problem_type=problem_type,
                masks=masks[f:f + 1])
            for v in fold_best.validated:
                key = grid_keys[(v.model_uid, _grid_key(v.grid))]
                cells.setdefault(key, []).append(float(v.fold_metrics[0]))
                self._workflow_cv_routes[key] = v.route
        means = {k: float(np.mean(v)) for k, v in cells.items()}
        # NaN guard mirroring Validator.validate: a degenerate fold's NaN
        # metric must never win max() by comparison short-circuit
        fallback = -np.inf if larger else np.inf
        rank = {k: (v if np.isfinite(v) else fallback)
                for k, v in means.items()}
        best_key = (max if larger else min)(rank, key=rank.get)
        mi, _ = best_key
        winner_est, winner_grids = sel.models[mi]
        best_grid = next(g for g in (winner_grids or [dict()])
                         if _grid_key(g) == best_key[1])
        # stash the full sweep for the summary, narrow the selector to the
        # winner (reference refits the winner on the full prepared data)
        sel._extra_validation_results = [
            {"model_name": type(sel.models[k[0]][0]).__name__,
             "model_uid": sel.models[k[0]][0].uid,
             "grid": dict(next(g for g in (sel.models[k[0]][1] or [dict()])
                               if _grid_key(g) == k[1])),
             "metric_name": metric, "fold_metrics": v,
             "mean_metric": means[k], "workflow_cv": True}
            for k, v in cells.items()]
        sel.models = [(winner_est.copy(**best_grid), [dict(best_grid)])]

    def compute_data_up_to(self, feature: Feature) -> Dataset:
        """Materialize the DAG only up to `feature` (reference
        OpWorkflow.computeDataUpTo / runner Features run type)."""
        sub = Workflow().set_result_features(feature)
        if self._reader is not None:
            sub.set_reader(self._reader)
        if self._input_dataset is not None:
            sub.set_input_dataset(self._input_dataset)
        raw = sub.generate_raw_data()
        dag = compute_dag((feature,))
        runner = LayerRunner()
        out, _ = runner.fit_dag(raw, dag)
        return out

    def load_model(self, path: str, custom_stages: Optional[Dict[str, PipelineStage]] = None
                   ) -> "WorkflowModel":
        from .io import load_model
        return load_model(path, custom_stages=custom_stages)


class WorkflowModel:
    """Fitted workflow: every stage is a transformer; scoring is a fixed
    sequence of per-layer XLA programs."""

    def __init__(self, result_features: Sequence[Feature],
                 dag: StagesDAG,
                 runner: Optional[LayerRunner] = None,
                 blacklist: Sequence[str] = (),
                 rff_results=None):
        self.result_features = tuple(result_features)
        self.dag = dag
        self.runner = runner or LayerRunner()
        self.blacklist = list(blacklist)
        self.rff_results = rff_results
        self._train_data: Optional[Dataset] = None
        self._reader: Optional[Reader] = None
        #: directory this model was loaded from (io.load_model sets it) —
        #: the serving engine keys its prewarm manifest off it
        self.source_path: Optional[str] = None

    # -- access ------------------------------------------------------------
    @property
    def stages(self) -> List[Transformer]:
        return self.dag.stages  # type: ignore[return-value]

    def raw_features(self) -> List[Feature]:
        return collect_raw_features(self.result_features)

    def set_reader(self, reader: Reader) -> "WorkflowModel":
        self._reader = reader
        return self

    def _selected_model(self):
        from ..automl.selector import SelectedModel
        for st in self.stages:
            if isinstance(st, SelectedModel):
                return st
        return None

    def _sanity_checker(self):
        from ..automl.preparators import SanityCheckerModel
        for st in self.stages:
            if isinstance(st, SanityCheckerModel):
                return st
        return None

    # -- scoring (reference OpWorkflowModel.score:254 / scoreFn:326) -------
    def transform(self, ds: Optional[Dataset] = None) -> Dataset:
        """Apply the full DAG; returns raw+derived columns."""
        if ds is None:
            if self._reader is None:
                raise ValueError("score needs a dataset or a reader")
            ds = self._reader.generate_dataset(self.raw_features())
        from ..utils.metrics import collector
        with paused_gc(), collector.trace_span(
                f"{type(self).__name__}.transform", kind="workflow",
                n_rows=len(ds)):
            return self.runner.apply_dag(ds, self.dag)

    def score(self, ds: Optional[Dataset] = None,
              keep_raw_features: bool = False) -> Dataset:
        """Reference saveScores:376 — keep result-feature columns (+ raw if
        asked), plus the row key when the reader produced one (the
        reference's scored frames always carry KeyFieldName)."""
        full = self.transform(ds)
        from ..readers.readers import KEY_COLUMN
        keep = [KEY_COLUMN] if KEY_COLUMN in full else []
        keep += [f.name for f in self.result_features if f.name in full]
        if keep_raw_features:
            keep = [f.name for f in self.raw_features() if f.name in full] + keep
        return full.select(keep)

    def score_fixed(self, ds: Dataset) -> Dataset:
        """Fixed-shape serving entry (serve/engine.py): the same compiled
        per-layer programs as score(), with ZERO per-call span/gc
        bookkeeping — transform()'s trace_span plus the per-layer and
        per-stage spans grow the in-memory span tree per call, which a
        request loop must not do. Callers own the batch shape: pad to a
        prewarmed bucket (the runner's jit cache then re-uses the bucket's
        executables; any new shape compiles fresh, which the engine's
        post-warmup recompile watch will flag)."""
        full = self.runner.apply_dag(ds, self.dag, traced=False)
        from ..readers.readers import KEY_COLUMN
        keep = [KEY_COLUMN] if KEY_COLUMN in full else []
        keep += [f.name for f in self.result_features if f.name in full]
        return full.select(keep)

    def score_and_evaluate(self, evaluator: Evaluator,
                           ds: Optional[Dataset] = None
                           ) -> Tuple[Dataset, Dict[str, float]]:
        full = self.transform(ds)
        metrics = self._evaluate_on(full, evaluator)
        keep = [f.name for f in self.result_features if f.name in full]
        return full.select(keep), metrics

    def evaluate(self, evaluator: Evaluator,
                 ds: Optional[Dataset] = None) -> Dict[str, Any]:
        """Reference OpWorkflowModel.evaluate:319 (falls back to the cached
        training data like the reference's evaluate-on-train)."""
        if ds is None and self._train_data is not None:
            return self._evaluate_on(self._train_data, evaluator)
        return self._evaluate_on(self.transform(ds), evaluator)

    def _evaluate_on(self, full: Dataset, evaluator: Evaluator) -> Dict[str, Any]:
        label_name = self._response_name()
        pred_name = self._prediction_name()
        labels = np.asarray(full.data(label_name), dtype=np.float64)
        pred_col = full.column(pred_name)
        mask = ~np.isnan(labels)
        if not mask.all():
            labels = labels[mask]
            pred_col = Column(kind=pred_col.kind, data=pred_col.data[mask],
                              metadata=pred_col.metadata)
        return evaluator.evaluate_all(labels, pred_col)

    def _response_name(self) -> str:
        for f in self.raw_features():
            if f.is_response:
                return f.name
        raise ValueError("No response raw feature in this workflow")

    def _prediction_name(self) -> str:
        for f in self.result_features:
            if issubclass(f.feature_type, Prediction):
                return f.name
        # fall back to the selector's output
        sel = self._selected_model()
        if sel is not None:
            return sel.output_name()
        raise ValueError("No Prediction result feature")

    # -- introspection -----------------------------------------------------
    def selector_summary(self):
        sel = self._selected_model()
        return sel.summary if sel is not None else None

    def sanity_checker_summary(self):
        sc = self._sanity_checker()
        return sc.summary if sc is not None else None

    def model_insights(self):
        from ..insights.model_insights import extract_insights
        return extract_insights(self)

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"stages": [st.stage_name for st in self.stages],
                               "blacklisted_features": self.blacklist}
        sel = self.selector_summary()
        if sel is not None:
            out["model_selection"] = sel.to_json()
        sc = self.sanity_checker_summary()
        if sc is not None:
            out["sanity_check"] = sc.to_json()
        if self.rff_results is not None:
            out["raw_feature_filter"] = self.rff_results.to_json()
        return out

    def summary_pretty(self) -> str:
        """Reference OpWorkflowModel.summaryPretty:205 — the README table."""
        lines: List[str] = []
        sel = self.selector_summary()
        if sel is not None:
            lines.append(sel.pretty())
        sc = self.sanity_checker_summary()
        if sc is not None and getattr(sc, "dropped", None) is not None:
            lines.append(f"SanityChecker dropped {len(sc.dropped)} columns: "
                         f"{sc.dropped[:10]}")
        if self.blacklist:
            lines.append(f"RawFeatureFilter excluded: {self.blacklist}")
        return "\n".join(lines) if lines else "(no selector in workflow)"

    # -- persistence -------------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        from .io import save_model
        save_model(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str, custom_stages: Optional[Dict[str, PipelineStage]] = None
             ) -> "WorkflowModel":
        from .io import load_model
        return load_model(path, custom_stages=custom_stages)

    # -- local scoring hook (reference local/OpWorkflowModelLocal) ---------
    def score_function(self) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        from ..local.scoring import score_function
        return score_function(self)

    # -- serving hook (serve/engine.py) ------------------------------------
    def serving_engine(self, **kwargs) -> Any:
        """Production serving engine over this fitted model: AOT-prewarmed
        shape-bucketed executables + micro-batching (docs/serving.md).
        Keyword args forward to serve.engine.ServingEngine."""
        from ..serve.engine import ServingEngine
        return ServingEngine(self, **kwargs)
