"""OpParams run configuration + OpWorkflowRunner/OpApp entry points.

Reference: features/.../OpParams.scala:81 (JSON run config: per-stage param
overrides withValues:116, reader params :229, model/write/metrics locations,
fromFile:300) and core/.../OpWorkflowRunner.scala:70 / OpApp.scala:49 —
run types Train/Score/Features/Evaluate (:296, 358-365) dispatched from CLI
args, each returning a typed result and writing its artifacts.

The Spark-session bootstrap of OpApp is replaced by process-local JAX; the
run loop, artifact layout (model dir + scores + metrics JSON) and
stage-param override semantics carry over.
"""
from __future__ import annotations

import argparse
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


# -- params -----------------------------------------------------------------

@dataclass
class ReaderParams:
    """Reference ReaderParams:229 — per-reader path/partition overrides."""

    path: Optional[str] = None
    limit: Optional[int] = None
    custom: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {"path": self.path, "limit": self.limit, "custom": self.custom}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ReaderParams":
        return ReaderParams(path=d.get("path"), limit=d.get("limit"),
                            custom=d.get("custom", {}))


@dataclass
class OpParams:
    """Reference OpParams.scala:81 — the JSON-file run configuration."""

    stage_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    reader_params: Dict[str, ReaderParams] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics_location: Optional[str] = None
    custom_params: Dict[str, Any] = field(default_factory=dict)
    collect_stage_metrics: bool = False
    # sanitizer opt-in (utils/sanitizers): trap NaNs/Infs produced by any
    # jitted program during the run — the compiled-pipeline analogue of the
    # reference's closure-serializability validation (OpWorkflow.scala:265)
    debug_nans: bool = False

    def with_values(self, **kwargs: Any) -> "OpParams":
        """Reference withValues:116 — functional update."""
        out = OpParams(**{**self.__dict__})
        for k, v in kwargs.items():
            setattr(out, k, v)
        return out

    def to_json(self) -> Dict[str, Any]:
        return {
            "stage_params": self.stage_params,
            "reader_params": {k: v.to_json()
                              for k, v in self.reader_params.items()},
            "model_location": self.model_location,
            "write_location": self.write_location,
            "metrics_location": self.metrics_location,
            "custom_params": self.custom_params,
            "collect_stage_metrics": self.collect_stage_metrics,
            "debug_nans": self.debug_nans,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "OpParams":
        return OpParams(
            stage_params=d.get("stage_params", {}),
            reader_params={k: ReaderParams.from_json(v)
                           for k, v in d.get("reader_params", {}).items()},
            model_location=d.get("model_location"),
            write_location=d.get("write_location"),
            metrics_location=d.get("metrics_location"),
            custom_params=d.get("custom_params", {}),
            collect_stage_metrics=d.get("collect_stage_metrics", False),
            debug_nans=d.get("debug_nans", False),
        )

    @staticmethod
    def from_file(path: str) -> "OpParams":
        """Reference OpParams.fromFile:300."""
        with open(path) as f:
            return OpParams.from_json(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)


def apply_stage_params(workflow, params: OpParams) -> None:
    """Reference OpWorkflow.setStageParameters:166-188 — override stage
    params by stage class name or uid before fitting."""
    if not params.stage_params:
        return
    from .dag import compute_dag
    dag = compute_dag(workflow.result_features)
    for st in dag.stages:
        for key in (st.uid, type(st).__name__):
            overrides = params.stage_params.get(key)
            if overrides:
                for name, value in overrides.items():
                    if st.has_param(name):
                        st.set_param(name, value)


# -- run results ------------------------------------------------------------

@dataclass
class RunResult:
    run_type: str
    wall_seconds: float = 0.0


@dataclass
class TrainResult(RunResult):
    model_summary: str = ""
    model_location: Optional[str] = None


@dataclass
class ScoreResult(RunResult):
    n_rows: int = 0
    metrics: Dict[str, float] = field(default_factory=dict)
    write_location: Optional[str] = None


@dataclass
class FeaturesResult(RunResult):
    n_rows: int = 0
    feature_name: str = ""
    write_location: Optional[str] = None


@dataclass
class EvaluateResult(RunResult):
    metrics: Dict[str, float] = field(default_factory=dict)


class OpWorkflowRunner:
    """Reference OpWorkflowRunner.scala:70: one object owning the workflow,
    readers and evaluator, dispatching run types."""

    TRAIN = "Train"
    SCORE = "Score"
    STREAMING_SCORE = "StreamingScore"
    FEATURES = "Features"
    EVALUATE = "Evaluate"

    def __init__(self, workflow, train_reader=None, score_reader=None,
                 evaluator=None, features_to_compute: Sequence[Any] = ()):
        self.workflow = workflow
        self.train_reader = train_reader
        self.score_reader = score_reader
        self.evaluator = evaluator
        self.features_to_compute = list(features_to_compute)
        self._end_handlers: List[Callable[[RunResult], None]] = []

    def add_application_end_handler(self, fn: Callable[[RunResult], None]
                                    ) -> "OpWorkflowRunner":
        """Reference addApplicationEndHandler:145."""
        self._end_handlers.append(fn)
        return self

    def _finish(self, result: RunResult, params: OpParams) -> RunResult:
        if params.collect_stage_metrics and params.metrics_location:
            from ..utils.metrics import collector
            os.makedirs(params.metrics_location, exist_ok=True)
            # a collection this run JOINED (outer enable) must not be
            # finished from here: write snapshots, leave the tree open
            close = getattr(self, "_owns_collection", True)
            collector.save(os.path.join(
                params.metrics_location,
                f"{result.run_type.lower()}_stage_metrics.json"),
                close=close)
            # the same span tree as a Chrome trace: open in Perfetto
            # (ui.perfetto.dev) or chrome://tracing; validated by
            # `python -m transmogrifai_tpu trace-report <dir> --check`
            collector.save_chrome_trace(os.path.join(
                params.metrics_location,
                f"{result.run_type.lower()}_trace.json"), close=close)
        if params.metrics_location:
            os.makedirs(params.metrics_location, exist_ok=True)
            payload = {k: v for k, v in result.__dict__.items()
                       if isinstance(v, (str, int, float, dict, list,
                                         type(None)))}
            with open(os.path.join(params.metrics_location,
                                   f"{result.run_type.lower()}_metrics.json"),
                      "w") as f:
                json.dump(payload, f, indent=2, default=str)
        for fn in self._end_handlers:
            fn(result)
        return result

    # -- dispatch (reference run:296) --------------------------------------
    def run(self, run_type: str, params: Optional[OpParams] = None
            ) -> RunResult:
        params = params or OpParams()
        from ..utils.metrics import collector
        # a collection this run STARTS it also ends (finish + disable in
        # the finally below): without that, a run with no
        # metrics_location never finishes the collector and the next
        # run's enable() would join — accumulating spans across runs. A
        # collection an OUTER caller started is joined and left alone.
        started_collection = (params.collect_stage_metrics
                              and not collector.collecting)
        self._owns_collection = started_collection
        attached_log = False
        error: Optional[str] = None
        # ALL setup inside the try: a failing makedirs/attach after
        # enable() must still hit the finally, or the half-started
        # collection would stay open for the rest of the process
        try:
            if params.collect_stage_metrics:
                collector.enable(app_name=type(self.workflow).__name__)
            if params.metrics_location and not collector.has_event_log:
                # the streaming event log attaches whenever a metrics dir
                # is given (independent of span collection): a preempted
                # multi-hour run stays monitorable by tailing ONE file. A
                # log the CALLER attached (bench.py BENCH_TRACE_DIR) is
                # kept — this run's events flow there, it stays open after.
                os.makedirs(params.metrics_location, exist_ok=True)
                collector.attach_event_log(
                    os.path.join(params.metrics_location, "events.jsonl"))
                attached_log = True
            collector.event("run_start", run_type=run_type,
                            app=type(self.workflow).__name__)
            if params.debug_nans:
                from ..utils.sanitizers import debug_nans
                with debug_nans():
                    return self._dispatch(run_type, params)
            return self._dispatch(run_type, params)
        except BaseException as e:
            error = type(e).__name__
            raise
        finally:
            collector.event("run_end", run_type=run_type,
                            error=error is not None,
                            **({"error_type": error} if error else {}))
            if attached_log:  # never close a log this run did not open
                collector.detach_event_log()
            if started_collection:
                # idempotent when _finish already saved; collector.current
                # stays readable after the run, and the next enable()
                # starts fresh instead of appending to this run's tree
                collector.finish()
                collector.disable()

    def _dispatch(self, run_type: str, params: OpParams) -> RunResult:
        from ..utils.metrics import collector
        t0 = time.time()
        with collector.trace_span(run_type, kind="run"):
            if run_type == self.TRAIN:
                out = self._train(params)
            elif run_type == self.SCORE:
                out = self._score(params)
            elif run_type == self.STREAMING_SCORE:
                out = self._streaming_score(params)
            elif run_type == self.FEATURES:
                out = self._features(params)
            elif run_type == self.EVALUATE:
                out = self._evaluate(params)
            else:
                raise ValueError(f"Unknown run type: {run_type!r}")
        out.wall_seconds = time.time() - t0
        return self._finish(out, params)

    def _train(self, params: OpParams) -> TrainResult:
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        apply_stage_params(self.workflow, params)
        model = self.workflow.train()
        loc = params.model_location
        if loc:
            model.save(loc)
        return TrainResult(run_type=self.TRAIN,
                           model_summary=model.summary_pretty(),
                           model_location=loc)

    def _load_model(self, params: OpParams):
        from .workflow import WorkflowModel
        if not params.model_location:
            raise ValueError("model_location required")
        return WorkflowModel.load(params.model_location)

    def _score(self, params: OpParams) -> ScoreResult:
        model = self._load_model(params)
        if self.score_reader is not None:
            model.set_reader(self.score_reader)
        if self.evaluator is not None:
            scores, metrics = model.score_and_evaluate(self.evaluator)
        else:
            scores, metrics = model.score(), {}
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
            self._write_scores(scores, model, loc)
        return ScoreResult(run_type=self.SCORE, n_rows=scores.n_rows,
                           metrics=metrics, write_location=loc)

    @staticmethod
    def _write_scores(scores, model, loc: str) -> None:
        pred_name = model._prediction_name()
        col = scores.column(pred_name)
        rows = [v if not isinstance(v, np.ndarray) else v.tolist()
                for v in (col.data if col.kind != "vector"
                          else list(col.data))]
        with open(os.path.join(loc, "scores.jsonl"), "w") as f:
            for v in rows:
                f.write(json.dumps(v, default=str) + "\n")

    def _streaming_score(self, params: OpParams) -> ScoreResult:
        """Reference StreamingScore:232 — per-batch scoring over a
        StreamingReader (self.score_reader must be one)."""
        from ..readers.streaming import StreamingReader, score_stream
        if not isinstance(self.score_reader, StreamingReader):
            raise ValueError("StreamingScore needs a StreamingReader as "
                             "score_reader")
        model = self._load_model(params)
        loc = params.write_location
        n = 0
        out_f = None
        if loc:
            os.makedirs(loc, exist_ok=True)
            out_f = open(os.path.join(loc, "scores.jsonl"), "a")
        # custom_params["score_tile_rows"] overrides the tileplane's
        # fixed scoring tile (TMOG_SCORE_TILE_ROWS; 0 = legacy per-record
        # path) per run config, like any other reader param
        tile_rows = params.custom_params.get("score_tile_rows")
        try:
            for batch_scores in score_stream(model, self.score_reader,
                                             tile_rows=tile_rows):
                n += len(batch_scores)
                if out_f is not None:
                    for s in batch_scores:
                        out_f.write(json.dumps(s, default=str) + "\n")
        finally:
            if out_f is not None:
                out_f.close()
        return ScoreResult(run_type=self.STREAMING_SCORE, n_rows=n,
                           write_location=loc)

    def _features(self, params: OpParams) -> FeaturesResult:
        """Reference Features run type: computeDataUpTo(feature, path)."""
        if not self.features_to_compute:
            raise ValueError("features_to_compute required for Features run")
        feat = self.features_to_compute[0]
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        ds = self.workflow.compute_data_up_to(feat)
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
            cols = {}
            for name in ds.column_names():
                c = ds.column(name)
                if c.kind == "vector":
                    cols[name] = np.asarray(c.data)
                elif c.kind in ("float", "int", "bool"):
                    cols[name] = np.asarray(c.data, np.float64)
            np.savez(os.path.join(loc, "features.npz"), **cols)
        return FeaturesResult(run_type=self.FEATURES, n_rows=ds.n_rows,
                              feature_name=feat.name, write_location=loc)

    def _evaluate(self, params: OpParams) -> EvaluateResult:
        model = self._load_model(params)
        if self.score_reader is not None:
            model.set_reader(self.score_reader)
        if self.evaluator is None:
            raise ValueError("evaluator required for Evaluate run")
        metrics = model.evaluate(self.evaluator)
        return EvaluateResult(run_type=self.EVALUATE, metrics=metrics)


class OpApp:
    """Reference OpApp.scala:49 — arg parsing -> runner.run. Subclass and
    implement `runner()`; call `main(argv)`."""

    def runner(self) -> OpWorkflowRunner:  # pragma: no cover - abstract
        raise NotImplementedError

    def parse_args(self, argv: Optional[Sequence[str]] = None
                   ) -> argparse.Namespace:
        p = argparse.ArgumentParser(description=type(self).__name__)
        p.add_argument("--run-type", required=True,
                       choices=["Train", "Score", "Features", "Evaluate"])
        p.add_argument("--param-location", default=None,
                       help="JSON OpParams file")
        p.add_argument("--model-location", default=None)
        p.add_argument("--read-location", default=None)
        p.add_argument("--write-location", default=None)
        p.add_argument("--metrics-location", default=None)
        return p.parse_args(argv)

    def main(self, argv: Optional[Sequence[str]] = None) -> RunResult:
        a = self.parse_args(argv)
        params = (OpParams.from_file(a.param_location) if a.param_location
                  else OpParams())
        for k in ("model_location", "write_location", "metrics_location"):
            v = getattr(a, k)
            if v:
                setattr(params, k, v)
        return self.runner().run(a.run_type, params)
