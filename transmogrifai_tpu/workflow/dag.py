"""Feature-DAG computation & layering — THE scheduler.

Reference: core/.../utils/stages/FitStagesUtil.scala — ``computeDAG:173``
layers stages by max distance-to-result so that independent stages land in
the same layer, are fitted together, and their transforms fuse into one pass
(``fitAndTransformLayer:254``). Here each layer's jax-able transforms compile
into ONE jitted XLA program (workflow/fitting.py), so the layering directly
determines how many XLA computations the pipeline lowers to.

``cut_dag`` mirrors ``FitStagesUtil.cutDAG:305``: split the DAG into the
stages before / during / after model selection, used by workflow-level CV to
refit the in-fold DAG without leakage.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..features.feature import Feature
from ..features.generator import FeatureGeneratorStage
from ..stages.base import Estimator, PipelineStage


@dataclass
class StagesDAG:
    """Layers of stages, executed first-to-last; stages within a layer are
    independent (same max distance to a result feature)."""

    layers: List[List[PipelineStage]]

    @property
    def stages(self) -> List[PipelineStage]:
        return [s for layer in self.layers for s in layer]

    def __len__(self) -> int:
        return len(self.layers)


def collect_raw_features(result_features: Sequence[Feature]) -> List[Feature]:
    """All leaf (raw, FeatureGeneratorStage-origin) features reachable from
    the results, in first-seen order."""
    seen: Set[str] = set()
    out: List[Feature] = []

    def visit(f: Feature) -> None:
        if f.uid in seen:
            return
        seen.add(f.uid)
        if f.is_raw:
            if f.name not in {g.name for g in out}:
                out.append(f)
            return
        for p in f.parents:
            visit(p)

    for f in result_features:
        visit(f)
    return out


def collect_features(result_features: Sequence[Feature]) -> List[Feature]:
    """Every feature in the lineage graph (raw + derived), topological-ish
    (parents before children)."""
    seen: Set[str] = set()
    out: List[Feature] = []

    def visit(f: Feature) -> None:
        if f.uid in seen:
            return
        seen.add(f.uid)
        for p in f.parents:
            visit(p)
        out.append(f)

    for f in result_features:
        visit(f)
    return out


def compute_dag(result_features: Sequence[Feature]) -> StagesDAG:
    """Layer non-generator stages by max distance-to-result (reference
    FitStagesUtil.computeDAG:173: ``distance = longest path to a leaf``;
    stages at the same distance form a layer, furthest first)."""
    # stage -> set of consumer stages, discovered by walking the graph
    features = collect_features(result_features)
    stages: Dict[str, PipelineStage] = {}
    consumers: Dict[str, Set[str]] = {}
    for f in features:
        st = f.origin_stage
        if st is None or isinstance(st, FeatureGeneratorStage):
            continue
        stages[st.uid] = st
        consumers.setdefault(st.uid, set())
        for p in f.parents:
            ps = p.origin_stage
            if ps is not None and not isinstance(ps, FeatureGeneratorStage):
                consumers.setdefault(ps.uid, set()).add(st.uid)

    # distance-to-leaf: 0 for stages nothing consumes (they produce results)
    dist: Dict[str, int] = {}

    def distance(uid: str, trail: Tuple[str, ...] = ()) -> int:
        if uid in dist:
            return dist[uid]
        if uid in trail:
            raise ValueError(f"Cycle detected in feature DAG at stage {uid}")
        cons = consumers.get(uid, set())
        d = 0 if not cons else 1 + max(distance(c, trail + (uid,)) for c in cons)
        dist[uid] = d
        return d

    for uid in stages:
        distance(uid)

    if not stages:
        return StagesDAG(layers=[])
    max_d = max(dist.values())
    layers: List[List[PipelineStage]] = []
    for d in range(max_d, -1, -1):
        layer = [stages[uid] for uid in stages if dist[uid] == d]
        if layer:
            # deterministic order within a layer
            layer.sort(key=lambda s: s.uid)
            layers.append(layer)
    return StagesDAG(layers=layers)


def validate_stages(dag: StagesDAG) -> None:
    """Uniqueness checks (reference OpWorkflow.scala:265-323: distinct uids,
    ctor-uid match)."""
    seen: Dict[str, PipelineStage] = {}
    for st in dag.stages:
        if st.uid in seen and seen[st.uid] is not st:
            raise ValueError(
                f"Duplicate stage uid {st.uid}: {st} vs {seen[st.uid]}")
        seen[st.uid] = st
    names: Dict[str, str] = {}
    for st in dag.stages:
        out = st.output_name()
        if out in names and names[out] != st.uid:
            raise ValueError(f"Two stages produce output column '{out}'")
        names[out] = st.uid


@dataclass
class CutDAG:
    """DAG split around a model selector (reference FitStagesUtil.cutDAG:305)."""

    before: StagesDAG     # stages whose output does not depend on the selector
    during: StagesDAG     # stages feeding the selector (refit per CV fold)
    after: StagesDAG      # selector + downstream
    model_selector: Optional[PipelineStage]


def cut_dag(dag: StagesDAG) -> CutDAG:
    """Split layers at the model selector for workflow-level CV: everything
    in layers after the first estimator-bearing layer up to the selector is
    'during' (refit in-fold)."""
    from ..automl.selector import ModelSelector

    selector = None
    for st in dag.stages:
        if isinstance(st, ModelSelector):
            if selector is not None:
                raise ValueError(
                    "Multiple ModelSelectors in one workflow not supported "
                    "(matches reference restriction)")
            selector = st
    if selector is None:
        return CutDAG(before=dag, during=StagesDAG([]), after=StagesDAG([]),
                      model_selector=None)

    # ancestors of the selector
    anc: Set[str] = set()

    def mark(f: Feature) -> None:
        st = f.origin_stage
        if st is None or isinstance(st, FeatureGeneratorStage):
            return
        if st.uid in anc:
            return
        anc.add(st.uid)
        for p in st.input_features:
            mark(p)

    for p in selector.input_features:
        mark(p)

    # 'during': ancestor stages in/after the first layer containing an
    # estimator (those see fitted statistics -> leakage risk); 'before': the rest
    before_layers: List[List[PipelineStage]] = []
    during_layers: List[List[PipelineStage]] = []
    after_layers: List[List[PipelineStage]] = []
    est_seen = False
    sel_seen = False
    for layer in dag.layers:
        if any(st.uid == selector.uid for st in layer):
            sel_seen = True
        if sel_seen:
            after_layers.append(list(layer))
            continue
        if not est_seen and any(isinstance(st, Estimator) and st.uid in anc
                                for st in layer):
            est_seen = True
        b = [st for st in layer if not (st.uid in anc and est_seen)]
        d = [st for st in layer if st.uid in anc and est_seen]
        if b:
            before_layers.append(b)
        if d:
            during_layers.append(d)
    return CutDAG(before=StagesDAG(before_layers),
                  during=StagesDAG(during_layers),
                  after=StagesDAG(after_layers),
                  model_selector=selector)
