"""Layer fit/transform execution with XLA fusion.

Reference: core/.../utils/stages/FitStagesUtil.scala —
``fitAndTransformDAG:213`` fits a DAG layer-by-layer; within a layer every
estimator is fitted, then ``applyOpTransformations:96`` fuses all row-level
transformers of the layer into ONE rdd.map pass. The TPU redesign does the
fusing in the compiler: every jax-able transformer of a layer is traced into
a single jitted XLA program over whole columns (XLA then fuses the
elementwise work into as few kernels as HBM traffic requires); host-only
transformers (string/object columns) run columnar on the host.

Missing response columns at scoring time are synthesized as all-NaN columns
so (label, features) stages score without labels — the reference gets this
for free from nullable DataFrame columns.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Column, Dataset
from ..stages.base import Estimator, PipelineStage, Transformer
from ..types import ColumnKind
from .dag import StagesDAG

_DEVICE_KINDS = (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL,
                 ColumnKind.VECTOR)


def _ensure_input_columns(ds: Dataset, stage: PipelineStage) -> Dataset:
    """Synthesize all-NaN columns for missing *response* inputs (score path)."""
    for f in stage.input_features:
        if f.name not in ds and f.is_response:
            kind = f.feature_type.column_kind
            if kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL):
                ds = ds.with_column(f.name, Column(
                    kind=kind, data=np.full(ds.n_rows, np.nan)))
            else:
                arr = np.empty(ds.n_rows, dtype=object)
                ds = ds.with_column(f.name, Column(kind=kind, data=arr))
    return ds


class LayerRunner:
    """Applies the transformers of DAG layers, fusing jax-able ones into one
    jitted XLA program per layer. Keeps a jit cache keyed by the layer's stage
    uids so scoring re-uses the compiled programs."""

    def __init__(self):
        self._jit_cache: Dict[Tuple[str, ...], Callable] = {}

    # -- one layer ---------------------------------------------------------
    def apply_layer(self, ds: Dataset,
                    transformers: Sequence[Transformer],
                    sinks: Optional[Tuple[Dict, Dict]] = None,
                    traced: bool = True) -> Dataset:
        import contextlib

        producer_views, combiner_plans = sinks or ({}, {})
        for st in transformers:
            ds = _ensure_input_columns(ds, st)
        fusable: List[Transformer] = []
        host: List[Transformer] = []
        for st in transformers:
            fn = st.get_jax_fn()
            ok = fn is not None and all(
                ds.column(n).kind in _DEVICE_KINDS for n in st.input_names())
            (fusable if ok else host).append(st)

        from ..utils.metrics import collector

        def span(*args, **kw):
            # traced=False: the serving engine's per-request path — a span
            # per stage per request would grow the in-memory tree without
            # bound under traffic (the engine records ONE span per batch
            # instead, workflow.score_fixed / serve/engine.py)
            return collector.span(*args, **kw) if traced \
                else contextlib.nullcontext()

        if fusable:
            with span("+".join(st.stage_name for st in fusable)[:120],
                      fusable[0].uid, "fused-transform", n_rows=len(ds),
                      n_stages_fused=len(fusable)):
                ds = self._apply_fused(ds, fusable)
        for st in host:
            with span(st.stage_name, st.uid, "transform", n_rows=len(ds)):
                plan = combiner_plans.get(st.uid)
                view = producer_views.get(st.uid)
                if plan is not None:
                    ds = self._apply_combiner_sink(ds, st, plan)
                elif view is not None:
                    ds = self._apply_into_sink(ds, st, view)
                else:
                    ds = st.transform(ds)
        return ds

    # -- serving sink fusion ----------------------------------------------
    # The reference fused a layer's row transforms into ONE rdd.map pass
    # (FitStagesUtil.applyOpTransformations:96). The memory-traffic analog
    # here: at score time the VectorsCombiner's [n, W] output is allocated
    # up front and every host vectorizer writes its block straight into
    # its column slice, so wide blocks (512-bin text hashes) exist exactly
    # once — no per-family temporary + full-matrix copy.
    def _apply_into_sink(self, ds: Dataset, st, view: np.ndarray) -> Dataset:
        try:
            cols = [ds.column(n) for n in st.input_names()]
            st.transform_block_into(cols, view)
            col = Column(kind=ColumnKind.VECTOR, data=view,
                         metadata=st.output_metadata())
            return ds.with_column(st.output_name(), col)
        except Exception:
            # partially-written view is dead weight: the combiner sees the
            # fallback column object (not the view) and re-copies over it
            view[:] = 0.0
            return st.transform(ds)

    def _apply_combiner_sink(self, ds: Dataset, st, plan) -> Dataset:
        final, views = plan
        try:
            cols = [ds.column(n) for n in st.input_names()]
            for n, c in zip(st.input_names(), cols):
                v = views[n]
                if c.data is not v:
                    d = c.data
                    if d.ndim == 1:
                        d = d[:, None]
                    if d.shape != v.shape:
                        # loud, like the pre-sink width assertion — a bare
                        # `v[:] = d` would silently broadcast (n,1) wide
                        raise AssertionError(
                            f"combiner input {n}: block {d.shape} vs "
                            f"planned slice {v.shape}")
                    v[:] = d
            md = st.combine_metadata(cols)
            col = Column(kind=ColumnKind.VECTOR, data=final, metadata=md)
            return ds.with_column(st.output_name(), col)
        except Exception:
            return st.transform(ds)

    def _plan_sinks(self, ds: Dataset,
                    dag: StagesDAG) -> Tuple[Dict, Dict]:
        """(producer uid -> slice view, combiner uid -> (final, views)).

        A sink forms when every input of a VectorsCombiner has a fitted
        vectorizer producer whose metadata pins its width. Host producers
        get their slice to write in place; device-fused producers' blocks
        are copied in at combiner time (they materialize on transfer
        anyway)."""
        from ..automl.vectorizers.base import VectorizerModel
        from ..automl.vectorizers.combiner import VectorsCombiner
        n = ds.n_rows
        stages = [st for layer in dag.layers for st in layer]
        by_out = {st.output_name(): st for st in stages}
        producer_views: Dict[str, np.ndarray] = {}
        combiner_plans: Dict[str, Tuple[np.ndarray, Dict[str, np.ndarray]]] = {}
        for st in stages:
            if not isinstance(st, VectorsCombiner):
                continue
            producers, widths = [], []
            for name in st.input_names():
                p = by_out.get(name)
                size = None
                if isinstance(p, VectorizerModel):
                    md = p.output_metadata()
                    if md is not None:
                        size = md.size
                if size is None:
                    break
                producers.append(p)
                widths.append(size)
            else:
                if not widths:
                    continue
                final = np.zeros((n, int(sum(widths))), np.float32)
                views: Dict[str, np.ndarray] = {}
                at = 0
                for name, p, w in zip(st.input_names(), producers, widths):
                    views[name] = final[:, at:at + w]
                    if p.get_jax_fn() is None:
                        producer_views[p.uid] = views[name]
                    at += w
                combiner_plans[st.uid] = (final, views)
        return producer_views, combiner_plans

    def _apply_fused(self, ds: Dataset, stages: List[Transformer]) -> Dataset:
        input_names: List[str] = []
        for st in stages:
            for n in st.input_names():
                if n not in input_names:
                    input_names.append(n)
        key = tuple(st.uid for st in stages) + ("|",) + tuple(input_names)
        jitted = self._jit_cache.get(key)
        if jitted is None:
            jitted = _build_fused_program(stages, input_names)
            self._jit_cache[key] = jitted
        arrays = [ds.data(n) for n in input_names]
        outs = jitted(*arrays)
        for st, out in zip(stages, outs):
            out = np.asarray(out)
            kind = st.output_type.column_kind
            if kind == ColumnKind.VECTOR:
                if out.ndim == 1:
                    out = out[:, None]
                col = Column(kind=kind, data=out.astype(np.float32),
                             metadata=st.output_metadata())
            else:
                col = Column(kind=kind, data=out.astype(np.float64))
            ds = ds.with_column(st.output_name(), col)
        return ds

    # -- whole DAG ---------------------------------------------------------
    def apply_dag(self, ds: Dataset, dag: StagesDAG,
                  traced: bool = True) -> Dataset:
        """Score path: every stage must already be a transformer (reference
        OpWorkflowCore.applyTransformationsDAG:290). traced=False skips
        all per-layer/per-stage span bookkeeping (the serving fast path,
        WorkflowModel.score_fixed)."""
        import contextlib

        from ..utils.metrics import collector
        for layer in dag.layers:
            for st in layer:
                if isinstance(st, Estimator):
                    raise ValueError(
                        f"DAG contains unfitted estimator {st.stage_name}; "
                        f"train the workflow first")
        sinks = self._plan_sinks(ds, dag)
        for i, layer in enumerate(dag.layers):
            span = collector.trace_span(f"layer_{i}", kind="layer",
                                        n_stages=len(layer)) if traced \
                else contextlib.nullcontext()
            with span:
                ds = self.apply_layer(ds, layer, sinks,  # type: ignore[arg-type]
                                      traced=traced)
        return ds

    def fit_dag(self, ds: Dataset, dag: StagesDAG,
                prefitted: Optional[Dict[str, Transformer]] = None
                ) -> Tuple[Dataset, StagesDAG]:
        """Train path (reference fitAndTransformDAG:213): per layer — fit all
        estimators, then apply the layer's transformers (originals + freshly
        fitted models) in one fused pass. `prefitted` maps stage uid -> an
        already-fitted transformer (Workflow.with_model_stages — reference
        OpWorkflow.withModelStages:457); matching estimators reuse it,
        rewired to this DAG's features, instead of refitting."""
        from ..utils.metrics import collector
        prefitted = prefitted or {}
        fitted_layers: List[List[Transformer]] = []
        for li, layer in enumerate(dag.layers):
            with collector.trace_span(f"layer_{li}", kind="layer",
                                      n_stages=len(layer)):
                fitted: List[Transformer] = []
                for st in layer:
                    if isinstance(st, Estimator):
                        prev = prefitted.get(st.uid)
                        if prev is not None:
                            # deep-copy before rewiring: the source model's
                            # DAG still aliases these objects, and mutating
                            # their input/output wiring would corrupt it
                            import copy
                            prev = copy.deepcopy(prev)
                            prev.set_input(*st.input_features)
                            prev.set_output_name(st.output_name())
                            fitted.append(prev)
                            continue
                        ds_in = _ensure_input_columns(ds, st)
                        with collector.span(st.stage_name, st.uid, "fit",
                                            n_rows=len(ds_in)):
                            model = st.fit(ds_in)
                        fitted.append(model)
                    else:
                        fitted.append(st)  # type: ignore[arg-type]
                ds = self.apply_layer(ds, fitted)
                fitted_layers.append(fitted)
        return ds, StagesDAG(layers=fitted_layers)  # type: ignore[arg-type]


def _build_fused_program(stages: Sequence[Transformer],
                         input_names: Sequence[str]) -> Callable:
    import jax

    fns = [st.get_jax_fn() for st in stages]
    index = {n: i for i, n in enumerate(input_names)}
    arg_ix = [[index[n] for n in st.input_names()] for st in stages]

    def fused(*arrays):
        return tuple(fn(*[arrays[i] for i in ix])
                     for fn, ix in zip(fns, arg_ix))

    return jax.jit(fused)
