"""Evaluators: named metric bundles over prediction columns.

Reference: core/.../evaluators/{OpEvaluatorBase.scala, Evaluators.scala:40,
OpBinaryClassificationEvaluator.scala:56, OpMultiClassificationEvaluator.scala:58,
OpRegressionEvaluator.scala:61, OpBinScoreEvaluator.scala}.

Each evaluator computes a dict of metrics (floats) from (label column,
prediction column); `evaluate` returns the single default metric used by
validators to rank models. Compute is the jitted kernels in ops/metrics_ops.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence

import numpy as np

# metric name for top-N hit rate, e.g. "top_1_accuracy"
_TOP_N_RE = re.compile(r"^top_(\d+)_accuracy$")

from ..data.dataset import Column, Dataset
from ..models.prediction import (
    n_classes_of, positive_score_of, prediction_of, probability_of,
)
from ..ops import metrics_ops as M


class Evaluator:
    """Base: named, with a default metric and larger-is-better flag."""

    name: str = "evaluator"
    default_metric: str = ""
    larger_better: bool = True

    def __init__(self, metric: Optional[str] = None):
        if metric is not None:
            self.default_metric = metric

    def evaluate_all(self, labels: np.ndarray, pred_col: Column,
                     w: Optional[np.ndarray] = None) -> Dict[str, float]:
        raise NotImplementedError

    def evaluate(self, labels: np.ndarray, pred_col: Column,
                 w: Optional[np.ndarray] = None) -> float:
        return self.evaluate_all(labels, pred_col, w)[self.default_metric]

    def is_larger_better(self, metric: Optional[str] = None) -> bool:
        m = metric or self.default_metric
        return m not in _SMALLER_BETTER

    @staticmethod
    def larger_better_metric(metric: str) -> bool:
        """Direction of a metric by name (single source of truth)."""
        return metric not in _SMALLER_BETTER

    def __repr__(self) -> str:
        return f"{type(self).__name__}(metric={self.default_metric})"


_SMALLER_BETTER = {"error", "rmse", "mse", "mae", "log_loss", "brier_score"}


class BinaryClassificationEvaluator(Evaluator):
    """AuROC/AuPR/Precision/Recall/F1/Error/confusion counts."""

    name = "binEval"
    default_metric = "au_pr"

    def __init__(self, metric: Optional[str] = None, threshold: float = 0.5):
        super().__init__(metric)
        self.threshold = threshold

    def _scalar_metrics(self, labels, pred_col, w=None) -> Dict[str, float]:
        score = positive_score_of(pred_col)
        # non-probabilistic models (SVC) score by margin: the decision
        # boundary is 0, not probability 0.5
        thr = self.threshold if probability_of(pred_col) is not None else 0.0
        m = M.binary_metrics(
            np.asarray(score, np.float32), np.asarray(labels, np.float32),
            None if w is None else np.asarray(w, np.float32), thr)
        return {k: float(v) for k, v in m._asdict().items()}

    def evaluate(self, labels, pred_col, w=None) -> float:
        # hot path (one call per grid x fold in the sequential validator):
        # scalar metrics only — no curve sort
        return self._scalar_metrics(labels, pred_col, w)[self.default_metric]

    def evaluate_all(self, labels, pred_col, w=None) -> Dict[str, Any]:
        """Scalar metrics + threshold curves (the summary-artifact path;
        curve values are lists, which summary builders filter on)."""
        out: Dict[str, Any] = self._scalar_metrics(labels, pred_col, w)
        out.update(self.threshold_curves(labels, pred_col, w))
        return out

    def threshold_curves(self, labels, pred_col, w=None,
                         num_bins: int = 100) -> Dict[str, list]:
        """Per-threshold P/R/F1 + ROC points at num_bins score cutoffs
        (reference OpBinaryClassificationEvaluator.scala:68 threshold
        curves, numBins default 100) — one sort + cumsums, no per-threshold
        pass."""
        score = np.asarray(positive_score_of(pred_col), np.float64)
        y = np.asarray(labels, np.float64)
        if len(y) == 0:
            return {k: [] for k in
                    ("thresholds", "precision_by_threshold",
                     "recall_by_threshold", "f1_by_threshold",
                     "false_positive_rate_by_threshold")}
        wv = np.ones_like(y) if w is None else np.asarray(w, np.float64)
        order = np.argsort(-score, kind="stable")
        ys, ws, ss = y[order], wv[order], score[order]
        tp_cum = np.cumsum(ws * ys)
        fp_cum = np.cumsum(ws * (1.0 - ys))
        P = max(tp_cum[-1], 1e-12)
        N = max(fp_cum[-1], 1e-12)
        lo, hi = float(ss.min()), float(ss.max())
        thresholds = np.linspace(hi, lo, num_bins)
        # rows with score >= t are predicted positive: index of the last
        # such row in descending order
        idx = np.searchsorted(-ss, -thresholds, side="right") - 1
        valid = idx >= 0
        tp = np.where(valid, tp_cum[np.maximum(idx, 0)], 0.0)
        fp = np.where(valid, fp_cum[np.maximum(idx, 0)], 0.0)
        precision = tp / np.maximum(tp + fp, 1e-12)
        recall = tp / P
        f1 = 2 * precision * recall / np.maximum(precision + recall, 1e-12)
        return {
            "thresholds": [float(t) for t in thresholds],
            "precision_by_threshold": [float(v) for v in precision],
            "recall_by_threshold": [float(v) for v in recall],
            "f1_by_threshold": [float(v) for v in f1],
            "false_positive_rate_by_threshold": [float(v) for v in fp / N],
        }


class BinScoreEvaluator(Evaluator):
    """Calibration bins + Brier score (reference OpBinScoreEvaluator.scala)."""

    name = "binScoreEval"
    default_metric = "brier_score"
    larger_better = False

    def __init__(self, num_bins: int = 100, metric: Optional[str] = None):
        super().__init__(metric)
        self.num_bins = num_bins

    def evaluate_all(self, labels, pred_col, w=None) -> Dict[str, float]:
        score = np.asarray(positive_score_of(pred_col), np.float64)
        y = np.asarray(labels, np.float64)
        if w is None:
            w = np.ones_like(y)
        brier = float((w * (score - y) ** 2).sum() / max(w.sum(), 1e-12))
        bins = np.clip((score * self.num_bins).astype(int), 0, self.num_bins - 1)
        counts = np.bincount(bins, weights=w, minlength=self.num_bins)
        avg_score = np.bincount(bins, weights=w * score, minlength=self.num_bins)
        avg_label = np.bincount(bins, weights=w * y, minlength=self.num_bins)
        nz = counts > 0
        avg_score[nz] /= counts[nz]
        avg_label[nz] /= counts[nz]
        return {
            "brier_score": brier,
            "bin_centers": list((np.arange(self.num_bins) + 0.5) / self.num_bins),
            "bin_counts": [float(c) for c in counts],
            "bin_avg_scores": [float(s) for s in avg_score],
            "bin_avg_labels": [float(l) for l in avg_label],
        }

    def evaluate(self, labels, pred_col, w=None) -> float:
        return self.evaluate_all(labels, pred_col, w)["brier_score"]


class MultiClassificationEvaluator(Evaluator):
    """Weighted precision/recall/F1/error + top-N threshold metrics."""

    name = "multiEval"
    default_metric = "error"
    larger_better = False

    def __init__(self, metric: Optional[str] = None,
                 top_ns: Sequence[int] = (1, 3)):
        super().__init__(metric)
        self.top_ns = tuple(top_ns)

    def _scalar_metrics(self, labels, pred_col, w=None) -> Dict[str, float]:
        y = np.asarray(labels, np.float32)
        pred = np.asarray(prediction_of(pred_col), np.float32)
        # n_classes is a static jit key of multiclass_metrics; the max with
        # the column layout (model class count, dataset-constant) keeps it
        # stable across folds/grid points — the data-derived terms only
        # raise it when a label id exceeds the model's classes
        n_classes = max(int(y.max()) + 1 if y.size else 1,
                        n_classes_of(pred_col), int(pred.max()) + 1 if pred.size else 1)
        m = M.multiclass_metrics(pred, y, n_classes,
                                 None if w is None else np.asarray(w, np.float32))
        return {k: float(v) for k, v in m._asdict().items()}

    def evaluate(self, labels, pred_col, w=None) -> float:
        # hot path (one call per grid x fold in the sequential validator):
        # no threshold-curve kernel. top_N_accuracy needs only the cheap
        # argsort hit-rate, not evaluate_all.
        m = _TOP_N_RE.match(self.default_metric)
        if m:
            n = int(m.group(1))
            y = np.asarray(labels, np.float32)
            prob = probability_of(pred_col)
            if prob is None or not prob.size:
                return float("nan")
            ww = np.ones_like(y) if w is None else np.asarray(w, np.float64)
            hit = (np.argsort(-prob, axis=1)[:, :n]
                   == y[:, None].astype(int)).any(axis=1)
            return float((ww * hit).sum() / max(ww.sum(), 1e-12))
        return self._scalar_metrics(labels, pred_col, w)[self.default_metric]

    def evaluate_all(self, labels, pred_col, w=None) -> Dict[str, Any]:
        y = np.asarray(labels, np.float32)
        prob = probability_of(pred_col)
        out: Dict[str, Any] = self._scalar_metrics(labels, pred_col, w)
        if prob is not None and prob.size:
            ww = np.ones_like(y) if w is None else np.asarray(w, np.float64)
            order = np.argsort(-prob, axis=1)
            for topn in self.top_ns:
                hit = (order[:, :topn] == y[:, None].astype(int)).any(axis=1)
                out[f"top_{topn}_accuracy"] = float(
                    (ww * hit).sum() / max(ww.sum(), 1e-12))
            # per-probability-threshold top-N correctness curves (reference
            # calculateThresholdMetrics, OpMultiClassificationEvaluator
            # .scala:154); counts are unweighted like the reference's
            tm = M.multiclass_threshold_metrics(prob, y, top_ns=self.top_ns)
            out["threshold_metrics"] = tm.to_json()
        return out


class RegressionEvaluator(Evaluator):
    """RMSE/MSE/MAE/R2."""

    name = "regEval"
    default_metric = "rmse"
    larger_better = False

    def evaluate_all(self, labels, pred_col, w=None) -> Dict[str, float]:
        pred = np.asarray(prediction_of(pred_col), np.float32)
        m = M.regression_metrics(
            pred, np.asarray(labels, np.float32),
            None if w is None else np.asarray(w, np.float32))
        return {k: float(v) for k, v in m._asdict().items()}


class CustomEvaluator(Evaluator):
    """User-supplied metric (reference Evaluators.custom adapters):
    ``evaluate_fn(labels, pred_col, w) -> float`` wrapped with a name and
    a direction, usable anywhere a built-in evaluator is (validators,
    score_and_evaluate, runner Evaluate)."""

    name = "customEval"
    # no jitted kernel for a user lambda: validators take the sequential
    # per-fold route and call evaluate() on host columns
    device_metric = False

    def __init__(self, metric_name: str, larger_better: bool, evaluate_fn):
        super().__init__(metric_name)
        self.larger_better = bool(larger_better)
        self._fn = evaluate_fn

    @property
    def metric_key(self) -> str:
        """Checkpoint identity: metric name + a fingerprint of the user
        function's bytecode, so editing the function invalidates cached
        sweep cells instead of silently replaying the old metric."""
        import hashlib
        try:
            code = self._fn.__code__
            fp = hashlib.sha1(code.co_code
                              + repr(code.co_consts).encode()).hexdigest()[:10]
        except AttributeError:  # non-function callable
            fp = type(self._fn).__name__
        return f"{self.default_metric}@{fp}"

    def evaluate_all(self, labels, pred_col, w=None) -> Dict[str, float]:
        return {self.default_metric: float(self._fn(labels, pred_col, w))}

    def is_larger_better(self, metric: Optional[str] = None) -> bool:
        return self.larger_better


class Evaluators:
    """Factory namespace (reference Evaluators.scala:40)."""

    @staticmethod
    def custom(metric_name: str, larger_better: bool,
               evaluate_fn) -> CustomEvaluator:
        """Reference Evaluators.*.custom(metricName, isLargerBetter,
        evaluateFn). `evaluate_fn(labels, pred_col, w) -> float`; helpers
        `prediction_of`/`probability_of`/`positive_score_of` (models/
        prediction.py) extract the score views from the column."""
        return CustomEvaluator(metric_name, larger_better, evaluate_fn)

    class BinaryClassification:
        @staticmethod
        def au_pr() -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(metric="au_pr")

        auPR = au_pr

        @staticmethod
        def au_roc() -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(metric="au_roc")

        auROC = au_roc

        @staticmethod
        def precision() -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(metric="precision")

        @staticmethod
        def recall() -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(metric="recall")

        @staticmethod
        def f1() -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(metric="f1")

        @staticmethod
        def error() -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(metric="error")

        @staticmethod
        def brier_score() -> BinScoreEvaluator:
            return BinScoreEvaluator()

    class MultiClassification:
        @staticmethod
        def precision() -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(metric="precision")

        @staticmethod
        def recall() -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(metric="recall")

        @staticmethod
        def f1() -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(metric="f1")

        @staticmethod
        def error() -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(metric="error")

    class Regression:
        @staticmethod
        def rmse() -> RegressionEvaluator:
            return RegressionEvaluator(metric="rmse")

        @staticmethod
        def mse() -> RegressionEvaluator:
            return RegressionEvaluator(metric="mse")

        @staticmethod
        def mae() -> RegressionEvaluator:
            return RegressionEvaluator(metric="mae")

        @staticmethod
        def r2() -> RegressionEvaluator:
            return RegressionEvaluator(metric="r2")
