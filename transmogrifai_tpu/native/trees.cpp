// Native host tree builder — the CPU twin of ops/trees.py.
//
// The XLA tree kernels are designed for the TPU regime (N >> 2^depth):
// dense per-level histograms over all 2^d nodes lower to MXU contractions
// and tile perfectly. On the host at small N with deep trees (the
// reference's default RF grid reaches maxDepth=12 -> 4096-node levels for
// 900-row Titanic) that density is pure waste: most nodes are empty or
// stopped. This builder is the occupancy-aware equivalent — per-node row
// partitions, work only on live nodes, early subtree termination — i.e.
// the same role libxgboost's C++ hist algorithm plays for the reference
// (XGBoost4J JNI, SURVEY 2.9). Semantics mirror ops/trees.py grow_tree:
//   - binned matrix with dedicated missing bin 0, present bins [1, B-1]
//   - gain = sum_k GL_k^2/(HL+l) + GR_k^2/(HR+l) - Gt_k^2/(Ht+l) with
//     sparsity-aware missing direction (left prefix keeps / drops the
//     missing-bin mass), validity = min_child_weight / min_instances /
//     min_info_gain (optionally normalized by max(Ht,1)) / gamma
//   - candidate order (feature, bin, direction) with first-max wins,
//     matching jnp.argmax over the same flattening
//   - dead node encoding feat=0, thresh=B-1, miss=0 (all rows left); a
//     dead node's subtree is provably dead (children inherit the exact
//     row set), so its mass lands at the leftmost descendant leaf.
//     One RF nuance: with per-node feature subsets the XLA path redraws
//     a new subset for the (same-rows) child at the next level and may
//     find a split there; this builder finalizes the node immediately —
//     Spark's semantics (a no-split node is a leaf). Both are defensible;
//     RF parity is statistical anyway (different bootstrap RNG).
//   - leaf = lr * -G/(H+lambda+eps) (newton) or G/(H+eps) (mean),
//     zeroed when the (H>0) row count is < 0.5
// Differences: accumulation in double (XLA: f32 tree-reduce) and its own
// splitmix64 RNG for bootstrap/feature subsets — near-tie splits and
// sampled ensembles agree statistically, not bit-for-bit.
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <vector>
#include <algorithm>

namespace {

constexpr double EPS = 1e-12;

int64_t g_group_sweeps = 0;  // histogram sweeps (tests probe grouping)

struct Rng {  // splitmix64
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  int poisson(double mean) {  // Knuth; mean <= ~10 here
    double L = std::exp(-mean), p = 1.0;
    int k = 0;
    do { ++k; p *= uniform(); } while (p > L);
    return k - 1;
  }
};

struct GrowParams {
  int depth, B, K;
  double reg_lambda, min_child_weight, min_instances, min_info_gain, gamma;
  bool normalize_gain;
  double lr;
  int leaf_mode;  // 0 newton, 1 mean
  double feature_frac;  // < 1 => per-node subsets (RF)
};

inline double score(const double* g, double h, int K, double lam) {
  double s = 0.0;
  for (int k = 0; k < K; ++k) s += g[k] * g[k];
  return s / (h + lam + EPS);
}

// Grow one tree. Xb [N, F] bins (int32 or uint8 — 1-byte bins matter:
// the Xb stream is the dominant memory traffic at big N); G [N, K];
// H [N]. Outputs feat/thresh/miss [2^depth - 1] (pre-filled dead), leaf
// [2^depth, K] (pre-zeroed), and per-row payload `row_out` [N, K]
// (training-time prediction for boosting; may be null).
//
// Level pass = SEQUENTIAL sweeps over the whole row array (libxgboost's
// cache strategy): one sweep accumulates every live node's interleaved
// histogram (each uint8 row of F=64 is exactly one cache line), a second
// sweep routes rows / settles dead nodes in place via `nodeid`. The
// earlier range-partition design gathered rows per node — one cache miss
// per (row, pass) at big N. Live-node histograms are compact (allocated
// for occupied nodes only, grouped under a memory budget when a deep
// level has many live nodes), so deep trees on small data stay cheap and
// big data stays bandwidth-bound, not latency-bound.
template <typename XbT>
void grow_tree(const XbT* Xb, int64_t N, int F, const float* G,
               const float* H, const GrowParams& P,
               const uint8_t* tree_fmask, Rng& rng,
               int32_t* feat, int32_t* thresh, int32_t* miss, float* leaf,
               float* row_out, int32_t* nodeid) {
  const int B = P.B, K = P.K, depth = P.depth;
  const int M = (1 << depth) - 1;
  const int L = 1 << depth;
  for (int i = 0; i < M; ++i) { feat[i] = 0; thresh[i] = B - 1; miss[i] = 0; }
  std::memset(leaf, 0, sizeof(float) * L * K);
  const int C2 = K + 2;  // interleaved cell: [g_0..g_{K-1}, h, count]
  const size_t hist_sz = (size_t)F * B * C2;
  // histogram bytes per group; TMOG_TREE_HIST_BUDGET_MB overrides (the
  // grouping path is hard to reach with real sizes — tests shrink it)
  static const size_t BUDGET = [] {
    const char* e = std::getenv("TMOG_TREE_HIST_BUDGET_MB");
    long mb = e ? std::atol(e) : 0;
    return (size_t)(mb > 0 ? mb : 768) << 20;
  }();

  // rel node id of each row at the current level; -1 = settled
  for (int64_t r = 0; r < N; ++r) nodeid[r] = 0;

  std::vector<double> cg(K), bg(K);
  std::vector<uint8_t> node_fmask(F);

  // terminal payload for node (lvl, rel) with totals (gt, ht, ct); the
  // subtree of a dead node is provably dead (children inherit the exact
  // row set), so the mass lands at the leftmost descendant leaf
  auto leaf_value = [&](const double* gt, double ht, double ct, int lvl,
                        int rel) -> const float* {
    float* out = leaf + ((size_t)rel << (depth - lvl)) * K;
    if (ct >= 0.5)
      for (int k = 0; k < K; ++k)
        out[k] = (float)(P.lr * (P.leaf_mode == 0
                                     ? -gt[k] / (ht + P.reg_lambda + EPS)
                                     : gt[k] / (ht + EPS)));
    return out;
  };

  // split search over one node's histogram: (feature, bin, direction)
  // first-max order (matches jnp.argmax over the same flattening)
  auto search = [&](const double* hist, const double* gt, double ht,
                    double ct, const uint8_t* fmask, int* out_f,
                    int* out_t, int* out_m) {
    const double parent = score(gt, ht, K, P.reg_lambda);
    const double norm = P.normalize_gain ? std::max(ht, 1.0) : 1.0;
    double best_gain = -1.0;
    int bf = -1, bt = -1, bm = 0;
    for (int f = 0; f < F; ++f) {
      if (fmask && !fmask[f]) continue;
      const double* fcell = hist + (size_t)f * B * C2;
      const double* gm = fcell;  // missing-bin (slot 0) mass
      const double hm = fcell[K], cm = fcell[K + 1];
      for (int k = 0; k < K; ++k) cg[k] = 0.0;
      double chl = 0.0, ccl = 0.0;
      for (int b = 0; b < B; ++b) {
        const double* cell = fcell + (size_t)b * C2;
        for (int k = 0; k < K; ++k) cg[k] += cell[k];
        chl += cell[K];
        ccl += cell[K + 1];
        for (int dir = 0; dir < 2; ++dir) {
          double hl = chl, cl = ccl;
          const double* gl = cg.data();
          if (dir == 1) {  // move missing mass right
            for (int k = 0; k < K; ++k) bg[k] = cg[k] - gm[k];
            gl = bg.data();
            hl -= hm;
            cl -= cm;
          }
          const double hr = ht - hl, cr = ct - cl;
          double sr = 0.0, sl = 0.0, grk;
          for (int k = 0; k < K; ++k) {
            grk = gt[k] - gl[k];
            sr += grk * grk;
          }
          for (int k = 0; k < K; ++k) sl += gl[k] * gl[k];
          const double gain = sl / (hl + P.reg_lambda + EPS)
              + sr / (hr + P.reg_lambda + EPS) - parent;
          const bool ok = hl >= P.min_child_weight
              && hr >= P.min_child_weight && cl >= P.min_instances
              && cr >= P.min_instances && gain / norm > P.min_info_gain
              && gain > 2.0 * P.gamma;
          if (ok && gain > best_gain) {
            best_gain = gain;
            bf = f; bt = b; bm = dir;
          }
        }
      }
    }
    *out_f = bf; *out_t = bt; *out_m = bm;
  };

  std::vector<int32_t> live{0};  // sorted rel ids of occupied nodes
  std::vector<double> hists, gtot, htot, ctot;
  std::vector<int32_t> slot_of, bf_s, bt_s, bm_s;
  std::vector<const float*> dead_leaf;
  std::vector<int64_t> child_cnt;

  for (int lvl = 0; lvl < depth && !live.empty(); ++lvl) {
    const int n_live = (int)live.size();
    slot_of.assign((size_t)1 << lvl, -1);
    for (int s = 0; s < n_live; ++s) slot_of[live[s]] = s;
    gtot.assign((size_t)n_live * K, 0.0);
    htot.assign(n_live, 0.0);
    ctot.assign(n_live, 0.0);
    bf_s.assign(n_live, -1);
    bt_s.assign(n_live, B - 1);
    bm_s.assign(n_live, 0);

    const int group = std::max<int>(1, (int)std::min<size_t>(
        (size_t)n_live, BUDGET / (hist_sz * sizeof(double))));
    for (int g0 = 0; g0 < n_live; g0 += group) {
      const int g1 = std::min(n_live, g0 + group);
      ++g_group_sweeps;
      hists.assign((size_t)(g1 - g0) * hist_sz, 0.0);
      for (int64_t r = 0; r < N; ++r) {  // sequential histogram sweep
        const int32_t rel = nodeid[r];
        if (rel < 0) continue;
        const int32_t s = slot_of[rel];
        if (s < g0 || s >= g1) continue;
        double* hist = hists.data() + (size_t)(s - g0) * hist_sz;
        const XbT* xr = Xb + (size_t)r * F;
        const float* gr = G + (size_t)r * K;
        const double h = H[r];
        const double c = H[r] > 0.f ? 1.0 : 0.0;
        for (int f = 0; f < F; ++f) {
          double* cell = hist + ((size_t)f * B + xr[f]) * C2;
          for (int k = 0; k < K; ++k) cell[k] += gr[k];
          cell[K] += h;
          cell[K + 1] += c;
        }
        double* gt = gtot.data() + (size_t)s * K;
        for (int k = 0; k < K; ++k) gt[k] += gr[k];
        htot[s] += h;
        ctot[s] += c;
      }
      for (int s = g0; s < g1; ++s) {
        const uint8_t* fmask = tree_fmask;
        if (P.feature_frac < 1.0) {
          // per-node feature subset (Spark featureSubsetStrategy):
          // partial Fisher-Yates drawing kf distinct features, in live
          // (sorted-rel) order so the RNG stream is deterministic
          int kf = std::max(1, (int)std::lround(P.feature_frac * F));
          std::fill(node_fmask.begin(), node_fmask.end(), 0);
          std::vector<int> ids(F);
          for (int f = 0; f < F; ++f) ids[f] = f;
          for (int t = 0; t < kf; ++t) {
            int j = t + (int)(rng.next() % (uint64_t)(F - t));
            std::swap(ids[t], ids[j]);
            node_fmask[ids[t]] = 1;
          }
          fmask = node_fmask.data();
        }
        search(hists.data() + (size_t)(s - g0) * hist_sz,
               gtot.data() + (size_t)s * K, htot[s], ctot[s], fmask,
               &bf_s[s], &bt_s[s], &bm_s[s]);
      }
    }

    dead_leaf.assign(n_live, nullptr);
    for (int s = 0; s < n_live; ++s) {
      const int rel = live[s];
      if (bf_s[s] < 0) {
        dead_leaf[s] = leaf_value(gtot.data() + (size_t)s * K, htot[s],
                                  ctot[s], lvl, rel);
      } else {
        const int gi = (1 << lvl) - 1 + rel;
        feat[gi] = bf_s[s];
        thresh[gi] = bt_s[s];
        miss[gi] = bm_s[s];
      }
    }

    // sequential routing sweep: settle dead rows, advance the rest
    child_cnt.assign((size_t)2 * n_live, 0);
    for (int64_t r = 0; r < N; ++r) {
      const int32_t rel = nodeid[r];
      if (rel < 0) continue;
      const int32_t s = slot_of[rel];
      if (bf_s[s] < 0) {
        if (row_out) {
          const float* out = dead_leaf[s];
          for (int k = 0; k < K; ++k)
            row_out[(size_t)r * K + k] = out[k];
        }
        nodeid[r] = -1;
        continue;
      }
      const int32_t b = (int32_t)Xb[(size_t)r * F + bf_s[s]];
      const int right = (b > bt_s[s]) || (b == 0 && bm_s[s] > 0) ? 1 : 0;
      nodeid[r] = 2 * rel + right;
      ++child_cnt[2 * s + right];
    }

    std::vector<int32_t> nxt;
    nxt.reserve((size_t)2 * n_live);
    for (int s = 0; s < n_live; ++s) {
      if (bf_s[s] < 0) continue;
      if (child_cnt[2 * s]) nxt.push_back(2 * live[s]);
      if (child_cnt[2 * s + 1]) nxt.push_back(2 * live[s] + 1);
    }
    live.swap(nxt);
  }

  // full-depth survivors: one totals sweep -> leaves (+ row_out)
  if (!live.empty()) {
    const int n_live = (int)live.size();
    slot_of.assign((size_t)1 << depth, -1);
    for (int s = 0; s < n_live; ++s) slot_of[live[s]] = s;
    gtot.assign((size_t)n_live * K, 0.0);
    htot.assign(n_live, 0.0);
    ctot.assign(n_live, 0.0);
    for (int64_t r = 0; r < N; ++r) {
      const int32_t rel = nodeid[r];
      if (rel < 0) continue;
      const int32_t s = slot_of[rel];
      const float* gr = G + (size_t)r * K;
      double* gt = gtot.data() + (size_t)s * K;
      for (int k = 0; k < K; ++k) gt[k] += gr[k];
      htot[s] += H[r];
      ctot[s] += H[r] > 0.f ? 1.0 : 0.0;
    }
    std::vector<const float*> outp(n_live);
    for (int s = 0; s < n_live; ++s)
      outp[s] = leaf_value(gtot.data() + (size_t)s * K, htot[s], ctot[s],
                           depth, live[s]);
    if (row_out) {
      for (int64_t r = 0; r < N; ++r) {
        const int32_t rel = nodeid[r];
        if (rel < 0) continue;
        const float* out = outp[slot_of[rel]];
        for (int k = 0; k < K; ++k) row_out[(size_t)r * K + k] = out[k];
      }
    }
  }
}

void tree_feature_mask(std::vector<uint8_t>& mask, int F,
                       double feature_frac, Rng& rng) {
  mask.assign(F, 1);
  if (feature_frac >= 1.0) return;
  int kf = std::max(1, (int)std::lround(feature_frac * F));
  mask.assign(F, 0);
  std::vector<int> ids(F);
  for (int f = 0; f < F; ++f) ids[f] = f;
  for (int t = 0; t < kf; ++t) {
    int j = t + (int)(rng.next() % (uint64_t)(F - t));
    std::swap(ids[t], ids[j]);
    mask[ids[t]] = 1;
  }
}


// Binary-logistic / squared-loss boosting (ops/trees.fit_gbt twin).
// feat/thresh/miss [n_rounds, 2^depth - 1]; leaf [n_rounds, 2^depth].
template <typename XbT>
int gbt_fit_impl(const XbT* Xb, int64_t N, int32_t F, int32_t B,
                 const float* y, const float* w, int32_t loss,
                 int32_t n_rounds, int32_t depth, double lr,
                 double reg_lambda, double min_child_weight,
                 double min_instances, double min_info_gain, double gamma,
                 double subsample, double feature_frac, uint64_t seed,
                 int32_t* feat, int32_t* thresh, int32_t* miss, float* leaf,
                 float* base_out) {
  if (N <= 0 || depth < 1 || depth > 20) return 1;
  Rng rng(seed * 0x9e3779b97f4a7c15ULL + 1);
  double wsum = 0.0, wy = 0.0;
  for (int64_t r = 0; r < N; ++r) { wsum += w[r]; wy += w[r] * y[r]; }
  wsum += EPS;
  double base;
  if (loss == 0) {
    double p0 = std::min(std::max(wy / wsum, 1e-6), 1.0 - 1e-6);
    base = std::log(p0 / (1.0 - p0));
  } else {
    base = wy / wsum;
  }
  *base_out = (float)base;

  const int M = (1 << depth) - 1, L = 1 << depth;
  std::vector<float> margin(N, (float)base), g(N), h(N), step(N);
  std::vector<float> gsub(N), hsub(N);
  std::vector<int32_t> nodeid(N);
  std::vector<uint8_t> fmask;
  GrowParams P{depth, B, 1, reg_lambda, min_child_weight, min_instances,
               min_info_gain, gamma, false, lr, 0, 1.0};
  for (int t = 0; t < n_rounds; ++t) {
    for (int64_t r = 0; r < N; ++r) {
      if (loss == 0) {
        const double m = margin[r];
        const double p = 1.0 / (1.0 + std::exp(-m));
        g[r] = (float)(w[r] * (p - y[r]));
        h[r] = (float)std::max((double)w[r] * p * (1.0 - p), EPS);
      } else {
        g[r] = w[r] * (margin[r] - y[r]);
        h[r] = w[r];
      }
    }
    float* gp = g.data();
    float* hp = h.data();
    if (subsample < 1.0) {
      for (int64_t r = 0; r < N; ++r) {
        const float keep = rng.uniform() < subsample ? 1.f : 0.f;
        gsub[r] = g[r] * keep;
        hsub[r] = h[r] * keep;
      }
      gp = gsub.data();
      hp = hsub.data();
    }
    tree_feature_mask(fmask, F, feature_frac, rng);
    grow_tree(Xb, N, F, gp, hp, P, fmask.data(), rng,
              feat + (size_t)t * M, thresh + (size_t)t * M,
              miss + (size_t)t * M, leaf + (size_t)t * L, step.data(),
              nodeid.data());
    for (int64_t r = 0; r < N; ++r) margin[r] += step[r];
  }
  return 0;
}

// Multiclass softmax boosting (fit_gbt_softmax twin).
// Outputs stacked [n_rounds * n_classes] trees (round-major, class-minor).
template <typename XbT>
int gbt_softmax_impl(const XbT* Xb, int64_t N, int32_t F, int32_t B,
                         const float* y, const float* w, int32_t n_classes,
                         int32_t n_rounds, int32_t depth, double lr,
                         double reg_lambda, double min_child_weight,
                         double gamma, double subsample, double feature_frac,
                         uint64_t seed, int32_t* feat, int32_t* thresh,
                         int32_t* miss, float* leaf) {
  if (N <= 0 || depth < 1 || depth > 20 || n_classes < 2) return 1;
  Rng rng(seed * 0x9e3779b97f4a7c15ULL + 2);
  const int M = (1 << depth) - 1, L = 1 << depth, C = n_classes;
  std::vector<float> margin((size_t)N * C, 0.f), p((size_t)N * C);
  std::vector<float> g(N), h(N), step(N), keep(N);
  std::vector<int32_t> nodeid(N);
  std::vector<uint8_t> fmask;
  // min_instances=1, min_info_gain=0: fit_gbt_softmax grows with
  // grow_tree's defaults for those
  GrowParams P{depth, B, 1, reg_lambda, min_child_weight, 1.0, 0.0, gamma,
               false, lr, 0, 1.0};
  for (int t = 0; t < n_rounds; ++t) {
    for (int64_t r = 0; r < N; ++r) {  // softmax over classes
      const float* mr = margin.data() + (size_t)r * C;
      float mx = mr[0];
      for (int c = 1; c < C; ++c) mx = std::max(mx, mr[c]);
      double Z = 0.0;
      for (int c = 0; c < C; ++c) Z += std::exp((double)mr[c] - mx);
      for (int c = 0; c < C; ++c)
        p[(size_t)r * C + c] = (float)(std::exp((double)mr[c] - mx) / Z);
    }
    for (int64_t r = 0; r < N; ++r)
      keep[r] = (subsample >= 1.0 || rng.uniform() < subsample) ? 1.f : 0.f;
    tree_feature_mask(fmask, F, feature_frac, rng);
    for (int c = 0; c < C; ++c) {
      for (int64_t r = 0; r < N; ++r) {
        const double pc = p[(size_t)r * C + c];
        const double yc = ((int)y[r] == c) ? 1.0 : 0.0;
        g[r] = (float)(w[r] * (pc - yc)) * keep[r];
        h[r] = (float)std::max((double)w[r] * pc * (1.0 - pc), EPS)
            * keep[r];
      }
      const size_t ti = (size_t)t * C + c;
      grow_tree(Xb, N, F, g.data(), h.data(), P, fmask.data(), rng,
                feat + ti * M, thresh + ti * M, miss + ti * M, leaf + ti * L,
                step.data(), nodeid.data());
      for (int64_t r = 0; r < N; ++r) margin[(size_t)r * C + c] += step[r];
    }
  }
  return 0;
}

// Random forest / single tree (fit_forest twin): mean-mode leaves, Poisson
// bootstrap, per-node feature subsets. G [N, K] payload (class one-hots x
// weight, or y x weight); H [N] weights. leaf [n_trees, 2^depth, K].
template <typename XbT>
int rf_fit_impl(const XbT* Xb, int64_t N, int32_t F, int32_t B,
                const float* G, const float* H, int32_t K, int32_t n_trees,
                int32_t depth, double reg_lambda, double min_instances,
                double min_info_gain, double subsample, double feature_frac,
                int32_t bootstrap, uint64_t seed, int32_t* feat,
                int32_t* thresh, int32_t* miss, float* leaf) {
  if (N <= 0 || depth < 1 || depth > 20 || K < 1) return 1;
  Rng rng(seed * 0x9e3779b97f4a7c15ULL + 3);
  const int M = (1 << depth) - 1, L = 1 << depth;
  std::vector<float> Gt((size_t)N * K), Ht(N);
  std::vector<int32_t> nodeid(N);
  GrowParams P{depth, B, (int)K, reg_lambda, 0.0, min_instances,
               min_info_gain, 0.0, true, 1.0, 1, feature_frac};
  for (int t = 0; t < n_trees; ++t) {
    for (int64_t r = 0; r < N; ++r) {
      float rw;
      if (bootstrap) rw = (float)rng.poisson(subsample);
      else rw = rng.uniform() < subsample ? 1.f : 0.f;
      Ht[r] = H[r] * rw;
      for (int k = 0; k < K; ++k)
        Gt[(size_t)r * K + k] = G[(size_t)r * K + k] * rw;
    }
    grow_tree(Xb, N, F, Gt.data(), Ht.data(), P, nullptr, rng,
              feat + (size_t)t * M, thresh + (size_t)t * M,
              miss + (size_t)t * M, leaf + (size_t)t * L * K, nullptr,
              nodeid.data());
  }
  return 0;
}

// Sum of tree payloads on binned rows (predict_forest_bins twin). Rows
// outer, trees inner: each row's bins stay in cache across the whole
// ensemble; node arrays live in L1. feat/thresh/miss [T, 2^depth - 1],
// leaf [T, 2^depth, K], out [N, K] (pre-zeroed by the caller).
template <typename XbT>
void predict_bins_impl(const XbT* Xb, int64_t N, int32_t F,
                              const int32_t* feat, const int32_t* thresh,
                              const int32_t* miss, const float* leaf,
                              int32_t T, int32_t depth, int32_t K,
                              float* out) {
  const int M = (1 << depth) - 1;
  const int L = 1 << depth;
  for (int64_t r = 0; r < N; ++r) {
    const XbT* xr = Xb + (size_t)r * F;
    float* o = out + (size_t)r * K;
    for (int t = 0; t < T; ++t) {
      const int32_t* tf = feat + (size_t)t * M;
      const int32_t* tt = thresh + (size_t)t * M;
      const int32_t* tm = miss + (size_t)t * M;
      int rel = 0;
      for (int d = 0; d < depth; ++d) {
        const int gi = (1 << d) - 1 + rel;
        const int32_t b = (int32_t)xr[tf[gi]];
        const int right = (b > tt[gi]) || (b == 0 && tm[gi] > 0) ? 1 : 0;
        rel = 2 * rel + right;
      }
      const float* lf = leaf + ((size_t)t * L + rel) * K;
      for (int k = 0; k < K; ++k) o[k] += lf[k];
    }
  }
}



// Raw-value ensemble traversal (serving): x >= thresh goes right, NaN
// follows the learned miss direction. thresh_val in raw units
// (+inf = all-left dead node, -inf = all-present-right).
void predict_raw_impl(const float* X, int64_t N, int32_t F,
                      const int32_t* feat, const float* thresh_val,
                      const int32_t* miss, const float* leaf, int32_t T,
                      int32_t depth, int32_t K, float* out) {
  const int M = (1 << depth) - 1;
  const int L = 1 << depth;
  for (int64_t r = 0; r < N; ++r) {
    const float* xr = X + (size_t)r * F;
    float* o = out + (size_t)r * K;
    for (int t = 0; t < T; ++t) {
      const int32_t* tf = feat + (size_t)t * M;
      const float* tv = thresh_val + (size_t)t * M;
      const int32_t* tm = miss + (size_t)t * M;
      int rel = 0;
      for (int d = 0; d < depth; ++d) {
        const int gi = (1 << d) - 1 + rel;
        const float x = xr[tf[gi]];
        int right;
        if (std::isnan(x)) right = tm[gi] > 0 ? 1 : 0;
        else right = x >= tv[gi] ? 1 : 0;
        rel = 2 * rel + right;
      }
      const float* lf = leaf + ((size_t)t * L + rel) * K;
      for (int k = 0; k < K; ++k) o[k] += lf[k];
    }
  }
}

}  // namespace

// C ABI: `xb_itemsize` selects the bin dtype (4 = int32, 1 = uint8 —
// 1-byte bins quarter the dominant Xb memory stream at big N).
extern "C" {

int tmog_gbt_fit(const void* Xb, int64_t N, int32_t F, int32_t B,
                 int32_t xb_itemsize, const float* y, const float* w,
                 int32_t loss, int32_t n_rounds, int32_t depth, double lr,
                 double reg_lambda, double min_child_weight,
                 double min_instances, double min_info_gain, double gamma,
                 double subsample, double feature_frac, uint64_t seed,
                 int32_t* feat, int32_t* thresh, int32_t* miss, float* leaf,
                 float* base_out) {
  if (xb_itemsize == 1)
    return gbt_fit_impl((const uint8_t*)Xb, N, F, B, y, w, loss, n_rounds,
                        depth, lr, reg_lambda, min_child_weight,
                        min_instances, min_info_gain, gamma, subsample,
                        feature_frac, seed, feat, thresh, miss, leaf,
                        base_out);
  if (xb_itemsize == 4)
    return gbt_fit_impl((const int32_t*)Xb, N, F, B, y, w, loss, n_rounds,
                        depth, lr, reg_lambda, min_child_weight,
                        min_instances, min_info_gain, gamma, subsample,
                        feature_frac, seed, feat, thresh, miss, leaf,
                        base_out);
  return 2;
}

int tmog_gbt_softmax_fit(const void* Xb, int64_t N, int32_t F, int32_t B,
                         int32_t xb_itemsize, const float* y, const float* w,
                         int32_t n_classes, int32_t n_rounds, int32_t depth,
                         double lr, double reg_lambda,
                         double min_child_weight, double gamma,
                         double subsample, double feature_frac,
                         uint64_t seed, int32_t* feat, int32_t* thresh,
                         int32_t* miss, float* leaf) {
  if (xb_itemsize == 1)
    return gbt_softmax_impl((const uint8_t*)Xb, N, F, B, y, w, n_classes,
                            n_rounds, depth, lr, reg_lambda,
                            min_child_weight, gamma, subsample,
                            feature_frac, seed, feat, thresh, miss, leaf);
  if (xb_itemsize == 4)
    return gbt_softmax_impl((const int32_t*)Xb, N, F, B, y, w, n_classes,
                            n_rounds, depth, lr, reg_lambda,
                            min_child_weight, gamma, subsample,
                            feature_frac, seed, feat, thresh, miss, leaf);
  return 2;
}

int tmog_rf_fit(const void* Xb, int64_t N, int32_t F, int32_t B,
                int32_t xb_itemsize, const float* G, const float* H,
                int32_t K, int32_t n_trees, int32_t depth,
                double reg_lambda, double min_instances,
                double min_info_gain, double subsample, double feature_frac,
                int32_t bootstrap, uint64_t seed, int32_t* feat,
                int32_t* thresh, int32_t* miss, float* leaf) {
  if (xb_itemsize == 1)
    return rf_fit_impl((const uint8_t*)Xb, N, F, B, G, H, K, n_trees,
                       depth, reg_lambda, min_instances, min_info_gain,
                       subsample, feature_frac, bootstrap, seed, feat,
                       thresh, miss, leaf);
  if (xb_itemsize == 4)
    return rf_fit_impl((const int32_t*)Xb, N, F, B, G, H, K, n_trees,
                       depth, reg_lambda, min_instances, min_info_gain,
                       subsample, feature_frac, bootstrap, seed, feat,
                       thresh, miss, leaf);
  return 2;
}

int64_t tmog_debug_group_sweeps(void) { return g_group_sweeps; }

int tmog_predict_raw(const float* X, int64_t N, int32_t F,
                     const int32_t* feat, const float* thresh_val,
                     const int32_t* miss, const float* leaf, int32_t T,
                     int32_t depth, int32_t K, float* out) {
  predict_raw_impl(X, N, F, feat, thresh_val, miss, leaf, T, depth, K,
                   out);
  return 0;
}

int tmog_predict_bins(const void* Xb, int64_t N, int32_t F,
                      int32_t xb_itemsize, const int32_t* feat,
                      const int32_t* thresh, const int32_t* miss,
                      const float* leaf, int32_t T, int32_t depth,
                      int32_t K, float* out) {
  if (xb_itemsize == 1) {
    predict_bins_impl((const uint8_t*)Xb, N, F, feat, thresh, miss, leaf,
                      T, depth, K, out);
    return 0;
  }
  if (xb_itemsize == 4) {
    predict_bins_impl((const int32_t*)Xb, N, F, feat, thresh, miss, leaf,
                      T, depth, K, out);
    return 0;
  }
  return 2;
}

}  // extern "C"
