"""Native host kernels (C++, ctypes-loaded): murmur3 hashing trick, fused
tokenize+hash+count, CSV scanning (hashing.cpp) and the occupancy-aware
tree builder (trees.cpp). See build.py, ops/native_bridge.py and
ops/trees_host.py."""
from .build import LIB, SOURCES, build

__all__ = ["LIB", "SOURCES", "build"]
