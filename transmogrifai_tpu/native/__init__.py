"""Native host kernels (C++, ctypes-loaded): murmur3 hashing trick, fused
tokenize+hash+count, CSV scanning. See build.py and ops/native_bridge.py."""
from .build import LIB, SRC, build

__all__ = ["LIB", "SRC", "build"]
