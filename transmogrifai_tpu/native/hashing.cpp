// Host-side native kernels: MurmurHash3 hashing trick, fused
// tokenize+hash+count, and CSV field scanning.
//
// The reference's host hot loops ran on the JVM (Lucene tokenization,
// MurmurHash3 via Spark's HashingTF, spark-csv parsing; see
// core/.../impl/feature/OPCollectionHashingVectorizer.scala and
// readers/.../CSVReaders.scala). In the TPU build those loops prepare
// fixed-width tensors on the host before device_put; this library is that
// data path in C++ — bulk byte-packed APIs, no per-row Python overhead.
// Loaded via ctypes (ops/native_bridge.py); every entry point has a pure
// NumPy fallback, so the library is an accelerator, not a dependency.
//
// Build: g++ -O3 -shared -fPIC (driven by native/build.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---- MurmurHash3 x86_32 ---------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

uint32_t tmog_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51;
  const uint32_t c2 = 0x1b873593;

  const uint8_t* blocks = data;
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, blocks + i * 4, 4);  // little-endian hosts
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k1 ^= static_cast<uint32_t>(tail[1]) << 8; [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

// ---- batch string hashing -------------------------------------------------

// buf: concatenated UTF-8 bytes; offsets: [n+1] prefix offsets.
// out: [n] uint32 hash values.
void tmog_hash_strings(const uint8_t* buf, const int64_t* offsets, int64_t n,
                       uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = tmog_murmur3_32(buf + offsets[i], offsets[i + 1] - offsets[i],
                             seed);
  }
}

// token stream -> per-doc hashed counts.
// buf/tok_offsets: [n_tokens+1] packed tokens; doc_tok_counts: [n_docs]
// tokens per document. out: [n_docs * bins] float32, caller-zeroed
// (float: the block feeds the f32 device matrix; counts fit exactly).
void tmog_hash_tokens_to_counts(const uint8_t* buf, const int64_t* tok_offsets,
                                const int64_t* doc_tok_counts, int64_t n_docs,
                                int64_t bins, uint32_t seed, float* out) {
  int64_t t = 0;
  for (int64_t d = 0; d < n_docs; d++) {
    float* row = out + d * bins;
    const int64_t end = t + doc_tok_counts[d];
    for (; t < end; t++) {
      const uint32_t h = tmog_murmur3_32(buf + tok_offsets[t],
                                         tok_offsets[t + 1] - tok_offsets[t],
                                         seed);
      row[h % bins] += 1.0f;
    }
  }
}

// ---- fused tokenize + hash + count ---------------------------------------

// ASCII-lowercase tokenizer matching transformers/text.tokenize_text:
// tokens are maximal runs of [A-Za-z0-9'], lowercased, len >= min_len.
// docs packed in buf with [n_docs+1] offsets; out: [n_docs * bins] float32,
// caller-zeroed. This is the whole text->tensor hot loop in one pass.
// row_stride >= bins lets the caller write counts directly into a wider
// matrix (e.g. [n, bins+1] with a trailing null-indicator column) without
// a second full-size copy on the serving path.
void tmog_tokenize_hash_counts_s(const uint8_t* buf, const int64_t* doc_offsets,
                               int64_t n_docs, int64_t bins, uint32_t seed,
                               int64_t min_len, int64_t row_stride,
                               float* out) {
  uint8_t tok[256];
  for (int64_t d = 0; d < n_docs; d++) {
    float* row = out + d * row_stride;
    const uint8_t* p = buf + doc_offsets[d];
    const uint8_t* end = buf + doc_offsets[d + 1];
    int64_t tlen = 0;
    for (; p <= end; p++) {
      uint8_t c = (p < end) ? *p : 0;
      uint8_t lc = (c >= 'A' && c <= 'Z') ? c + 32 : c;
      bool is_tok = (lc >= 'a' && lc <= 'z') || (lc >= '0' && lc <= '9') ||
                    lc == '\'';
      if (is_tok) {
        if (tlen < static_cast<int64_t>(sizeof(tok))) tok[tlen++] = lc;
      } else {
        if (tlen >= min_len) {
          const uint32_t h = tmog_murmur3_32(tok, tlen, seed);
          row[h % bins] += 1.0f;
        }
        tlen = 0;
      }
    }
  }
}

// ---- CSV field scanning ---------------------------------------------------

// Scans one CSV buffer, recording field start/end offsets (RFC-4180 quoting:
// fields may be "..." with doubled quotes). Returns the number of fields
// written, or -(needed) if out capacity is insufficient.
// field_bounds: [capacity * 2] (start, end) byte offsets into buf (quotes
// stripped); row_ends records the running field count at each row boundary
// into row_field_counts [max_rows]; n_rows receives the row count.
int64_t tmog_csv_scan(const uint8_t* buf, int64_t len, uint8_t delim,
                      int64_t* field_bounds, int64_t capacity,
                      int64_t* row_field_counts, int64_t max_rows,
                      int64_t* n_rows) {
  int64_t nf = 0;      // fields emitted
  int64_t rows = 0;
  int64_t i = 0;
  while (i < len) {
    // one row
    int64_t row_start_nf = nf;
    while (true) {
      // one field
      int64_t start, endo;
      if (buf[i] == '"') {
        start = ++i;
        int64_t w = i;  // write cursor for unescaping "" -> " in place is
        // not allowed (const buf); instead record bounds only when no
        // doubled quotes exist; bail to slow path by marking end=-1.
        bool doubled = false;
        while (i < len) {
          if (buf[i] == '"') {
            if (i + 1 < len && buf[i + 1] == '"') { doubled = true; i += 2; }
            else break;
          } else i++;
        }
        endo = i;
        if (i < len) i++;  // closing quote
        if (doubled) { start = -(start + 1); }  // flag: python re-parses
        (void)w;
      } else {
        start = i;
        while (i < len && buf[i] != delim && buf[i] != '\n' && buf[i] != '\r')
          i++;
        endo = i;
      }
      if (nf >= capacity) return -(nf + 1);
      field_bounds[2 * nf] = start;
      field_bounds[2 * nf + 1] = endo;
      nf++;
      if (i < len && buf[i] == delim) { i++; continue; }
      break;
    }
    // row terminator
    while (i < len && (buf[i] == '\r' || buf[i] == '\n')) {
      if (buf[i] == '\n') { i++; break; }
      i++;
    }
    if (rows < max_rows) row_field_counts[rows] = nf - row_start_nf;
    rows++;
  }
  *n_rows = rows;
  return nf;
}

// ---- bulk float parsing ---------------------------------------------------

// Parse fields [bounds as from tmog_csv_scan] into float64 (NaN when empty
// or non-numeric). Small fast strtod over the bounded field.
void tmog_parse_floats(const uint8_t* buf, const int64_t* field_bounds,
                       int64_t n_fields, double* out) {
  for (int64_t f = 0; f < n_fields; f++) {
    int64_t s = field_bounds[2 * f];
    int64_t e = field_bounds[2 * f + 1];
    if (s < 0) { out[f] = __builtin_nan(""); continue; }  // quoted-escaped
    // trim spaces
    while (s < e && (buf[s] == ' ' || buf[s] == '\t')) s++;
    while (e > s && (buf[e - 1] == ' ' || buf[e - 1] == '\t')) e--;
    if (s >= e) { out[f] = __builtin_nan(""); continue; }
    char tmp[64];
    int64_t n = e - s;
    if (n >= static_cast<int64_t>(sizeof(tmp))) { out[f] = __builtin_nan(""); continue; }
    std::memcpy(tmp, buf + s, n);
    tmp[n] = 0;
    char* endp = nullptr;
    double v = std::strtod(tmp, &endp);
    out[f] = (endp == tmp + n) ? v : __builtin_nan("");
  }
}


// ---- exact dictionary encoding --------------------------------------------

// Dictionary-encode packed strings: open-addressing hash table keyed by
// murmur3 with memcmp verification (exact, not hashed-bucket). Emits
// codes[i] = dense id in FIRST-OCCURRENCE order and firsts[id] = row index
// of each id's first occurrence (so the caller materializes the unique
// strings without re-scanning). Returns n_unique, or -1 when the caller's
// table capacity (table_cap, must be a power of two > n) is too small.
//
// This is the ingest-side replacement for per-column np.unique sorts
// (O(n log n) + object comparisons): one O(n) pass at C speed. The
// reference's analogue is Spark's StringIndexer/dictionary encoding on the
// JVM.
int64_t tmog_dict_encode(const uint8_t* buf, const int64_t* offsets,
                         int64_t n, int64_t* table, int64_t table_cap,
                         int64_t* codes, int64_t* firsts) {
  // table entries: -1 = empty, else row index of the representative
  for (int64_t i = 0; i < table_cap; i++) table[i] = -1;
  const int64_t mask = table_cap - 1;
  int64_t n_unique = 0;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* s = buf + offsets[i];
    const int64_t len = offsets[i + 1] - offsets[i];
    uint64_t slot = tmog_murmur3_32(s, len, 0x9747b28c) & mask;
    for (int64_t probe = 0;; probe++) {
      if (probe > table_cap) return -1;  // table full (caller sized wrong)
      int64_t rep = table[slot];
      if (rep < 0) {
        table[slot] = i;
        codes[i] = n_unique;
        firsts[n_unique] = i;
        n_unique++;
        break;
      }
      const int64_t rlen = offsets[rep + 1] - offsets[rep];
      if (rlen == len && std::memcmp(buf + offsets[rep], s, len) == 0) {
        codes[i] = codes[rep];
        break;
      }
      slot = (slot + probe + 1) & mask;  // quadratic-ish probing
    }
  }
  return n_unique;
}


}  // extern "C"
