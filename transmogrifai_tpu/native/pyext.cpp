// CPython extension: C-speed per-object loops for the host transform path.
//
// The ctypes library (hashing.cpp) gives C-speed loops over PACKED bytes,
// but packing itself — and every other per-PyObject pass (dictionary
// encoding, one-hot code lookup, map-key explosion, float coercion) — was
// a Python-interpreter loop. At serving time those passes dominate the
// score pass (reference anchor: the fused row-map of
// core/.../utils/stages/FitStagesUtil.scala:96-118 ran these loops as
// compiled JVM bytecode; this module is the equivalent compiled tier).
//
// Contract: every function degrades — callers catch ImportError/absence
// and keep their NumPy/pure-Python fallback. Outputs are written into
// caller-allocated numpy arrays through the buffer protocol, so this file
// needs no numpy headers.
//
// Build: g++ -O3 -shared -fPIC -I<python-include> (native/build.py).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Writable int64/float64/uint8 view of a caller-provided numpy array.
struct BufView {
  Py_buffer view{};
  bool ok = false;
  BufView(PyObject* obj, Py_ssize_t itemsize) {
    if (PyObject_GetBuffer(obj, &view, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) !=
        0) {
      return;
    }
    if (view.itemsize != itemsize) {
      PyBuffer_Release(&view);
      PyErr_SetString(PyExc_TypeError, "output buffer itemsize mismatch");
      return;
    }
    ok = true;
  }
  ~BufView() {
    if (ok) PyBuffer_Release(&view);
  }
  Py_ssize_t n() const { return view.len / view.itemsize; }
  void* data() const { return view.buf; }
};

// Borrowed fast-sequence items (list/tuple fast path; ndarray via listify).
struct FastSeq {
  PyObject* fast = nullptr;
  PyObject** items = nullptr;
  Py_ssize_t n = 0;
  explicit FastSeq(PyObject* seq) {
    fast = PySequence_Fast(seq, "expected a sequence");
    if (!fast) return;
    n = PySequence_Fast_GET_SIZE(fast);
    items = PySequence_Fast_ITEMS(fast);
  }
  ~FastSeq() { Py_XDECREF(fast); }
};

// utf8 view of a str object; owns a temporary bytes object only when the
// surrogatepass fallback fires (lone surrogates from surrogateescape
// ingest must hash, not crash).
struct Utf8 {
  const char* p = nullptr;
  Py_ssize_t len = 0;
  PyObject* owned = nullptr;
  bool from(PyObject* s) {
    p = PyUnicode_AsUTF8AndSize(s, &len);
    if (p) return true;
    PyErr_Clear();
    owned = PyUnicode_AsEncodedString(s, "utf-8", "surrogatepass");
    if (!owned) return false;
    p = PyBytes_AS_STRING(owned);
    len = PyBytes_GET_SIZE(owned);
    return true;
  }
  void release() {
    Py_XDECREF(owned);
    owned = nullptr;
  }
};

// pack_strings(seq) -> (bytes, offsets_bytes):
// concatenated utf8 payload + (n+1) int64 offsets, None -> "".
PyObject* pack_strings(PyObject*, PyObject* args) {
  PyObject* seq;
  if (!PyArg_ParseTuple(args, "O", &seq)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  std::vector<const char*> ptrs(fs.n);
  std::vector<Py_ssize_t> lens(fs.n);
  std::vector<PyObject*> owned;
  Py_ssize_t total = 0;
  for (Py_ssize_t i = 0; i < fs.n; i++) {
    PyObject* v = fs.items[i];
    if (v == Py_None) {
      ptrs[i] = "";
      lens[i] = 0;
      continue;
    }
    PyObject* s = v;
    PyObject* tmp = nullptr;
    if (!PyUnicode_Check(v)) {
      tmp = PyObject_Str(v);
      if (!tmp) {
        for (PyObject* o : owned) Py_DECREF(o);
        return nullptr;
      }
      owned.push_back(tmp);
      s = tmp;
    }
    Utf8 u;
    if (!u.from(s)) {
      for (PyObject* o : owned) Py_DECREF(o);
      return nullptr;
    }
    if (u.owned) owned.push_back(u.owned);
    ptrs[i] = u.p;
    lens[i] = u.len;
    total += u.len;
  }
  PyObject* buf = PyBytes_FromStringAndSize(nullptr, total ? total : 1);
  PyObject* offs = PyBytes_FromStringAndSize(
      nullptr, (Py_ssize_t)((fs.n + 1) * sizeof(int64_t)));
  if (!buf || !offs) {
    Py_XDECREF(buf);
    Py_XDECREF(offs);
    for (PyObject* o : owned) Py_DECREF(o);
    return nullptr;
  }
  char* bp = PyBytes_AS_STRING(buf);
  auto* op = reinterpret_cast<int64_t*>(PyBytes_AS_STRING(offs));
  int64_t at = 0;
  op[0] = 0;
  for (Py_ssize_t i = 0; i < fs.n; i++) {
    if (lens[i]) std::memcpy(bp + at, ptrs[i], (size_t)lens[i]);
    at += lens[i];
    op[i + 1] = at;
  }
  if (!total) bp[0] = 0;
  for (PyObject* o : owned) Py_DECREF(o);
  return Py_BuildValue("NN", buf, offs);
}

// dict_encode(seq) -> (n_unique, uniques_list); codes written into the
// int64 out array. None -> "", non-str stringified. First-occurrence
// order. Uses the interpreter's cached str hashes — one PyDict probe per
// row, no packing pass.
PyObject* dict_encode(PyObject*, PyObject* args) {
  PyObject* seq;
  PyObject* out;
  if (!PyArg_ParseTuple(args, "OO", &seq, &out)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  BufView ob(out, sizeof(int64_t));
  if (!ob.ok) return nullptr;
  if (ob.n() < fs.n) {
    PyErr_SetString(PyExc_ValueError, "codes buffer too small");
    return nullptr;
  }
  auto* codes = static_cast<int64_t*>(ob.data());
  PyObject* table = PyDict_New();
  PyObject* uniques = PyList_New(0);
  if (!table || !uniques) {
    Py_XDECREF(table);
    Py_XDECREF(uniques);
    return nullptr;
  }
  PyObject* empty = PyUnicode_FromString("");
  if (!empty) {
    Py_DECREF(table);
    Py_DECREF(uniques);
    return nullptr;
  }
  int64_t next = 0;
  for (Py_ssize_t i = 0; i < fs.n; i++) {
    PyObject* v = fs.items[i];
    PyObject* key;
    PyObject* tmp = nullptr;
    if (v == Py_None) {
      key = empty;
    } else if (PyUnicode_Check(v)) {
      key = v;
    } else {
      tmp = PyObject_Str(v);
      if (!tmp) goto fail;
      key = tmp;
    }
    {
      PyObject* code = PyDict_GetItemWithError(table, key);
      if (code) {
        codes[i] = PyLong_AsLongLong(code);
      } else {
        if (PyErr_Occurred()) {
          Py_XDECREF(tmp);
          goto fail;
        }
        PyObject* c = PyLong_FromLongLong(next);
        if (!c || PyDict_SetItem(table, key, c) != 0 ||
            PyList_Append(uniques, key) != 0) {
          Py_XDECREF(c);
          Py_XDECREF(tmp);
          goto fail;
        }
        Py_DECREF(c);
        codes[i] = next++;
      }
    }
    Py_XDECREF(tmp);
  }
  Py_DECREF(table);
  Py_DECREF(empty);
  return Py_BuildValue("LN", (long long)next, uniques);
fail:
  Py_DECREF(table);
  Py_DECREF(uniques);
  Py_XDECREF(empty);
  return nullptr;
}

// pivot_codes(seq, index_dict, other_code, null_code, clean_cb, out_i64):
// the one-hot code_of loop (encoding.py pivot_block_single) at C speed.
// Memoizes per distinct (type, value); clean_cb (a Python callable) runs
// only on memo misses, so cardinality bounds the interpreter work.
PyObject* pivot_codes(PyObject*, PyObject* args) {
  PyObject *seq, *index, *clean_cb, *out;
  long long other_code, null_code;
  if (!PyArg_ParseTuple(args, "OOLLOO", &seq, &index, &other_code, &null_code,
                        &clean_cb, &out)) {
    return nullptr;
  }
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  BufView ob(out, sizeof(int64_t));
  if (!ob.ok) return nullptr;
  if (ob.n() < fs.n) {
    PyErr_SetString(PyExc_ValueError, "codes buffer too small");
    return nullptr;
  }
  auto* codes = static_cast<int64_t*>(ob.data());
  PyObject* memo = PyDict_New();
  if (!memo) return nullptr;

  // resolve(v_str_obj) -> code: clean via callback, then index lookup.
  auto resolve = [&](PyObject* sobj, int64_t* out_code) -> bool {
    PyObject* cleaned = PyObject_CallFunctionObjArgs(clean_cb, sobj, nullptr);
    if (!cleaned) return false;
    PyObject* hit = PyDict_GetItemWithError(index, cleaned);
    Py_DECREF(cleaned);
    if (hit) {
      *out_code = PyLong_AsLongLong(hit);
      return true;
    }
    if (PyErr_Occurred()) return false;
    *out_code = other_code;
    return true;
  };

  for (Py_ssize_t i = 0; i < fs.n; i++) {
    PyObject* v = fs.items[i];
    if (v == Py_None) {
      codes[i] = null_code;
      continue;
    }
    int is_str = PyUnicode_Check(v);
    if (!is_str && PyFloat_Check(v)) {
      double d = PyFloat_AS_DOUBLE(v);
      if (d != d) {  // NaN: resolve directly, never memoize (nan != nan
                     // would grow the memo one entry per row)
        PyObject* s = PyObject_Str(v);
        if (!s) goto fail;
        int64_t c;
        bool okr = resolve(s, &c);
        Py_DECREF(s);
        if (!okr) goto fail;
        codes[i] = c;
        continue;
      }
    }
    {
      // memo key carries the type: 1, 1.0, True are ==/same-hash but
      // stringify differently (str fast path keys on the value itself —
      // a str never equals a non-str)
      PyObject* mk;
      if (is_str) {
        mk = v;
        Py_INCREF(mk);
      } else {
        mk = PyTuple_Pack(2, (PyObject*)Py_TYPE(v), v);
        if (!mk) goto fail;
      }
      PyObject* hit = PyDict_GetItemWithError(memo, mk);
      if (hit) {
        codes[i] = PyLong_AsLongLong(hit);
        Py_DECREF(mk);
        continue;
      }
      if (PyErr_Occurred()) {
        PyErr_Clear();  // unhashable oddball: stringify, no memo
        Py_DECREF(mk);
        PyObject* s = PyObject_Str(v);
        if (!s) goto fail;
        int64_t c;
        bool okr = resolve(s, &c);
        Py_DECREF(s);
        if (!okr) goto fail;
        codes[i] = c;
        continue;
      }
      PyObject* s = is_str ? v : PyObject_Str(v);
      if (!s) {
        Py_DECREF(mk);
        goto fail;
      }
      int64_t c;
      bool okr = resolve(s, &c);
      if (!is_str) Py_DECREF(s);
      if (!okr) {
        Py_DECREF(mk);
        goto fail;
      }
      PyObject* cobj = PyLong_FromLongLong(c);
      if (!cobj || PyDict_SetItem(memo, mk, cobj) != 0) {
        Py_XDECREF(cobj);
        Py_DECREF(mk);
        goto fail;
      }
      Py_DECREF(cobj);
      Py_DECREF(mk);
      codes[i] = c;
    }
  }
  Py_DECREF(memo);
  Py_RETURN_NONE;
fail:
  Py_DECREF(memo);
  return nullptr;
}

// extract_key_columns(seq_of_dicts, keys_tuple, clean_cb_or_None) ->
// {key: [values]}: explode map rows into per-key lists in one C pass.
// With clean_cb, raw keys memoize to their target column (first-wins on
// cleaned collisions, matching the Python fallback).
PyObject* extract_key_columns(PyObject*, PyObject* args) {
  PyObject *seq, *keys, *clean_cb;
  if (!PyArg_ParseTuple(args, "OOO", &seq, &keys, &clean_cb)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  FastSeq ks(keys);
  if (!ks.fast) return nullptr;
  PyObject* result = PyDict_New();
  if (!result) return nullptr;
  std::vector<PyObject*> cols(ks.n);  // borrowed (result owns)
  for (Py_ssize_t j = 0; j < ks.n; j++) {
    // duplicate keys would make the later PyDict_SetItem replace (and
    // free) an earlier column while cols[] still holds its borrowed
    // pointer — enforce the no-duplicate invariant here instead of
    // assuming the caller upheld it
    int dup = PyDict_Contains(result, ks.items[j]);
    if (dup < 0) goto fail;
    if (dup) {
      PyErr_Format(PyExc_ValueError,
                   "extract_key_columns: duplicate key %R", ks.items[j]);
      goto fail;
    }
    PyObject* lst = PyList_New(fs.n);
    if (!lst) goto fail;
    for (Py_ssize_t i = 0; i < fs.n; i++) {
      Py_INCREF(Py_None);
      PyList_SET_ITEM(lst, i, Py_None);
    }
    if (PyDict_SetItem(result, ks.items[j], lst) != 0) {
      Py_DECREF(lst);
      goto fail;
    }
    Py_DECREF(lst);
    cols[j] = PyDict_GetItem(result, ks.items[j]);
  }
  {
    bool clean = clean_cb != Py_None;
    // raw key -> target list (borrowed) or Py_None when unmatched
    PyObject* key_memo = clean ? PyDict_New() : nullptr;
    PyObject* index = PyDict_New();  // key/cleaned-key -> col position
    if ((clean && !key_memo) || !index) {
      Py_XDECREF(key_memo);
      Py_XDECREF(index);
      goto fail;
    }
    for (Py_ssize_t j = 0; j < ks.n; j++) {
      PyObject* pos = PyLong_FromSsize_t(j);
      if (!pos || PyDict_SetItem(index, ks.items[j], pos) != 0) {
        Py_XDECREF(pos);
        Py_XDECREF(key_memo);
        Py_DECREF(index);
        goto fail;
      }
      Py_DECREF(pos);
    }
    for (Py_ssize_t i = 0; i < fs.n; i++) {
      PyObject* m = fs.items[i];
      if (m == Py_None || !PyDict_Check(m) || PyDict_GET_SIZE(m) == 0) {
        continue;
      }
      PyObject *k, *v;
      Py_ssize_t pos = 0;
      while (PyDict_Next(m, &pos, &k, &v)) {
        PyObject* target;
        if (!clean) {
          target = PyDict_GetItemWithError(index, k);
          if (!target && PyErr_Occurred()) {
            Py_DECREF(index);
            goto fail;
          }
        } else {
          target = PyDict_GetItemWithError(key_memo, k);
          if (!target) {
            if (PyErr_Occurred()) {
              Py_DECREF(key_memo);
              Py_DECREF(index);
              goto fail;
            }
            PyObject* ks_ = PyObject_Str(k);
            PyObject* cleaned =
                ks_ ? PyObject_CallFunctionObjArgs(clean_cb, ks_, nullptr)
                    : nullptr;
            Py_XDECREF(ks_);
            if (!cleaned) {
              Py_DECREF(key_memo);
              Py_DECREF(index);
              goto fail;
            }
            PyObject* hit = PyDict_GetItemWithError(index, cleaned);
            Py_DECREF(cleaned);
            if (!hit && PyErr_Occurred()) {
              Py_DECREF(key_memo);
              Py_DECREF(index);
              goto fail;
            }
            target = hit ? hit : Py_None;
            if (PyDict_SetItem(key_memo, k, target) != 0) {
              Py_DECREF(key_memo);
              Py_DECREF(index);
              goto fail;
            }
          }
        }
        if (target && target != Py_None) {
          Py_ssize_t j = PyLong_AsSsize_t(target);
          // first-wins on cleaned collisions
          if (!clean || PyList_GET_ITEM(cols[j], i) == Py_None) {
            Py_INCREF(v);
            PyObject* old = PyList_GET_ITEM(cols[j], i);
            PyList_SET_ITEM(cols[j], i, v);
            Py_DECREF(old);
          }
        }
      }
    }
    Py_XDECREF(key_memo);
    Py_DECREF(index);
  }
  return result;
fail:
  Py_DECREF(result);
  return nullptr;
}

// float_column(seq, fill, out_f64): None -> fill, numbers coerced.
PyObject* float_column(PyObject*, PyObject* args) {
  PyObject *seq, *out;
  double fill;
  if (!PyArg_ParseTuple(args, "OdO", &seq, &fill, &out)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  BufView ob(out, sizeof(double));
  if (!ob.ok) return nullptr;
  if (ob.n() < fs.n) {
    PyErr_SetString(PyExc_ValueError, "output buffer too small");
    return nullptr;
  }
  auto* o = static_cast<double*>(ob.data());
  for (Py_ssize_t i = 0; i < fs.n; i++) {
    PyObject* v = fs.items[i];
    if (v == Py_None) {
      o[i] = fill;
    } else if (PyFloat_Check(v)) {
      o[i] = PyFloat_AS_DOUBLE(v);
    } else {
      // float(v) semantics incl. numeric strings — PyNumber_Float parses
      // str like the python fallback's float() does
      PyObject* f = PyNumber_Float(v);
      if (!f) return nullptr;
      o[i] = PyFloat_AS_DOUBLE(f);
      Py_DECREF(f);
    }
  }
  Py_RETURN_NONE;
}

// null_mask(seq, out_u8): 1 where None. empty_mask: 1 where falsy.
PyObject* null_mask(PyObject*, PyObject* args) {
  PyObject *seq, *out;
  if (!PyArg_ParseTuple(args, "OO", &seq, &out)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  BufView ob(out, 1);
  if (!ob.ok) return nullptr;
  if (ob.n() < fs.n) {
    PyErr_SetString(PyExc_ValueError, "output buffer too small");
    return nullptr;
  }
  auto* o = static_cast<uint8_t*>(ob.data());
  for (Py_ssize_t i = 0; i < fs.n; i++) o[i] = fs.items[i] == Py_None;
  Py_RETURN_NONE;
}

PyObject* empty_mask(PyObject*, PyObject* args) {
  PyObject *seq, *out;
  if (!PyArg_ParseTuple(args, "OO", &seq, &out)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  BufView ob(out, 1);
  if (!ob.ok) return nullptr;
  if (ob.n() < fs.n) {
    PyErr_SetString(PyExc_ValueError, "output buffer too small");
    return nullptr;
  }
  auto* o = static_cast<uint8_t*>(ob.data());
  for (Py_ssize_t i = 0; i < fs.n; i++) {
    int t = PyObject_IsTrue(fs.items[i]);
    if (t < 0) return nullptr;
    o[i] = t == 0;
  }
  Py_RETURN_NONE;
}

// all_ascii(seq) -> bool: every item None or an ascii-only str (the text
// kernel's eligibility gate, previously a 200k-call genexpr).
PyObject* all_ascii(PyObject*, PyObject* args) {
  PyObject* seq;
  if (!PyArg_ParseTuple(args, "O", &seq)) return nullptr;
  FastSeq fs(seq);
  if (!fs.fast) return nullptr;
  for (Py_ssize_t i = 0; i < fs.n; i++) {
    PyObject* v = fs.items[i];
    if (v == Py_None) continue;
    if (!PyUnicode_Check(v) || !PyUnicode_IS_ASCII(v)) Py_RETURN_FALSE;
  }
  Py_RETURN_TRUE;
}

PyMethodDef methods[] = {
    {"all_ascii", all_ascii, METH_VARARGS,
     "all_ascii(seq) -> bool (None or ascii str everywhere)"},
    {"pack_strings", pack_strings, METH_VARARGS,
     "pack_strings(seq) -> (utf8_bytes, offsets_i64_bytes)"},
    {"dict_encode", dict_encode, METH_VARARGS,
     "dict_encode(seq, codes_out_i64) -> (n_unique, uniques)"},
    {"pivot_codes", pivot_codes, METH_VARARGS,
     "pivot_codes(seq, index, other, null_code, clean_cb, out_i64)"},
    {"extract_key_columns", extract_key_columns, METH_VARARGS,
     "extract_key_columns(rows, keys, clean_cb_or_None) -> {key: list}"},
    {"float_column", float_column, METH_VARARGS,
     "float_column(seq, fill, out_f64)"},
    {"null_mask", null_mask, METH_VARARGS, "null_mask(seq, out_u8)"},
    {"empty_mask", empty_mask, METH_VARARGS, "empty_mask(seq, out_u8)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                         "_tmog_pyext",
                         "C-speed per-object host transform loops",
                         -1,
                         methods,
                         nullptr,
                         nullptr,
                         nullptr,
                         nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__tmog_pyext(void) { return PyModule_Create(&moduledef); }
