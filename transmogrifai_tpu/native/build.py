"""On-demand build of the native host-kernel library.

Compiles native/*.cpp (hashing.cpp text/CSV kernels + trees.cpp
occupancy-aware tree builder) into _tmog_native.so next to this file with
the baked-in g++ toolchain; rebuilt when any source is newer than the
binary. Everything degrades gracefully — when no compiler is available the
callers fall back to the NumPy/XLA paths (see ops/native_bridge.py,
ops/trees_host.py).
"""
from __future__ import annotations

import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCES = [os.path.join(_DIR, "hashing.cpp"), os.path.join(_DIR, "trees.cpp")]
LIB = os.path.join(_DIR, "_tmog_native.so")


def build(force: bool = False) -> Optional[str]:
    """Build (if needed) and return the library path, or None on failure."""
    srcs = [s for s in SOURCES if os.path.exists(s)]
    if not srcs:
        return None
    if (not force and os.path.exists(LIB)
            and all(os.path.getmtime(LIB) >= os.path.getmtime(s)
                    for s in srcs)):
        return LIB
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", LIB] + srcs
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=240)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return LIB
