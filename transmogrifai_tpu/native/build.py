"""On-demand build of the native host-kernel library.

Compiles native/hashing.cpp into _tmog_native.so next to this file with the
baked-in g++ toolchain; rebuilt when the source is newer than the binary.
Everything degrades gracefully — when no compiler is available the callers
fall back to the NumPy paths (see ops/native_bridge.py).
"""
from __future__ import annotations

import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "hashing.cpp")
LIB = os.path.join(_DIR, "_tmog_native.so")


def build(force: bool = False) -> Optional[str]:
    """Build (if needed) and return the library path, or None on failure."""
    if not os.path.exists(SRC):
        return None
    if (not force and os.path.exists(LIB)
            and os.path.getmtime(LIB) >= os.path.getmtime(SRC)):
        return LIB
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", LIB, SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return LIB
