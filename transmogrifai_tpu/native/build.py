"""On-demand build of the native host-kernel library.

Compiles native/*.cpp (hashing.cpp text/CSV kernels + trees.cpp
occupancy-aware tree builder) into _tmog_native.so next to this file with
the baked-in g++ toolchain; rebuilt when any source is newer than the
binary. Everything degrades gracefully — when no compiler is available the
callers fall back to the NumPy/XLA paths (see ops/native_bridge.py,
ops/trees_host.py).
"""
from __future__ import annotations

import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCES = [os.path.join(_DIR, "hashing.cpp"), os.path.join(_DIR, "trees.cpp")]
LIB = os.path.join(_DIR, "_tmog_native.so")
PYEXT_SRC = os.path.join(_DIR, "pyext.cpp")
PYEXT_LIB = os.path.join(_DIR, "_tmog_pyext.so")


def _compile(cmd) -> bool:
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=240)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0


def build(force: bool = False) -> Optional[str]:
    """Build (if needed) and return the library path, or None on failure."""
    srcs = [s for s in SOURCES if os.path.exists(s)]
    if not srcs:
        return None
    if (not force and os.path.exists(LIB)
            and all(os.path.getmtime(LIB) >= os.path.getmtime(s)
                    for s in srcs)):
        return LIB
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", LIB] + srcs
    if not _compile(cmd):
        return None
    return LIB


def build_pyext(force: bool = False) -> Optional[str]:
    """Build (if needed) the CPython extension module; path or None.

    A real extension module (not ctypes): the per-PyObject loops need the
    CPython API. Linked without libpython like any wheel .so — symbols
    resolve from the host interpreter at import.
    """
    if not os.path.exists(PYEXT_SRC):
        return None
    if (not force and os.path.exists(PYEXT_LIB)
            and os.path.getmtime(PYEXT_LIB) >= os.path.getmtime(PYEXT_SRC)):
        return PYEXT_LIB
    import sysconfig
    inc = sysconfig.get_paths().get("include")
    if not inc:
        return None
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-I", inc,
           "-o", PYEXT_LIB, PYEXT_SRC]
    if not _compile(cmd):
        return None
    return PYEXT_LIB
