"""Spark-free row-level scoring: ``Map[String,Any] -> Map[String,Any]``.

Reference: local/.../OpWorkflowModelLocal.scala:93,141,154 — the fitted
workflow replayed per input map, OP stages applied via ``transformKeyValue``
and Spark-wrapped stages through MLeap. Here every fitted stage already
scores host-side through the same ``transform_keyvalue`` protocol (tree
ensembles traverse raw-value thresholds in numpy; GLMs are a dot product),
so no second model format is needed — one artifact serves both the batch
XLA path and this dependency-light local path.

Serving error contract (docs/serving.md): a bad record must fail with a
TYPED error naming the offending key BEFORE it reaches a stage — the
serving frontend maps :class:`UnknownFeatureError` /
:class:`MissingFeatureError` / :class:`InvalidFeatureError` to HTTP 400
(client error), where an opaque ``KeyError``/``TypeError`` escaping a
stage deep in the DAG would surface as a 500.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel

ScoreFunction = Callable[[Dict[str, Any]], Dict[str, Any]]


class UnknownFeatureError(ValueError):
    """Record carries a key that matches no raw feature of the workflow."""

    def __init__(self, key: str, known=()):
        self.key = key
        hint = f" (known features: {sorted(known)})" if known else ""
        super().__init__(f"unknown record key {key!r}{hint}")


class MissingFeatureError(KeyError):
    """A raw feature's extract function needs a key the record lacks.

    Subclasses KeyError so pre-existing callers catching the opaque
    original keep working — but the message now NAMES the feature."""

    def __init__(self, feature: str, key: Any = None):
        self.feature = feature
        self.key = key
        detail = f" (record key {key!r})" if key is not None else ""
        super().__init__(f"record is missing data for raw feature "
                         f"{feature!r}{detail}")

    def __str__(self) -> str:  # KeyError.__str__ repr()s the arg
        return self.args[0]


class InvalidFeatureError(ValueError):
    """A record value failed its feature type's coercion."""

    def __init__(self, feature: str, value: Any, cause: Exception):
        self.feature = feature
        self.value = value
        super().__init__(f"invalid value for raw feature {feature!r}: "
                         f"{value!r} ({type(cause).__name__}: {cause})")


def _extract(gen, record: Dict[str, Any]):
    """One generator's extract with the typed-error boundary applied."""
    try:
        return gen.extract(record)
    except KeyError as e:
        raise MissingFeatureError(gen.feature_name,
                                  key=e.args[0] if e.args else None) from e
    except (TypeError, ValueError, AttributeError) as e:
        raise InvalidFeatureError(
            gen.feature_name,
            record.get(gen.feature_name) if isinstance(record, dict)
            else record, e) from e


def record_validator(model: "WorkflowModel", strict_keys: bool = True
                     ) -> Callable[[Dict[str, Any]], None]:
    """Up-front record validation for the serving path.

    Returns validate(record) raising :class:`UnknownFeatureError` for a
    key naming no raw feature (strict_keys=False skips that check —
    batch readers legitimately carry extra columns like row ids),
    :class:`MissingFeatureError` / :class:`InvalidFeatureError` when a
    predictor's extract cannot produce a value. Response features are
    exempt: serving records are unlabeled by contract.

    The extraction here runs AGAIN at batch assembly — deliberate: the
    duplicate is a few dict lookups + float coercions (microseconds
    against a millisecond-scale request), and paying it at submit time
    is what lets the batcher reject a bad record BEFORE it joins a batch
    other requests share.
    """
    raw = model.raw_features()
    known = {f.name for f in raw}
    generators = [f.origin_stage for f in raw if not f.is_response]

    def validate(record: Dict[str, Any]) -> None:
        if not isinstance(record, dict):
            raise InvalidFeatureError(
                "<record>", record, TypeError("record must be a dict"))
        if strict_keys:
            for k in record:
                if k not in known:
                    raise UnknownFeatureError(k, known)
        for gen in generators:
            _extract(gen, record)

    return validate


def score_function(model: "WorkflowModel") -> ScoreFunction:
    """Build the per-row scorer for a fitted workflow.

    The returned function takes a raw record dict (same keys the reader's
    extract functions expect), replays raw-feature extraction and every
    fitted stage in DAG order, and returns {result_feature_name: value}.
    Mirrors OpWorkflowModelLocal.scoreFunction (stage replay in DAG order,
    local/.../OpWorkflowModelLocal.scala:93). Extraction failures raise
    the typed errors above (never a bare KeyError from inside a stage);
    key-set strictness is the caller's choice via `record_validator`.
    """
    raw_feats = model.raw_features()
    # responses are not extracted at serving time (records are unlabeled;
    # reference scores without labels) — downstream stages read them as None
    generators = [f.origin_stage for f in raw_feats if not f.is_response]
    response_names = [f.name for f in raw_feats if f.is_response]
    layers = model.dag.layers
    result_names = [f.name for f in model.result_features]

    def score(record: Dict[str, Any]) -> Dict[str, Any]:
        row: Dict[str, Any] = {n: None for n in response_names}
        for gen in generators:
            row[gen.feature_name] = _extract(gen, record)
        for layer in layers:
            for st in layer:
                row[st.output_name()] = st.transform_keyvalue(row)
        return {n: row[n] for n in result_names}

    return score
