"""Spark-free row-level scoring: ``Map[String,Any] -> Map[String,Any]``.

Reference: local/.../OpWorkflowModelLocal.scala:93,141,154 — the fitted
workflow replayed per input map, OP stages applied via ``transformKeyValue``
and Spark-wrapped stages through MLeap. Here every fitted stage already
scores host-side through the same ``transform_keyvalue`` protocol (tree
ensembles traverse raw-value thresholds in numpy; GLMs are a dot product),
so no second model format is needed — one artifact serves both the batch
XLA path and this dependency-light local path.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..workflow.workflow import WorkflowModel

ScoreFunction = Callable[[Dict[str, Any]], Dict[str, Any]]


def score_function(model: "WorkflowModel") -> ScoreFunction:
    """Build the per-row scorer for a fitted workflow.

    The returned function takes a raw record dict (same keys the reader's
    extract functions expect), replays raw-feature extraction and every
    fitted stage in DAG order, and returns {result_feature_name: value}.
    Mirrors OpWorkflowModelLocal.scoreFunction (stage replay in DAG order,
    local/.../OpWorkflowModelLocal.scala:93).
    """
    raw_feats = model.raw_features()
    # responses are not extracted at serving time (records are unlabeled;
    # reference scores without labels) — downstream stages read them as None
    generators = [f.origin_stage for f in raw_feats if not f.is_response]
    response_names = [f.name for f in raw_feats if f.is_response]
    layers = model.dag.layers
    result_names = [f.name for f in model.result_features]

    def score(record: Dict[str, Any]) -> Dict[str, Any]:
        row: Dict[str, Any] = {n: None for n in response_names}
        for gen in generators:
            row[gen.feature_name] = gen.extract(record)
        for layer in layers:
            for st in layer:
                row[st.output_name()] = st.transform_keyvalue(row)
        return {n: row[n] for n in result_names}

    return score
