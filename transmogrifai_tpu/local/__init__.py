"""Spark-free local serving (reference local/ module, 402 LoC): one fitted
workflow artifact scores as a plain ``dict -> dict`` function with no
cluster runtime — see `scoring.score_function`."""
from .scoring import ScoreFunction, score_function

__all__ = ["ScoreFunction", "score_function"]
