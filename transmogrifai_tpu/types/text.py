"""Text feature types.

Reference: features/.../types/Text.scala:48-298 — Text plus 13 refined
subtypes. The subtypes matter because the Transmogrifier dispatches default
vectorization per static type (PickList -> one-hot pivot, Text -> smart
vectorize, Email -> domain pivot, etc).
"""
from __future__ import annotations

from typing import Any, Optional

from .base import Categorical, ColumnKind, FeatureType


class Text(FeatureType):
    """Optional string (reference Text.scala:48)."""

    column_kind = ColumnKind.STRING

    @classmethod
    def _convert(cls, value: Any) -> Optional[str]:
        if value is None:
            return None
        if isinstance(value, Text):
            return value.value
        if isinstance(value, float):
            import math
            if math.isnan(value):
                return None
        s = str(value)
        return s if s != "" else None


class Email(Text):
    """Reference Text.scala:65. `prefix`/`domain` helpers mirror
    RichTextFeature's email ops."""

    def prefix(self) -> Optional[str]:
        p = self._split()
        return p[0] if p else None

    def domain(self) -> Optional[str]:
        p = self._split()
        return p[1] if p else None

    def _split(self):
        v = self.value
        if v is None or v.count("@") != 1:
            return None
        pre, dom = v.split("@")
        if not pre or not dom:
            return None
        return pre, dom


class Base64(Text):
    """Reference Text.scala:101."""

    def as_bytes(self) -> Optional[bytes]:
        if self.value is None:
            return None
        import base64
        try:
            return base64.b64decode(self.value)
        except Exception:
            return None


class Phone(Text):
    """Reference Text.scala:139."""


class ID(Text):
    """Reference Text.scala:153."""


class URL(Text):
    """Reference Text.scala:167."""

    def domain(self) -> Optional[str]:
        v = self.value
        if v is None:
            return None
        from urllib.parse import urlparse
        try:
            # hostname strips userinfo and port (java.net.URL.getHost
            # semantics, which the reference's RichURLFeature relies on)
            return urlparse(v).hostname or None
        except Exception:
            return None

    def protocol(self) -> Optional[str]:
        v = self.value
        if v is None:
            return None
        from urllib.parse import urlparse
        try:
            scheme = urlparse(v).scheme
            return scheme or None
        except Exception:
            return None

    def is_valid(self, protocols=("http", "https", "ftp")) -> bool:
        v = self.value
        if v is None:
            return False
        from urllib.parse import urlparse
        try:
            p = urlparse(v)
            return p.scheme in tuple(s.lower() for s in protocols) \
                and bool(p.netloc)
        except Exception:
            return False


class TextArea(Text):
    """Long-form text (reference Text.scala:201)."""


class PickList(Text, Categorical):
    """Categorical single-select (reference Text.scala:215)."""


class ComboBox(Text, Categorical):
    """Categorical with free entry (reference Text.scala:228)."""


class Country(Text, Categorical):
    """Reference Text.scala:242."""


class State(Text, Categorical):
    """Reference Text.scala:256."""


class PostalCode(Text, Categorical):
    """Reference Text.scala:270."""


class City(Text, Categorical):
    """Reference Text.scala:284."""


class Street(Text):
    """Reference Text.scala:298."""
