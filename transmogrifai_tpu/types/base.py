"""FeatureType hierarchy root.

TPU-native rebuild of the reference's typed feature-value system
(reference: features/src/main/scala/com/salesforce/op/features/types/FeatureType.scala:44).

Design departure from the reference: in the Scala/Spark original a FeatureType
instance wraps ONE row's value and transformers run row-by-row over RDDs. Here
feature *values* are lightweight wrappers used only at the API boundary
(row-level extraction, local scoring, testkit); the compute path is columnar —
each FeatureType class additionally declares its columnar storage spec
(`ColumnSpec`) so whole columns lower to dense arrays in HBM and transforms
compile to XLA programs over them.
"""
from __future__ import annotations

import math
from typing import Any, ClassVar, Dict, Optional, Tuple, Type


class ColumnKind:
    """How a column of this feature type is stored host-side / on device."""

    FLOAT = "float"          # numpy float64 with NaN for missing -> f32 on device
    INT = "int"              # numpy float64 (NaN-able) or int64; lowered to f32/i32
    BOOL = "bool"            # float64 with NaN for missing (0/1)
    STRING = "string"        # host-only object array (tokenized/hashed before device)
    STRING_LIST = "string_list"
    FLOAT_LIST = "float_list"  # ragged host-side; fixed-width on device after vectorize
    STRING_SET = "string_set"
    MAP = "map"              # host-side dict per row; expanded per-key by vectorizers
    VECTOR = "vector"        # fixed-width dense f32 row -> the device feature matrix
    GEO = "geo"              # (lat, lon, accuracy) triple


class FeatureTypeMeta(type):
    """Metaclass keeping a registry of all feature types by name
    (mirrors FeatureType.typeName / isSubtype, reference FeatureType.scala:155,176)."""

    _registry: ClassVar[Dict[str, Type["FeatureType"]]] = {}

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        FeatureTypeMeta._registry[name] = cls
        return cls


class FeatureType(metaclass=FeatureTypeMeta):
    """Root of the typed feature value hierarchy.

    Subclasses wrap a single (possibly empty) value. Emptiness is the
    nullability protocol: ``None`` value <=> empty (reference
    FeatureType.scala:62 ``isEmpty``).
    """

    __slots__ = ("_value",)

    # columnar storage spec, overridden per concrete type
    column_kind: ClassVar[str] = ColumnKind.FLOAT
    # True if the type never admits an empty value (RealNN etc.)
    is_non_nullable: ClassVar[bool] = False

    def __init__(self, value: Any = None):
        self._value = self._convert(value)
        if self.is_non_nullable and self._value is None:
            raise ValueError(
                f"{type(self).__name__} cannot be empty (NonNullable)")

    # -- value protocol ----------------------------------------------------
    @property
    def value(self) -> Any:
        return self._value

    @property
    def is_empty(self) -> bool:
        return self._value is None

    @property
    def non_empty(self) -> bool:
        return self._value is not None

    @classmethod
    def _convert(cls, value: Any) -> Any:
        """Coerce a raw python value into canonical stored form; None = empty."""
        return value

    @classmethod
    def empty(cls) -> "FeatureType":
        return cls(None)

    @classmethod
    def type_name(cls) -> str:
        return cls.__name__

    @classmethod
    def is_subtype_of(cls, other: Type["FeatureType"]) -> bool:
        return issubclass(cls, other)

    @classmethod
    def from_name(cls, name: str) -> Type["FeatureType"]:
        try:
            return FeatureTypeMeta._registry[name]
        except KeyError:
            raise ValueError(f"Unknown feature type name: {name}") from None

    @classmethod
    def all_types(cls) -> Dict[str, Type["FeatureType"]]:
        return dict(FeatureTypeMeta._registry)

    # -- equality / hashing / repr ----------------------------------------
    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self._eq_value(other._value)

    def _eq_value(self, other_value: Any) -> bool:
        v = self._value
        if isinstance(v, float) and isinstance(other_value, float):
            if math.isnan(v) and math.isnan(other_value):
                return True
        return v == other_value

    def __hash__(self) -> int:
        v = self._value
        if isinstance(v, (dict, list, set)):
            return hash((type(self).__name__, repr(sorted(str(x) for x in v))))
        return hash((type(self).__name__, v))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._value!r})"

    def __bool__(self) -> bool:
        return self.non_empty


# -- marker traits (reference FeatureType.scala:122-150) -------------------
class NonNullable(FeatureType):
    """Types that may never be empty."""
    is_non_nullable = True


class Categorical(FeatureType):
    """Marker: categorical-valued (drives contingency-table stats)."""


class Location(FeatureType):
    """Marker: location-valued (geo handling)."""


class SingleResponse(NonNullable):
    """Marker: usable as single-response label."""


class MultiResponse(FeatureType):
    """Marker: usable as multi-response label."""
