"""Map (keyed) feature types + the Prediction type.

Reference: features/.../types/Maps.scala:40-357. Map features carry a dynamic
set of keys per row; vectorizers expand them per-key into fixed columns during
fit (two-phase: key discovery -> static-shape transform).

Prediction (Maps.scala:302) is the reserved-key output type of every model:
key "prediction" plus optional "rawPrediction_*" and "probability_*" keys.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Set

import numpy as np

from .base import ColumnKind, FeatureType, Location, MultiResponse, NonNullable, SingleResponse
from .collections import Geolocation


class OPMap(FeatureType):
    """Base of map-valued types: empty map <=> empty value."""

    column_kind = ColumnKind.MAP

    @classmethod
    def _convert(cls, value: Any) -> Dict:
        if value is None:
            return {}
        if isinstance(value, OPMap):
            return dict(value.value)
        return dict(value)

    @property
    def value(self) -> Dict:
        return self._value

    @property
    def is_empty(self) -> bool:
        return len(self._value) == 0

    @property
    def non_empty(self) -> bool:
        return len(self._value) > 0

    def __len__(self) -> int:
        return len(self._value)

    def __contains__(self, key: str) -> bool:
        return key in self._value

    def __getitem__(self, key: str):
        return self._value[key]

    def get(self, key: str, default=None):
        return self._value.get(key, default)

    def keys(self):
        return self._value.keys()

    def __hash__(self) -> int:
        return hash((type(self).__name__, tuple(sorted(self._value.items(),
                                                       key=lambda kv: kv[0]))))


# -- text maps (Maps.scala:40-135) -----------------------------------------
class TextMap(OPMap):
    @classmethod
    def _convert(cls, value: Any) -> Dict[str, str]:
        d = super()._convert(value)
        return {str(k): str(v) for k, v in d.items() if v is not None}


class EmailMap(TextMap): pass
class Base64Map(TextMap): pass
class PhoneMap(TextMap): pass
class IDMap(TextMap): pass
class URLMap(TextMap): pass
class TextAreaMap(TextMap): pass
class PickListMap(TextMap, SingleResponse):
    is_non_nullable = False
class ComboBoxMap(TextMap): pass
class CountryMap(TextMap, Location): pass
class StateMap(TextMap, Location): pass
class CityMap(TextMap, Location): pass
class PostalCodeMap(TextMap, Location): pass
class StreetMap(TextMap, Location): pass


# -- numeric maps (Maps.scala:139-211) -------------------------------------
class NumericMap(OPMap):
    def to_double_map(self) -> Dict[str, float]:
        return {k: float(v) for k, v in self._value.items()}


class BinaryMap(NumericMap, SingleResponse):
    is_non_nullable = False

    @classmethod
    def _convert(cls, value: Any) -> Dict[str, bool]:
        d = OPMap._convert(value)
        return {str(k): bool(v) for k, v in d.items() if v is not None}

    def to_double_map(self) -> Dict[str, float]:
        return {k: (1.0 if v else 0.0) for k, v in self._value.items()}


class IntegralMap(NumericMap):
    @classmethod
    def _convert(cls, value: Any) -> Dict[str, int]:
        d = OPMap._convert(value)
        return {str(k): int(v) for k, v in d.items() if v is not None}


class RealMap(NumericMap):
    @classmethod
    def _convert(cls, value: Any) -> Dict[str, float]:
        d = OPMap._convert(value)
        out = {}
        for k, v in d.items():
            if v is None:
                continue
            f = float(v)
            if not math.isnan(f):
                out[str(k)] = f
        return out


class PercentMap(RealMap): pass
class CurrencyMap(RealMap): pass
class DateMap(IntegralMap): pass
class DateTimeMap(DateMap): pass


class MultiPickListMap(OPMap, MultiResponse):
    @classmethod
    def _convert(cls, value: Any) -> Dict[str, Set[str]]:
        d = OPMap._convert(value)
        return {str(k): {str(x) for x in v} for k, v in d.items() if v is not None}


class GeolocationMap(OPMap, Location):
    @classmethod
    def _convert(cls, value: Any) -> Dict[str, List[float]]:
        d = OPMap._convert(value)
        return {str(k): list(Geolocation(v).value) for k, v in d.items() if v is not None}


# -- Prediction (Maps.scala:302-357) ---------------------------------------
class Prediction(RealMap, NonNullable):
    """Reserved-key model output: 'prediction' (required),
    'rawPrediction_{i}', 'probability_{i}'."""

    is_non_nullable = True

    PREDICTION_NAME = "prediction"
    RAW_PREDICTION_NAME = "rawPrediction"
    PROBABILITY_NAME = "probability"

    def __init__(self, value: Any = None, *, prediction: Optional[float] = None,
                 raw_prediction: Optional[Sequence[float]] = None,
                 probability: Optional[Sequence[float]] = None):
        if value is None and prediction is not None:
            value = {self.PREDICTION_NAME: float(prediction)}
            for i, r in enumerate(raw_prediction if raw_prediction is not None else []):
                value[f"{self.RAW_PREDICTION_NAME}_{i}"] = float(r)
            for i, p in enumerate(probability if probability is not None else []):
                value[f"{self.PROBABILITY_NAME}_{i}"] = float(p)
        super().__init__(value)
        if self.PREDICTION_NAME not in self._value:
            raise ValueError(
                f"Prediction map must contain '{self.PREDICTION_NAME}' key, "
                f"got keys {sorted(self._value)}")

    @property
    def prediction(self) -> float:
        return self._value[self.PREDICTION_NAME]

    def _keys_starting_with(self, prefix: str) -> List[str]:
        ks = [k for k in self._value if k.startswith(prefix + "_")]
        return sorted(ks, key=lambda k: int(k.rsplit("_", 1)[1]))

    @property
    def raw_prediction(self) -> List[float]:
        return [self._value[k] for k in self._keys_starting_with(self.RAW_PREDICTION_NAME)]

    @property
    def probability(self) -> List[float]:
        return [self._value[k] for k in self._keys_starting_with(self.PROBABILITY_NAME)]

    @property
    def score(self) -> List[float]:
        """Probability vector if present else [prediction]
        (reference Maps.scala:346)."""
        prob = self.probability
        return prob if prob else [self.prediction]
