"""Collection feature types: lists, sets, geolocation, and OPVector.

Reference: features/.../types/{Lists.scala:38-64, Sets.scala:38,
Geolocation.scala:47, OPVector.scala:41}.

OPVector is the central type of the compute path: a fixed-width dense float
row. In the reference it wraps a Spark ml Vector; here it wraps a numpy
array — whole OPVector columns ARE the HBM feature matrix.
"""
from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Set, Tuple

import numpy as np

from .base import ColumnKind, FeatureType, Location, MultiResponse


class OPCollection(FeatureType):
    """Base for collection types: empty collection <=> empty value."""

    @property
    def is_empty(self) -> bool:
        return self._value is None or len(self._value) == 0

    @property
    def non_empty(self) -> bool:
        return not self.is_empty


class OPList(OPCollection):
    """Base of list-valued types (reference Lists.scala:38)."""

    @classmethod
    def _convert(cls, value: Any):
        if value is None:
            return []
        if isinstance(value, OPList):
            return list(value.value)
        return list(value)

    @property
    def value(self) -> List:
        return self._value

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


class TextList(OPList):
    """Reference Lists.scala:51."""
    column_kind = ColumnKind.STRING_LIST

    @classmethod
    def _convert(cls, value: Any):
        v = super()._convert(value)
        return [str(x) for x in v]


class DateList(OPList):
    """Epoch-millis list (reference Lists.scala:64)."""
    column_kind = ColumnKind.FLOAT_LIST

    @classmethod
    def _convert(cls, value: Any):
        v = super()._convert(value)
        return [int(x) for x in v]


class DateTimeList(DateList):
    """Reference Lists.scala:77."""


class OPSet(OPCollection):
    """Base of set-valued types (reference Sets.scala:38)."""

    @classmethod
    def _convert(cls, value: Any):
        if value is None:
            return set()
        if isinstance(value, OPSet):
            return set(value.value)
        return set(value)

    @property
    def value(self) -> Set:
        return self._value

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


class MultiPickList(OPSet, MultiResponse):
    """Categorical multi-select (reference Sets.scala:38)."""
    column_kind = ColumnKind.STRING_SET

    @classmethod
    def _convert(cls, value: Any):
        v = super()._convert(value)
        return {str(x) for x in v}


class Geolocation(OPList, Location):
    """(lat, lon, accuracy) triple (reference Geolocation.scala:47)."""

    column_kind = ColumnKind.GEO

    @classmethod
    def _convert(cls, value: Any):
        if value is None:
            return []
        if isinstance(value, Geolocation):
            return list(value.value)
        v = [float(x) for x in value]
        if len(v) == 0:
            return []
        if len(v) != 3:
            raise ValueError(
                f"Geolocation must have lat, lon, accuracy; got {len(v)} values")
        lat, lon, acc = v
        if not (-90.0 <= lat <= 90.0):
            raise ValueError(f"Latitude out of range: {lat}")
        if not (-180.0 <= lon <= 180.0):
            raise ValueError(f"Longitude out of range: {lon}")
        return [lat, lon, acc]

    @property
    def lat(self) -> Optional[float]:
        return self._value[0] if self.non_empty else None

    @property
    def lon(self) -> Optional[float]:
        return self._value[1] if self.non_empty else None

    @property
    def accuracy(self) -> Optional[float]:
        return self._value[2] if self.non_empty else None

    def to_unit_sphere(self) -> Optional[Tuple[float, float, float]]:
        """3-D unit-sphere embedding used by geo vectorizers so that mean
        imputation stays on the globe."""
        if self.is_empty:
            return None
        lat, lon = math.radians(self._value[0]), math.radians(self._value[1])
        return (math.cos(lat) * math.cos(lon),
                math.cos(lat) * math.sin(lon),
                math.sin(lat))


class OPVector(OPCollection):
    """Fixed-width dense float vector — one row of the device feature matrix
    (reference OPVector.scala:41 wrapping Spark ml Vector)."""

    column_kind = ColumnKind.VECTOR

    @classmethod
    def _convert(cls, value: Any) -> np.ndarray:
        if value is None:
            return np.zeros((0,), dtype=np.float32)
        if isinstance(value, OPVector):
            return value.value
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim != 1:
            arr = arr.reshape(-1)
        return arr

    @property
    def is_empty(self) -> bool:
        return self._value.size == 0

    def __len__(self) -> int:
        return int(self._value.size)

    def combine(self, *others: "OPVector") -> "OPVector":
        """Concatenate vectors (reference RichVector.combine)."""
        parts = [self._value] + [o.value for o in others]
        return OPVector(np.concatenate(parts))

    def _eq_value(self, other_value: Any) -> bool:
        return (isinstance(other_value, np.ndarray)
                and self._value.shape == other_value.shape
                and bool(np.allclose(self._value, other_value, equal_nan=True)))

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._value.tobytes()))
