"""Numeric feature types.

Reference: features/.../types/Numerics.scala:40-133 (Real, RealNN, Binary,
Integral, Percent, Currency, Date, DateTime).
"""
from __future__ import annotations

import math
from typing import Any, Optional

from .base import Categorical, ColumnKind, FeatureType, NonNullable, SingleResponse


class OPNumeric(FeatureType):
    """Base for numeric value types."""

    column_kind = ColumnKind.FLOAT

    def to_double(self) -> Optional[float]:
        v = self.value
        if v is None:
            return None
        if isinstance(v, bool):
            return 1.0 if v else 0.0
        return float(v)


class Real(OPNumeric):
    """Optional real value (reference Numerics.scala:40)."""

    @classmethod
    def _convert(cls, value: Any) -> Optional[float]:
        if value is None:
            return None
        if isinstance(value, Real):
            return value.value
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        v = float(value)
        if math.isnan(v):
            return None
        return v


class RealNN(Real, SingleResponse):
    """Non-nullable real — the required label/response type
    (reference Numerics.scala:59)."""
    is_non_nullable = True


class Binary(OPNumeric, SingleResponse):
    """Optional boolean (reference Numerics.scala:73)."""

    column_kind = ColumnKind.BOOL
    is_non_nullable = False

    @classmethod
    def _convert(cls, value: Any) -> Optional[bool]:
        if value is None:
            return None
        if isinstance(value, Binary):
            return value.value
        if isinstance(value, float) and math.isnan(value):
            return None
        return bool(value)

    def to_double(self) -> Optional[float]:
        v = self.value
        return None if v is None else (1.0 if v else 0.0)


class Integral(OPNumeric):
    """Optional integer (reference Numerics.scala:90)."""

    column_kind = ColumnKind.INT

    @classmethod
    def _convert(cls, value: Any) -> Optional[int]:
        if value is None:
            return None
        if isinstance(value, Integral):
            return value.value
        if isinstance(value, float):
            if math.isnan(value):
                return None
            return int(value)
        return int(value)


class Percent(Real):
    """Reference Numerics.scala:105."""


class Currency(Real):
    """Reference Numerics.scala:119."""


class Date(Integral):
    """Epoch-millis date (reference Numerics.scala:133)."""


class DateTime(Date):
    """Epoch-millis datetime (reference Numerics.scala:147)."""
