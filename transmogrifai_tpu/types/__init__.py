"""Typed feature value system (reference features/.../types/).

Exports the full FeatureType hierarchy plus factory/default helpers
(reference FeatureTypeFactory.scala / FeatureTypeDefaults.scala /
package.scala implicit conversions).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Type

from .base import (
    Categorical,
    ColumnKind,
    FeatureType,
    Location,
    MultiResponse,
    NonNullable,
    SingleResponse,
)
from .numerics import (
    Binary,
    Currency,
    Date,
    DateTime,
    Integral,
    OPNumeric,
    Percent,
    Real,
    RealNN,
)
from .text import (
    ID,
    URL,
    Base64,
    City,
    ComboBox,
    Country,
    Email,
    Phone,
    PickList,
    PostalCode,
    State,
    Street,
    Text,
    TextArea,
)
from .collections import (
    DateList,
    DateTimeList,
    Geolocation,
    MultiPickList,
    OPCollection,
    OPList,
    OPSet,
    OPVector,
    TextList,
)
from .maps import (
    Base64Map,
    BinaryMap,
    CityMap,
    ComboBoxMap,
    CountryMap,
    CurrencyMap,
    DateMap,
    DateTimeMap,
    EmailMap,
    GeolocationMap,
    IDMap,
    IntegralMap,
    MultiPickListMap,
    NumericMap,
    OPMap,
    PercentMap,
    PhoneMap,
    PickListMap,
    PostalCodeMap,
    Prediction,
    RealMap,
    StateMap,
    StreetMap,
    TextAreaMap,
    TextMap,
    URLMap,
)


def make(type_cls: Type[FeatureType], value: Any) -> FeatureType:
    """Factory: build a feature value of the given type from a raw value
    (reference FeatureTypeFactory.scala)."""
    if isinstance(value, type_cls):
        return value
    return type_cls(value)


def default_of(type_cls: Type[FeatureType]) -> FeatureType:
    """The default (empty) instance of a type
    (reference FeatureTypeDefaults.scala). NonNullable numerics default to 0."""
    if type_cls.is_non_nullable:
        if issubclass(type_cls, Prediction):
            return Prediction(prediction=0.0)
        if issubclass(type_cls, RealNN):
            return RealNN(0.0)
        return type_cls(0)
    return type_cls.empty()


__all__ = [name for name in dir() if not name.startswith("_")]
