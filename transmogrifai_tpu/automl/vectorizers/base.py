"""Vectorizer base machinery.

Vectorizers are sequence estimators/transformers: N same-typed input features
-> one OPVector output whose columns carry VectorMetadata provenance
(reference: the vectorizer family under core/.../impl/feature/ — each is a
SequenceEstimator producing OPVector with OpVectorMetadata).

Two-phase contract: fit computes a static shape (vocabularies, fill values,
hash widths) as concrete host values; the resulting model's transform is pure
array math, fusable into the layer's XLA program.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ...data.dataset import Column, Dataset
from ...data.vector import (
    NULL_STRING, OTHER_STRING, VectorColumnMetadata, VectorMetadata,
)
from ...stages.base import Estimator, Transformer
from ...types import ColumnKind, FeatureType, OPVector


class VectorizerModel(Transformer):
    """Base fitted vectorizer: emits a dense [n, width] block + metadata."""

    output_type = OPVector
    is_sequence = True

    def __init__(self, operation_name: str, uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)
        self._metadata: Optional[VectorMetadata] = None

    def output_metadata(self) -> Optional[VectorMetadata]:
        if self._metadata is not None and self._metadata.name != self.output_name():
            self._metadata = VectorMetadata(
                name=self.output_name(), columns=self._metadata.columns,
                history=self._metadata.history)
        return self._metadata

    def set_metadata(self, md: VectorMetadata) -> "VectorizerModel":
        self._metadata = md
        return self

    # columnar protocol: subclasses implement transform_block
    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        raise NotImplementedError

    def transform_block_into(self, cols: Sequence[Column],
                             out: np.ndarray) -> None:
        """Write this vectorizer's block into `out` (pre-zeroed, possibly a
        strided column-slice of the final combined matrix). Serving sink
        fusion: the DAG runner hands each producer its slice of the
        VectorsCombiner output so wide blocks never materialize twice
        (the fused row-map's one-pass discipline,
        reference FitStagesUtil.scala:96-118, applied to memory traffic).
        Default: materialize and copy; hot families override to write in
        place."""
        out[:] = np.asarray(self.transform_block(cols), np.float32)

    def transform_columns(self, *cols: Column) -> Column:
        block = self.transform_block(list(cols))
        block = np.asarray(block, dtype=np.float32)
        md = self.output_metadata()
        if md is not None and block.shape[1] != md.size:
            raise AssertionError(
                f"{self.stage_name}: produced {block.shape[1]} cols, "
                f"metadata has {md.size}")
        return Column(kind=ColumnKind.VECTOR, data=block, metadata=md)

    def transform_value(self, *vals: FeatureType):
        cols = [_single_value_column(v) for v in vals]
        block = self.transform_block(cols)
        return OPVector(np.asarray(block, dtype=np.float32)[0])


def _single_value_column(v: FeatureType) -> Column:
    from ...data.dataset import column_from_values
    return column_from_values(type(v), [v])


def numeric_block(cols: Sequence[Column]) -> np.ndarray:
    """Stack numeric columns into [n, k] float64 (NaN = missing)."""
    return np.stack([np.asarray(c.data, dtype=np.float64) for c in cols], axis=1)


class SequenceVectorizer(Estimator):
    """Base estimator for N same-typed inputs -> OPVector."""

    output_type = OPVector
    is_sequence = True

    def feature_names(self) -> List[str]:
        return self.input_names()
