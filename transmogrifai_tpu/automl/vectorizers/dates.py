"""Date/time vectorizers: time-since-reference + circular encodings.

Reference: core/.../impl/feature/{DateToUnitCircleTransformer.scala,
DateListVectorizer.scala:309}. Default circular periods per
TransmogrifierDefaults.CircularDateRepresentations: HourOfDay, DayOfWeek,
DayOfMonth, DayOfYear — each maps to (sin, cos) on the unit circle so
midnight is close to 23:59.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...data.dataset import Column
from ...data.vector import NULL_STRING, VectorColumnMetadata, VectorMetadata
from ...stages.params import Param
from ...types import Date, DateList, Integral
from .base import SequenceVectorizer, VectorizerModel, numeric_block
from .encoding import list_reduce

MS_PER_DAY = 86400000.0

# name -> period length; extractors are derived from _PERIOD_FROM_DT64
# below (single source of truth — the vectorizer's one-pass block writer
# and the dsl DateToUnitCircleTransformer must stay bitwise-identical)
_PERIOD_LENGTHS: Dict[str, float] = {
    "HourOfDay": 24.0,
    "DayOfWeek": 7.0,   # epoch day 0 was a Thursday (+3 offset)
    "DayOfMonth": 31.0,
    "DayOfYear": 366.0,
    "WeekOfYear": 53.0,
    "MonthOfYear": 12.0,
}


def _dt64(ms: np.ndarray):
    """(datetime64[ms] array, finite mask) — calendar math fully in numpy;
    the previous per-row datetime.utcfromtimestamp loop was 1000x slower."""
    finite = np.isfinite(ms)
    safe = np.where(finite, ms, 0.0).astype(np.int64)
    return safe.astype("datetime64[ms]"), finite


def _cal_delta_d(d: np.ndarray, unit: str, anchor: str,
                 cache: Optional[Dict[str, np.ndarray]] = None
                 ) -> np.ndarray:
    """Elapsed `unit`s since the enclosing `anchor` period start, on a
    PRE-COMPUTED dt64 array. `cache` (unit -> d@[unit]) lets the one-pass
    block writer share casts across periods (DayOfMonth/DayOfYear/
    WeekOfYear all need d@[D])."""
    if cache is None:
        du = d.astype(f"M8[{unit}]")
        da = d.astype(f"M8[{anchor}]")
    else:
        du = cache.get(unit)
        if du is None:
            du = cache[unit] = d.astype(f"M8[{unit}]")
        da = cache.get(anchor)
        if da is None:
            da = cache[anchor] = d.astype(f"M8[{anchor}]")
    return (du - da.astype(f"M8[{unit}]")).astype(np.int64).astype(
        np.float64)


# calendar periods as DATA — (unit, anchor, divisor) — so the one-pass
# block writer (shared cast cache) and the standalone extractors read
# the same definition; ms-math periods live in _MS_PERIODS
_CAL_PERIODS = {
    "DayOfMonth": ("D", "M", 1.0),
    "DayOfYear": ("D", "Y", 1.0),
    "WeekOfYear": ("D", "Y", 7.0),
    "MonthOfYear": ("M", "Y", 1.0),
}
_MS_PERIODS = {
    "HourOfDay": lambda ms: (ms / 3600000.0) % 24.0,
    "DayOfWeek": lambda ms: ((ms / MS_PER_DAY) + 3.0) % 7.0,
}

# period -> value from (epoch ms, shared dt64) — derived views of the
# tables above; everything else (PERIODS, unit_circle) derives from this
# (x / 1.0 is bitwise x, so the uniform divide is exact)
_PERIOD_FROM_DT64 = {
    **{name: (lambda ms, d, _f=fn: _f(ms)) for name, fn in
       _MS_PERIODS.items()},
    **{name: (lambda ms, d, _u=u, _a=a, _dv=dv:
              _cal_delta_d(d, _u, _a) / _dv)
       for name, (u, a, dv) in _CAL_PERIODS.items()},
}


def _standalone_extract(name: str):
    """ms-only extractor (derives + masks the dt64 form): NaN where the
    input is NaN, matching the old _calendar_delta behavior."""
    fn = _PERIOD_FROM_DT64[name]

    def extract(ms: np.ndarray) -> np.ndarray:
        d, finite = _dt64(ms)
        val = fn(ms, d)
        return np.where(finite, val, np.nan)

    return extract


PERIODS: Dict[str, Any] = {
    name: (length, _standalone_extract(name))
    for name, length in _PERIOD_LENGTHS.items()
}


def unit_circle(ms: np.ndarray, period_name: str
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(sin, cos, finite_mask) of one calendar period for epoch-ms values —
    the circular encoding shared by DateVectorizer and the dsl-exposed
    DateToUnitCircleTransformer. Missing dates map to the origin (0, 0):
    equidistant from every point on the circle."""
    period, extract = PERIODS[period_name]
    finite = np.isfinite(ms)
    ang = 2.0 * np.pi * extract(ms) / period
    s = np.where(finite, np.sin(ang), 0.0)
    c = np.where(finite, np.cos(ang), 0.0)
    return s, c, finite


class DateVectorizerModel(VectorizerModel):
    input_types = (Integral,)  # mirrors DateVectorizer: Date/DateTime

    def __init__(self, reference_date_ms: float,
                 circular_periods: Sequence[str], track_nulls: bool = True,
                 operation_name: str = "vecDate", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.reference_date_ms = float(reference_date_ms)
        self.circular_periods = list(circular_periods)
        self.track_nulls = track_nulls

    def _feature_width(self) -> int:
        return 1 + 2 * len(self.circular_periods) + (
            1 if self.track_nulls else 0)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        n = len(cols[0]) if cols else 0
        out = np.zeros((n, self._feature_width() * len(cols)), np.float32)
        self.transform_block_into(cols, out)
        return out

    def transform_block_into(self, cols: Sequence[Column],
                             out: np.ndarray) -> None:
        # one pass per column: the dt64 representation and the angle
        # buffer are computed once and shared across periods (each
        # unit_circle call re-derived them — 4 periods paid 4x the
        # calendar casts), and sin/cos land in the destination slice
        X = numeric_block(cols)  # epoch millis, NaN missing
        at = 0
        for j in range(X.shape[1]):
            ms = X[:, j]
            finite = np.isfinite(ms)
            d, _ = _dt64(ms)
            cast_cache: Dict[str, np.ndarray] = {}
            out[:, at] = np.where(
                finite, (self.reference_date_ms - ms) / MS_PER_DAY, 0.0)
            k = at + 1
            for p in self.circular_periods:
                period = _PERIOD_LENGTHS[p]
                if p in _CAL_PERIODS:
                    u, a, dv = _CAL_PERIODS[p]
                    val = _cal_delta_d(d, u, a, cast_cache) / dv
                else:
                    val = _MS_PERIODS[p](ms)
                # same fp op order AND precision as unit_circle (f64 trig
                # then the f32 store) — bitwise parity with the dsl
                # DateToUnitCircleTransformer is a stated invariant
                ang = 2.0 * np.pi * val / period
                out[:, k] = np.where(finite, np.sin(ang), 0.0)
                out[:, k + 1] = np.where(finite, np.cos(ang), 0.0)
                k += 2
            if self.track_nulls:
                out[:, k] = ~finite
                k += 1
            at = k
        if at != out.shape[1]:  # python -O strips assert; sink fallback
            raise AssertionError((at, out.shape))  # relies on this firing

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(reference_date_ms=self.reference_date_ms,
                 circular_periods=self.circular_periods,
                 track_nulls=self.track_nulls)
        return d


class DateVectorizer(SequenceVectorizer):
    """Date/DateTime group vectorizer."""

    input_types = (Integral,)  # Date extends Integral; accepts Date/DateTime

    @classmethod
    def _declare_params(cls):
        return [
            Param("reference_date_ms", "reference time (None = fit time)", None),
            Param("circular_periods", "periods to encode",
                  ["HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear"]),
            Param("track_nulls", "append null indicators", True),
        ]

    def __init__(self, operation_name: str = "vecDate",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> DateVectorizerModel:
        ref = self.get_param("reference_date_ms")
        if ref is None:
            import time
            ref = time.time() * 1000.0
        periods = list(self.get_param("circular_periods"))
        track = self.get_param("track_nulls")
        model = DateVectorizerModel(
            reference_date_ms=float(ref), circular_periods=periods,
            track_nulls=track, operation_name=self.operation_name)
        md_cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            md_cols.append(VectorColumnMetadata(
                parent_feature_name=f.name, parent_feature_type=f.type_name,
                descriptor_value="daysSinceReference"))
            for p in periods:
                for trig in ("sin", "cos"):
                    md_cols.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        descriptor_value=f"{p}_{trig}"))
            if track:
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    indicator_value=NULL_STRING))
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model


class DateListVectorizerModel(VectorizerModel):
    """DateList pivot modes (reference DateListPivot): SinceLast (default) —
    days from reference to most recent event; also ModeDay etc. are reduced
    to SinceFirst/SinceLast here."""

    input_types = (DateList,)  # mirrors DateListVectorizer

    def __init__(self, reference_date_ms: float, mode: str = "SinceLast",
                 operation_name: str = "vecDateList", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.reference_date_ms = float(reference_date_ms)
        self.mode = mode

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        n = len(cols[0])
        blocks = []
        for c in cols:
            anchor, empty = list_reduce(
                c.data, "max" if self.mode == "SinceLast" else "min")
            out = np.zeros((n, 2), dtype=np.float64)
            out[:, 0] = np.where(
                empty, 0.0, (self.reference_date_ms - anchor) / MS_PER_DAY)
            out[:, 1] = empty.astype(np.float64)
            blocks.append(out)
        return np.concatenate(blocks, axis=1)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(reference_date_ms=self.reference_date_ms, mode=self.mode)
        return d


class DateListVectorizer(SequenceVectorizer):
    input_types = (DateList,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("reference_date_ms", "reference time (None = fit time)", None),
            Param("mode", "SinceLast|SinceFirst", "SinceLast",
                  lambda v: v in ("SinceLast", "SinceFirst")),
        ]

    def __init__(self, operation_name: str = "vecDateList",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> DateListVectorizerModel:
        ref = self.get_param("reference_date_ms")
        if ref is None:
            import time
            ref = time.time() * 1000.0
        model = DateListVectorizerModel(
            reference_date_ms=float(ref), mode=self.get_param("mode"),
            operation_name=self.operation_name)
        md_cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            md_cols.append(VectorColumnMetadata(
                parent_feature_name=f.name, parent_feature_type=f.type_name,
                descriptor_value=f"days{self.get_param('mode')}"))
            md_cols.append(VectorColumnMetadata(
                parent_feature_name=f.name, parent_feature_type=f.type_name,
                indicator_value=NULL_STRING))
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model
