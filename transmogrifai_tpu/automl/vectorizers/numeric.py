"""Numeric vectorizers: imputation + null tracking, and bucketizers.

Reference: core/.../impl/feature/{RealVectorizer, IntegralVectorizer,
BinaryVectorizer, NumericBucketizer, DecisionTreeNumericBucketizer}.scala.

Layout matches the reference: for each input feature, its (imputed) value
column, then — when track_nulls — its null-indicator column.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from ...data.dataset import Column
from ...data.vector import NULL_STRING, VectorColumnMetadata, VectorMetadata
from ...stages.params import Param
from ...types import (
    Binary, Currency, Date, DateTime, Integral, OPNumeric, Percent, Real,
    RealNN,
)
from .base import SequenceVectorizer, VectorizerModel, numeric_block


class NumericVectorizerModel(VectorizerModel):
    """Fitted numeric vectorizer: impute with per-feature fill, track nulls."""

    # any numeric flavor: the Real/Integral/RealNN estimators all fit this
    input_types = (OPNumeric,)

    def __init__(self, fills: Sequence[float], track_nulls: bool = True,
                 operation_name: str = "vecReal", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.fills = np.asarray(fills, dtype=np.float64)
        self.track_nulls = bool(track_nulls)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        X = numeric_block(cols)
        isnan = np.isnan(X)
        filled = np.where(isnan, self.fills[None, :], X)
        if not self.track_nulls:
            return filled
        k = X.shape[1]
        out = np.empty((X.shape[0], 2 * k), dtype=np.float64)
        out[:, 0::2] = filled
        out[:, 1::2] = isnan.astype(np.float64)
        return out

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(fills=self.fills.tolist(), track_nulls=self.track_nulls)
        return d


class NumericVectorizer(SequenceVectorizer):
    """Impute (mean / constant) + null-track N numeric features.

    Reference RealVectorizer.scala (fillWithMean default true,
    TransmogrifierDefaults.TrackNulls=true).
    """

    input_types = (Real,)
    # fitted-model class; subclasses narrow it so save/load records the
    # faithful class name (BinaryVectorizer -> BinaryVectorizerModel)
    model_cls: Type["NumericVectorizerModel"]

    @classmethod
    def _declare_params(cls):
        return [
            Param("fill_mode", "mean|constant|mode", "mean",
                  lambda v: v in ("mean", "constant", "mode")),
            Param("fill_value", "constant fill value", 0.0),
            Param("track_nulls", "append null-indicator columns", True),
        ]

    def __init__(self, operation_name: str = "vecReal",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> NumericVectorizerModel:
        X = numeric_block(cols)
        mode = self.get_param("fill_mode")
        if mode == "mean":
            with np.errstate(invalid="ignore"):
                fills = np.nan_to_num(np.nanmean(X, axis=0), nan=0.0)
        elif mode == "mode":
            fills = []
            for j in range(X.shape[1]):
                col = X[:, j]
                col = col[np.isfinite(col)]
                if col.size == 0:
                    fills.append(0.0)
                else:
                    vals, counts = np.unique(col, return_counts=True)
                    fills.append(float(vals[np.argmax(counts)]))
            fills = np.asarray(fills)
        else:
            fills = np.full((X.shape[1],), float(self.get_param("fill_value")))
        track = self.get_param("track_nulls")
        model = self.model_cls(
            fills=fills, track_nulls=track, operation_name=self.operation_name)
        model.set_metadata(self._make_metadata(track))
        return model

    def _make_metadata(self, track_nulls: bool) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            cols.append(VectorColumnMetadata(
                parent_feature_name=f.name, parent_feature_type=f.type_name))
            if track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    indicator_value=NULL_STRING))
        return VectorMetadata(name=self.output_name(), columns=cols)


NumericVectorizer.model_cls = NumericVectorizerModel


class BinaryVectorizerModel(NumericVectorizerModel):
    pass


class BinaryVectorizer(NumericVectorizer):
    """Booleans -> {0,1} with fill=false + null tracking
    (reference BinaryVectorizer.scala, BinaryFillValue=false)."""

    input_types = (Binary,)
    model_cls = BinaryVectorizerModel

    def __init__(self, operation_name: str = "vecBin",
                 uid: Optional[str] = None, **params):
        params.setdefault("fill_mode", "constant")
        params.setdefault("fill_value", 0.0)
        super().__init__(operation_name, uid=uid, **params)


class IntegralVectorizer(NumericVectorizer):
    """Integers, default fill with mode (reference IntegralVectorizer,
    FillWithMode=true)."""

    input_types = (Integral,)

    def __init__(self, operation_name: str = "vecInt",
                 uid: Optional[str] = None, **params):
        params.setdefault("fill_mode", "mode")
        super().__init__(operation_name, uid=uid, **params)


class RealNNVectorizer(SequenceVectorizer):
    """Non-nullable reals pass straight through (no imputation needed)."""

    input_types = (RealNN,)

    def __init__(self, operation_name: str = "vecRealNN",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> NumericVectorizerModel:
        model = NumericVectorizerModel(
            fills=np.zeros(len(cols)), track_nulls=False,
            operation_name=self.operation_name)
        md_cols = [VectorColumnMetadata(parent_feature_name=f.name,
                                        parent_feature_type=f.type_name)
                   for f in self.input_features]
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model


class NumericBucketizerModel(VectorizerModel):
    """Fixed-split bucketing -> one-hot bucket indicators (+ null col).

    Reference NumericBucketizer.scala:303 — splits are [-inf, s1), [s1, s2)...
    """

    input_types = (OPNumeric,)  # mirrors NumericBucketizer's numeric family

    def __init__(self, splits: Sequence[Sequence[float]], track_nulls: bool = True,
                 track_invalid: bool = False,
                 operation_name: str = "bucketize", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.splits = [np.asarray(s, dtype=np.float64) for s in splits]
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        X = numeric_block(cols)
        blocks = []
        for j, s in enumerate(self.splits):
            x = X[:, j]
            nbuckets = len(s) - 1
            idx = np.clip(np.searchsorted(s, x, side="right") - 1, 0, nbuckets - 1)
            onehot = np.zeros((x.shape[0], nbuckets), dtype=np.float64)
            valid = np.isfinite(x)
            onehot[np.arange(x.shape[0])[valid], idx[valid]] = 1.0
            blocks.append(onehot)
            if self.track_nulls:
                blocks.append((~valid).astype(np.float64)[:, None])
        return np.concatenate(blocks, axis=1)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(splits=[s.tolist() for s in self.splits],
                 track_nulls=self.track_nulls, track_invalid=self.track_invalid)
        return d


class NumericBucketizer(SequenceVectorizer):
    """Quantile or fixed-split bucketizer (reference NumericBucketizer)."""

    input_types = (Real,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("splits", "explicit split points per feature (list of lists)", None),
            Param("num_buckets", "quantile bucket count when splits not given", 4),
            Param("track_nulls", "append null-indicator columns", True),
        ]

    def __init__(self, operation_name: str = "bucketize",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> NumericBucketizerModel:
        X = numeric_block(cols)
        given = self.get_param("splits")
        nb = int(self.get_param("num_buckets"))
        track = self.get_param("track_nulls")
        splits: List[np.ndarray] = []
        for j in range(X.shape[1]):
            if given is not None:
                s = np.asarray(given[j], dtype=np.float64)
            else:
                col = X[:, j][np.isfinite(X[:, j])]
                if col.size == 0:
                    s = np.array([-np.inf, np.inf])
                else:
                    qs = np.quantile(col, np.linspace(0, 1, nb + 1)[1:-1])
                    s = np.concatenate([[-np.inf], np.unique(qs), [np.inf]])
            splits.append(s)
        model = NumericBucketizerModel(
            splits=splits, track_nulls=track, operation_name=self.operation_name)
        md_cols: List[VectorColumnMetadata] = []
        for f, s in zip(self.input_features, splits):
            for b in range(len(s) - 1):
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=f.name, indicator_value=f"bucket_{b}"))
            if track:
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=f.name, indicator_value=NULL_STRING))
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model
