"""VectorsCombiner: concatenate OPVector features into the final matrix.

Reference: core/.../impl/feature/VectorsCombiner.scala — a SequenceTransformer
assembling per-family vectors into the single feature vector consumed by
SanityChecker and models. The combined 2-D block is exactly what gets
device_put to HBM.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ...data.dataset import Column
from ...data.vector import VectorMetadata
from ...types import ColumnKind, OPVector
from .base import VectorizerModel


class VectorsCombiner(VectorizerModel):
    """Transformer (no fitting): concat vector columns + their metadata."""

    input_types = (OPVector,)
    is_sequence = True

    def __init__(self, operation_name: str = "combineVectors",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        # single preallocated f32 pass — input vector columns are already
        # f32, so each part is one slice copy, never an f64 round-trip
        n = len(cols[0]) if cols else 0
        mats = []
        for c in cols:
            m = c.data
            if m.ndim == 1:
                m = m[:, None]
            mats.append(m)
        out = np.empty((n, sum(m.shape[1] for m in mats)), np.float32)
        at = 0
        for m in mats:
            out[:, at:at + m.shape[1]] = m
            at += m.shape[1]
        return out

    def combine_metadata(self, cols: Sequence[Column]) -> VectorMetadata:
        parts: List[VectorMetadata] = []
        for c, f in zip(cols, self.input_features):
            if c.metadata is not None:
                parts.append(c.metadata)
            else:
                from ...data.vector import VectorColumnMetadata
                width = c.data.shape[1] if c.data.ndim == 2 else 1
                parts.append(VectorMetadata(name=f.name, columns=[
                    VectorColumnMetadata(parent_feature_name=f.name,
                                         parent_feature_type=f.type_name,
                                         descriptor_value=str(i))
                    for i in range(width)]))
        md = VectorMetadata.concat(self.output_name(), parts)
        self.set_metadata(md)
        return md

    def transform_columns(self, *cols: Column) -> Column:
        self.combine_metadata(cols)
        return super().transform_columns(*cols)
