"""Vectorized host-side encoding primitives shared by the vectorizers.

The reference fused all row-level transforms of a DAG layer into ONE
distributed `rdd.map` pass (FitStagesUtil.applyOpTransformations:96); the
TPU build's equivalent discipline is that host transforms must be O(n)
*C-speed* passes, never O(n) Python-interpreter loops — at the 10M-row
BASELINE config a per-row Python loop would dominate total wall-clock over
the device sweep itself.

Design: factorize once (np.unique over an object array), apply the
Python-level work (cleaning, vocab lookup) only to the UNIQUE values
(usually << n), then scatter indicator/codes with numpy fancy indexing.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...ops import pyext_bridge as _px


def null_mask(data: Sequence[Any]) -> np.ndarray:
    """[n] bool: value is None (missing)."""
    out = _px.null_mask(data)
    if out is not None:
        return out
    return np.fromiter((v is None for v in data), np.bool_, len(data))


def empty_mask(data: Sequence[Any]) -> np.ndarray:
    """[n] bool: value is falsy (None or empty collection/string)."""
    out = _px.empty_mask(data)
    if out is not None:
        return out
    return np.fromiter((not v for v in data), np.bool_, len(data))


def factorize(data: Sequence[Any]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(uniques, inverse, null_mask) for a column of scalar-ish values.

    None becomes "" in the unique table (masked separately); non-strings
    stringify. Fast path: one O(n) pass in the C extension (PyDict over
    the interpreter's cached str hashes — no stringify/pack prepass);
    middle path: the ctypes hashed dictionary-encode over packed bytes;
    fallback: np.unique's O(n log n) sort. Callers never rely on unique
    ORDER — codes are remapped through vocab lookups — so the paths are
    interchangeable.
    """
    nm = null_mask(data)
    out = _px.dict_encode(data)
    if out is not None:
        codes, uniques = out
        return (np.asarray(uniques, dtype=object), codes, nm)
    strs = ["" if v is None else (v if type(v) is str else str(v))
            for v in data]
    try:
        from ...ops.native_bridge import native_dict_encode
        nout = native_dict_encode(strs)
        if nout is not None:
            codes, uniques = nout
            return (np.asarray(uniques, dtype=object), codes, nm)
    except ImportError:
        pass
    arr = np.empty(len(strs), dtype=object)
    arr[:] = strs
    uniq, inv = np.unique(arr, return_inverse=True)
    return uniq, inv, nm


def pivot_codes(uniq: np.ndarray, vocab_index: Dict[str, int], other_code: int,
                clean_fn) -> np.ndarray:
    """Map each UNIQUE raw value to its indicator column (vocab index or
    OTHER). Cleaning and dict lookups run once per unique value."""
    out = np.empty(len(uniq), np.int64)
    for i, u in enumerate(uniq):
        out[i] = vocab_index.get(clean_fn(u), other_code)
    return out


def pivot_block_single(data: Sequence[Any], vocab: Sequence[str],
                       track_nulls: bool, clean_fn,
                       out: Optional[np.ndarray] = None) -> np.ndarray:
    """One-hot pivot of a scalar categorical column: [n, K+1(+1)] with
    topK indicators, OTHER, and optionally a null column.

    Serving hot path (the fused row-map slot, FitStagesUtil.scala:96):
    one C pass (pyext pivot_codes, memoized raw-value -> column) plus a
    fancy-index scatter — categorical cardinality is tiny next to n, so
    every row after the first sighting of a value is a single dict hit.
    `out` (pre-zeroed, may be a strided view of the combined matrix)
    receives the block in place — the serving sink-fusion path."""
    n = len(data)
    k = len(vocab)
    width = k + 1 + (1 if track_nulls else 0)
    block = np.zeros((n, width), dtype=np.float32) if out is None else out
    if n == 0:
        return block
    index = {v: i for i, v in enumerate(vocab)}
    null_code = k + 1 if track_nulls else -1
    codes = _px.pivot_codes(data, index, k, null_code, clean_fn)
    if codes is not None:
        keep = codes >= 0
        block[np.arange(n)[keep], codes[keep]] = 1.0
        return block
    memo: Dict[Any, int] = {}

    def code_of(v):
        if v is None:
            return null_code
        if v != v:  # NaN: every instance misses a (cls, v) memo ((nan !=
            # nan) and they share hash 0) — memoizing would grow the dict
            # one entry per NaN row with full-chain probes; resolve
            # directly like the old factorize dedup did
            return index.get(clean_fn(str(v)), k)
        # memo keys carry the type: 1, 1.0 and True are ==/same-hash but
        # stringify differently, and the pivot must see str(v) semantics
        mk = (v.__class__, v)
        try:
            c = memo.get(mk)
        except TypeError:  # unhashable oddball: stringify, no memo
            return index.get(clean_fn(str(v)), k)
        if c is None:
            s = v if type(v) is str else str(v)
            c = index.get(clean_fn(s), k)
            memo[mk] = c
        return c

    codes = np.fromiter(map(code_of, data), np.int64, n)
    keep = codes >= 0
    block[np.arange(n)[keep], codes[keep]] = 1.0
    return block


def pivot_block_multi(data: Sequence[Any], vocab: Sequence[str],
                      track_nulls: bool, clean_fn,
                      out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pivot of a multi-valued (set/list) categorical column. Rows with
    multiple items set multiple indicators; empty rows hit the null col.
    `out`: pre-zeroed in-place destination (sink fusion), like
    pivot_block_single."""
    n = len(data)
    k = len(vocab)
    width = k + 1 + (1 if track_nulls else 0)
    block = np.zeros((n, width), dtype=np.float32) if out is None else out
    if n == 0:
        return block
    lengths = np.fromiter((len(v) if v else 0 for v in data), np.int64, n)
    total = int(lengths.sum())
    if total:
        flat = np.fromiter(
            (it if type(it) is str else str(it)
             for v in data if v for it in v),
            dtype=object, count=total)
        row_ids = np.repeat(np.arange(n), lengths)
        uniq, inv = np.unique(flat, return_inverse=True)
        index = {v: i for i, v in enumerate(vocab)}
        codes = pivot_codes(uniq, index, k, clean_fn)[inv]
        block[row_ids, codes] = 1.0
    if track_nulls:
        block[lengths == 0, k + 1] = 1.0
    return block


def category_counts(data: Sequence[Any], clean_fn,
                    multiset: bool = False) -> Tuple[Dict[str, int], int]:
    """(cleaned-value -> count, n_present_rows), computed from uniques.

    Replaces a per-row Counter loop: np.unique counts raw values at C
    speed; cleaning collapses raw uniques into cleaned buckets after.
    """
    n = len(data)
    if multiset:
        lengths = np.fromiter((len(v) if v else 0 for v in data), np.int64, n)
        # present = non-None row (an EMPTY collection still counts: it feeds
        # the cardinality-ratio guard's denominator like any observed row)
        n_present = int((~null_mask(data)).sum())
        total = int(lengths.sum())
        if not total:
            return {}, n_present
        flat = np.fromiter(
            (it if type(it) is str else str(it)
             for v in data if v for it in v),
            dtype=object, count=total)
        uniq, counts = np.unique(flat, return_counts=True)
    else:
        uniq, inv, nm = factorize(data)
        n_present = int((~nm).sum())
        if n_present == 0:
            return {}, 0
        counts = np.bincount(inv[~nm], minlength=len(uniq))
        keep = counts > 0
        uniq, counts = uniq[keep], counts[keep]
    out: Dict[str, int] = {}
    for u, c in zip(uniq, counts):
        cv = clean_fn(u)
        out[cv] = out.get(cv, 0) + int(c)
    return out, n_present


def float_column(vals: Sequence[Any], fill: float) -> np.ndarray:
    """[n] float64 with None -> fill. One C-speed pass."""
    out = _px.float_column(vals, fill)
    if out is not None:
        return out
    return np.fromiter(
        (fill if v is None else float(v) for v in vals),
        np.float64, len(vals))


def triple_block(data: Sequence[Any], fill: Sequence[float]) -> np.ndarray:
    """[n, 3] from (lat, lon, acc) triples with falsy -> fill."""
    n = len(data)
    f0, f1, f2 = (float(fill[0]), float(fill[1]),
                  float(fill[2])) if len(fill) >= 3 else (0.0, 0.0, 0.0)
    return np.fromiter(
        ((v[0], v[1], v[2]) if v else (f0, f1, f2) for v in data),
        dtype=np.dtype((np.float64, 3)), count=n)


def extract_key_columns(data: Sequence[Any], keys: Sequence[str],
                        clean_fn=None) -> Dict[str, List[Any]]:
    """Explode a column of dict rows into per-key value lists in ONE pass.

    Replaces per-key row scans (O(keys x n), and O(items) per lookup when
    keys are cleaned) with a single O(total entries) pass. `clean_fn`
    normalizes raw keys before matching (None = exact match).
    """
    n = len(data)
    out = _px.extract_key_columns(data, keys, clean_fn)
    if out is not None:
        return out
    cols: Dict[str, List[Any]] = {k: [None] * n for k in keys}
    if clean_fn is None:
        for i, m in enumerate(data):
            if m:
                for k, v in m.items():
                    c = cols.get(k)
                    if c is not None:
                        c[i] = v
    else:
        # first-wins on cleaned-key collisions ({'First.Name', 'FirstName'}
        # both cleaning to 'firstname'): matches dict iteration order the
        # way a first-match scan would
        for i, m in enumerate(data):
            if m:
                for k, v in m.items():
                    c = cols.get(clean_fn(str(k)))
                    if c is not None and c[i] is None:
                        c[i] = v
    return cols


def list_reduce(data: Sequence[Any], mode: str) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row max/min of variable-length numeric lists.

    Returns (reduced [n] float64 with 0.0 for empty, empty_mask [n] bool).
    np.maximum/minimum.reduceat over the flattened values — no Python loop
    over rows, only the flattening generator.
    """
    n = len(data)
    lengths = np.fromiter((len(v) if v else 0 for v in data), np.int64, n)
    empty = lengths == 0
    out = np.zeros(n, np.float64)
    total = int(lengths.sum())
    if total:
        flat = np.fromiter(
            (float(x) for v in data if v for x in v), np.float64, total)
        nz = np.nonzero(~empty)[0]
        starts = np.zeros(len(nz), np.int64)
        np.cumsum(lengths[nz][:-1], out=starts[1:])
        ufunc = np.maximum if mode == "max" else np.minimum
        out[nz] = ufunc.reduceat(flat, starts)
    return out, empty
