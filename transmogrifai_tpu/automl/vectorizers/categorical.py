"""Categorical one-hot / pivot vectorizers.

Reference: core/.../impl/feature/OpOneHotVectorizer.scala (top-K pivot with
OTHER + null-indicator columns, min support, text cleaning) and
OpSetVectorizer for MultiPickList.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...data.dataset import Column
from ...data.vector import NULL_STRING, OTHER_STRING, VectorColumnMetadata, VectorMetadata
from ...stages.params import Param
from ...types import MultiPickList, Text
from .base import SequenceVectorizer, VectorizerModel
from .encoding import category_counts, pivot_block_multi, pivot_block_single

_CLEAN_RE = re.compile(r"[^\w\s]|_", re.UNICODE)


def clean_text_value(s: str, clean: bool = True) -> str:
    """Reference TextParams.cleanTextFn: trim, strip punctuation, lowercase."""
    if not clean:
        return s
    return _CLEAN_RE.sub("", s).strip().lower()


class OneHotModel(VectorizerModel):
    """Fitted pivot: per feature, topK indicator cols + OTHER + null."""

    # class-level: any element (Text-ish or MultiPickList); Estimator.fit
    # pins each fitted instance to its estimator's concrete contract
    input_types = (None,)

    def __init__(self, vocabs: Sequence[Sequence[str]], track_nulls: bool = True,
                 clean_text: bool = True, multiset: bool = False,
                 operation_name: str = "pivot", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.vocabs = [list(v) for v in vocabs]
        self.track_nulls = track_nulls
        self.clean_text = clean_text
        self.multiset = multiset

    def _width(self, j: int) -> int:
        return len(self.vocabs[j]) + 1 + (1 if self.track_nulls else 0)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        n = len(cols[0]) if cols else 0
        out = np.zeros((n, sum(self._width(j) for j in range(len(cols)))),
                       np.float32)
        self.transform_block_into(cols, out)
        return out

    def transform_block_into(self, cols: Sequence[Column],
                             out: np.ndarray) -> None:
        # indicator scatters land straight in the final combined matrix
        clean = self.clean_text
        pivot = pivot_block_multi if self.multiset else pivot_block_single
        at = 0
        for j, c in enumerate(cols):
            w = self._width(j)
            pivot(c.data, self.vocabs[j], self.track_nulls,
                  lambda s: clean_text_value(s, clean),
                  out=out[:, at:at + w])
            at += w
        if at != out.shape[1]:  # python -O strips assert; sink fallback
            raise AssertionError((at, out.shape))  # relies on this firing

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(vocabs=self.vocabs, track_nulls=self.track_nulls,
                 clean_text=self.clean_text, multiset=self.multiset)
        return d


class OneHotVectorizer(SequenceVectorizer):
    """Top-K categorical pivot estimator (reference OpOneHotVectorizer:
    TopK=20, MinSupport=10, CleanText=true, TrackNulls=true)."""

    input_types = (Text,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("top_k", "max categories per feature", 20,
                  lambda v: v > 0),
            Param("min_support", "min occurrences to keep a category", 10,
                  lambda v: v >= 0),
            Param("clean_text", "normalize category strings", True),
            Param("track_nulls", "append null-indicator columns", True),
            Param("max_pct_cardinality",
                  "drop pivot if distinct/count exceeds this", 1.0),
        ]

    def __init__(self, operation_name: str = "pivot",
                 uid: Optional[str] = None, multiset: bool = False, **params):
        self.multiset = multiset
        if multiset:
            self.input_types = (MultiPickList,)
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> OneHotModel:
        top_k = int(self.get_param("top_k"))
        min_support = int(self.get_param("min_support"))
        clean = self.get_param("clean_text")
        track = self.get_param("track_nulls")
        max_pct = float(self.get_param("max_pct_cardinality"))
        vocabs: List[List[str]] = []
        for c in cols:
            counts, n_present = category_counts(
                c.data, lambda s: clean_text_value(s, clean),
                multiset=self.multiset)
            if n_present > 0 and len(counts) / n_present > max_pct:
                # near-unique (ID-like) column: drop the pivot entirely
                # (reference OpOneHotVectorizer.MaxPctCardinality guard)
                vocabs.append([])
                continue
            kept = [(val, n) for val, n in counts.items()
                    if n >= min_support and val != ""]
            # order: by count desc then value asc (stable, reproducible)
            kept.sort(key=lambda kv: (-kv[1], kv[0]))
            vocabs.append([val for val, _ in kept[:top_k]])
        model = OneHotModel(vocabs=vocabs, track_nulls=track, clean_text=clean,
                            multiset=self.multiset,
                            operation_name=self.operation_name)
        md_cols: List[VectorColumnMetadata] = []
        for f, vocab in zip(self.input_features, vocabs):
            for v in vocab:
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=f.name, indicator_value=v))
            md_cols.append(VectorColumnMetadata(
                parent_feature_name=f.name, parent_feature_type=f.type_name,
                grouping=f.name, indicator_value=OTHER_STRING))
            if track:
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=f.name, indicator_value=NULL_STRING))
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model
