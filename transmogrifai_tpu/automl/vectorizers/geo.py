"""Geolocation vectorizer: mean-impute with null tracking.

Reference: core/.../impl/feature/GeolocationVectorizer.scala:156 — fills
missing locations with the geographic mean (computed on the unit sphere so
the mean stays on the globe), emitting (lat, lon, accuracy, null) columns.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...data.dataset import Column
from ...data.vector import NULL_STRING, VectorColumnMetadata, VectorMetadata
from ...stages.params import Param
from ...types import Geolocation
from .base import SequenceVectorizer, VectorizerModel
from .encoding import empty_mask, triple_block


def geo_mean(values: Sequence[Sequence[float]]) -> List[float]:
    """Unit-sphere mean of (lat, lon, acc) triples (vectorized)."""
    if not len(values):
        return [0.0, 0.0, 0.0]
    arr = np.asarray(values, np.float64)[:, :3]
    la = np.radians(arr[:, 0])
    lo = np.radians(arr[:, 1])
    xs = float(np.mean(np.cos(la) * np.cos(lo)))
    ys = float(np.mean(np.cos(la) * np.sin(lo)))
    zs = float(np.mean(np.sin(la)))
    hyp = math.sqrt(xs * xs + ys * ys)
    return [math.degrees(math.atan2(zs, hyp)),
            math.degrees(math.atan2(ys, xs)), float(np.mean(arr[:, 2]))]


class GeolocationModel(VectorizerModel):
    input_types = (Geolocation,)  # mirrors GeolocationVectorizer

    def __init__(self, fills: Sequence[Sequence[float]], track_nulls: bool = True,
                 operation_name: str = "vecGeo", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.fills = [list(f) for f in fills]
        self.track_nulls = track_nulls

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        blocks = []
        for j, c in enumerate(cols):
            triples = triple_block(c.data, self.fills[j])
            if self.track_nulls:
                nulls = empty_mask(c.data).astype(np.float64)[:, None]
                triples = np.concatenate([triples, nulls], axis=1)
            blocks.append(triples)
        return np.concatenate(blocks, axis=1)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(fills=self.fills, track_nulls=self.track_nulls)
        return d


class GeolocationVectorizer(SequenceVectorizer):
    input_types = (Geolocation,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("fill_with_mean", "impute with spherical mean", True),
            Param("fill_value", "constant (lat, lon, acc)", (0.0, 0.0, 0.0)),
            Param("track_nulls", "append null indicators", True),
        ]

    def __init__(self, operation_name: str = "vecGeo",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> GeolocationModel:
        track = self.get_param("track_nulls")
        fills = []
        for c in cols:
            if self.get_param("fill_with_mean"):
                vals = [v for v in c.data if v]
                fills.append(geo_mean(vals))
            else:
                fills.append(list(self.get_param("fill_value")))
        model = GeolocationModel(fills=fills, track_nulls=track,
                                 operation_name=self.operation_name)
        md_cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            for d in ("lat", "lon", "accuracy"):
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    descriptor_value=d))
            if track:
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    indicator_value=NULL_STRING))
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model
