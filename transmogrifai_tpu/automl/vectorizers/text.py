"""Text vectorizers: smart cardinality-dispatch, hashing, tokenization.

Reference: core/.../impl/feature/{SmartTextVectorizer.scala:60,
OPCollectionHashingVectorizer.scala, TextTokenizer.scala}. SmartText computes
per-feature TextStats cardinality during fit: low-cardinality features pivot
(one-hot), high-cardinality features hash into a fixed bin space — the
hash-early-fixed-width design that keeps device shapes static.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...data.dataset import Column
from ...data.vector import NULL_STRING, OTHER_STRING, VectorColumnMetadata, VectorMetadata
from ...ops.hashing import hash_string, hash_tokens_to_counts
from ...stages.params import Param
from ...types import Text, TextList
from .base import SequenceVectorizer, VectorizerModel
from .categorical import clean_text_value
from .encoding import category_counts, null_mask, pivot_block_single

MIN_TOKEN_LENGTH = 1  # reference TextTokenizer.MinTokenLength


def tokenize(s: Optional[str], to_lowercase: bool = True,
             min_token_length: int = MIN_TOKEN_LENGTH) -> List[str]:
    """Default analyzer (reference TextTokenizer.scala:196 uses Lucene's
    standard analyzer): maximal runs of [A-Za-z0-9'], lowercased — the
    same semantics as the fused C++ tokenize+hash path, so host fallback
    and native fast path produce identical tensors."""
    from ...transformers.text import tokenize_text

    return tokenize_text(s, min_token_length, to_lowercase, False)


def tokenize_hash_counts(docs: Sequence[Optional[str]], bins: int,
                         seed: int = 0, pad_cols: int = 0,
                         out: Optional[np.ndarray] = None) -> np.ndarray:
    """Documents -> [n, bins + pad_cols] hashed token counts: the whole
    text->tensor loop in ONE native pass when the C++ library is built,
    else a python tokenize + (native or numpy) hashing fallback.
    `pad_cols` appends zero columns for in-place indicator writes (the
    serving path's null tracker) without a second full-matrix copy.
    `out`: pre-zeroed in-place destination (may be a strided slice of the
    final combined matrix — serving sink fusion).

    The C++ tokenizer is byte-level ASCII; it only takes over when every
    document isascii(), where it is token-for-token identical to the
    unicode python analyzer. Non-ASCII corpora keep unicode tokens."""
    from ...ops import pyext_bridge as _px
    ascii_ok = _px.all_ascii(docs)
    if ascii_ok is None:
        ascii_ok = all(d is None or d.isascii() for d in docs)
    if ascii_ok:
        try:
            from ...ops.native_bridge import native_tokenize_hash_counts
            res = native_tokenize_hash_counts(docs, bins, seed=seed,
                                              min_len=MIN_TOKEN_LENGTH,
                                              pad_cols=pad_cols, out=out)
            if res is not None:
                return res
        except ImportError:
            pass
    counts = hash_tokens_to_counts([tokenize(d) for d in docs], bins,
                                   seed=seed)
    if out is not None:
        out[:, :bins] = counts
        return out
    if pad_cols:
        res = np.zeros((counts.shape[0], bins + pad_cols), np.float32)
        res[:, :bins] = counts
        return res
    return counts


class SmartTextModel(VectorizerModel):
    """Fitted smart-text: per feature either a pivot vocab or a hash space."""

    input_types = (Text,)  # mirrors SmartTextVectorizer

    def __init__(self, plans: Sequence[Dict[str, Any]],
                 operation_name: str = "smartTxt", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        # each plan: {mode: 'pivot'|'hash'|'ignore', vocab: [...], bins: int,
        #            track_nulls: bool, clean_text: bool}
        self.plans = [dict(p) for p in plans]

    def _plan_width(self, plan: Dict[str, Any]) -> int:
        extra = 1 if plan["track_nulls"] else 0
        if plan["mode"] == "pivot":
            return len(plan["vocab"]) + 1 + extra
        return plan["bins"] + extra

    def _plan_block(self, plan: Dict[str, Any], c: Column,
                    out: Optional[np.ndarray]) -> Optional[np.ndarray]:
        data = c.data
        track = plan["track_nulls"]
        if plan["mode"] == "pivot":
            clean = plan["clean_text"]
            return pivot_block_single(
                data, plan["vocab"], track,
                lambda s: clean_text_value(s, clean), out=out)
        # hash: counts land directly in a [n, bins(+1)] destination (the
        # native kernel writes with the destination's row stride — out may
        # be a slice of the final combined matrix) and the null indicator
        # fills the trailing column in place — no second full-matrix copy
        # on serving
        block = tokenize_hash_counts(data, plan["bins"],
                                     pad_cols=1 if track else 0, out=out)
        if track:
            block[:, plan["bins"]] = null_mask(data)
        return block

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        blocks: List[np.ndarray] = []
        for plan, c in zip(self.plans, cols):
            blocks.append(np.asarray(
                self._plan_block(plan, c, None), np.float32))
        if len(blocks) == 1:
            return blocks[0]
        return np.concatenate(blocks, axis=1)

    def transform_block_into(self, cols: Sequence[Column],
                             out: np.ndarray) -> None:
        at = 0
        for plan, c in zip(self.plans, cols):
            w = self._plan_width(plan)
            self._plan_block(plan, c, out[:, at:at + w])
            at += w
        if at != out.shape[1]:  # python -O strips assert; sink fallback
            raise AssertionError((at, out.shape))  # relies on this firing

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(plans=self.plans)
        return d


class SmartTextVectorizer(SequenceVectorizer):
    """Cardinality-dispatched text vectorizer (reference
    SmartTextVectorizer.fitFn:79: cardinality <= maxCardinality(30) => pivot
    else hash into num_features bins)."""

    input_types = (Text,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("max_cardinality", "pivot if distinct values <= this", 30),
            Param("num_features", "hash bins for high-cardinality text", 512),
            Param("top_k", "pivot vocabulary cap", 20),
            Param("min_support", "min occurrences for pivot category", 10),
            Param("clean_text", "normalize strings", True),
            Param("track_nulls", "append null indicators", True),
        ]

    def __init__(self, operation_name: str = "smartTxt",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> SmartTextModel:
        max_card = int(self.get_param("max_cardinality"))
        bins = int(self.get_param("num_features"))
        top_k = int(self.get_param("top_k"))
        min_support = int(self.get_param("min_support"))
        clean = self.get_param("clean_text")
        track = self.get_param("track_nulls")
        plans: List[Dict[str, Any]] = []
        md_cols: List[VectorColumnMetadata] = []
        for f, c in zip(self.input_features, cols):
            counts, _ = category_counts(
                c.data, lambda s: clean_text_value(s, clean))
            if len(counts) <= max_card:
                kept = [(val, n) for val, n in counts.items()
                        if n >= min_support and val != ""]
                kept.sort(key=lambda kv: (-kv[1], kv[0]))
                vocab = [v for v, _ in kept[:top_k]]
                plans.append(dict(mode="pivot", vocab=vocab, bins=0,
                                  track_nulls=track, clean_text=clean))
                for v in vocab:
                    md_cols.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=f.name, indicator_value=v))
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=f.name, indicator_value=OTHER_STRING))
            else:
                plans.append(dict(mode="hash", vocab=[], bins=bins,
                                  track_nulls=track, clean_text=clean))
                for b in range(bins):
                    md_cols.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=f.name, descriptor_value=f"hash_{b}"))
            if track:
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=f.name, indicator_value=NULL_STRING))
        model = SmartTextModel(plans=plans, operation_name=self.operation_name)
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model


class HashingModel(VectorizerModel):
    """Pure hashing-trick vectorizer (no fit stats beyond widths)."""

    # class-level: TextList (is_list=True) or pre-tokenized Text;
    # Estimator.fit pins each fitted instance to its estimator's contract
    input_types = (None,)

    def __init__(self, num_features: int = 512, shared_hash_space: bool = False,
                 binary_freq: bool = False, is_list: bool = True,
                 operation_name: str = "hashText", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.num_features = int(num_features)
        self.shared_hash_space = shared_hash_space
        self.binary_freq = binary_freq
        self.is_list = is_list

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        # per-column count matrices straight from the (native) kernels;
        # token-hash counts are additive, so a shared hash space is the SUM
        # of per-column matrices — no per-row list concatenation needed
        mats = [hash_tokens_to_counts(c.data, self.num_features)
                if self.is_list
                else tokenize_hash_counts(c.data, self.num_features)
                for c in cols]
        if self.shared_hash_space:
            out = mats[0] if len(mats) == 1 else np.sum(mats, axis=0)
            return np.minimum(out, 1.0) if self.binary_freq else out
        if self.binary_freq:
            mats = [np.minimum(m, 1.0) for m in mats]
        return np.concatenate(mats, axis=1)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(num_features=self.num_features,
                 shared_hash_space=self.shared_hash_space,
                 binary_freq=self.binary_freq, is_list=self.is_list)
        return d


class TextListHashingVectorizer(SequenceVectorizer):
    """TextList -> hashed token counts (reference
    OPCollectionHashingVectorizer.scala:398; HashSpaceStrategy.Auto =>
    separate spaces unless many features)."""

    input_types = (TextList,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("num_features", "hash bins per feature", 512),
            Param("shared_hash_space", "share one hash space", False),
            Param("binary_freq", "0/1 instead of counts", False),
        ]

    def __init__(self, operation_name: str = "hashList",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> HashingModel:
        nf = int(self.get_param("num_features"))
        shared = self.get_param("shared_hash_space")
        model = HashingModel(
            num_features=nf, shared_hash_space=shared,
            binary_freq=self.get_param("binary_freq"), is_list=True,
            operation_name=self.operation_name)
        md_cols: List[VectorColumnMetadata] = []
        if shared:
            for b in range(nf):
                md_cols.append(VectorColumnMetadata(
                    parent_feature_name="+".join(self.input_names()),
                    parent_feature_type=self.input_features[0].type_name,
                    descriptor_value=f"hash_{b}"))
        else:
            for f in self.input_features:
                for b in range(nf):
                    md_cols.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=f.name, descriptor_value=f"hash_{b}"))
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model
